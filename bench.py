"""Round benchmark: the north-star configs from BASELINE.md.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., "stages": {...}}

Headline metric (unchanged across rounds): wall time to verify a
10,240-signature commit + the 64k-leaf block Merkle root — ONE combined
device dispatch from packed operands (the kernel number). `stages` carries
the rest of BASELINE.md's configs so regressions are attributable:

  pack_sigs_ms            host: SHA-512 challenges + limb/digit packing (10,240 sigs)
  pack_leaves_ms          host: SHA-256 padding/packing (65,536 leaves)
  verify_ms               device: ZIP-215 batch verify dispatch, steady state
  merkle_ms               device: leaf-hash + tree root dispatch, steady state
  combined_ms             device: ONE dispatch doing both  <- headline
  first_dispatch_s        cold-cache wall for the first combined dispatch
                          (compile or persistent-cache hit; VERDICT r3 #2)
  commit_light_e2e_ms     the SHIPPED path: types/validation VerifyCommitLight
                          over a real 10,240-validator Commit -> crypto.batch
                          -> backend -> kernel (includes all marshalling);
                          COLD — the verified-triple cache is cleared per rep
  commit_light_cached_ms  same call with the cache warm (production behavior
                          for blocksync's double verification)
  blocksync_replay_ms_per_block   100-block fast-sync replay, 1,024-validator
                          commits (blocksync/reactor.go:355 trySync shape)
  light_bisection_ms      light-client skipping verification to height 500
                          over 4,096-validator sets with rotation forcing
                          multi-hop bisection (light/client.go:706)

vs_baseline: reference Go path cost for the headline work, from BASELINE.md:
RFC-6962 Merkle ~77.7us/100 leaves -> ~50.9 ms at 64k; curve25519-voi batch
verify ~32us/sig -> ~327 ms for 10,240 sigs; total ~378 ms.
vs_baseline = baseline_ms / measured_ms (>1 = faster than the reference).

Stage plan for resilience (driver records the stderr tail):
  1. relay probe, 2. device probe (subprocess), 3. TPU worker (phase-logged,
  optional stages time-gated so the JSON line always lands), 4. CPU fallback
  (C-speed host path, not XLA:CPU).
"""

import json
import os
import socket
import subprocess
import sys
import time

BASELINE_MS = 10240 * 0.032 + 50.9
# Overridable for smoke tests on hosts without the device (the driver runs
# the defaults).
N_SIGS = int(os.environ.get("CMTPU_BENCH_SIGS", "10240"))
N_LEAVES = int(os.environ.get("CMTPU_BENCH_LEAVES", "65536"))
BS_VALS = int(os.environ.get("CMTPU_BENCH_BS_VALS", "1024"))
BS_BLOCKS = int(os.environ.get("CMTPU_BENCH_BS_BLOCKS", "100"))
LIGHT_VALS = int(os.environ.get("CMTPU_BENCH_LIGHT_VALS", "4096"))
RELAY_PORT = 8082
# The watcher's device A/B records its adopted lowering in .tpu_fe_mode so
# later watcher runs stick to it; honor the same decision when bench.py is
# invoked directly (the driver's end-of-round run), explicit env winning.
_sticky = None
try:
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".tpu_fe_mode")) as _f:
        _sticky = _f.read().strip() or None
except OSError:
    pass
if _sticky == "pallas":
    os.environ.setdefault("CMTPU_LADDER", "pallas")
elif _sticky:
    os.environ.setdefault("CMTPU_FE_MODE", _sticky)
PROBE_TIMEOUT_S = int(os.environ.get("CMTPU_BENCH_PROBE_TIMEOUT", "120"))
TPU_TIMEOUT_S = int(os.environ.get("CMTPU_BENCH_TPU_TIMEOUT", "480"))
MESH_TIMEOUT_S = int(os.environ.get("CMTPU_BENCH_MESH_TIMEOUT", "480"))
# Leave headroom before TPU_TIMEOUT_S: optional stages are skipped once the
# worker passes this many seconds.
STAGE_BUDGET_S = int(os.environ.get("CMTPU_BENCH_STAGE_BUDGET", "330"))
HERE = os.path.dirname(os.path.abspath(__file__))

T0 = time.time()


def log(msg: str) -> None:
    print(f"[bench {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def relay_open() -> bool:
    s = socket.socket()
    s.settimeout(3)
    try:
        s.connect(("127.0.0.1", RELAY_PORT))
        return True
    except OSError as e:
        log(f"relay probe: 127.0.0.1:{RELAY_PORT} -> {e}")
        return False
    finally:
        s.close()


def run_phase_logged(args: list, timeout_s: int, tag: str, env=None):
    out_path = os.path.join(HERE, f".bench_{tag}.out")
    err_path = os.path.join(HERE, f".bench_{tag}.err")
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        try:
            proc = subprocess.run(
                args, stdout=out_f, stderr=err_f, timeout=timeout_s, env=env
            )
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            rc = "timeout"
    tail = open(err_path).read()[-2000:]
    for line in tail.splitlines():
        log(f"  {tag}| {line}")
    if rc != 0:
        log(f"{tag}: rc={rc} after <= {timeout_s}s")
        return None
    return open(out_path).read()


# -- workload builders (host crypto is C-speed) --------------------------------


def _devnet_throughput(
    seconds: float = 12.0, n_vals: int = 4, target_blocks: int | None = None
):
    """System-level stage: an in-process 4-validator devnet over real TCP
    (SecretConnection, gossip, mempool) under continuous tx load. Returns
    (blocks/s, committed tx/s) — the analog of the reference's QA
    saturation measurements (docs/qa/: ~0.7 blocks/s, ~400 tx/s on a
    200-node DigitalOcean testnet; here everything shares one host).
    `target_blocks` ends the run early once that many blocks committed
    (`seconds` stays the hard cap) — the hotpath A/B uses it so both arms
    measure the same amount of work."""
    import threading

    from cometbft_tpu.abci.client import LocalClientCreator
    from cometbft_tpu.abci.example.kvstore import KVStoreApplication
    from cometbft_tpu.config import test_config
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types import cmttime
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    pvs = [FilePV(ed25519.gen_priv_key_from_secret(b"bench-val-%d" % i)) for i in range(n_vals)]
    gen = GenesisDoc(
        chain_id="bench-devnet",
        genesis_time=cmttime.now(),
        validators=[
            GenesisValidator(pv.get_pub_key().address(), pv.get_pub_key(), 10, f"v{i}")
            for i, pv in enumerate(pvs)
        ],
    )
    gen.validate_and_complete()
    nodes = []
    for pv in pvs:
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        nodes.append(Node(cfg, gen, pv, LocalClientCreator(KVStoreApplication())))
    try:
        for nd in nodes:
            nd.start()
        addrs = [nd.switch.node_info.listen_addr for nd in nodes]
        for i, nd in enumerate(nodes):
            for j, a in enumerate(addrs):
                if i != j:
                    nd.switch.dial_peer(a)
        stop = [False]

        def pump():
            k = 0
            while not stop[0]:
                for nd in nodes:
                    try:
                        nd.mempool.check_tx(b"bench%d=v" % k)
                    except Exception:
                        pass
                k += 1
                time.sleep(0.002)

        threading.Thread(target=pump, daemon=True).start()
        t0 = time.time()
        h0 = nodes[0].block_store.height()  # committed-height semantics
        deadline = t0 + seconds
        while time.time() < deadline:
            time.sleep(0.25)
            if (
                target_blocks is not None
                and nodes[0].block_store.height() - h0 >= target_blocks
            ):
                break
        stop[0] = True
        dt = time.time() - t0
        h1 = nodes[0].block_store.height()
        txs = 0
        for h in range(h0 + 1, h1 + 1):
            blk = nodes[0].block_store.load_block(h)
            if blk is not None:
                txs += len(blk.data.txs)
        return (h1 - h0) / dt, txs / dt
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:
                pass


def _pick_headline(stages: dict) -> float:
    """Headline = fastest measured combined path; records which one won so
    the JSON schema is identical for full and truncated emits.  A truncated
    snapshot may predate the combined stage entirely — emit a -1 sentinel
    then, so the watchdog's partial record still goes out instead of a
    KeyError being swallowed by its bare except."""
    headline = stages.get("combined_ms")
    stages["combined_path"] = "device"
    hyb = stages.get("combined_hybrid_ms")
    if headline is None:
        headline, stages["combined_path"] = (
            (hyb, "hybrid") if hyb is not None else (-1.0, "none")
        )
    elif hyb is not None and hyb < headline:
        headline, stages["combined_path"] = hyb, "hybrid"
    return headline


def best_of(f, reps=3):
    """Best wall time over reps calls, in ms."""
    best = float("inf")
    for _ in range(reps):
        t1 = time.perf_counter()
        f()
        best = min(best, time.perf_counter() - t1)
    return best * 1000.0


def _signed_batch(n, tag=b"bench"):
    from cometbft_tpu.crypto import ed25519 as host_ed

    pvs = [host_ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    pubs = [pv.pub_key().bytes() for pv in pvs]
    msgs = [b"commit-vote-%d" % i for i in range(n)]
    sigs = [pv.sign(m) for pv, m in zip(pvs, msgs)]
    return pvs, pubs, msgs, sigs


def _commit_fixture(n_vals, heights=1, chain_id="bench-chain", tag=b"cl"):
    """Real ValidatorSet + Commit(s) shaped like the shipped path sees them."""
    from cometbft_tpu.types import BlockID, Commit, Time, Vote
    from cometbft_tpu.types.block import PRECOMMIT_TYPE
    from cometbft_tpu.types.part_set import PartSetHeader
    from cometbft_tpu.types.priv_validator import MockPV
    from cometbft_tpu.types.validator import Validator
    from cometbft_tpu.types.validator_set import ValidatorSet
    from cometbft_tpu.types.vote import vote_to_commit_sig

    pvs = sorted((MockPV() for _ in range(n_vals)), key=lambda p: p.address())
    vals = ValidatorSet([Validator.new(pv.get_pub_key(), 10) for pv in pvs])
    pv_by_addr = {pv.address(): pv for pv in pvs}
    commits = []
    for h in range(1, heights + 1):
        bid = BlockID(
            h.to_bytes(8, "big") * 4, PartSetHeader(1, b"\x02" * 32)
        )
        sigs = []
        for idx, v in enumerate(vals.validators):
            vote = Vote(
                type=PRECOMMIT_TYPE, height=h, round=0, block_id=bid,
                timestamp=Time(1700000000 + h, 0),
                validator_address=v.address, validator_index=idx,
            )
            sigs.append(vote_to_commit_sig(pv_by_addr[v.address].sign_vote(chain_id, vote)))
        commits.append((bid, Commit(height=h, round=0, block_id=bid, signatures=sigs)))
    return vals, commits


class _LazyChain:
    """Light blocks generated only when the bisection touches them:
    4,096-validator sets rotating 8 per height, so a 1 -> 500 jump dilutes
    trust below 1/3 and forces multi-hop bisection."""

    CHAIN_ID = "bench-light"

    def __init__(self, n_vals=4096, rotate=8, heights=500):
        from cometbft_tpu.types.priv_validator import MockPV

        self.n_vals, self.rotate, self.heights = n_vals, rotate, heights
        self.pool = [MockPV() for _ in range(n_vals + rotate * heights)]
        self.blocks = {}
        self.built = 0

    def _vals_at(self, h):
        from cometbft_tpu.types.validator import Validator
        from cometbft_tpu.types.validator_set import ValidatorSet

        start = (h - 1) * self.rotate
        return ValidatorSet(
            [
                Validator.new(pv.get_pub_key(), 10)
                for pv in self.pool[start : start + self.n_vals]
            ]
        )

    def light_block(self, h):
        from cometbft_tpu.types import BlockID, Commit, Time, Vote
        from cometbft_tpu.types.block import PRECOMMIT_TYPE, Header, SignedHeader
        from cometbft_tpu.types.light_block import LightBlock
        from cometbft_tpu.types.part_set import PartSetHeader
        from cometbft_tpu.types.vote import vote_to_commit_sig

        if h in self.blocks:
            return self.blocks[h]
        vals = self._vals_at(h)
        next_vals = self._vals_at(h + 1)
        header = Header(
            chain_id=self.CHAIN_ID, height=h, time=Time(1700000000 + 10 * h, 0),
            last_block_id=BlockID(b"\x01" * 32, PartSetHeader(1, b"\x01" * 32)),
            validators_hash=vals.hash(), next_validators_hash=next_vals.hash(),
            app_hash=b"\x00" * 32, proposer_address=vals.validators[0].address,
        )
        bid = BlockID(header.hash(), PartSetHeader(1, b"\x02" * 32))
        pv_by_addr = {pv.address(): pv for pv in self.pool}
        sigs = []
        for idx, v in enumerate(vals.validators):
            vote = Vote(
                type=PRECOMMIT_TYPE, height=h, round=0, block_id=bid,
                timestamp=header.time.add_nanos(10**9),
                validator_address=v.address, validator_index=idx,
            )
            sigs.append(vote_to_commit_sig(pv_by_addr[v.address].sign_vote(self.CHAIN_ID, vote)))
        lb = LightBlock(
            signed_header=SignedHeader(header, Commit(height=h, round=0, block_id=bid, signatures=sigs)),
            validator_set=vals,
        )
        self.blocks[h] = lb
        self.built += 1
        return lb

    def provider(self):
        from cometbft_tpu.light.provider import Provider

        chain = self

        class _P(Provider):
            def chain_id(self):
                return chain.CHAIN_ID

            def light_block(self, height):
                if height == 0:
                    height = chain.heights
                return chain.light_block(height)

            def report_evidence(self, ev):
                pass

        return _P()


# -- pod-scale mesh stage ------------------------------------------------------


def _fit_and_model(widths, n_sigs, ms_per_lane, overhead_ms):
    """Pure model: the verify wall for ONE merged n_sigs dispatch at each
    mesh width, from a measured per-lane rate and a fixed per-dispatch
    overhead (the sharded program is pure data parallel — zero collectives
    — so lanes split evenly; the mesh-aware ladder pads the remainder).
    Returns the curve narrowest-first, each row carrying its speedup vs the
    width-1 row."""
    curve = []
    for w in sorted({int(w) for w in widths if int(w) >= 1}):
        lanes = -(-n_sigs // w)  # ceil: the padded per-chip share
        curve.append(
            {
                "devices": w,
                "verify_ms": round(overhead_ms + lanes * ms_per_lane, 3),
            }
        )
    base = next(
        (r["verify_ms"] for r in curve if r["devices"] == 1),
        curve[0]["verify_ms"] if curve else 0.0,
    )
    for row in curve:
        row["speedup"] = (
            round(base / row["verify_ms"], 2) if row["verify_ms"] > 0 else 0.0
        )
    return curve


def _mesh_stage_inner(plog) -> dict:
    """Pod-scaling stage (runs inside a jax-capable process): calibrate the
    REAL single-device and mesh-sharded verify walls at two small buckets,
    assert the sharded program is bit-identical to the single-device bitmap,
    then model the CMTPU_BENCH_MESH_SIGS merged dispatch across
    CMTPU_BENCH_MESH_WIDTHS from the measured per-lane rate + dispatch
    overhead (`modeled: true` in the JSON — on the single-core virtual mesh
    the chips share one core, so the curve is the rate model's, same
    convention as the other stages' simulated dispatch costs; on a real pod
    the calibration walls themselves are the device evidence).  Also runs
    the subtree-parallel Merkle route against the host root."""
    t0 = time.time()
    import numpy as np

    from cometbft_tpu.ops import ed25519_kernel as ek

    n_sigs = int(os.environ.get("CMTPU_BENCH_MESH_SIGS", "65536"))
    widths = os.environ.get("CMTPU_BENCH_MESH_WIDTHS", "1,2,4,8").split(",")
    b2 = int(os.environ.get("CMTPU_BENCH_MESH_CAL_MAX", "4096"))
    b1 = 128 if b2 > 128 else 8
    width = ek.mesh_width()

    pvs, pubs, msgs, sigs = _signed_batch(b2, tag=b"mesh")
    plog(f"mesh: signed {b2} calibration messages (mesh width {width})")
    operands2, host_ok2 = ek.pack_batch(pubs, msgs, sigs)
    operands1, _ = ek.pack_batch(pubs[:b1], msgs[:b1], sigs[:b1])
    f1 = ek._compiled(*ek._bucket_key(operands1))
    f2 = ek._compiled(*ek._bucket_key(operands2))
    ok1 = np.asarray(f1(*operands1))  # compile + correctness
    ok2 = np.asarray(f2(*operands2))
    assert ok2[:b2].all(), "mesh calibration batch must verify"
    w1 = best_of(lambda: np.asarray(f1(*operands1)), reps=2)
    w2 = best_of(lambda: np.asarray(f2(*operands2)), reps=2)
    plog(f"mesh: single-device walls {b1}: {w1:.1f} ms, {b2}: {w2:.1f} ms")
    ms_per_lane = max((w2 - w1) / max(b2 - b1, 1), 1e-6)
    overhead_ms = max(w1 - b1 * ms_per_lane, 0.0)

    cal = {
        "bucket_small": b1,
        "bucket_large": b2,
        "single_ms_small": round(w1, 3),
        "single_ms_large": round(w2, 3),
        "ms_per_lane": round(ms_per_lane, 6),
        "dispatch_overhead_ms": round(overhead_ms, 3),
    }
    sh = ek._sharded_verify()
    if sh is not None and b2 % sh[0] == 0:
        sharded_ok = np.asarray(sh[1](*operands2))  # compile
        cal["sharded_ms_large"] = round(
            best_of(lambda: np.asarray(sh[1](*operands2)), reps=2), 3
        )
        cal["sharded_bit_identical"] = bool(np.array_equal(sharded_ok, ok2))
        assert cal["sharded_bit_identical"], "mesh bitmap != single-device"
        plog(
            f"mesh: sharded wall {b2} over {sh[0]} chips "
            f"{cal['sharded_ms_large']} ms (bit-identical)"
        )

    curve = _fit_and_model(widths, n_sigs, ms_per_lane, overhead_ms)
    result = {
        "n_devices": width,
        "sigs": n_sigs,
        "modeled": True,
        "calibration": cal,
        "curve": curve,
        "speedup_widest_vs_1": curve[-1]["speedup"] if curve else 0.0,
    }

    # ---- subtree-parallel Merkle route (time-gated: 2 more compiles) ----
    if time.time() - t0 < MESH_TIMEOUT_S * 0.6:
        try:
            from cometbft_tpu.crypto.merkle import hash_from_byte_slices
            from cometbft_tpu.ops import merkle_kernel as mk
            from cometbft_tpu.ops import sha256_kernel as sha

            n_leaves = int(os.environ.get("CMTPU_BENCH_MESH_LEAVES", "4096"))
            txs = [b"mesh-tx-%08d" % i for i in range(n_leaves)]
            blocks, nblocks = sha.pack_messages([b"\x00" + t for t in txs])
            want = hash_from_byte_slices(txs)
            shr = mk._sharded_root()
            if shr is not None and n_leaves % shr[0] == 0:
                import jax.numpy as jnp

                db, dn = jnp.asarray(blocks), jnp.asarray(nblocks)
                single_fn = mk._leaves_to_root_jit(blocks.shape[0], n_leaves)

                def _single():
                    return sha.digest_words_to_bytes(
                        np.asarray(single_fn(db, dn))
                    )[0]

                def _mesh_root():
                    return sha.digest_words_to_bytes(
                        np.asarray(shr[1](db, dn))
                    )[0]

                assert _single() == want and _mesh_root() == want
                result["merkle"] = {
                    "leaves": n_leaves,
                    "single_ms": round(best_of(_single, reps=2), 3),
                    "sharded_ms": round(best_of(_mesh_root, reps=2), 3),
                    "root_identical": True,
                }
                plog(
                    f"mesh: merkle {n_leaves} leaves single "
                    f"{result['merkle']['single_ms']} ms, sharded "
                    f"{result['merkle']['sharded_ms']} ms (roots match)"
                )
        except Exception as e:
            plog(f"mesh merkle sub-stage failed: {type(e).__name__}: {e}")

    result["mesh_counters"] = ek.mesh_counters()
    return result


def mesh_worker() -> None:
    """--mesh-worker argv mode: the mesh stage in its own jax process (the
    CPU fallback parent deliberately never imports jax), pinned to the
    virtual mesh by the parent's env. Emits one MESH_JSON line."""
    t0 = time.time()

    def plog(msg):
        print(f"[mesh {time.time() - t0:6.1f}s] {msg}", file=sys.stderr, flush=True)

    plog(f"start; JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}")
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from cometbft_tpu.ops import xla_cache

    if not xla_cache.enable_persistent_cache(HERE):
        plog("cache config failed (jaxlib lacks the persistent-cache knobs)")
    print("MESH_JSON " + json.dumps(_mesh_stage_inner(plog)), flush=True)


def _mesh_stage_subprocess():
    """Launch --mesh-worker on the 8-device virtual CPU mesh; returns the
    parsed stage dict or None (a wedged/failed worker never gates the
    fallback's JSON line)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the axon relay
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # Small real-wall calibration buckets: XLA:CPU verifies ~7 ms/lane, so
    # the defaults sized for a pod would spend minutes on calibration.
    env.setdefault("CMTPU_BENCH_MESH_CAL_MAX", "512")
    env.setdefault("CMTPU_BENCH_MESH_LEAVES", "1024")
    out = run_phase_logged(
        [sys.executable, "-u", __file__, "--mesh-worker"],
        MESH_TIMEOUT_S,
        "mesh",
        env=env,
    )
    for line in (out or "").splitlines():
        if line.startswith("MESH_JSON "):
            try:
                return json.loads(line[len("MESH_JSON "):])
            except ValueError:
                return None
    return None


# -- TPU worker ----------------------------------------------------------------


def tpu_worker() -> None:
    t0 = time.time()

    def plog(msg):
        print(f"[worker {time.time() - t0:6.1f}s] {msg}", file=sys.stderr, flush=True)

    def budget_left() -> bool:
        return time.time() - t0 < STAGE_BUDGET_S

    plog(f"start; JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}")
    import jax

    # The env var alone does not always stop the axon PJRT plugin from
    # initializing (and hanging on a wedged tunnel); pin the platform in
    # jax.config too (same workaround as tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from cometbft_tpu.ops import xla_cache

    if not xla_cache.enable_persistent_cache(HERE):
        plog("cache config failed (jaxlib lacks the persistent-cache knobs)")
    devs = jax.devices()
    plog(f"devices: {devs} platform={devs[0].platform}")
    if "--probe-only" in sys.argv:
        import jax.numpy as jnp

        y = jax.block_until_ready(jnp.ones((256, 256)) @ jnp.ones((256, 256)))
        plog(f"matmul ok ({float(y[0, 0])})")
        print("PROBE_OK")
        return

    import numpy as np

    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.ops import merkle_kernel as mk
    from cometbft_tpu.ops import sha256_kernel as sha

    stages = {}
    stages["n_devices"] = len(devs)
    # Attribution: which kernel variant produced this line (the RESOLVED
    # lowering — 'auto' would label different variants identically).
    from cometbft_tpu.ops import field25519 as _fe

    stages["fe_mode"] = _fe._mode()
    stages["ladder"] = (
        "pallas" if os.environ.get("CMTPU_LADDER") == "pallas" else "xla"
    )
    if os.environ.get("CMTPU_HOST_HASH") == "1":
        stages["host_hash"] = True

    # ---- host packing ----
    pvs, pubs, msgs, sigs = _signed_batch(N_SIGS)
    plog(f"signed {N_SIGS} messages")
    operands, host_ok = ek.pack_batch(pubs, msgs, sigs)
    stages["pack_sigs_ms"] = round(best_of(lambda: ek.pack_batch(pubs, msgs, sigs)), 2)
    assert host_ok[:N_SIGS].all()
    txs = [b"bench-tx-%08d" % i for i in range(N_LEAVES)]
    leaf_msgs = [b"\x00" + t for t in txs]
    blocks, nblocks = sha.pack_messages(leaf_msgs)
    stages["pack_leaves_ms"] = round(
        best_of(lambda: sha.pack_messages(leaf_msgs)), 2
    )
    plog(f"host packing: sigs {stages['pack_sigs_ms']}ms leaves {stages['pack_leaves_ms']}ms")

    # ---- combined single-dispatch program (headline) ----
    import jax.numpy as jnp

    verify_fn = ek.verify_core_hosthash if len(operands) == 4 else ek.verify_core

    @jax.jit
    def combined(ops, blk, nblk):
        ok = verify_fn(*ops)
        root = mk.leaves_to_root_core(blk, nblk)
        return ok, root

    dev_operands = tuple(jnp.asarray(o) for o in operands)
    dev_blocks, dev_nblocks = jnp.asarray(blocks), jnp.asarray(nblocks)

    def run_combined():
        ok, root = combined(dev_operands, dev_blocks, dev_nblocks)
        return np.asarray(ok), np.asarray(root)

    t1 = time.time()
    ok, root = run_combined()
    first = time.time() - t1
    stages["first_dispatch_s"] = round(first, 2)
    plog(f"combined first dispatch {first:.1f}s (compile or cache hit)")
    assert ok.all(), "bench batch must verify"
    from cometbft_tpu.crypto.merkle import hash_from_byte_slices

    want_root = hash_from_byte_slices(txs)
    got_root = sha.digest_words_to_bytes(root)[0]
    assert got_root == want_root, "device merkle root != host root"

    stages["combined_ms"] = round(best_of(run_combined), 3)
    plog(f"combined steady {stages['combined_ms']} ms")

    # The headline number exists now; everything below is stage diagnostics.
    # A single wedged remote compile must not discard it (the parent kills
    # this worker at TPU_TIMEOUT_S and previously fell back to CPU, losing
    # the device evidence): a deadline watchdog emits whatever stages have
    # completed and exits 0 just before the parent's timeout.
    import threading

    finished = threading.Event()
    emit_once = threading.Lock()  # exactly one thread prints the JSON line

    def _watchdog():
        delay = (t0 + TPU_TIMEOUT_S - 30) - time.time()
        if delay > 0:
            time.sleep(delay)
        with emit_once:
            if finished.is_set():
                return
            finished.set()
            try:
                # Snapshot: the main thread may be mutating stages mid-stall.
                snap = dict(stages)
            except RuntimeError:
                snap = (
                    {"combined_ms": stages["combined_ms"]}
                    if stages.get("combined_ms") is not None
                    else {}
                )
            snap["truncated"] = True
            plog("stage budget exhausted mid-stage; emitting partial result")
            try:
                emit(_pick_headline(snap), snap, devs[0].platform)
            except BaseException:
                pass
            os._exit(0)

    threading.Thread(target=_watchdog, daemon=True).start()

    # ---- hybrid tier: device share in flight + host MSM + SHA-NI merkle --
    # The candidate headline: split the 10,240-sig batch at the rate-model
    # point (device bucket lanes async, native Pippenger MSM on the rest in
    # this thread, SHA-NI merkle under the device wait), merge bitmaps.
    if budget_left():
        try:
            from cometbft_tpu import native as _native
            from cometbft_tpu.sidecar import backend as _be

            if _native.available():
                hb = _be.HybridBackend()

                def run_hybrid():
                    (hok, _bits), hroot = hb.verify_and_root(pubs, msgs, sigs, txs)
                    return hok, hroot

                hok, hroot = run_hybrid()  # first call pays the share-bucket compile
                assert hok, "hybrid batch must verify"
                assert hroot == want_root, "hybrid root != host root"
                # 10 reps (~1.5 s total): the rate EMA learns from reps 2+
                # and re-plans the split each call, so later reps run at the
                # converged balance point — and the tunnel's run-to-run
                # variance (measured 50-150 ms fixed cost across watcher
                # wakes) needs several samples for an honest best-of.
                stages["combined_hybrid_ms"] = round(
                    best_of(run_hybrid, reps=10), 3
                )
                stages["hybrid_device_share"] = hb.last_share
                stages["hybrid_timing"] = dict(hb.last_timing)
                stages["hybrid_rates"] = {
                    "dev_sigs_per_ms": round(hb._dev_rate, 1),
                    "host_sigs_per_ms": round(hb._host_rate, 1),
                }
                plog(
                    f"hybrid combined {stages['combined_hybrid_ms']} ms "
                    f"(device share {stages['hybrid_device_share']}, "
                    f"rates d={hb._dev_rate:.0f}/h={hb._host_rate:.0f} sigs/ms, "
                    f"last timing {stages['hybrid_timing']})"
                )
            else:
                plog("hybrid stage skipped: native tier unavailable")
        except Exception as e:
            plog(f"hybrid stage failed: {type(e).__name__}: {e}")

    # ---- stage splits ----
    if budget_left():
        try:
            verify = ek._compiled(*ek._bucket_key(dev_operands))
            stages["verify_ms"] = round(
                best_of(lambda: np.asarray(verify(*dev_operands))), 3
            )
            plog(f"split: verify {stages['verify_ms']}ms")
        except Exception as e:
            plog(f"verify split failed: {type(e).__name__}: {e}")
    if budget_left():
        try:
            root_fn = mk._leaves_to_root_jit(blocks.shape[0], N_LEAVES)
            stages["merkle_ms"] = round(
                best_of(lambda: np.asarray(root_fn(dev_blocks, dev_nblocks))), 3
            )
            plog(f"split: merkle {stages['merkle_ms']}ms")
        except Exception as e:
            plog(f"merkle split failed: {type(e).__name__}: {e}")

    # ---- BASELINE #3 tail: inclusion proofs for every tx (proof.go:35) ----
    # Shipped path (proofs_from_byte_slices routes to the native SHA-NI
    # one-pass tree at this scale) is the headline; the device levels+aunts
    # program stays as a diagnostic of the on-device path.
    if budget_left():
        try:
            from cometbft_tpu.crypto.merkle import proof as _proof_mod
            from cometbft_tpu.crypto.merkle import proofs_from_byte_slices

            stages["merkle_proofs_ms"] = round(
                best_of(lambda: proofs_from_byte_slices(txs), reps=2), 1
            )
            # Host-side by default even on device runs (CMTPU_DEVICE_PROOFS=1
            # opts back into the device path, which measured ~12x slower).
            stages["merkle_proofs_path"] = _proof_mod.last_proofs_path
            plog(
                f"proofs (shipped path): {stages['merkle_proofs_ms']} ms "
                f"[{stages['merkle_proofs_path']}]"
            )
        except Exception as e:
            plog(f"proofs stage failed: {type(e).__name__}: {e}")
    if budget_left():
        try:
            mk.proofs_aunts_device(txs)  # warm the all-levels program
            stages["merkle_proofs_device_ms"] = round(
                best_of(lambda: mk.proofs_aunts_device(txs), reps=2), 1
            )
            plog(
                f"proofs (device levels + aunts): "
                f"{stages['merkle_proofs_device_ms']} ms"
            )
        except Exception as e:
            plog(f"device proofs stage failed: {type(e).__name__}: {e}")

    # ---- pod-scale mesh scaling curve (calibrated + modeled widths) ----
    if budget_left():
        try:
            stages["mesh"] = _mesh_stage_inner(plog)
            plog(
                f"mesh: width {stages['mesh']['n_devices']}, "
                f"{stages['mesh'].get('speedup_widest_vs_1')}x vs 1 device"
            )
        except Exception as e:
            plog(f"mesh stage failed: {type(e).__name__}: {e}")

    # ---- shipped-path configs (BASELINE #2/#4/#5) over the shipped
    # backend: hybrid when the native tier built, device-only otherwise ----
    try:
        from cometbft_tpu import native as _native2

        ship = "hybrid" if _native2.available() else "tpu"
    except Exception:
        ship = "tpu"
    shipped_path_stages(stages, plog, budget_left, backend=ship)

    stages["mesh_counters"] = ek.mesh_counters()
    plog(f"done on {devs[0].platform}")
    with emit_once:
        finished.set()
    emit(_pick_headline(stages), stages, devs[0].platform)


def _resilience_stage(stages: dict, plog) -> None:
    """Supervisor observability (ISSUE 2): drive a deliberately wedged
    primary tier through the ResilientBackend degradation chain and report
    the trip/degradation counters in the JSON line.  Deterministic and
    device-free — every round records what a dead relay actually costs:
    one deadline for the first call, fail-fast after the breaker opens."""
    from cometbft_tpu.sidecar.backend import CpuBackend
    from cometbft_tpu.sidecar.chaos import ChaosBackend
    from cometbft_tpu.sidecar.supervisor import ResilientBackend

    deadline_ms = 200.0
    sup = ResilientBackend(
        [
            ("tpu", ChaosBackend(CpuBackend(), "wedge:1:30000", seed=1)),
            ("cpu", CpuBackend()),
        ],
        deadline_ms=deadline_ms,
        retries=0,
        breaker_threshold=2,
        breaker_cooldown_ms=60_000,
        crosscheck="off",
    )
    pvs, pubs, msgs, sigs = _signed_batch(128, tag=b"resil")
    # Pre-warm the verified-triple cache so the measured wall isolates the
    # supervisor + wedge cost (one deadline), not the anchor's verify time
    # (that's what the other stages measure).
    CpuBackend().batch_verify(pubs, msgs, sigs)
    t1 = time.perf_counter()
    ok, bits = sup.batch_verify(pubs, msgs, sigs)
    first_ms = (time.perf_counter() - t1) * 1000
    assert ok and all(bits), "degraded result must still be correct"
    t1 = time.perf_counter()
    ok, _ = sup.batch_verify(pubs, msgs, sigs)  # wedged worker: fail fast
    second_ms = (time.perf_counter() - t1) * 1000
    assert ok
    c = sup.counters()
    stages["resilience"] = {
        "deadline_ms": deadline_ms,
        "degraded_first_call_ms": round(first_ms, 2),
        "tripped_call_ms": round(second_ms, 2),
        "active_tier": c["active_tier"],
        "trips": c["trips"],
        "deadline_exceeded": c["deadline_exceeded"],
        "degraded_calls": c["degraded_calls"],
    }
    plog(
        f"resilience: wedged-primary call {first_ms:.0f} ms "
        f"(deadline {deadline_ms:.0f}), post-trip {second_ms:.0f} ms, "
        f"active tier {c['active_tier']}, trips {c['trips']}"
    )
    sup.close()


def _coalesce_stage(stages: dict, plog) -> None:
    """Scheduler micro-batching (ISSUE 3): K concurrent SIGS-sig commit
    verifications through the coalescing scheduler vs serialized per-caller
    dispatch.  Both arms run the same commits through the same host-MSM
    backend wrapped with a fixed per-dispatch latency
    (CMTPU_BENCH_DISPATCH_MS, default 50 — the LOW end of the measured
    50-150 ms axon-tunnel fixed cost per device dispatch,
    cometbft_tpu/ops/DESIGN.md), so the number reports what coalescing
    saves when every dispatch pays the device round trip: the serialized
    arm pays it K times, the coalesced arm once or twice.  The simulated
    cost is labeled in the JSON (`simulated_dispatch_ms`; set it to 0 to
    measure raw host MSM coalescing alone)."""
    import threading as _threading

    from cometbft_tpu.crypto import ed25519 as _ed
    from cometbft_tpu.sidecar import backend as _be
    from cometbft_tpu.sidecar.backend import CpuBackend
    from cometbft_tpu.sidecar.scheduler import CoalescingScheduler
    from cometbft_tpu.types import validation

    k = int(os.environ.get("CMTPU_BENCH_COALESCE_K", "8"))
    sigs = int(os.environ.get("CMTPU_BENCH_COALESCE_SIGS", "1024"))
    dispatch_ms = float(os.environ.get("CMTPU_BENCH_DISPATCH_MS", "50"))

    vals, commits = _commit_fixture(sigs, heights=k, tag=b"co")
    plog(f"coalesce fixture built ({k} x {sigs})")
    for _, commit in commits:
        commit.vote_sign_bytes_all("bench-chain")  # warm encodes, both arms

    class _DispatchLatency:
        """CpuBackend plus the fixed per-dispatch cost a device pays."""

        name = "latency"

        def __init__(self):
            self._cpu = CpuBackend()
            self.calls = 0

        def batch_verify(self, pubs, msgs, sigs_):
            self.calls += 1
            if dispatch_ms > 0:
                time.sleep(dispatch_ms / 1000.0)
            return self._cpu.batch_verify(pubs, msgs, sigs_)

        def merkle_root(self, leaves):
            return self._cpu.merkle_root(leaves)

    def _run_commit(i):
        bid, commit = commits[i]
        validation.verify_commit_light("bench-chain", vals, bid, i + 1, commit)

    old_backend = _be._backend
    try:
        # -- serialized per-caller dispatch (the pre-scheduler world) --
        lat = _DispatchLatency()
        _be.set_backend(lat)
        _ed._verified.clear()
        t0 = time.perf_counter()
        for i in range(k):
            _run_commit(i)
        serialized_ms = (time.perf_counter() - t0) * 1000
        assert lat.calls == k

        # -- coalesced: K concurrent callers through the scheduler --
        lat2 = _DispatchLatency()
        sched = CoalescingScheduler(lat2, window_ms=5.0)
        _be.set_backend(sched)
        _ed._verified.clear()
        start = _threading.Barrier(k + 1)
        errors = []

        def _caller(i):
            start.wait()
            try:
                _run_commit(i)
            except Exception as e:  # pragma: no cover - stage must report
                errors.append(e)

        threads = [
            _threading.Thread(target=_caller, args=(i,)) for i in range(k)
        ]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join(300.0)
        coalesced_ms = (time.perf_counter() - t0) * 1000
        if errors:
            raise errors[0]
        c = sched.counters()
        sched.close()
        stages["coalesce"] = {
            "k": k,
            "sigs_per_request": sigs,
            "simulated_dispatch_ms": dispatch_ms,
            "serialized_ms": round(serialized_ms, 2),
            "coalesced_ms": round(coalesced_ms, 2),
            "speedup": round(serialized_ms / max(coalesced_ms, 1e-9), 2),
            "serialized_dispatches": lat.calls,
            "coalesced_dispatches": lat2.calls,
            "coalesce_ratio": c["coalesce_ratio"],
            "queue_wait_p50_ms": c["queue_wait_p50_ms"],
            "queue_wait_p95_ms": c["queue_wait_p95_ms"],
            "fallback_splits": c["fallback_splits"],
        }
        plog(
            f"coalesce: {k}x{sigs} serialized {serialized_ms:.0f} ms "
            f"-> coalesced {coalesced_ms:.0f} ms "
            f"({stages['coalesce']['speedup']}x, "
            f"{lat2.calls} dispatches, ratio {c['coalesce_ratio']})"
        )
    finally:
        _ed._verified.clear()
        _be.set_backend(old_backend)


def _engine_stage(stages: dict, plog) -> None:
    """Continuous-batching engine (ISSUE 14): all four verification classes
    (consensus votes, blocksync prefetch, ingress preverify, light-client
    descent) driven concurrently through ONE VerificationEngine vs the
    pre-engine world of four independent window-then-dispatch batchers over
    the same serialized simulated device (CMTPU_BENCH_ENGINE_DISPATCH_MS
    fixed cost per dispatch, default 5 — same convention as the other
    simulated stages, labeled in the JSON).  The engine arm skips the
    admission window entirely (dispatch sizing happens when the device
    frees up), drains strict-priority so a vote never queues behind bulk,
    and deadline-caps merged growth while a vote is pending.  The headline
    metric is per-class p95 ADMISSION latency — submit until the request
    is on the device, the part of the wall the scheduler controls (both
    arms pay the same simulated dispatch once admitted; end-to-end p95s
    are reported alongside as `*_done_p95_ms`).  Acceptance: consensus
    admission p95 >= 3x better with total dispatches no higher."""
    import threading as _threading

    from cometbft_tpu.sidecar.engine import (
        CLASS_BLOCKSYNC,
        CLASS_CONSENSUS,
        CLASS_INGRESS,
        CLASS_LIGHT,
        CLASS_NAMES,
        VerificationEngine,
    )

    dispatch_ms = float(os.environ.get("CMTPU_BENCH_ENGINE_DISPATCH_MS", "5"))
    votes = int(os.environ.get("CMTPU_BENCH_ENGINE_VOTES", "40"))
    flooders = int(os.environ.get("CMTPU_BENCH_ENGINE_FLOODERS", "3"))
    window_ms = float(os.environ.get("CMTPU_BENCH_ENGINE_WINDOW_MS", "2"))

    class _SimDev:
        """Serialized simulated device: fixed dispatch cost + tiny per-sig
        cost, verdicts from a marker byte (the stage measures scheduling,
        not crypto)."""

        name = "engine-sim"

        def __init__(self):
            self.calls = 0
            self._lock = _threading.Lock()

        def batch_verify(self, pubs, msgs, sigs_, on_start=None):
            with self._lock:
                if on_start is not None:
                    on_start()  # device actually free: admission happened
                self.calls += 1
                time.sleep(dispatch_ms / 1000.0 + len(pubs) * 10e-6)
            return True, [True] * len(pubs)

        def merkle_root(self, leaves):  # pragma: no cover - unused here
            raise NotImplementedError

    def _triples(n, tag):
        pubs = [(b"%s-p-%d" % (tag, i)).ljust(32, b"\x00") for i in range(n)]
        msgs = [b"%s-m-%d" % (tag, i) for i in range(n)]
        sigs_ = [(b"%s-s-%d" % (tag, i)).ljust(64, b"\x01") for i in range(n)]
        return pubs, msgs, sigs_

    class _WindowBatcher:
        """The pre-engine per-surface pattern: a private dispatcher thread
        batches a window from the first waiter, then merges everything
        queued into one dispatch — no cross-class priority, no
        device-freed admission.  Records each request's admission wait
        (submit -> dispatch start) in `waits`."""

        def __init__(self, dev):
            self._dev = dev
            self._cond = _threading.Condition()
            self._queue = []  # (pubs, msgs, sigs, event-box)
            self._closed = False
            self.waits = []  # admission waits, ms
            self._thread = _threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def submit(self, pubs, msgs, sigs_):
            box = {"event": _threading.Event(), "t": time.perf_counter()}
            with self._cond:
                self._queue.append((pubs, msgs, sigs_, box))
                self._cond.notify()
            return box

        def _loop(self):
            while True:
                with self._cond:
                    while not self._queue and not self._closed:
                        self._cond.wait(0.1)
                    if self._closed and not self._queue:
                        return
                time.sleep(window_ms / 1000.0)  # window from first waiter
                with self._cond:
                    batch, self._queue = self._queue, []
                ps = [p for b in batch for p in b[0]]
                ms = [m for b in batch for m in b[1]]
                ss = [s for b in batch for s in b[2]]

                def _admitted(batch=batch):
                    t_disp = time.perf_counter()
                    for _, _, _, box in batch:
                        self.waits.append((t_disp - box["t"]) * 1000)

                _, bits = self._dev.batch_verify(ps, ms, ss, on_start=_admitted)
                off = 0
                for bp, _, _, box in batch:
                    box["bits"] = bits[off : off + len(bp)]
                    off += len(bp)
                    box["event"].set()

        def close(self):
            with self._cond:
                self._closed = True
                self._cond.notify()
            self._thread.join(5.0)

    def _drive(submit):
        """Shared mixed workload.  submit(klass, n, tag) -> wait().
        Returns {class_name: [admission_ms, ...]}."""
        lat = {name: [] for name in CLASS_NAMES}
        llock = _threading.Lock()
        stop = _threading.Event()

        def _timed(klass, n, tag):
            t0 = time.perf_counter()
            submit(klass, n, tag)()
            ms = (time.perf_counter() - t0) * 1000
            with llock:
                lat[CLASS_NAMES[klass]].append(ms)

        def _flood(klass, n, tid, pause_s=0.0):
            i = 0
            while not stop.is_set():
                _timed(klass, n, b"%d-%d-%d" % (klass, tid, i))
                i += 1
                if pause_s:
                    time.sleep(pause_s)

        threads = [
            _threading.Thread(target=_flood, args=(CLASS_INGRESS, 16, t))
            for t in range(flooders)
        ]
        threads.append(
            _threading.Thread(target=_flood, args=(CLASS_BLOCKSYNC, 64, 90))
        )
        threads.append(
            _threading.Thread(
                target=_flood, args=(CLASS_LIGHT, 8, 91), kwargs={"pause_s": 0.003}
            )
        )
        for t in threads:
            t.start()
        time.sleep(0.02)  # let the floods saturate the device first
        for i in range(votes):
            _timed(CLASS_CONSENSUS, 2, b"vote-%d" % i)
            time.sleep(0.002)
        stop.set()
        for t in threads:
            t.join(60.0)
        return lat

    def _p95(xs):
        if not xs:
            return 0.0
        return sorted(xs)[min(len(xs) - 1, int(0.95 * len(xs)))]

    # -- baseline: four independent window batchers, one serialized device --
    dev_base = _SimDev()
    batchers = [_WindowBatcher(dev_base) for _ in CLASS_NAMES]

    def _submit_base(klass, n, tag):
        box = batchers[klass].submit(*_triples(n, tag))
        return lambda: box["event"].wait(60.0)

    base_lat = _drive(_submit_base)
    for b in batchers:
        b.close()

    # -- engine: one continuous-batching queue, all classes --
    dev_eng = _SimDev()
    eng = VerificationEngine(dev_eng, hold_ms=0, max_sigs=16384)
    try:
        def _submit_eng(klass, n, tag):
            fut = eng.submit(*_triples(n, tag), klass=klass)
            return lambda: fut.result(60.0)

        eng_lat = _drive(_submit_eng)
        eng_counters = eng.counters()
    finally:
        eng.close()

    per_class = {}
    for klass, name in enumerate(CLASS_NAMES):
        per_class[name] = {
            # Headline: admission wait, submit -> on the device.
            "baseline_p95_ms": round(_p95(batchers[klass].waits), 2),
            "engine_p95_ms": round(
                eng_counters["classes"][name]["p95_us"] / 1000.0, 2
            ),
            # End-to-end (admission + the shared simulated dispatch).
            "baseline_done_p95_ms": round(_p95(base_lat[name]), 2),
            "engine_done_p95_ms": round(_p95(eng_lat[name]), 2),
            "baseline_n": len(base_lat[name]),
            "engine_n": len(eng_lat[name]),
        }
    cons = per_class["consensus"]
    speedup = round(
        cons["baseline_p95_ms"] / max(cons["engine_p95_ms"], 1e-9), 2
    )
    stages["engine"] = {
        "simulated_dispatch_ms": dispatch_ms,
        "votes": votes,
        "flooders": flooders,
        "baseline_window_ms": window_ms,
        "classes": per_class,
        "baseline_dispatches": dev_base.calls,
        "engine_dispatches": dev_eng.calls,
        "consensus_p95_speedup": speedup,
        "starvation_promotions": sum(
            c["starvation_promotions"] for c in eng_counters["classes"].values()
        ),
    }
    plog(
        f"engine: consensus p95 {cons['baseline_p95_ms']} ms -> "
        f"{cons['engine_p95_ms']} ms ({speedup}x), dispatches "
        f"{dev_base.calls} -> {dev_eng.calls}"
    )


def _ingress_stage(stages: dict, plog) -> None:
    """QoS ingress admission (ISSUE 5): K concurrent senders flood signed
    envelopes; serialized per-tx verification admission (the pre-ingress
    world — every tx pays its own backend dispatch) vs the ingress
    pipeline's micro-batched pre-verification through the coalescing
    scheduler.  Same convention as the coalesce stage: both arms run the
    same host-MSM backend wrapped with a fixed per-dispatch latency
    (CMTPU_BENCH_INGRESS_DISPATCH_MS, default 5 — deliberately far below
    the coalesce stage's 50 ms tunnel cost, because the serialized arm
    pays it K*TXS times and the stage must stay inside the bench budget;
    the JSON labels it)."""
    import threading as _threading

    from cometbft_tpu.abci.example.kvstore import KVStoreApplication
    from cometbft_tpu.config.config import MempoolConfig
    from cometbft_tpu.crypto import ed25519 as _ed
    from cometbft_tpu.mempool.clist_mempool import CListMempool
    from cometbft_tpu.mempool.ingress import IngressPipeline, decode_envelope, encode_envelope
    from cometbft_tpu.proxy import LocalClientCreator
    from cometbft_tpu.sidecar import backend as _be
    from cometbft_tpu.sidecar.backend import CpuBackend
    from cometbft_tpu.sidecar.scheduler import CoalescingScheduler

    k = int(os.environ.get("CMTPU_BENCH_INGRESS_SENDERS", "8"))
    per = int(os.environ.get("CMTPU_BENCH_INGRESS_TXS", "512"))
    dispatch_ms = float(os.environ.get("CMTPU_BENCH_INGRESS_DISPATCH_MS", "5"))
    total = k * per

    privs = [_ed.gen_priv_key_from_secret(b"ing-%d" % i) for i in range(k)]
    floods = [
        [
            encode_envelope(privs[i], b"ing/%d/%d=v" % (i, j), priority=i % 4, nonce=j)
            for j in range(per)
        ]
        for i in range(k)
    ]
    plog(f"ingress fixture built ({k} senders x {per} envelopes)")

    class _DispatchLatency:
        name = "latency"

        def __init__(self):
            self._cpu = CpuBackend()
            self.calls = 0

        def batch_verify(self, pubs, msgs, sigs_):
            self.calls += 1
            if dispatch_ms > 0:
                time.sleep(dispatch_ms / 1000.0)
            return self._cpu.batch_verify(pubs, msgs, sigs_)

        def merkle_root(self, leaves):
            return self._cpu.merkle_root(leaves)

    def _fresh_mempool():
        app = KVStoreApplication()
        cli = LocalClientCreator(app).new_abci_client()
        return CListMempool(MempoolConfig(size=total * 2, cache_size=total * 2), cli)

    old_backend = _be._backend
    try:
        # -- serialized: each tx verified with its own dispatch, then admitted --
        lat = _DispatchLatency()
        _be.set_backend(lat)
        _ed._verified.clear()
        mp1 = _fresh_mempool()
        start = _threading.Barrier(k + 1)

        def _serial_sender(i):
            start.wait()
            for tx in floods[i]:
                env = decode_envelope(tx)
                ok, bits = _be.get_backend().batch_verify(
                    [env.pubkey], [env.sign_bytes()], [env.signature]
                )
                if bits[0]:
                    mp1.check_tx(tx, sender=env.sender)

        threads = [_threading.Thread(target=_serial_sender, args=(i,)) for i in range(k)]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join(600.0)
        serialized_ms = (time.perf_counter() - t0) * 1000
        assert lat.calls == total and mp1.size() == total

        # -- batched: the ingress pipeline's micro-batched preverify --
        lat2 = _DispatchLatency()
        sched = CoalescingScheduler(lat2, window_ms=2.0)
        _be.set_backend(sched)
        _ed._verified.clear()
        mp2 = _fresh_mempool()
        ing = IngressPipeline(
            MempoolConfig(
                size=total * 2,
                cache_size=total * 2,
                ingress_queue_max=total,
                ingress_window_ms=2.0,
            ),
            mp2,
        )
        start2 = _threading.Barrier(k + 1)

        def _ingress_sender(i):
            start2.wait()
            for tx in floods[i]:
                ing.check_tx(tx)

        threads = [_threading.Thread(target=_ingress_sender, args=(i,)) for i in range(k)]
        for t in threads:
            t.start()
        start2.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join(600.0)
        deadline = time.monotonic() + 120.0
        while mp2.size() < total and time.monotonic() < deadline:
            time.sleep(0.002)
        batched_ms = (time.perf_counter() - t0) * 1000
        st = ing.stats()
        ing.close()
        sched.close()
        if mp2.size() != total:
            raise RuntimeError(f"ingress arm admitted {mp2.size()}/{total}")
        stages["ingress"] = {
            "senders": k,
            "txs_per_sender": per,
            "simulated_dispatch_ms": dispatch_ms,
            "serialized_ms": round(serialized_ms, 2),
            "batched_ms": round(batched_ms, 2),
            "speedup": round(serialized_ms / max(batched_ms, 1e-9), 2),
            "serialized_dispatches": lat.calls,
            "batched_dispatches": lat2.calls,
            "preverify_batches": st["preverify_batches"],
            "preverify_batch_max": st["preverify_batch_max"],
            "admitted": st["admitted"],
            "shed_total": st["shed_total"],
        }
        plog(
            f"ingress: {k}x{per} serialized {serialized_ms:.0f} ms "
            f"-> batched {batched_ms:.0f} ms "
            f"({stages['ingress']['speedup']}x, {lat2.calls} dispatches, "
            f"max preverify batch {st['preverify_batch_max']})"
        )
    finally:
        _ed._verified.clear()
        _be.set_backend(old_backend)


def _hotpath_stage(stages: dict, plog) -> None:
    """Consensus hot path (ISSUE 6): vote-admission micro-batching A/B plus
    a devnet before/after.

    Micro-stage: K peers x M precommits each, admitted into K INDEPENDENT
    VoteSets — one VoteSet serializes admissions on its own mutex (the
    reference's addVote locking), so the window-sharing surface is many
    in-process nodes, the devnet shape.  The serialized arm pays one device
    dispatch per vote (SigBatcher inline mode); the batched arm lets the
    concurrent admissions share CMTPU_VOTE_BATCH_WINDOW_MS windows.  Both
    arms run the same votes over the same host-crypto backend wrapped with
    a fixed per-dispatch latency (CMTPU_BENCH_HOTPATH_DISPATCH_MS, default
    20 ms — well under the 50-150 ms the axon tunnel actually measures per
    dispatch), and the latency backend SERIALIZES dispatches: one device
    executes one dispatch at a time, so overlapping the sleeps would model
    an infinitely parallel device and hide exactly the cost batching
    removes.  The simulated cost is labeled in the JSON
    (`simulated_dispatch_ms`; 0 measures raw host-crypto batching alone).

    Devnet sub-stage: the in-process devnet run twice over real TCP —
    hot-path features forced off (window 0, pipeline off, group commit off)
    vs on — reporting blocks/s + tx/s for both arms.  On one host the
    in-process nodes share the verified-triple cache and consensus is
    timeout-paced, so this arm is expected to be flat; it is reported so
    the micro-stage's dispatch-bound win is never mistaken for a claim
    about timeout-bound block rate."""
    import threading as _threading

    from cometbft_tpu.crypto import ed25519 as _ed
    from cometbft_tpu.crypto import sigbatch
    from cometbft_tpu.sidecar import backend as _be
    from cometbft_tpu.sidecar.backend import CpuBackend
    from cometbft_tpu.state import make_genesis_state
    from cometbft_tpu.types import BlockID, GenesisDoc, GenesisValidator, Time, Vote
    from cometbft_tpu.types.block import PRECOMMIT_TYPE
    from cometbft_tpu.types.part_set import PartSetHeader
    from cometbft_tpu.types.priv_validator import MockPV
    from cometbft_tpu.types.vote_set import VoteSet

    k = int(os.environ.get("CMTPU_BENCH_HOTPATH_PEERS", "8"))
    per = int(os.environ.get("CMTPU_BENCH_HOTPATH_VOTES", "16"))
    dispatch_ms = float(os.environ.get("CMTPU_BENCH_HOTPATH_DISPATCH_MS", "20"))
    chain_id = "bench-hotpath"
    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))

    class _DeviceLatency:
        """CpuBackend plus the fixed per-dispatch device cost; the lock is
        the device itself — dispatches execute one at a time."""

        name = "latency"

        def __init__(self):
            self._cpu = CpuBackend()
            self._mtx = _threading.Lock()
            self.calls = 0

        def batch_verify(self, pubs, msgs, sigs_):
            with self._mtx:
                self.calls += 1
                if dispatch_ms > 0:
                    time.sleep(dispatch_ms / 1000.0)
                return self._cpu.batch_verify(pubs, msgs, sigs_)

        def merkle_root(self, leaves):
            return self._cpu.merkle_root(leaves)

    def _mk_rig(tag):
        pvs = [MockPV() for _ in range(per)]
        gen = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Time(1700000000, 0),
            validators=[
                GenesisValidator(pv.address(), pv.get_pub_key(), 10, "")
                for pv in pvs
            ],
        )
        gen.validate_and_complete()
        vals = make_genesis_state(gen).validators
        by_addr = {pv.address(): pv for pv in pvs}
        ordered = [by_addr[v.address] for v in vals.validators]
        votes = [
            pv.sign_vote(
                chain_id,
                Vote(
                    type=PRECOMMIT_TYPE, height=1, round=0, block_id=bid,
                    timestamp=Time(1700000001, tag),
                    validator_address=pv.address(), validator_index=i,
                ),
            )
            for i, pv in enumerate(ordered)
        ]
        return vals, votes

    rigs = [_mk_rig(i) for i in range(k)]
    plog(f"hotpath fixture built ({k} peers x {per} votes)")

    def _admit_arm(batcher):
        old_b = sigbatch.set_batcher(batcher)
        with _ed._verified_lock:
            _ed._verified.clear()
        errs: list[str] = []
        sums: list[int] = []
        lock = _threading.Lock()
        barrier = _threading.Barrier(k)

        def worker(vals, votes):
            vs = VoteSet(chain_id, 1, 0, PRECOMMIT_TYPE, vals)
            barrier.wait()
            for v in votes:
                try:
                    if not vs.add_vote(v):
                        with lock:
                            errs.append("vote not added")
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errs.append(repr(e))
            with lock:
                sums.append(vs.sum)

        threads = [
            _threading.Thread(target=worker, args=rig, daemon=True)
            for rig in rigs
        ]
        t1 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        dt = time.perf_counter() - t1
        sigbatch.set_batcher(old_b)
        assert not errs, f"hotpath arm rejected valid votes: {errs[:3]}"
        assert sums == [per * 10] * k, "a valid vote was dropped"
        return dt

    lat = _DeviceLatency()
    old_backend = _be._backend
    _be.set_backend(lat)
    try:
        ser_s = _admit_arm(sigbatch.SigBatcher(window_ms=0, inline=True))
        ser_dispatches = lat.calls
        batched = sigbatch.SigBatcher(window_ms=2)
        bat_s = _admit_arm(batched)
        bat_dispatches = lat.calls - ser_dispatches
        bc = batched.counters()
    finally:
        with _ed._verified_lock:
            _ed._verified.clear()
        _be.set_backend(old_backend)

    st = {
        "peers": k,
        "votes_per_peer": per,
        "simulated_dispatch_ms": dispatch_ms,
        "serialized_ms": round(ser_s * 1000, 1),
        "batched_ms": round(bat_s * 1000, 1),
        "speedup": round(ser_s / bat_s, 2) if bat_s > 0 else 0.0,
        "serialized_dispatches": ser_dispatches,
        "batched_dispatches": bat_dispatches,
        "batched_max_batch": bc["max_batch"],
        "batched_fallbacks": bc["fallbacks"],
    }
    plog(
        f"hotpath votes: serialized {st['serialized_ms']:.0f} ms "
        f"({ser_dispatches} dispatches) -> batched {st['batched_ms']:.0f} ms "
        f"({bat_dispatches} dispatches, max batch {bc['max_batch']}): "
        f"{st['speedup']}x @ {dispatch_ms:.0f} ms simulated dispatch"
    )

    # ---- devnet before/after: the same system stage, features off vs on ----
    n_vals = int(os.environ.get("CMTPU_BENCH_HOTPATH_VALS", "4"))
    blocks = int(os.environ.get("CMTPU_BENCH_HOTPATH_BLOCKS", "40"))
    knobs = (
        "CMTPU_VOTE_BATCH_WINDOW_MS",
        "CMTPU_BLOCKSYNC_PIPELINE",
        "CMTPU_WAL_GROUP_MS",
    )
    saved = {kk: os.environ.get(kk) for kk in knobs}

    def _devnet_arm(window, pipeline, group):
        os.environ["CMTPU_VOTE_BATCH_WINDOW_MS"] = window
        os.environ["CMTPU_BLOCKSYNC_PIPELINE"] = pipeline
        os.environ["CMTPU_WAL_GROUP_MS"] = group
        sigbatch.reset()  # singleton re-reads the window env on next use
        with _ed._verified_lock:
            _ed._verified.clear()
        return _devnet_throughput(
            seconds=15.0, n_vals=n_vals, target_blocks=blocks
        )

    try:
        bps0, tps0 = _devnet_arm("0", "0", "0")
        bps1, tps1 = _devnet_arm("2", "1", "2")
    finally:
        for kk, vv in saved.items():
            if vv is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = vv
        sigbatch.reset()
    st.update(
        {
            "devnet_vals": n_vals,
            "devnet_target_blocks": blocks,
            "devnet_before_blocks_per_s": round(bps0, 2),
            "devnet_before_tx_per_s": round(tps0, 1),
            "devnet_after_blocks_per_s": round(bps1, 2),
            "devnet_after_tx_per_s": round(tps1, 1),
            "devnet_speedup": round(bps1 / bps0, 2) if bps0 > 0 else 0.0,
        }
    )
    stages["hotpath"] = st
    plog(
        f"hotpath devnet ({n_vals} vals, {blocks}-block target): "
        f"off {bps0:.2f} blocks/s {tps0:.0f} tx/s -> "
        f"on {bps1:.2f} blocks/s {tps1:.0f} tx/s ({st['devnet_speedup']}x)"
    )


def _simnet_stage(stages: dict, plog) -> None:
    """Virtual-clock scenario throughput (ISSUE 13): VALS validators commit
    BLOCKS blocks in-process on one SimClock with a seeded WAN latency
    matrix.  Three arms on the same seed — baseline, vote-admission window
    armed (the sim analog of CMTPU_VOTE_BATCH_WINDOW_MS), and tx load
    injected — reporting blocks per simulated second, the sim-time /
    wall-time acceleration, and the block-rate deltas across arms.  Knobs:
    CMTPU_BENCH_SIMNET_VALS (100), CMTPU_BENCH_SIMNET_BLOCKS (20),
    CMTPU_BENCH_SIMNET_WINDOW_MS (50)."""
    from cometbft_tpu.simnet.scenario import run_scenario

    vals = int(os.environ.get("CMTPU_BENCH_SIMNET_VALS", "") or 100)
    blocks = int(os.environ.get("CMTPU_BENCH_SIMNET_BLOCKS", "") or 20)
    window = float(os.environ.get("CMTPU_BENCH_SIMNET_WINDOW_MS", "") or 50.0)
    base = dict(
        validators=vals, blocks=blocks, seed=1234,
        max_sim_s=40.0 * blocks + 120.0,
    )

    def _arm(name: str, **kw) -> dict:
        rep = run_scenario(**{**base, **kw})
        committed = rep["height_node0"] - 1
        rate = (
            round(committed / rep["sim_time_s"], 4) if rep["sim_time_s"] else 0.0
        )
        out = {
            "ok": rep["ok"],
            "sim_blocks_per_s": rate,
            "sim_time_s": rep["sim_time_s"],
            "wall_time_s": rep["wall_time_s"],
            "accel": rep["accel"],
            "events": rep["events"],
            "vote_dispatches": rep["counters"]["vote_dispatches"],
        }
        plog(
            f"simnet[{name}]: {committed} blocks, {rate} blocks/sim-s, "
            f"{rep['accel']}x accel ({rep['wall_time_s']:.1f}s wall)"
        )
        return out

    arms = {
        "base": _arm("base"),
        "vote_window": _arm("vote_window", vote_window_ms=window),
        "tx_load": _arm("tx_load", tx_interval_s=1.0, txs_per_interval=8),
    }
    b = arms["base"]["sim_blocks_per_s"] or 1.0
    stages["simnet"] = {
        "validators": vals,
        "blocks": blocks,
        "vote_window_ms": window,
        **{f"{k}_{m}": v for k, a in arms.items() for m, v in a.items()},
        "block_rate_vote_window_ratio": round(
            arms["vote_window"]["sim_blocks_per_s"] / b, 3
        ),
        "block_rate_tx_load_ratio": round(
            arms["tx_load"]["sim_blocks_per_s"] / b, 3
        ),
    }


def _byz_stage(stages: dict, plog) -> None:
    """Byzantine simnet accountability (ISSUE 19): the same seeded scenario
    run honest, with an equivocator under a partition+heal, and with a
    vote-flooder.  Reports the evidence pipeline's sim-latency (conflict
    detection -> DuplicateVoteEvidence committed in a block), the honest
    block-rate ratio under each adversary, and post-window recovery lag.
    Knobs: CMTPU_BENCH_BYZ_VALS (20), CMTPU_BENCH_BYZ_BLOCKS (10),
    CMTPU_BENCH_BYZ_FLOOD_HZ (10)."""
    from cometbft_tpu.simnet.scenario import run_scenario

    vals = int(os.environ.get("CMTPU_BENCH_BYZ_VALS", "") or 20)
    blocks = int(os.environ.get("CMTPU_BENCH_BYZ_BLOCKS", "") or 10)
    flood_hz = float(os.environ.get("CMTPU_BENCH_BYZ_FLOOD_HZ", "") or 10.0)
    base = dict(
        validators=vals, blocks=blocks, seed=1234, jitter_ms=5.0,
        max_sim_s=40.0 * blocks + 200.0,
        partitions=[{"at_s": 20.0, "heal_s": 45.0, "fraction": 0.5}],
    )

    def _arm(name: str, **kw) -> dict:
        rep = run_scenario(**{**base, **kw})
        committed = rep["height_node0"] - 1
        rate = (
            round(committed / rep["sim_time_s"], 4) if rep["sim_time_s"] else 0.0
        )
        ev = rep["evidence"]
        out = {
            "ok": rep["ok"],
            "safety_ok": rep["safety_ok"],
            "sim_blocks_per_s": rate,
            "sim_time_s": rep["sim_time_s"],
            "accel": rep["accel"],
            "evidence_detections": ev["detections"],
            "evidence_committed": ev["committed_count"],
            "evidence_commit_sim_s": ev["first_commit_sim_s"],
            "detect_to_commit_s": ev["detect_to_commit_s"],
            "recovery_lag_s": rep["recovery"].get("recovery_lag_s"),
        }
        plog(
            f"byz[{name}]: {committed} blocks, {rate} blocks/sim-s, "
            f"safety={rep['safety_ok']}, "
            f"evidence {ev['detections']} detected / "
            f"{ev['committed_count']} committed"
            + (
                f" (detect->commit {ev['detect_to_commit_s']} sim-s)"
                if ev["detect_to_commit_s"] is not None else ""
            )
        )
        return out

    arms = {
        "honest": _arm("honest"),
        "equivocator": _arm(
            "equivocator",
            byzantine=[{
                "role": "equivocator", "node": 1, "from_s": 10.0,
                "until_s": 50.0, "only_partitioned": True,
            }],
        ),
        "vote_flood": _arm(
            "vote_flood",
            byzantine=[{
                "role": "flooder", "node": 1, "from_s": 10.0,
                "until_s": 50.0, "rate_hz": flood_hz,
            }],
        ),
    }
    b = arms["honest"]["sim_blocks_per_s"] or 1.0
    stages["byz"] = {
        "validators": vals,
        "blocks": blocks,
        "flood_hz": flood_hz,
        **{f"{k}_{m}": v for k, a in arms.items() for m, v in a.items()},
        "block_rate_equivocator_ratio": round(
            arms["equivocator"]["sim_blocks_per_s"] / b, 3
        ),
        "block_rate_vote_flood_ratio": round(
            arms["vote_flood"]["sim_blocks_per_s"] / b, 3
        ),
    }


def _lightgw_stage(stages: dict, plog) -> None:
    """Light-client gateway (ISSUE 7): N concurrent light clients sync the
    same span, independent bisections vs one shared gateway.

    Arm A (the pre-gateway world): N clients bisect serially, each with a
    cold verified-triple cache — every client re-pays every hop's
    dispatch.  Arm B: the same N clients swarm a shared LightGateway whose
    descent plan is computed once and whose hop verifications land in the
    coalescing scheduler; the clients' mandatory re-verification then hits
    the warm shared cache.  Both arms run the same host-MSM backend
    wrapped with a fixed per-dispatch latency
    (CMTPU_BENCH_LIGHTGW_DISPATCH_MS, default 20 — labeled in the JSON;
    0 measures raw host coalescing).  The stage also reports the cold-sync
    story: the MMR inclusion-proof wire size (`lightgw_proof_bytes`,
    client-verified) vs shipping every block the bisection trace touches."""
    import threading as _threading

    from cometbft_tpu.crypto import ed25519 as _ed
    from cometbft_tpu.libs.db import MemDB
    from cometbft_tpu.light.client import Client, TrustOptions
    from cometbft_tpu.light.gateway import LightGateway
    from cometbft_tpu.light.mmr import verify_inclusion
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.sidecar import backend as _be
    from cometbft_tpu.sidecar.backend import CpuBackend
    from cometbft_tpu.sidecar.scheduler import CoalescingScheduler
    from cometbft_tpu.types import Time as _Time

    n_clients = int(os.environ.get("CMTPU_BENCH_LIGHTGW_CLIENTS", "8"))
    height = int(os.environ.get("CMTPU_BENCH_LIGHTGW_HEIGHT", "120"))
    dispatch_ms = float(os.environ.get("CMTPU_BENCH_LIGHTGW_DISPATCH_MS", "20"))

    # 32-validator sets rotating 1/height: a 1 -> height jump dilutes trust
    # below 1/3 within ~22 heights, forcing a real multi-hop descent while
    # the lazily-signed fixture stays far cheaper than the 4,096-val
    # light_bisection stage.
    chain = _LazyChain(n_vals=32, rotate=1, heights=height)
    lb1 = chain.light_block(1)
    now = lambda: _Time(1700000000 + 10 * height + 600, 0)
    opts = TrustOptions(
        period_ns=365 * 24 * 3600 * 10**9, height=1, hash=lb1.hash()
    )

    def _fresh_client(gateway=None):
        return Client(
            chain.CHAIN_ID, opts, chain.provider(), [], LightStore(MemDB()),
            gateway=gateway, gateway_proofs=False,
        )

    class _DispatchLatency:
        """CpuBackend plus the fixed per-dispatch cost a device pays."""

        name = "latency"

        def __init__(self):
            self._cpu = CpuBackend()
            self.calls = 0

        def batch_verify(self, pubs, msgs, sigs_):
            self.calls += 1
            if dispatch_ms > 0:
                time.sleep(dispatch_ms / 1000.0)
            return self._cpu.batch_verify(pubs, msgs, sigs_)

        def merkle_root(self, leaves):
            return self._cpu.merkle_root(leaves)

    # Materialize the fixture blocks (provider-side OpenSSL signing cost,
    # not client cost) and record the bisection trace for the byte count.
    warm = _fresh_client()
    lb = warm.verify_light_block_at_height(height, now=now())
    assert lb.height == height
    trace_heights = sorted(warm.store._heights())
    bisection_bytes = sum(
        len(chain.light_block(h).encode()) for h in trace_heights
    )
    plog(
        f"lightgw fixture built ({chain.built} headers, "
        f"{len(trace_heights)}-hop trace)"
    )

    old_backend = _be._backend
    try:
        # -- arm A: N independent bisections, serialized cold clients --
        lat = _DispatchLatency()
        _be.set_backend(lat)
        solo_ms = []
        for _ in range(n_clients):
            _ed._verified.clear()
            t0 = time.perf_counter()
            assert _fresh_client().verify_light_block_at_height(
                height, now=now()
            ).height == height
            solo_ms.append((time.perf_counter() - t0) * 1000)
        serialized_ms = sum(solo_ms)

        # -- arm B: shared gateway, coalesced dispatch, one warm cache --
        lat2 = _DispatchLatency()
        sched = CoalescingScheduler(lat2, window_ms=5.0)
        _be.set_backend(sched)
        _ed._verified.clear()
        gw = LightGateway(chain.CHAIN_ID, chain.provider())
        swarm_ms: list = [0.0] * n_clients
        errors: list = []
        start = _threading.Barrier(n_clients + 1)

        def _sync(i):
            try:
                start.wait()
                t0 = time.perf_counter()
                c = _fresh_client(gateway=gw)
                assert c.verify_light_block_at_height(
                    height, now=now()
                ).height == height
                swarm_ms[i] = (time.perf_counter() - t0) * 1000
                if c.gateway_stats["fallbacks"]:
                    errors.append(RuntimeError("gateway fallback in bench"))
            except Exception as e:  # pragma: no cover - stage must report
                errors.append(e)

        threads = [
            _threading.Thread(target=_sync, args=(i,)) for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join(300.0)
        swarm_wall_ms = (time.perf_counter() - t0) * 1000
        for i, t in enumerate(threads):
            if t.is_alive():
                # A hung client would leave swarm_ms[i] at 0.0 and skew
                # swarm_p95/speedup — fail the stage loudly instead.
                errors.append(RuntimeError(
                    f"lightgw swarm client {i} still running after 300s join"
                ))
        if errors:
            raise errors[0]
        c = sched.counters()
        sched.close()

        # -- cold sync: one MMR proof instead of the whole trace --
        proof = gw.prove(height, anchor_height=1)
        verify_inclusion(
            proof["root"], proof["size"], height - 1,
            proof["target"]["aunts"], proof["light_block"].hash(),
        )
        verify_inclusion(
            proof["root"], proof["size"], 0, proof["anchor"]["aunts"],
            lb1.hash(),
        )

        p95 = lambda xs: sorted(xs)[max(0, int(0.95 * (len(xs) - 1)))]
        gw_stats = gw.stats()
        stages["lightgw"] = {
            "clients": n_clients,
            "height": height,
            "trace_hops": len(trace_heights),
            "simulated_dispatch_ms": dispatch_ms,
            "serialized_ms": round(serialized_ms, 2),
            "swarm_wall_ms": round(swarm_wall_ms, 2),
            "speedup": round(serialized_ms / max(swarm_wall_ms, 1e-9), 2),
            "solo_p95_ms": round(p95(solo_ms), 2),
            "swarm_p95_ms": round(p95(swarm_ms), 2),
            "serialized_dispatches": lat.calls,
            "swarm_dispatches": lat2.calls,
            "coalesce_ratio": c["coalesce_ratio"],
            "plan_misses": gw_stats["plan_misses"],
            "plan_shared": gw_stats["plan_hits"] + gw_stats["plan_waits"],
            "lightgw_proof_bytes": proof["bytes"],
            "bisection_bytes": bisection_bytes,
            "proof_bytes_ratio": round(bisection_bytes / proof["bytes"], 1),
        }
        plog(
            f"lightgw: {n_clients} clients to {height}: serialized "
            f"{serialized_ms:.0f} ms -> swarm {swarm_wall_ms:.0f} ms "
            f"({stages['lightgw']['speedup']}x, {lat2.calls} dispatches); "
            f"cold proof {proof['bytes']} B vs {bisection_bytes} B "
            f"({stages['lightgw']['proof_bytes_ratio']}x)"
        )
    finally:
        _ed._verified.clear()
        _be.set_backend(old_backend)


def _bundle_stage(stages: dict, plog) -> None:
    """Checkpoint bundles (ISSUE 20): N clients cold-sync to a checkpoint,
    one shared cached bundle vs per-client gateway proofs vs per-client
    bisection.

    Every interaction with the origin node is billed one simulated RTT
    (CMTPU_BENCH_BUNDLE_RTT_MS, default 20) and its wire bytes counted.
    The trust anchor (height 1) ships in client config — no arm pays for
    it.  Arm `bundle`: the FIRST client pulls the checkpoint artifact; the
    rest read a dumb shared cache (content addressing is what makes that
    cache safe), and the target light block rides inside the bundle — one
    origin round trip for the whole swarm.  Arm `gateway_proof`: each
    client fetches the target AND calls light_proof.  Arm `bisection`:
    each client fetches the target and bisects (no-rotation chain: the
    1 -> target hop verifies directly, so this is the floor the bundle
    trace must be bit-identical to).  The stage asserts the acceptance
    bar: >= 3x fewer origin round trips AND >= 3x fewer total wire bytes
    than the gateway-proof arm, with bundle-arm trust decisions (stored
    trace heights + hashes) bit-identical to plain bisection."""
    import threading as _threading

    from cometbft_tpu.libs.db import MemDB
    from cometbft_tpu.light.bundle import Bundle
    from cometbft_tpu.light.client import Client, TrustOptions
    from cometbft_tpu.light.gateway import LightGateway
    from cometbft_tpu.light.origin import BundleOrigin
    from cometbft_tpu.light.provider import MockProvider
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.types import Time as _Time

    n_clients = int(os.environ.get("CMTPU_BENCH_BUNDLE_CLIENTS", "8"))
    height = int(os.environ.get("CMTPU_BENCH_BUNDLE_HEIGHT", "120"))
    interval = int(os.environ.get("CMTPU_BENCH_BUNDLE_INTERVAL", str(height)))
    rtt_ms = float(os.environ.get("CMTPU_BENCH_BUNDLE_RTT_MS", "20"))

    chain = _LazyChain(n_vals=32, rotate=0, heights=height)
    lb1 = chain.light_block(1)
    now = lambda: _Time(1700000000 + 10 * height + 600, 0)
    opts = TrustOptions(
        period_ns=365 * 24 * 3600 * 10**9, height=1, hash=lb1.hash()
    )

    origin = BundleOrigin(chain.CHAIN_ID, chain.provider(), interval=interval)
    t0 = time.perf_counter()
    bname, bdata, boundary = origin.get_encoded(0)
    build_ms = (time.perf_counter() - t0) * 1000
    anchor = Bundle.decode(bdata).anchor
    plog(
        f"bundle fixture built: checkpoint {boundary}, {len(bdata)} B "
        f"({build_ms:.0f} ms origin-side build)"
    )

    class _Meter:
        """One origin round trip = one billed RTT + the bytes shipped."""

        def __init__(self):
            self.trips = 0
            self.bytes = 0
            self._lock = _threading.Lock()

        def bill(self, nbytes):
            with self._lock:
                self.trips += 1
                self.bytes += nbytes
            if rtt_ms > 0:
                time.sleep(rtt_ms / 1000.0)

    class _RemoteProvider:
        """Height 1 is the baked-in trust root (free); everything else is
        an origin round trip."""

        def __init__(self, meter):
            self._meter = meter

        def chain_id(self):
            return chain.CHAIN_ID

        def light_block(self, h):
            lb = chain.light_block(h if h else boundary)
            if lb.height != 1:
                self._meter.bill(len(lb.encode()))
            return lb

        def report_evidence(self, ev):
            pass

    class _RemoteGateway:
        def __init__(self, gw, meter):
            self._gw = gw
            self._meter = meter

        def prove(self, height_, anchor_height=0):
            resp = self._gw.prove(height_, anchor_height=anchor_height)
            self._meter.bill(int(resp.get("bytes", 0)))
            return resp

        def plan(self, *a, **kw):
            resp = self._gw.plan(*a, **kw)
            self._meter.bill(0)
            return resp

    class _CachedSource:
        """The CDN edge: one origin pull, then every client reads the
        content-addressed blob locally."""

        def __init__(self, meter):
            self._meter = meter
            self._lock = _threading.Lock()
            self._data = None

        def bundle(self, height_=0):
            with self._lock:
                if self._data is None:
                    _, data, _ = origin.get_encoded(height_)
                    self._meter.bill(len(data))
                    self._data = data
            return self._data

    def _swarm(make_client):
        times: list = [0.0] * n_clients
        stores: list = [None] * n_clients
        errors: list = []
        start = _threading.Barrier(n_clients + 1)

        def _run(i):
            try:
                start.wait()
                t1 = time.perf_counter()
                c = make_client()
                assert c.verify_light_block_at_height(
                    boundary, now=now()
                ).height == boundary
                times[i] = (time.perf_counter() - t1) * 1000
                stores[i] = c
            except Exception as e:  # pragma: no cover - stage must report
                errors.append(e)

        threads = [
            _threading.Thread(target=_run, args=(i,)) for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        start.wait()
        t1 = time.perf_counter()
        for t in threads:
            t.join(300.0)
        wall = (time.perf_counter() - t1) * 1000
        if errors:
            raise errors[0]
        if any(t.is_alive() for t in threads):
            raise RuntimeError("bundle swarm client still running after 300s")
        return times, wall, stores

    p95 = lambda xs: sorted(xs)[max(0, int(0.95 * (len(xs) - 1)))]

    def _arm(meter, times, wall):
        return {
            "origin_round_trips": meter.trips,
            "wire_bytes": meter.bytes,
            "p95_ms": round(p95(times), 2),
            "wall_ms": round(wall, 2),
        }

    # -- arm A: per-client local bisection (the reference decision) --
    m_bis = _Meter()
    times, wall, clients = _swarm(lambda: Client(
        chain.CHAIN_ID, opts, _RemoteProvider(m_bis), [], LightStore(MemDB()),
    ))
    arm_bis = _arm(m_bis, times, wall)
    ref = clients[0]
    ref_trace = {
        h: ref.store.light_block(h).hash() for h in ref.store._heights()
    }

    # -- arm B: per-client gateway MMR proofs --
    gw = LightGateway(chain.CHAIN_ID, chain.provider())
    m_gw = _Meter()
    times, wall, clients = _swarm(lambda: Client(
        chain.CHAIN_ID, opts, _RemoteProvider(m_gw), [], LightStore(MemDB()),
        gateway=_RemoteGateway(gw, m_gw), gateway_proofs=True,
    ))
    arm_gw = _arm(m_gw, times, wall)
    for c in clients:
        if c.gateway_stats["proof_syncs"] != 1:
            raise RuntimeError("gateway arm client missed the proof path")

    # -- arm C: one cached bundle for the whole swarm --
    m_bun = _Meter()
    src = _CachedSource(m_bun)
    times, wall, clients = _swarm(lambda: Client(
        chain.CHAIN_ID, opts,
        MockProvider(chain.CHAIN_ID, {1: lb1, boundary: anchor}),
        [], LightStore(MemDB()), bundle_source=src,
    ))
    arm_bun = _arm(m_bun, times, wall)
    for c in clients:
        if c.gateway_stats["bundle_syncs"] != 1 or \
                c.gateway_stats["bundle_rejects"]:
            raise RuntimeError("bundle arm client missed the bundle path")
        got = {
            h: c.store.light_block(h).hash() for h in c.store._heights()
        }
        if got != ref_trace:
            raise RuntimeError(
                "bundle trust decisions diverge from plain bisection"
            )

    trip_ratio = arm_gw["origin_round_trips"] / max(
        arm_bun["origin_round_trips"], 1
    )
    bytes_ratio = arm_gw["wire_bytes"] / max(arm_bun["wire_bytes"], 1)
    if trip_ratio < 3 or bytes_ratio < 3:
        raise RuntimeError(
            f"bundle arm below the 3x bar: trips {trip_ratio:.1f}x, "
            f"bytes {bytes_ratio:.1f}x vs gateway proofs"
        )
    stages["bundle"] = {
        "clients": n_clients,
        "height": boundary,
        "interval": interval,
        "simulated_rtt_ms": rtt_ms,
        "bundle_bytes": len(bdata),
        "bundle_name": bname,
        "origin_build_ms": round(build_ms, 1),
        "arms": {
            "bisection": arm_bis,
            "gateway_proof": arm_gw,
            "bundle": arm_bun,
        },
        "round_trips_vs_proof": round(trip_ratio, 1),
        "wire_bytes_vs_proof": round(bytes_ratio, 1),
        "trace_identical": True,
    }
    plog(
        f"bundle: {n_clients} clients to {boundary}: "
        f"{arm_bun['origin_round_trips']} origin trips / "
        f"{arm_bun['wire_bytes']} B vs gateway "
        f"{arm_gw['origin_round_trips']} / {arm_gw['wire_bytes']} B "
        f"({trip_ratio:.0f}x trips, {bytes_ratio:.1f}x bytes), "
        f"p95 {arm_bun['p95_ms']} vs {arm_gw['p95_ms']} ms"
    )


def agg_worker() -> None:
    """--agg-worker argv mode: the bn254 device multi-pairing arm in its own
    jax process (always pinned to JAX_PLATFORMS=cpu by the parent — the
    kernel's exact-f64 limb arithmetic has no TPU-native f64 path, so the
    honest device evidence on this deployment is the XLA:CPU wall; a real
    f64-capable accelerator would run the same program). Emits one AGG_JSON
    line: warm per-lane slope fit over two buckets plus accept/reject
    decision checks against the host engine."""
    t0 = time.time()

    def plog(msg):
        print(f"[agg {time.time() - t0:6.1f}s] {msg}", file=sys.stderr, flush=True)

    plog(f"start; JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}")
    import jax

    from cometbft_tpu.ops import xla_cache

    if not xla_cache.enable_persistent_cache(HERE):
        plog("cache config failed (jaxlib lacks the persistent-cache knobs)")
    os.environ["CMTPU_BN254_DEVICE"] = "1"
    from cometbft_tpu.crypto import bn254 as b
    from cometbft_tpu.ops import bn254_kernel as bk

    result = {
        "platform": jax.devices()[0].platform,
        "width": bk.mesh_width(),
    }
    k_small, k_large = 7, 15  # +1 aggregate lane each -> buckets 8 and 16
    privs = [b.gen_priv_key() for _ in range(k_large)]
    pubs = [p.pub_key().bytes() for p in privs]
    msgs = [b"agg-bench-vote-%06d" % i for i in range(k_large)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    plog(f"signed {k_large} bn254 calibration votes")
    be = bk.Bn254DeviceBackend()

    agg_small = b.aggregate_signatures(sigs[:k_small])
    t1 = time.time()
    ok = be.aggregate_verify(pubs[:k_small], msgs[:k_small], agg_small)
    result["compile_s_small"] = round(time.time() - t1, 1)
    result["accept_ok"] = bool(ok)
    # Poisoned aggregate (signer 3's message swapped) must reject, and the
    # decision must match the host engine's.
    poisoned = list(msgs[:k_small])
    poisoned[3] = b"agg-bench-vote-POISON"
    dev_reject = be.aggregate_verify(pubs[:k_small], poisoned, agg_small)
    host_reject = b.verify_aggregate(pubs[:k_small], poisoned, agg_small)
    result["reject_ok"] = (not dev_reject) and (dev_reject == host_reject)
    plog(
        f"bucket 8: compile {result['compile_s_small']}s, "
        f"accept={result['accept_ok']} poisoned-reject={result['reject_ok']}"
    )

    agg_large = b.aggregate_signatures(sigs)
    t1 = time.time()
    assert be.aggregate_verify(pubs, msgs, agg_large)
    result["compile_s_large"] = round(time.time() - t1, 1)
    w_small = best_of(
        lambda: be.aggregate_verify(pubs[:k_small], msgs[:k_small], agg_small),
        reps=3,
    )
    w_large = best_of(lambda: be.aggregate_verify(pubs, msgs, agg_large), reps=3)
    # Linear fit over the two bucket walls: slope = per-lane cost (Miller
    # scan + host f12 product share), intercept = fixed cost (dispatch +
    # the one shared final exponentiation).
    slope = max((w_large - w_small) / (k_large - k_small), 1e-6)
    intercept = max(w_small - (k_small + 1) * slope, 0.0)
    result.update(
        {
            "lanes_small": k_small + 1,
            "lanes_large": k_large + 1,
            "wall_ms_small": round(w_small, 2),
            "wall_ms_large": round(w_large, 2),
            "ms_per_lane": round(slope, 4),
            "fixed_ms": round(intercept, 2),
            "counters": bk.counters(),
        }
    )
    plog(
        f"walls {k_small + 1}: {w_small:.0f} ms, {k_large + 1}: {w_large:.0f} ms "
        f"-> {slope:.1f} ms/lane + {intercept:.0f} ms fixed"
    )
    print("AGG_JSON " + json.dumps(result), flush=True)


def _agg_worker_subprocess(timeout_s: int):
    """Launch --agg-worker with the axon relay scrubbed and jax pinned to
    CPU; returns the parsed dict or None (never gates the JSON line)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # Same 8-chip virtual mesh as the mesh stage: exercises the kernel's
    # sharded dispatch (bit-identical lanes) even though the virtual chips
    # share one core — the width scaling is reported modeled, never as a
    # measured wall.
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    out = run_phase_logged(
        [sys.executable, "-u", __file__, "--agg-worker"], timeout_s, "agg", env=env
    )
    for line in (out or "").splitlines():
        if line.startswith("AGG_JSON "):
            try:
                return json.loads(line[len("AGG_JSON "):])
            except ValueError:
                return None
    return None


def _agg_stage(stages: dict, plog) -> None:
    """Aggregate BLS commits (ISSUE 9): A/B one CMTPU_BENCH_AGG_VALS-
    validator commit across three arms — today's scalar pure-Python pairing
    (per-vote), the host multi-pairing aggregate (n+1 Miller loops sharing
    one final exponentiation), and the device multi-pairing kernel — plus
    honest wire-byte accounting. The scalar and host arms are calibrated on
    small real walls and extrapolated linearly to the target size
    (`modeled: true`); the device arm runs in a jax subprocess and reports
    its own platform, or `absent` with the reason."""
    from cometbft_tpu.crypto import bn254 as b

    n_vals = int(os.environ.get("CMTPU_BENCH_AGG_VALS", "10240"))
    cal = int(os.environ.get("CMTPU_BENCH_AGG_CAL", "8"))
    scalar_n = int(os.environ.get("CMTPU_BENCH_AGG_SCALAR_N", "2"))
    timeout_s = int(os.environ.get("CMTPU_BENCH_AGG_TIMEOUT", "300"))

    privs = [b.gen_priv_key() for _ in range(cal)]
    pubs = [p.pub_key().bytes() for p in privs]
    msgs = [b"agg-vote-%06d" % i for i in range(cal)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    plog(f"agg: signed {cal} bn254 calibration votes (target {n_vals} vals)")

    # ---- arm 1: scalar pure-Python pairing, one check per vote ----
    t1 = time.perf_counter()
    for i in range(scalar_n):
        assert b.verify_signature_slow(pubs[i], msgs[i], sigs[i])
    scalar_per_sig = (time.perf_counter() - t1) * 1000.0 / scalar_n
    scalar_modeled = scalar_per_sig * n_vals
    plog(f"agg: scalar arm {scalar_per_sig:.0f} ms/sig ({scalar_n} measured)")

    # ---- arm 2: host multi-pairing aggregate, slope fit over two sizes ----
    half = max(cal // 2, 2)
    agg_full = b.aggregate_signatures(sigs)
    agg_half = b.aggregate_signatures(sigs[:half])
    assert b.verify_aggregate(pubs, msgs, agg_full)  # warms the H(m) cache
    assert not b.verify_aggregate(pubs, list(reversed(msgs)), agg_full)
    w_half = best_of(
        lambda: b.verify_aggregate(pubs[:half], msgs[:half], agg_half), reps=2
    )
    w_full = best_of(lambda: b.verify_aggregate(pubs, msgs, agg_full), reps=2)
    host_slope = max((w_full - w_half) / (cal - half), 1e-6)
    host_fixed = max(w_half - (half + 1) * host_slope, 0.0)
    host_modeled = host_slope * (n_vals + 1) + host_fixed
    plog(
        f"agg: host arm {host_slope:.0f} ms/pair + {host_fixed:.0f} ms "
        f"shared final exp"
    )

    # ---- arm 3: device multi-pairing kernel (own jax subprocess) ----
    device = _agg_worker_subprocess(timeout_s)
    if device is None:
        device = {"absent": "agg worker failed or timed out (see .bench_agg.err)"}

    # ---- wire bytes: per-vote columns vs bitmap + one G2 point ----
    # Round 10: the block carries the 64-byte COMPRESSED aggregate; the
    # uncompressed 128-byte form is kept for comparison (pre-round-10 wire).
    agg_bytes = 64 + (n_vals + 7) // 8
    agg_bytes_uncompressed = 128 + (n_vals + 7) // 8
    ed_bytes = 64 * n_vals
    wire = {
        "vals": n_vals,
        "ed25519_per_vote_bytes": ed_bytes,
        "bn254_per_vote_bytes": 128 * n_vals,
        "aggregate_bytes": agg_bytes,
        "aggregate_bytes_uncompressed": agg_bytes_uncompressed,
        "aggregate_vs_ed25519": round(agg_bytes / ed_bytes, 5),
    }

    result = {
        "vals": n_vals,
        "modeled": True,
        "scalar": {
            "measured_sigs": scalar_n,
            "ms_per_sig": round(scalar_per_sig, 1),
            "modeled_total_ms": round(scalar_modeled, 0),
        },
        "host_aggregate": {
            "cal_pairs": cal + 1,
            "ms_per_pair": round(host_slope, 2),
            "fixed_ms": round(host_fixed, 1),
            "modeled_total_ms": round(host_modeled, 0),
            "speedup_vs_scalar": round(scalar_modeled / max(host_modeled, 1e-9), 1),
        },
        "device": device,
        "wire": wire,
    }
    if "ms_per_lane" in device:
        # Width curve is the rate model's (lanes shard data-parallel, the
        # final exponentiation stays one shared host pass) — on the virtual
        # mesh the chips share a core, so only width 1 is a measured wall.
        width = max(int(device.get("width", 1)), 1)
        curve = {}
        for w in sorted({1, width}):
            total = device["ms_per_lane"] * (n_vals + 1) / w + device["fixed_ms"]
            curve[str(w)] = {
                "modeled_total_ms": round(total, 0),
                "speedup_vs_scalar": round(scalar_modeled / max(total, 1e-9), 1),
            }
        result["device_modeled"] = curve
        result["speedup_device_vs_scalar"] = curve[str(width)][
            "speedup_vs_scalar"
        ]
        plog(
            f"agg: device arm {device['ms_per_lane']:.1f} ms/lane "
            f"[{device.get('platform')}, width {width}] -> "
            f"{result['speedup_device_vs_scalar']}x vs scalar (modeled)"
        )
    stages["agg"] = result
    plog(
        f"agg: wire {agg_bytes} B vs {ed_bytes} B ed25519 per-vote "
        f"({wire['aggregate_vs_ed25519'] * 100:.2f}%), host aggregate "
        f"{result['host_aggregate']['speedup_vs_scalar']}x vs scalar"
    )


class _LatencyRelay:
    """TCP relay that delays every forwarded buffer by a fixed latency in
    each direction (pure latency, unbounded bandwidth): the tunneled-WAN
    shape a remote sidecar actually sees. Frames queued behind each other
    stay ordered but do NOT serialize on the delay — that is exactly what
    lets a pipelined client overlap wire time with device dispatch, and
    what a sequential unary client cannot exploit."""

    def __init__(self, upstream_host: str, upstream_port: int, delay_s: float):
        import socket as _socket

        self._socket = _socket
        self._up = (upstream_host, upstream_port)
        self._delay = delay_s
        self._conns: list = []
        self._lsock = _socket.socket()
        self._lsock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(16)
        self.port = self._lsock.getsockname()[1]
        self.addr = f"127.0.0.1:{self.port}"
        import threading as _threading

        self._threading = _threading
        t = _threading.Thread(target=self._accept_loop, daemon=True)
        t.start()

    def _accept_loop(self):
        while True:
            try:
                down, _ = self._lsock.accept()
            except OSError:
                return
            try:
                up = self._socket.create_connection(self._up, timeout=5)
            except OSError:
                down.close()
                continue
            down.setsockopt(self._socket.IPPROTO_TCP, self._socket.TCP_NODELAY, 1)
            up.setsockopt(self._socket.IPPROTO_TCP, self._socket.TCP_NODELAY, 1)
            self._conns += [down, up]
            self._pump(down, up)
            self._pump(up, down)

    def _pump(self, src, dst):
        import queue as _queue

        q = _queue.Queue()

        def reader():
            while True:
                try:
                    data = src.recv(65536)
                except OSError:
                    data = b""
                q.put((time.perf_counter() + self._delay, data))
                if not data:
                    return

        def writer():
            while True:
                deadline, data = q.get()
                dt = deadline - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                if not data:
                    try:
                        dst.shutdown(self._socket.SHUT_WR)
                    except OSError:
                        pass
                    return
                try:
                    dst.sendall(data)
                except OSError:
                    return

        for fn in (reader, writer):
            self._threading.Thread(target=fn, daemon=True).start()

    def close(self):
        try:
            self._lsock.close()
        except OSError:
            pass
        for s in self._conns:
            try:
                s.close()
            except OSError:
                pass


def _sidecar_stage(stages: dict, plog) -> None:
    """Pod-scale sidecar streaming (ISSUE 10): one big BatchVerify against a
    remote sidecar behind a latency relay (every buffer delayed RTT/2 per
    direction) with a fixed simulated per-dispatch device cost on the
    server. The unary baseline splits the batch into chunk-sized requests
    and pays the full round trip per chunk, serially — the pre-round-10
    remote path under a frame cap. The streamed arm sends the same chunks
    through the windowed chunk protocol, overlapping wire time with device
    dispatch. Both simulated costs are labeled (`simulated_rtt_ms`,
    `simulated_dispatch_ms`; zero them to measure raw framing overhead).
    Also reports the server-side cross-connection merge ratio from
    concurrent unary clients, and asserts every bitmap bit-identical to the
    in-process CPU backend."""
    import threading as _threading

    from cometbft_tpu.sidecar.backend import CpuBackend
    from cometbft_tpu.sidecar.service import GrpcBackend, SidecarServer

    n = int(os.environ.get("CMTPU_BENCH_SIDECAR_SIGS", "512"))
    chunk = int(os.environ.get("CMTPU_BENCH_SIDECAR_CHUNK", "16"))
    rtt_ms = float(os.environ.get("CMTPU_BENCH_SIDECAR_RTT_MS", "40"))
    dispatch_ms = float(os.environ.get("CMTPU_BENCH_SIDECAR_DISPATCH_MS", "5"))

    _, pubs, msgs, sigs = _signed_batch(n, tag=b"sidecar")
    for i in (3, n // 2, n - 2):  # non-trivial bitmap
        sigs[i] = sigs[i][:-1] + bytes([sigs[i][-1] ^ 1])
    cpu = CpuBackend()
    expect_ok, expect_bits = cpu.batch_verify(pubs, msgs, sigs)  # also warms

    class _DispatchLatency:
        name = "latency"

        def __init__(self):
            self._cpu = CpuBackend()

        def batch_verify(self, pubs_, msgs_, sigs_):
            if dispatch_ms > 0:
                time.sleep(dispatch_ms / 1000.0)
            return self._cpu.batch_verify(pubs_, msgs_, sigs_)

        def merkle_root(self, leaves):
            return self._cpu.merkle_root(leaves)

    old_chunk_env = os.environ.get("CMTPU_SIDECAR_CHUNK")
    os.environ["CMTPU_SIDECAR_CHUNK"] = str(chunk)
    server = relay = client = None
    try:
        server = SidecarServer("127.0.0.1:0", backend=_DispatchLatency())
        server.addr = "127.0.0.1:%d" % server._server.server_address[1]
        server.start()
        relay = _LatencyRelay(
            "127.0.0.1", server._server.server_address[1], rtt_ms / 2000.0
        )
        client = GrpcBackend(relay.addr, timeout_s=120)
        n_chunks = (n + chunk - 1) // chunk

        # -- unary baseline: one frame-capped request per chunk, serial --
        t0 = time.perf_counter()
        un_bits: list = []
        un_ok = True
        for s in range(0, n, chunk):
            ok, bits = client.batch_verify(
                pubs[s : s + chunk], msgs[s : s + chunk], sigs[s : s + chunk]
            )
            un_ok = un_ok and ok
            un_bits.extend(bits)
        unary_ms = (time.perf_counter() - t0) * 1000
        assert client.counters_["unary_calls"] == n_chunks

        # -- streamed: the same chunks pipelined down one connection --
        t0 = time.perf_counter()
        st_ok, st_bits = client.batch_verify(pubs, msgs, sigs)
        streamed_ms = (time.perf_counter() - t0) * 1000
        c = client.counters()
        assert c["streamed_calls"] == 1 and c["streamed_chunks"] == n_chunks

        bit_identical = (
            un_bits == expect_bits
            and st_bits == expect_bits
            and un_ok == expect_ok
            and st_ok == expect_ok
        )
        if not bit_identical:  # pragma: no cover - acceptance guard
            raise AssertionError("sidecar bitmaps diverged from CPU backend")
    finally:
        if old_chunk_env is None:
            os.environ.pop("CMTPU_SIDECAR_CHUNK", None)
        else:
            os.environ["CMTPU_SIDECAR_CHUNK"] = old_chunk_env
        if client is not None:
            client.close()
        if relay is not None:
            relay.close()
        if server is not None:
            server.shutdown()

    # -- cross-connection merge: concurrent unary clients, fresh server --
    k_merge = 3
    old_window = os.environ.get("CMTPU_COALESCE_WINDOW_MS")
    os.environ["CMTPU_COALESCE_WINDOW_MS"] = "50"
    merge_server = None
    merge_clients: list = []
    try:
        merge_server = SidecarServer("127.0.0.1:0", backend=_DispatchLatency())
        merge_server.addr = (
            "127.0.0.1:%d" % merge_server._server.server_address[1]
        )
        merge_server.start()
        merge_clients = [
            GrpcBackend(merge_server.addr, timeout_s=60) for _ in range(k_merge)
        ]
        span = n // k_merge
        start = _threading.Barrier(k_merge)
        merge_errors: list = []

        def _merge_caller(i):
            s = i * span
            start.wait()
            try:
                ok, bits = merge_clients[i].batch_verify(
                    pubs[s : s + span], msgs[s : s + span], sigs[s : s + span]
                )
                assert bits == expect_bits[s : s + span]
            except Exception as e:  # pragma: no cover - stage must report
                merge_errors.append(e)

        threads = [
            _threading.Thread(target=_merge_caller, args=(i,))
            for i in range(k_merge)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        if merge_errors:
            raise merge_errors[0]
        mc = merge_server.scheduler_counters()
    finally:
        if old_window is None:
            os.environ.pop("CMTPU_COALESCE_WINDOW_MS", None)
        else:
            os.environ["CMTPU_COALESCE_WINDOW_MS"] = old_window
        for mcli in merge_clients:
            mcli.close()
        if merge_server is not None:
            merge_server.shutdown()

    stages["sidecar"] = {
        "sigs": n,
        "chunk": chunk,
        "n_chunks": n_chunks,
        "simulated_rtt_ms": rtt_ms,
        "simulated_dispatch_ms": dispatch_ms,
        "unary_ms": round(unary_ms, 2),
        "streamed_ms": round(streamed_ms, 2),
        "speedup": round(unary_ms / max(streamed_ms, 1e-9), 2),
        "streamed_chunks": c["streamed_chunks"],
        "stream_retries": c["stream_retries"],
        "bitmap_identical": bit_identical,
        "merge": {
            "clients": k_merge,
            "requests": mc.get("requests", 0),
            "coalesced_dispatches": mc.get("coalesced_dispatches", 0),
            "batched_requests": mc.get("batched_requests", 0),
            "coalesce_ratio": mc.get("coalesce_ratio", 0),
        },
    }
    plog(
        f"sidecar: {n} sigs/{n_chunks} chunks @ rtt {rtt_ms} ms: "
        f"unary {unary_ms:.0f} ms -> streamed {streamed_ms:.0f} ms "
        f"({stages['sidecar']['speedup']}x), merge ratio "
        f"{stages['sidecar']['merge']['coalesce_ratio']}"
    )


def _fanout_stage(stages: dict, plog) -> None:
    """Multi-host fan-out (ISSUE 15): one batch split into width-weighted
    slices across N sidecar shards, each behind its own latency relay and
    a simulated rate-model device (fixed dispatch cost + n/rate ms, real
    CPU bits). Three arms: 1 shard (everything serial through one host),
    N shards (slices dispatched concurrently — the fleet), and N shards
    with one WEDGED (its slice must time out and redistribute across the
    survivors, completing with redistribution counter > 0). All simulated
    costs are labeled; every arm's bitmap is asserted bit-identical to the
    in-process CPU backend."""
    from cometbft_tpu.sidecar.backend import CpuBackend
    from cometbft_tpu.sidecar.fanout import FanoutBackend
    from cometbft_tpu.sidecar.service import GrpcBackend, SidecarServer

    n = int(os.environ.get("CMTPU_BENCH_FANOUT_SIGS", "2048"))
    n_shards = int(os.environ.get("CMTPU_BENCH_FANOUT_SHARDS", "4"))
    rate = float(os.environ.get("CMTPU_BENCH_FANOUT_RATE", "2.0"))
    dispatch_ms = float(os.environ.get("CMTPU_BENCH_FANOUT_DISPATCH_MS", "5"))
    rtt_ms = float(os.environ.get("CMTPU_BENCH_FANOUT_RTT_MS", "20"))
    # Wide enough that the serial 1-shard arm (the whole batch through one
    # host, plus real CPU verification) never trips it — only the wedged
    # shard's slice should time out.
    deadline_ms = float(
        os.environ.get("CMTPU_BENCH_FANOUT_DEADLINE_MS", "4000")
    )

    _, pubs, msgs, sigs = _signed_batch(n, tag=b"fanout")
    for i in (1, n // 3, n - 5):  # non-trivial bitmap
        sigs[i] = sigs[i][:-1] + bytes([sigs[i][-1] ^ 1])
    cpu = CpuBackend()
    expect_ok, expect_bits = cpu.batch_verify(pubs, msgs, sigs)
    # The shard servers answer from this table (real bits, computed ONCE by
    # the CPU backend above) instead of re-running crypto: all N "shards"
    # live in this one process, so real verification would serialize on the
    # GIL and dilute the dispatch-orchestration speedup this stage measures.
    # Slicing/reassembly correctness is still exercised for real — a
    # misplaced slice boundary scrambles which lanes carry the flipped bits.
    table = {
        (p, m, s): b for p, m, s, b in zip(pubs, msgs, sigs, expect_bits)
    }

    wedge_s = deadline_ms * 3 / 1000.0

    class _RateModel:
        """Simulated per-shard device: fixed dispatch cost + n/rate ms,
        bits from the precomputed table — shard walls scale with slice
        size, so splitting the batch is what buys the speedup."""

        name = "ratemodel"

        def __init__(self):
            self.wedged = False

        def batch_verify(self, pubs_, msgs_, sigs_):
            if self.wedged:
                time.sleep(wedge_s)
            time.sleep((dispatch_ms + len(pubs_) / rate) / 1000.0)
            bits = [
                table.get((p, m, s), False)
                for p, m, s in zip(pubs_, msgs_, sigs_)
            ]
            return all(bits), bits

        def merkle_root(self, leaves):
            return cpu.merkle_root(leaves)

    # Inline dispatch on the shard servers (no coalescer): the wedge sleep
    # must live in a disposable handler thread, not a dispatcher the
    # server shutdown would wait on.
    old_coalesce = os.environ.get("CMTPU_COALESCE")
    os.environ["CMTPU_COALESCE"] = "0"
    servers: list = []
    relays: list = []
    backends: list = []
    try:
        for _ in range(n_shards):
            backend = _RateModel()
            backends.append(backend)
            srv = SidecarServer("127.0.0.1:0", backend=backend).start()
            servers.append(srv)
            relays.append(
                _LatencyRelay(
                    "127.0.0.1",
                    srv._server.server_address[1],
                    rtt_ms / 2000.0,
                )
            )

        def run_arm(k: int):
            fan = FanoutBackend(
                [
                    (f"shard{i}", GrpcBackend(relays[i].addr, timeout_s=120))
                    for i in range(k)
                ],
                deadline_ms=deadline_ms,
            )
            try:
                t0 = time.perf_counter()
                ok, bits = fan.batch_verify(pubs, msgs, sigs)
                wall = (time.perf_counter() - t0) * 1000
                return wall, ok, bits, fan.counters()
            finally:
                fan.close()

        one_ms, ok1, bits1, _ = run_arm(1)
        n_ms, okn, bitsn, cn = run_arm(n_shards)
        backends[-1].wedged = True  # one sick host for the last arm
        wedged_ms, okw, bitsw, cw = run_arm(n_shards)

        bit_identical = (
            bits1 == expect_bits
            and bitsn == expect_bits
            and bitsw == expect_bits
            and ok1 == okn == okw == expect_ok
        )
        if not bit_identical:  # pragma: no cover - acceptance guard
            raise AssertionError("fanout bitmaps diverged from CPU backend")
        if cw["redistributions"] < 1:  # pragma: no cover - acceptance guard
            raise AssertionError("wedged-shard arm never redistributed")
    finally:
        if old_coalesce is None:
            os.environ.pop("CMTPU_COALESCE", None)
        else:
            os.environ["CMTPU_COALESCE"] = old_coalesce
        for r in relays:
            r.close()
        for s in servers:
            s.shutdown()

    stages["fanout"] = {
        "sigs": n,
        "shards": n_shards,
        "shard_widths": {k: v["width"] for k, v in cn["shards"].items()},
        "simulated_rate_sigs_per_ms": rate,
        "simulated_dispatch_ms": dispatch_ms,
        "simulated_rtt_ms": rtt_ms,
        "deadline_ms": deadline_ms,
        "one_shard_ms": round(one_ms, 2),
        "n_shard_ms": round(n_ms, 2),
        "speedup": round(one_ms / max(n_ms, 1e-9), 2),
        "wedged_ms": round(wedged_ms, 2),
        "redistributions": cw["redistributions"],
        "redistributed_sigs": cw["redistributed_sigs"],
        "bitmap_identical": bit_identical,
    }
    plog(
        f"fanout: {n} sigs @ rate {rate}/ms, rtt {rtt_ms} ms: "
        f"1 shard {one_ms:.0f} ms -> {n_shards} shards {n_ms:.0f} ms "
        f"({stages['fanout']['speedup']}x); wedged arm {wedged_ms:.0f} ms, "
        f"{cw['redistributions']} redistribution(s)"
    )


def _recvq_stage(stages: dict, plog) -> None:
    """Recv-path QoS: block-part delivery p95 on a flooded connection,
    prioritized demux vs the serialized baseline.

    One real MConnection pair over a socketpair.  The receiver's on_receive
    simulates reactor work (CMTPU_BENCH_RECVQ_HANDLE_MS per message — the
    cost that serializes the legacy recv path).  Phase 1 lands a burst of
    FLOOD mempool messages; phase 2 sends PARTS consensus-data messages
    ("block parts") at a steady cadence while the flood backlog drains.
    Baseline (CMTPU_RECVQ=0): each part waits behind every queued mempool
    message.  Demux: the drain loop delivers consensus first, so part
    latency collapses to ~one handler slot.  Both arms must deliver
    bit-identical per-channel payload sequences (the demux reorders only
    ACROSS channels, never within one)."""
    import threading

    from cometbft_tpu.p2p.conn.connection import ChannelDescriptor, MConnection

    flood_n = int(os.environ.get("CMTPU_BENCH_RECVQ_FLOOD", "300"))
    parts_n = int(os.environ.get("CMTPU_BENCH_RECVQ_PARTS", "20"))
    handle_ms = float(os.environ.get("CMTPU_BENCH_RECVQ_HANDLE_MS", "2"))
    CONS, MEMP = 0x21, 0x30
    # Small flood payloads: the whole burst must fit in the socketpair's
    # kernel buffer so the baseline backlog forms in the recv PROCESSING
    # path (the serialization under test), not in sendall().
    flood_msgs = [b"tx-%06d" % i for i in range(flood_n)]
    part_msgs = [bytes([j % 256]) * 64 + b"part-%04d" % j for j in range(parts_n)]

    def run_arm(demux: bool):
        old_q = os.environ.get("CMTPU_RECVQ")
        old_max = os.environ.get("CMTPU_RECVQ_MAX")
        os.environ["CMTPU_RECVQ"] = "1" if demux else "0"
        # No shedding in the A/B: bit-identity requires every message.
        os.environ["CMTPU_RECVQ_MAX"] = str(flood_n + parts_n + 64)
        a, b = socket.socketpair()
        try:
            seqs: dict[int, list] = {CONS: [], MEMP: []}
            lat: list[float] = []
            send_t: dict[bytes, float] = {}
            done = threading.Event()

            def on_recv(ch, msg):
                time.sleep(handle_ms / 1000.0)  # simulated reactor work
                if ch == CONS:
                    lat.append(time.perf_counter() - send_t[msg])
                seqs[ch].append(msg)
                if len(seqs[CONS]) == parts_n and len(seqs[MEMP]) == flood_n:
                    done.set()

            descs = [
                ChannelDescriptor(CONS, priority=10, send_queue_capacity=8192),
                ChannelDescriptor(MEMP, priority=5, send_queue_capacity=8192),
            ]
            recv_c = MConnection(b, list(descs), on_recv, lambda e: None)
            send_c = MConnection(
                a, list(descs), lambda *x: None, lambda e: None
            )
            recv_c.start()
            send_c.start()
            for m in flood_msgs:
                if not send_c.send(MEMP, m):
                    raise AssertionError("flood send failed")
            # Let the flood reach the wire before the first part goes out
            # (the backlog must already be in front of it).
            time.sleep(5 * handle_ms / 1000.0)
            for m in part_msgs:
                send_t[m] = time.perf_counter()
                if not send_c.send(CONS, m):
                    raise AssertionError("part send failed")
                time.sleep(2 * handle_ms / 1000.0)
            if not done.wait(timeout=60 + (flood_n + parts_n) * handle_ms / 500):
                raise AssertionError(
                    f"arm incomplete: {len(seqs[CONS])}/{parts_n} parts, "
                    f"{len(seqs[MEMP])}/{flood_n} flood"
                )
            st = recv_c.recvq_stats()
            send_c.stop()
            recv_c.stop()
            return lat, seqs, st
        finally:
            for sock in (a, b):
                try:
                    sock.close()
                except OSError:
                    pass
            for key, old in (("CMTPU_RECVQ", old_q), ("CMTPU_RECVQ_MAX", old_max)):
                if old is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = old

    def p95(xs):
        s = sorted(xs)
        return s[min(len(s) - 1, int(0.95 * len(s)))] * 1000.0

    base_lat, base_seqs, _ = run_arm(demux=False)
    demux_lat, demux_seqs, demux_stats = run_arm(demux=True)
    order_identical = (
        base_seqs[CONS] == demux_seqs[CONS] == part_msgs
        and base_seqs[MEMP] == demux_seqs[MEMP] == flood_msgs
    )
    if not order_identical:  # pragma: no cover - acceptance guard
        raise AssertionError("recvq per-channel delivery order diverged")
    base_p95, demux_p95 = p95(base_lat), p95(demux_lat)
    if base_p95 < 2.0 * demux_p95:  # pragma: no cover - acceptance guard
        raise AssertionError(
            f"recvq demux p95 {demux_p95:.2f} ms not >=2x better than "
            f"serialized {base_p95:.2f} ms"
        )
    stages["recvq"] = {
        "flood_msgs": flood_n,
        "parts": parts_n,
        "simulated_handle_ms": handle_ms,
        "baseline_p95_ms": round(base_p95, 2),
        "demux_p95_ms": round(demux_p95, 2),
        "speedup": round(base_p95 / max(demux_p95, 1e-9), 2),
        "order_identical": order_identical,
        "demux_delivered": demux_stats.get("delivered_total", 0),
        "demux_promoted": demux_stats.get("promoted_total", 0),
        "demux_shed": demux_stats.get("shed_total", 0),
    }
    plog(
        f"recvq: {flood_n} flood + {parts_n} parts @ {handle_ms} ms/handle: "
        f"part p95 {base_p95:.1f} ms serialized -> {demux_p95:.1f} ms demux "
        f"({stages['recvq']['speedup']}x), per-channel order identical"
    )


def shipped_path_stages(stages: dict, plog, budget_left, backend: str) -> None:
    """BASELINE.md configs measured through the SHIPPED call path
    (types/validation -> crypto.batch -> backend), shared by the TPU worker
    and the CPU fallback so every round records them: VerifyCommitLight over
    a real N_SIGS-validator commit, the BS_BLOCKS x BS_VALS blocksync-replay
    shape, and a multi-hop light bisection to height 500."""
    if budget_left():
        try:
            _resilience_stage(stages, plog)
        except Exception as e:
            plog(f"resilience stage failed: {type(e).__name__}: {e}")
    if budget_left():
        os.environ["CMTPU_BACKEND"] = backend
        from cometbft_tpu.sidecar import backend as be

        be.set_backend(None)
        from cometbft_tpu.types import validation

        from cometbft_tpu.crypto import ed25519 as _ed

        vals, commits = _commit_fixture(N_SIGS, heights=1)
        bid, commit = commits[0]
        plog(f"commit fixture built ({N_SIGS} validators)")
        validation.verify_commit_light("bench-chain", vals, bid, 1, commit)  # warm

        def _cold_verify():
            # The verified-triple cache would otherwise make every rep after
            # the first a cache hit; the e2e number must measure real crypto.
            _ed._verified.clear()
            validation.verify_commit_light("bench-chain", vals, bid, 1, commit)

        stages["commit_light_e2e_ms"] = round(best_of(_cold_verify), 2)
        # The cached path IS production behavior (blocksync re-verifies the
        # same commits in ApplyBlock) — report it separately, labeled.
        stages["commit_light_cached_ms"] = round(
            best_of(
                lambda: validation.verify_commit_light(
                    "bench-chain", vals, bid, 1, commit
                )
            ),
            2,
        )
        plog(
            f"VerifyCommitLight e2e {stages['commit_light_e2e_ms']} ms "
            f"(cached {stages['commit_light_cached_ms']} ms)"
        )

    # ---- blocksync replay: 100 blocks x 1,024-validator commits ----
    if budget_left():
        from cometbft_tpu.types import validation

        vals1k, commits1k = _commit_fixture(BS_VALS, heights=BS_BLOCKS, tag=b"bs")
        plog(f"blocksync fixture built ({BS_BLOCKS} x {BS_VALS})")
        t1 = time.perf_counter()
        for h, (bid, commit) in enumerate(commits1k, start=1):
            validation.verify_commit_light("bench-chain", vals1k, bid, h, commit)
        dt = time.perf_counter() - t1
        stages["blocksync_replay_ms_per_block"] = round(dt * 1000 / len(commits1k), 2)
        plog(
            f"blocksync replay {dt:.1f}s "
            f"({stages['blocksync_replay_ms_per_block']} ms/block)"
        )

    # ---- scheduler micro-batching: coalesced vs serialized dispatch ----
    if budget_left():
        try:
            _coalesce_stage(stages, plog)
        except Exception as e:
            plog(f"coalesce stage failed: {type(e).__name__}: {e}")

    # ---- QoS ingress: batched preverify admission vs per-tx dispatch ----
    if budget_left():
        try:
            _ingress_stage(stages, plog)
        except Exception as e:
            plog(f"ingress stage failed: {type(e).__name__}: {e}")

    # ---- consensus hot path: micro-batched vote admission + devnet A/B ----
    if budget_left():
        try:
            _hotpath_stage(stages, plog)
        except Exception as e:
            plog(f"hotpath stage failed: {type(e).__name__}: {e}")

    # ---- light gateway: shared-plan swarm vs independent bisections ----
    if budget_left():
        try:
            _lightgw_stage(stages, plog)
        except Exception as e:
            plog(f"lightgw stage failed: {type(e).__name__}: {e}")

    # ---- checkpoint bundles: cached artifact vs proofs vs bisection ----
    if budget_left():
        try:
            _bundle_stage(stages, plog)
        except Exception as e:
            plog(f"bundle stage failed: {type(e).__name__}: {e}")

    # ---- simnet: virtual-clock 100-node scenario, sim vs wall time ----
    if budget_left():
        try:
            _simnet_stage(stages, plog)
        except Exception as e:
            plog(f"simnet stage failed: {type(e).__name__}: {e}")

    # ---- byz: byzantine simnet arms, evidence-commit latency ----
    if budget_left():
        try:
            _byz_stage(stages, plog)
        except Exception as e:
            plog(f"byz stage failed: {type(e).__name__}: {e}")

    # ---- aggregate BLS commits: scalar / host / device multi-pairing ----
    if budget_left():
        try:
            _agg_stage(stages, plog)
        except Exception as e:
            plog(f"agg stage failed: {type(e).__name__}: {e}")

    # ---- pod-scale sidecar: unary vs streamed at simulated RTT ----
    if budget_left():
        try:
            _sidecar_stage(stages, plog)
        except Exception as e:
            plog(f"sidecar stage failed: {type(e).__name__}: {e}")

    # ---- continuous-batching engine: one queue vs four windows ----
    if budget_left():
        try:
            _engine_stage(stages, plog)
        except Exception as e:
            plog(f"engine stage failed: {type(e).__name__}: {e}")

    # ---- multi-host fan-out: 1 shard vs N shards vs N-with-one-wedged ----
    if budget_left():
        try:
            _fanout_stage(stages, plog)
        except Exception as e:
            plog(f"fanout stage failed: {type(e).__name__}: {e}")

    # ---- recv-path QoS: prioritized demux vs serialized recv ----
    if budget_left():
        try:
            _recvq_stage(stages, plog)
        except Exception as e:
            plog(f"recvq stage failed: {type(e).__name__}: {e}")

    # ---- BASELINE #3 tail on the host tier: all inclusion proofs ----
    if budget_left() and backend == "cpu":
        from cometbft_tpu.crypto.merkle import proof as _proof_mod
        from cometbft_tpu.crypto.merkle import proofs_from_byte_slices

        txs = [b"bench-tx-%08d" % i for i in range(N_LEAVES)]
        stages["merkle_proofs_ms"] = round(
            best_of(lambda: proofs_from_byte_slices(txs), reps=2), 1
        )
        # Which implementation served the shipped call (host by default;
        # device only under CMTPU_DEVICE_PROOFS=1).
        stages["merkle_proofs_path"] = _proof_mod.last_proofs_path
        plog(
            f"proofs (host) @{N_LEAVES}: {stages['merkle_proofs_ms']} ms "
            f"[{stages['merkle_proofs_path']}]"
        )

    # ---- system level: 4-validator devnet over real TCP, tx throughput ----
    if budget_left():
        try:
            bps, tps = _devnet_throughput(seconds=12)
            stages["devnet_blocks_per_s"] = round(bps, 2)
            stages["devnet_tx_per_s"] = round(tps, 1)
            plog(f"devnet: {bps:.2f} blocks/s, {tps:.0f} tx/s (4 vals, TCP)")
        except Exception as e:
            plog(f"devnet stage failed: {type(e).__name__}: {e}")

    # ---- loadtime: sustained-load block-interval/latency report over
    # >= 100 blocks (test/loadtime + e2e/runner/benchmark.go:14-56) ----
    if budget_left():
        try:
            from cometbft_tpu.loadtime import run_load

            rep = run_load(rate=200, min_blocks=100, timeout_s=60)
            stages["loadtime"] = {
                "blocks": rep.blocks,
                "tx_per_s": round(rep.tx_per_s, 1),
                "block_interval_mean_s": round(rep.block_interval_mean_s, 4),
                "block_interval_stddev_s": round(rep.block_interval_stddev_s, 4),
                "block_interval_min_s": round(rep.block_interval_min_s, 4),
                "block_interval_max_s": round(rep.block_interval_max_s, 4),
                "tx_latency_p50_s": round(rep.tx_latency_p50_s, 4),
                "tx_latency_p95_s": round(rep.tx_latency_p95_s, 4),
            }
            plog(
                f"loadtime: {rep.blocks} blocks @ {rep.tx_per_s:.0f} tx/s, "
                f"interval {rep.block_interval_mean_s*1000:.0f}"
                f"±{rep.block_interval_stddev_s*1000:.0f} ms, "
                f"tx p50 {rep.tx_latency_p50_s*1000:.0f} ms"
            )
        except Exception as e:
            plog(f"loadtime stage failed: {type(e).__name__}: {e}")

    # ---- light-client bisection to height 500 over 4,096-val sets ----
    if budget_left():
        from cometbft_tpu.libs.db import MemDB
        from cometbft_tpu.light.client import Client, TrustOptions
        from cometbft_tpu.light.store import LightStore
        from cometbft_tpu.types import Time as _Time

        chain = _LazyChain(n_vals=LIGHT_VALS, rotate=max(1, LIGHT_VALS // 512))
        lb1 = chain.light_block(1)
        now = lambda: _Time(1700000000 + 10 * 500 + 600, 0)
        opts = TrustOptions(
            period_ns=365 * 24 * 3600 * 10**9, height=1, hash=lb1.hash()
        )
        # Pass 1 materializes the lazily-signed fixture blocks the bisection
        # touches (8k+ OpenSSL signs — provider cost, not client cost); the
        # measured pass re-runs a FRESH client/store over the warm fixture
        # with the verified-triple cache cleared, so the number is the
        # client's verification work for the 500-header skipping trace.
        lb = Client(
            chain.CHAIN_ID, opts, chain.provider(), [], LightStore(MemDB())
        ).verify_light_block_at_height(500, now=now())
        assert lb.height == 500
        built = chain.built
        _ed._verified.clear()
        client = Client(
            chain.CHAIN_ID, opts, chain.provider(), [], LightStore(MemDB())
        )
        t1 = time.perf_counter()
        lb = client.verify_light_block_at_height(500, now=now())
        dt = time.perf_counter() - t1
        assert lb.height == 500
        stages["light_bisection_ms"] = round(dt * 1000, 2)
        plog(
            f"light bisection to 500: {dt * 1000:.0f} ms "
            f"({built} headers built)"
        )

    # Live supervisor counters when the shipped backend is the supervised
    # chain (CMTPU_BACKEND=auto): any degradations/trips the stages above
    # actually caused land in the JSON line.
    from cometbft_tpu.sidecar import backend as _be_mod

    live = _be_mod._backend
    if live is not None and hasattr(live, "counters"):
        stages["backend_counters"] = live.counters()


def cpu_fallback() -> None:
    """Stage 4: the host-tier C-speed path (what CpuBackend actually runs),
    plus the shipped-path stage configs so a device-less round still records
    the BASELINE numbers."""
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.crypto.merkle import hash_from_byte_slices

    from cometbft_tpu import native
    from cometbft_tpu.sidecar import backend as be

    os.environ["CMTPU_BACKEND"] = "cpu"  # keep get_backend() away from jax
    be.set_backend(None)
    log(f"cpu fallback: building {N_SIGS} signed messages")
    pvs, pubs, msgs, sigs = _signed_batch(N_SIGS)
    keys = [ed25519.PubKey(p) for p in pubs]
    txs = [b"bench-tx-%08d" % i for i in range(N_LEAVES)]
    log("cpu fallback: measuring")
    best = float("inf")
    for _ in range(3):
        # The verified-triple cache would turn reps 2..3 into dict lookups;
        # this number must measure real verification work every rep.  The
        # path measured is exactly what CpuBackend ships: the native C
        # MSM batch verifier when built, per-signature OpenSSL otherwise.
        ed25519._verified.clear()
        t1 = time.perf_counter()
        bv = ed25519.BatchVerifier()
        for k, m, s in zip(keys, msgs, sigs):
            bv.add(k, m, s)
        ok, _bits = bv.verify()
        hash_from_byte_slices(txs)
        best = min(best, time.perf_counter() - t1)
        assert ok
    how = (
        "native C MSM + SHA-NI merkle"
        if native.available()
        else "cryptography/OpenSSL + hashlib"
    )
    log(f"cpu fallback best {best * 1000:.1f} ms ({how})")
    stages = {}
    t0 = time.time()
    try:
        shipped_path_stages(
            stages, log, lambda: time.time() - t0 < STAGE_BUDGET_S, backend="cpu"
        )
    except Exception as e:  # never lose the JSON line to a stage failure
        log(f"cpu shipped-path stages failed: {type(e).__name__}: {e}")
    # Pod-scale mesh curve on the virtual 8-device mesh (subprocess: this
    # process pinned CMTPU_BACKEND=cpu away from jax on purpose).
    if time.time() - t0 < STAGE_BUDGET_S:
        mesh = _mesh_stage_subprocess()
        if mesh is not None:
            stages["mesh"] = mesh
    # The axon relay flaps for hours at a time. If the tpu_watch.sh watcher
    # captured a device run earlier (while the relay was up), attach it —
    # clearly labeled as a previous run — so a dead-tunnel round still
    # reports the kernel's real device number next to the CPU fallback.
    last_device = None
    try:
        with open(os.path.join(HERE, "tpu_bench_latest.json")) as f:
            last_device = json.loads(f.read().strip() or "null")
    except (OSError, ValueError):
        pass
    if last_device:
        stages["last_device_run"] = last_device
        log(f"attaching last device run: {last_device.get('value')} ms")
    emit(best * 1000.0, stages, "cpu-host")


def emit(measured_ms: float, stages: dict, platform: str) -> None:
    print(
        json.dumps(
            {
                "metric": "verify_10k_commit_plus_64k_merkle_ms",
                "value": round(measured_ms, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / measured_ms, 3),
                "platform": platform,
                "stages": stages,
            }
        ),
        flush=True,
    )


def main() -> int:
    platforms = os.environ.get("JAX_PLATFORMS", "")
    want_tpu = platforms != "cpu"
    relay_gated = platforms == "axon" or os.environ.get("AXON_LOOPBACK_RELAY")
    if want_tpu and relay_gated and not relay_open():
        log("axon relay is down (connection refused) — no TPU reachable; CPU fallback")
    elif want_tpu:
        log("probing device")
        out = run_phase_logged(
            [sys.executable, "-u", __file__, "--worker", "--probe-only"],
            PROBE_TIMEOUT_S,
            "probe",
        )
        if out and "PROBE_OK" in out:
            log("device probe ok; running TPU bench")
            out = run_phase_logged(
                [sys.executable, "-u", __file__, "--worker"], TPU_TIMEOUT_S, "tpu"
            )
            for line in (out or "").splitlines():
                if line.startswith("{"):
                    print(line)
                    return 0
            log("TPU attempt produced no result; falling back to CPU")
        else:
            log("device probe failed (tunnel wedged or PJRT init hang); CPU fallback")
    cpu_fallback()
    return 0


if __name__ == "__main__":
    if "--worker" in sys.argv:
        tpu_worker()
    elif "--mesh-worker" in sys.argv:
        mesh_worker()
    elif "--agg-worker" in sys.argv:
        agg_worker()
    else:
        sys.exit(main())

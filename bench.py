"""Round benchmark: the north-star configs from BASELINE.md.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Headline metric: wall time to verify a 10,240-signature commit (the
10k-validator VerifyCommitLight analog — ZIP-215 batch verification on
device) PLUS the 64k-leaf block Merkle root: the full "verify a block's
crypto" step.

vs_baseline: the reference's Go path cost for the same work, derived from
its published numbers (BASELINE.md): RFC-6962 Merkle at 77.7 us / 100 leaves
(crypto/merkle/tree.go:42) -> ~50.9 ms for 64k leaves; curve25519-voi batch
verify ~2x single-verify throughput -> ~32 us/sig -> ~327 ms for 10,240
sigs. Baseline total ~378 ms; vs_baseline = baseline_ms / measured_ms
(>1 = faster than the reference path).

Robustness: the default-platform (TPU) attempt runs in a subprocess with a
timeout; if the TPU tunnel stalls, a CPU-pinned subprocess produces the line
instead, so the driver always gets a result.
"""

import json
import os
import subprocess
import sys
import time

BASELINE_MS = 10240 * 0.032 + 50.9
TPU_TIMEOUT_S = int(os.environ.get("CMTPU_BENCH_TPU_TIMEOUT", "480"))
CPU_TIMEOUT_S = int(os.environ.get("CMTPU_BENCH_CPU_TIMEOUT", "1500"))


def worker() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # Env alone has been observed to still init the TPU plugin; pin it.
        jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: the first on-TPU run pays the XLA compile
    # once; every later run (and the driver's) hits the disk cache.
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass
    import numpy as np

    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.ops import merkle_kernel as mk
    from cometbft_tpu.ops.sharded import make_example_batch

    n_sigs = 10240
    n_leaves = 65536

    print(f"platform: {jax.devices()[0].platform}", file=sys.stderr, flush=True)
    t0 = time.time()
    operands = tuple(np.asarray(o) for o in make_example_batch(n_sigs))
    print(f"packed batch in {time.time()-t0:.1f}s", file=sys.stderr, flush=True)
    verify = ek._compiled(n_sigs)
    txs = [b"bench-tx-%08d" % i for i in range(n_leaves)]

    t0 = time.time()
    ok = np.asarray(jax.block_until_ready(verify(*operands)))
    print(f"verify compile+run {time.time()-t0:.1f}s", file=sys.stderr, flush=True)
    assert ok.all(), "bench batch must verify"
    t0 = time.time()
    digests = mk.hash_leaves_device(txs)
    root = mk.merkle_root_pow2(digests)
    print(f"merkle compile+run {time.time()-t0:.1f}s", file=sys.stderr, flush=True)
    from cometbft_tpu.crypto.merkle import hash_from_byte_slices

    assert root == hash_from_byte_slices(txs), "device merkle root != host root"

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(verify(*operands))
        mk.merkle_root_pow2(mk.hash_leaves_device(txs))
        best = min(best, time.perf_counter() - t0)

    measured_ms = best * 1000.0
    print(
        json.dumps(
            {
                "metric": "verify_10k_commit_plus_64k_merkle_ms",
                "value": round(measured_ms, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / measured_ms, 3),
            }
        ),
        flush=True,
    )


def main() -> int:
    here = os.path.abspath(__file__)
    attempts = [({}, TPU_TIMEOUT_S), ({"JAX_PLATFORMS": "cpu"}, CPU_TIMEOUT_S)]
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        attempts = attempts[1:]
    for extra_env, timeout_s in attempts:
        env = dict(os.environ, **extra_env)
        try:
            res = subprocess.run(
                [sys.executable, "-u", here, "--worker"],
                capture_output=True,
                timeout=timeout_s,
                env=env,
                text=True,
            )
        except subprocess.TimeoutExpired:
            print(
                f"bench attempt timed out after {timeout_s}s (env {extra_env}); "
                f"falling back",
                file=sys.stderr,
            )
            continue
        for line in res.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                print(line)
                return 0
        print(res.stderr[-2000:], file=sys.stderr)
    print("bench: all attempts failed", file=sys.stderr)
    return 1


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        sys.exit(main())

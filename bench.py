"""Round benchmark: the north-star configs from BASELINE.md on the real chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Headline metric: wall time to verify a 10,240-signature commit (10k-validator
VerifyCommitLight analog: ZIP-215 batch verification on device) PLUS the
64k-leaf block Merkle root — the full "verify a block's crypto" step.

vs_baseline: the reference's Go path cost for the same work, derived from its
published numbers (BASELINE.md): RFC-6962 Merkle at 77.7 us / 100 leaves
(crypto/merkle/tree.go:42) scales to ~50.9 ms for 64k leaves; curve25519-voi
batch verification runs ~2x single-verify throughput (crypto/ed25519
bench shapes), i.e. ~32 us/sig on server cores -> ~327 ms for 10,240 sigs.
Baseline total: ~378 ms. vs_baseline = baseline_ms / measured_ms (>1 = faster
than the reference path).
"""

import json
import sys
import time


def main() -> None:
    # Run on the default platform (TPU under axon; CPU elsewhere). The
    # verification workload is packed host-side exactly as production does.
    import jax
    import numpy as np

    from cometbft_tpu.ops import merkle_kernel as mk
    from cometbft_tpu.ops.sharded import make_example_batch
    from cometbft_tpu.ops import ed25519_kernel as ek

    n_sigs = 10240
    n_leaves = 65536

    operands = tuple(np.asarray(o) for o in make_example_batch(n_sigs))
    verify = ek._compiled(n_sigs)
    txs = [b"bench-tx-%08d" % i for i in range(n_leaves)]

    # Warmup / compile.
    ok = np.asarray(jax.block_until_ready(verify(*operands)))
    assert ok.all(), "bench batch must verify"
    mk.merkle_root(txs[:1024])

    # Timed: 10,240-sig verify + 64k-leaf merkle root (3 reps, min).
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(verify(*operands))
        mk.merkle_root(txs)
        best = min(best, time.perf_counter() - t0)

    measured_ms = best * 1000.0
    baseline_ms = 10240 * 0.032 + 50.9  # Go batch-verify + merkle (see module doc)
    print(
        json.dumps(
            {
                "metric": "verify_10k_commit_plus_64k_merkle_ms",
                "value": round(measured_ms, 3),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / measured_ms, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())

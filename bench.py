"""Round benchmark: the north-star configs from BASELINE.md.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Headline metric: wall time to verify a 10,240-signature commit (the
10k-validator VerifyCommitLight analog — ZIP-215 batch verification) PLUS
the 64k-leaf block Merkle root: the full "verify a block's crypto" step.

vs_baseline: the reference's Go path cost for the same work, derived from
its published numbers (BASELINE.md): RFC-6962 Merkle at 77.7 us / 100 leaves
(crypto/merkle/tree.go:42) -> ~50.9 ms for 64k leaves; curve25519-voi batch
verify ~2x single-verify throughput -> ~32 us/sig -> ~327 ms for 10,240
sigs. Baseline total ~378 ms; vs_baseline = baseline_ms / measured_ms
(>1 = faster than the reference path).

Stage plan (every stage logs a timestamped line to stderr — the driver
records the stderr tail, so a failure is always attributable):
  1. relay probe   — raw TCP connect to the axon tunnel (127.0.0.1:8082),
                     3 s: no JAX involved, cannot wedge anything.
  2. device probe  — short subprocess doing jax.devices() + one matmul,
                     bounded; stderr phases go to a file that survives the
                     kill, and the tail is re-printed here.
  3. TPU attempt   — full worker, phase-logged the same way.
  4. CPU fallback  — the C-speed host path (cryptography/OpenSSL verifies +
                     hashlib Merkle), NOT the XLA:CPU emulated limb kernels:
                     this is what a host-only deployment of this framework
                     actually runs (sidecar/backend.py CpuBackend).
"""

import json
import os
import socket
import subprocess
import sys
import time

BASELINE_MS = 10240 * 0.032 + 50.9
N_SIGS = 10240
N_LEAVES = 65536
RELAY_PORT = 8082
PROBE_TIMEOUT_S = int(os.environ.get("CMTPU_BENCH_PROBE_TIMEOUT", "120"))
TPU_TIMEOUT_S = int(os.environ.get("CMTPU_BENCH_TPU_TIMEOUT", "480"))
HERE = os.path.dirname(os.path.abspath(__file__))

T0 = time.time()


def log(msg: str) -> None:
    print(f"[bench {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def relay_open() -> bool:
    """Stage 1: is anything listening on the axon tunnel port at all?"""
    s = socket.socket()
    s.settimeout(3)
    try:
        s.connect(("127.0.0.1", RELAY_PORT))
        return True
    except OSError as e:
        log(f"relay probe: 127.0.0.1:{RELAY_PORT} -> {e}")
        return False
    finally:
        s.close()


def run_phase_logged(args: list, timeout_s: int, tag: str, env=None):
    """Run a subprocess whose stdout/stderr go to files (so a timeout kill
    loses nothing), then replay the stderr tail here. Returns stdout text or
    None on timeout/nonzero exit."""
    out_path = os.path.join(HERE, f".bench_{tag}.out")
    err_path = os.path.join(HERE, f".bench_{tag}.err")
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        try:
            proc = subprocess.run(
                args, stdout=out_f, stderr=err_f, timeout=timeout_s, env=env
            )
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            rc = "timeout"
    tail = open(err_path).read()[-1500:]
    for line in tail.splitlines():
        log(f"  {tag}| {line}")
    if rc != 0:
        log(f"{tag}: rc={rc} after <= {timeout_s}s")
        return None
    return open(out_path).read()


def tpu_worker() -> None:
    """Stages 2+3 child: phase-logged device run on the default (TPU)
    platform."""
    t0 = time.time()

    def plog(msg):
        print(f"[worker {time.time() - t0:6.1f}s] {msg}", file=sys.stderr, flush=True)

    plog(f"start; JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}")
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", os.path.join(HERE, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:
        plog(f"cache config failed: {e}")
    devs = jax.devices()
    plog(f"devices: {devs} platform={devs[0].platform}")
    if "--probe-only" in sys.argv:
        import jax.numpy as jnp

        y = jax.block_until_ready(jnp.ones((256, 256)) @ jnp.ones((256, 256)))
        plog(f"matmul ok ({float(y[0, 0])})")
        print("PROBE_OK")
        return

    import numpy as np

    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.ops import merkle_kernel as mk
    from cometbft_tpu.ops.sharded import make_example_batch

    operands = tuple(np.asarray(o) for o in make_example_batch(N_SIGS))
    plog("batch packed")
    verify = ek._compiled(operands[0].shape[1])
    txs = [b"bench-tx-%08d" % i for i in range(N_LEAVES)]
    t1 = time.time()
    ok = np.asarray(jax.block_until_ready(verify(*operands)))
    plog(f"verify compile+run {time.time() - t1:.1f}s")
    assert ok.all(), "bench batch must verify"
    t1 = time.time()
    digests = mk.hash_leaves_device(txs)
    root = mk.merkle_root_pow2(digests)
    plog(f"merkle compile+run {time.time() - t1:.1f}s")
    from cometbft_tpu.crypto.merkle import hash_from_byte_slices

    assert root == hash_from_byte_slices(txs), "device merkle root != host root"
    best = float("inf")
    for _ in range(3):
        t1 = time.perf_counter()
        jax.block_until_ready(verify(*operands))
        mk.merkle_root_pow2(mk.hash_leaves_device(txs))
        best = min(best, time.perf_counter() - t1)
    plog(f"steady-state best {best * 1000:.3f} ms on {devs[0].platform}")
    emit(best * 1000.0)


def cpu_fallback() -> None:
    """Stage 4: the host-tier C-speed path (what CpuBackend actually runs) —
    honest CPU numbers, not the XLA:CPU emulated limb kernels."""
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.crypto.merkle import hash_from_byte_slices

    log(f"cpu fallback: building {N_SIGS} signed messages")
    pvs = [ed25519.gen_priv_key() for _ in range(N_SIGS)]
    msgs = [b"bench-msg-%06d" % i for i in range(N_SIGS)]
    sigs = [pv.sign(m) for pv, m in zip(pvs, msgs)]
    pubs = [pv.pub_key() for pv in pvs]
    txs = [b"bench-tx-%08d" % i for i in range(N_LEAVES)]
    log("cpu fallback: measuring")
    best = float("inf")
    for _ in range(3):
        t1 = time.perf_counter()
        ok = all(p.verify_signature(m, s) for p, m, s in zip(pubs, msgs, sigs))
        hash_from_byte_slices(txs)
        best = min(best, time.perf_counter() - t1)
        assert ok
    log(f"cpu fallback best {best * 1000:.1f} ms (cryptography/OpenSSL + hashlib)")
    emit(best * 1000.0)


def emit(measured_ms: float) -> None:
    print(
        json.dumps(
            {
                "metric": "verify_10k_commit_plus_64k_merkle_ms",
                "value": round(measured_ms, 3),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / measured_ms, 3),
            }
        ),
        flush=True,
    )


def main() -> int:
    platforms = os.environ.get("JAX_PLATFORMS", "")
    want_tpu = platforms != "cpu"
    # The relay TCP probe only applies to THIS host's axon tunnel; on a real
    # TPU VM (JAX_PLATFORMS unset or "tpu") go straight to the device probe.
    relay_gated = platforms == "axon" or os.environ.get("AXON_LOOPBACK_RELAY")
    if want_tpu and relay_gated and not relay_open():
        log("axon relay is down (connection refused) — no TPU reachable; CPU fallback")
    elif want_tpu:
        log("probing device")
        out = run_phase_logged(
            [sys.executable, "-u", __file__, "--worker", "--probe-only"],
            PROBE_TIMEOUT_S,
            "probe",
        )
        if out and "PROBE_OK" in out:
            log("device probe ok; running TPU bench")
            out = run_phase_logged(
                [sys.executable, "-u", __file__, "--worker"], TPU_TIMEOUT_S, "tpu"
            )
            for line in (out or "").splitlines():
                if line.startswith("{"):
                    print(line)
                    return 0
            log("TPU attempt produced no result; falling back to CPU")
        else:
            log("device probe failed (tunnel wedged or PJRT init hang); CPU fallback")
    cpu_fallback()
    return 0


if __name__ == "__main__":
    if "--worker" in sys.argv:
        tpu_worker()
    else:
        sys.exit(main())

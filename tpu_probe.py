"""Short TPU device probe: timestamps every phase so a hang is attributable.

Run alone (never concurrently with another JAX process — the axon tunnel
wedges under concurrent clients). Writes phase logs to stdout; the caller
redirects to a file that survives any timeout kill.
"""

import os
import sys
import time

T0 = time.time()


def log(msg: str) -> None:
    print(f"[{time.time() - T0:7.1f}s] {msg}", flush=True)


def main() -> None:
    log(f"start; JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}")
    import jax

    log(f"jax {jax.__version__} imported")
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    devs = jax.devices()
    log(f"devices: {devs} (platform={devs[0].platform})")
    import jax.numpy as jnp

    x = jnp.ones((256, 256), dtype=jnp.float32)
    log("device array created")
    y = jax.block_until_ready(x @ x)
    log(f"matmul done: {float(y[0, 0])}")
    import numpy as np

    z = np.asarray(y)
    log(f"transfer back done: {z.shape}")
    log("PROBE OK")


if __name__ == "__main__":
    main()

#!/bin/bash
# Poll the axon relay port; the moment it accepts, run ONE phased TPU warmup
# (bench worker) to compile+cache the kernels and capture a real device
# number. Logs to tpu_watch.log. Exits after the first successful warmup or
# after ~11h.
cd /root/repo
log() { echo "[watch $(date +%H:%M:%S)] $*" >> tpu_watch.log; }
log "watcher started"
for i in $(seq 1 660); do
  if python - <<'EOF'
import socket, sys
s = socket.socket(); s.settimeout(3)
try:
    s.connect(("127.0.0.1", 8082)); sys.exit(0)
except OSError:
    sys.exit(1)
EOF
  then
    log "relay port OPEN (iteration $i); running warmup"
    timeout 900 python -u bench.py --worker > tpu_warm.out 2> tpu_warm.err
    rc=$?
    log "warmup rc=$rc"
    tail -20 tpu_warm.err >> tpu_watch.log
    cat tpu_warm.out >> tpu_watch.log
    if [ "$rc" = "0" ]; then
      log "TPU warmup SUCCEEDED — compile cache warm"
      exit 0
    fi
    log "warmup failed; continuing to poll"
    sleep 300
  fi
  sleep 60
done
log "watcher expired without a successful warmup"

#!/bin/bash
# Long-lived watcher for the axon TPU relay (127.0.0.1:8082).
#
# The tunnel flaps: it wedges under concurrent jax clients (every python
# start dials it via sitecustomize's register() while PALLAS_AXON_POOL_IPS
# is set) and can stay down for hours. This loop polls the port and, each
# time it comes up, runs bench.py to (a) warm the remote-compile cache and
# (b) capture a real device number for the round. Re-runs every ~30 min
# while the tunnel stays up so the newest kernel code gets measured.
#
# State files (repo root):
#   .tpu_status    one line: POLLING | RUNNING | OK <unix-ts>
#   tpu_watch.log  append-only history
#   tpu_bench_latest.json  last JSON line bench.py printed on a real device
cd /root/repo
PIDFILE=.tpu_watch.pid
# Stale-pidfile guard: the PID must both be alive AND still be this script
# (a SIGKILLed watcher leaves the file behind; a recycled PID would
# otherwise make every later launch exit "already running" with rc 0).
if [ -f "$PIDFILE" ]; then
  oldpid=$(cat "$PIDFILE")
  if kill -0 "$oldpid" 2>/dev/null && \
     grep -qa tpu_watch /proc/"$oldpid"/cmdline 2>/dev/null; then
    echo "watcher already running (pid $oldpid)"; exit 0
  fi
fi
echo $$ > "$PIDFILE"
trap 'rm -f "$PIDFILE"' EXIT
log() { echo "[watch $(date +%H:%M:%S)] $*" >> tpu_watch.log; }
log "watcher started (pid $$)"
echo POLLING > .tpu_status

port_open() {
  timeout 5 bash -c 'echo > /dev/tcp/127.0.0.1/8082' 2>/dev/null
}

for i in $(seq 1 1400); do
  if port_open; then
    log "relay OPEN (iter $i); running bench"
    echo RUNNING > .tpu_status
    # A previous A/B round may have picked a winning lowering; stick to it
    # for every later bench run (otherwise the next loop iteration would
    # clobber the better alt-mode result with the default mode's).
    FE_MODE=$(cat .tpu_fe_mode 2>/dev/null || true)
    # "pallas" is the Mosaic ladder probe (CMTPU_LADDER), not an fe mode.
    if [ "$FE_MODE" = "pallas" ]; then
      CMTPU_LADDER=pallas timeout 1500 python -u bench.py \
        > tpu_bench.out 2> tpu_bench.err
    elif [ -n "$FE_MODE" ]; then
      CMTPU_FE_MODE="$FE_MODE" timeout 1500 python -u bench.py \
        > tpu_bench.out 2> tpu_bench.err
    else
      timeout 1500 python -u bench.py > tpu_bench.out 2> tpu_bench.err
    fi
    rc=$?
    log "bench rc=$rc"
    tail -25 tpu_bench.err >> tpu_watch.log
    cat tpu_bench.out >> tpu_watch.log
    # Device success = a JSON line whose platform is NOT the cpu fallback
    # (the PJRT platform may register as "tpu" or "axon" depending on the
    # tunnel deployment).
    if [ "$rc" = "0" ] && grep -q '"platform"' tpu_bench.out && \
       ! grep -q '"platform": "cpu' tpu_bench.out; then
      grep '"metric"' tpu_bench.out | tail -1 > tpu_bench_latest.json
      # The coalesce + ingress + hotpath + lightgw + mesh + sidecar + engine + fanout + recvq + bundle stages ride in the
      # carried JSON (host-side scheduler/admission/vote-batching/gateway
      # speedups measured while the device was serving); surface them in
      # the history. None gates alt-mode adoption below. Helper python is
      # CPU-only parsing.
      CO=$(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu timeout 60 \
           python - <<'PYEOF' 2>/dev/null
import json
rec = json.load(open("tpu_bench_latest.json"))
c = rec.get("stages", {}).get("coalesce")
parts = [f"coalesce {c['speedup']}x ratio {c['coalesce_ratio']}" if c
         else "coalesce absent"]
g = rec.get("stages", {}).get("ingress")
parts.append(
    f"ingress {g['speedup']}x {g['batched_dispatches']}dsp "
    f"shed {g['shed_total']}" if g else "ingress absent")
h = rec.get("stages", {}).get("hotpath")
parts.append(
    f"hotpath {h['speedup']}x {h['batched_dispatches']}dsp "
    f"devnet {h['devnet_before_blocks_per_s']}->"
    f"{h['devnet_after_blocks_per_s']}b/s" if h else "hotpath absent")
lg = rec.get("stages", {}).get("lightgw")
parts.append(
    f"lightgw {lg['speedup']}x proof {lg['lightgw_proof_bytes']}B "
    f"({lg['proof_bytes_ratio']}x)" if lg else "lightgw absent")
m = rec.get("stages", {}).get("mesh")
parts.append(
    f"mesh {m['n_devices']}dev {m['speedup_widest_vs_1']}x"
    + (" bit-identical"
       if m.get("calibration", {}).get("sharded_bit_identical") else "")
    if m else "mesh absent")
a = rec.get("stages", {}).get("agg")
parts.append(
    f"agg {a.get('speedup_device_vs_scalar', a['host_aggregate']['speedup_vs_scalar'])}x "
    f"wire {a['wire']['aggregate_vs_ed25519'] * 100:.2f}%"
    + (" verified" if a.get("device", {}).get("reject_ok") else "")
    if a else "agg absent")
sc = rec.get("stages", {}).get("sidecar")
parts.append(
    f"sidecar {sc['speedup']}x stream {sc['n_chunks']}ch "
    f"merge {sc['merge']['coalesce_ratio']}"
    + (" bit-identical" if sc.get("bitmap_identical") else "")
    if sc else "sidecar absent")
e = rec.get("stages", {}).get("engine")
parts.append(
    f"engine {e['consensus_p95_speedup']}x cons-p95 "
    f"{e['baseline_dispatches']}->{e['engine_dispatches']}dsp"
    if e else "engine absent")
f = rec.get("stages", {}).get("fanout")
parts.append(
    f"fanout {f['speedup']}x {f['shards']}sh "
    f"redis {f['redistributions']}"
    + (" bit-identical" if f.get("bitmap_identical") else "")
    if f else "fanout absent")
rq = rec.get("stages", {}).get("recvq")
parts.append(
    f"recvq {rq['speedup']}x part-p95 {rq['baseline_p95_ms']}->"
    f"{rq['demux_p95_ms']}ms"
    + (" order-identical" if rq.get("order_identical") else "")
    if rq else "recvq absent")
bu = rec.get("stages", {}).get("bundle")
parts.append(
    f"bundle {bu['round_trips_vs_proof']}x trips "
    f"{bu['wire_bytes_vs_proof']}x bytes {bu['bundle_bytes']}B"
    + (" trace-identical" if bu.get("trace_identical") else "")
    if bu else "bundle absent")
bz = rec.get("stages", {}).get("byz")
parts.append(
    f"byz ev-commit {bz.get('equivocator_detect_to_commit_s')}sim-s "
    f"rate {bz.get('block_rate_equivocator_ratio')}/"
    f"{bz.get('block_rate_vote_flood_ratio')}"
    + ("" if bz.get("equivocator_safety_ok") else " SAFETY-FAIL")
    if bz else "byz absent")
print("; ".join(parts))
PYEOF
      )
      log "device bench OK -> tpu_bench_latest.json ($CO)"
      echo "OK $(date +%s)" > .tpu_status
      # While the tunnel is up, also A/B the fe lowerings (guides the next
      # kernel iteration even if the tunnel dies later). Re-run until at
      # least the two tractable modes (stacked, compact) each produced a
      # steady_ms line — a partial run (tunnel died mid-probe) retries;
      # planar timing out forever must not retrigger the probe.
      AB_TRIES=$(cat .tpu_ab_tries 2>/dev/null || echo 0)
      if { [ ! -f tpu_ab.log ] || [ "$(grep -c steady_ms tpu_ab.log)" -lt 2 ]; } \
         && [ "$AB_TRIES" -lt 3 ]; then
        echo $((AB_TRIES + 1)) > .tpu_ab_tries
        log "running fe-lowering A/B probe (attempt $((AB_TRIES + 1)))"
        # Fresh log per probe: --best must reflect THIS kernel build, not
        # steady_ms lines from superseded code in an append-only history.
        # The attempt counter bounds re-probing when a mode persistently
        # fails to produce its steady_ms line.
        [ -f tpu_ab.log ] && mv tpu_ab.log tpu_ab.log.1
        timeout 1800 python -u tpu_ab.py > tpu_ab.log 2>> tpu_watch.log
        log "A/B probe done"
        # If a non-default lowering won the A/B, re-bench with it and keep
        # whichever JSON line reports the better (smaller) headline value.
        # Helper pythons are CPU-only file parsing: strip the relay env
        # (sitecustomize would dial the wedge-prone tunnel) and bound them.
        BEST=$(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
               timeout 60 python tpu_ab.py --best 2>/dev/null)
        if [ -n "$BEST" ] && [ "$BEST" != "stacked" ]; then
          log "A/B winner is $BEST; re-running bench with it"
          if [ "$BEST" = "pallas" ]; then
            CMTPU_LADDER=pallas timeout 1500 python -u bench.py \
              > tpu_bench_alt.out 2>> tpu_watch.log
          else
            CMTPU_FE_MODE="$BEST" timeout 1500 python -u bench.py \
              > tpu_bench_alt.out 2>> tpu_watch.log
          fi
          # Adopt the mode ONLY if the full bench agrees it is better
          # (microbench winners can lose end-to-end); otherwise clear any
          # stale sticky mode so later runs use the default.
          AB_BEST="$BEST" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
            timeout 60 python - <<'PYEOF' >> tpu_watch.log 2>&1
import json, os
def val(path):
    try:
        for line in open(path):
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if "cpu" not in str(rec.get("platform", "")) and "value" in rec:
                    return rec
    except OSError:
        pass
    return None
cur, alt = val("tpu_bench_latest.json"), val("tpu_bench_alt.out")
# Adoption needs more than a better headline: the alt mode's compile cost
# must not have truncated the stage table (a late stage present proves the
# worker finished within budget) — a mode that wins 5 ms but loses half
# the stages is a worse round artifact. The coalesce stage is carried but
# never gates adoption: it measures the host-side scheduler, not the
# lowering under A/B.
complete = bool(alt) and "blocksync_replay_ms_per_block" in alt.get("stages", {})
if alt and complete and (cur is None or alt["value"] < cur["value"]):
    open("tpu_bench_latest.json", "w").write(json.dumps(alt) + "\n")
    open(".tpu_fe_mode", "w").write(os.environ["AB_BEST"] + "\n")
    print(f"[watch] alt-mode bench better ({alt['value']} ms); mode kept")
else:
    try:
        os.remove(".tpu_fe_mode")
    except OSError:
        pass
    print("[watch] alt-mode bench not better; default mode stays")
PYEOF
        fi
      fi
      sleep 1800
    else
      echo POLLING > .tpu_status
      log "no device number; back to polling"
      sleep 180
    fi
  else
    echo POLLING > .tpu_status
    sleep 30
  fi
done
log "watcher expired"
rm -f "$PIDFILE"

"""A/B the field-multiply lowerings on the real device (run by tpu_watch.sh
after a successful bench, or by hand when the relay is up).

For each probe spawn a fresh worker process (the mode is sampled at import)
that compiles the 10,240-lane verify program and times steady-state
dispatches.  Probes: the three CMTPU_FE_MODE XLA lowerings (stacked /
compact / planar) and the CMTPU_LADDER=pallas Mosaic ladder kernel
(ops/pallas_ladder.py — weak-#5: the planar arithmetic inside one kernel,
dodging the XLA graph-size ceiling).  planar goes late under a hard
timeout: its XLA compile has never finished on the device (>8 min
observed) and a hang must not eat the tunnel-up window.

Appends one JSON line per probe to stdout; tpu_watch.sh redirects to
tpu_ab.log.
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
N = int(os.environ.get("CMTPU_AB_SIGS", "10240"))
# (label, extra env, timeout)
MODES = (
    ("stacked", {"CMTPU_FE_MODE": "stacked"}, 600),
    ("compact", {"CMTPU_FE_MODE": "compact"}, 600),
    ("pallas", {"CMTPU_FE_MODE": "stacked", "CMTPU_LADDER": "pallas"}, 600),
    ("planar", {"CMTPU_FE_MODE": "planar"}, 420),
)


def worker(mode: str) -> None:
    t0 = time.time()

    def log(msg):
        print(f"[ab:{mode} {time.time() - t0:6.1f}s] {msg}", file=sys.stderr, flush=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(HERE, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    import numpy as np

    from cometbft_tpu.ops import ed25519_kernel as ek

    devs = jax.devices()
    log(f"devices: {devs}")
    msg = b"\x00" * 120  # canonical-vote-sized challenge (2 blocks)
    operands, _ = ek.pack_batch([b"\x00" * 32] * N, [msg] * N, [b"\x00" * 64] * N)
    log("packed")
    t1 = time.time()
    fn = ek._compiled(*ek._bucket_key(operands))  # honors CMTPU_HOST_HASH
    jax.block_until_ready(fn(*operands))
    compile_s = time.time() - t1
    log(f"first dispatch {compile_s:.1f}s")
    best = float("inf")
    for _ in range(3):
        t1 = time.perf_counter()
        jax.block_until_ready(fn(*operands))
        best = min(best, time.perf_counter() - t1)
    log(f"steady {best * 1000:.1f} ms")
    print(
        json.dumps(
            {
                "mode": mode,
                "n": N,
                "platform": devs[0].platform,
                "first_dispatch_s": round(compile_s, 2),
                "steady_ms": round(best * 1000, 2),
            }
        ),
        flush=True,
    )


def best_mode(log_path: str = "tpu_ab.log") -> str:
    """Fastest mode with a steady_ms line in the A/B log ('' if none)."""
    best, best_ms = "", float("inf")
    try:
        with open(log_path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                ms = rec.get("steady_ms")
                if ms is not None and ms < best_ms:
                    best, best_ms = rec.get("mode", ""), ms
    except OSError:
        pass
    return best


def main() -> int:
    if "--best" in sys.argv:
        print(best_mode())
        return 0
    for mode, extra_env, tmo in MODES:
        env = {**os.environ, **extra_env, "CMTPU_AB_LABEL": mode}
        try:
            out = subprocess.run(
                [sys.executable, "-u", __file__, "--worker"],
                env=env,
                timeout=tmo,
                capture_output=True,
                text=True,
            )
            for line in out.stdout.splitlines():
                if line.startswith("{"):
                    print(line, flush=True)
            if out.returncode != 0:
                tail = (out.stderr or "").strip().splitlines()[-3:]
                print(
                    json.dumps({"mode": mode, "error": f"rc={out.returncode}", "tail": tail}),
                    flush=True,
                )
        except subprocess.TimeoutExpired:
            print(json.dumps({"mode": mode, "error": f"timeout>{tmo}s"}), flush=True)
    return 0


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker(
            os.environ.get(
                "CMTPU_AB_LABEL", os.environ.get("CMTPU_FE_MODE", "auto")
            )
        )
    else:
        sys.exit(main())

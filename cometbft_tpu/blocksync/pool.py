"""Block pool: tracks in-flight block requests across peers
(reference: blocksync/pool.go).

Requesters cover a moving window of heights (~600 in flight, pool.go:63);
peers advertise their heights via status messages; timed-out or bad peers
get their requests redistributed.
"""

from __future__ import annotations

import threading
import time

MAX_PENDING_REQUESTS = 600
REQUEST_TIMEOUT = 15.0
POOL_WINDOW = 200


class _Requester:
    def __init__(self, height: int):
        self.height = height
        self.peer_id: str | None = None
        self.block = None
        self.requested_at = 0.0


class BlockPool:
    """blocksync/pool.go BlockPool."""

    def __init__(self, start_height: int, send_request, clock=None):
        from cometbft_tpu.simnet.clock import MonotonicClock

        self.height = start_height  # next height to sync
        self._send_request = send_request  # fn(peer_id, height)
        self.clock = clock or MonotonicClock()
        self._mtx = threading.RLock()
        self._requesters: dict[int, _Requester] = {}
        self._peers: dict[str, int] = {}  # peer_id -> reported height
        self.max_peer_height = 0
        self._last_advance = self.clock.now()

    # -- peers ----------------------------------------------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        with self._mtx:
            self._peers[peer_id] = height
            self.max_peer_height = max(self.max_peer_height, height)

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self._peers.pop(peer_id, None)
            for req in self._requesters.values():
                if req.peer_id == peer_id and req.block is None:
                    req.peer_id = None

    # -- scheduling -----------------------------------------------------------

    def make_requests(self) -> None:
        """Spawn requesters for the window and (re)assign idle ones."""
        with self._mtx:
            for h in range(self.height, min(self.height + POOL_WINDOW, self.max_peer_height + 1)):
                if h not in self._requesters:
                    if len(self._requesters) >= MAX_PENDING_REQUESTS:
                        break
                    self._requesters[h] = _Requester(h)
            now = self.clock.now()
            for req in self._requesters.values():
                if req.block is not None:
                    continue
                if req.peer_id is not None and now - req.requested_at < REQUEST_TIMEOUT:
                    continue
                peer = self._pick_peer(req.height)
                if peer is None:
                    continue
                req.peer_id = peer
                req.requested_at = now
                self._send_request(peer, req.height)

    def _pick_peer(self, height: int) -> str | None:
        for peer_id, peer_height in self._peers.items():
            if peer_height >= height:
                return peer_id
        return None

    # -- block flow -----------------------------------------------------------

    def add_block(self, peer_id: str, block) -> bool:
        """pool.go:246 AddBlock."""
        with self._mtx:
            req = self._requesters.get(block.header.height)
            if req is None or req.block is not None:
                return False
            req.block = block
            req.peer_id = peer_id
            return True

    def peek_two_blocks(self):
        """pool.go:193 PeekTwoBlocks: (first, second) at height, height+1."""
        with self._mtx:
            first = self._requesters.get(self.height)
            second = self._requesters.get(self.height + 1)
            return (
                first.block if first else None,
                second.block if second else None,
            )

    def peek_window(self, max_k: int) -> list:
        """Consecutive fetched blocks starting at the sync height (up to
        max_k) — the prefetch window the reactor batch-verifies in one
        device dispatch."""
        with self._mtx:
            out = []
            h = self.height
            while len(out) < max_k:
                req = self._requesters.get(h)
                if req is None or req.block is None:
                    break
                out.append(req.block)
                h += 1
            return out

    def pop_request(self) -> None:
        """Advance after the first block validated + applied."""
        with self._mtx:
            self._requesters.pop(self.height, None)
            self.height += 1
            self._last_advance = self.clock.now()

    def redo_request(self, height: int) -> str | None:
        """Invalid block: drop both pending blocks, re-request (reactor.go:375)."""
        with self._mtx:
            bad_peer = None
            for h in (height, height + 1):
                req = self._requesters.get(h)
                if req is not None:
                    if bad_peer is None:
                        bad_peer = req.peer_id
                    req.block = None
                    req.peer_id = None
            return bad_peer

    def is_caught_up(self) -> bool:
        """pool.go IsCaughtUp."""
        with self._mtx:
            if not self._peers:
                return False
            return self.height >= self.max_peer_height

    def stalled_for(self) -> float:
        with self._mtx:
            return self.clock.now() - self._last_advance

"""Blocksync ("fast sync") — catch-up by downloading blocks from peers
(reference: blocksync/, 1,184 LoC)."""

from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.blocksync.reactor import BlocksyncReactor

__all__ = ["BlockPool", "BlocksyncReactor"]

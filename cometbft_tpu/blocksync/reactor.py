"""Blocksync reactor (reference: blocksync/reactor.go, channel 0x40).

The sync loop validates each block with the NEXT block's LastCommit via
VerifyCommitLight — the TPU-batched hot path (reactor.go:355-400, call at
:360, SURVEY.md §3.3) — then applies it; switches to consensus when caught
up.

Wire (proto/tendermint/blocksync/types.proto): Message oneof
{block_request=1{height}, no_block_response=2{height}, block_response=3
{block}, status_request=4, status_response=5{height, base}}.
"""

from __future__ import annotations

import os
import threading
import time

from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.reactor import BLOCKSYNC_CHANNEL, Reactor
from cometbft_tpu.sidecar import engine
from cometbft_tpu.types.block import Block, BlockID
from cometbft_tpu.wire import proto as wire


def _encode(tag: int, inner: bytes) -> bytes:
    return wire.field_message(tag, inner, emit_empty=True)


def encode_block_request(height: int) -> bytes:
    return _encode(1, wire.field_varint(1, height))


def encode_no_block_response(height: int) -> bytes:
    return _encode(2, wire.field_varint(1, height))


def encode_block_response(block: Block) -> bytes:
    return _encode(3, wire.field_message(1, block.encode(), emit_empty=True))


def encode_status_request() -> bytes:
    return _encode(4, b"")


def encode_status_response(height: int, base: int) -> bytes:
    return _encode(5, wire.field_varint(1, height) + wire.field_varint(2, base))


def decode_message(data: bytes):
    f = wire.decode_fields(data)
    if 1 in f:
        return ("block_request", wire.get_varint(wire.decode_fields(wire.get_bytes(f, 1)), 1))
    if 2 in f:
        return ("no_block_response", wire.get_varint(wire.decode_fields(wire.get_bytes(f, 2)), 1))
    if 3 in f:
        inner = wire.decode_fields(wire.get_bytes(f, 3))
        return ("block_response", Block.decode(wire.get_bytes(inner, 1)))
    if 4 in f:
        return ("status_request", None)
    if 5 in f:
        inner = wire.decode_fields(wire.get_bytes(f, 5))
        return ("status_response", (wire.get_varint(inner, 1), wire.get_varint(inner, 2)))
    raise ValueError("unknown blocksync message")


class BlocksyncReactor(Reactor):
    """blocksync/reactor.go Reactor."""

    def __init__(
        self, state, block_exec, block_store, block_sync: bool,
        on_caught_up=None, clock=None,
    ):
        from cometbft_tpu.simnet.clock import MonotonicClock

        super().__init__("BLOCKSYNC")
        self.clock = clock or MonotonicClock()
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.block_sync_enabled = block_sync
        self.on_caught_up = on_caught_up  # fn(state) -> switch to consensus
        self.pool = BlockPool(
            state.last_block_height + 1, self._send_request, clock=self.clock
        )
        self._running = False
        self.synced = False
        self._prefetched_to = 0  # height up to which the window was batched
        # One-deep verify/apply pipeline: the prefetch producer (device-
        # bound commit verification for the window ahead) runs on a worker
        # while apply_block (app-bound) runs on the sync thread, so the
        # serial per-block decision path below stays unchanged and lands on
        # cache hits. CMTPU_BLOCKSYNC_PIPELINE=0 restores the inline
        # prefetch-then-verify ordering.
        self._pipeline_enabled = (
            os.environ.get("CMTPU_BLOCKSYNC_PIPELINE", "1") != "0"
        )
        self._pf_job: tuple[threading.Event, list[float]] | None = None
        self.pipeline_overlap_ms = 0.0  # verify/apply overlap accumulated

    def get_channels(self):
        return [
            ChannelDescriptor(
                BLOCKSYNC_CHANNEL, priority=5, send_queue_capacity=1000,
                recv_message_capacity=50 * 1024 * 1024,
            )
        ]

    def start(self) -> None:
        self._running = True
        if self.block_sync_enabled:
            threading.Thread(target=self._pool_routine, daemon=True).start()

    def stop(self) -> None:
        self._running = False

    def switch_to_block_sync(self, state, block_exec=None) -> None:
        """reactor.go SwitchToBlockSync: statesync finished — start fast-sync
        from the freshly bootstrapped state (node.go:423-433 boot phasing)."""
        self.state = state
        if block_exec is not None:
            self.block_exec = block_exec
        self.synced = False
        with self.pool._mtx:
            self.pool.height = state.last_block_height + 1
        was_enabled = self.block_sync_enabled
        self.block_sync_enabled = True
        if self._running and not was_enabled:
            threading.Thread(target=self._pool_routine, daemon=True).start()

    # -- peers ----------------------------------------------------------------

    def add_peer(self, peer) -> None:
        peer.try_send(
            BLOCKSYNC_CHANNEL,
            encode_status_response(self.block_store.height(), self.block_store.base()),
        )
        peer.try_send(BLOCKSYNC_CHANNEL, encode_status_request())

    def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        kind, payload = decode_message(msg_bytes)
        if kind == "block_request":
            block = self.block_store.load_block(payload)
            if block is not None:
                peer.try_send(BLOCKSYNC_CHANNEL, encode_block_response(block))
            else:
                peer.try_send(BLOCKSYNC_CHANNEL, encode_no_block_response(payload))
        elif kind == "block_response":
            self.pool.add_block(peer.id, payload)
        elif kind == "status_request":
            peer.try_send(
                BLOCKSYNC_CHANNEL,
                encode_status_response(self.block_store.height(), self.block_store.base()),
            )
        elif kind == "status_response":
            height, base = payload
            self.pool.set_peer_range(peer.id, base, height)
        elif kind == "no_block_response":
            pass

    def _send_request(self, peer_id: str, height: int) -> None:
        peer = self.switch.get_peer(peer_id) if self.switch else None
        if peer is not None:
            peer.try_send(BLOCKSYNC_CHANNEL, encode_block_request(height))

    # -- sync loop (reactor.go:280-410 poolRoutine) ---------------------------

    def _pool_routine(self) -> None:
        status_tick = 0.0
        while self._running and not self.synced:
            self.pool.make_requests()
            now = self.clock.now()
            if now - status_tick > 10:
                status_tick = now
                if self.switch:
                    self.switch.broadcast(BLOCKSYNC_CHANNEL, encode_status_request())
            if self._try_sync_one():
                continue  # immediately try the next pair
            # IsCaughtUp needs >= 1 peer STATUS (pool._peers non-empty), so a
            # fresh all-genesis net switches to consensus as soon as statuses
            # arrive — matching reactor.go's switchToConsensusTicker, which
            # gates on IsCaughtUp alone (a max-height>0 guard would deadlock
            # the everyone-at-height-0 boot).
            if self.pool.is_caught_up():
                self.synced = True
                if self.on_caught_up:
                    self.on_caught_up(self.state)
                return
            self.clock.sleep(0.01)

    # Prefetch window: how many consecutive fetched blocks to batch-verify
    # in ONE device dispatch. 32 blocks x 1k validators fills the 32768
    # bucket; the verified-triple cache then makes both the trySync
    # VerifyCommitLight AND ApplyBlock's full LastCommit check cache hits.
    PREFETCH_WINDOW = 32
    # Signature budget for one prefetch dispatch: stay within the largest
    # precompiled device bucket AND well under the verified-triple cache
    # (ed25519._VERIFIED_MAX = 131072), else a large validator set makes the
    # window force a one-off oversized XLA compile and evict its own cache
    # entries before trySync consumes them.
    PREFETCH_MAX_SIGS = 32768

    def _prefetch_verify_window(self) -> None:
        """TPU-first fast sync: while validator sets are unchanged
        (header.validators_hash pins the exact set that signed each
        commit), the signatures of MANY consecutive blocks' commits are
        independent — verify them all in one batched device call and let
        the per-commit protocol checks hit the verified-triple cache.
        Failures are simply not cached; the per-block path then attributes
        the bad block and punishes the peer as before."""
        from cometbft_tpu.crypto import ed25519

        if self.pool.height < self._prefetched_to:
            return
        vals = self.state.validators
        # Clamp the window in SIGNATURES, not blocks (a 10k-validator set
        # at 32 blocks would be ~320k triples in one dispatch).  Below 3
        # blocks there is nothing to batch (window covers window-1 commits);
        # skip before paying the pool-mutex peek.
        window_blocks = min(
            self.PREFETCH_WINDOW,
            self.PREFETCH_MAX_SIGS // max(1, len(vals.validators)),
        )
        if window_blocks < 3:
            return
        window = self.pool.peek_window(window_blocks)
        if len(window) < 3:
            return
        # Only ed25519 carries the verified-triple cache; for other key
        # types a prefetch would be pure extra work (three verifications
        # per commit instead of two).
        if not all(
            isinstance(v.pub_key, ed25519.PubKey) for v in vals.validators
        ):
            self._prefetched_to = self.pool.height + self.PREFETCH_WINDOW
            return
        # A pure optimization must never take down the sync thread: blocks
        # here are unvalidated peer input (oversized signatures etc. make
        # bv.add raise), and backend hiccups surface from bv.verify — the
        # per-block path re-verifies, attributes, and punishes as before.
        try:
            bv = ed25519.BatchVerifier()
            vh = vals.hash()
            chain_id = self.state.chain_id
            covered = 0
            for j in range(len(window) - 1):
                blk, nxt = window[j], window[j + 1]
                commit = nxt.last_commit
                if (
                    blk.header.validators_hash != vh
                    or commit is None
                    or commit.height != blk.header.height
                    or len(commit.signatures) != len(vals.validators)
                ):
                    break
                sbs = commit.vote_sign_bytes_all(chain_id)
                for idx, cs in enumerate(commit.signatures):
                    if cs.is_absent():
                        continue
                    bv.add(vals.validators[idx].pub_key, sbs[idx], cs.signature)
                covered += 1
            self._prefetched_to = self.pool.height + max(covered, 1)
            if covered >= 2 and len(bv):
                # Blocksync-class engine admission (the untagged default,
                # made explicit): window pre-verify yields to consensus
                # votes but outranks ingress and light prewarm.
                with engine.submission_class(engine.CLASS_BLOCKSYNC):
                    bv.verify()  # populates the cache; bad sigs fall to per-block
        except Exception:
            self._prefetched_to = self.pool.height + 1

    # -- verify/apply pipeline ------------------------------------------------

    def _pipeline_submit(self) -> None:
        """Kick the prefetch producer on a worker so it overlaps the
        apply_block that follows. One-deep: a still-running job means the
        producer is already ahead — never stack a second one."""
        job = self._pf_job
        if job is not None and not job[0].is_set():
            return
        done = threading.Event()
        times = [time.monotonic(), 0.0]

        def run():
            try:
                self._prefetch_verify_window()
            finally:
                times[1] = time.monotonic()
                done.set()

        self._pf_job = (done, times)
        threading.Thread(target=run, daemon=True).start()

    def _pipeline_wait(self) -> None:
        """Barrier before the serial verify: the producer must have finished
        populating the verified cache for the height we are about to check.
        Bounded — _prefetch_verify_window swallows its own errors, so the
        worker always terminates."""
        job = self._pf_job
        if job is not None:
            job[0].wait(timeout=60.0)

    def _pipeline_account(self, apply_t0: float, apply_t1: float) -> None:
        job = self._pf_job
        if job is None:
            return
        done, times = job
        end = times[1] if done.is_set() else apply_t1
        overlap = min(apply_t1, end) - max(apply_t0, times[0])
        if overlap > 0:
            self.pipeline_overlap_ms += overlap * 1000.0

    def _try_sync_one(self) -> bool:
        """reactor.go:340-400 trySync: verify `first` with `second.LastCommit`
        (VerifyCommitLight — batched on device), then apply."""
        first, second = self.pool.peek_two_blocks()
        if first is None or second is None:
            return False
        if self._pipeline_enabled:
            self._pipeline_wait()
        else:
            self._prefetch_verify_window()
        first_parts = first.make_part_set()
        first_id = BlockID(first.hash(), first_parts.header())
        try:
            # ★ the TPU call (types/validation.go:59 via blocksync/reactor.go:360)
            self.state.validators.verify_commit_light(
                self.state.chain_id, first_id, first.header.height, second.last_commit
            )
            self.block_exec.validate_block(self.state, first)
        except Exception:
            bad_peer = self.pool.redo_request(first.header.height)
            if bad_peer and self.switch:
                peer = self.switch.get_peer(bad_peer)
                if peer:
                    self.switch.stop_peer_for_error(peer, "sent us an invalid block")
            return False
        self.block_store.save_block(first, first_parts, second.last_commit)
        if self._pipeline_enabled:
            # Overlap the next window's verification (device) with this
            # block's application (app). The worker only POPULATES the
            # verified-triple cache — the accepting verify_commit_light
            # above still runs serially on this thread, so a validator-set
            # change simply misses the cache and verifies inline.
            self._pipeline_submit()
            t0 = time.monotonic()
            self.state, _ = self.block_exec.apply_block(self.state, first_id, first)
            self._pipeline_account(t0, time.monotonic())
        else:
            self.state, _ = self.block_exec.apply_block(self.state, first_id, first)
        self.pool.pop_request()
        return True

"""Handshake replay: sync CometBFT state, block store, and the app on startup
(reference: consensus/replay.go:241 Handshake, :284 ReplayBlocks).

The three persisted tiers can legally differ by at most one height after a
crash (state <= store <= state+1, app <= store). The case analysis replays
whatever is behind so all three advance together — crucially, the block at
state_height+1 is applied via BlockExecutor.apply_block so consensus state,
store, and app stay in lockstep instead of the app silently running ahead
(the round-1 bug: replaying store-height blocks into the app without
updating state double-executed that block on restart).
"""

from __future__ import annotations

from cometbft_tpu.abci import types as abci_types
from cometbft_tpu.state.execution import (
    BlockExecutor,
    build_last_commit_info,
    decode_responses,
)


class _ReplayMempool:
    """Stub mempool for handshake-time ApplyBlock (replay.go emptyMempool)."""

    def lock(self):
        pass

    def unlock(self):
        pass

    def flush_app_conn(self):
        pass

    def update(self, *a, **k):
        pass

    def reap_max_bytes_max_gas(self, *a):
        return []


class _MockCommitConn:
    """Proxy-app stand-in replaying stored ABCI responses
    (consensus/replay_stubs.go newMockProxyApp): used when the app already
    ran Commit but CometBFT crashed before saving state — re-running the
    block against the real app would double-execute it."""

    def __init__(self, app_hash: bytes, stored_responses: dict):
        self._app_hash = app_hash
        self._responses = stored_responses
        self._tx_idx = 0

    def begin_block(self, req):
        return self._responses["begin_block"]

    def deliver_tx(self, req):
        r = self._responses["deliver_txs"][self._tx_idx]
        self._tx_idx += 1
        return r

    def end_block(self, req):
        return self._responses["end_block"]

    def commit(self):
        return abci_types.ResponseCommit(data=self._app_hash)

    def prepare_proposal(self, req):  # pragma: no cover - not used in replay
        return abci_types.ResponsePrepareProposal(txs=list(req.txs))

    def process_proposal(self, req):  # pragma: no cover
        return abci_types.ResponseProcessProposal(
            status=abci_types.PROCESS_PROPOSAL_ACCEPT
        )


class AppHashMismatchError(RuntimeError):
    pass


class AppHeightError(RuntimeError):
    pass


class Handshaker:
    """consensus/replay.go:213-238."""

    def __init__(self, state_store, state, block_store, genesis_doc, event_bus=None, logger=None):
        self.state_store = state_store
        self.initial_state = state
        self.store = block_store
        self.genesis_doc = genesis_doc
        self.event_bus = event_bus
        self.logger = logger
        self.n_blocks = 0

    def handshake(self, proxy_app):
        """Query app Info, replay as needed. Returns the synced State."""
        info = proxy_app.query.info(abci_types.RequestInfo())
        app_height = info.last_block_height
        if app_height < 0:
            raise AppHeightError(f"got negative app height {app_height}")
        return self.replay_blocks(
            self.initial_state, info.last_block_app_hash, app_height, proxy_app
        )

    # -- replay.go:284 ReplayBlocks -------------------------------------------

    def replay_blocks(self, state, app_hash, app_height, proxy_app):
        store_base = self.store.base()
        store_height = self.store.height()
        state_height = state.last_block_height

        if app_height == 0:
            state, app_hash = self._init_chain(state, proxy_app)

        # Edge cases on store height/base (replay.go:358-383).
        if store_height == 0:
            _assert_app_hash(app_hash, state.app_hash, "state")
            return state
        if app_height == 0 and state.initial_height < store_base:
            raise AppHeightError(
                f"app has no state; block store truncated to base {store_base}"
            )
        if 0 < app_height < store_base - 1:
            raise AppHeightError(
                f"app height {app_height} too far below store base {store_base}"
            )
        if store_height < app_height:
            raise AppHeightError(
                f"app height ({app_height}) is higher than core ({store_height})"
            )
        if store_height < state_height:
            raise RuntimeError(
                f"StateBlockHeight ({state_height}) > StoreBlockHeight ({store_height})"
            )
        if store_height > state_height + 1:
            raise RuntimeError(
                f"StoreBlockHeight ({store_height}) > StateBlockHeight+1 ({state_height + 1})"
            )

        if store_height == state_height:
            # CometBFT ran Commit and saved state; app may ask for replay.
            if app_height < store_height:
                replayed = self._replay_blocks_through_app(
                    state, proxy_app, app_height, store_height
                )
                # replay.go:488 assertAppHashEqualsOneFromState: replay does
                # not mutate state here, so the app must land exactly on the
                # hash consensus already committed to.
                _assert_app_hash(replayed, state.app_hash, "state")
            elif app_height == store_height:
                _assert_app_hash(app_hash, state.app_hash, "state")
            return state

        # store_height == state_height + 1: block saved, state not updated.
        if app_height < state_height:
            # App even further behind: replay up to state_height through the
            # app, then apply the final block for real (mutateState).
            self._replay_blocks_through_app(state, proxy_app, app_height, state_height)
            return self._replay_final_block(state, store_height, proxy_app.consensus)
        if app_height == state_height:
            # Commit never ran: apply the stored block via the real app so
            # state/store/app advance together (replay.go:421).
            return self._replay_final_block(state, store_height, proxy_app.consensus)
        if app_height == store_height:
            # App ran Commit but state wasn't saved: replay through a mock
            # conn fed by the stored ABCI responses (replay.go:429-438).
            raw = self.state_store.load_abci_responses(store_height)
            if raw is None:
                raise RuntimeError(
                    f"no stored ABCI responses for height {store_height}; "
                    "cannot replay the committed block without re-executing it"
                )
            mock = _MockCommitConn(app_hash, decode_responses(raw))
            return self._replay_final_block(state, store_height, mock)
        raise RuntimeError(
            f"uncovered replay case: app {app_height}, store {store_height}, "
            f"state {state_height}"
        )

    # -- helpers ---------------------------------------------------------------

    def _init_chain(self, state, proxy_app):
        """replay.go:303-355 (InitChain at genesis)."""
        validators = [
            abci_types.ValidatorUpdate(pub_key=v.pub_key, power=v.power)
            for v in self.genesis_doc.validators
        ]
        res = proxy_app.consensus.init_chain(
            abci_types.RequestInitChain(
                time_seconds=self.genesis_doc.genesis_time.seconds,
                chain_id=self.genesis_doc.chain_id,
                consensus_params=self.genesis_doc.consensus_params,
                validators=validators,
                app_state_bytes=_app_state_bytes(self.genesis_doc.app_state),
                initial_height=self.genesis_doc.initial_height,
            )
        )
        app_hash = res.app_hash
        if state.last_block_height == 0:
            if res.app_hash:
                state.app_hash = res.app_hash
            if res.validators:
                from cometbft_tpu.types.validator import Validator
                from cometbft_tpu.types.validator_set import ValidatorSet

                vals = [Validator.new(vu.pub_key, vu.power) for vu in res.validators]
                state.validators = ValidatorSet(vals)
                state.next_validators = state.validators.copy_increment_proposer_priority(1)
            elif not self.genesis_doc.validators:
                raise RuntimeError(
                    "validator set is nil in genesis and still empty after InitChain"
                )
            if res.consensus_params is not None:
                state.consensus_params = state.consensus_params.update(
                    res.consensus_params
                )
            self.state_store.save(state)
        return state, app_hash

    def _replay_blocks_through_app(self, state, proxy_app, from_height, to_height):
        """replay.go:439-490 replayBlocks: raw ABCI execution (no state
        mutation — historical validator sets come from the state store).
        Returns the app hash of the last replayed Commit so callers can run
        the reference's assertAppHashEqualsOneFromState check."""
        first = from_height + 1
        if first == 1:
            first = state.initial_height
        app_hash = b""
        for h in range(first, to_height + 1):
            block = self.store.load_block(h)
            if block is None:
                raise RuntimeError(f"block store has no block at height {h}")
            app_hash = self._exec_commit_block(
                proxy_app.consensus, block, h, state.initial_height
            )
            self.n_blocks += 1
        return app_hash

    def _exec_commit_block(self, conn, block, height, initial_height=1):
        """sm.ExecCommitBlock: BeginBlock/DeliverTx*/EndBlock/Commit with the
        historical validator set for last_commit_info."""
        vals_prev = None
        if height > initial_height:
            # A missing validator record is fatal (sm.ExecCommitBlock panics):
            # replaying with an empty last_commit_info would silently feed the
            # app different vote info than it saw live → app-hash divergence.
            vals_prev = self.state_store.load_validators(height - 1)
        commit_info = build_last_commit_info(block.last_commit, vals_prev)
        conn.begin_block(
            abci_types.RequestBeginBlock(
                hash=block.hash() or b"",
                header=block.header,
                last_commit_info=commit_info,
            )
        )
        for tx in block.data.txs:
            conn.deliver_tx(abci_types.RequestDeliverTx(tx=tx))
        conn.end_block(abci_types.RequestEndBlock(height=height))
        res = conn.commit()
        return res.data

    def _replay_final_block(self, state, height, conn):
        """replay.go:492-512 replayBlock: full ApplyBlock so state advances."""
        block = self.store.load_block(height)
        meta = self.store.load_block_meta(height)
        if block is None or meta is None:
            raise RuntimeError(f"block store missing block/meta at height {height}")
        block_exec = BlockExecutor(
            self.state_store,
            conn,
            _ReplayMempool(),
            None,
            self.store,
            self.event_bus,
            self.logger,
        )
        new_state, _ = block_exec.apply_block(state, meta.block_id, block)
        self.n_blocks += 1
        return new_state


def _app_state_bytes(app_state) -> bytes:
    """GenesisDoc.app_state is parsed JSON; ABCI wants the raw bytes."""
    if app_state is None:
        return b""
    if isinstance(app_state, (bytes, bytearray)):
        return bytes(app_state)
    import json

    return json.dumps(app_state).encode()


def _assert_app_hash(app_hash: bytes, expected: bytes, what: str) -> None:
    if app_hash != expected:
        raise AppHashMismatchError(
            f"app hash {app_hash.hex()} does not match {what} app hash "
            f"{expected.hex()} after replay. Did you reset CometBFT without "
            "resetting the application?"
        )

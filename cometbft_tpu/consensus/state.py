"""The Tendermint BFT consensus state machine (reference: consensus/state.go).

Single-writer design exactly like the reference's receiveRoutine
(consensus/state.go:718-806): one thread owns all round state; peer
messages, own messages, and timeouts are serialized through one queue. Own
messages are fsynced to the WAL before processing (state.go:774), peer
messages are buffered-written.

Height/round/step transitions (state.go:988-1720): NewRound → Propose →
Prevote → PrevoteWait → Precommit → PrecommitWait → Commit, with POL
locking/unlocking rules and valid-block tracking.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import traceback
from dataclasses import replace

from cometbft_tpu.consensus import cstypes
from cometbft_tpu.consensus.cstypes import (
    STEP_COMMIT,
    STEP_NEW_HEIGHT,
    STEP_NEW_ROUND,
    STEP_PRECOMMIT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
    HeightVoteSet,
    RoundState,
)
from cometbft_tpu.consensus.messages import (
    BlockPartMessage,
    HasVoteMessage,
    NewRoundStepMessage,
    ProposalMessage,
    TimeoutInfo,
    VoteMessage,
)
from cometbft_tpu.consensus.ticker import TimeoutTicker
from cometbft_tpu.consensus.wal import WAL, EndHeightMessage
from cometbft_tpu.libs import fail
from cometbft_tpu.privval.file import (
    STEP_PRECOMMIT as PV_STEP_PRECOMMIT,
    STEP_PREVOTE as PV_STEP_PREVOTE,
)
from cometbft_tpu.types import cmttime, events as ev
from cometbft_tpu.types.canonical import decode_canonical_vote
from cometbft_tpu.types.block import (
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    BlockID,
    Commit,
    PartSetHeader,
)
from cometbft_tpu.types.part_set import PartSet
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.types.vote_set import ErrVoteConflictingVotes, VoteError


class _NilWAL:
    def write(self, msg):
        pass

    def write_sync(self, msg):
        pass

    def flush_and_sync(self):
        pass

    def start(self):
        pass

    def stop(self):
        pass


class ConsensusState:
    """consensus/state.go State."""

    def __init__(
        self,
        config,
        state,
        block_exec,
        block_store,
        mempool,
        evpool=None,
        event_bus=None,
        wal: WAL | None = None,
        ticker: TimeoutTicker | None = None,
        logger=None,
        name: str = "",
        metrics=None,
        clock=None,
    ):
        from cometbft_tpu.simnet.clock import MonotonicClock

        self.clock = clock or MonotonicClock()
        self.config = config
        self.block_exec = block_exec
        self.block_store = block_store
        self.mempool = mempool
        self.evpool = evpool
        self.event_bus = event_bus
        self.wal = wal or _NilWAL()
        self.ticker = ticker or TimeoutTicker(clock=self.clock)
        self.logger = logger
        self.name = name
        from cometbft_tpu.consensus.metrics import Metrics as _CsMetrics

        self.metrics = metrics or _CsMetrics()

        self.rs = RoundState()
        self.state = None  # sm.State, set in update_to_state
        # Known-bad (pub, sig, signbytes) triples seen by the prebatcher —
        # see _prebatch_vote_signatures.
        self._failed_triples: dict[bytes, None] = {}
        self.priv_validator = None
        self.priv_validator_pub_key = None
        self.replay_mode = False
        self.do_wal_catchup = True

        # Unbounded: the single consumer also produces (own proposal parts and
        # votes enter this queue from inside the receive routine), so a
        # bounded queue could self-deadlock on large blocks.
        self._queue: queue.Queue = queue.Queue()
        self._mtx = threading.RLock()
        self._running = False
        self._thread: threading.Thread | None = None
        self._broadcast = None  # fn(msg) -> None: reactor / test harness hook
        self._height_events = threading.Condition()
        # Stall watchdog: no round-step progress for stall_factor × the
        # current round's full timeout budget ⇒ re-announce + re-arm.
        self._on_stall = None  # reactor hook: fn() -> None
        self._last_progress = self.clock.now()
        self._stall_factor = getattr(config, "stall_watchdog_factor", 10.0)
        env_factor = os.environ.get("CMTPU_STALL_FACTOR")
        if env_factor:
            try:
                self._stall_factor = float(env_factor)
            except ValueError:
                pass

        self.update_to_state(state)
        self._reconstruct_last_commit_if_needed(state)

    # -- wiring ---------------------------------------------------------------

    def set_priv_validator(self, pv) -> None:
        with self._mtx:
            self.priv_validator = pv
            if pv is not None:
                self.priv_validator_pub_key = pv.get_pub_key()

    def set_broadcast(self, fn) -> None:
        """Reactor hook: called with every own message to gossip
        (ProposalMessage / BlockPartMessage / VoteMessage)."""
        self._broadcast = fn

    def set_on_stall(self, fn) -> None:
        """Reactor hook: called (from the watchdog thread) when no round-step
        progress has been made for the stall budget."""
        self._on_stall = fn

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self.wal.start()
        self.ticker.start()
        # Catch up within the current height from the WAL BEFORE processing
        # new messages (state.go:318-370): a node that crashed mid-height
        # replays its own proposals/votes/timeouts so it can't equivocate and
        # doesn't stall the round. Corrupted WALs get one repair attempt.
        if self.do_wal_catchup:
            self._wal_catchup_with_repair()
        # Hand ticker tocks into the unified queue.
        self._tock_pump = threading.Thread(target=self._pump_tocks, daemon=True)
        self._running = True
        self._tock_pump.start()
        self._thread = threading.Thread(target=self._receive_routine, daemon=True)
        self._thread.start()
        if self.rs.round == 0 and self.rs.step == STEP_NEW_HEIGHT:
            self._schedule_round0()
        else:
            # WAL replay restored a later round/step: a round-0 NEW_HEIGHT
            # timeout would be discarded by _handle_timeout AND (single-timer
            # ticker) would clobber the restored step's pending timer — re-arm
            # the timer the restored step actually needs.
            with self._mtx:
                self._rearm_step_timeout()
        self._last_progress = self.clock.now()
        if self._stall_factor > 0:
            threading.Thread(
                target=self._stall_watchdog_routine, daemon=True
            ).start()

    def _wal_catchup_with_repair(self) -> None:
        """state.go:320-370: catchupReplay, with a one-shot corrupted-WAL
        repair (backup to .CORRUPTED, keep intact prefix, retry)."""
        from cometbft_tpu.consensus.wal import DataCorruptionError, repair_wal

        repair_attempted = False
        while True:
            try:
                self._catchup_replay(self.rs.height)
                return
            except DataCorruptionError as e:
                if repair_attempted:
                    raise
                repair_attempted = True
                path = getattr(self.wal, "path", None)
                if path is None:
                    raise
                self._log(f"WAL corrupted ({e}); attempting repair")
                self.wal.stop()
                corrupted = path + ".CORRUPTED"
                import shutil

                shutil.copyfile(path, corrupted)
                repair_wal(corrupted, path)
                self.wal.reopen()
                # Re-anchor: if repair emptied the file, start() rewrites the
                # EndHeightMessage(0) replay anchor (state.go loadWalFile
                # re-runs OnStart).
                self.wal.start()
            except Exception as e:
                # Non-corruption replay errors: log and start anyway
                # (state.go:330 "proceeding to start state anyway").
                self._log(f"error on WAL catchup replay; starting anyway: {e}")
                return

    def _catchup_replay(self, cs_height: int) -> None:
        """consensus/replay.go:93 catchupReplay: re-apply every WAL message
        recorded after the last committed height's EndHeightMessage."""
        from cometbft_tpu.consensus.wal import DataCorruptionError

        self.replay_mode = True
        try:
            if cs_height < self.state.initial_height:
                raise RuntimeError(
                    f"cannot replay height {cs_height}, below initial height "
                    f"{self.state.initial_height}"
                )
            end_height = cs_height - 1
            if cs_height == self.state.initial_height:
                end_height = 0
            if not hasattr(self.wal, "catchup_scan"):
                return  # nil WAL
            # One pass answers both: messages to replay, and the sanity check
            # that no #ENDHEIGHT exists for the CURRENT height (that would
            # mean update_to_state should already have advanced past it).
            msgs, saw_cs_end = self.wal.catchup_scan(end_height, cs_height)
            if saw_cs_end:
                raise RuntimeError(f"wal should not contain #ENDHEIGHT {cs_height}")
            if msgs is None:
                raise RuntimeError(
                    f"cannot replay height {cs_height}: WAL has no #ENDHEIGHT "
                    f"for {end_height}"
                )
            msgs = list(msgs)
            # Restore the ROUND reached before the crash, not round 0. Only
            # own messages (write_sync, fsynced) and our own ticker's
            # timeouts are trusted for this — a garbage peer vote in the
            # buffered WAL tail must not drag us to an arbitrary round.
            wal_round = self._scan_wal_round(msgs, cs_height)
            if wal_round > 0:
                # Enter BEFORE replaying: _set_proposal only accepts the
                # proposal for rs.round, and entering pre-creates the vote
                # sets so own votes from intermediate rounds land instead of
                # tripping HeightVoteSet's 2-catchup-round peer limit.
                with self._mtx:
                    self.rs.votes.set_round(wal_round + 1)
                    self._enter_new_round(cs_height, wal_round)
            n = 0
            for tm in msgs:
                self._read_replay_message(tm)
                n += 1
            # Message replay alone leaves the step wherever vote majorities
            # drove it; if our own recorded votes prove we got further
            # (peer votes/timeouts are buffered writes and die with a
            # SIGKILL), re-enter those steps. replay_mode swallows the
            # double-sign refusals; identical re-signs rebroadcast our votes.
            with self._mtx:
                self._recover_privval_vote(cs_height)
                self._restore_wal_step(cs_height)
            self.metrics.wal_replay_round.set(self.rs.round)
            if n:
                self._log(
                    f"WAL catchup: replayed {n} messages at height {cs_height}"
                    f" (round {self.rs.round})"
                )
        finally:
            self.replay_mode = False

    def _scan_wal_round(self, msgs, cs_height: int) -> int:
        """Highest round provably reached before the crash: our own signed
        votes (fsynced before processing) and our own ticker's timeouts."""
        own_addr = (
            self.priv_validator_pub_key.address()
            if self.priv_validator_pub_key is not None
            else None
        )
        wal_round = 0
        for tm in msgs:
            msg = tm.msg
            if isinstance(msg, TimeoutInfo) and msg.height == cs_height:
                wal_round = max(wal_round, msg.round)
            elif (
                isinstance(msg, VoteMessage)
                and msg.vote.height == cs_height
                and own_addr is not None
                and msg.vote.validator_address == own_addr
            ):
                wal_round = max(wal_round, msg.vote.round)
        # The privval fsyncs its last-sign state BEFORE the vote reaches the
        # WAL (sign_vote persists, then _send_internal queues the write), so
        # a crash in that window leaves a signed round the WAL never saw.
        # The sign state is as trustworthy as our own fsynced votes.
        lss = getattr(self.priv_validator, "last_sign_state", None)
        if (
            lss is not None
            and getattr(lss, "height", None) == cs_height
            and getattr(lss, "step", 0) in (PV_STEP_PREVOTE, PV_STEP_PRECOMMIT)
        ):
            wal_round = max(wal_round, lss.round)
        return wal_round

    def _recover_privval_vote(self, cs_height: int) -> None:
        """Re-publish the privval's last signed vote when the WAL lost it.

        A crash between FilePV's fsync and the WAL's write_sync leaves the
        privval remembering a vote this node never recorded or broadcast.
        After restart the double-sign guard then refuses to vote at that
        (height, round, step) — correctly — but the round's quorum may be
        impossible without this validator's power, livelocking the whole
        network at that round. The persisted sign_bytes + signature are
        enough to reconstruct the exact vote; feeding it back through
        _send_internal fsyncs it to the WAL, broadcasts it to peers, and
        adds it to our own vote set like any other own vote."""
        pv = self.priv_validator
        lss = getattr(pv, "last_sign_state", None)
        if lss is None or not getattr(lss, "sign_bytes", None):
            return
        if not getattr(lss, "signature", None):
            return
        if lss.height != cs_height or lss.step not in (
            PV_STEP_PREVOTE,
            PV_STEP_PRECOMMIT,
        ):
            return
        rs = self.rs
        if rs.height != cs_height or rs.votes is None:
            return
        if self.priv_validator_pub_key is None:
            return
        own_addr = self.priv_validator_pub_key.address()
        if not rs.validators.has_address(own_addr):
            return
        vote_set = (
            rs.votes.prevotes(lss.round)
            if lss.step == PV_STEP_PREVOTE
            else rs.votes.precommits(lss.round)
        )
        if vote_set is None or vote_set.get_by_address(own_addr) is not None:
            return  # WAL replay already restored it
        try:
            msg_type, height, round_, block_id, ts = decode_canonical_vote(
                lss.sign_bytes
            )
        except Exception as e:
            self._log(f"cannot decode privval last sign bytes: {e}")
            return
        if height != cs_height or round_ != lss.round:
            return
        idx, _ = rs.validators.get_by_address(own_addr)
        vote = Vote(
            type=msg_type,
            height=height,
            round=round_,
            block_id=block_id,
            timestamp=ts,
            validator_address=own_addr,
            validator_index=idx,
            signature=lss.signature,
        )
        self._send_internal(VoteMessage(vote))
        self._log(
            f"recovered last signed vote from privval state "
            f"(h={height} r={round_} type={msg_type})"
        )

    def _restore_wal_step(self, cs_height: int) -> None:
        """Re-enter prevote/precommit at the restored round when the WAL
        holds our own vote for that step (replay-mode only)."""
        rs = self.rs
        if rs.height != cs_height or rs.votes is None:
            return
        if self.priv_validator_pub_key is None:
            return
        own_addr = self.priv_validator_pub_key.address()
        prevotes = rs.votes.prevotes(rs.round)
        precommits = rs.votes.precommits(rs.round)
        prevoted = prevotes is not None and prevotes.get_by_address(own_addr) is not None
        precommitted = (
            precommits is not None and precommits.get_by_address(own_addr) is not None
        )
        # The privval sign state is fsynced before the WAL write, so it can
        # prove a step the WAL lost (see _recover_privval_vote).
        lss = getattr(self.priv_validator, "last_sign_state", None)
        if (
            lss is not None
            and getattr(lss, "height", None) == cs_height
            and lss.round == rs.round
        ):
            prevoted = prevoted or lss.step >= PV_STEP_PREVOTE
            precommitted = precommitted or lss.step >= PV_STEP_PRECOMMIT
        if prevoted:
            self._enter_prevote(cs_height, rs.round)
        if precommitted:
            self._enter_precommit(cs_height, rs.round)

    def _read_replay_message(self, tm) -> None:
        """replay.go:36-90 readReplayMessage: route one TimedWALMessage back
        through the live handlers (sign attempts hit the double-sign guard
        and are ignored in replay mode)."""
        msg = tm.msg
        if isinstance(msg, EndHeightMessage):
            return
        with self._mtx:
            if isinstance(msg, TimeoutInfo):
                self._handle_timeout(msg)
            else:
                self._handle_msg(msg, "")

    def _log(self, text: str) -> None:
        if self.logger is not None and hasattr(self.logger, "error"):
            self.logger.error(text)
        else:
            print(f"[{self.name or 'consensus'}] {text}")

    def stop(self) -> None:
        self._running = False
        self.ticker.stop()
        self.wal.stop()

    def _pump_tocks(self) -> None:
        while self._running:
            try:
                ti = self.ticker.tock_queue.get(timeout=0.1)
            except queue.Empty:
                continue
            self._queue.put(("timeout", ti, ""))

    # -- message entry points -------------------------------------------------

    def send_peer_message(self, msg, peer_id: str = "peer") -> None:
        self._queue.put(("peer", msg, peer_id))

    def _send_internal(self, msg) -> None:
        self._queue.put(("internal", msg, ""))
        if self._broadcast is not None:
            self._broadcast(msg)

    # -- the single-writer event loop (state.go:718-806) ----------------------

    def _receive_routine(self) -> None:
        while self._running:
            try:
                items = [self._queue.get(timeout=0.1)]
            except queue.Empty:
                continue
            # Opportunistic drain: under vote storms (large validator sets,
            # gossip bursts) the queue holds many VoteMessages — pre-verify
            # their signatures in ONE device batch so the serial per-vote
            # checks below become verified-cache hits. No reordering, no
            # added latency: only what is ALREADY queued is drained.
            while len(items) < 256:
                try:
                    items.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            # Never delay a drained timeout or own-message behind a device
            # call: round progression (and the WAL fsync of own msgs) must
            # not wait on a possibly-slow backend. Note ApplyBlock already
            # rides the device for commit verification, so prebatching adds
            # no NEW device dependency to consensus — only this ordering
            # hazard, which the guard removes.
            if len(items) >= 8 and all(k == "peer" for k, _, _ in items):
                self._prebatch_vote_signatures(items)
            for kind, payload, peer_id in items:
                try:
                    with self._mtx:
                        if kind == "timeout":
                            self.wal.write(payload)
                            self._handle_timeout(payload)
                        elif kind == "internal":
                            # fsync own messages before acting (state.go:774).
                            self.wal.write_sync(payload)
                            fail.fail()  # kill-point: own msg durable, unprocessed (state.go:787)
                            self._handle_msg(payload, "")
                        else:
                            self.wal.write(payload)
                            self._handle_msg(payload, peer_id)
                except Exception:
                    if self.logger:
                        self.logger.error(
                            f"consensus failure: {traceback.format_exc()}"
                        )
                    else:
                        traceback.print_exc()

    # Bound on the known-bad-triple memo (below): enough for a sustained
    # invalid-vote storm without growing unboundedly.
    _FAILED_TRIPLES_MAX = 4096

    def _prebatch_vote_signatures(self, items) -> None:
        """Batch-verify the signatures of queued peer votes (crypto only —
        every protocol check still runs in _try_add_vote; invalid sigs are
        simply not cached and fail there as before). A pure optimization:
        errors here must never disturb the state machine.

        Triples that already failed a batch are memoized and skipped, so an
        attacker replaying invalid signatures costs one device dispatch and
        one host verify per UNIQUE bad triple, not one of each per drain."""
        try:
            from cometbft_tpu.crypto import ed25519 as _ed

            votes = []
            for kind, payload, _ in items:
                if kind == "peer" and isinstance(payload, VoteMessage):
                    votes.append(payload.vote)
            if len(votes) < 8:
                return
            vals = self.state.validators
            bv = _ed.BatchVerifier()
            keys = []
            for v in votes:
                if not (0 <= v.validator_index < vals.size()):
                    continue
                val = vals.validators[v.validator_index]
                if val.address != v.validator_address or not isinstance(
                    val.pub_key, _ed.PubKey
                ):
                    continue
                if len(v.signature) != _ed.SIGNATURE_SIZE:
                    continue
                sb = v.sign_bytes(self.state.chain_id)
                key = val.pub_key.bytes() + v.signature + sb
                if key in self._failed_triples:
                    continue
                bv.add(val.pub_key, sb, v.signature)
                keys.append(key)
            if len(bv) >= 8:
                from cometbft_tpu.sidecar import engine as _engine

                # Consensus-class engine admission: drained vote queues go
                # to the head of the shared device queue under the
                # admission deadline.
                with _engine.submission_class(_engine.CLASS_CONSENSUS):
                    _, bits = bv.verify()
                for key, valid in zip(keys, bits):
                    if not valid:
                        if len(self._failed_triples) >= self._FAILED_TRIPLES_MAX:
                            self._failed_triples.clear()
                        self._failed_triples[key] = None
        except Exception:
            pass

    def _handle_msg(self, msg, peer_id: str) -> None:
        """state.go:810-880 handleMsg."""
        if isinstance(msg, ProposalMessage):
            self._set_proposal(msg.proposal)
        elif isinstance(msg, BlockPartMessage):
            added = self._add_proposal_block_part(msg, peer_id)
            if added and self._broadcast is not None and peer_id:
                pass  # reactor handles gossip
        elif isinstance(msg, VoteMessage):
            self._try_add_vote(msg.vote, peer_id)
        elif isinstance(msg, HasVoteMessage):
            pass  # peer-state bookkeeping lives in the reactor
        else:
            raise ValueError(f"unknown consensus message {msg!r}")

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """state.go:885-940 handleTimeout."""
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < rs.step
        ):
            return
        if ti.step == STEP_NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == STEP_NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == STEP_PROPOSE:
            if self.event_bus:
                self.event_bus.publish_timeout_propose(rs.round_state_event())
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == STEP_PREVOTE_WAIT:
            if self.event_bus:
                self.event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == STEP_PRECOMMIT_WAIT:
            if self.event_bus:
                self.event_bus.publish_timeout_wait(rs.round_state_event())
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise ValueError(f"invalid timeout step {ti.step}")

    # -- state update ---------------------------------------------------------

    def update_to_state(self, state) -> None:
        """state.go:530-640 updateToState."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and rs.height != state.last_block_height:
            raise RuntimeError(
                f"updateToState() expected state height of {rs.height} but found {state.last_block_height}"
            )
        last_precommits = None
        if rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if precommits is None or not precommits.has_two_thirds_majority():
                raise RuntimeError("updateToState called with commitRound but no +2/3")
            last_precommits = precommits
        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height
        validators = state.validators
        rs.height = height
        rs.round = 0
        rs.step = STEP_NEW_HEIGHT
        if rs.commit_time.is_zero():
            rs.start_time = cmttime.now().add_nanos(
                int(self.config.timeout_commit * 1e9)
            )
        else:
            rs.start_time = rs.commit_time.add_nanos(
                int(self.config.timeout_commit * 1e9)
            )
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.last_commit = last_precommits
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = state
        self._last_progress = self.clock.now()
        with self._height_events:
            self._height_events.notify_all()

    def _reconstruct_last_commit_if_needed(self, state) -> None:
        """state.go reconstructLastCommit: after restart, rebuild LastCommit
        votes from the block store's seen commit."""
        if state.last_block_height == 0 or self.rs.last_commit is not None:
            return
        seen_commit = (
            self.block_store.load_seen_commit(state.last_block_height)
            if self.block_store
            else None
        )
        if seen_commit is None:
            return
        from cometbft_tpu.types.vote_set import VoteSet

        # Pre-verify the whole seen commit in ONE dispatch so the serial
        # add_vote loop below runs on cache hits — otherwise every signature
        # would pay a scalar verify (or a micro-batch window wait) one at a
        # time at boot. Purely an optimization: failures just miss the cache
        # and add_vote verifies as before.
        try:
            from cometbft_tpu.crypto import ed25519 as _ed

            vals = state.last_validators
            if all(isinstance(v.pub_key, _ed.PubKey) for v in vals.validators):
                bv = _ed.BatchVerifier()
                sbs = seen_commit.vote_sign_bytes_all(state.chain_id)
                for idx, cs in enumerate(seen_commit.signatures):
                    if not cs.is_absent():
                        bv.add(vals.validators[idx].pub_key, sbs[idx], cs.signature)
                if len(bv) >= 2:
                    from cometbft_tpu.sidecar import engine as _engine

                    with _engine.submission_class(_engine.CLASS_CONSENSUS):
                        bv.verify()
        except Exception:
            pass
        vote_set = VoteSet(
            state.chain_id,
            state.last_block_height,
            seen_commit.round,
            PRECOMMIT_TYPE,
            state.last_validators,
        )
        for idx, cs_sig in enumerate(seen_commit.signatures):
            if cs_sig.is_absent():
                continue
            vote = Vote(
                type=PRECOMMIT_TYPE,
                height=seen_commit.height,
                round=seen_commit.round,
                block_id=cs_sig.block_id(seen_commit.block_id),
                timestamp=cs_sig.timestamp,
                validator_address=cs_sig.validator_address,
                validator_index=idx,
                signature=cs_sig.signature,
            )
            vote_set.add_vote(vote)
        self.rs.last_commit = vote_set

    # -- scheduling -----------------------------------------------------------

    def _schedule_round0(self) -> None:
        sleep = max(
            0.0, (self.rs.start_time.unix_nanos() - cmttime.now().unix_nanos()) / 1e9
        )
        self.ticker.schedule_timeout(
            TimeoutInfo(sleep, self.rs.height, 0, STEP_NEW_HEIGHT)
        )

    def _schedule_timeout(self, duration: float, height: int, round_: int, step: int) -> None:
        self.ticker.schedule_timeout(TimeoutInfo(duration, height, round_, step))

    def _new_step(self) -> None:
        self._last_progress = self.clock.now()
        if self.event_bus:
            self.event_bus.publish_new_round_step(self.rs.round_state_event())

    # -- stall watchdog -------------------------------------------------------

    # Wall-clock poll cadence of the watchdog thread. The check itself is
    # clock-driven (_stall_check reads self.clock), so tests and the simnet
    # scenario harness invoke it directly on a virtual clock with no sleeps.
    _WATCHDOG_POLL_S = 0.05

    def _stall_watchdog_routine(self) -> None:
        while self._running:
            self.clock.sleep(self._WATCHDOG_POLL_S)
            self._stall_check()

    def _stall_check(self) -> bool:
        """One watchdog evaluation against the injected clock: if the round
        state made no progress for _stall_factor × the current round's full
        (escalated) timeout budget, assume our announcements or timers were
        lost — re-broadcast our round step + observed majorities through the
        reactor hook and re-arm the current step's timeout. Every action is
        idempotent, so a false positive costs a few duplicate messages,
        never safety. Returns True when the stall action fired."""
        factor = self._stall_factor
        if factor <= 0:
            return False
        rs = self.rs
        # Waiting for transactions is idle by design, not a stall.
        if not self.config.create_empty_blocks and rs.step == STEP_NEW_ROUND:
            self._last_progress = self.clock.now()
            return False
        budget = self.config.round_timeout_budget(rs.round) * factor
        idle = self.clock.now() - self._last_progress
        if idle < budget:
            return False
        self._last_progress = self.clock.now()  # re-arm before acting
        self.metrics.consensus_stalls_total.inc()
        self._log(
            f"stall watchdog: no progress for {idle:.1f}s at "
            f"{rs.height}/{rs.round}/{cstypes.STEP_NAMES.get(rs.step, rs.step)}"
            "; re-announcing round state"
        )
        cb = self._on_stall
        if cb is not None:
            try:
                cb()
            except Exception:
                pass
        try:
            with self._mtx:
                self._rearm_step_timeout()
        except Exception:
            pass
        return True

    def _rearm_step_timeout(self) -> None:
        """Re-schedule the timeout the CURRENT step depends on (the ticker
        keeps a single pending timer, so a lost/clobbered tock would
        otherwise leave the step waiting forever). Steps that legitimately
        wait on votes/parts (Prevote, Precommit without 2/3-any, Commit)
        have no timer to re-arm."""
        rs = self.rs
        if rs.step == STEP_NEW_HEIGHT:
            self._schedule_round0()
        elif rs.step in (STEP_NEW_ROUND, STEP_PROPOSE):
            if rs.step == STEP_NEW_ROUND and not self.config.create_empty_blocks:
                return  # waiting for txs: no timer by design
            self._schedule_timeout(
                self.config.propose_timeout(rs.round), rs.height, rs.round, STEP_PROPOSE
            )
        elif rs.step == STEP_PREVOTE_WAIT:
            self._schedule_timeout(
                self.config.prevote_timeout(rs.round),
                rs.height, rs.round, STEP_PREVOTE_WAIT,
            )
        elif rs.step == STEP_PRECOMMIT and rs.triggered_timeout_precommit:
            self._schedule_timeout(
                self.config.precommit_timeout(rs.round),
                rs.height, rs.round, STEP_PRECOMMIT_WAIT,
            )

    # -- transitions ----------------------------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        """state.go:988-1046."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != STEP_NEW_HEIGHT
        ):
            return
        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)
        rs.round = round_
        rs.step = STEP_NEW_ROUND
        rs.validators = validators
        self._last_progress = self.clock.now()
        self.metrics.rounds.set(round_)
        if round_ != 0:
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)
        rs.triggered_timeout_precommit = False
        if self.event_bus:
            proposer = validators.get_proposer()
            self.event_bus.publish_new_round(
                ev.EventDataNewRound(
                    height=height,
                    round=round_,
                    step=cstypes.STEP_NAMES[STEP_NEW_ROUND],
                    proposer_address=proposer.address if proposer else b"",
                )
            )
        wait_for_txs = (
            not self.config.create_empty_blocks
            and round_ == 0
            and not self._need_proof_block(height)
        )
        if wait_for_txs:
            if self.config.create_empty_blocks_interval > 0:
                self._schedule_timeout(
                    self.config.create_empty_blocks_interval, height, round_, STEP_NEW_ROUND
                )
            self.mempool.tx_available_callback = lambda: self._queue.put(
                ("timeout", TimeoutInfo(0, height, round_, STEP_NEW_ROUND), "")
            )
        else:
            self._enter_propose(height, round_)

    def _need_proof_block(self, height: int) -> bool:
        """state.go:1049-1063: first height, or the app hash changed."""
        if height == self.state.initial_height:
            return True
        last_meta = (
            self.block_store.load_block_meta(height - 1) if self.block_store else None
        )
        if last_meta is None:
            return True
        return self.state.app_hash != last_meta.header.app_hash

    def _enter_propose(self, height: int, round_: int) -> None:
        """state.go:1071-1132."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and STEP_PROPOSE <= rs.step
        ):
            return
        try:
            self._schedule_timeout(
                self.config.propose_timeout(round_), height, round_, STEP_PROPOSE
            )
            if self.priv_validator is None or self.priv_validator_pub_key is None:
                return
            address = self.priv_validator_pub_key.address()
            if not rs.validators.has_address(address):
                return
            if rs.validators.get_proposer().address == address:
                self._decide_proposal(height, round_)
        finally:
            rs.round = round_
            rs.step = STEP_PROPOSE
            self._new_step()
            if self._is_proposal_complete():
                self._enter_prevote(height, rs.round)

    def _decide_proposal(self, height: int, round_: int) -> None:
        """state.go:1135-1190 defaultDecideProposal."""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            block = self._create_proposal_block()
            if block is None:
                return
            block_parts = block.make_part_set()
        self.wal.flush_and_sync()
        prop_block_id = BlockID(block.hash(), block_parts.header())
        proposal = Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=prop_block_id,
            timestamp=cmttime.now(),
        )
        try:
            proposal = self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:
            if not self.replay_mode:
                # Same contract as _sign_add_vote: log and skip, never
                # propagate a privval refusal into the step machinery.
                self._log(
                    f"failed signing proposal h={height} r={round_}: {e}"
                )
            return
        self._send_internal(ProposalMessage(proposal))
        for i in range(block_parts.total):
            part = block_parts.get_part(i)
            self._send_internal(BlockPartMessage(rs.height, rs.round, part))

    def _create_proposal_block(self):
        """state.go:1196-1233 createProposalBlock."""
        rs = self.rs
        if rs.height == self.state.initial_height:
            commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            commit = rs.last_commit.make_commit()
            if os.environ.get("CMTPU_AGG_COMMITS", "") == "1":
                # Block-embedded form only: the seen commit saved in
                # _finalize keeps per-vote signatures so restart
                # reconstruction can rebuild the VoteSet (see
                # types.block.aggregate_commit).
                from cometbft_tpu.types.block import aggregate_commit

                commit = aggregate_commit(commit, self.state.last_validators)
        else:
            return None
        proposer_addr = self.priv_validator_pub_key.address()
        return self.block_exec.create_proposal_block(
            rs.height, self.state, commit, proposer_addr
        )

    def _is_proposal_complete(self) -> bool:
        """state.go:1193-1208."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        """state.go:1250-1275."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and STEP_PREVOTE <= rs.step
        ):
            return
        self._do_prevote(height, round_)
        rs.round = round_
        rs.step = STEP_PREVOTE
        self._new_step()

    def _do_prevote(self, height: int, round_: int) -> None:
        """state.go:1277-1335 defaultDoPrevote."""
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(
                PREVOTE_TYPE, rs.locked_block.hash(), rs.locked_block_parts.header()
            )
            return
        if rs.proposal_block is None:
            self._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except Exception:
            self._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
            return
        if not self.block_exec.process_proposal(rs.proposal_block, self.state):
            self._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
            return
        self._sign_add_vote(
            PREVOTE_TYPE, rs.proposal_block.hash(), rs.proposal_block_parts.header()
        )

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and STEP_PREVOTE_WAIT <= rs.step
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise RuntimeError(
                f"entering prevote wait step ({height}/{round_}) without +2/3"
            )
        rs.round = round_
        rs.step = STEP_PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(
            self.config.prevote_timeout(round_), height, round_, STEP_PREVOTE_WAIT
        )

    def _enter_precommit(self, height: int, round_: int) -> None:
        """state.go:1373-1471."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and STEP_PRECOMMIT <= rs.step
        ):
            return
        try:
            prevotes = rs.votes.prevotes(round_)
            block_id, ok = (
                prevotes.two_thirds_majority() if prevotes else (None, False)
            )
            if not ok:
                self._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
                return
            if self.event_bus:
                self.event_bus.publish_polka(rs.round_state_event())
            pol_round, _ = rs.votes.pol_info()
            if pol_round < round_:
                raise RuntimeError(
                    f"this POLRound should be {round_} but got {pol_round}"
                )
            if len(block_id.hash) == 0:
                # +2/3 prevoted nil: unlock and precommit nil.
                if rs.locked_block is not None:
                    rs.locked_round = -1
                    rs.locked_block = None
                    rs.locked_block_parts = None
                    if self.event_bus:
                        self.event_bus.publish_unlock(rs.round_state_event())
                self._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
                return
            if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
                rs.locked_round = round_
                if self.event_bus:
                    self.event_bus.publish_relock(rs.round_state_event())
                self._sign_add_vote(
                    PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header
                )
                return
            if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                self.block_exec.validate_block(self.state, rs.proposal_block)
                rs.locked_round = round_
                rs.locked_block = rs.proposal_block
                rs.locked_block_parts = rs.proposal_block_parts
                if self.event_bus:
                    self.event_bus.publish_lock(rs.round_state_event())
                self._sign_add_vote(
                    PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header
                )
                return
            # Polka for a block we don't have: unlock, fetch, precommit nil.
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.part_set_header
            ):
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet(block_id.part_set_header)
            self._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
        finally:
            rs.round = round_
            rs.step = STEP_PRECOMMIT
            self._new_step()

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise RuntimeError(
                f"entering precommit wait step ({height}/{round_}) without +2/3"
            )
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(
            self.config.precommit_timeout(round_), height, round_, STEP_PRECOMMIT_WAIT
        )

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """state.go:1527-1588."""
        rs = self.rs
        if rs.height != height or STEP_COMMIT <= rs.step:
            return
        try:
            precommits = rs.votes.precommits(commit_round)
            block_id, ok = precommits.two_thirds_majority()
            if not ok:
                raise RuntimeError("enterCommit expects +2/3 precommits")
            if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
                rs.proposal_block = rs.locked_block
                rs.proposal_block_parts = rs.locked_block_parts
            if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
                if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                    block_id.part_set_header
                ):
                    rs.proposal_block = None
                    rs.proposal_block_parts = PartSet(block_id.part_set_header)
                    if self.event_bus:
                        self.event_bus.publish_valid_block(rs.round_state_event())
        finally:
            rs.step = STEP_COMMIT
            rs.commit_round = commit_round
            rs.commit_time = cmttime.now()
            self._new_step()
            self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        """state.go:1590-1616."""
        rs = self.rs
        if rs.height != height:
            raise RuntimeError(f"tryFinalizeCommit() height mismatch")
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok or block_id is None or len(block_id.hash) == 0:
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """state.go:1618-1720."""
        rs = self.rs
        if rs.height != height or rs.step != STEP_COMMIT:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if not ok:
            raise RuntimeError("cannot finalize commit; no 2/3 majority")
        if not block_parts.has_header(block_id.part_set_header):
            raise RuntimeError("expected ProposalBlockParts header to be commit header")
        if block.hash() != block_id.hash:
            raise RuntimeError("cannot finalize commit; block hash mismatch")
        self.block_exec.validate_block(self.state, block)
        fail.fail()  # kill-point: before SaveBlock (state.go:1656)
        # Save to block store before the WAL end-height marker.
        if self.block_store.height() < block.header.height:
            seen_commit = precommits.make_commit()
            self.block_store.save_block(block, block_parts, seen_commit)
        fail.fail()  # kill-point: block saved, no #ENDHEIGHT yet (state.go:1670)
        self.wal.write_sync(EndHeightMessage(height))
        fail.fail()  # kill-point: #ENDHEIGHT durable, state not applied (state.go:1693)
        state_copy = self.state.copy()
        state_copy, retain_height = self.block_exec.apply_block(
            state_copy, BlockID(block.hash(), block_parts.header()), block
        )
        fail.fail()  # kill-point: after ApplyBlock (state.go:1720)
        if retain_height > 0:
            try:
                self.block_store.prune_blocks(retain_height)
                # the reference prunes state records alongside blocks
                # (state/execution.go pruneBlocks -> Store().PruneStates)
                self.block_exec.state_store.prune_states(retain_height)
            except Exception:
                pass
        self._record_commit_metrics(block)
        self.update_to_state(state_copy)
        if self.priv_validator is not None:
            self.priv_validator_pub_key = self.priv_validator.get_pub_key()
        self._schedule_round0()

    def _record_commit_metrics(self, block) -> None:
        """consensus/state.go recordMetrics (:1726-1790 subset)."""
        from cometbft_tpu.consensus.metrics import _Nop

        m = self.metrics
        if isinstance(m.height, _Nop):
            return  # metrics disabled: skip the block re-encode + DB read
        h = block.header.height
        m.height.set(h)
        m.latest_block_height.set(h)
        m.validators.set(self.rs.validators.size())
        m.validators_power.set(self.rs.validators.total_voting_power())
        ntxs = len(block.data.txs)
        m.num_txs.set(ntxs)
        if ntxs:
            m.total_txs.inc(ntxs)
        m.block_size_bytes.set(len(block.encode()))
        prev = self.block_store.load_block_meta(h - 1)
        if prev is not None and prev.header.time is not None:
            dt = (block.header.time.seconds - prev.header.time.seconds) + (
                block.header.time.nanos - prev.header.time.nanos
            ) / 1e9
            if dt >= 0:
                m.block_interval_seconds.observe(dt)

    # -- proposals ------------------------------------------------------------

    def _set_proposal(self, proposal: Proposal) -> None:
        """state.go defaultSetProposal (:1865-1905)."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round
        ):
            raise VoteError("error invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            raise VoteError("error invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(proposal.block_id.part_set_header)

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> bool:
        """state.go addProposalBlockPart (:1905-1990)."""
        rs = self.rs
        if rs.height != msg.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        try:
            added = rs.proposal_block_parts.add_part(msg.part)
        except ValueError:
            if msg.round != rs.round:
                # A part from an earlier round's proposal at the same height
                # fails the proof check against the current round's part-set
                # header — benign late gossip, not a bad peer; don't take
                # down message processing for it.
                if self.logger:
                    self.logger.debug(
                        f"block part from another round does not match "
                        f"current proposal (h={msg.height} r={msg.round} "
                        f"cs_round={rs.round})"
                    )
                return False
            raise  # same-round invalid proof: a genuinely faulty peer
        if added and rs.proposal_block_parts.is_complete():
            from cometbft_tpu.types.block import Block

            rs.proposal_block = Block.decode(rs.proposal_block_parts.get_reader())
            if self.event_bus:
                self.event_bus.publish_complete_proposal(
                    ev.EventDataCompleteProposal(
                        height=rs.height,
                        round=rs.round,
                        step=cstypes.STEP_NAMES[rs.step],
                        block_id=BlockID(
                            rs.proposal_block.hash(), rs.proposal_block_parts.header()
                        ),
                    )
                )
            prevotes = rs.votes.prevotes(rs.round)
            if prevotes is not None:
                block_id, has_maj = prevotes.two_thirds_majority()
                if (
                    has_maj
                    and block_id is not None
                    and len(block_id.hash) > 0
                    and rs.valid_round < rs.round
                ):
                    if rs.proposal_block.hash() == block_id.hash:
                        rs.valid_round = rs.round
                        rs.valid_block = rs.proposal_block
                        rs.valid_block_parts = rs.proposal_block_parts
            if rs.step <= STEP_PROPOSE and self._is_proposal_complete():
                self._enter_prevote(msg.height, rs.round)
            elif rs.step == STEP_COMMIT:
                self._try_finalize_commit(msg.height)
        return added

    # -- votes ----------------------------------------------------------------

    def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """state.go:1974-2020 tryAddVote."""
        from cometbft_tpu.consensus.cstypes import GotVoteFromUnwantedRoundError

        try:
            return self._add_vote(vote, peer_id)
        except GotVoteFromUnwantedRoundError:
            return False
        except ErrVoteConflictingVotes as e:
            if (
                self.priv_validator_pub_key is not None
                and vote.validator_address == self.priv_validator_pub_key.address()
            ):
                # Found conflicting vote from ourselves — bad, don't report.
                return False
            if self.evpool is not None:
                self.evpool.report_conflicting_votes(e.vote_a, e.vote_b)
            return False
        except VoteError:
            return False

    def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        """state.go:2022-2160 addVote."""
        rs = self.rs
        # Precommit for the previous height (LastCommit catchup).
        if vote.height + 1 == rs.height and vote.type == PRECOMMIT_TYPE:
            if rs.step != STEP_NEW_HEIGHT:
                return False
            if rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if not added:
                return False
            if self.event_bus:
                self.event_bus.publish_vote(ev.EventDataVote(vote))
            if self.config.skip_timeout_commit and rs.last_commit.has_all():
                self._enter_new_round(rs.height, 0)
            return added
        if vote.height != rs.height:
            return False
        height = rs.height
        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        if self.event_bus:
            self.event_bus.publish_vote(ev.EventDataVote(vote))
        if vote.type == PREVOTE_TYPE:
            prevotes = rs.votes.prevotes(vote.round)
            # Unlock on a polka for a later round than our lock.
            block_id, ok = prevotes.two_thirds_majority()
            if (
                rs.locked_block is not None
                and rs.locked_round < vote.round <= rs.round
                and ok
                and rs.locked_block.hash() != block_id.hash
            ):
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
            # Valid-block update.
            if (
                ok
                and block_id is not None
                and len(block_id.hash) > 0
                and rs.valid_round < vote.round
                and vote.round == rs.round
            ):
                if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    rs.proposal_block = None
                    if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                        block_id.part_set_header
                    ):
                        rs.proposal_block_parts = PartSet(block_id.part_set_header)
            if rs.round < vote.round and prevotes.has_two_thirds_any():
                self._enter_new_round(height, vote.round)
            elif rs.round == vote.round and STEP_PREVOTE <= rs.step:
                block_id2, ok2 = prevotes.two_thirds_majority()
                if ok2 and (
                    self._is_proposal_complete()
                    or (block_id2 is not None and len(block_id2.hash) == 0)
                ):
                    self._enter_precommit(height, vote.round)
                elif prevotes.has_two_thirds_any():
                    self._enter_prevote_wait(height, vote.round)
            elif (
                rs.proposal is not None
                and 0 <= rs.proposal.pol_round == vote.round
            ):
                if self._is_proposal_complete():
                    self._enter_prevote(height, rs.round)
        elif vote.type == PRECOMMIT_TYPE:
            precommits = rs.votes.precommits(vote.round)
            block_id, ok = precommits.two_thirds_majority()
            if ok:
                self._enter_new_round(height, vote.round)
                self._enter_precommit(height, vote.round)
                if block_id is not None and len(block_id.hash) > 0:
                    self._enter_commit(height, vote.round)
                    if self.config.skip_timeout_commit and precommits.has_all():
                        self._enter_new_round(rs.height, 0)
                else:
                    self._enter_precommit_wait(height, vote.round)
            elif rs.round <= vote.round and precommits.has_two_thirds_any():
                self._enter_new_round(height, vote.round)
                self._enter_precommit_wait(height, vote.round)
        else:
            raise ValueError(f"unexpected vote type {vote.type}")
        return added

    def _sign_add_vote(self, msg_type: int, hash_: bytes, header: PartSetHeader):
        """state.go signAddVote."""
        rs = self.rs
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            return None
        address = self.priv_validator_pub_key.address()
        if not rs.validators.has_address(address):
            return None
        idx, _ = rs.validators.get_by_address(address)
        vote = Vote(
            type=msg_type,
            height=rs.height,
            round=rs.round,
            block_id=BlockID(hash_, header),
            timestamp=self._vote_time(),
            validator_address=address,
            validator_index=idx,
        )
        try:
            vote = self.priv_validator.sign_vote(self.state.chain_id, vote)
        except Exception as e:
            if not self.replay_mode:
                # state.go:2270 "failed signing vote": a refusing privval
                # (double-sign guard, remote signer down) must never abort a
                # step transition — _enter_prevote/_enter_precommit set
                # rs.step AFTER the vote goes out, so a raise here would
                # re-enter the same step forever and wedge the round. The
                # node simply doesn't vote this step.
                self._log(
                    f"failed signing vote h={rs.height} r={rs.round} "
                    f"type={msg_type}: {e}"
                )
            return None
        # An in-process signer's signature is valid by construction (it just
        # computed it over exactly these sign bytes) — prove the triple into
        # the verified cache so our own admission is a dict hit instead of a
        # crypto call or a micro-batch window wait. FilePV and MockPV both
        # sign locally with a key this process holds; remote/untrusted
        # signers keep the full verify — a byzantine privval must not be
        # able to plant unverified triples.
        try:
            from cometbft_tpu.crypto import ed25519 as _ed
            from cometbft_tpu.privval.file import FilePV as _FilePV
            from cometbft_tpu.types.priv_validator import MockPV as _MockPV

            pk = self.priv_validator_pub_key
            if isinstance(self.priv_validator, (_FilePV, _MockPV)) and isinstance(
                pk, _ed.PubKey
            ):
                _ed.mark_self_signed(
                    pk.bytes(), vote.sign_bytes(self.state.chain_id), vote.signature
                )
        except Exception:
            pass
        self._send_internal(VoteMessage(vote))
        return vote

    def _vote_time(self):
        """state.go:2242 voteTime: now, floored strictly after the locked (or
        proposal) block's own time per the BFT-time spec — NOT last_block_time:
        flooring on the previous block would let an ahead-of-clock proposer
        push MedianTime(commit) <= block time and stall next-height proposals."""
        now = cmttime.now()
        min_time = now
        if self.rs.locked_block is not None:
            min_time = self.rs.locked_block.header.time.add_nanos(1_000_000)
        elif self.rs.proposal_block is not None:
            min_time = self.rs.proposal_block.header.time.add_nanos(1_000_000)
        if now.unix_nanos() > min_time.unix_nanos():
            return now
        return min_time

    # -- introspection --------------------------------------------------------

    def get_round_state(self) -> RoundState:
        """Shallow snapshot under the mutex — readers (RPC) must not see the
        receive routine mutating fields mid-transition (state.go GetRoundState
        returns a copy)."""
        import copy as _copy

        with self._mtx:
            return _copy.copy(self.rs)

    def wait_for_height(self, height: int, timeout: float = 30.0) -> bool:
        """Test helper: block until consensus reaches `height`."""
        import time as _t

        deadline = _t.monotonic() + timeout
        with self._height_events:
            while self.rs.height < height:
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    return False
                self._height_events.wait(remaining)
        return True

"""Consensus round state types (reference: consensus/types/round_state.go +
height_vote_set.go).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dfield

from cometbft_tpu.types.block import PRECOMMIT_TYPE, PREVOTE_TYPE, Block, BlockID, Commit
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.part_set import PartSet
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote_set import VoteSet

# RoundStepType (consensus/types/round_state.go:12-40).
STEP_NEW_HEIGHT = 1
STEP_NEW_ROUND = 2
STEP_PROPOSE = 3
STEP_PREVOTE = 4
STEP_PREVOTE_WAIT = 5
STEP_PRECOMMIT = 6
STEP_PRECOMMIT_WAIT = 7
STEP_COMMIT = 8

STEP_NAMES = {
    STEP_NEW_HEIGHT: "RoundStepNewHeight",
    STEP_NEW_ROUND: "RoundStepNewRound",
    STEP_PROPOSE: "RoundStepPropose",
    STEP_PREVOTE: "RoundStepPrevote",
    STEP_PREVOTE_WAIT: "RoundStepPrevoteWait",
    STEP_PRECOMMIT: "RoundStepPrecommit",
    STEP_PRECOMMIT_WAIT: "RoundStepPrecommitWait",
    STEP_COMMIT: "RoundStepCommit",
}


class HeightVoteSet:
    """consensus/types/height_vote_set.go: prevotes + precommits for every
    round of one height; peers may each point one catchup round."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self._mtx = threading.RLock()
        self.round = 0
        self._round_vote_sets: dict[int, dict[int, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            raise ValueError("addRound() for an existing round")
        self._round_vote_sets[round_] = {
            PREVOTE_TYPE: VoteSet(
                self.chain_id, self.height, round_, PREVOTE_TYPE, self.val_set
            ),
            PRECOMMIT_TYPE: VoteSet(
                self.chain_id, self.height, round_, PRECOMMIT_TYPE, self.val_set
            ),
        }

    def set_round(self, round_: int) -> None:
        """Create vote sets up to and including round_ (the caller passes
        current+1 — height_vote_set.go SetRound)."""
        with self._mtx:
            new_round = self.round - 1 if self.round > 0 else 0
            if self.round != 0 and round_ < new_round:
                raise ValueError("SetRound() must increment hvs.round")
            for r in range(new_round, round_ + 1):
                if r not in self._round_vote_sets:
                    self._add_round(r)
            self.round = round_

    def add_vote(self, vote, peer_id: str = "") -> bool:
        """height_vote_set.go AddVote: unknown future rounds from peers are
        limited to one catchup round per peer."""
        with self._mtx:
            if not _is_vote_type_valid(vote.type):
                return False
            vote_set = self._get_vote_set(vote.round, vote.type)
            if vote_set is None:
                rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
                if len(rounds) < 2:
                    self._add_round(vote.round)
                    vote_set = self._get_vote_set(vote.round, vote.type)
                    rounds.append(vote.round)
                else:
                    raise GotVoteFromUnwantedRoundError(vote.round)
            return vote_set.add_vote(vote)

    def prevotes(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_vote_set(round_, PREVOTE_TYPE)

    def precommits(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_vote_set(round_, PRECOMMIT_TYPE)

    def pol_info(self) -> tuple[int, BlockID | None]:
        """Last round with a prevote 2/3 majority (height_vote_set.go POLInfo)."""
        with self._mtx:
            for r in range(self.round, -1, -1):
                rvs = self._get_vote_set(r, PREVOTE_TYPE)
                if rvs is not None:
                    block_id, ok = rvs.two_thirds_majority()
                    if ok:
                        return r, block_id
            return -1, None

    def _get_vote_set(self, round_: int, vote_type: int) -> VoteSet | None:
        rvs = self._round_vote_sets.get(round_)
        if rvs is None:
            return None
        return rvs.get(vote_type)

    def set_peer_maj23(self, round_: int, vote_type: int, peer_id: str, block_id) -> None:
        with self._mtx:
            if not _is_vote_type_valid(vote_type):
                raise ValueError(f"SetPeerMaj23: invalid vote type {vote_type}")
            vote_set = self._get_vote_set(round_, vote_type)
            if vote_set is None:
                return
            vote_set.set_peer_maj23(peer_id, block_id)


class GotVoteFromUnwantedRoundError(Exception):
    def __init__(self, round_: int):
        super().__init__(
            f"peer has sent a vote that does not match our round for more than one round: {round_}"
        )


def _is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


@dataclass
class RoundState:
    """consensus/types/round_state.go:65-120: the full internal consensus
    state, exposed via RPC dump_consensus_state."""

    height: int = 0
    round: int = 0
    step: int = STEP_NEW_HEIGHT
    start_time: Time = dfield(default_factory=Time)
    commit_time: Time = dfield(default_factory=Time)
    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None
    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None
    votes: HeightVoteSet | None = None
    commit_round: int = -1
    last_commit: VoteSet | None = None
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def round_state_event(self):
        from cometbft_tpu.types import events as ev

        return ev.EventDataRoundState(
            height=self.height, round=self.round, step=STEP_NAMES[self.step]
        )

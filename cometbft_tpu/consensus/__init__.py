"""Consensus: the Tendermint BFT state machine (reference: consensus/, 7,275 LoC)."""

"""Timeout ticker (reference: consensus/ticker.go).

Schedules one pending round-step timeout at a time; scheduling a new one
cancels the old (ticker.go:40-110). Fired timeouts land on `tock_queue`,
drained by the consensus receive routine.
"""

from __future__ import annotations

import queue
import threading

from cometbft_tpu.consensus.messages import TimeoutInfo
from cometbft_tpu.simnet.clock import Clock, MonotonicClock


class TimeoutTicker:
    def __init__(self, clock: Clock | None = None):
        self.tock_queue: queue.Queue[TimeoutInfo] = queue.Queue()
        self.clock = clock or MonotonicClock()
        self._timer = None  # TimerHandle of the single pending timeout
        self._mtx = threading.Lock()
        self._running = False

    def start(self) -> None:
        self._running = True

    def stop(self) -> None:
        with self._mtx:
            self._running = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        """ticker.go ScheduleTimeout: replaces any pending timeout."""
        with self._mtx:
            if not self._running:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = self.clock.timer(ti.duration, self._fire, ti)

    def _fire(self, ti: TimeoutInfo) -> None:
        self.tock_queue.put(ti)


class MockTickerFactory:
    """consensus/common_test.go newMockTickerFunc: fires immediately on
    schedule (only for OnTimeoutPropose-style steps when fire_on_propose),
    keeping in-process multi-node tests fast and deterministic."""

    def __init__(self, fire_immediately: bool = True):
        self.fire_immediately = fire_immediately

    def __call__(self) -> "MockTicker":
        return MockTicker(self.fire_immediately)


class MockTicker(TimeoutTicker):
    def __init__(self, fire_immediately: bool):
        super().__init__()
        self.fire_immediately = fire_immediately

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        if not self._running:
            return
        if self.fire_immediately:
            self.tock_queue.put(ti)
        else:
            super().schedule_timeout(ti)

"""Consensus write-ahead log (reference: consensus/wal.go).

Every message is written BEFORE processing so a crashed node can replay to
exactly where it left off (wal.go:19-30). Framing matches the reference's
encoder (wal.go:300-340): crc32(IEEE) of payload [4B BE] || length [4B BE]
|| payload, where payload is an encoded TimedWALMessage. An EndHeightMessage
marks each committed height (the replay anchor, consensus/state.go:1686).

Message payloads use a compact tagged encoding (type byte + proto bytes) —
the WAL is node-local, not a wire protocol.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib

from cometbft_tpu.types import cmttime
from cometbft_tpu.types.cmttime import Time

MAX_MSG_SIZE_BYTES = 1048576  # 1MB (wal.go maxMsgSizeBytes)

# WAL message type tags.
MSG_END_HEIGHT = 0x01
MSG_PROPOSAL = 0x02
MSG_BLOCK_PART = 0x03
MSG_VOTE = 0x04
MSG_TIMEOUT = 0x05
MSG_EVENT_ROUND_STATE = 0x06
MSG_HAS_VOTE = 0x07


class EndHeightMessage:
    """wal.go EndHeightMessage: height H is irrevocably committed."""

    def __init__(self, height: int):
        self.height = height

    def __eq__(self, other):
        return isinstance(other, EndHeightMessage) and other.height == self.height


class TimedWALMessage:
    def __init__(self, time: Time, msg):
        self.time = time
        self.msg = msg


class WALWriteError(Exception):
    pass


class DataCorruptionError(Exception):
    """wal.go DataCorruptionError: checksum/length failures during decode."""


class WAL:
    """consensus/wal.go baseWAL over a rotating autofile Group: CRC-framed
    frames appended to the head file, rotated at head_size_limit so the WAL
    no longer grows unboundedly in one file (libs/autofile/group.go)."""

    def __init__(self, path: str, codec=None, head_size_limit: int = 10 * 1024 * 1024):
        from cometbft_tpu.libs.autofile import Group

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._codec = codec or _default_codec
        self._decode = _default_decode
        self.group = Group(path, head_size_limit=head_size_limit)
        self._mtx = threading.Lock()
        self._running = True
        # Group commit (CMTPU_WAL_GROUP_MS > 0): concurrent write_sync
        # callers share one fsync — a leader holds a short window open,
        # then syncs once for every frame appended so far. Durability is
        # NEVER weakened: a caller returns only after an fsync that covers
        # its own frame (whole-message durability; the fsync-before-
        # processing invariant of state.go:774 holds unchanged). Default 0
        # keeps the exact serial write+fsync path.
        self._group_ms = float(os.environ.get("CMTPU_WAL_GROUP_MS", "") or 0.0)
        self._sync_cond = threading.Condition()
        self._seq = 0  # frames appended through write_sync
        self._synced = 0  # highest seq covered by a completed fsync
        self._sync_leader = False
        self.group_commits = 0  # fsyncs that covered more than one caller
        self.group_syncs = 0  # total group-path fsyncs

    def start(self) -> None:
        """OnStart writes EndHeightMessage(0) into an empty WAL (wal.go:110)."""
        if os.path.getsize(self.path) == 0 and not self.group.chunk_indices():
            self.write_sync(EndHeightMessage(0))

    def write(self, msg) -> None:
        """Buffered write (wal.go Write; group-buffered in the reference)."""
        if not self._running:
            return
        data = _encode_timed(self._codec, TimedWALMessage(cmttime.now(), msg))
        with self._mtx:
            self.group.write(data)

    def write_sync(self, msg) -> None:
        """Write + fsync — used for own messages so the node never signs
        without the intent being durable (wal.go WriteSync,
        consensus/state.go:774). Rotation is checked AFTER the frame lands
        so a record never splits across chunk files."""
        if not self._running:
            return
        data = _encode_timed(self._codec, TimedWALMessage(cmttime.now(), msg))
        if self._group_ms <= 0:
            with self._mtx:
                self.group.write(data)
                self.group.flush_and_sync()
            self.group.maybe_rotate()
            return
        with self._mtx:
            self.group.write(data)
            self._seq += 1
            my_seq = self._seq
        while True:
            with self._sync_cond:
                if self._synced >= my_seq:
                    return  # a leader's fsync already covered our frame
                if not self._sync_leader:
                    self._sync_leader = True
                    break
                self._sync_cond.wait(0.05)
        # Leader: hold the window open so concurrent writers can append,
        # then fsync once for everyone appended so far. On failure the
        # leadership is released (a follower retakes it and retries) and
        # the error propagates to our caller like the serial path would.
        try:
            time.sleep(self._group_ms / 1000.0)
            with self._mtx:
                target = self._seq
                if self._running:
                    self.group.flush_and_sync()
            self.group.maybe_rotate()
            with self._sync_cond:
                if target - self._synced > 1:
                    self.group_commits += 1
                self.group_syncs += 1
                self._synced = max(self._synced, target)
        finally:
            with self._sync_cond:
                self._sync_leader = False
                self._sync_cond.notify_all()

    def flush_and_sync(self) -> None:
        with self._mtx:
            self.group.flush_and_sync()

    def stop(self) -> None:
        with self._mtx:
            if self._running:
                self._running = False
                self.group.close()

    def reopen(self) -> None:
        """Re-open the append handle after an external rewrite (the repair
        path: state.go loadWalFile after repairWalFile)."""
        with self._mtx:
            self.group.reopen()
            self._running = True

    # -- reading / replay -----------------------------------------------------

    def has_end_height(self, height: int) -> bool:
        """Sanity probe: does ANY intact frame carry EndHeightMessage(height)?
        Tolerant of corruption (wal.go SearchForEndHeight with
        IgnoreDataCorruptionErrors) — skippable bad frames are skipped, an
        unskippable tail ends the scan."""
        for ok, tm in self._scan_frames():
            if ok and isinstance(tm.msg, EndHeightMessage) and tm.msg.height == height:
                return True
        return False

    def search_for_end_height(self, height: int):
        """wal.go SearchForEndHeight semantics for catchup replay: the list of
        messages AFTER the LAST EndHeightMessage(height), or None if the
        marker is absent."""
        msgs, _ = self.catchup_scan(height, None)
        return msgs

    def catchup_scan(self, end_height: int, cs_height: int | None):
        """One pass serving both catchup questions (replay.go:93-120):
        returns (messages after the LAST EndHeightMessage(end_height) or None
        if that marker is absent, whether EndHeightMessage(cs_height) was
        seen). The marker search tolerates corruption in earlier heights; a
        corrupt frame AFTER the marker (the height being replayed) raises
        DataCorruptionError so the caller can repair the WAL."""
        after: list | None = None
        saw_cs = False
        for ok, tm in self._scan_frames():
            if ok and isinstance(tm.msg, EndHeightMessage):
                if cs_height is not None and tm.msg.height == cs_height:
                    saw_cs = True
                if tm.msg.height == end_height:
                    after = []  # restart collection at the latest marker
                    continue
            if after is None:
                continue  # still searching; corruption here is ignorable
            if not ok:
                raise DataCorruptionError(tm)
            after.append(tm)
        return after, saw_cs

    def _scan_frames(self):
        """Yield (True, TimedWALMessage) per intact frame and (False, reason)
        per skippable corrupt frame (bad CRC with a plausible length — the
        reader can still advance); stop silently at a truncated/garbage tail
        (no resync possible without the reference's per-file groups)."""
        with self.group.reader() as f:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    return
                crc, length = struct.unpack(">II", hdr)
                if length > MAX_MSG_SIZE_BYTES:
                    return  # garbage length: cannot resync
                payload = f.read(length)
                if len(payload) < length:
                    return  # truncated tail
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    yield False, "checksums do not match"
                    continue
                try:
                    yield True, _decode_timed(self._decode, payload)
                except Exception as e:
                    yield False, f"undecodable payload: {e}"

    def iter_messages(self):
        """Decode every frame; raises DataCorruptionError on a bad frame."""
        with self.group.reader() as f:
            while True:
                hdr = f.read(8)
                if len(hdr) == 0:
                    return
                if len(hdr) < 8:
                    raise DataCorruptionError("truncated frame header")
                crc, length = struct.unpack(">II", hdr)
                if length > MAX_MSG_SIZE_BYTES:
                    raise DataCorruptionError(
                        f"length {length} exceeds maximum possible value"
                    )
                payload = f.read(length)
                if len(payload) < length:
                    raise DataCorruptionError("truncated frame payload")
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    raise DataCorruptionError("checksums do not match")
                yield _decode_timed(self._decode, payload)


def _repair_scan(src_path: str):
    """Yield (ok, frame_bytes, is_end_height) per frame; ok=False for
    skippable bad frames (bad CRC or undecodable payload with a plausible
    length). Stops at an unskippable tail (garbage length / truncation)."""
    with open(src_path, "rb") as src:
        while True:
            hdr = src.read(8)
            if len(hdr) < 8:
                return
            crc, length = struct.unpack(">II", hdr)
            if length > MAX_MSG_SIZE_BYTES:
                return
            payload = src.read(length)
            if len(payload) < length:
                return
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                yield False, hdr + payload, False
                continue
            try:
                tm = _decode_timed(_default_decode, payload)
            except Exception:
                # CRC-valid but undecodable (e.g. foreign tag byte): keeping
                # it would make every repair attempt a no-op — drop it.
                yield False, hdr + payload, False
                continue
            yield True, hdr + payload, isinstance(tm.msg, EndHeightMessage)


def repair_wal(src_path: str, dst_path: str) -> int:
    """Rewrite the WAL keeping a gap-free replayable suffix
    (consensus/state.go:320-360 corrupted-WAL repair): skippable bad frames
    BEFORE the last EndHeightMessage are dropped (old heights — replay skips
    them anyway), and the file is truncated at the first bad frame AFTER the
    last marker (the torn-write tail: replaying past a gap could replay
    messages out of order). Returns frames kept."""
    frames = list(_repair_scan(src_path))
    last_marker = -1
    for i, (ok, _, is_end) in enumerate(frames):
        if ok and is_end:
            last_marker = i
    kept = 0
    with open(dst_path, "wb") as dst:
        for i, (ok, raw, _) in enumerate(frames):
            if not ok:
                if i <= last_marker:
                    continue  # droppable old-height frame
                break  # first gap after the marker: stop
            dst.write(raw)
            kept += 1
    return kept


# -- codec --------------------------------------------------------------------


def _encode_timed(codec, tm: TimedWALMessage) -> bytes:
    body = struct.pack(">qi", tm.time.seconds, tm.time.nanos) + codec(tm.msg)
    if len(body) > MAX_MSG_SIZE_BYTES:
        raise WALWriteError(f"msg is too big: {len(body)} bytes")
    return struct.pack(">II", zlib.crc32(body) & 0xFFFFFFFF, len(body)) + body


def _decode_timed(decode, payload: bytes) -> TimedWALMessage:
    secs, nanos = struct.unpack(">qi", payload[:12])
    return TimedWALMessage(Time(secs, nanos), decode(payload[12:]))


def _default_codec(msg) -> bytes:
    """Tag + payload; consensus messages provide .encode()."""
    from cometbft_tpu.consensus import messages as cmsg

    if isinstance(msg, EndHeightMessage):
        from cometbft_tpu.wire import proto as wire

        return bytes([MSG_END_HEIGHT]) + wire.encode_varint_signed(msg.height)
    return cmsg.encode_wal_message(msg)


def _default_decode(data: bytes):
    from cometbft_tpu.consensus import messages as cmsg

    tag = data[0]
    if tag == MSG_END_HEIGHT:
        from cometbft_tpu.wire import proto as wire

        height, _ = wire.decode_varint_signed(data[1:], 0)
        return EndHeightMessage(height)
    return cmsg.decode_wal_message(data)

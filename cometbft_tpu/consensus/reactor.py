"""Consensus gossip reactor (reference: consensus/reactor.go, 1,796 LoC).

Four channels (reactor.go:25-28): state 0x20 (round-step + has-vote
broadcasts), data 0x21 (proposals + block parts), vote 0x22, vote-set-bits
0x23. Per-peer gossip threads push block parts and votes a peer is missing
(gossipDataRoutine :535, gossipVotesRoutine :694); PeerState tracks what
each peer has seen.
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.consensus import messages as cmsg
from cometbft_tpu.consensus.cstypes import STEP_NAMES
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.reactor import (
    CONSENSUS_DATA_CHANNEL,
    CONSENSUS_STATE_CHANNEL,
    CONSENSUS_VOTE_CHANNEL,
    CONSENSUS_VOTE_SET_BITS_CHANNEL,
    Reactor,
)
from cometbft_tpu.types.block import PRECOMMIT_TYPE


class PeerState:
    """reactor.go PeerState: the peer's view of consensus."""

    def __init__(self, peer):
        self.peer = peer
        self.height = 0
        self.round = 0
        self.step = 0
        self.last_commit_round = 0
        self._mtx = threading.Lock()
        self._sent_parts: set = set()
        self._sent_votes: set = set()

    def apply_new_round_step(self, msg: cmsg.NewRoundStepMessage) -> None:
        with self._mtx:
            if (msg.height, msg.round) != (self.height, self.round):
                self._sent_parts.clear()
                self._sent_votes.clear()
            self.height = msg.height
            self.round = msg.round
            self.step = msg.step
            self.last_commit_round = msg.last_commit_round

    def mark_part_sent(self, height: int, index: int) -> bool:
        with self._mtx:
            key = (height, index)
            if key in self._sent_parts:
                return False
            self._sent_parts.add(key)
            return True

    def unmark_part_sent(self, height: int, index: int) -> None:
        with self._mtx:
            self._sent_parts.discard((height, index))

    def mark_vote_sent(self, key) -> bool:
        with self._mtx:
            if key in self._sent_votes:
                return False
            self._sent_votes.add(key)
            return True

    def unmark_vote_sent(self, key) -> None:
        with self._mtx:
            self._sent_votes.discard(key)


class ConsensusReactor(Reactor):
    """consensus/reactor.go Reactor."""

    def __init__(self, consensus_state, gossip_sleep: float = 0.1):
        super().__init__("CONSENSUS")
        self.cs = consensus_state
        self.gossip_sleep = gossip_sleep
        self.peer_states: dict[str, PeerState] = {}
        self._running = False
        # Own messages from the state machine get gossiped.
        self.cs.set_broadcast(self._broadcast_own_message)

    def get_channels(self):
        """reactor.go:139-175 channel descriptors."""
        return [
            ChannelDescriptor(CONSENSUS_STATE_CHANNEL, priority=6, send_queue_capacity=100),
            ChannelDescriptor(CONSENSUS_DATA_CHANNEL, priority=10, send_queue_capacity=100),
            ChannelDescriptor(CONSENSUS_VOTE_CHANNEL, priority=7, send_queue_capacity=100),
            ChannelDescriptor(CONSENSUS_VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=2),
        ]

    def start(self) -> None:
        self._running = True
        threading.Thread(target=self._broadcast_round_step_routine, daemon=True).start()
        threading.Thread(target=self._query_maj23_routine, daemon=True).start()

    def stop(self) -> None:
        self._running = False

    # -- peers ----------------------------------------------------------------

    def add_peer(self, peer) -> None:
        ps = PeerState(peer)
        self.peer_states[peer.id] = ps
        peer.set("consensus_peer_state", ps)
        self._send_round_step(peer)
        threading.Thread(target=self._gossip_routine, args=(ps,), daemon=True).start()

    def remove_peer(self, peer, reason) -> None:
        self.peer_states.pop(peer.id, None)

    # -- receive --------------------------------------------------------------

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        msg = cmsg.decode_consensus_message(msg_bytes)
        ps = self.peer_states.get(peer.id)
        if chan_id == CONSENSUS_STATE_CHANNEL:
            if isinstance(msg, cmsg.NewRoundStepMessage) and ps:
                ps.apply_new_round_step(msg)
            elif isinstance(msg, cmsg.HasVoteMessage) and ps:
                ps.mark_vote_sent((msg.height, msg.round, msg.type, msg.index))
            elif isinstance(msg, cmsg.VoteSetMaj23Message):
                # reactor.go:300-340: record the claimed majority, then tell
                # the peer which of those votes we ALREADY have. A conflicting
                # claim is LOGGED, not punished: our state reads are lock-free
                # snapshots, so a round race can mislabel an honest claim and
                # killing the peer for it degrades the gossip mesh (the
                # reference stops the peer; deliberate softening).
                rs = self.cs.rs
                if msg.height != rs.height or rs.votes is None:
                    return
                try:
                    self.cs.rs.votes.set_peer_maj23(
                        msg.round, msg.type, peer.id, msg.block_id
                    )
                except Exception:
                    return
                from cometbft_tpu.types.block import PREVOTE_TYPE

                vote_set = (
                    rs.votes.prevotes(msg.round)
                    if msg.type == PREVOTE_TYPE
                    else rs.votes.precommits(msg.round)
                )
                our = vote_set.bit_array_by_block_id(msg.block_id) if vote_set else None
                peer.try_send(
                    CONSENSUS_VOTE_SET_BITS_CHANNEL,
                    cmsg.encode_consensus_message(
                        cmsg.VoteSetBitsMessage(
                            height=msg.height, round=msg.round, type=msg.type,
                            block_id=msg.block_id, votes=our,
                        )
                    ),
                )
        elif chan_id in (CONSENSUS_DATA_CHANNEL, CONSENSUS_VOTE_CHANNEL):
            self.cs.send_peer_message(msg, peer_id=peer.id)
        elif chan_id == CONSENSUS_VOTE_SET_BITS_CHANNEL:
            # The peer's answer to our VoteSetMaj23: which of those votes it
            # already has — gossip skips them (reactor.go:377-402).
            if isinstance(msg, cmsg.VoteSetBitsMessage) and ps and msg.votes:
                for i in range(msg.votes.size):
                    if msg.votes.get_index(i):
                        ps.mark_vote_sent((msg.height, msg.round, msg.type, i))

    # -- own-message gossip ---------------------------------------------------

    def _broadcast_own_message(self, msg) -> None:
        if self.switch is None:
            return
        data = cmsg.encode_consensus_message(msg)
        if isinstance(msg, (cmsg.ProposalMessage, cmsg.BlockPartMessage)):
            self.switch.broadcast(CONSENSUS_DATA_CHANNEL, data)
        elif isinstance(msg, cmsg.VoteMessage):
            self.switch.broadcast(CONSENSUS_VOTE_CHANNEL, data)

    # -- broadcast round steps (reactor.go broadcastNewRoundStepMessage) ------

    # Re-announce our round step even without a change: peers track our
    # height from these messages, and the channel is lossy (try_send
    # broadcasts, reconnections).  A STUCK node is exactly the one whose
    # step never changes — without the refresh, a peer whose PeerState for
    # us was lost to a reconnect keeps height 0 forever, its catch-up
    # gossip never engages, and a 4/0/6-vs-5/0/4 partition aftermath
    # deadlocks permanently (found by the e2e disconnect perturbation).
    ROUND_STEP_REFRESH_S = 1.0

    def _broadcast_round_step_routine(self) -> None:
        last = None
        last_sent = 0.0
        while self._running:
            rs = self.cs.rs
            cur = (rs.height, rs.round, rs.step)
            now = time.monotonic()
            if (cur != last or now - last_sent >= self.ROUND_STEP_REFRESH_S) \
                    and self.switch is not None:
                last = cur
                last_sent = now
                msg = self._round_step_msg(rs)
                self.switch.broadcast(
                    CONSENSUS_STATE_CHANNEL, cmsg.encode_consensus_message(msg)
                )
            time.sleep(0.02)

    def _round_step_msg(self, rs) -> cmsg.NewRoundStepMessage:
        return cmsg.NewRoundStepMessage(
            height=rs.height,
            round=rs.round,
            step=rs.step,
            seconds_since_start_time=0,
            last_commit_round=rs.last_commit.round if rs.last_commit else 0,
        )

    def _send_round_step(self, peer) -> None:
        # Reliable send (blocking enqueue): this is the message that seeds
        # the peer's PeerState height — dropping it on a full queue would
        # disable catch-up gossip toward us until the next refresh.
        peer.send(
            CONSENSUS_STATE_CHANNEL,
            cmsg.encode_consensus_message(self._round_step_msg(self.cs.rs)),
        )

    # -- maj23 queries (reactor.go:827 queryMaj23Routine) ----------------------

    def _query_maj23_routine(self) -> None:
        """Tell peers at our height about any 2/3 majority we observe, so a
        lagging/partitioned peer learns a quorum exists and can answer with
        the votes it still needs (liveness under partial gossip)."""
        from cometbft_tpu.types.block import PRECOMMIT_TYPE, PREVOTE_TYPE

        interval = getattr(
            self.cs.config, "peer_query_maj23_sleep_duration", 2.0
        )
        while self._running:
            time.sleep(interval)
            rs = self.cs.rs
            if rs.votes is None or self.switch is None:
                continue
            # Snapshot (height, round) ONCE: reading rs.round again per claim
            # races the state machine — a round advance mid-loop would tag a
            # majority with the wrong round, and the receiver treats
            # conflicting claims from one peer as misbehavior.
            height, round_ = rs.height, rs.round
            claims = []
            for vtype, vote_set in (
                (PREVOTE_TYPE, rs.votes.prevotes(round_)),
                (PRECOMMIT_TYPE, rs.votes.precommits(round_)),
            ):
                if vote_set is None:
                    continue
                block_id, ok = vote_set.two_thirds_majority()
                if ok:
                    claims.append((vtype, block_id))
            if not claims:
                continue
            for ps in list(self.peer_states.values()):
                if ps.height != height:
                    continue
                for vtype, block_id in claims:
                    ps.peer.try_send(
                        CONSENSUS_STATE_CHANNEL,
                        cmsg.encode_consensus_message(
                            cmsg.VoteSetMaj23Message(
                                height=height, round=round_, type=vtype,
                                block_id=block_id,
                            )
                        ),
                    )

    # -- per-peer gossip (reactor.go:535 gossipDataRoutine + :694 votes) ------

    def _gossip_routine(self, ps: PeerState) -> None:
        while self._running and ps.peer.id in self.peer_states:
            try:
                advanced = self._gossip_once(ps)
            except Exception:
                advanced = False
            if not advanced:
                time.sleep(self.gossip_sleep)

    def _gossip_once(self, ps: PeerState) -> bool:
        rs = self.cs.rs
        # Peer behind: feed them committed block parts + the seen commit's
        # precommits so they can catch up (gossipDataForCatchup).
        if 0 < ps.height < rs.height:
            block_meta = self.cs.block_store.load_block_meta(ps.height)
            if block_meta is None:
                return False
            sent = False
            for i in range(block_meta.block_id.part_set_header.total):
                if ps.mark_part_sent(ps.height, i):
                    part = self.cs.block_store.load_block_part(ps.height, i)
                    # A full send queue drops the message: un-mark so the
                    # next gossip pass retries instead of losing the part
                    # forever (liveness under backpressure).
                    if part is not None and ps.peer.try_send(
                        CONSENSUS_DATA_CHANNEL,
                        cmsg.encode_consensus_message(
                            cmsg.BlockPartMessage(ps.height, ps.round, part)
                        ),
                    ):
                        sent = True
                    else:
                        ps.unmark_part_sent(ps.height, i)
            seen_commit = self.cs.block_store.load_seen_commit(ps.height)
            if seen_commit is not None:
                from cometbft_tpu.types.vote import Vote

                for idx, cs_sig in enumerate(seen_commit.signatures):
                    if cs_sig.is_absent():
                        continue
                    key = ("commit", ps.height, idx)
                    if not ps.mark_vote_sent(key):
                        continue
                    vote = Vote(
                        type=PRECOMMIT_TYPE,
                        height=seen_commit.height,
                        round=seen_commit.round,
                        block_id=cs_sig.block_id(seen_commit.block_id),
                        timestamp=cs_sig.timestamp,
                        validator_address=cs_sig.validator_address,
                        validator_index=idx,
                        signature=cs_sig.signature,
                    )
                    if ps.peer.try_send(
                        CONSENSUS_VOTE_CHANNEL,
                        cmsg.encode_consensus_message(cmsg.VoteMessage(vote)),
                    ):
                        sent = True
                    else:
                        ps.unmark_vote_sent(key)
            return sent
        # Same height: re-send our proposal/parts and known votes they lack.
        if ps.height == rs.height:
            sent = False
            if rs.proposal is not None and ps.round == rs.round:
                key = ("proposal", rs.height, rs.round)
                if ps.mark_vote_sent(key):
                    if ps.peer.try_send(
                        CONSENSUS_DATA_CHANNEL,
                        cmsg.encode_consensus_message(cmsg.ProposalMessage(rs.proposal)),
                    ):
                        sent = True
                    else:
                        ps.unmark_vote_sent(key)
                if rs.proposal_block_parts is not None:
                    for i in range(rs.proposal_block_parts.total):
                        part = rs.proposal_block_parts.get_part(i)
                        if part is not None and ps.mark_part_sent(rs.height, i):
                            if ps.peer.try_send(
                                CONSENSUS_DATA_CHANNEL,
                                cmsg.encode_consensus_message(
                                    cmsg.BlockPartMessage(rs.height, rs.round, part)
                                ),
                            ):
                                sent = True
                            else:
                                ps.unmark_part_sent(rs.height, i)
            if rs.votes is not None:
                for vote_set in (
                    rs.votes.prevotes(rs.round),
                    rs.votes.precommits(rs.round),
                ):
                    if vote_set is None:
                        continue
                    for vote in vote_set.list_votes():
                        key = (vote.height, vote.round, vote.type, vote.validator_index)
                        if ps.mark_vote_sent(key):
                            if ps.peer.try_send(
                                CONSENSUS_VOTE_CHANNEL,
                                cmsg.encode_consensus_message(cmsg.VoteMessage(vote)),
                            ):
                                sent = True
                            else:
                                ps.unmark_vote_sent(key)
            return sent
        return False

"""Consensus gossip reactor (reference: consensus/reactor.go, 1,796 LoC).

Four channels (reactor.go:25-28): state 0x20 (round-step + has-vote
broadcasts), data 0x21 (proposals + block parts), vote 0x22, vote-set-bits
0x23. Per-peer gossip threads push block parts and votes a peer is missing
(gossipDataRoutine :535, gossipVotesRoutine :694); PeerState tracks what
each peer has seen.
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.consensus import messages as cmsg
from cometbft_tpu.consensus.cstypes import (
    STEP_NAMES,
    STEP_NEW_HEIGHT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
)
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.reactor import (
    CONSENSUS_DATA_CHANNEL,
    CONSENSUS_STATE_CHANNEL,
    CONSENSUS_VOTE_CHANNEL,
    CONSENSUS_VOTE_SET_BITS_CHANNEL,
    Reactor,
)
from cometbft_tpu.types.block import PRECOMMIT_TYPE


class PeerState:
    """reactor.go PeerState: the peer's view of consensus.

    Beyond height/round/step this tracks whether the peer has the current
    proposal and that proposal's POL round (reactor.go SetHasProposal) —
    the round-catchup gossip cascade needs both to feed a peer lagging in
    ROUNDS the votes for *its* round instead of ours."""

    def __init__(self, peer):
        self.peer = peer
        self.height = 0
        self.round = 0
        self.step = 0
        self.last_commit_round = 0
        self.proposal = False
        self.proposal_pol_round = -1
        self._mtx = threading.Lock()
        self._sent_parts: set = set()
        self._sent_votes: set = set()

    def apply_new_round_step(self, msg: cmsg.NewRoundStepMessage) -> None:
        with self._mtx:
            if (msg.height, msg.round) != (self.height, self.round):
                self._sent_parts.clear()
                self._sent_votes.clear()
                self.proposal = False
                self.proposal_pol_round = -1
            self.height = msg.height
            self.round = msg.round
            self.step = msg.step
            self.last_commit_round = msg.last_commit_round

    def set_has_proposal(self, proposal) -> None:
        """reactor.go PeerState.SetHasProposal: the peer has (sent us, or
        acked) the proposal for its current height/round."""
        with self._mtx:
            if (proposal.height, proposal.round) != (self.height, self.round):
                return
            self.proposal = True
            self.proposal_pol_round = proposal.pol_round

    def apply_proposal_pol(self, msg: cmsg.ProposalPOLMessage) -> None:
        with self._mtx:
            if msg.height != self.height:
                return
            self.proposal_pol_round = msg.proposal_pol_round

    def mark_part_sent(self, height: int, round: int, index: int) -> bool:
        """The round is part of the key: each round proposes a DIFFERENT
        block, so "peer has part (h, idx)" is only meaningful per round.
        Keying on (height, index) alone let a STALE part — one relayed
        rounds late during a livelock — mark the peer as having the
        CURRENT round's part, silently suppressing part gossip for every
        later round of the height (the e2e matrix height-5/7 stall: the
        proposal and votes, whose keys carry the round, kept flowing while
        the one block part starved round after round).  Catchup parts of a
        committed block pass round=-1 (unique per height, no round
        needed)."""
        with self._mtx:
            key = (height, round, index)
            if key in self._sent_parts:
                return False
            self._sent_parts.add(key)
            return True

    def unmark_part_sent(self, height: int, round: int, index: int) -> None:
        with self._mtx:
            self._sent_parts.discard((height, round, index))

    def mark_vote_sent(self, key) -> bool:
        with self._mtx:
            if key in self._sent_votes:
                return False
            self._sent_votes.add(key)
            return True

    def unmark_vote_sent(self, key) -> None:
        with self._mtx:
            self._sent_votes.discard(key)


class ConsensusReactor(Reactor):
    """consensus/reactor.go Reactor."""

    def __init__(self, consensus_state, gossip_sleep: float = 0.1):
        super().__init__("CONSENSUS")
        self.cs = consensus_state
        self.gossip_sleep = gossip_sleep
        self.peer_states: dict[str, PeerState] = {}
        self._running = False
        # Own messages from the state machine get gossiped.
        self.cs.set_broadcast(self._broadcast_own_message)
        # Stall watchdog (state.py): when the state machine detects no
        # round-step progress, re-announce our position and re-advertise any
        # 2/3 majorities so a desynced mesh can re-engage catch-up gossip.
        if hasattr(self.cs, "set_on_stall"):
            self.cs.set_on_stall(self._on_stall)

    def get_channels(self):
        """reactor.go:139-175 channel descriptors."""
        return [
            ChannelDescriptor(CONSENSUS_STATE_CHANNEL, priority=6, send_queue_capacity=100),
            ChannelDescriptor(CONSENSUS_DATA_CHANNEL, priority=10, send_queue_capacity=100),
            ChannelDescriptor(CONSENSUS_VOTE_CHANNEL, priority=7, send_queue_capacity=100),
            ChannelDescriptor(CONSENSUS_VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=2),
        ]

    def start(self) -> None:
        self._running = True
        threading.Thread(target=self._broadcast_round_step_routine, daemon=True).start()
        threading.Thread(target=self._query_maj23_routine, daemon=True).start()

    def stop(self) -> None:
        self._running = False

    # -- peers ----------------------------------------------------------------

    def add_peer(self, peer) -> None:
        ps = PeerState(peer)
        self.peer_states[peer.id] = ps
        peer.set("consensus_peer_state", ps)
        self._send_round_step(peer)
        threading.Thread(target=self._gossip_routine, args=(ps,), daemon=True).start()

    def remove_peer(self, peer, reason) -> None:
        self.peer_states.pop(peer.id, None)

    # -- receive --------------------------------------------------------------

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        msg = cmsg.decode_consensus_message(msg_bytes)
        ps = self.peer_states.get(peer.id)
        if chan_id == CONSENSUS_STATE_CHANNEL:
            if isinstance(msg, cmsg.NewRoundStepMessage) and ps:
                ps.apply_new_round_step(msg)
            elif isinstance(msg, cmsg.HasVoteMessage) and ps:
                ps.mark_vote_sent((msg.height, msg.round, msg.type, msg.index))
            elif isinstance(msg, cmsg.VoteSetMaj23Message):
                # reactor.go:300-340: record the claimed majority, then tell
                # the peer which of those votes we ALREADY have. A conflicting
                # claim is LOGGED, not punished: our state reads are lock-free
                # snapshots, so a round race can mislabel an honest claim and
                # killing the peer for it degrades the gossip mesh (the
                # reference stops the peer; deliberate softening).
                rs = self.cs.rs
                if msg.height != rs.height or rs.votes is None:
                    return
                try:
                    self.cs.rs.votes.set_peer_maj23(
                        msg.round, msg.type, peer.id, msg.block_id
                    )
                except Exception:
                    return
                from cometbft_tpu.types.block import PREVOTE_TYPE

                vote_set = (
                    rs.votes.prevotes(msg.round)
                    if msg.type == PREVOTE_TYPE
                    else rs.votes.precommits(msg.round)
                )
                our = vote_set.bit_array_by_block_id(msg.block_id) if vote_set else None
                peer.try_send(
                    CONSENSUS_VOTE_SET_BITS_CHANNEL,
                    cmsg.encode_consensus_message(
                        cmsg.VoteSetBitsMessage(
                            height=msg.height, round=msg.round, type=msg.type,
                            block_id=msg.block_id, votes=our,
                        )
                    ),
                )
        elif chan_id in (CONSENSUS_DATA_CHANNEL, CONSENSUS_VOTE_CHANNEL):
            # Bookkeeping first (reactor.go:249-297): whatever a peer SENDS
            # us it already HAS — mark it so gossip never echoes it back,
            # and learn the peer's proposal POL round for the vote cascade.
            if ps is not None:
                if isinstance(msg, cmsg.ProposalMessage):
                    ps.set_has_proposal(msg.proposal)
                    ps.mark_vote_sent(
                        ("proposal", msg.proposal.height, msg.proposal.round)
                    )
                elif isinstance(msg, cmsg.ProposalPOLMessage):
                    ps.apply_proposal_pol(msg)
                    return  # peer-state only; not a state-machine input
                elif isinstance(msg, cmsg.BlockPartMessage):
                    ps.mark_part_sent(msg.height, msg.round, msg.part.index)
                elif isinstance(msg, cmsg.VoteMessage):
                    v = msg.vote
                    ps.mark_vote_sent(
                        (v.height, v.round, v.type, v.validator_index)
                    )
            self.cs.send_peer_message(msg, peer_id=peer.id)
        elif chan_id == CONSENSUS_VOTE_SET_BITS_CHANNEL:
            # The peer's answer to our VoteSetMaj23: which of those votes it
            # already has — gossip skips them (reactor.go:377-402).
            if isinstance(msg, cmsg.VoteSetBitsMessage) and ps and msg.votes:
                for i in range(msg.votes.size):
                    if msg.votes.get_index(i):
                        ps.mark_vote_sent((msg.height, msg.round, msg.type, i))

    # -- own-message gossip ---------------------------------------------------

    def _broadcast_own_message(self, msg) -> None:
        if self.switch is None:
            return
        data = cmsg.encode_consensus_message(msg)
        if isinstance(msg, (cmsg.ProposalMessage, cmsg.BlockPartMessage)):
            self.switch.broadcast(CONSENSUS_DATA_CHANNEL, data)
        elif isinstance(msg, cmsg.VoteMessage):
            self.switch.broadcast(CONSENSUS_VOTE_CHANNEL, data)

    # -- broadcast round steps (reactor.go broadcastNewRoundStepMessage) ------

    # Re-announce our round step even without a change: peers track our
    # height from these messages, and the channel is lossy (try_send
    # broadcasts, reconnections).  A STUCK node is exactly the one whose
    # step never changes — without the refresh, a peer whose PeerState for
    # us was lost to a reconnect keeps height 0 forever, its catch-up
    # gossip never engages, and a 4/0/6-vs-5/0/4 partition aftermath
    # deadlocks permanently (found by the e2e disconnect perturbation).
    ROUND_STEP_REFRESH_S = 1.0

    def _broadcast_round_step_routine(self) -> None:
        last = None
        last_sent = 0.0
        while self._running:
            rs = self.cs.rs
            cur = (rs.height, rs.round, rs.step)
            now = time.monotonic()
            if (cur != last or now - last_sent >= self.ROUND_STEP_REFRESH_S) \
                    and self.switch is not None:
                last = cur
                last_sent = now
                msg = self._round_step_msg(rs)
                self.switch.broadcast(
                    CONSENSUS_STATE_CHANNEL, cmsg.encode_consensus_message(msg)
                )
            time.sleep(0.02)

    def _round_step_msg(self, rs) -> cmsg.NewRoundStepMessage:
        return cmsg.NewRoundStepMessage(
            height=rs.height,
            round=rs.round,
            step=rs.step,
            seconds_since_start_time=0,
            last_commit_round=rs.last_commit.round if rs.last_commit else 0,
        )

    def _send_round_step(self, peer) -> None:
        # Reliable send (blocking enqueue): this is the message that seeds
        # the peer's PeerState height — dropping it on a full queue would
        # disable catch-up gossip toward us until the next refresh.
        peer.send(
            CONSENSUS_STATE_CHANNEL,
            cmsg.encode_consensus_message(self._round_step_msg(self.cs.rs)),
        )

    # -- maj23 queries (reactor.go:827 queryMaj23Routine) ----------------------

    def _query_maj23_routine(self) -> None:
        """Tell peers at our height about any 2/3 majority we observe, so a
        lagging/partitioned peer learns a quorum exists and can answer with
        the votes it still needs (liveness under partial gossip)."""
        interval = getattr(
            self.cs.config, "peer_query_maj23_sleep_duration", 2.0
        )
        while self._running:
            time.sleep(interval)
            self._query_maj23_once()

    def _query_maj23_once(self) -> None:
        from cometbft_tpu.types.block import PRECOMMIT_TYPE, PREVOTE_TYPE

        rs = self.cs.rs
        if rs.votes is None or self.switch is None:
            return
        # Snapshot (height, round) ONCE: reading rs.round again per claim
        # races the state machine — a round advance mid-loop would tag a
        # majority with the wrong round, and the receiver treats
        # conflicting claims from one peer as misbehavior.
        height, round_ = rs.height, rs.round
        claims = []
        for vtype, vote_set in (
            (PREVOTE_TYPE, rs.votes.prevotes(round_)),
            (PRECOMMIT_TYPE, rs.votes.precommits(round_)),
        ):
            if vote_set is None:
                continue
            block_id, ok = vote_set.two_thirds_majority()
            if ok:
                claims.append((vtype, block_id))
        if not claims:
            return
        for ps in list(self.peer_states.values()):
            if ps.height != height:
                continue
            for vtype, block_id in claims:
                ps.peer.try_send(
                    CONSENSUS_STATE_CHANNEL,
                    cmsg.encode_consensus_message(
                        cmsg.VoteSetMaj23Message(
                            height=height, round=round_, type=vtype,
                            block_id=block_id,
                        )
                    ),
                )

    # -- stall recovery (state.py watchdog callback) ---------------------------

    def _on_stall(self) -> None:
        """Stall-watchdog hook: loudly re-announce our round step (the lossy
        broadcast may have dropped it) and re-advertise observed majorities.
        Both are idempotent; the receivers dedupe via PeerState marks."""
        if self.switch is not None:
            self.switch.broadcast(
                CONSENSUS_STATE_CHANNEL,
                cmsg.encode_consensus_message(self._round_step_msg(self.cs.rs)),
            )
        self._query_maj23_once()

    # -- per-peer gossip (reactor.go:535 gossipDataRoutine + :694 votes) ------

    def _gossip_routine(self, ps: PeerState) -> None:
        while self._running and ps.peer.id in self.peer_states:
            try:
                advanced = self._gossip_once(ps)
            except Exception:
                advanced = False
            if not advanced:
                time.sleep(self.gossip_sleep)

    def _gossip_once(self, ps: PeerState) -> bool:
        rs = self.cs.rs
        # Peer behind in HEIGHTS: committed block parts + seen-commit
        # precommits from the block store (gossipDataForCatchup).
        if 0 < ps.height < rs.height:
            return self._gossip_height_catchup(ps, rs)
        # Same height: proposal/parts for the matching round, then the vote
        # pick cascade for a peer behind in ROUNDS.
        if ps.height == rs.height:
            sent = self._gossip_data(ps, rs)
            return self._gossip_votes(ps, rs) or sent
        return False

    def _gossip_height_catchup(self, ps: PeerState, rs) -> bool:
        block_meta = self.cs.block_store.load_block_meta(ps.height)
        if block_meta is None:
            # Store already pruned / not yet saved. If the peer is exactly
            # one height behind, our live last_commit still holds the
            # precommits it needs to finish (gossipVotesRoutine's
            # rs.Height == prs.Height+1 pick).
            if rs.height == ps.height + 1 and rs.last_commit is not None:
                return self._pick_send_vote(ps, rs.last_commit, catchup=True)
            return False
        sent = False
        for i in range(block_meta.block_id.part_set_header.total):
            if ps.mark_part_sent(ps.height, -1, i):
                part = self.cs.block_store.load_block_part(ps.height, i)
                # A full send queue drops the message: un-mark so the
                # next gossip pass retries instead of losing the part
                # forever (liveness under backpressure).
                if part is not None and ps.peer.try_send(
                    CONSENSUS_DATA_CHANNEL,
                    cmsg.encode_consensus_message(
                        cmsg.BlockPartMessage(ps.height, ps.round, part)
                    ),
                ):
                    sent = True
                else:
                    ps.unmark_part_sent(ps.height, -1, i)
        seen_commit = self.cs.block_store.load_seen_commit(ps.height)
        if seen_commit is not None:
            from cometbft_tpu.types.vote import Vote

            for idx, cs_sig in enumerate(seen_commit.signatures):
                if cs_sig.is_absent():
                    continue
                key = ("commit", ps.height, idx)
                if not ps.mark_vote_sent(key):
                    continue
                vote = Vote(
                    type=PRECOMMIT_TYPE,
                    height=seen_commit.height,
                    round=seen_commit.round,
                    block_id=cs_sig.block_id(seen_commit.block_id),
                    timestamp=cs_sig.timestamp,
                    validator_address=cs_sig.validator_address,
                    validator_index=idx,
                    signature=cs_sig.signature,
                )
                if ps.peer.try_send(
                    CONSENSUS_VOTE_CHANNEL,
                    cmsg.encode_consensus_message(cmsg.VoteMessage(vote)),
                ):
                    sent = True
                    self.cs.metrics.round_catchup_votes_sent.inc()
                else:
                    ps.unmark_vote_sent(key)
        return sent

    def _gossip_data(self, ps: PeerState, rs) -> bool:
        """Same-height data gossip (gossipDataRoutine): proposal + parts +
        ProposalPOL when the peer is at our round."""
        if rs.proposal is None or ps.round != rs.round:
            return False
        sent = False
        key = ("proposal", rs.height, rs.round)
        if ps.mark_vote_sent(key):
            if ps.peer.try_send(
                CONSENSUS_DATA_CHANNEL,
                cmsg.encode_consensus_message(cmsg.ProposalMessage(rs.proposal)),
            ):
                sent = True
                ps.set_has_proposal(rs.proposal)
                # reactor.go:600-612: a POL proposal is useless without the
                # POL round hint — send ProposalPOL right behind it. Best
                # effort: the cascade's POL branch re-serves the votes.
                if rs.proposal.pol_round >= 0 and rs.votes is not None:
                    pol = rs.votes.prevotes(rs.proposal.pol_round)
                    if pol is not None:
                        ps.peer.try_send(
                            CONSENSUS_DATA_CHANNEL,
                            cmsg.encode_consensus_message(
                                cmsg.ProposalPOLMessage(
                                    height=rs.height,
                                    proposal_pol_round=rs.proposal.pol_round,
                                    proposal_pol=pol.bit_array(),
                                )
                            ),
                        )
            else:
                ps.unmark_vote_sent(key)
        if rs.proposal_block_parts is not None:
            for i in range(rs.proposal_block_parts.total):
                part = rs.proposal_block_parts.get_part(i)
                if part is not None and ps.mark_part_sent(rs.height, rs.round, i):
                    if ps.peer.try_send(
                        CONSENSUS_DATA_CHANNEL,
                        cmsg.encode_consensus_message(
                            cmsg.BlockPartMessage(rs.height, rs.round, part)
                        ),
                    ):
                        sent = True
                    else:
                        ps.unmark_part_sent(rs.height, rs.round, i)
        return sent

    def _gossip_votes(self, ps: PeerState, rs) -> bool:
        """The reference's gossipVotesForHeight pick cascade (reactor.go:740-
        802): serve the votes for the PEER'S position, not ours. A peer
        lagging in rounds gets its-round prevotes/precommits (so it can climb
        back to the live round after a restart), a peer holding a POL
        proposal gets the POL-round prevotes, and a peer still in NewHeight
        gets our last-commit precommits. Without this cascade a node
        restarted mid-height re-enters round 0 and — when its voting power
        is needed for quorum — the whole network round-livelocks."""
        if rs.votes is None:
            return False
        # 1. Peer just entered this height: it needs the previous height's
        #    precommits (our last_commit) to build its own LastCommit.
        if ps.step == STEP_NEW_HEIGHT and rs.last_commit is not None:
            if self._pick_send_vote(ps, rs.last_commit, catchup=True):
                return True
        behind = ps.round < rs.round
        # 2. Peer stuck in Propose holding a POL proposal: POL prevotes.
        if ps.step <= STEP_PROPOSE and 0 <= ps.proposal_pol_round <= rs.round:
            pol = rs.votes.prevotes(ps.proposal_pol_round)
            if pol is not None and self._pick_send_vote(ps, pol, catchup=True):
                return True
        # 3. Peer in/below PrevoteWait: prevotes for ITS round.
        if ps.step <= STEP_PREVOTE_WAIT and 0 <= ps.round <= rs.round:
            pv = rs.votes.prevotes(ps.round)
            if pv is not None and self._pick_send_vote(ps, pv, catchup=behind):
                return True
        # 4. Peer in/below PrecommitWait: precommits for ITS round.
        if ps.step <= STEP_PRECOMMIT_WAIT and 0 <= ps.round <= rs.round:
            pc = rs.votes.precommits(ps.round)
            if pc is not None and self._pick_send_vote(ps, pc, catchup=behind):
                return True
        # 5. Catchall by round: any prevotes for the peer's round.
        if 0 <= ps.round <= rs.round:
            pv = rs.votes.prevotes(ps.round)
            if pv is not None and self._pick_send_vote(ps, pv, catchup=behind):
                return True
        # 6. POL prevotes regardless of step.
        if 0 <= ps.proposal_pol_round <= rs.round:
            pol = rs.votes.prevotes(ps.proposal_pol_round)
            if pol is not None and self._pick_send_vote(ps, pol, catchup=True):
                return True
        # 7. Fallback (pre-cascade behavior): our current round's votes —
        #    lets a lagging peer observe a +2/3-any future round and skip
        #    forward, and covers ps.step values outside the cascade.
        sent = False
        for vote_set in (rs.votes.prevotes(rs.round), rs.votes.precommits(rs.round)):
            if vote_set is not None and self._pick_send_vote(ps, vote_set):
                sent = True
        return sent

    def _pick_send_vote(self, ps: PeerState, vote_set, catchup: bool = False) -> bool:
        """reactor.go PickSendVote: send ONE vote from vote_set the peer
        doesn't have yet. On a full send queue the mark is unwound so the
        next gossip pass retries (mark/unmark symmetry — liveness under
        backpressure)."""
        for vote in vote_set.list_votes():
            key = (vote.height, vote.round, vote.type, vote.validator_index)
            if not ps.mark_vote_sent(key):
                continue
            if ps.peer.try_send(
                CONSENSUS_VOTE_CHANNEL,
                cmsg.encode_consensus_message(cmsg.VoteMessage(vote)),
            ):
                if catchup:
                    self.cs.metrics.round_catchup_votes_sent.inc()
                return True
            ps.unmark_vote_sent(key)
            return False  # queue full: back off, retry next pass
        return False

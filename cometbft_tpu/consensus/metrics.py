"""Consensus metrics (reference: consensus/metrics.go:23 Metrics struct).

Real instances bind to a libs.metrics.Registry; the default is a no-op so
ConsensusState never branches on instrumentation being enabled (the
reference's NopMetrics pattern).
"""

from __future__ import annotations


class _Nop:
    def inc(self, *a, **k):
        pass

    def dec(self, *a, **k):
        pass

    def set(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass

    def labels(self, **k):
        return self


_NOP = _Nop()


class Metrics:
    """consensus/metrics.go Metrics (the load-bearing subset)."""

    def __init__(self, registry=None):
        if registry is None:
            self.height = _NOP
            self.rounds = _NOP
            self.round_duration_seconds = _NOP
            self.validators = _NOP
            self.validators_power = _NOP
            self.num_txs = _NOP
            self.total_txs = _NOP
            self.block_size_bytes = _NOP
            self.latest_block_height = _NOP
            self.block_interval_seconds = _NOP
            self.block_parts = _NOP
            self.consensus_stalls_total = _NOP
            self.round_catchup_votes_sent = _NOP
            self.wal_replay_round = _NOP
            return
        sub = "consensus"
        self.height = registry.gauge(sub, "height", "Height of the chain.")
        self.rounds = registry.gauge(sub, "rounds", "Number of rounds at this height.")
        self.round_duration_seconds = registry.histogram(
            sub, "round_duration_seconds", "Time spent in a round.",
            buckets=(0.1, 0.27, 0.72, 1.9, 5.2, 14, 37, 100),
        )
        self.validators = registry.gauge(sub, "validators", "Number of validators.")
        self.validators_power = registry.gauge(
            sub, "validators_power", "Total voting power of validators."
        )
        self.num_txs = registry.gauge(sub, "num_txs", "Txs in the latest block.")
        self.total_txs = registry.counter(sub, "total_txs", "Total committed txs.")
        self.block_size_bytes = registry.gauge(
            sub, "block_size_bytes", "Size of the latest block."
        )
        self.latest_block_height = registry.gauge(
            sub, "latest_block_height", "Latest committed block height."
        )
        self.block_interval_seconds = registry.histogram(
            sub, "block_interval_seconds", "Time between this and the last block.",
        )
        self.block_parts = registry.counter(
            sub, "block_parts", "Block parts transmitted per peer.", labels=("peer_id",)
        )
        # Liveness hardening: stall watchdog + round-catchup gossip + WAL
        # round restore (consensus/reactor.py pick cascade, state.py watchdog).
        self.consensus_stalls_total = registry.counter(
            sub, "stalls_total",
            "Stall-watchdog firings: no round-step progress for the "
            "escalated-timeout budget.",
        )
        self.round_catchup_votes_sent = registry.counter(
            sub, "round_catchup_votes_sent",
            "Votes gossiped to peers lagging in rounds (peer-round prevotes/"
            "precommits, POL prevotes, last-commit precommits).",
        )
        self.wal_replay_round = registry.gauge(
            sub, "wal_replay_round",
            "Round restored from the WAL on the last mid-height restart.",
        )


NOP_METRICS = Metrics()

"""Consensus messages (reference: consensus/msgs.go + proto/tendermint/consensus).

Used both by the gossip reactor (wire) and the WAL (tagged local encoding).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from cometbft_tpu.libs.bit_array import BitArray
from cometbft_tpu.types.block import BlockID, PartSetHeader
from cometbft_tpu.types.part_set import Part
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.wire import proto as wire


@dataclass
class NewRoundStepMessage:
    """consensus/reactor.go NewRoundStepMessage."""

    height: int = 0
    round: int = 0
    step: int = 0
    seconds_since_start_time: int = 0
    last_commit_round: int = 0

    def encode(self) -> bytes:
        return (
            wire.field_varint(1, self.height)
            + wire.field_varint(2, self.round)
            + wire.field_varint(3, self.step)
            + wire.field_varint(4, self.seconds_since_start_time)
            + wire.field_varint(5, self.last_commit_round)
        )

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        return cls(
            wire.get_varint(f, 1), wire.get_varint(f, 2), wire.get_varint(f, 3),
            wire.get_varint(f, 4), wire.get_varint(f, 5),
        )


@dataclass
class NewValidBlockMessage:
    height: int = 0
    round: int = 0
    block_part_set_header: PartSetHeader = dfield(default_factory=PartSetHeader)
    block_parts: BitArray | None = None
    is_commit: bool = False

    def encode(self) -> bytes:
        out = wire.field_varint(1, self.height)
        out += wire.field_varint(2, self.round)
        out += wire.field_message(3, self.block_part_set_header.encode(), emit_empty=True)
        if self.block_parts is not None:
            out += wire.field_message(4, self.block_parts.encode(), emit_empty=True)
        out += wire.field_bool(5, self.is_commit)
        return out

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        bp = None
        if 4 in f:
            bp = BitArray.decode(wire.get_bytes(f, 4))
        return cls(
            wire.get_varint(f, 1),
            wire.get_varint(f, 2),
            PartSetHeader.decode(wire.get_bytes(f, 3)),
            bp,
            wire.get_bool(f, 5),
        )


@dataclass
class ProposalMessage:
    proposal: Proposal = None

    def encode(self) -> bytes:
        return wire.field_message(1, self.proposal.encode(), emit_empty=True)

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        return cls(Proposal.decode(wire.get_bytes(f, 1)))


@dataclass
class ProposalPOLMessage:
    height: int = 0
    proposal_pol_round: int = 0
    proposal_pol: BitArray | None = None

    def encode(self) -> bytes:
        out = wire.field_varint(1, self.height)
        out += wire.field_varint(2, self.proposal_pol_round)
        if self.proposal_pol is not None:
            out += wire.field_message(3, self.proposal_pol.encode(), emit_empty=True)
        return out

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        pol = BitArray.decode(wire.get_bytes(f, 3)) if 3 in f else None
        return cls(wire.get_varint(f, 1), wire.get_varint(f, 2), pol)


@dataclass
class BlockPartMessage:
    height: int = 0
    round: int = 0
    part: Part = None

    def encode(self) -> bytes:
        return (
            wire.field_varint(1, self.height)
            + wire.field_varint(2, self.round)
            + wire.field_message(3, self.part.encode(), emit_empty=True)
        )

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        return cls(
            wire.get_varint(f, 1),
            wire.get_varint(f, 2),
            Part.decode(wire.get_bytes(f, 3)),
        )


@dataclass
class VoteMessage:
    vote: Vote = None

    def encode(self) -> bytes:
        return wire.field_message(1, self.vote.encode(), emit_empty=True)

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        return cls(Vote.decode(wire.get_bytes(f, 1)))


@dataclass
class HasVoteMessage:
    height: int = 0
    round: int = 0
    type: int = 0
    index: int = 0

    def encode(self) -> bytes:
        return (
            wire.field_varint(1, self.height)
            + wire.field_varint(2, self.round)
            + wire.field_varint(3, self.type)
            + wire.field_varint(4, self.index)
        )

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        return cls(
            wire.get_varint(f, 1), wire.get_varint(f, 2),
            wire.get_varint(f, 3), wire.get_varint(f, 4),
        )


@dataclass
class VoteSetMaj23Message:
    height: int = 0
    round: int = 0
    type: int = 0
    block_id: BlockID = dfield(default_factory=BlockID)

    def encode(self) -> bytes:
        return (
            wire.field_varint(1, self.height)
            + wire.field_varint(2, self.round)
            + wire.field_varint(3, self.type)
            + wire.field_message(4, self.block_id.encode(), emit_empty=True)
        )

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        return cls(
            wire.get_varint(f, 1), wire.get_varint(f, 2), wire.get_varint(f, 3),
            BlockID.decode(wire.get_bytes(f, 4)),
        )


@dataclass
class VoteSetBitsMessage:
    height: int = 0
    round: int = 0
    type: int = 0
    block_id: BlockID = dfield(default_factory=BlockID)
    votes: BitArray | None = None

    def encode(self) -> bytes:
        out = (
            wire.field_varint(1, self.height)
            + wire.field_varint(2, self.round)
            + wire.field_varint(3, self.type)
            + wire.field_message(4, self.block_id.encode(), emit_empty=True)
        )
        if self.votes is not None:
            out += wire.field_message(5, self.votes.encode(), emit_empty=True)
        return out

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        votes = BitArray.decode(wire.get_bytes(f, 5)) if 5 in f else None
        return cls(
            wire.get_varint(f, 1), wire.get_varint(f, 2), wire.get_varint(f, 3),
            BlockID.decode(wire.get_bytes(f, 4)), votes,
        )


@dataclass
class TimeoutInfo:
    """consensus/state.go timeoutInfo: a scheduled timeout firing."""

    duration: float = 0.0
    height: int = 0
    round: int = 0
    step: int = 0

    def encode(self) -> bytes:
        return (
            wire.field_varint(1, int(self.duration * 1e9))
            + wire.field_varint(2, self.height)
            + wire.field_varint(3, self.round)
            + wire.field_varint(4, self.step)
        )

    @classmethod
    def decode(cls, d: bytes):
        f = wire.decode_fields(d)
        return cls(
            wire.get_varint(f, 1) / 1e9, wire.get_varint(f, 2),
            wire.get_varint(f, 3), wire.get_varint(f, 4),
        )


# -- reactor channel wire envelope (oneof tag) --------------------------------

_WIRE_TAGS = [
    (NewRoundStepMessage, 1),
    (NewValidBlockMessage, 2),
    (ProposalMessage, 3),
    (ProposalPOLMessage, 4),
    (BlockPartMessage, 5),
    (VoteMessage, 6),
    (HasVoteMessage, 7),
    (VoteSetMaj23Message, 8),
    (VoteSetBitsMessage, 9),
]
_TAG_BY_TYPE = {t: n for t, n in _WIRE_TAGS}
_TYPE_BY_TAG = {n: t for t, n in _WIRE_TAGS}


def encode_consensus_message(msg) -> bytes:
    """tendermint.consensus.Message oneof envelope."""
    tag = _TAG_BY_TYPE[type(msg)]
    return wire.field_message(tag, msg.encode(), emit_empty=True)


def decode_consensus_message(data: bytes):
    f = wire.decode_fields(data)
    for tag, typ in _TYPE_BY_TAG.items():
        if tag in f:
            return typ.decode(wire.get_bytes(f, tag))
    raise ValueError("unknown consensus message")


# -- WAL tagged encoding ------------------------------------------------------

from cometbft_tpu.consensus import wal as _walmod  # noqa: E402  (tags)


def encode_wal_message(msg) -> bytes:
    if isinstance(msg, ProposalMessage):
        return bytes([_walmod.MSG_PROPOSAL]) + msg.encode()
    if isinstance(msg, BlockPartMessage):
        return bytes([_walmod.MSG_BLOCK_PART]) + msg.encode()
    if isinstance(msg, VoteMessage):
        return bytes([_walmod.MSG_VOTE]) + msg.encode()
    if isinstance(msg, TimeoutInfo):
        return bytes([_walmod.MSG_TIMEOUT]) + msg.encode()
    if isinstance(msg, HasVoteMessage):
        return bytes([_walmod.MSG_HAS_VOTE]) + msg.encode()
    raise ValueError(f"unknown WAL message {msg!r}")


def decode_wal_message(data: bytes):
    tag, body = data[0], data[1:]
    if tag == _walmod.MSG_PROPOSAL:
        return ProposalMessage.decode(body)
    if tag == _walmod.MSG_BLOCK_PART:
        return BlockPartMessage.decode(body)
    if tag == _walmod.MSG_VOTE:
        return VoteMessage.decode(body)
    if tag == _walmod.MSG_TIMEOUT:
        return TimeoutInfo.decode(body)
    if tag == _walmod.MSG_HAS_VOTE:
        return HasVoteMessage.decode(body)
    raise ValueError(f"unknown WAL tag {tag}")

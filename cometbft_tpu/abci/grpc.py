"""ABCI over gRPC: the reference's second process-boundary transport
(abci/client/grpc_client.go:435, abci/server/grpc_server.go:61, service
`tendermint.abci.ABCIApplication` in proto/tendermint/abci/types.proto).

grpcio is driven through its generic bytes-passthrough API: each RPC method
carries the INNER Request*/Response* message encoded by the hand-rolled
gogoproto-compatible codec in abci/wire.py, so no generated stubs (and no
python protobuf runtime) are involved. Method routing gives the type, which
is exactly how the reference's per-rpc signatures work
(`rpc CheckTx(RequestCheckTx) returns (ResponseCheckTx)`).

Application errors surface as StatusCode.INTERNAL with the exception text —
the gRPC analog of the socket transport's ResponseException frame.
"""

from __future__ import annotations

import threading
from concurrent import futures

import grpc

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci import wire as abci_wire
from cometbft_tpu.abci.client import AsyncCheckTxMixin, Client, ClientCreator

_SERVICE = "tendermint.abci.ABCIApplication"

# rpc names of the ABCIApplication service; `Request{name}`/`Response{name}`
# are the wire types each method carries.
_METHODS = frozenset(
    {
        "Echo",
        "Flush",
        "Info",
        "InitChain",
        "Query",
        "BeginBlock",
        "CheckTx",
        "DeliverTx",
        "EndBlock",
        "Commit",
        "ListSnapshots",
        "OfferSnapshot",
        "LoadSnapshotChunk",
        "ApplySnapshotChunk",
        "PrepareProposal",
        "ProcessProposal",
    }
)


def _strip_scheme(addr: str) -> str:
    """grpc targets are bare host:port, or unix:<path> for sockets. unix://
    always means a socket path (relative or absolute); for the other schemes
    an absolute path (grpc:///tmp/x) means a unix socket too."""
    if addr.startswith("unix://"):
        return "unix:" + addr[len("unix://") :]
    for scheme in ("grpc://", "tcp://"):
        if addr.startswith(scheme):
            addr = addr[len(scheme) :]
            break
    if addr.startswith("/"):
        return "unix:" + addr
    return addr


class GrpcServer:
    """abci/server/grpc_server.go: serve an Application over gRPC. All
    dispatches funnel through one application mutex — the same serialization
    the socket server enforces (the reference relies on the app's own
    locking; this keeps both transports behaviorally identical here)."""

    def __init__(self, app: abci.Application, addr: str, max_workers: int = 8):
        self.app = app
        self.addr = addr
        self._mtx = threading.Lock()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((_AppHandler(self),))
        self.bound: str | None = None

    def start(self) -> str:
        target = _strip_scheme(self.addr)
        # grpcio reports bind failure by returning port 0 instead of
        # raising (unix sockets return 1 on success); fail fast like the
        # socket server's bind() would.
        port = self._server.add_insecure_port(target)
        if port == 0:
            raise OSError(f"cannot bind ABCI grpc server to {self.addr}")
        if target.startswith("unix:"):
            # Keep the unix: marker in the bound address so relative socket
            # paths round-trip through _strip_scheme too (grpc://unix:x.sock
            # -> unix:x.sock; a bare relative path would parse as DNS).
            self.bound = f"grpc://{target}"
        else:
            host = target.rsplit(":", 1)[0] or "127.0.0.1"
            self.bound = f"grpc://{host}:{port}"
        self._server.start()
        return self.bound

    def stop(self) -> None:
        self._server.stop(grace=0.2)

    def _dispatch(self, req):
        from cometbft_tpu.abci.server import dispatch_request

        with self._mtx:
            return dispatch_request(self.app, req)


class _AppHandler(grpc.GenericRpcHandler):
    def __init__(self, server: GrpcServer):
        self._server = server

    def service(self, handler_call_details):
        path = handler_call_details.method
        prefix = f"/{_SERVICE}/"
        if not path.startswith(prefix):
            return None
        name = path[len(prefix) :]
        if name not in _METHODS:
            return None
        req_name = f"Request{name}"

        def handle(req, context):
            try:
                return self._server._dispatch(req)
            except Exception as e:  # -> INTERNAL, like ResponseException
                context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

        return grpc.unary_unary_rpc_method_handler(
            handle,
            request_deserializer=lambda b, n=req_name: abci_wire._dec_req_body(
                n, b
            ),
            response_serializer=abci_wire._enc_resp_body,
        )


class GrpcClient(AsyncCheckTxMixin, Client):
    """abci/client/grpc_client.go in synchronous form (the node's proxy
    connections block on results; see SocketClient's rationale). CheckTxAsync
    keeps the mempool's pipelined ordering with a single dispatch thread."""

    def __init__(self, addr: str, connect_timeout: float = 10.0):
        self._channel = grpc.insecure_channel(_strip_scheme(addr))
        try:
            grpc.channel_ready_future(self._channel).result(timeout=connect_timeout)
        except grpc.FutureTimeoutError:
            self._channel.close()
            raise ConnectionError(f"cannot connect to ABCI app at {addr}")
        self._stubs = {}
        for name in _METHODS:
            self._stubs[name] = self._channel.unary_unary(
                f"/{_SERVICE}/{name}",
                request_serializer=abci_wire._enc_req_body,
                response_deserializer=lambda b, n=f"Response{name}": (
                    abci_wire._dec_resp_body(n, b)
                ),
            )
        self._start_async("abci-grpc-async")

    def close(self) -> None:
        self._stop_async()
        self._channel.close()

    def _call(self, name: str, req):
        # No deadline: ABCI calls block for as long as the app needs (a
        # commit that triggers a long snapshot, a first-call device compile),
        # exactly like the socket transport's untimed reads.
        try:
            return self._stubs[name](req)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.INTERNAL:
                raise RuntimeError(f"ABCI app exception: {e.details()}") from None
            raise ConnectionError(f"ABCI grpc {name}: {e.code()}: {e.details()}")

    def _do_check_tx(self, req):
        return self._call("CheckTx", req)

    def echo(self, msg: str):
        return self._call("Echo", abci.RequestEcho(message=msg))

    def flush(self) -> None:
        self._call("Flush", abci.RequestFlush())

    def info(self, req):
        return self._call("Info", req)

    def init_chain(self, req):
        return self._call("InitChain", req)

    def query(self, req):
        return self._call("Query", req)

    def check_tx(self, req):
        return self._call("CheckTx", req)

    def begin_block(self, req):
        return self._call("BeginBlock", req)

    def deliver_tx(self, req):
        return self._call("DeliverTx", req)

    def end_block(self, req):
        return self._call("EndBlock", req)

    def commit(self):
        return self._call("Commit", abci.RequestCommit())

    def prepare_proposal(self, req):
        return self._call("PrepareProposal", req)

    def process_proposal(self, req):
        return self._call("ProcessProposal", req)

    def list_snapshots(self, req):
        return self._call("ListSnapshots", req)

    def offer_snapshot(self, req):
        return self._call("OfferSnapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("LoadSnapshotChunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("ApplySnapshotChunk", req)


class GrpcClientCreator(ClientCreator):
    """proxy/client.go NewRemoteClientCreator with transport=grpc: one fresh
    channel per logical app connection."""

    def __init__(self, addr: str):
        self._addr = addr

    def new_abci_client(self) -> Client:
        return GrpcClient(self._addr)

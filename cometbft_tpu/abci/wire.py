"""ABCI wire codec: Request/Response oneof messages + varint framing
(reference: proto/tendermint/abci/types.proto + abci/types/messages.go
WriteMessage/ReadMessage — gogoproto length-delimited framing).

Field numbers follow types.proto exactly (Request oneof :23-42, Response
oneof :156-176) so a conforming external app server can speak to this node.
Submessages reuse the hand-rolled codec in wire/proto.py and the existing
types-layer encoders (Header, ConsensusParams).
"""

from __future__ import annotations

from cometbft_tpu.abci import types as abci
from cometbft_tpu.wire import proto as wire


# -- submessages -------------------------------------------------------------


def _enc_timestamp(seconds: int, nanos: int = 0) -> bytes:
    return wire.field_varint(1, seconds) + wire.field_varint(2, nanos)


def _dec_timestamp(data: bytes) -> int:
    f = wire.decode_fields(data)
    return wire.get_varint(f, 1)


def _enc_event_attr(a: abci.EventAttribute) -> bytes:
    return (
        wire.field_string(1, a.key)
        + wire.field_string(2, a.value)
        + wire.field_bool(3, a.index)
    )


def _dec_event_attr(data: bytes) -> abci.EventAttribute:
    f = wire.decode_fields(data)
    return abci.EventAttribute(
        key=wire.get_string(f, 1), value=wire.get_string(f, 2), index=wire.get_bool(f, 3)
    )


def _enc_event(e: abci.Event) -> bytes:
    out = wire.field_string(1, e.type)
    for a in e.attributes:
        out += wire.field_message(2, _enc_event_attr(a), emit_empty=True)
    return out


def _dec_event(data: bytes) -> abci.Event:
    f = wire.decode_fields(data)
    return abci.Event(
        type=wire.get_string(f, 1),
        attributes=[_dec_event_attr(b) for b in wire.get_repeated_bytes(f, 2)],
    )


def _enc_pub_key(pub) -> bytes:
    """crypto.proto PublicKey oneof: ed25519=1, secp256k1=2, bn254=3."""
    from cometbft_tpu.crypto import bn254, ed25519, secp256k1

    if isinstance(pub, ed25519.PubKey):
        return wire.field_bytes(1, pub.bytes())
    if isinstance(pub, secp256k1.PubKey):
        return wire.field_bytes(2, pub.bytes())
    if isinstance(pub, bn254.PubKey):
        return wire.field_bytes(3, pub.bytes())
    raise ValueError(f"unsupported pubkey type {type(pub)!r}")


def _dec_pub_key(data: bytes):
    from cometbft_tpu.crypto import bn254, ed25519, secp256k1

    f = wire.decode_fields(data)
    if 1 in f:
        return ed25519.PubKey(wire.get_bytes(f, 1))
    if 2 in f:
        return secp256k1.PubKey(wire.get_bytes(f, 2))
    if 3 in f:
        return bn254.PubKey(wire.get_bytes(f, 3))
    raise ValueError("empty PublicKey")


def _enc_validator_update(vu: abci.ValidatorUpdate) -> bytes:
    return wire.field_message(
        1, _enc_pub_key(vu.pub_key), emit_empty=True
    ) + wire.field_varint(2, vu.power)


def _dec_validator_update(data: bytes) -> abci.ValidatorUpdate:
    f = wire.decode_fields(data)
    return abci.ValidatorUpdate(
        pub_key=_dec_pub_key(wire.get_bytes(f, 1)), power=wire.get_varint(f, 2)
    )


def _enc_vote_info(v: abci.VoteInfo) -> bytes:
    val = wire.field_bytes(1, v.validator_address) + wire.field_varint(
        2, v.validator_power
    )
    return wire.field_message(1, val, emit_empty=True) + wire.field_bool(
        2, v.signed_last_block
    )


def _dec_vote_info(data: bytes) -> abci.VoteInfo:
    f = wire.decode_fields(data)
    vf = wire.decode_fields(wire.get_bytes(f, 1))
    return abci.VoteInfo(
        validator_address=wire.get_bytes(vf, 1),
        validator_power=wire.get_varint(vf, 2),
        signed_last_block=wire.get_bool(f, 2),
    )


def _enc_commit_info(ci: abci.CommitInfo) -> bytes:
    out = wire.field_varint(1, ci.round)
    for v in ci.votes:
        out += wire.field_message(2, _enc_vote_info(v), emit_empty=True)
    return out


def _dec_commit_info(data: bytes) -> abci.CommitInfo:
    f = wire.decode_fields(data)
    return abci.CommitInfo(
        round=wire.get_varint(f, 1),
        votes=[_dec_vote_info(b) for b in wire.get_repeated_bytes(f, 2)],
    )


def _enc_misbehavior(m: abci.Misbehavior) -> bytes:
    val = wire.field_bytes(1, m.validator_address) + wire.field_varint(
        2, m.validator_power
    )
    return (
        wire.field_varint(1, m.type)
        + wire.field_message(2, val, emit_empty=True)
        + wire.field_varint(3, m.height)
        + wire.field_message(4, _enc_timestamp(m.time_seconds), emit_empty=True)
        + wire.field_varint(5, m.total_voting_power)
    )


def _dec_misbehavior(data: bytes) -> abci.Misbehavior:
    f = wire.decode_fields(data)
    vf = wire.decode_fields(wire.get_bytes(f, 2))
    return abci.Misbehavior(
        type=wire.get_varint(f, 1),
        validator_address=wire.get_bytes(vf, 1),
        validator_power=wire.get_varint(vf, 2),
        height=wire.get_varint(f, 3),
        time_seconds=_dec_timestamp(wire.get_bytes(f, 4)),
        total_voting_power=wire.get_varint(f, 5),
    )


def _enc_snapshot(s: abci.Snapshot) -> bytes:
    return (
        wire.field_varint(1, s.height)
        + wire.field_varint(2, s.format)
        + wire.field_varint(3, s.chunks)
        + wire.field_bytes(4, s.hash)
        + wire.field_bytes(5, s.metadata)
    )


def _dec_snapshot(data: bytes) -> abci.Snapshot:
    f = wire.decode_fields(data)
    return abci.Snapshot(
        height=wire.get_uvarint(f, 1),
        format=wire.get_uvarint(f, 2),
        chunks=wire.get_uvarint(f, 3),
        hash=wire.get_bytes(f, 4),
        metadata=wire.get_bytes(f, 5),
    )


def _enc_proof_ops(ops: list) -> bytes:
    out = b""
    for op in ops:
        body = (
            wire.field_string(1, op.type)
            + wire.field_bytes(2, op.key)
            + wire.field_bytes(3, op.data)
        )
        out += wire.field_message(1, body, emit_empty=True)
    return out


def _dec_proof_ops(data: bytes) -> list:
    from cometbft_tpu.crypto.merkle.proof_op import ProofOp

    f = wire.decode_fields(data)
    out = []
    for b in wire.get_repeated_bytes(f, 1):
        of = wire.decode_fields(b)
        out.append(
            ProofOp(
                type=wire.get_string(of, 1),
                key=wire.get_bytes(of, 2),
                data=wire.get_bytes(of, 3),
            )
        )
    return out


def _enc_params(params) -> bytes | None:
    if params is None:
        return None
    return params.encode()


def _dec_params(data: bytes):
    if not data:
        return None
    from cometbft_tpu.types.params import ConsensusParams

    return ConsensusParams.decode(data)


def _dec_header(data: bytes):
    from cometbft_tpu.types.block import Header

    return Header.decode(data)


# -- request bodies ----------------------------------------------------------


def _enc_req_body(req) -> bytes:
    t = type(req).__name__
    if t == "RequestEcho":
        return wire.field_string(1, req.message)
    if t in ("RequestFlush", "RequestCommit", "RequestListSnapshots"):
        return b""
    if t == "RequestInfo":
        return (
            wire.field_string(1, req.version)
            + wire.field_varint(2, req.block_version)
            + wire.field_varint(3, req.p2p_version)
            + wire.field_string(4, req.abci_version)
        )
    if t == "RequestInitChain":
        out = wire.field_message(1, _enc_timestamp(req.time_seconds), emit_empty=True)
        out += wire.field_string(2, req.chain_id)
        out += wire.field_message(3, _enc_params(req.consensus_params))
        for vu in req.validators:
            out += wire.field_message(4, _enc_validator_update(vu), emit_empty=True)
        out += wire.field_bytes(5, req.app_state_bytes)
        out += wire.field_varint(6, req.initial_height)
        return out
    if t == "RequestQuery":
        return (
            wire.field_bytes(1, req.data)
            + wire.field_string(2, req.path)
            + wire.field_varint(3, req.height)
            + wire.field_bool(4, req.prove)
        )
    if t == "RequestBeginBlock":
        out = wire.field_bytes(1, req.hash)
        out += wire.field_message(
            2, req.header.encode() if req.header else b"", emit_empty=True
        )
        out += wire.field_message(3, _enc_commit_info(req.last_commit_info), emit_empty=True)
        for m in req.byzantine_validators:
            out += wire.field_message(4, _enc_misbehavior(m), emit_empty=True)
        return out
    if t == "RequestCheckTx":
        return wire.field_bytes(1, req.tx) + wire.field_varint(2, req.type)
    if t == "RequestDeliverTx":
        return wire.field_bytes(1, req.tx)
    if t == "RequestEndBlock":
        return wire.field_varint(1, req.height)
    if t == "RequestOfferSnapshot":
        return wire.field_message(
            1, _enc_snapshot(req.snapshot) if req.snapshot else None
        ) + wire.field_bytes(2, req.app_hash)
    if t == "RequestLoadSnapshotChunk":
        return (
            wire.field_varint(1, req.height)
            + wire.field_varint(2, req.format)
            + wire.field_varint(3, req.chunk)
        )
    if t == "RequestApplySnapshotChunk":
        return (
            wire.field_varint(1, req.index)
            + wire.field_bytes(2, req.chunk)
            + wire.field_string(3, req.sender)
        )
    if t == "RequestPrepareProposal":
        out = wire.field_varint(1, req.max_tx_bytes)
        for tx in req.txs:
            out += wire.field_bytes(2, tx, emit_default=True)
        out += wire.field_message(3, _enc_commit_info(req.local_last_commit), emit_empty=True)
        for m in req.misbehavior:
            out += wire.field_message(4, _enc_misbehavior(m), emit_empty=True)
        out += wire.field_varint(5, req.height)
        out += wire.field_message(6, _enc_timestamp(req.time_seconds), emit_empty=True)
        out += wire.field_bytes(7, req.next_validators_hash)
        out += wire.field_bytes(8, req.proposer_address)
        return out
    if t == "RequestProcessProposal":
        out = b""
        for tx in req.txs:
            out += wire.field_bytes(1, tx, emit_default=True)
        out += wire.field_message(2, _enc_commit_info(req.proposed_last_commit), emit_empty=True)
        for m in req.misbehavior:
            out += wire.field_message(3, _enc_misbehavior(m), emit_empty=True)
        out += wire.field_bytes(4, req.hash)
        out += wire.field_varint(5, req.height)
        out += wire.field_message(6, _enc_timestamp(req.time_seconds), emit_empty=True)
        out += wire.field_bytes(7, req.next_validators_hash)
        out += wire.field_bytes(8, req.proposer_address)
        return out
    raise ValueError(f"unknown request type {t}")


_REQ_FIELDS = {
    "RequestEcho": 1,
    "RequestFlush": 2,
    "RequestInfo": 3,
    "RequestInitChain": 5,
    "RequestQuery": 6,
    "RequestBeginBlock": 7,
    "RequestCheckTx": 8,
    "RequestDeliverTx": 9,
    "RequestEndBlock": 10,
    "RequestCommit": 11,
    "RequestListSnapshots": 12,
    "RequestOfferSnapshot": 13,
    "RequestLoadSnapshotChunk": 14,
    "RequestApplySnapshotChunk": 15,
    "RequestPrepareProposal": 16,
    "RequestProcessProposal": 17,
}
_REQ_BY_FIELD = {v: k for k, v in _REQ_FIELDS.items()}


def encode_request(req) -> bytes:
    """Request oneof (types.proto:22-42)."""
    num = _REQ_FIELDS[type(req).__name__]
    return wire.field_message(num, _enc_req_body(req), emit_empty=True)


def decode_request(data: bytes):
    f = wire.decode_fields(data)
    for num, name in _REQ_BY_FIELD.items():
        if num in f:
            return _dec_req_body(name, wire.get_bytes(f, num))
    raise ValueError("empty Request")


def _dec_req_body(name: str, data: bytes):
    f = wire.decode_fields(data)
    if name == "RequestEcho":
        return abci.RequestEcho(message=wire.get_string(f, 1))
    if name == "RequestFlush":
        return abci.RequestFlush()
    if name == "RequestInfo":
        return abci.RequestInfo(
            version=wire.get_string(f, 1),
            block_version=wire.get_uvarint(f, 2),
            p2p_version=wire.get_uvarint(f, 3),
            abci_version=wire.get_string(f, 4),
        )
    if name == "RequestInitChain":
        return abci.RequestInitChain(
            time_seconds=_dec_timestamp(wire.get_bytes(f, 1)),
            chain_id=wire.get_string(f, 2),
            consensus_params=_dec_params(wire.get_bytes(f, 3)),
            validators=[_dec_validator_update(b) for b in wire.get_repeated_bytes(f, 4)],
            app_state_bytes=wire.get_bytes(f, 5),
            initial_height=wire.get_varint(f, 6),
        )
    if name == "RequestQuery":
        return abci.RequestQuery(
            data=wire.get_bytes(f, 1),
            path=wire.get_string(f, 2),
            height=wire.get_varint(f, 3),
            prove=wire.get_bool(f, 4),
        )
    if name == "RequestBeginBlock":
        hdr = wire.get_bytes(f, 2)
        return abci.RequestBeginBlock(
            hash=wire.get_bytes(f, 1),
            header=_dec_header(hdr) if hdr else None,
            last_commit_info=_dec_commit_info(wire.get_bytes(f, 3)),
            byzantine_validators=[
                _dec_misbehavior(b) for b in wire.get_repeated_bytes(f, 4)
            ],
        )
    if name == "RequestCheckTx":
        return abci.RequestCheckTx(tx=wire.get_bytes(f, 1), type=wire.get_varint(f, 2))
    if name == "RequestDeliverTx":
        return abci.RequestDeliverTx(tx=wire.get_bytes(f, 1))
    if name == "RequestEndBlock":
        return abci.RequestEndBlock(height=wire.get_varint(f, 1))
    if name == "RequestCommit":
        return abci.RequestCommit()
    if name == "RequestListSnapshots":
        return abci.RequestListSnapshots()
    if name == "RequestOfferSnapshot":
        snap = wire.get_bytes(f, 1)
        return abci.RequestOfferSnapshot(
            snapshot=_dec_snapshot(snap) if snap else None,
            app_hash=wire.get_bytes(f, 2),
        )
    if name == "RequestLoadSnapshotChunk":
        return abci.RequestLoadSnapshotChunk(
            height=wire.get_uvarint(f, 1),
            format=wire.get_uvarint(f, 2),
            chunk=wire.get_uvarint(f, 3),
        )
    if name == "RequestApplySnapshotChunk":
        return abci.RequestApplySnapshotChunk(
            index=wire.get_uvarint(f, 1),
            chunk=wire.get_bytes(f, 2),
            sender=wire.get_string(f, 3),
        )
    if name == "RequestPrepareProposal":
        return abci.RequestPrepareProposal(
            max_tx_bytes=wire.get_varint(f, 1),
            txs=wire.get_repeated_bytes(f, 2),
            local_last_commit=_dec_commit_info(wire.get_bytes(f, 3)),
            misbehavior=[_dec_misbehavior(b) for b in wire.get_repeated_bytes(f, 4)],
            height=wire.get_varint(f, 5),
            time_seconds=_dec_timestamp(wire.get_bytes(f, 6)),
            next_validators_hash=wire.get_bytes(f, 7),
            proposer_address=wire.get_bytes(f, 8),
        )
    if name == "RequestProcessProposal":
        return abci.RequestProcessProposal(
            txs=wire.get_repeated_bytes(f, 1),
            proposed_last_commit=_dec_commit_info(wire.get_bytes(f, 2)),
            misbehavior=[_dec_misbehavior(b) for b in wire.get_repeated_bytes(f, 3)],
            hash=wire.get_bytes(f, 4),
            height=wire.get_varint(f, 5),
            time_seconds=_dec_timestamp(wire.get_bytes(f, 6)),
            next_validators_hash=wire.get_bytes(f, 7),
            proposer_address=wire.get_bytes(f, 8),
        )
    raise ValueError(f"unknown request name {name}")


# -- response bodies ---------------------------------------------------------


def _enc_events(num: int, events: list) -> bytes:
    out = b""
    for e in events:
        out += wire.field_message(num, _enc_event(e), emit_empty=True)
    return out


def _enc_resp_body(resp) -> bytes:
    t = type(resp).__name__
    if t == "ResponseException":
        return wire.field_string(1, resp.error)
    if t == "ResponseEcho":
        return wire.field_string(1, resp.message)
    if t == "ResponseFlush":
        return b""
    if t == "ResponseInfo":
        return (
            wire.field_string(1, resp.data)
            + wire.field_string(2, resp.version)
            + wire.field_varint(3, resp.app_version)
            + wire.field_varint(4, resp.last_block_height)
            + wire.field_bytes(5, resp.last_block_app_hash)
        )
    if t == "ResponseInitChain":
        out = wire.field_message(1, _enc_params(resp.consensus_params))
        for vu in resp.validators:
            out += wire.field_message(2, _enc_validator_update(vu), emit_empty=True)
        out += wire.field_bytes(3, resp.app_hash)
        return out
    if t == "ResponseQuery":
        return (
            wire.field_varint(1, resp.code)
            + wire.field_string(3, resp.log)
            + wire.field_string(4, resp.info)
            + wire.field_varint(5, resp.index)
            + wire.field_bytes(6, resp.key)
            + wire.field_bytes(7, resp.value)
            + wire.field_message(8, _enc_proof_ops(resp.proof_ops) if resp.proof_ops else None)
            + wire.field_varint(9, resp.height)
            + wire.field_string(10, resp.codespace)
        )
    if t == "ResponseBeginBlock":
        return _enc_events(1, resp.events)
    if t in ("ResponseCheckTx", "ResponseDeliverTx"):
        return (
            wire.field_varint(1, resp.code)
            + wire.field_bytes(2, resp.data)
            + wire.field_string(3, resp.log)
            + wire.field_string(4, resp.info)
            + wire.field_varint(5, resp.gas_wanted)
            + wire.field_varint(6, resp.gas_used)
            + _enc_events(7, resp.events)
            + wire.field_string(8, resp.codespace)
        )
    if t == "ResponseEndBlock":
        out = b""
        for vu in resp.validator_updates:
            out += wire.field_message(1, _enc_validator_update(vu), emit_empty=True)
        out += wire.field_message(2, _enc_params(resp.consensus_param_updates))
        out += _enc_events(3, resp.events)
        return out
    if t == "ResponseCommit":
        return wire.field_bytes(2, resp.data) + wire.field_varint(3, resp.retain_height)
    if t == "ResponseListSnapshots":
        out = b""
        for s in resp.snapshots:
            out += wire.field_message(1, _enc_snapshot(s), emit_empty=True)
        return out
    if t == "ResponseOfferSnapshot":
        return wire.field_varint(1, resp.result)
    if t == "ResponseLoadSnapshotChunk":
        return wire.field_bytes(1, resp.chunk)
    if t == "ResponseApplySnapshotChunk":
        out = wire.field_varint(1, resp.result)
        for c in resp.refetch_chunks:
            out += wire.field_varint(2, c, emit_default=True)
        for s in resp.reject_senders:
            out += wire.field_string(3, s, emit_default=True)
        return out
    if t == "ResponsePrepareProposal":
        out = b""
        for tx in resp.txs:
            out += wire.field_bytes(1, tx, emit_default=True)
        return out
    if t == "ResponseProcessProposal":
        return wire.field_varint(1, resp.status)
    raise ValueError(f"unknown response type {t}")


_RESP_FIELDS = {
    "ResponseException": 1,
    "ResponseEcho": 2,
    "ResponseFlush": 3,
    "ResponseInfo": 4,
    "ResponseInitChain": 6,
    "ResponseQuery": 7,
    "ResponseBeginBlock": 8,
    "ResponseCheckTx": 9,
    "ResponseDeliverTx": 10,
    "ResponseEndBlock": 11,
    "ResponseCommit": 12,
    "ResponseListSnapshots": 13,
    "ResponseOfferSnapshot": 14,
    "ResponseLoadSnapshotChunk": 15,
    "ResponseApplySnapshotChunk": 16,
    "ResponsePrepareProposal": 17,
    "ResponseProcessProposal": 18,
}
_RESP_BY_FIELD = {v: k for k, v in _RESP_FIELDS.items()}


def encode_response(resp) -> bytes:
    num = _RESP_FIELDS[type(resp).__name__]
    return wire.field_message(num, _enc_resp_body(resp), emit_empty=True)


def decode_response(data: bytes):
    f = wire.decode_fields(data)
    for num, name in _RESP_BY_FIELD.items():
        if num in f:
            return _dec_resp_body(name, wire.get_bytes(f, num))
    raise ValueError("empty Response")


def _dec_resp_body(name: str, data: bytes):
    f = wire.decode_fields(data)
    if name == "ResponseException":
        return abci.ResponseException(error=wire.get_string(f, 1))
    if name == "ResponseEcho":
        return abci.ResponseEcho(message=wire.get_string(f, 1))
    if name == "ResponseFlush":
        return abci.ResponseFlush()
    if name == "ResponseInfo":
        return abci.ResponseInfo(
            data=wire.get_string(f, 1),
            version=wire.get_string(f, 2),
            app_version=wire.get_uvarint(f, 3),
            last_block_height=wire.get_varint(f, 4),
            last_block_app_hash=wire.get_bytes(f, 5),
        )
    if name == "ResponseInitChain":
        return abci.ResponseInitChain(
            consensus_params=_dec_params(wire.get_bytes(f, 1)),
            validators=[_dec_validator_update(b) for b in wire.get_repeated_bytes(f, 2)],
            app_hash=wire.get_bytes(f, 3),
        )
    if name == "ResponseQuery":
        proof = wire.get_bytes(f, 8)
        return abci.ResponseQuery(
            code=wire.get_uvarint(f, 1),
            log=wire.get_string(f, 3),
            info=wire.get_string(f, 4),
            index=wire.get_varint(f, 5),
            key=wire.get_bytes(f, 6),
            value=wire.get_bytes(f, 7),
            proof_ops=_dec_proof_ops(proof) if proof else [],
            height=wire.get_varint(f, 9),
            codespace=wire.get_string(f, 10),
        )
    if name == "ResponseBeginBlock":
        return abci.ResponseBeginBlock(
            events=[_dec_event(b) for b in wire.get_repeated_bytes(f, 1)]
        )
    if name in ("ResponseCheckTx", "ResponseDeliverTx"):
        cls = abci.ResponseCheckTx if name == "ResponseCheckTx" else abci.ResponseDeliverTx
        return cls(
            code=wire.get_uvarint(f, 1),
            data=wire.get_bytes(f, 2),
            log=wire.get_string(f, 3),
            info=wire.get_string(f, 4),
            gas_wanted=wire.get_varint(f, 5),
            gas_used=wire.get_varint(f, 6),
            events=[_dec_event(b) for b in wire.get_repeated_bytes(f, 7)],
            codespace=wire.get_string(f, 8),
        )
    if name == "ResponseEndBlock":
        params = wire.get_bytes(f, 2)
        return abci.ResponseEndBlock(
            validator_updates=[
                _dec_validator_update(b) for b in wire.get_repeated_bytes(f, 1)
            ],
            consensus_param_updates=_dec_params(params),
            events=[_dec_event(b) for b in wire.get_repeated_bytes(f, 3)],
        )
    if name == "ResponseCommit":
        return abci.ResponseCommit(
            data=wire.get_bytes(f, 2), retain_height=wire.get_varint(f, 3)
        )
    if name == "ResponseListSnapshots":
        return abci.ResponseListSnapshots(
            snapshots=[_dec_snapshot(b) for b in wire.get_repeated_bytes(f, 1)]
        )
    if name == "ResponseOfferSnapshot":
        return abci.ResponseOfferSnapshot(result=wire.get_varint(f, 1))
    if name == "ResponseLoadSnapshotChunk":
        return abci.ResponseLoadSnapshotChunk(chunk=wire.get_bytes(f, 1))
    if name == "ResponseApplySnapshotChunk":
        return abci.ResponseApplySnapshotChunk(
            result=wire.get_varint(f, 1),
            refetch_chunks=wire.get_repeated_uvarint(f, 2),
            reject_senders=[b.decode() for b in wire.get_repeated_bytes(f, 3)],
        )
    if name == "ResponsePrepareProposal":
        return abci.ResponsePrepareProposal(txs=wire.get_repeated_bytes(f, 1))
    if name == "ResponseProcessProposal":
        return abci.ResponseProcessProposal(status=wire.get_varint(f, 1))
    raise ValueError(f"unknown response name {name}")


# -- stream framing ----------------------------------------------------------


def write_message(sock_file, msg_bytes: bytes) -> None:
    """gogoproto length-delimited: uvarint byte length then the message
    (abci/types/messages.go WriteMessage)."""
    sock_file.write(wire.encode_uvarint(len(msg_bytes)) + msg_bytes)


def read_message(sock_file) -> bytes | None:
    """Counterpart of write_message; None on clean EOF."""
    shift = 0
    length = 0
    while True:
        b = sock_file.read(1)
        if not b:
            return None
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ValueError("varint length overflow")
    if length > 256 * 1024 * 1024:
        raise ValueError(f"message too large: {length}")
    data = b""
    while len(data) < length:
        chunk = sock_file.read(length - len(data))
        if not chunk:
            raise EOFError("short read inside message")
        data += chunk
    return data

"""ABCI clients (reference: abci/client/).

LocalClient mirrors abci/client/local_client.go:356 — in-process calls to
the Application behind one shared mutex (the application sees requests from
the four logical connections serialized exactly as in the reference).
"""

from __future__ import annotations

import threading

from cometbft_tpu.abci import types as abci


class AsyncCheckTxMixin:
    """Single-dispatch-thread CheckTxAsync, shared by the remote transports
    (socket, grpc): preserves the mempool's pipelined ordering, and a failed
    CheckTx must NOT kill the dispatch thread — the mempool would silently
    stop admitting txs forever. Transports implement _do_check_tx(req) and
    call _start_async()/_stop_async() around their connection lifetime."""

    def _start_async(self, name: str) -> None:
        self._async_queue: list = []
        self._async_cv = threading.Condition()
        self._async_running = True
        threading.Thread(target=self._async_loop, daemon=True, name=name).start()

    def _stop_async(self) -> None:
        self._async_running = False
        with self._async_cv:
            self._async_cv.notify_all()

    def _do_check_tx(self, req) -> "abci.ResponseCheckTx":
        raise NotImplementedError

    def _async_error_response(self, e: Exception) -> "abci.ResponseCheckTx":
        return abci.ResponseCheckTx(code=1, log=f"abci transport error: {e}")

    def check_tx_async(self, req, callback=None):
        with self._async_cv:
            self._async_queue.append((req, callback))
            self._async_cv.notify()

    def _async_loop(self) -> None:
        while self._async_running:
            with self._async_cv:
                while self._async_running and not self._async_queue:
                    self._async_cv.wait()
                if not self._async_running:
                    return
                req, callback = self._async_queue.pop(0)
            try:
                res = self._do_check_tx(req)
            except Exception as e:
                res = self._async_error_response(e)
            if callback is not None:
                try:
                    callback(res)
                except Exception:
                    pass


class Client:
    """Sync client surface used by proxy.AppConns."""

    def echo(self, msg: str) -> abci.ResponseEcho:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    def check_tx_async(self, req: abci.RequestCheckTx, callback=None):
        """Async CheckTx (mempool pipeline). The local client executes
        inline and invokes the callback synchronously — same observable
        ordering as local_client.go's CheckTxAsync."""
        raise NotImplementedError

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    def prepare_proposal(self, req: abci.RequestPrepareProposal) -> abci.ResponsePrepareProposal:
        raise NotImplementedError

    def process_proposal(self, req: abci.RequestProcessProposal) -> abci.ResponseProcessProposal:
        raise NotImplementedError

    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError


class LocalClient(Client):
    """abci/client/local_client.go: shared-mutex in-process client."""

    def __init__(self, app: abci.Application, mtx: threading.RLock | None = None):
        self._app = app
        self._mtx = mtx or threading.RLock()

    def echo(self, msg: str) -> abci.ResponseEcho:
        return abci.ResponseEcho(message=msg)

    def flush(self) -> None:
        return None

    def info(self, req):
        with self._mtx:
            return self._app.info(req)

    def init_chain(self, req):
        with self._mtx:
            return self._app.init_chain(req)

    def query(self, req):
        with self._mtx:
            return self._app.query(req)

    def check_tx(self, req):
        with self._mtx:
            return self._app.check_tx(req)

    def check_tx_async(self, req, callback=None):
        with self._mtx:
            res = self._app.check_tx(req)
        if callback is not None:
            callback(res)
        return res

    def begin_block(self, req):
        with self._mtx:
            return self._app.begin_block(req)

    def deliver_tx(self, req):
        with self._mtx:
            return self._app.deliver_tx(req)

    def end_block(self, req):
        with self._mtx:
            return self._app.end_block(req)

    def commit(self):
        with self._mtx:
            return self._app.commit()

    def prepare_proposal(self, req):
        with self._mtx:
            return self._app.prepare_proposal(req)

    def process_proposal(self, req):
        with self._mtx:
            return self._app.process_proposal(req)

    def list_snapshots(self, req):
        with self._mtx:
            return self._app.list_snapshots(req)

    def offer_snapshot(self, req):
        with self._mtx:
            return self._app.offer_snapshot(req)

    def load_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.apply_snapshot_chunk(req)


class SocketClient(AsyncCheckTxMixin, Client):
    """abci/client/socket_client.go over the gogoproto-framed stream, in
    synchronous form: the node's four proxy connections each own one
    SocketClient, every call writes Request+Flush and reads Response+Flush
    under the connection lock — the observable per-connection ordering of
    the reference's send/receive goroutine pair, without the pending queue
    (callers here block on the result anyway). CheckTxAsync keeps the
    mempool's pipelined ordering with a single dispatch thread."""

    def __init__(self, addr: str, connect_timeout: float = 10.0):
        import socket as socketlib
        import time

        from cometbft_tpu.abci.server import parse_addr

        scheme, target = parse_addr(addr)
        deadline = time.monotonic() + connect_timeout
        last_err = None
        while time.monotonic() < deadline:
            try:
                if scheme == "unix":
                    s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
                else:
                    s = socketlib.socket(socketlib.AF_INET, socketlib.SOCK_STREAM)
                s.connect(target)
                break
            except OSError as e:  # app process may still be booting
                last_err = e
                time.sleep(0.05)
        else:
            raise ConnectionError(f"cannot connect to ABCI app at {addr}: {last_err}")
        if scheme == "tcp":
            s.setsockopt(socketlib.IPPROTO_TCP, socketlib.TCP_NODELAY, 1)
        self._sock = s
        self._rf = s.makefile("rb")
        self._wf = s.makefile("wb")
        self._mtx = threading.Lock()
        self._start_async("abci-socket-async")

    def close(self) -> None:
        self._stop_async()
        try:
            self._sock.close()
        except OSError:
            pass

    def _call(self, req):
        from cometbft_tpu.abci import wire as abci_wire

        with self._mtx:
            abci_wire.write_message(self._wf, abci_wire.encode_request(req))
            abci_wire.write_message(
                self._wf, abci_wire.encode_request(abci.RequestFlush())
            )
            self._wf.flush()
            data = abci_wire.read_message(self._rf)
            if data is None:
                raise ConnectionError("ABCI app closed the connection")
            resp = abci_wire.decode_response(data)
            flush = abci_wire.read_message(self._rf)
            if flush is None:
                raise ConnectionError("ABCI app closed the connection mid-flush")
        if isinstance(resp, abci.ResponseException):
            raise RuntimeError(f"ABCI app exception: {resp.error}")
        return resp

    def _do_check_tx(self, req):
        return self._call(req)

    def echo(self, msg: str):
        return self._call(abci.RequestEcho(message=msg))

    def flush(self) -> None:
        self._call(abci.RequestFlush())

    def info(self, req):
        return self._call(req)

    def init_chain(self, req):
        return self._call(req)

    def query(self, req):
        return self._call(req)

    def check_tx(self, req):
        return self._call(req)

    def begin_block(self, req):
        return self._call(req)

    def deliver_tx(self, req):
        return self._call(req)

    def end_block(self, req):
        return self._call(req)

    def commit(self):
        return self._call(abci.RequestCommit())

    def prepare_proposal(self, req):
        return self._call(req)

    def process_proposal(self, req):
        return self._call(req)

    def list_snapshots(self, req):
        return self._call(req)

    def offer_snapshot(self, req):
        return self._call(req)

    def load_snapshot_chunk(self, req):
        return self._call(req)

    def apply_snapshot_chunk(self, req):
        return self._call(req)


class ClientCreator:
    """proxy.ClientCreator (proxy/client.go): builds clients per connection."""

    def new_abci_client(self) -> Client:
        raise NotImplementedError


class LocalClientCreator(ClientCreator):
    """One shared mutex across all four connections (proxy/client.go
    NewLocalClientCreator)."""

    def __init__(self, app: abci.Application):
        self._app = app
        self._mtx = threading.RLock()

    def new_abci_client(self) -> Client:
        return LocalClient(self._app, self._mtx)


class SocketClientCreator(ClientCreator):
    """proxy/client.go NewRemoteClientCreator: one fresh socket connection
    per logical app connection."""

    def __init__(self, addr: str):
        self._addr = addr

    def new_abci_client(self) -> Client:
        return SocketClient(self._addr)

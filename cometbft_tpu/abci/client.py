"""ABCI clients (reference: abci/client/).

LocalClient mirrors abci/client/local_client.go:356 — in-process calls to
the Application behind one shared mutex (the application sees requests from
the four logical connections serialized exactly as in the reference).
"""

from __future__ import annotations

import threading

from cometbft_tpu.abci import types as abci


class Client:
    """Sync client surface used by proxy.AppConns."""

    def echo(self, msg: str) -> abci.ResponseEcho:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    def check_tx_async(self, req: abci.RequestCheckTx, callback=None):
        """Async CheckTx (mempool pipeline). The local client executes
        inline and invokes the callback synchronously — same observable
        ordering as local_client.go's CheckTxAsync."""
        raise NotImplementedError

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    def prepare_proposal(self, req: abci.RequestPrepareProposal) -> abci.ResponsePrepareProposal:
        raise NotImplementedError

    def process_proposal(self, req: abci.RequestProcessProposal) -> abci.ResponseProcessProposal:
        raise NotImplementedError

    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError


class LocalClient(Client):
    """abci/client/local_client.go: shared-mutex in-process client."""

    def __init__(self, app: abci.Application, mtx: threading.RLock | None = None):
        self._app = app
        self._mtx = mtx or threading.RLock()

    def echo(self, msg: str) -> abci.ResponseEcho:
        return abci.ResponseEcho(message=msg)

    def flush(self) -> None:
        return None

    def info(self, req):
        with self._mtx:
            return self._app.info(req)

    def init_chain(self, req):
        with self._mtx:
            return self._app.init_chain(req)

    def query(self, req):
        with self._mtx:
            return self._app.query(req)

    def check_tx(self, req):
        with self._mtx:
            return self._app.check_tx(req)

    def check_tx_async(self, req, callback=None):
        with self._mtx:
            res = self._app.check_tx(req)
        if callback is not None:
            callback(res)
        return res

    def begin_block(self, req):
        with self._mtx:
            return self._app.begin_block(req)

    def deliver_tx(self, req):
        with self._mtx:
            return self._app.deliver_tx(req)

    def end_block(self, req):
        with self._mtx:
            return self._app.end_block(req)

    def commit(self):
        with self._mtx:
            return self._app.commit()

    def prepare_proposal(self, req):
        with self._mtx:
            return self._app.prepare_proposal(req)

    def process_proposal(self, req):
        with self._mtx:
            return self._app.process_proposal(req)

    def list_snapshots(self, req):
        with self._mtx:
            return self._app.list_snapshots(req)

    def offer_snapshot(self, req):
        with self._mtx:
            return self._app.offer_snapshot(req)

    def load_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req):
        with self._mtx:
            return self._app.apply_snapshot_chunk(req)


class ClientCreator:
    """proxy.ClientCreator (proxy/client.go): builds clients per connection."""

    def new_abci_client(self) -> Client:
        raise NotImplementedError


class LocalClientCreator(ClientCreator):
    """One shared mutex across all four connections (proxy/client.go
    NewLocalClientCreator)."""

    def __init__(self, app: abci.Application):
        self._app = app
        self._mtx = threading.RLock()

    def new_abci_client(self) -> Client:
        return LocalClient(self._app, self._mtx)

"""abci-cli: exercise an ABCI server from the command line
(reference: abci/cmd/abci-cli/abci-cli.go).

Batch mode:   python -m cometbft_tpu.abci.cli --addr tcp://... echo hello
Console mode: python -m cometbft_tpu.abci.cli --addr tcp://... console

Commands: echo <msg> | info | deliver_tx <tx> | check_tx <tx> | commit |
query <key> | prepare_proposal <tx>... | process_proposal <tx>... — tx/key
accept 0xHEX or raw strings, like the reference's parsing."""

from __future__ import annotations

import argparse
import shlex
import sys

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import SocketClient


def _arg_bytes(s: str) -> bytes:
    if s.startswith("0x"):
        return bytes.fromhex(s[2:])
    return s.encode()


def _print_resp(resp) -> None:
    pairs = []
    for k in ("code", "log", "info", "message", "data", "value", "key", "height"):
        v = getattr(resp, k, None)
        if v in (None, "", b"", 0) and k != "code":
            continue
        if isinstance(v, bytes):
            v = "0x" + v.hex().upper()
        pairs.append(f"{k}: {v}")
    if hasattr(resp, "txs"):
        pairs.append(f"txs: {[t.decode('utf-8', 'replace') for t in resp.txs]}")
    if hasattr(resp, "status"):
        pairs.append(f"status: {resp.status}")
    print("-> " + "\n-> ".join(pairs or [type(resp).__name__]))


_NEEDS_ARG = {"deliver_tx", "check_tx", "query"}


def run_command(client: SocketClient, parts: list[str]) -> int:
    cmd, args = parts[0], parts[1:]
    if cmd in _NEEDS_ARG and not args:
        print(f"usage: {cmd} <arg>", file=sys.stderr)
        return 1
    if cmd == "echo":
        _print_resp(client.echo(args[0] if args else ""))
    elif cmd == "info":
        _print_resp(client.info(abci.RequestInfo(version="abci-cli")))
    elif cmd == "deliver_tx":
        _print_resp(client.deliver_tx(abci.RequestDeliverTx(tx=_arg_bytes(args[0]))))
    elif cmd == "check_tx":
        _print_resp(client.check_tx(abci.RequestCheckTx(tx=_arg_bytes(args[0]))))
    elif cmd == "commit":
        _print_resp(client.commit())
    elif cmd == "query":
        _print_resp(
            client.query(abci.RequestQuery(path="/store", data=_arg_bytes(args[0])))
        )
    elif cmd == "prepare_proposal":
        _print_resp(
            client.prepare_proposal(
                abci.RequestPrepareProposal(
                    max_tx_bytes=1 << 20, txs=[_arg_bytes(a) for a in args]
                )
            )
        )
    elif cmd == "process_proposal":
        _print_resp(
            client.process_proposal(
                abci.RequestProcessProposal(txs=[_arg_bytes(a) for a in args])
            )
        )
    elif cmd in ("help", "?"):
        print(__doc__)
    else:
        print(f"unknown command {cmd!r} (try help)", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="abci-cli")
    p.add_argument("--addr", default="tcp://127.0.0.1:26658")
    p.add_argument(
        "--transport",
        choices=["socket", "grpc"],
        default=None,
        help="defaults to socket, or grpc when --addr is grpc://",
    )
    p.add_argument("command", nargs="*", help="command, or 'console'")
    args = p.parse_args(argv)
    transport = args.transport or (
        "grpc" if args.addr.startswith("grpc://") else "socket"
    )
    if transport == "grpc":
        from cometbft_tpu.abci.grpc import GrpcClient

        client = GrpcClient(args.addr, connect_timeout=5.0)
    else:
        client = SocketClient(args.addr, connect_timeout=5.0)
    try:
        if not args.command or args.command[0] == "console":
            print(f"connected to {args.addr}; 'help' for commands, ctrl-d to exit")
            while True:
                try:
                    line = input("> ")
                except EOFError:
                    return 0
                parts = shlex.split(line)
                if parts:
                    run_command(client, parts)
        return run_command(client, args.command)
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())

"""ABCI request/response types + Application interface
(reference: abci/types/application.go, proto/tendermint/abci/types.proto).

Dataclasses mirror the proto schema field-for-field; see wire.py for the
socket serialization. Code 0 means OK everywhere (abci/types/result.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

CODE_TYPE_OK = 0

# CheckTxType (abci.proto CheckTxType)
CHECK_TX_TYPE_NEW = 0
CHECK_TX_TYPE_RECHECK = 1

# ResponseOfferSnapshot.Result / ResponseApplySnapshotChunk.Result
OFFER_SNAPSHOT_UNKNOWN = 0
OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5

APPLY_CHUNK_UNKNOWN = 0
APPLY_CHUNK_ACCEPT = 1
APPLY_CHUNK_ABORT = 2
APPLY_CHUNK_RETRY = 3
APPLY_CHUNK_RETRY_SNAPSHOT = 4
APPLY_CHUNK_REJECT_SNAPSHOT = 5

# ProcessProposal status (abci.proto ResponseProcessProposal.ProposalStatus)
PROCESS_PROPOSAL_UNKNOWN = 0
PROCESS_PROPOSAL_ACCEPT = 1
PROCESS_PROPOSAL_REJECT = 2


@dataclass
class EventAttribute:
    key: str = ""
    value: str = ""
    index: bool = False


@dataclass
class Event:
    type: str = ""
    attributes: list = dfield(default_factory=list)


@dataclass
class ValidatorUpdate:
    """abci.ValidatorUpdate: proto PublicKey bytes + power."""

    pub_key: object = None  # crypto PubKey
    power: int = 0


@dataclass
class CommitInfo:
    """abci.LastCommitInfo: who signed the last block (for incentives)."""

    round: int = 0
    votes: list = dfield(default_factory=list)  # list[VoteInfo]


@dataclass
class VoteInfo:
    validator_address: bytes = b""
    validator_power: int = 0
    signed_last_block: bool = False


@dataclass
class Misbehavior:
    """abci.Misbehavior (evidence forwarded to the app)."""

    type: int = 0  # 0 unknown, 1 duplicate vote, 2 light client attack
    validator_address: bytes = b""
    validator_power: int = 0
    height: int = 0
    time_seconds: int = 0
    total_voting_power: int = 0


MISBEHAVIOR_DUPLICATE_VOTE = 1
MISBEHAVIOR_LIGHT_CLIENT_ATTACK = 2


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


# -- requests ----------------------------------------------------------------


@dataclass
class RequestEcho:
    message: str = ""


@dataclass
class RequestFlush:
    pass


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0
    abci_version: str = ""


@dataclass
class RequestInitChain:
    time_seconds: int = 0
    chain_id: str = ""
    consensus_params: object = None
    validators: list = dfield(default_factory=list)  # list[ValidatorUpdate]
    app_state_bytes: bytes = b""
    initial_height: int = 0


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: object = None  # types.Header
    last_commit_info: CommitInfo = dfield(default_factory=CommitInfo)
    byzantine_validators: list = dfield(default_factory=list)


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type: int = CHECK_TX_TYPE_NEW


@dataclass
class RequestDeliverTx:
    tx: bytes = b""


@dataclass
class RequestEndBlock:
    height: int = 0


@dataclass
class RequestCommit:
    pass


@dataclass
class RequestListSnapshots:
    pass


@dataclass
class RequestOfferSnapshot:
    snapshot: Snapshot | None = None
    app_hash: bytes = b""


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


@dataclass
class RequestPrepareProposal:
    max_tx_bytes: int = 0
    txs: list = dfield(default_factory=list)
    local_last_commit: CommitInfo = dfield(default_factory=CommitInfo)
    misbehavior: list = dfield(default_factory=list)
    height: int = 0
    time_seconds: int = 0
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class RequestProcessProposal:
    txs: list = dfield(default_factory=list)
    proposed_last_commit: CommitInfo = dfield(default_factory=CommitInfo)
    misbehavior: list = dfield(default_factory=list)
    hash: bytes = b""
    height: int = 0
    time_seconds: int = 0
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


# -- responses ---------------------------------------------------------------


@dataclass
class ResponseException:
    error: str = ""


@dataclass
class ResponseEcho:
    message: str = ""


@dataclass
class ResponseFlush:
    pass


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseInitChain:
    consensus_params: object = None
    validators: list = dfield(default_factory=list)
    app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = 0
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: list = dfield(default_factory=list)
    height: int = 0
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseBeginBlock:
    events: list = dfield(default_factory=list)


@dataclass
class ResponseCheckTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list = dfield(default_factory=list)
    codespace: str = ""
    sender: str = ""
    priority: int = 0
    mempool_error: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseDeliverTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list = dfield(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseEndBlock:
    validator_updates: list = dfield(default_factory=list)
    consensus_param_updates: object = None
    events: list = dfield(default_factory=list)


@dataclass
class ResponseCommit:
    data: bytes = b""  # the AppHash
    retain_height: int = 0


@dataclass
class ResponseListSnapshots:
    snapshots: list = dfield(default_factory=list)


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_UNKNOWN


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_CHUNK_UNKNOWN
    refetch_chunks: list = dfield(default_factory=list)
    reject_senders: list = dfield(default_factory=list)


@dataclass
class ResponsePrepareProposal:
    txs: list = dfield(default_factory=list)


@dataclass
class ResponseProcessProposal:
    status: int = PROCESS_PROPOSAL_UNKNOWN

    def is_accepted(self) -> bool:
        return self.status == PROCESS_PROPOSAL_ACCEPT


class Application:
    """The 14-method application interface (abci/types/application.go:13-35).
    Subclass and override; the base returns empty/OK responses (BaseApplication)."""

    # Info/Query connection
    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery(code=CODE_TYPE_OK)

    # Mempool connection
    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx(code=CODE_TYPE_OK)

    # Consensus connection
    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def prepare_proposal(self, req: RequestPrepareProposal) -> ResponsePrepareProposal:
        """Default: include txs unchanged up to max_tx_bytes
        (abci/types/application.go BaseApplication.PrepareProposal)."""
        total = 0
        out = []
        for tx in req.txs:
            total += len(tx) + 5
            if req.max_tx_bytes > 0 and total > req.max_tx_bytes:
                break
            out.append(tx)
        return ResponsePrepareProposal(txs=out)

    def process_proposal(self, req: RequestProcessProposal) -> ResponseProcessProposal:
        return ResponseProcessProposal(status=PROCESS_PROPOSAL_ACCEPT)

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock:
        return ResponseBeginBlock()

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx:
        return ResponseDeliverTx(code=CODE_TYPE_OK)

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    # State-sync connection
    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots:
        return ResponseListSnapshots()

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot()

    def load_snapshot_chunk(self, req: RequestLoadSnapshotChunk) -> ResponseLoadSnapshotChunk:
        return ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req: RequestApplySnapshotChunk) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk()

    # Echo (connection handshake)
    def echo(self, req: RequestEcho) -> ResponseEcho:
        return ResponseEcho(message=req.message)

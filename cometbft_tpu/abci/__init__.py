"""ABCI — the application blockchain interface (reference: abci/, 5,380 LoC).

The boundary between consensus middleware and the replicated application:
14 methods over 4 logical connections (consensus, mempool, query, snapshot)
per abci/types/application.go:13-35 (ABCI 1.0).
"""

"""In-memory kvstore application (reference: abci/example/kvstore/kvstore.go)
plus the persistent variant with validator updates
(persistent_kvstore.go: "val:pubkeybase64!power" txs).
"""

from __future__ import annotations

import base64
import json

from cometbft_tpu.abci import types as abci
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.libs.db import DB, MemDB

_STATE_KEY = b"stateKey"
_KV_PAIR_PREFIX = b"kvPairKey:"

VALIDATOR_TX_PREFIX = "val:"

CODE_TYPE_OK = 0
CODE_TYPE_ENCODING_ERROR = 1
CODE_TYPE_BAD_NONCE = 2
CODE_TYPE_UNAUTHORIZED = 3
CODE_TYPE_EXECUTED = 5
CODE_TYPE_REJECTED = 6


def _put_varint_8(v: int) -> bytes:
    """Go binary.PutVarint into an 8-byte buffer (kvstore.go Commit)."""
    uv = (v << 1) if v >= 0 else ((-v) << 1) - 1
    out = bytearray()
    while True:
        b = uv & 0x7F
        uv >>= 7
        if uv:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    out.extend(b"\x00" * (8 - len(out)))
    return bytes(out[:8])


class KVStoreApplication(abci.Application):
    """abci/example/kvstore/kvstore.go: tx is "key=value" or raw bytes;
    AppHash = varint(size) in 8 bytes."""

    def __init__(
        self,
        db: DB | None = None,
        retain_blocks: int = 0,
        snapshot_interval: int = 0,
        snapshot_chunk_size: int = 65536,
        provable: bool = False,
    ):
        self.db = db or MemDB()
        self.retain_blocks = retain_blocks
        # Provable mode: AppHash is the SimpleMap Merkle root over the kv
        # pairs and /store queries answer with ValueOp proofs — what the
        # light proxy's verified abci_query needs (light/rpc/client.go:166;
        # the reference kvstore itself doesn't prove, its e2e app does).
        self.provable = provable
        # State-sync snapshots (reference: test/e2e/app/app.go:22-60 — the
        # purpose-built e2e app is the one that snapshots; plain kvstore.go
        # doesn't). Off unless snapshot_interval > 0.
        self.snapshot_interval = snapshot_interval
        self.snapshot_chunk_size = snapshot_chunk_size
        self._snapshots: dict[tuple, tuple[abci.Snapshot, list[bytes]]] = {}
        self._restore: tuple[abci.Snapshot, list] | None = None
        self._tx_to_remove: set[bytes] = set()
        st = self.db.get(_STATE_KEY)
        if st:
            d = json.loads(st)
            self.size = d["size"]
            self.height = d["height"]
            self.app_hash = base64.b64decode(d["app_hash"]) if d["app_hash"] else b""
        else:
            self.size = 0
            self.height = 0
            self.app_hash = b""

    def _save_state(self) -> None:
        self.db.set(
            _STATE_KEY,
            json.dumps(
                {
                    "size": self.size,
                    "height": self.height,
                    "app_hash": base64.b64encode(self.app_hash).decode(),
                }
            ).encode(),
        )

    def info(self, req):
        return abci.ResponseInfo(
            data=json.dumps({"size": self.size}),
            version="1.0.0",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, req):
        if len(req.tx) == 0:
            return abci.ResponseCheckTx(code=CODE_TYPE_REJECTED)
        if req.type == abci.CHECK_TX_TYPE_RECHECK and req.tx in self._tx_to_remove:
            return abci.ResponseCheckTx(code=CODE_TYPE_EXECUTED, gas_wanted=1)
        return abci.ResponseCheckTx(code=CODE_TYPE_OK, gas_wanted=1)

    def begin_block(self, req):
        self._tx_to_remove = set()
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req):
        parts = req.tx.split(b"=", 1)
        if len(parts) == 2:
            key, value = parts
        else:
            key = value = req.tx
        self.db.set(_KV_PAIR_PREFIX + key, value)
        self.size += 1
        events = [
            abci.Event(
                type="app",
                attributes=[
                    abci.EventAttribute("creator", "Cosmoshi Netowoko", True),
                    abci.EventAttribute("key", key.decode("utf-8", "replace"), True),
                    abci.EventAttribute("index_key", "index is working", True),
                    abci.EventAttribute("noindex_key", "index is working", False),
                ],
            )
        ]
        return abci.ResponseDeliverTx(code=CODE_TYPE_OK, events=events)

    def process_proposal(self, req):
        for tx in req.txs:
            if len(tx) == 0:
                return abci.ResponseProcessProposal(status=abci.PROCESS_PROPOSAL_REJECT)
        return abci.ResponseProcessProposal(status=abci.PROCESS_PROPOSAL_ACCEPT)

    def commit(self):
        if self.provable:
            from cometbft_tpu.crypto.merkle import hash_from_byte_slices

            app_hash = hash_from_byte_slices(self._kv_leaves()[1])
        else:
            app_hash = _put_varint_8(self.size)
        self.app_hash = app_hash
        self.height += 1
        self._save_state()
        if self.snapshot_interval and self.height % self.snapshot_interval == 0:
            self._take_snapshot()
        resp = abci.ResponseCommit(data=app_hash)
        if self.retain_blocks > 0 and self.height >= self.retain_blocks:
            resp.retain_height = self.height - self.retain_blocks + 1
        return resp

    # -- state-sync snapshots (test/e2e/app/snapshots.go shape) ---------------

    def _snapshot_blob(self) -> bytes:
        pairs = {}
        for k, v in self.db.iterator():
            if k.startswith(_KV_PAIR_PREFIX):
                pairs[base64.b64encode(k[len(_KV_PAIR_PREFIX):]).decode()] = (
                    base64.b64encode(v).decode()
                )
        return json.dumps(
            {
                "height": self.height,
                "size": self.size,
                "app_hash": base64.b64encode(self.app_hash).decode(),
                "pairs": pairs,
            },
            sort_keys=True,
        ).encode()

    def _take_snapshot(self) -> None:
        import hashlib

        blob = self._snapshot_blob()
        cs = self.snapshot_chunk_size
        chunks = [blob[i : i + cs] for i in range(0, len(blob), cs)] or [b""]
        snap = abci.Snapshot(
            height=self.height,
            format=1,
            chunks=len(chunks),
            hash=hashlib.sha256(blob).digest(),
        )
        self._snapshots[(snap.height, snap.format)] = (snap, chunks)

    def list_snapshots(self, req):
        return abci.ResponseListSnapshots(
            snapshots=[s for s, _ in self._snapshots.values()]
        )

    def load_snapshot_chunk(self, req):
        entry = self._snapshots.get((req.height, req.format))
        if entry is None or not (0 <= req.chunk < len(entry[1])):
            return abci.ResponseLoadSnapshotChunk()
        return abci.ResponseLoadSnapshotChunk(chunk=entry[1][req.chunk])

    def offer_snapshot(self, req):
        snap = req.snapshot
        if snap is None or snap.format != 1 or snap.chunks < 1:
            return abci.ResponseOfferSnapshot(
                result=abci.OFFER_SNAPSHOT_REJECT_FORMAT
            )
        self._restore = (snap, [None] * snap.chunks)
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, req):
        import hashlib

        if self._restore is None:
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_REJECT_SNAPSHOT
            )
        snap, chunks = self._restore
        if not (0 <= req.index < len(chunks)):
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_RETRY)
        chunks[req.index] = req.chunk
        if any(c is None for c in chunks):
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)
        blob = b"".join(chunks)
        if hashlib.sha256(blob).digest() != snap.hash:
            # Whole snapshot is bad: refetch everything, drop the senders.
            self._restore = (snap, [None] * len(chunks))
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY_SNAPSHOT,
                refetch_chunks=list(range(len(chunks))),
            )
        d = json.loads(blob)
        for k, v in d["pairs"].items():
            self.db.set(
                _KV_PAIR_PREFIX + base64.b64decode(k), base64.b64decode(v)
            )
        self.height = d["height"]
        self.size = d["size"]
        self.app_hash = base64.b64decode(d["app_hash"])
        self._save_state()
        self._restore = None
        return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)

    def query(self, req):
        value = self.db.get(_KV_PAIR_PREFIX + req.data)
        resp = abci.ResponseQuery(
            code=CODE_TYPE_OK,
            key=req.data,
            value=value or b"",
            log="exists" if value is not None else "does not exist",
            height=self.height,
        )
        if req.prove and self.provable and value is not None:
            resp.proof_ops = self._prove(req.data)
        return resp

    # -- provable-state helpers ------------------------------------------------

    def _kv_leaves(self) -> tuple[list[bytes], list[bytes]]:
        """Sorted keys and their SimpleMap leaf encodings
        (crypto/merkle KVPair form: len-prefixed key || len-prefixed
        SHA256(value) — the shape ValueOp.run reconstructs)."""
        import hashlib

        from cometbft_tpu.wire.proto import encode_bytes_len_prefixed

        items = []
        for k, v in self.db.iterator(_KV_PAIR_PREFIX, _KV_PAIR_PREFIX + b"\xff"):
            items.append((k[len(_KV_PAIR_PREFIX):], v))
        items.sort()
        keys = [k for k, _ in items]
        leaves = [
            encode_bytes_len_prefixed(k)
            + encode_bytes_len_prefixed(hashlib.sha256(v).digest())
            for k, v in items
        ]
        return keys, leaves

    def _prove(self, key: bytes) -> list:
        from cometbft_tpu.crypto.merkle import proofs_from_byte_slices
        from cometbft_tpu.crypto.merkle.proof_value import ValueOp

        keys, leaves = self._kv_leaves()
        try:
            idx = keys.index(key)
        except ValueError:
            return []
        _, proofs = proofs_from_byte_slices(leaves)
        return [ValueOp(key, proofs[idx]).proof_op()]


class PersistentKVStoreApplication(KVStoreApplication):
    """abci/example/kvstore/persistent_kvstore.go: adds validator-set changes
    driven by "val:base64(pubkey)!power" transactions."""

    def __init__(self, db: DB | None = None, **kwargs):
        super().__init__(db, **kwargs)
        self._val_updates: list[abci.ValidatorUpdate] = []
        self._validators: dict[bytes, int] = {}  # pubkey bytes -> power
        raw = self.db.get(b"validatorsKey")
        if raw:
            self._validators = {
                base64.b64decode(k): v for k, v in json.loads(raw).items()
            }

    def _save_validators(self) -> None:
        self.db.set(
            b"validatorsKey",
            json.dumps(
                {base64.b64encode(k).decode(): v for k, v in self._validators.items()}
            ).encode(),
        )

    def init_chain(self, req):
        for vu in req.validators:
            self._validators[vu.pub_key.bytes()] = vu.power
        self._save_validators()
        return abci.ResponseInitChain()

    def begin_block(self, req):
        self._val_updates = []
        return super().begin_block(req)

    def deliver_tx(self, req):
        if req.tx.startswith(VALIDATOR_TX_PREFIX.encode()):
            return self._exec_validator_tx(req.tx)
        return super().deliver_tx(req)

    def _exec_validator_tx(self, tx: bytes):
        body = tx[len(VALIDATOR_TX_PREFIX) :]
        parts = body.split(b"!")
        if len(parts) != 2:
            return abci.ResponseDeliverTx(
                code=CODE_TYPE_ENCODING_ERROR,
                log="expected 'pubkeyB64!power'",
            )
        try:
            pub_bytes = base64.b64decode(parts[0])
            power = int(parts[1])
        except Exception:
            return abci.ResponseDeliverTx(
                code=CODE_TYPE_ENCODING_ERROR, log="malformed validator tx"
            )
        pub = ed25519.PubKey(pub_bytes)
        if power == 0 and pub_bytes not in self._validators:
            return abci.ResponseDeliverTx(
                code=CODE_TYPE_UNAUTHORIZED,
                log="cannot remove non-existent validator",
            )
        if power == 0:
            self._validators.pop(pub_bytes, None)
        else:
            self._validators[pub_bytes] = power
        self._save_validators()
        self._val_updates.append(abci.ValidatorUpdate(pub_key=pub, power=power))
        return abci.ResponseDeliverTx(code=CODE_TYPE_OK)

    def end_block(self, req):
        return abci.ResponseEndBlock(validator_updates=list(self._val_updates))

    def query(self, req):
        if req.path == "/val":
            power = self._validators.get(req.data, 0)
            return abci.ResponseQuery(
                code=CODE_TYPE_OK, key=req.data, value=str(power).encode()
            )
        return super().query(req)

"""Example ABCI applications (reference: abci/example/)."""

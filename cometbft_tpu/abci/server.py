"""ABCI socket server: run an Application in its own OS process and serve
the node over unix/TCP (reference: abci/server/socket_server.go:267
handleRequests + the acceptConnectionsRoutine at :107).

The node opens four logical connections (consensus/mempool/query/snapshot);
each gets its own handler thread here, all funneled through ONE application
mutex — the same serialization the reference enforces via the shared
local-client mutex and per-connection goroutines.
"""

from __future__ import annotations

import os
import socket
import threading

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci import wire as abci_wire


def parse_addr(addr: str) -> tuple[str, object]:
    """'tcp://host:port' or 'unix://path' -> (scheme, bind target)."""
    if addr.startswith("unix://"):
        return "unix", addr[len("unix://"):]
    hostport = addr.split("://", 1)[-1]
    host, _, port = hostport.rpartition(":")
    return "tcp", (host or "127.0.0.1", int(port))


class ABCIServer:
    """abci/server/socket_server.go SocketServer."""

    def __init__(self, app: abci.Application, addr: str):
        self.app = app
        self.addr = addr
        self._mtx = threading.Lock()
        self._listener: socket.socket | None = None
        self._conns: list[socket.socket] = []
        self._running = False

    def start(self) -> str:
        scheme, target = parse_addr(self.addr)
        if scheme == "unix":
            if os.path.exists(target):
                os.unlink(target)
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(target)
            self.bound = f"unix://{target}"
        else:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind(target)
            self.bound = f"tcp://{target[0]}:{ls.getsockname()[1]}"
        ls.listen(16)
        self._listener = ls
        self._running = True
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self.bound

    def stop(self) -> None:
        self._running = False
        if self._listener:
            try:
                self._listener.close()
            except OSError:
                pass
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        """socket_server.go:267 handleRequests: read loop; responses written
        in order; Flush drains the buffered writer."""
        rf = conn.makefile("rb")
        wf = conn.makefile("wb")
        try:
            while self._running:
                data = abci_wire.read_message(rf)
                if data is None:
                    return
                req = None
                try:
                    req = abci_wire.decode_request(data)
                    resp = self._dispatch(req)
                except Exception as e:  # ResponseException, like the reference
                    resp = abci.ResponseException(error=str(e))
                abci_wire.write_message(wf, abci_wire.encode_response(resp))
                if req is None or isinstance(req, abci.RequestFlush):
                    wf.flush()
        except (OSError, EOFError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, req):
        with self._mtx:
            return dispatch_request(self.app, req)


def dispatch_request(a: abci.Application, req):
    """Route one decoded ABCI request to the Application — shared by the
    socket and gRPC servers (the reference duplicates this shape in
    socket_server.go handleRequest and types/application.go
    GRPCApplication). The caller holds whatever serialization lock it wants."""
    t = type(req).__name__
    if t == "RequestEcho":
        return abci.ResponseEcho(message=req.message)
    if t == "RequestFlush":
        return abci.ResponseFlush()
    if t == "RequestInfo":
        return a.info(req)
    if t == "RequestInitChain":
        return a.init_chain(req)
    if t == "RequestQuery":
        return a.query(req)
    if t == "RequestCheckTx":
        return a.check_tx(req)
    if t == "RequestBeginBlock":
        return a.begin_block(req)
    if t == "RequestDeliverTx":
        return a.deliver_tx(req)
    if t == "RequestEndBlock":
        return a.end_block(req)
    if t == "RequestCommit":
        return a.commit()
    if t == "RequestPrepareProposal":
        return a.prepare_proposal(req)
    if t == "RequestProcessProposal":
        return a.process_proposal(req)
    if t == "RequestListSnapshots":
        return a.list_snapshots(req)
    if t == "RequestOfferSnapshot":
        return a.offer_snapshot(req)
    if t == "RequestLoadSnapshotChunk":
        return a.load_snapshot_chunk(req)
    if t == "RequestApplySnapshotChunk":
        return a.apply_snapshot_chunk(req)
    raise ValueError(f"unknown request {t}")


def main(argv=None) -> int:
    """`python -m cometbft_tpu.abci.server kvstore --addr tcp://...`: the
    abci-cli-style standalone app server used by the process-boundary tests
    and external deployments."""
    import argparse
    import time

    p = argparse.ArgumentParser(prog="cometbft_tpu.abci.server")
    p.add_argument("app", choices=["kvstore", "persistent_kvstore", "noop"])
    p.add_argument("--addr", default="tcp://127.0.0.1:26658")
    p.add_argument(
        "--transport",
        choices=["socket", "grpc"],
        default="socket",
        help="process-boundary transport (abci-cli --abci flag analog)",
    )
    p.add_argument("--snapshot-interval", type=int, default=0)
    args = p.parse_args(argv)
    if args.app == "kvstore":
        from cometbft_tpu.abci.example.kvstore import KVStoreApplication

        app = KVStoreApplication(snapshot_interval=args.snapshot_interval)
    elif args.app == "persistent_kvstore":
        from cometbft_tpu.abci.example.kvstore import PersistentKVStoreApplication

        app = PersistentKVStoreApplication(
            snapshot_interval=args.snapshot_interval
        )
    else:
        app = abci.Application()
    if args.transport == "grpc":
        from cometbft_tpu.abci.grpc import GrpcServer

        srv = GrpcServer(app, args.addr)
    else:
        srv = ABCIServer(app, args.addr)
    bound = srv.start()
    print(f"ABCI server ({args.app}) listening on {bound}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

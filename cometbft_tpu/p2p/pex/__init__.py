"""Peer exchange: discovery reactor + address book
(reference: p2p/pex/pex_reactor.go, p2p/pex/addrbook.go)."""

from cometbft_tpu.p2p.pex.addrbook import AddrBook, NetAddress
from cometbft_tpu.p2p.pex.reactor import PEX_CHANNEL, PexReactor

__all__ = ["AddrBook", "NetAddress", "PexReactor", "PEX_CHANNEL"]

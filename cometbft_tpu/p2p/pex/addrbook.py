"""Address book: bucketed peer-address store with JSON persistence
(reference: p2p/pex/addrbook.go — 947 LoC; same new/old bucket design,
group-key hashing, good/bad promotion, and biased sampling, without the
amortized-iteration micro-structures Go needs for its GC profile).

New addresses land in one of 256 "new" buckets keyed by
hash(key || src-group || bucket#); addresses that survive a successful
connection are promoted to one of 64 "old" buckets. pick_address samples
new vs old with a configurable bias, like addrbook.go:368.
"""

from __future__ import annotations

import hashlib
import ipaddress
import json
import os
import random
import threading
import time
from dataclasses import dataclass, field as dfield

NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
NEW_BUCKET_SIZE = 64
OLD_BUCKET_SIZE = 64
MAX_NEW_BUCKETS_PER_ADDRESS = 4


@dataclass
class NetAddress:
    """p2p/netaddress.go NetAddress: id@ip:port."""

    id: str = ""
    ip: str = ""
    port: int = 0

    @classmethod
    def parse(cls, addr: str) -> "NetAddress":
        if "@" not in addr:
            raise ValueError(f"address {addr!r} missing id@")
        node_id, hostport = addr.split("@", 1)
        host, _, port = hostport.rpartition(":")
        return cls(id=node_id.lower(), ip=host, port=int(port))

    def dial_string(self) -> str:
        return f"{self.id}@{self.ip}:{self.port}"

    def routable(self) -> bool:
        """netaddress.go Routable: valid and not in a reserved range."""
        try:
            ip = ipaddress.ip_address(self.ip)
        except ValueError:
            return False
        return not (
            ip.is_loopback
            or ip.is_private
            or ip.is_link_local
            or ip.is_multicast
            or ip.is_unspecified
        )

    def valid(self) -> bool:
        if not self.id or self.port <= 0 or self.port > 65535:
            return False
        try:
            ipaddress.ip_address(self.ip)
        except ValueError:
            return False
        return True

    def group_key(self) -> str:
        """addrbook.go groupKey: /16 for IPv4 — dials spread across groups."""
        try:
            ip = ipaddress.ip_address(self.ip)
        except ValueError:
            return "unroutable"
        if not self.routable():
            return "unroutable"
        if ip.version == 4:
            return ".".join(self.ip.split(".")[:2])
        return self.ip[:10]


@dataclass
class _KnownAddress:
    """addrbook.go knownAddress."""

    addr: NetAddress
    src_id: str = ""
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket_type: str = "new"  # "new" | "old"
    buckets: list = dfield(default_factory=list)

    def is_bad(self, now: float) -> bool:
        """addrbook.go isBad: too many failed attempts recently."""
        if self.bucket_type == "old":
            return False
        if self.attempts >= 3 and self.last_success == 0:
            return True
        return self.attempts >= 10


class AddrBook:
    """p2p/pex/addrbook.go addrBook."""

    def __init__(self, file_path: str = "", strict: bool = True, key: bytes | None = None):
        self.file_path = file_path
        self.strict = strict  # strict routability (False for loopback tests)
        self._key = key or os.urandom(24)
        self._addrs: dict[str, _KnownAddress] = {}
        self._new_buckets: list[set] = [set() for _ in range(NEW_BUCKET_COUNT)]
        self._old_buckets: list[set] = [set() for _ in range(OLD_BUCKET_COUNT)]
        self._our_ids: set[str] = set()
        self._private_ids: set[str] = set()
        self._mtx = threading.RLock()
        self._rand = random.Random()
        if file_path and os.path.exists(file_path):
            self.load(file_path)

    # -- identity filters -----------------------------------------------------

    def add_our_address(self, node_id: str) -> None:
        with self._mtx:
            self._our_ids.add(node_id.lower())

    def add_private_ids(self, ids: list[str]) -> None:
        with self._mtx:
            self._private_ids.update(i.lower() for i in ids)

    # -- core -----------------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return len(self._addrs)

    def is_empty(self) -> bool:
        return self.size() == 0

    def need_more_addrs(self) -> bool:
        """addrbook.go NeedMoreAddrs: < 1000 known."""
        return self.size() < 1000

    def _bucket_index_new(self, addr: NetAddress, src_group: str) -> int:
        h = hashlib.sha256(
            self._key + addr.id.encode() + b"|" + src_group.encode()
        ).digest()
        return int.from_bytes(h[:8], "big") % NEW_BUCKET_COUNT

    def _bucket_index_old(self, addr: NetAddress) -> int:
        h = hashlib.sha256(
            self._key + addr.id.encode() + b"|" + addr.group_key().encode()
        ).digest()
        return int.from_bytes(h[:8], "big") % OLD_BUCKET_COUNT

    def add_address(self, addr: NetAddress, src: NetAddress | None = None) -> bool:
        """addrbook.go AddAddress: new addresses go to a new bucket chosen by
        (addr, source group). Returns True when stored."""
        if not addr.valid():
            return False
        if self.strict and not addr.routable():
            return False
        with self._mtx:
            if addr.id in self._our_ids or addr.id in self._private_ids:
                return False
            ka = self._addrs.get(addr.id)
            if ka is not None:
                if ka.bucket_type == "old":
                    return False
                if len(ka.buckets) >= MAX_NEW_BUCKETS_PER_ADDRESS:
                    return False
                # Probabilistically skip re-adding to more buckets.
                if self._rand.random() > 0.5 ** len(ka.buckets):
                    return False
            else:
                ka = _KnownAddress(addr=addr, src_id=src.id if src else "")
                self._addrs[addr.id] = ka
            idx = self._bucket_index_new(
                addr, src.group_key() if src else addr.group_key()
            )
            if idx not in ka.buckets:
                bucket = self._new_buckets[idx]
                if len(bucket) >= NEW_BUCKET_SIZE:
                    self._evict_new(idx)
                bucket.add(addr.id)
                ka.buckets.append(idx)
            return True

    def _evict_new(self, idx: int) -> None:
        """Drop the worst (most-attempted, oldest) entry from a full bucket."""
        bucket = self._new_buckets[idx]
        worst_id, worst_score = None, None
        for aid in bucket:
            ka = self._addrs.get(aid)
            if ka is None:
                worst_id = aid
                break
            score = (ka.attempts, -ka.last_success)
            if worst_score is None or score > worst_score:
                worst_id, worst_score = aid, score
        if worst_id is not None:
            bucket.discard(worst_id)
            ka = self._addrs.get(worst_id)
            if ka is not None:
                if idx in ka.buckets:
                    ka.buckets.remove(idx)
                if not ka.buckets:
                    del self._addrs[worst_id]

    def mark_attempt(self, addr: NetAddress) -> None:
        with self._mtx:
            ka = self._addrs.get(addr.id)
            if ka:
                ka.attempts += 1
                ka.last_attempt = time.time()

    def mark_good(self, node_id: str) -> None:
        """addrbook.go MarkGood: promote to an old bucket."""
        with self._mtx:
            ka = self._addrs.get(node_id.lower())
            if ka is None:
                return
            ka.attempts = 0
            ka.last_success = time.time()
            if ka.bucket_type == "old":
                return
            for idx in ka.buckets:
                self._new_buckets[idx].discard(node_id)
            ka.buckets = []
            ka.bucket_type = "old"
            idx = self._bucket_index_old(ka.addr)
            bucket = self._old_buckets[idx]
            if len(bucket) >= OLD_BUCKET_SIZE:
                # Demote a random old entry back to new (addrbook.go style).
                victim = self._rand.choice(sorted(bucket))
                bucket.discard(victim)
                vka = self._addrs.get(victim)
                if vka:
                    vka.bucket_type = "new"
                    vidx = self._bucket_index_new(vka.addr, vka.addr.group_key())
                    vka.buckets = [vidx]
                    self._new_buckets[vidx].add(victim)
            bucket.add(node_id)
            ka.buckets = [idx]

    def mark_bad(self, addr: NetAddress) -> None:
        """Remove entirely (addrbook.go MarkBad banishes for a duration)."""
        with self._mtx:
            self._remove(addr.id)

    def _remove(self, node_id: str) -> None:
        ka = self._addrs.pop(node_id, None)
        if ka is None:
            return
        buckets = self._old_buckets if ka.bucket_type == "old" else self._new_buckets
        for idx in ka.buckets:
            buckets[idx].discard(node_id)

    def pick_address(self, bias_towards_new: int = 30) -> NetAddress | None:
        """addrbook.go PickAddress: weighted coin between old and new, then a
        uniform sample. bias is a percentage 0..100."""
        now = time.time()
        with self._mtx:
            if not self._addrs:
                return None
            bias = max(0, min(100, bias_towards_new))
            old_ids = [a for b in self._old_buckets for a in b]
            new_ids = [a for b in self._new_buckets for a in b]
            pool = None
            if old_ids and (not new_ids or self._rand.random() * 100 >= bias):
                pool = old_ids
            elif new_ids:
                pool = new_ids
            if not pool:
                return None
            candidates = [
                self._addrs[a]
                for a in pool
                if a in self._addrs and not self._addrs[a].is_bad(now)
            ]
            if not candidates:
                return None
            return self._rand.choice(candidates).addr

    def get_selection(self, max_count: int = 30) -> list[NetAddress]:
        """addrbook.go GetSelection: a random sample (23% of book, capped) to
        answer a pex request."""
        with self._mtx:
            all_addrs = [ka.addr for ka in self._addrs.values()]
        if not all_addrs:
            return []
        n = max(1, min(max_count, (len(all_addrs) * 23) // 100 + 1))
        self._rand.shuffle(all_addrs)
        return all_addrs[:n]

    def has_address(self, node_id: str) -> bool:
        with self._mtx:
            return node_id.lower() in self._addrs

    # -- persistence (addrbook.go saveToFile/loadFromFile) ---------------------

    def save(self, path: str | None = None) -> None:
        path = path or self.file_path
        if not path:
            return
        with self._mtx:
            dump = {
                "key": self._key.hex(),
                "addrs": [
                    {
                        "addr": ka.addr.dial_string(),
                        "src": ka.src_id,
                        "attempts": ka.attempts,
                        "last_success": ka.last_success,
                        "bucket_type": ka.bucket_type,
                    }
                    for ka in self._addrs.values()
                ],
            }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dump, f)
        os.replace(tmp, path)

    def load(self, path: str) -> None:
        """Load the persisted book. The book is a peer-discovery CACHE, not
        consensus state: a corrupt file must not stop node boot (the Go
        reference errors out and operators end up deleting the file by
        hand). On corruption the file is set aside as <path>.corrupt for
        diagnosis and the node starts with an empty book."""
        try:
            with open(path) as f:
                dump = json.load(f)
            if not isinstance(dump, dict):
                raise ValueError("addrbook dump must be a JSON object")
            # Validate EVERYTHING before mutating the book: the key seeds
            # bucket placement, and adopting it from a file we then reject
            # as corrupt would let a tampered file steer bucketing.
            key = bytes.fromhex(dump.get("key", self._key.hex()))
            entries = dump.get("addrs", [])
            if not isinstance(entries, list):
                raise ValueError("addrbook addrs must be a list")
        except (ValueError, TypeError, AttributeError):
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
            return
        self._key = key
        for e in entries:
            if not isinstance(e, dict):
                continue
            try:
                addr = NetAddress.parse(e["addr"])
                attempts = int(e.get("attempts", 0))
                last_success = float(e.get("last_success", 0))
            except (ValueError, KeyError, TypeError):
                continue
            self.add_address(addr)
            ka = self._addrs.get(addr.id)
            if ka is not None:
                ka.attempts = attempts
                ka.last_success = last_success
                if e.get("bucket_type") == "old":
                    self.mark_good(addr.id)

"""PEX reactor: peer discovery over channel 0x00
(reference: p2p/pex/pex_reactor.go).

Protocol (proto tendermint.p2p.Message oneof): PexRequest (field 1, empty)
asks for addresses; PexAddrs (field 2, repeated NetAddress{id,ip,port})
answers. A peer may only send PexAddrs after we asked (unsolicited lists are
a fingerprinting/poisoning vector — pex_reactor.go:268), and may only ask at
a bounded rate (:253 receiveRequest).

ensurePeersRoutine dials book addresses until max_num_outbound_peers is
reached; in seed mode the reactor crawls (dial → exchange → disconnect) and
serves its book to inbound nodes, pex_reactor.go:39,:478 crawlPeersRoutine.
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.p2p.pex.addrbook import AddrBook, NetAddress
from cometbft_tpu.p2p.reactor import Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.wire import proto as wire

PEX_CHANNEL = 0x00


def encode_pex_request() -> bytes:
    return wire.field_message(1, b"", emit_empty=True)


def encode_pex_addrs(addrs: list[NetAddress]) -> bytes:
    body = b""
    for a in addrs:
        na = (
            wire.field_string(1, a.id)
            + wire.field_string(2, a.ip)
            + wire.field_varint(3, a.port)
        )
        body += wire.field_message(1, na, emit_empty=True)
    return wire.field_message(2, body, emit_empty=True)


def decode_pex_message(data: bytes):
    f = wire.decode_fields(data)
    if 1 in f:
        return ("request", None)
    if 2 in f:
        inner = wire.decode_fields(wire.get_bytes(f, 2))
        addrs = []
        for b in wire.get_repeated_bytes(inner, 1):
            af = wire.decode_fields(b)
            addrs.append(
                NetAddress(
                    id=wire.get_string(af, 1).lower(),
                    ip=wire.get_string(af, 2),
                    port=wire.get_uvarint(af, 3),
                )
            )
        return ("addrs", addrs)
    raise ValueError("unknown pex message")


class PexReactor(Reactor):
    """p2p/pex/pex_reactor.go Reactor."""

    def __init__(
        self,
        book: AddrBook,
        seeds: list[str] | None = None,
        seed_mode: bool = False,
        ensure_interval: float = 30.0,
        max_outbound: int = 10,
        request_interval: float = 10.0,
    ):
        super().__init__("PEX")
        self.book = book
        self.seeds = [s for s in (seeds or []) if s]
        self.seed_mode = seed_mode
        self.ensure_interval = ensure_interval
        self.max_outbound = max_outbound
        self.request_interval = request_interval
        self._requests_sent: set[str] = set()  # peers we asked (may answer)
        self._last_request_from: dict[str, float] = {}  # rate limit inbound asks
        self._attempts: dict[str, int] = {}
        self._mtx = threading.Lock()
        self._running = False

    def get_channels(self):
        return [
            ChannelDescriptor(
                PEX_CHANNEL, priority=1, send_queue_capacity=10,
                recv_message_capacity=64 * 1024,
            )
        ]

    def start(self) -> None:
        self._running = True
        threading.Thread(
            target=self._ensure_peers_routine, daemon=True, name="pex-ensure"
        ).start()

    def stop(self) -> None:
        self._running = False
        self.book.save()

    # -- peer events ----------------------------------------------------------

    def add_peer(self, peer) -> None:
        """pex_reactor.go:173 AddPeer: learn an inbound peer's self-reported
        address; ask an outbound peer for more when the book runs low."""
        addr = self._peer_net_address(peer)
        if peer.is_outbound:
            if addr is not None:
                self.book.mark_good(peer.id)
            if self.book.need_more_addrs() and not self.seed_mode:
                self._request_addrs(peer)
        elif addr is not None:
            self.book.add_address(addr, addr)

    def remove_peer(self, peer, reason) -> None:
        with self._mtx:
            self._requests_sent.discard(peer.id)
            # The inbound rate-limit clock must die with the connection
            # (pex_reactor.go:206-212 deletes lastReceivedRequests): a peer
            # reconnecting after a partition asks for addresses immediately,
            # and a stale timestamp would punish it as an abuser — dropping
            # the peer again and looping redial against the rate limit.
            self._last_request_from.pop(peer.id, None)

    def _peer_net_address(self, peer) -> NetAddress | None:
        """Observed IP + self-reported listen port (pex_reactor.go uses
        NodeInfo.NetAddress)."""
        la = peer.node_info.listen_addr
        if not la:
            return None
        port = la.rsplit(":", 1)[-1]
        try:
            return NetAddress(id=peer.id, ip=peer.remote_ip, port=int(port))
        except ValueError:
            return None

    # -- receive --------------------------------------------------------------

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        kind, payload = decode_pex_message(msg_bytes)
        if kind == "request":
            # Check-and-set under the same lock remove_peer pops under: an
            # in-flight request racing a disconnect must not write a stale
            # timestamp back after the pop (it would punish the reconnect).
            with self._mtx:
                now = time.monotonic()
                last = self._last_request_from.get(peer.id, 0.0)
                if now - last < self.request_interval and not self.seed_mode:
                    raise ValueError("peer is asking for addresses too often")
                self._last_request_from[peer.id] = now
            sel = self.book.get_selection()
            me = self._self_net_address()
            if me is not None:
                sel = [me] + [a for a in sel if a.id != me.id]
            peer.try_send(PEX_CHANNEL, encode_pex_addrs(sel))
            if self.seed_mode and peer.is_outbound is False:
                # Seeds serve then hang up to stay available (crawler shape).
                threading.Timer(
                    1.0, lambda: self.switch
                    and self.switch.stop_peer_for_error(peer, "seed disconnect")
                ).start()
        elif kind == "addrs":
            with self._mtx:
                asked = peer.id in self._requests_sent
                self._requests_sent.discard(peer.id)
            if not asked:
                raise ValueError("unsolicited pex addrs")
            src = self._peer_net_address(peer) or NetAddress(
                id=peer.id, ip=peer.remote_ip, port=0
            )
            for a in payload[:100]:
                self.book.add_address(a, src)

    def _self_net_address(self) -> NetAddress | None:
        """Our own dialable address, so one hop through a seed is enough for
        third parties to find us."""
        if self.switch is None:
            return None
        la = self.switch.node_info.listen_addr
        if not la:
            return None
        host, _, port = la.split("://")[-1].rpartition(":")
        try:
            return NetAddress(id=self.switch.node_info.node_id, ip=host or "127.0.0.1", port=int(port))
        except ValueError:
            return None

    def _request_addrs(self, peer) -> None:
        with self._mtx:
            if peer.id in self._requests_sent:
                return
            self._requests_sent.add(peer.id)
        peer.try_send(PEX_CHANNEL, encode_pex_request())

    # -- ensure-peers loop -----------------------------------------------------

    def _ensure_peers_routine(self) -> None:
        self._dial_seeds()
        while self._running:
            self._ensure_peers()
            time.sleep(self.ensure_interval)

    def _dial_seeds(self) -> None:
        for s in self.seeds:
            try:
                addr = NetAddress.parse(s)
                self.book.add_address(addr, addr)
            except ValueError:
                continue

    def _ensure_peers(self) -> None:
        """pex_reactor.go:313 ensurePeers: top up outbound connections from
        the book, ask a connected peer for more when dry."""
        if self.switch is None:
            return
        out = sum(1 for p in self.switch.peers() if p.is_outbound)
        need = self.max_outbound - out
        if need <= 0:
            return
        connected = {p.id for p in self.switch.peers()}
        tried = set()
        for _ in range(need * 3):
            cand = self.book.pick_address(bias_towards_new=30 if out > 4 else 70)
            if cand is None:
                break
            if cand.id in connected or cand.id in tried:
                continue
            tried.add(cand.id)
            self.book.mark_attempt(cand)
            threading.Thread(
                target=self._dial, args=(cand,), daemon=True
            ).start()
        if self.book.is_empty() or (need > 0 and not tried):
            peers = self.switch.peers()
            if peers:
                import random

                self._request_addrs(random.choice(peers))

    def _dial(self, cand: NetAddress) -> None:
        try:
            peer = self.switch.dial_peer(cand.dial_string())
            if peer is not None:
                self.book.mark_good(cand.id)
        except Exception:
            with self._mtx:
                self._attempts[cand.id] = self._attempts.get(cand.id, 0) + 1
                if self._attempts[cand.id] >= 5:
                    self.book.mark_bad(cand)

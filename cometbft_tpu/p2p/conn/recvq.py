"""Prioritized recv demux: bounded per-channel queues + a DRR drain loop.

Channel priorities have always shaped the SEND side of an MConnection
(`_next_channel_to_send`'s recently-sent/priority ratio); the RECV side was
one serialized stream — `_recv_routine` called `on_receive` inline, so a
block part could sit behind hundreds of queued mempool messages and cross
timeout_propose (the e2e matrix seed 2/3/9 stall signature).  This module
is the recv-side counterpart: `_recv_routine` becomes a thin framer that
enqueues reassembled messages here, and one drain thread per connection
delivers them to `on_receive` in priority order.

Scheduling is deficit round robin over four channel CLASSES (consensus >
blocksync > mempool > other), the `mempool/lanes.py` machinery adapted to
message units: each cycle every backlogged class is granted its quantum and
classes are drained high-to-low, so consensus bytes go first while heavily
out-weighted low classes still progress every cycle.  A starvation hatch
promotes any message older than `CMTPU_RECVQ_STARVATION_MS` ahead of the
DRR pass (oldest first, like `sidecar/engine.py`), bounding worst-case
queue delay under a sustained high-class storm.

Queues are bounded (`CMTPU_RECVQ_MAX` messages per channel) with a
per-class overflow policy: consensus/blocksync overflow BLOCKS the framer
(TCP backpressure propagates to the sender — these messages must never be
dropped), mempool/other overflow SHEDS the arriving message (gossip is
best-effort and retried by design).  Per-channel FIFO order is preserved
unconditionally — the drain only ever pops queue heads — so delivery is
bit-identical per channel to the serialized path; only the interleaving
ACROSS channels changes.

The clock is injected (`simnet.clock` surface) so queue-delay accounting
and starvation ages run on virtual time inside simnet scenarios.
"""

from __future__ import annotations

import os
import threading
from collections import deque

CLASS_CONSENSUS = 0
CLASS_BLOCKSYNC = 1
CLASS_MEMPOOL = 2
CLASS_OTHER = 3
CLASS_NAMES = ("consensus", "blocksync", "mempool", "other")

# Classes whose overflow sheds the arriving message instead of blocking
# the framer: loss here is the protocol's normal best-effort regime.
SHED_CLASSES = frozenset({CLASS_MEMPOOL, CLASS_OTHER})

DEFAULT_MAX = 1024
DEFAULT_STARVATION_MS = 100.0
DEFAULT_QUANTA = (8, 4, 2, 1)


def classify(chan_id: int) -> int:
    """Map a global channel byte id (p2p/reactor.py) to a drain class."""
    if 0x20 <= chan_id <= 0x23:  # consensus state/data/vote/vote-set-bits
        return CLASS_CONSENSUS
    if chan_id in (0x38, 0x40, 0x60, 0x61):  # evidence, blocksync, statesync
        return CLASS_BLOCKSYNC
    if chan_id == 0x30:  # mempool
        return CLASS_MEMPOOL
    return CLASS_OTHER  # PEX + anything future


def enabled() -> bool:
    return os.environ.get("CMTPU_RECVQ", "1").lower() not in ("0", "false", "off")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_quanta() -> tuple[int, ...]:
    raw = os.environ.get("CMTPU_RECVQ_QUANTA", "")
    if not raw:
        return DEFAULT_QUANTA
    try:
        parts = [max(1, int(x)) for x in raw.split(",")]
    except ValueError:
        return DEFAULT_QUANTA
    if len(parts) != len(CLASS_NAMES):
        return DEFAULT_QUANTA
    return tuple(parts)


class RecvQueues:
    """Per-connection bounded recv queues + one priority drain thread.

    ``push`` runs on the framer thread; ``deliver(chan_id, msg)`` runs on
    the drain thread.  A deliver exception stops the drain and surfaces
    through ``on_error`` — the same contract the inline path had.
    """

    def __init__(
        self,
        deliver,
        channels,
        clock=None,
        on_error=None,
        max_depth: int | None = None,
        starvation_ms: float | None = None,
        quanta: tuple[int, ...] | None = None,
    ):
        from cometbft_tpu.simnet.clock import MonotonicClock

        self._deliver = deliver
        self._on_error = on_error
        self._clock = clock or MonotonicClock()
        self.max_depth = int(
            max_depth
            if max_depth is not None
            else _env_float("CMTPU_RECVQ_MAX", DEFAULT_MAX)
        )
        self.starvation_ms = (
            starvation_ms
            if starvation_ms is not None
            else _env_float("CMTPU_RECVQ_STARVATION_MS", DEFAULT_STARVATION_MS)
        )
        self.quanta = tuple(quanta) if quanta else _env_quanta()
        self._cv = threading.Condition()
        # chan_id -> deque[(msg_bytes, enqueue_time)]; registration order is
        # sorted ids so the within-class round robin is deterministic.
        self._queues: dict[int, deque] = {}
        self._class_chans: list[list[int]] = [[] for _ in CLASS_NAMES]
        for cid in sorted(channels):
            self._queues[cid] = deque()
            self._class_chans[classify(cid)].append(cid)
        self._rr = [0] * len(CLASS_NAMES)
        self._deficit = [0] * len(CLASS_NAMES)
        self._depth = 0
        self._stopped = False
        self._thread: threading.Thread | None = None
        self.counters_ = {
            "delivered": 0,
            "shed": 0,
            "promoted": 0,
            "backpressure_waits": 0,
            "max_delay_us": 0,
        }
        self.class_counters_ = [
            {"delivered": 0, "shed": 0, "promoted": 0} for _ in CLASS_NAMES
        ]

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._drain_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    # -- producer side (framer thread) --------------------------------------

    def push(self, chan_id: int, msg: bytes) -> bool:
        """Enqueue a reassembled message.  Returns False when the message
        was shed (sheddable-class overflow) or the demux is stopped."""
        k = classify(chan_id)
        with self._cv:
            q = self._queues.get(chan_id)
            if q is None:  # unregistered channel: framer raises before this
                q = self._queues.setdefault(chan_id, deque())
                if chan_id not in self._class_chans[k]:
                    self._class_chans[k].append(chan_id)
            while len(q) >= self.max_depth:
                if self._stopped:
                    return False
                if k in SHED_CLASSES:
                    self.counters_["shed"] += 1
                    self.class_counters_[k]["shed"] += 1
                    return False
                # Backpressure: park the framer (and therefore the socket
                # read loop) until the drain makes room — the kernel's TCP
                # window then throttles the remote sender.
                self.counters_["backpressure_waits"] += 1
                self._cv.wait(0.1)
            if self._stopped:
                return False
            q.append((msg, self._clock.now()))
            self._depth += 1
            self._cv.notify_all()
        return True

    # -- consumer side (drain thread) ----------------------------------------

    def _select_locked(self):
        """Pick the next (chan_id, msg, enq_t, promoted) under the lock.

        Starvation hatch first: the OLDEST queue head past the age bound is
        delivered regardless of class (heads only, so per-channel FIFO
        holds).  Then one DRR step: classes high-to-low, each spending its
        deficit; when every backlogged class is exhausted the cycle refills
        all deficits from the quanta.
        """
        now = self._clock.now()
        cutoff = now - self.starvation_ms / 1000.0
        stale_chan, stale_t = -1, None
        highest_backlog = None
        for k, chans in enumerate(self._class_chans):
            for cid in chans:
                q = self._queues[cid]
                if not q:
                    continue
                if highest_backlog is None:
                    highest_backlog = k
                t = q[0][1]
                if t <= cutoff and (stale_t is None or t < stale_t):
                    stale_chan, stale_t = cid, t
        if highest_backlog is None:
            return None
        if stale_t is not None:
            k = classify(stale_chan)
            msg, enq_t = self._queues[stale_chan].popleft()
            # A promotion only counts when it bypassed backlogged work of a
            # strictly higher class (engine.py's accounting rule).
            promoted = k > highest_backlog
            return stale_chan, msg, enq_t, promoted
        while True:
            for k, chans in enumerate(self._class_chans):
                live = [c for c in chans if self._queues[c]]
                if not live:
                    self._deficit[k] = 0  # lanes.py: reset on empty
                    continue
                if self._deficit[k] <= 0:
                    continue
                self._deficit[k] -= 1
                cid = live[self._rr[k] % len(live)]
                self._rr[k] += 1
                msg, enq_t = self._queues[cid].popleft()
                return cid, msg, enq_t, False
            for k in range(len(CLASS_NAMES)):
                self._deficit[k] += self.quanta[k]

    def _drain_loop(self) -> None:
        while True:
            with self._cv:
                while self._depth == 0 and not self._stopped:
                    self._cv.wait(0.1)
                if self._stopped:
                    return
                item = self._select_locked()
                if item is None:
                    continue
                cid, msg, enq_t, promoted = item
                k = classify(cid)
                self._depth -= 1
                self.counters_["delivered"] += 1
                self.class_counters_[k]["delivered"] += 1
                if promoted:
                    self.counters_["promoted"] += 1
                    self.class_counters_[k]["promoted"] += 1
                delay_us = int((self._clock.now() - enq_t) * 1e6)
                if delay_us > self.counters_["max_delay_us"]:
                    self.counters_["max_delay_us"] = delay_us
                self._cv.notify_all()  # wake backpressured pushers
            try:
                self._deliver(cid, msg)
            except Exception as e:
                if self._on_error is not None:
                    self._on_error(e)
                return

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """Flat counter snapshot for gauges / the recvq_stats RPC."""
        with self._cv:
            out = {
                "depth": self._depth,
                "delivered_total": self.counters_["delivered"],
                "shed_total": self.counters_["shed"],
                "promoted_total": self.counters_["promoted"],
                "backpressure_waits": self.counters_["backpressure_waits"],
                "max_delay_us": self.counters_["max_delay_us"],
                "channels": {
                    f"{cid:#04x}": len(q)
                    for cid, q in self._queues.items()
                    if q
                },
            }
            for k, cname in enumerate(CLASS_NAMES):
                cc = self.class_counters_[k]
                out[f"{cname}_delivered"] = cc["delivered"]
                out[f"{cname}_shed"] = cc["shed"]
                out[f"{cname}_promoted"] = cc["promoted"]
            return out

"""Connection layer: SecretConnection + MConnection (reference: p2p/conn/)."""

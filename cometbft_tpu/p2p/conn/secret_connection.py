"""Authenticated encrypted transport (reference: p2p/conn/secret_connection.go).

Station-to-Station handshake: exchange ephemeral X25519 keys (length-
delimited BytesValue, secret_connection.go:299-320), Diffie-Hellman, derive
recv/send keys + a 32-byte challenge via HKDF-SHA256 with the
"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN" info label
(:51,:335-360 — key order decided by sorted ephemeral pubkeys), sign the
challenge with the node's ed25519 key and exchange AuthSig messages over the
now-encrypted channel (:411-425).

Framing (:35-38,:185-260): ChaCha20-Poly1305 over 1028-byte frames
(4-byte LE length + 1024 data max), 12-byte nonces with a little-endian
64-bit counter in the low bytes, separate counters per direction.

The authentication challenge is the merlin transcript hash exactly as the
reference computes it (secret_connection.go:111-135): a
"TENDERMINT_SECRET_CONNECTION_TRANSCRIPT_HASH" transcript absorbing the
sorted ephemeral pubkeys and the DH secret, challenge extracted under the
"SECRET_CONNECTION_MAC" label — byte-for-byte the Go handshake.
"""

from __future__ import annotations

import socket
import hashlib
import hmac
import os
import struct

from cometbft_tpu.crypto import ed25519
from cometbft_tpu.crypto.compat import (
    ChaCha20Poly1305,
    X25519PrivateKey,
    X25519PublicKey,
)
from cometbft_tpu.crypto.encoding import pub_key_from_proto, pub_key_to_proto
from cometbft_tpu.crypto.merlin import Transcript
from cometbft_tpu.wire import proto as wire

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
AEAD_SIZE_OVERHEAD = 16
KEY_AND_CHALLENGE_GEN = b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"


class SecretConnectionError(Exception):
    pass


def _hkdf_sha256(secret: bytes, info: bytes, length: int) -> bytes:
    """RFC 5869 with empty salt (golang.org/x/crypto/hkdf defaults)."""
    prk = hmac.new(b"\x00" * 32, secret, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


def derive_secrets_and_challenge(
    dh_secret: bytes, loc_is_least: bool
) -> tuple[bytes, bytes, bytes]:
    """deriveSecretsAndChallenge (secret_connection.go:335-360): 96 bytes of
    HKDF output — two 32-byte AEAD keys ordered by which side sorts lower,
    plus the legacy 32-byte challenge in the tail.  Returns
    (recv_secret, send_secret, challenge).

    The handshake authenticates with the merlin transcript challenge (see
    _handshake), not this HKDF tail, but the key halves here are exactly
    what the live handshake uses — and the whole triple is pinned by the
    reference's TestDeriveSecretsAndChallengeGolden vectors."""
    okm = _hkdf_sha256(dh_secret, KEY_AND_CHALLENGE_GEN, 96)
    challenge = okm[64:96]
    if loc_is_least:
        recv_secret, send_secret = okm[:32], okm[32:64]
    else:
        send_secret, recv_secret = okm[:32], okm[32:64]
    return recv_secret, send_secret, challenge


class SecretConnection:
    """p2p/conn/secret_connection.go:92 MakeSecretConnection."""

    def __init__(self, conn, loc_priv_key):
        self._conn = conn
        self.loc_priv_key = loc_priv_key
        self.loc_pub_key = loc_priv_key.pub_key()
        self.rem_pub_key = None
        self._recv_buffer = b""
        self._send_nonce = 0
        self._recv_nonce = 0
        self._handshake()

    # -- handshake ------------------------------------------------------------

    def _handshake(self) -> None:
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()
        # Exchange ephemeral pubkeys: length-delimited BytesValue{value=1}.
        self._write_raw(wire.length_delimited(wire.field_bytes(1, eph_pub)))
        rem_eph_pub = self._read_delimited_bytes_value()
        if len(rem_eph_pub) != 32:
            raise SecretConnectionError("invalid ephemeral pubkey size")
        # Sorted ephemeral keys pick the HKDF key order.
        lo, hi = sorted([eph_pub, rem_eph_pub])
        loc_is_least = eph_pub == lo
        transcript = Transcript(b"TENDERMINT_SECRET_CONNECTION_TRANSCRIPT_HASH")
        transcript.append_message(b"EPHEMERAL_LOWER_PUBLIC_KEY", lo)
        transcript.append_message(b"EPHEMERAL_UPPER_PUBLIC_KEY", hi)
        dh_secret = eph_priv.exchange(X25519PublicKey.from_public_bytes(rem_eph_pub))
        transcript.append_message(b"DH_SECRET", dh_secret)
        recv_secret, send_secret, _ = derive_secrets_and_challenge(
            dh_secret, loc_is_least
        )
        challenge = transcript.extract_bytes(b"SECRET_CONNECTION_MAC", 32)
        self._send_aead = ChaCha20Poly1305(send_secret)
        self._recv_aead = ChaCha20Poly1305(recv_secret)
        # Authenticate: sign the challenge, swap AuthSig over the sealed channel.
        sig = self.loc_priv_key.sign(challenge)
        auth_msg = wire.field_message(
            1, pub_key_to_proto(self.loc_pub_key), emit_empty=True
        ) + wire.field_bytes(2, sig)
        self.write(wire.length_delimited(auth_msg))
        their_auth = self._read_auth_sig()
        rem_pub, rem_sig = their_auth
        if not rem_pub.verify_signature(challenge, rem_sig):
            raise SecretConnectionError("challenge verification failed")
        self.rem_pub_key = rem_pub

    def _read_auth_sig(self):
        buf = self.read(DATA_MAX_SIZE)
        ln, pos = wire.decode_uvarint(buf, 0)
        while len(buf) - pos < ln:
            buf += self.read(DATA_MAX_SIZE)
        f = wire.decode_fields(buf[pos : pos + ln])
        return pub_key_from_proto(wire.get_bytes(f, 1)), wire.get_bytes(f, 2)

    def _read_delimited_bytes_value(self) -> bytes:
        hdr = self._read_raw(1)
        while hdr[-1] & 0x80:
            hdr += self._read_raw(1)
        ln, _ = wire.decode_uvarint(hdr, 0)
        body = self._read_raw(ln)
        f = wire.decode_fields(body)
        return wire.get_bytes(f, 1)

    # -- sealed IO ------------------------------------------------------------

    def write(self, data: bytes) -> int:
        """Chunk into sealed frames (secret_connection.go:185-225)."""
        n = 0
        while data:
            chunk, data = data[:DATA_MAX_SIZE], data[DATA_MAX_SIZE:]
            frame = struct.pack("<I", len(chunk)) + chunk
            frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
            nonce = b"\x00\x00\x00\x00" + struct.pack("<Q", self._send_nonce)
            self._send_nonce += 1
            sealed = self._send_aead.encrypt(nonce, frame, None)
            self._write_raw(sealed)
            n += len(chunk)
        return n

    def read(self, max_bytes: int = DATA_MAX_SIZE) -> bytes:
        """One frame's worth (buffered; secret_connection.go:229-260)."""
        if self._recv_buffer:
            out, self._recv_buffer = (
                self._recv_buffer[:max_bytes],
                self._recv_buffer[max_bytes:],
            )
            return out
        sealed = self._read_raw(TOTAL_FRAME_SIZE + AEAD_SIZE_OVERHEAD)
        nonce = b"\x00\x00\x00\x00" + struct.pack("<Q", self._recv_nonce)
        self._recv_nonce += 1
        try:
            frame = self._recv_aead.decrypt(nonce, sealed, None)
        except Exception as e:
            raise SecretConnectionError(f"failed to decrypt frame: {e}") from e
        (length,) = struct.unpack("<I", frame[:DATA_LEN_SIZE])
        if length > DATA_MAX_SIZE:
            raise SecretConnectionError("chunk length exceeds maximum")
        data = frame[DATA_LEN_SIZE : DATA_LEN_SIZE + length]
        out, self._recv_buffer = data[:max_bytes], data[max_bytes:]
        return out

    def read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.read(n - len(out))
            if not chunk:
                raise SecretConnectionError("connection closed")
            out += chunk
        return out

    # -- raw socket -----------------------------------------------------------

    def _write_raw(self, data: bytes) -> None:
        self._conn.sendall(data)

    def _read_raw(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._conn.recv(n - len(out))
            if not chunk:
                raise SecretConnectionError("connection closed")
            out += chunk
        return out

    def close(self) -> None:
        # shutdown() before close(): close() alone does NOT wake a thread
        # blocked in recv() on another thread's stack (the fd stays open in
        # the kernel until the recv returns) — the recv loop would leak.
        try:
            self._conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._conn.close()
        except Exception:
            pass

"""Multiplexed connection (reference: p2p/conn/connection.go, 918 LoC).

N logical channels over one (secret) connection: per-channel priority queues
with recently-sent fairness accounting, global send/recv rate limiting,
ping/pong keep-alive, 10ms flush throttle. Packets are length-delimited
proto: Packet oneof {ping=1, pong=2, msg=3{channel_id, eof, data}}
(proto/tendermint/p2p/conn.proto); messages over max packet size are split
and reassembled at EOF markers.
"""

from __future__ import annotations

import queue
import struct
import threading
import time

from cometbft_tpu.libs import flowrate
from cometbft_tpu.p2p.conn import recvq
from cometbft_tpu.wire import proto as wire

DEFAULT_MAX_PACKET_MSG_PAYLOAD_SIZE = 1024
DEFAULT_SEND_RATE = 512000 * 10
DEFAULT_RECV_RATE = 512000 * 10
PING_INTERVAL = 60.0
PONG_TIMEOUT = 45.0
FLUSH_THROTTLE = 0.01
MAX_MSG_SIZE = 104857600


class UnknownChannelError(ValueError):
    """The remote sent a packet for a channel id this connection never
    registered — a peer-level protocol violation, surfaced through
    ``on_error`` so the switch tears the peer down (and, for persistent
    peers, redials)."""

    def __init__(self, chan_id: int):
        super().__init__(f"unknown channel {chan_id:#x}")
        self.chan_id = chan_id


class ChannelDescriptor:
    """conn/connection.go ChannelDescriptor."""

    def __init__(
        self,
        channel_id: int,
        priority: int = 1,
        send_queue_capacity: int = 100,
        recv_message_capacity: int = 22020096,
    ):
        self.id = channel_id
        self.priority = priority
        self.send_queue_capacity = send_queue_capacity
        self.recv_message_capacity = recv_message_capacity


class _Channel:
    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        self.send_queue: queue.Queue[bytes] = queue.Queue(desc.send_queue_capacity)
        self.sending: bytes | None = None
        self.recently_sent = 0
        self.recving = b""


class MConnection:
    """conn/connection.go:78 MConnection."""

    def __init__(
        self,
        conn,
        channel_descs: list[ChannelDescriptor],
        on_receive,
        on_error,
        max_packet_msg_payload_size: int = DEFAULT_MAX_PACKET_MSG_PAYLOAD_SIZE,
        send_rate: int = DEFAULT_SEND_RATE,
        recv_rate: int = DEFAULT_RECV_RATE,
        clock=None,
    ):
        self._conn = conn
        self.channels = {d.id: _Channel(d) for d in channel_descs}
        self.on_receive = on_receive
        self.on_error = on_error
        self.max_payload = max_packet_msg_payload_size
        # libs/flowrate Monitors: throttling + rate telemetry per direction
        # (conn/connection.go sendMonitor/recvMonitor).
        self.send_monitor = flowrate.Monitor()
        self.recv_monitor = flowrate.Monitor()
        self._send_rate = send_rate
        self._recv_rate = recv_rate
        self._send_signal = threading.Event()
        self._running = False
        self._pong_pending = False
        self._last_msg_recv = time.monotonic()
        # Prioritized recv demux (CMTPU_RECVQ, default on): _recv_routine
        # frames + enqueues; the demux's drain thread delivers in priority
        # order.  Off = the historical inline delivery, verbatim.
        self._recvq = None
        if recvq.enabled():
            self._recvq = recvq.RecvQueues(
                lambda ch, msg: self.on_receive(ch, msg),
                channels=self.channels,
                clock=clock,
                on_error=self._fatal,
            )

    def start(self) -> None:
        self._running = True
        if self._recvq is not None:
            self._recvq.start()
        threading.Thread(target=self._send_routine, daemon=True).start()
        threading.Thread(target=self._recv_routine, daemon=True).start()

    def stop(self) -> None:
        self._running = False
        self._send_signal.set()
        if self._recvq is not None:
            self._recvq.stop()
        try:
            self._conn.close()
        except Exception:
            pass

    def recvq_stats(self) -> dict:
        """Demux counters ({} when the demux is disabled)."""
        return self._recvq.stats() if self._recvq is not None else {}

    def _fatal(self, e: Exception) -> None:
        """Shared death path for the send/recv/drain threads: stop once,
        surface the first error through on_error."""
        was_running = self._running
        self._running = False
        if self._recvq is not None:
            self._recvq.stop()
        if was_running and self.on_error:
            self.on_error(e)

    # -- sending (conn/connection.go:422 sendRoutine) -------------------------

    def send(self, channel_id: int, msg_bytes: bytes) -> bool:
        """Blocking enqueue (connection.go Send)."""
        ch = self.channels.get(channel_id)
        if ch is None or not self._running:
            return False
        try:
            ch.send_queue.put(msg_bytes, timeout=10)
        except queue.Full:
            return False
        self._send_signal.set()
        return True

    def try_send(self, channel_id: int, msg_bytes: bytes) -> bool:
        """Non-blocking enqueue (connection.go TrySend)."""
        ch = self.channels.get(channel_id)
        if ch is None or not self._running:
            return False
        try:
            ch.send_queue.put_nowait(msg_bytes)
        except queue.Full:
            return False
        self._send_signal.set()
        return True

    def _send_routine(self) -> None:
        last_ping = time.monotonic()
        while self._running:
            try:
                sent_any = self._send_some_packets()
                if self._pong_pending:
                    self._write_packet(wire.field_message(2, b"", emit_empty=True))
                    self._pong_pending = False
                if time.monotonic() - last_ping > PING_INTERVAL:
                    self._write_packet(wire.field_message(1, b"", emit_empty=True))
                    last_ping = time.monotonic()
                if not sent_any:
                    self._send_signal.wait(FLUSH_THROTTLE)
                    self._send_signal.clear()
            except Exception as e:
                self._fatal(e)
                return

    def _send_some_packets(self) -> bool:
        """Up to a batch of packets, least recently-sent channel first
        (connection.go sendSomePacketMsgs/sendPacketMsg)."""
        sent = False
        for _ in range(32):
            ch = self._next_channel_to_send()
            if ch is None:
                break
            self._send_packet_for(ch)
            sent = True
        return sent

    def _next_channel_to_send(self):
        best, best_ratio = None, None
        for ch in self.channels.values():
            if ch.sending is None:
                try:
                    ch.sending = ch.send_queue.get_nowait()
                except queue.Empty:
                    continue
            ratio = ch.recently_sent / max(ch.desc.priority, 1)
            if best is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    def _send_packet_for(self, ch: _Channel) -> None:
        data = ch.sending
        chunk, rest = data[: self.max_payload], data[self.max_payload :]
        eof = len(rest) == 0
        pkt = (
            wire.field_varint(1, ch.desc.id)
            + wire.field_bool(2, eof)
            + wire.field_bytes(3, chunk)
        )
        self._write_packet(wire.field_message(3, pkt, emit_empty=True))
        ch.recently_sent += len(chunk)
        # decay fairness counter
        ch.recently_sent = int(ch.recently_sent * 0.8)
        ch.sending = rest if rest else None

    def _write_packet(self, packet_fields: bytes) -> None:
        framed = wire.length_delimited(packet_fields)
        self.send_monitor.limit(len(framed), self._send_rate)
        self.send_monitor.update(len(framed))
        self._conn.sendall(framed) if hasattr(self._conn, "sendall") else self._conn.write(framed)

    # -- receiving (conn/connection.go recvRoutine) ---------------------------

    def _recv_routine(self) -> None:
        """Thin framer: decode packets, reassemble messages at EOF markers,
        then hand off.  With the demux on, completed messages are enqueued
        into the per-channel recv queues and the demux's drain thread calls
        on_receive in priority order; off, delivery stays inline here."""
        while self._running:
            try:
                pkt = self._read_packet()
                self._last_msg_recv = time.monotonic()
                f = wire.decode_fields(pkt)
                if 1 in f:  # ping
                    self._pong_pending = True
                    self._send_signal.set()
                elif 2 in f:  # pong
                    pass
                elif 3 in f:
                    mf = wire.decode_fields(wire.get_bytes(f, 3))
                    chan_id = wire.get_uvarint(mf, 1)
                    eof = wire.get_bool(mf, 2)
                    data = wire.get_bytes(mf, 3)
                    ch = self.channels.get(chan_id)
                    if ch is None:
                        raise UnknownChannelError(chan_id)
                    ch.recving += data
                    if len(ch.recving) > ch.desc.recv_message_capacity:
                        raise ValueError("received message exceeds channel capacity")
                    if eof:
                        msg, ch.recving = ch.recving, b""
                        if self._recvq is not None:
                            self._recvq.push(chan_id, msg)
                        else:
                            self.on_receive(chan_id, msg)
            except Exception as e:
                self._fatal(e)
                return

    def _read_packet(self) -> bytes:
        hdr = b""
        while True:
            b = self._read_exact(1)
            hdr += b
            if not (b[0] & 0x80):
                break
            if len(hdr) > 10:
                raise ValueError("packet length varint too long")
        ln, _ = wire.decode_uvarint(hdr, 0)
        if ln > MAX_MSG_SIZE:
            raise ValueError("packet too large")
        # Rate-account the whole frame: the varint header was already read
        # off the wire above, so limiting only the payload undercounted
        # every packet by its header size.
        self.recv_monitor.limit(len(hdr) + ln, self._recv_rate)
        self.recv_monitor.update(len(hdr) + ln)
        return self._read_exact(ln)

    def _read_exact(self, n: int) -> bytes:
        if hasattr(self._conn, "read_exact"):
            return self._conn.read_exact(n)
        out = b""
        while len(out) < n:
            chunk = self._conn.recv(n - len(out))
            if not chunk:
                raise ConnectionError("connection closed")
            out += chunk
        return out

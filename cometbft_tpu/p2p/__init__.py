"""P2P: the distributed communication backend (reference: p2p/, 8,379 LoC).

An encrypted, multiplexed, rate-limited TCP mesh with gossip semantics —
point-to-point send/broadcast over per-reactor logical channels
(SURVEY.md §2.8). Consensus traffic stays host-side (DCN analog); the TPU
interconnect is used only inside the verification kernels.
"""

from cometbft_tpu.p2p.key import NodeKey, node_id_from_pub_key
from cometbft_tpu.p2p.reactor import Reactor

__all__ = ["NodeKey", "Reactor", "node_id_from_pub_key"]

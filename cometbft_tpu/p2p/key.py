"""Node identity (reference: p2p/key.go).

ID = hex(address(pubkey)) — 20 bytes of SHA256(pubkey), lowercase hex
(p2p/key.go:120).
"""

from __future__ import annotations

import base64
import json
import os

from cometbft_tpu.crypto import ed25519


def node_id_from_pub_key(pub) -> str:
    return pub.address().hex()


class NodeKey:
    """p2p/key.go NodeKey."""

    def __init__(self, priv_key=None):
        self.priv_key = priv_key or ed25519.gen_priv_key()

    @property
    def id(self) -> str:
        return node_id_from_pub_key(self.priv_key.pub_key())

    def pub_key(self):
        return self.priv_key.pub_key()

    def save_as(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {
                    "priv_key": {
                        "type": "tendermint/PrivKeyEd25519",
                        "value": base64.b64encode(self.priv_key.bytes()).decode(),
                    }
                },
                f,
            )

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            d = json.load(f)
        try:
            return cls(ed25519.PrivKey(base64.b64decode(d["priv_key"]["value"])))
        except (KeyError, TypeError, ValueError) as e:
            # ValueError covers binascii.Error (bad base64) and wrong-length
            # keys — the common corruption modes.
            raise ValueError(f"corrupt node key {path}: {e}") from None

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            return cls.load(path)
        nk = cls()
        nk.save_as(path)
        return nk

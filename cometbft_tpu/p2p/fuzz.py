"""Fuzzed p2p connections (reference: p2p/fuzz.go FuzzedConnection +
config.go FuzzConnConfig): probabilistic delay/drop injected between the
MConnection and the (secret) transport stream, for soak-testing reactor
resilience to a flaky network.

Modes (fuzz.go:16-20): "drop" randomly swallows writes or kills the
connection; "delay" randomly sleeps before IO. Swallowed writes corrupt the
framed stream by design — the peer's receive loop errors and the switch's
reconnect/redial machinery is what's actually under test. Enabled via
config.p2p.test_fuzz — never in production paths."""

from __future__ import annotations

import random
import time
from dataclasses import dataclass


@dataclass
class FuzzConnConfig:
    """config.go FuzzConnConfig defaults (config.go:1130)."""

    mode: str = "delay"  # "drop" | "delay"
    max_delay: float = 0.2
    prob_drop_rw: float = 0.2
    prob_drop_conn: float = 0.0
    prob_sleep: float = 0.0
    seed: int | None = None


class FuzzedConn:
    """Wraps the upgraded (secret) connection's write/read surface
    (fuzz.go:66 FuzzedConnection)."""

    def __init__(self, conn, config: FuzzConnConfig | None = None):
        self._conn = conn
        self.config = config or FuzzConnConfig()
        self._rand = random.Random(self.config.seed)

    def _fuzz_write(self) -> bool:
        """True when this write should be swallowed."""
        c = self.config
        if c.mode == "drop":
            r = self._rand.random()
            if r < c.prob_drop_rw:
                return True
            if r < c.prob_drop_rw + c.prob_drop_conn:
                self._conn.close()
                return True
            if r < c.prob_drop_rw + c.prob_drop_conn + c.prob_sleep:
                time.sleep(self._rand.random() * c.max_delay)
        elif c.mode == "delay":
            time.sleep(self._rand.random() * c.max_delay)
        return False

    def write(self, data: bytes) -> int:
        if self._fuzz_write():
            return len(data)  # lied about: bytes vanish like a lossy link
        return self._conn.write(data)

    def read(self, max_bytes: int = 65536) -> bytes:
        if self.config.mode == "delay":
            time.sleep(self._rand.random() * self.config.max_delay)
        return self._conn.read(max_bytes)

    def read_exact(self, n: int) -> bytes:
        if self.config.mode == "delay":
            time.sleep(self._rand.random() * self.config.max_delay)
        return self._conn.read_exact(n)

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)

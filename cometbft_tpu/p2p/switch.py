"""Switch: peer lifecycle + reactor routing (reference: p2p/switch.go, 860 LoC).

Reactors register channel descriptors; inbound/outbound peers get an
MConnection whose receive callback dispatches to the owning reactor.
Broadcast fan-outs TrySend to every peer (switch.go:271). Persistent peers
are redialed on a two-phase schedule (switch.go:474+ reconnectToPeer:
quick linear attempts, then exponential backoff).
"""

from __future__ import annotations

import random
import threading
import time

from cometbft_tpu.p2p.conn.connection import ChannelDescriptor, MConnection
from cometbft_tpu.p2p.node_info import NodeInfo
from cometbft_tpu.p2p.transport import MultiplexTransport, UpgradedConn

# Redial schedule — INTENTIONAL DIVERGENCE from the reference constants.
# switch.go:25-31 reconnectToPeer does 20 linear attempts at 5 s, then 3^i
# exponential backoff, and gives up after a finite attempt budget.  Here:
# 20 linear attempts at 1 s, then 2^i doubling capped at 60 s, retrying
# FOREVER (+/-20% jitter on every sleep).  Giving up permanently on a
# persistent peer costs liveness on small loopback testnets (a healed
# partition must always be redialed), so only the two-phase shape is kept.
REDIAL_LINEAR_ATTEMPTS = 20
REDIAL_LINEAR_SLEEP_S = 1.0
REDIAL_MAX_SLEEP_S = 60.0


def redial_delay(attempt: int) -> float:
    """Seconds to wait before redial `attempt` (1-based)."""
    if attempt <= REDIAL_LINEAR_ATTEMPTS:
        base = REDIAL_LINEAR_SLEEP_S
    else:
        # Clamp the exponent BEFORE computing the power: a peer down for
        # a day pushes attempt past 1000 and 2.0**1000 overflows float,
        # which would kill the redial thread right when persistence
        # matters most.
        exp = min(attempt - REDIAL_LINEAR_ATTEMPTS, 16)
        base = min(REDIAL_LINEAR_SLEEP_S * 2.0 ** exp, REDIAL_MAX_SLEEP_S)
    return base * (0.8 + 0.4 * random.random())


class Peer:
    """p2p/peer.go peer: MConnection + metadata."""

    def __init__(self, up: UpgradedConn, channel_descs, on_receive, on_error,
                 clock=None):
        self.node_info = up.node_info
        self.id = up.peer_id
        self.is_outbound = up.outbound
        self.remote_ip = up.remote_addr.rsplit(":", 1)[0]
        self._kv: dict = {}
        self.mconn = MConnection(
            up.conn,
            channel_descs,
            lambda ch, msg: on_receive(self, ch, msg),
            lambda err: on_error(self, err),
            clock=clock,
        )

    def start(self) -> None:
        self.mconn.start()

    def stop(self) -> None:
        self.mconn.stop()

    def send(self, chan_id: int, msg_bytes: bytes) -> bool:
        return self.mconn.send(chan_id, msg_bytes)

    def try_send(self, chan_id: int, msg_bytes: bytes) -> bool:
        return self.mconn.try_send(chan_id, msg_bytes)

    def set(self, key: str, value) -> None:
        self._kv[key] = value

    def get(self, key: str):
        return self._kv.get(key)

    def node_info_json(self) -> dict:
        return self.node_info.to_json()


class Switch:
    """p2p/switch.go Switch."""

    def __init__(
        self, node_info: NodeInfo, transport: MultiplexTransport, config=None,
        clock=None,
    ):
        from cometbft_tpu.simnet.clock import MonotonicClock

        self.node_info = node_info
        self.transport = transport
        self.config = config
        self.clock = clock or MonotonicClock()
        self.reactors: dict[str, object] = {}
        self._chan_to_reactor: dict[int, object] = {}
        self._channel_descs: list[ChannelDescriptor] = []
        self._peers: dict[str, Peer] = {}
        self._mtx = threading.RLock()
        self._running = False
        self._persistent_addrs: list[str] = []
        self._dialing: set[str] = set()
        # Peer instances whose connection died before they reached the
        # table (stop_peer_for_error in _add_peer's start->insert window).
        self._dead: set[Peer] = set()
        # Recv-demux counters folded in from stopped peers, so node-level
        # recvq_* gauges survive peer churn (depths die with the queues).
        self._recvq_retired: dict = {}

    # -- reactors -------------------------------------------------------------

    def add_reactor(self, name: str, reactor) -> None:
        """switch.go AddReactor: claims the reactor's channel ids."""
        for desc in reactor.get_channels():
            if desc.id in self._chan_to_reactor:
                raise ValueError(f"channel {desc.id:#x} already registered")
            self._chan_to_reactor[desc.id] = reactor
            self._channel_descs.append(desc)
        self.reactors[name] = reactor
        reactor.set_switch(self)
        self.node_info.channels = bytes(sorted(self._chan_to_reactor))

    # -- lifecycle ------------------------------------------------------------

    def start(self, listen_addr: str = "") -> str:
        self._running = True
        for reactor in self.reactors.values():
            reactor.start()
        actual = ""
        if listen_addr:
            actual = self.transport.listen(listen_addr, self._on_inbound)
            # Peers learn our dialable port from the handshake NodeInfo
            # (PEX hands it on): record the ACTUAL bound address, which
            # matters for the ephemeral :0 listeners tests use.
            if not self.node_info.listen_addr or self.node_info.listen_addr.endswith(":0"):
                self.node_info.listen_addr = actual
        return actual

    def stop(self) -> None:
        self._running = False
        with self._mtx:
            peers = list(self._peers.values())
        for p in peers:
            self.stop_peer_for_error(p, "switch stopping")
        self.transport.close()
        for reactor in self.reactors.values():
            reactor.stop()

    # -- peers ----------------------------------------------------------------

    def peers(self) -> list[Peer]:
        with self._mtx:
            return list(self._peers.values())

    def num_peers(self) -> int:
        with self._mtx:
            return len(self._peers)

    def get_peer(self, peer_id: str) -> Peer | None:
        with self._mtx:
            return self._peers.get(peer_id)

    def _on_inbound(self, result) -> None:
        if isinstance(result, Exception):
            return
        self._add_peer(result)

    def _add_peer(self, up: UpgradedConn) -> None:
        """switch.go:808 addPeer."""
        if up.peer_id == self.node_info.node_id:
            up.conn.close()  # self-connection
            return
        with self._mtx:
            if up.peer_id in self._peers:
                up.conn.close()
                return
        peer = Peer(up, self._channel_descs, self._on_peer_receive,
                    self._on_peer_error, clock=self.clock)
        for reactor in self.reactors.values():
            reactor.init_peer(peer)
        peer.start()
        with self._mtx:
            # Re-check at insert: a simultaneous cross-dial (inbound accept
            # + outbound dial, same id) passes the pre-upgrade duplicate
            # check in both threads; overwriting here would displace a peer
            # that reactors were told about and that stop_peer_for_error's
            # instance check would then never clean up. The _dead check
            # covers the other window: the conn can die between start()
            # and this insert, in which case stop_peer_for_error found no
            # table entry and tombstoned the instance — tabling it anyway
            # would park a permanently-idle ghost that blocks redial.
            if peer in self._dead:
                self._dead.discard(peer)
                dup = True
            elif up.peer_id in self._peers:
                dup = True
            else:
                self._peers[peer.id] = peer
                dup = False
        if dup:
            peer.stop()
            return
        for reactor in self.reactors.values():
            reactor.add_peer(peer)
        with self._mtx:
            still_tabled = self._peers.get(peer.id) is peer
        if not still_tabled:
            # Removal raced the add_peer loop above: the remover's
            # reactor.remove_peer ran before (some) add_peer calls, which
            # would leave gossip state for a stopped peer. remove_peer is
            # idempotent in every reactor, so re-run it.
            for reactor in self.reactors.values():
                reactor.remove_peer(peer, "removal raced add")

    def dial_peer(self, addr: str) -> Peer | None:
        """addr format: id@host:port."""
        expected_id = addr.split("@", 1)[0] if "@" in addr else ""
        with self._mtx:
            if addr in self._dialing:
                return None
            self._dialing.add(addr)
        try:
            up = self.transport.dial(addr, expected_id)
            self._add_peer(up)
            return self.get_peer(up.peer_id)
        finally:
            with self._mtx:
                self._dialing.discard(addr)

    def add_persistent_peers(self, addrs: list[str]) -> None:
        self._persistent_addrs.extend(a for a in addrs if a)

    def dial_persistent_peers(self) -> None:
        """Two-phase redial loop (switch.go reconnectToPeer): a burst of
        quick linear attempts first — a healed partition reconnects in
        seconds instead of waiting out a grown exponential backoff — then
        exponential growth to a 60 s cap for genuinely-gone peers. Jitter
        keeps a rebooted validator set from dialing in lockstep."""

        def redial(addr):
            attempt = 0
            while self._running:
                expected_id = addr.split("@", 1)[0] if "@" in addr else ""
                if expected_id and self.get_peer(expected_id) is not None:
                    attempt = 0
                    self.clock.sleep(5)
                    continue
                try:
                    self.dial_peer(addr)
                    attempt = 0
                except Exception:
                    attempt += 1
                    self.clock.sleep(redial_delay(attempt))

        for addr in self._persistent_addrs:
            threading.Thread(target=redial, args=(addr,), daemon=True).start()

    def stop_peer_for_error(self, peer: Peer, reason) -> None:
        """switch.go StopPeerForError."""
        import os

        if os.environ.get("CMTPU_P2P_DEBUG"):
            import sys
            import traceback

            print(
                f"[p2p] stop_peer_for_error {peer.id[:8]}: {reason!r}",
                file=sys.stderr, flush=True,
            )
            if isinstance(reason, Exception):
                traceback.print_exception(reason, file=sys.stderr)
        with self._mtx:
            existing = self._peers.get(peer.id)
            if existing is peer:
                del self._peers[peer.id]
            else:
                # Not (or not yet) tabled: possibly an error that fired in
                # _add_peer's start()->insert window. Tombstone the
                # instance so _add_peer won't table a dead peer; bounded
                # because _add_peer discards matches and the set only
                # grows on repeated errors from never-tabled instances.
                self._dead.add(peer)
                while len(self._dead) > 256:
                    self._dead.pop()
        self._fold_recvq(peer)
        # Always stop THIS instance's threads, but only the instance that
        # owns the table entry may tear down reactor state: a dead
        # connection errors from both its send and recv routines, and with
        # fast redial the replacement peer (same id) can already be live
        # when the second error fires — removing BY ID here evicted the
        # replacement, killed its gossip threads, and left a ghost TCP conn
        # that made the remote reject every subsequent redial as a
        # duplicate. That was the partition-heal wedge.
        peer.stop()
        if existing is not peer:
            return
        for reactor in self.reactors.values():
            reactor.remove_peer(peer, reason)

    def _fold_recvq(self, peer: Peer) -> None:
        """Accumulate a dying peer's demux counters exactly once (a dead
        connection reaches stop_peer_for_error from both its send and recv
        routines)."""
        if getattr(peer, "_recvq_folded", False):
            return
        peer._recvq_folded = True
        try:
            st = peer.mconn.recvq_stats()
        except Exception:
            return
        if not st:
            return
        with self._mtx:
            for key, v in st.items():
                if not isinstance(v, int) or key == "depth":
                    continue
                if key == "max_delay_us":
                    self._recvq_retired[key] = max(
                        self._recvq_retired.get(key, 0), v
                    )
                else:
                    self._recvq_retired[key] = self._recvq_retired.get(key, 0) + v

    def recvq_stats(self) -> dict:
        """Aggregate recv-demux counters across live peers + retired totals
        (the recvq_* node gauges and the recvq_stats RPC read this)."""
        with self._mtx:
            out: dict = {"enabled": False, **self._recvq_retired}
            if self._recvq_retired:
                out["enabled"] = True
        channels: dict[str, int] = {}
        for p in self.peers():
            try:
                st = p.mconn.recvq_stats()
            except Exception:
                continue
            if not st:
                continue
            out["enabled"] = True
            for key, v in st.items():
                if key == "channels":
                    for cid, d in v.items():
                        channels[cid] = channels.get(cid, 0) + d
                elif isinstance(v, int):
                    if key == "max_delay_us":
                        out[key] = max(out.get(key, 0), v)
                    else:
                        out[key] = out.get(key, 0) + v
        out["channels"] = channels
        out.setdefault("depth", 0)
        out.setdefault("delivered_total", 0)
        out.setdefault("shed_total", 0)
        out.setdefault("promoted_total", 0)
        out.setdefault("max_delay_us", 0)
        return out

    # -- routing --------------------------------------------------------------

    def _on_peer_receive(self, peer: Peer, chan_id: int, msg_bytes: bytes) -> None:
        reactor = self._chan_to_reactor.get(chan_id)
        if reactor is None:
            self.stop_peer_for_error(peer, f"unknown channel {chan_id:#x}")
            return
        try:
            reactor.receive(chan_id, peer, msg_bytes)
        except Exception as e:
            self.stop_peer_for_error(peer, e)

    def _on_peer_error(self, peer: Peer, err) -> None:
        self.stop_peer_for_error(peer, err)

    def broadcast(self, chan_id: int, msg_bytes: bytes) -> None:
        """switch.go:271 Broadcast: TrySend to every peer."""
        for peer in self.peers():
            peer.try_send(chan_id, msg_bytes)

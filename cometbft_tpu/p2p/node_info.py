"""Node info exchanged during the p2p handshake (reference: p2p/node_info.go).

Compatibility: same block protocol version, same network (chain id), and at
least one common channel (node_info.go CompatibleWith).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield

from cometbft_tpu.wire import proto as wire

MAX_NUM_CHANNELS = 16


@dataclass
class ProtocolVersion:
    p2p: int = 8
    block: int = 11
    app: int = 0


@dataclass
class NodeInfo:
    """p2p/node_info.go DefaultNodeInfo."""

    protocol_version: ProtocolVersion = dfield(default_factory=ProtocolVersion)
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""
    version: str = "0.1.0"
    channels: bytes = b""
    moniker: str = ""
    tx_index: str = "on"
    rpc_address: str = ""

    def validate_basic(self) -> None:
        if not self.node_id:
            raise ValueError("no node ID")
        if len(self.channels) > MAX_NUM_CHANNELS:
            raise ValueError(f"too many channels ({len(self.channels)})")
        if len(set(self.channels)) != len(self.channels):
            raise ValueError("duplicate channel ids")

    def compatible_with(self, other: "NodeInfo") -> None:
        """node_info.go CompatibleWith."""
        if self.protocol_version.block != other.protocol_version.block:
            raise ValueError(
                f"peer is on a different Block version. Got {other.protocol_version.block}, "
                f"expected {self.protocol_version.block}"
            )
        if self.network != other.network:
            raise ValueError(
                f"peer is on a different network. Got {other.network!r}, expected {self.network!r}"
            )
        if self.channels and other.channels:
            if not set(self.channels) & set(other.channels):
                raise ValueError(f"peer has no common channels. Our {self.channels.hex()}; theirs {other.channels.hex()}")

    def encode(self) -> bytes:
        pv = (
            wire.field_varint(1, self.protocol_version.p2p)
            + wire.field_varint(2, self.protocol_version.block)
            + wire.field_varint(3, self.protocol_version.app)
        )
        out = wire.field_message(1, pv, emit_empty=True)
        out += wire.field_string(2, self.node_id)
        out += wire.field_string(3, self.listen_addr)
        out += wire.field_string(4, self.network)
        out += wire.field_string(5, self.version)
        out += wire.field_bytes(6, self.channels)
        out += wire.field_string(7, self.moniker)
        other = wire.field_string(1, self.tx_index) + wire.field_string(2, self.rpc_address)
        out += wire.field_message(8, other, emit_empty=True)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "NodeInfo":
        f = wire.decode_fields(data)
        pvf = wire.decode_fields(wire.get_bytes(f, 1))
        other = wire.decode_fields(wire.get_bytes(f, 8))
        return cls(
            protocol_version=ProtocolVersion(
                wire.get_uvarint(pvf, 1), wire.get_uvarint(pvf, 2), wire.get_uvarint(pvf, 3)
            ),
            node_id=wire.get_string(f, 2),
            listen_addr=wire.get_string(f, 3),
            network=wire.get_string(f, 4),
            version=wire.get_string(f, 5),
            channels=wire.get_bytes(f, 6),
            moniker=wire.get_string(f, 7),
            tx_index=wire.get_string(other, 1),
            rpc_address=wire.get_string(other, 2),
        )

    def to_json(self) -> dict:
        return {
            "protocol_version": {
                "p2p": str(self.protocol_version.p2p),
                "block": str(self.protocol_version.block),
                "app": str(self.protocol_version.app),
            },
            "id": self.node_id,
            "listen_addr": self.listen_addr,
            "network": self.network,
            "version": self.version,
            "channels": self.channels.hex(),
            "moniker": self.moniker,
            "other": {"tx_index": self.tx_index, "rpc_address": self.rpc_address},
        }

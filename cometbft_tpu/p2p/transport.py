"""TCP transport: listen/dial + handshake (reference: p2p/transport.go
MultiplexTransport, 613 LoC).

Connection upgrade: TCP → SecretConnection (authenticated encryption) →
NodeInfo exchange (length-delimited proto) → compatibility filtering.
"""

from __future__ import annotations

import socket
import threading

from cometbft_tpu.p2p.conn.secret_connection import SecretConnection
from cometbft_tpu.p2p.key import NodeKey, node_id_from_pub_key
from cometbft_tpu.p2p.node_info import NodeInfo
from cometbft_tpu.wire import proto as wire

HANDSHAKE_TIMEOUT = 20.0
DIAL_TIMEOUT = 3.0


class TransportError(Exception):
    pass


class UpgradedConn:
    """A fully-handshaken connection ready for MConnection."""

    def __init__(self, secret_conn: SecretConnection, node_info: NodeInfo, outbound: bool, remote_addr: str):
        self.conn = secret_conn
        self.node_info = node_info
        self.outbound = outbound
        self.remote_addr = remote_addr

    @property
    def peer_id(self) -> str:
        return node_id_from_pub_key(self.conn.rem_pub_key)


class MultiplexTransport:
    """p2p/transport.go."""

    def __init__(self, node_info: NodeInfo, node_key: NodeKey, fuzz_config=None):
        self.fuzz_config = fuzz_config
        self.node_info = node_info
        self.node_key = node_key
        self._listener: socket.socket | None = None
        self._accept_cb = None
        self._running = False

    # -- listening ------------------------------------------------------------

    def listen(self, addr: str, accept_cb) -> str:
        """Start accepting; accept_cb(UpgradedConn | Exception)."""
        host, port = _split_addr(addr)
        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.5)
        actual = f"{host}:{self._listener.getsockname()[1]}"
        self.node_info.listen_addr = actual
        self._accept_cb = accept_cb
        self._running = True
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return actual

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._upgrade_inbound, args=(sock, addr), daemon=True
            ).start()

    def _upgrade_inbound(self, sock: socket.socket, addr) -> None:
        try:
            up = self._upgrade(sock, outbound=False, remote=f"{addr[0]}:{addr[1]}")
            self._accept_cb(up)
        except Exception as e:
            try:
                sock.close()
            except Exception:
                pass
            self._accept_cb(e)

    # -- dialing --------------------------------------------------------------

    def dial(self, addr: str, expected_id: str = "") -> UpgradedConn:
        host, port = _split_addr(addr)
        sock = socket.create_connection((host, port), timeout=DIAL_TIMEOUT)
        sock.settimeout(HANDSHAKE_TIMEOUT)
        up = self._upgrade(sock, outbound=True, remote=f"{host}:{port}")
        if expected_id and up.peer_id != expected_id:
            up.conn.close()
            raise TransportError(
                f"conn.ID ({up.peer_id}) dialed ID ({expected_id}) mismatch"
            )
        return up

    # -- upgrade pipeline (transport.go upgrade) ------------------------------

    def _upgrade(self, sock: socket.socket, outbound: bool, remote: str) -> UpgradedConn:
        sc = SecretConnection(sock, self.node_key.priv_key)
        # Fuzzing wraps AFTER the secret handshake (documented deviation
        # from fuzz.go's raw-conn wrap: with drop-mode probabilities the
        # handshake itself would rarely complete; the churn under test is
        # the message layer + reconnect machinery).
        # NodeInfo swap: length-delimited (transport.go handshake).
        sc.write(wire.length_delimited(self.node_info.encode()))
        their_info = _read_delimited_node_info(sc)
        their_info.validate_basic()
        self.node_info.compatible_with(their_info)
        # The authenticated key must match the claimed node ID (transport.go).
        authed_id = node_id_from_pub_key(sc.rem_pub_key)
        if their_info.node_id != authed_id:
            raise TransportError(
                f"nodeInfo.ID ({their_info.node_id}) doesn't match authenticated key ({authed_id})"
            )
        sock.settimeout(None)
        if self.fuzz_config is not None:
            from cometbft_tpu.p2p.fuzz import FuzzedConn

            sc = FuzzedConn(sc, self.fuzz_config)
        return UpgradedConn(sc, their_info, outbound, remote)

    def close(self) -> None:
        self._running = False
        if self._listener:
            try:
                self._listener.close()
            except Exception:
                pass


def _read_delimited_node_info(sc: SecretConnection) -> NodeInfo:
    buf = sc.read(1024)
    ln, pos = wire.decode_uvarint(buf, 0)
    while len(buf) - pos < ln:
        buf += sc.read(1024)
    return NodeInfo.decode(buf[pos : pos + ln])


def _split_addr(addr: str) -> tuple[str, int]:
    addr = addr.split("://", 1)[-1]
    if "@" in addr:
        addr = addr.split("@", 1)[1]
    host, _, port = addr.rpartition(":")
    return host or "0.0.0.0", int(port)

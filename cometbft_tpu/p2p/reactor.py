"""Reactor interface (reference: p2p/base_reactor.go:15).

Every networked subsystem implements this and registers on the Switch with
reserved global channel byte IDs (SURVEY.md §1): PEX 0x00, consensus
0x20-0x23, mempool 0x30, evidence 0x38, blocksync 0x40, statesync 0x60-0x61.
"""

from __future__ import annotations


class Reactor:
    def __init__(self, name: str):
        self.name = name
        self.switch = None

    def set_switch(self, switch) -> None:
        self.switch = switch

    def get_channels(self) -> list:
        """ChannelDescriptors this reactor speaks on."""
        return []

    def init_peer(self, peer) -> None:
        """Called before the peer starts (base_reactor.go InitPeer)."""

    def add_peer(self, peer) -> None:
        """Called once the peer is running."""

    def remove_peer(self, peer, reason) -> None:
        pass

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        pass

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


# Reserved channel IDs (SURVEY.md §1).
PEX_CHANNEL = 0x00
CONSENSUS_STATE_CHANNEL = 0x20
CONSENSUS_DATA_CHANNEL = 0x21
CONSENSUS_VOTE_CHANNEL = 0x22
CONSENSUS_VOTE_SET_BITS_CHANNEL = 0x23
MEMPOOL_CHANNEL = 0x30
EVIDENCE_CHANNEL = 0x38
BLOCKSYNC_CHANNEL = 0x40
SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

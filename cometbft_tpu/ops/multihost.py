"""Multi-HOST distributed verification (SURVEY.md §5.8).

The reference's distribution substrate is its p2p TCP mesh — every node
re-verifies everything. This framework adds a second, orthogonal axis the
reference cannot express: ONE logical verification step sharded across
the chips of SEVERAL hosts, with XLA collectives riding ICI within a
host and DCN between hosts. A JAX "process" per host joins a
coordinator (`jax.distributed`), the global device list forms the same
1-D `sig` mesh `ops/sharded.py` uses, and each host contributes only its
process-local lane slice — packing is embarrassingly columnar (every
packed lane depends on its own signature only, ed25519_kernel.pack_batch),
so a host packs exactly the commit slice it was assigned. all_gather /
psum give every host the identical Merkle root and all-valid bit.

CPU hosts participate through the same code path via jaxlib's gloo
collectives backend — which is also how this is TESTED without multi-host
TPU hardware: tests/test_multihost.py spawns real OS processes, each with
virtual CPU devices, forms the global mesh over the gloo coordinator, and
cross-checks the root against the host tree (the same validation contract
as __graft_entry__.dryrun_multichip, one level up the scaling ladder).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax

# NOTE: ops.sharded (and through it the kernels + field25519's lowering
# probe) is imported lazily inside the functions below — importing it at
# module scope initializes the XLA backend, which must not happen before
# distributed_init() joins the coordinator.


def distributed_init(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_devices: int | None = None,
) -> None:
    """Join (or form) the multi-host verification cluster.

    coordinator: "host:port" of process 0. For CPU hosts pass
    local_devices (virtual devices per host) — it is applied to XLA_FLAGS
    here, before backend init — and jaxlib's gloo backend carries the
    collectives; on TPU hosts leave it None and the PJRT topology
    provides the device set.
    """
    if local_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_devices}"
            ).strip()
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # TPU-only jaxlib builds have no CPU collectives knob
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_local_columns(mesh, spec, global_shape, local_cols):
    """Assemble a globally-sharded array from THIS host's column slice.

    local_cols must be exactly the columns this process's devices own
    under `spec` (mesh is 1-D over the batch axis, so that is the
    contiguous [pid*shard : (pid+1)*shard] slice of the batch dim).
    """
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), np.ascontiguousarray(local_cols), global_shape
    )


@functools.lru_cache(maxsize=None)
def _step_for(mesh, axis):
    """One jitted step per (mesh, axis) — a node runs this once per block,
    and rebuilding the jit wrapper per call would pay a cache lookup and
    wrapper allocation on the consensus hot path."""
    from cometbft_tpu.ops import sharded

    return sharded.sharded_commit_step_fn(mesh, axis)


def multihost_commit_step(mesh, local_operands, local_leaf_digests, axis="sig"):
    """Run ops/sharded.sharded_commit_step_fn with per-host inputs.

    local_operands: this host's lane slice of the packed verify operands
    (same tuple layout as ed25519_kernel.pack_batch, sliced on the batch
    dim). local_leaf_digests: uint32[8, n_local] leaf-digest columns of
    this host's Merkle shard. Returns (ok_local, all_valid, root_words):
    ok_local is this host's slice of the validity bitmap; all_valid and
    the root are replicated across every host by the step's collectives.
    """
    from cometbft_tpu.ops import sharded

    n_proc = jax.process_count()
    specs = (*sharded._verify_specs(axis), jax.sharding.PartitionSpec(None, axis))
    arrays = []
    for op, spec in zip((*local_operands, local_leaf_digests), specs):
        gshape = list(op.shape)
        # the sharded dim is the one carrying the batch axis in the spec
        dim = list(spec).index(axis)
        gshape[dim] = op.shape[dim] * n_proc
        arrays.append(process_local_columns(mesh, spec, tuple(gshape), op))
    *operands, leaves = arrays
    step = _step_for(mesh, axis)
    ok, all_valid, root = step(*operands, leaves)
    # Per-host view of the sharded bitmap: the addressable shards.
    local_ok = np.concatenate(
        [np.asarray(s.data) for s in sorted(
            ok.addressable_shards, key=lambda s: s.index[0].start or 0)]
    )
    return local_ok, bool(all_valid), np.asarray(root)

"""Multi-HOST distributed verification (SURVEY.md §5.8).

The reference's distribution substrate is its p2p TCP mesh — every node
re-verifies everything. This framework adds a second, orthogonal axis the
reference cannot express: ONE logical verification step sharded across
the chips of SEVERAL hosts, with XLA collectives riding ICI within a
host and DCN between hosts. A JAX "process" per host joins a
coordinator (`jax.distributed`), the global device list forms the same
1-D `sig` mesh `ops/sharded.py` uses, and each host contributes only its
process-local lane slice — packing is embarrassingly columnar (every
packed lane depends on its own signature only, ed25519_kernel.pack_batch),
so a host packs exactly the commit slice it was assigned. all_gather /
psum give every host the identical Merkle root and all-valid bit.

CPU hosts participate through the same code path via jaxlib's gloo
collectives backend — which is also how this is TESTED without multi-host
TPU hardware: tests/test_multihost.py spawns real OS processes, each with
virtual CPU devices, forms the global mesh over the gloo coordinator, and
cross-checks the root against the host tree (the same validation contract
as __graft_entry__.dryrun_multichip, one level up the scaling ladder).

Round 15 adds the FANOUT-SERVING seam: a multi-process mesh can act as
ONE shard of a `sidecar/fanout.py` fleet. The leader process (pid 0)
exposes a `MultihostShardBackend` through an ordinary `SidecarServer`; on
every batch it re-broadcasts the triples to its follower processes over
plain framed side sockets, then all processes enter the same collective
verify step (`multihost_verify`), whose replicated bitmap lets the leader
answer the fanout client alone. The Ping capability reply advertises the
GLOBAL device count, so the fleet's width sum counts every chip behind
every process of every shard.
"""

from __future__ import annotations

import functools
import os
import threading

import numpy as np

import jax

# NOTE: ops.sharded (and through it the kernels + field25519's lowering
# probe) is imported lazily inside the functions below — importing it at
# module scope initializes the XLA backend, which must not happen before
# distributed_init() joins the coordinator.


def distributed_init(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_devices: int | None = None,
) -> None:
    """Join (or form) the multi-host verification cluster.

    coordinator: "host:port" of process 0. For CPU hosts pass
    local_devices (virtual devices per host) — it is applied to XLA_FLAGS
    here, before backend init — and jaxlib's gloo backend carries the
    collectives; on TPU hosts leave it None and the PJRT topology
    provides the device set.
    """
    if local_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_devices}"
            ).strip()
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # TPU-only jaxlib builds have no CPU collectives knob
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_local_columns(mesh, spec, global_shape, local_cols):
    """Assemble a globally-sharded array from THIS host's column slice.

    local_cols must be exactly the columns this process's devices own
    under `spec` (mesh is 1-D over the batch axis, so that is the
    contiguous [pid*shard : (pid+1)*shard] slice of the batch dim).
    """
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), np.ascontiguousarray(local_cols), global_shape
    )


@functools.lru_cache(maxsize=None)
def _step_for(mesh, axis):
    """One jitted step per (mesh, axis) — a node runs this once per block,
    and rebuilding the jit wrapper per call would pay a cache lookup and
    wrapper allocation on the consensus hot path."""
    from cometbft_tpu.ops import sharded

    return sharded.sharded_commit_step_fn(mesh, axis)


def multihost_commit_step(mesh, local_operands, local_leaf_digests, axis="sig"):
    """Run ops/sharded.sharded_commit_step_fn with per-host inputs.

    local_operands: this host's lane slice of the packed verify operands
    (same tuple layout as ed25519_kernel.pack_batch, sliced on the batch
    dim). local_leaf_digests: uint32[8, n_local] leaf-digest columns of
    this host's Merkle shard. Returns (ok_local, all_valid, root_words):
    ok_local is this host's slice of the validity bitmap; all_valid and
    the root are replicated across every host by the step's collectives.
    """
    from cometbft_tpu.ops import sharded

    n_proc = jax.process_count()
    specs = (*sharded._verify_specs(axis), jax.sharding.PartitionSpec(None, axis))
    arrays = []
    for op, spec in zip((*local_operands, local_leaf_digests), specs):
        gshape = list(op.shape)
        # the sharded dim is the one carrying the batch axis in the spec
        dim = list(spec).index(axis)
        gshape[dim] = op.shape[dim] * n_proc
        arrays.append(process_local_columns(mesh, spec, tuple(gshape), op))
    *operands, leaves = arrays
    step = _step_for(mesh, axis)
    ok, all_valid, root = step(*operands, leaves)
    # Per-host view of the sharded bitmap: the addressable shards.
    local_ok = np.concatenate(
        [np.asarray(s.data) for s in sorted(
            ok.addressable_shards, key=lambda s: s.index[0].start or 0)]
    )
    return local_ok, bool(all_valid), np.asarray(root)


# -- fanout-serving seam (round 15) -------------------------------------------


@functools.lru_cache(maxsize=None)
def _verify_for(mesh, axis):
    from cometbft_tpu.ops import sharded

    return sharded.sharded_verify_replicated_fn(mesh, axis)


def multihost_verify(mesh, pubs, msgs, sigs, axis="sig"):
    """One collective batch verify over a multi-process mesh; every process
    must call this with IDENTICAL triples in the same order (the leader's
    broadcast guarantees that for the serving path).

    Every process packs the FULL batch — packing is cheap columnar host
    work, no crypto — and contributes its contiguous per-process column
    slice, exactly the tests/multihost_worker.py idiom, so the operand
    shapes agree across hosts by construction. The per-process slice is
    rounded up the kernel's bucket ladder (`bucket_for`), keeping the set
    of compiled global shapes as bounded as the single-host ladder; padded
    lanes are zeroed and fail device verification, and the returned bitmap
    is sliced back to the caller's n with the host-side veto applied.
    Returns (ok, bits) with the full bitmap on EVERY process (the
    replicated out-sharding of sharded_verify_replicated_fn)."""
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.ops import sharded

    n = len(pubs)
    n_proc = jax.process_count()
    pid = jax.process_index()
    per = ek.bucket_for(max(1, -(-n // n_proc)))
    total = per * n_proc
    if total > n:
        pad = total - n
        pubs = list(pubs) + [b"\x00" * 32] * pad
        msgs = list(msgs) + [b""] * pad
        sigs = list(sigs) + [b"\x00" * 64] * pad
    operands, host_ok = ek.pack_batch(pubs, msgs, sigs)
    if len(operands) != 5:
        raise NotImplementedError(
            "host-hash packing (CMTPU_HOST_HASH / oversized messages) "
            "cannot serve the multi-host verify step"
        )
    specs = sharded._verify_specs(axis)
    lo, hi = pid * per, (pid + 1) * per
    arrays = []
    for op, spec in zip(operands, specs):
        dim = list(spec).index(axis)
        local = op[:, lo:hi] if dim == 1 else op[lo:hi]
        gshape = list(local.shape)
        gshape[dim] = local.shape[dim] * n_proc
        arrays.append(process_local_columns(mesh, spec, tuple(gshape), local))
    dev_ok = np.asarray(_verify_for(mesh, axis)(*arrays))
    bits = [bool(host_ok[i] and dev_ok[i]) for i in range(n)]
    return all(bits), bits


def _encode_triples(pubs, msgs, sigs) -> bytes:
    """BatchVerifyReq-shaped body for the leader -> follower broadcast
    (same fields as the sidecar's wire format, so nothing new to fuzz)."""
    from cometbft_tpu.wire import proto

    return (
        b"".join(proto.field_bytes(1, p, emit_default=True) for p in pubs)
        + b"".join(proto.field_bytes(2, m, emit_default=True) for m in msgs)
        + b"".join(proto.field_bytes(3, s, emit_default=True) for s in sigs)
    )


def _decode_triples(body: bytes):
    from cometbft_tpu.wire import proto

    fields = proto.decode_fields(body)
    return (
        proto.get_repeated_bytes(fields, 1),
        proto.get_repeated_bytes(fields, 2),
        proto.get_repeated_bytes(fields, 3),
    )


class MultihostShardBackend:
    """The VerifyBackend the LEADER process of a multi-process mesh serves
    through its SidecarServer when the whole mesh is one fanout shard.

    batch_verify re-broadcasts the triples to every follower over the side
    sockets (one framed write each; an empty frame means shutdown), then
    joins the collective step itself — every process runs
    `multihost_verify` on the same batch in the same order, which is what
    the collectives require. The lock serializes broadcasts so the frame
    order IS the collective order even if the server coalescer ever grows
    a second dispatcher. A dead follower surfaces as a socket error or a
    wedged collective; either way the fanout tier times the shard out and
    redistributes its slice — exactly the failure contract fanout shards
    signed up for.

    merkle_root stays host-local (one tree per call has no cross-host
    slicing opportunity, and the leader's host tree is the same ground
    truth the supervisor's anchor uses)."""

    name = "multihost"

    def __init__(self, mesh, followers, axis: str = "sig"):
        self.mesh = mesh
        self.axis = axis
        self._followers = list(followers)  # connected side sockets
        self._lock = threading.Lock()

    def mesh_width(self) -> int:
        return int(self.mesh.devices.size)  # GLOBAL chips, every process

    def batch_verify(self, pubs, msgs, sigs):
        from cometbft_tpu.sidecar.service import write_frame

        if len(pubs) == 0:
            return False, []
        with self._lock:
            body = _encode_triples(pubs, msgs, sigs)
            for sock in self._followers:
                write_frame(sock, body)
            return multihost_verify(self.mesh, pubs, msgs, sigs, self.axis)

    def merkle_root(self, leaves):
        from cometbft_tpu.crypto.merkle.tree import hash_from_byte_slices

        return hash_from_byte_slices(list(leaves))

    def ping(self) -> bool:
        return True

    def close(self) -> None:
        with self._lock:
            for sock in self._followers:
                try:
                    write_frame(sock, b"")  # shutdown sentinel
                    sock.close()
                except OSError:
                    pass
            self._followers = []


def follow_verify_loop(mesh, sock, axis: str = "sig") -> int:
    """Follower side of the serving seam: block on the leader's side
    socket, mirror every broadcast batch into the collective verify step
    (result discarded — the replication already handed the leader the
    bitmap), return the number of batches served when the leader closes
    or sends the empty shutdown frame."""
    from cometbft_tpu.sidecar.service import read_frame

    served = 0
    while True:
        body = read_frame(sock)
        if not body:  # EOF or the b"" shutdown sentinel
            return served
        pubs, msgs, sigs = _decode_triples(body)
        multihost_verify(mesh, pubs, msgs, sigs, axis)
        served += 1

"""Level-synchronous RFC-6962 Merkle hashing on TPU (crypto/merkle device tier).

The reference builds trees by recursive splitting at the largest power of two
(crypto/merkle/tree.go:11-27); pairing adjacent nodes level-by-level with odd
promotion yields the identical tree (tree.go:68-98). The level-synchronous
form is the TPU-native one: each level is a single batched SHA-256 call over
all sibling pairs (full lane width), and a 64k-leaf tree is 17 device calls
instead of 131k sequential host hashes.

Domain separation per RFC 6962 (crypto/merkle/hash.go:11-13):
  leaf  = SHA-256(0x00 || leaf bytes)
  inner = SHA-256(0x01 || left(32) || right(32))   [65 bytes -> 2 blocks]
"""

from __future__ import annotations

import functools
import hashlib
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cometbft_tpu.ops import sha256_kernel as sha


def _inner_core(left, right):
    """Batched inner-node hash. left/right: uint32[8, N] digests."""
    n = left.shape[1]
    # Block 1: 0x01 || left || right[:31]  (big-endian byte stream -> words)
    w = [None] * 16
    w[0] = jnp.uint32(0x01 << 24) | (left[0] >> 8)
    for i in range(1, 8):
        w[i] = (left[i - 1] << 24) | (left[i] >> 8)
    w[8] = (left[7] << 24) | (right[0] >> 8)
    for i in range(9, 16):
        w[i] = (right[i - 9] << 24) | (right[i - 8] >> 8)
    st = sha.compress(sha.iv_state(n), jnp.stack(w))
    # Block 2: last byte of right || 0x80 pad || bit length (65*8 = 520)
    zero = jnp.zeros((n,), jnp.uint32)
    w2 = [zero] * 16
    w2[0] = (right[7] << 24) | jnp.uint32(0x80 << 16)
    w2[15] = jnp.broadcast_to(jnp.uint32(520), (n,))
    return sha.compress(st, jnp.stack(w2))


@functools.lru_cache(maxsize=None)
def _inner_jit(n: int):
    return jax.jit(_inner_core)


def _leaf_core(blocks, nblocks):
    """Hash N variable-length pre-padded messages: blocks uint32[B, 16, N],
    nblocks int32[N]. Lanes stop updating once their block count is reached."""
    n = blocks.shape[2]
    init = sha.iv_state(n)

    def body(i, st):
        new = sha.compress(st, blocks[i])
        active = (i < nblocks)[None, :]
        return jnp.where(active, new, st)

    return lax.fori_loop(0, blocks.shape[0], body, init)


@functools.lru_cache(maxsize=None)
def _leaf_jit(bmax: int, n: int):
    return jax.jit(_leaf_core)


def _pow2_pad(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def hash_leaves_device(items: list[bytes]) -> np.ndarray:
    """RFC-6962 leaf hashes of all items in one device program: uint32[8, n]."""
    n = len(items)
    msgs = [b"\x00" + it for it in items]
    blocks, nblocks = sha.pack_messages(msgs)
    npad = _pow2_pad(n)
    if npad != n:
        blocks = np.pad(blocks, ((0, 0), (0, 0), (0, npad - n)))
        nblocks = np.pad(nblocks, (0, npad - n), constant_values=1)
    out = _leaf_jit(blocks.shape[0], npad)(blocks, nblocks)
    return np.asarray(out)[:, :n]


def tree_levels(leaf_digests: np.ndarray) -> list[np.ndarray]:
    """All tree levels bottom-up from uint32[8, n] leaf digests; each level is
    one batched device call over its sibling pairs (odd node promoted)."""
    levels = [leaf_digests]
    cur = leaf_digests
    while cur.shape[1] > 1:
        m = cur.shape[1]
        pairs = m // 2
        left = cur[:, 0 : 2 * pairs : 2]
        right = cur[:, 1 : 2 * pairs : 2]
        ppad = _pow2_pad(pairs)
        if ppad != pairs:
            left = np.pad(left, ((0, 0), (0, ppad - pairs)))
            right = np.pad(right, ((0, 0), (0, ppad - pairs)))
        nxt = np.asarray(_inner_jit(ppad)(jnp.asarray(left), jnp.asarray(right)))
        nxt = nxt[:, :pairs]
        if m % 2 == 1:
            nxt = np.concatenate([nxt, cur[:, -1:]], axis=1)
        levels.append(nxt)
        cur = nxt
    return levels


def merkle_root(leaves: list[bytes]) -> bytes:
    """Root of the RFC-6962 tree over `leaves` (crypto/merkle/tree.go:11),
    computed level-parallel on device. Empty tree = SHA-256 of empty string
    (crypto/merkle/hash.go empty hash)."""
    if len(leaves) == 0:
        return hashlib.sha256(b"").digest()
    digests = hash_leaves_device(leaves)
    if len(leaves) == 1:
        return sha.digest_words_to_bytes(digests)[0]
    root = tree_levels(digests)[-1]
    return sha.digest_words_to_bytes(root)[0]


def leaves_to_root_core(blocks, nblocks):
    """ONE jittable program: leaf-hash all padded messages AND reduce the
    full tree to the root. blocks uint32[B, 16, n] (n a power of two),
    nblocks int32[n] -> uint32[8, 1]. Fusing the leaf pass and the log2(n)
    inner levels into a single dispatch matters on tunneled deployments
    where each dispatch costs a host round-trip."""
    cur = _leaf_core(blocks, nblocks)
    while cur.shape[1] > 1:
        cur = _inner_core(cur[:, 0::2], cur[:, 1::2])
    return cur


@functools.lru_cache(maxsize=None)
def _leaves_to_root_jit(bmax: int, n: int):
    return jax.jit(leaves_to_root_core)


@functools.lru_cache(maxsize=1)
def _sharded_root():
    """(mesh width, sharded fused leaves->root fn) when this process owns
    multiple chips and the width is a power of two (the subtree-roots top
    reduction pairs level-synchronously), else None. Lazy import: merkle
    callers on single-chip hosts never pull the ed25519 kernel graph."""
    from cometbft_tpu.ops import ed25519_kernel as ek

    w = ek.mesh_width()
    if w <= 1 or w & (w - 1):
        return None
    from cometbft_tpu.ops import sharded

    return w, sharded.sharded_leaves_to_root_fn(
        sharded.make_mesh(jax.local_devices())
    )


def _mesh_merkle_floor() -> int:
    """Leaf count from which the fused root routes to the subtree-parallel
    mesh program. On a single chip the fused program already wins; sharding
    only pays once the leaf pass dominates the collective + top reduction."""
    try:
        return max(1, int(os.environ.get("CMTPU_MESH_MERKLE_FLOOR", "16384")))
    except ValueError:
        return 16384


def merkle_root_fused(leaves: list[bytes]) -> bytes:
    """RFC-6962 root in one device dispatch (power-of-two leaf counts; the
    general path pads via duplicate-free promotion in merkle_root). Forests
    at/above CMTPU_MESH_MERKLE_FLOOR route to ops/sharded's subtree-parallel
    program when this process owns a power-of-two mesh — each chip leaf-
    hashes and reduces its own subtree, still one dispatch end to end."""
    n = len(leaves)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n & (n - 1):
        return merkle_root(leaves)
    msgs = [b"\x00" + it for it in leaves]
    blocks, nblocks = sha.pack_messages(msgs)
    if n >= _mesh_merkle_floor():
        sh = _sharded_root()
        # n and width are both pow2 here, so divisibility of the shard
        # size follows whenever the mesh isn't wider than the forest.
        if sh is not None and n % sh[0] == 0:
            from cometbft_tpu.ops import ed25519_kernel as ek

            ek._mesh_count("merkle_sharded_dispatches")
            out = sh[1](jnp.asarray(blocks), jnp.asarray(nblocks))
            return sha.digest_words_to_bytes(np.asarray(out))[0]
    out = _leaves_to_root_jit(blocks.shape[0], n)(blocks, nblocks)
    return sha.digest_words_to_bytes(np.asarray(out))[0]


@functools.lru_cache(maxsize=None)
def _tree_root_jit(n: int):
    """ONE compiled program reducing uint32[8, n] (n a power of two) leaf
    digests to the root: the level loop unrolls inside jit (log2(n) levels,
    ~100 ops each), so a 64k-leaf tree costs one compile + one dispatch."""

    def root(leaves):
        cur = leaves
        while cur.shape[1] > 1:
            cur = _inner_core(cur[:, 0::2], cur[:, 1::2])
        return cur

    return jax.jit(root)


def merkle_root_pow2(leaf_digests: np.ndarray) -> bytes:
    """Root from uint32[8, n] leaf digests, n a power of two — the bench/
    sharded fast path."""
    n = leaf_digests.shape[1]
    if n & (n - 1):
        raise ValueError("merkle_root_pow2 requires a power-of-two leaf count")
    if n == 1:
        return sha.digest_words_to_bytes(leaf_digests)[0]
    out = _tree_root_jit(n)(jnp.asarray(leaf_digests))
    return sha.digest_words_to_bytes(np.asarray(out))[0]


def merkle_levels_bytes(leaves: list[bytes]) -> list[list[bytes]]:
    """All levels as byte digests (bottom-up) — the proof-building form used
    by crypto/merkle.ProofsFromByteSlices (proof.go:35)."""
    if len(leaves) == 0:
        return [[]]
    digests = hash_leaves_device(leaves)
    return [sha.digest_words_to_bytes(lv) for lv in tree_levels(digests)]


def _leaves_to_levels_core(blocks, nblocks):
    """ONE jittable program: leaf-hash all padded messages and keep EVERY
    tree level (power-of-two n). Returns a tuple of uint32[8, n/2^l]."""
    cur = _leaf_core(blocks, nblocks)
    levels = [cur]
    while cur.shape[1] > 1:
        cur = _inner_core(cur[:, 0::2], cur[:, 1::2])
        levels.append(cur)
    return tuple(levels)


@functools.lru_cache(maxsize=None)
def _leaves_to_levels_jit(bmax: int, n: int):
    return jax.jit(_leaves_to_levels_core)


_level_bytes_arr = sha.digest_words_to_arr


def proof_levels_device(items: list[bytes]) -> list[np.ndarray]:
    """All tree levels as uint8[m, 32] digest arrays, bottom-up. One fused
    dispatch for power-of-two leaf counts; level-per-dispatch otherwise."""
    n = len(items)
    if n & (n - 1) == 0 and n > 0:
        msgs = [b"\x00" + it for it in items]
        blocks, nblocks = sha.pack_messages(msgs)
        levels = _leaves_to_levels_jit(blocks.shape[0], n)(blocks, nblocks)
        return [_level_bytes_arr(np.asarray(lv)) for lv in levels]
    return [_level_bytes_arr(lv) for lv in tree_levels(hash_leaves_device(items))]


def proofs_aunts_device(items: list[bytes]):
    """Device-computed inclusion proofs for every item, in vectorized form:
    (root bytes, leaf_hashes uint8[n, 32], aunts uint8[n, depth, 32],
    aunt_counts int32[n]). The aunt of leaf i at level l is node
    (i >> l) ^ 1 — absent (skipped, odd promotion) when past the level's
    end; identical aunts to the host ProofsFromByteSlices recursion."""
    n = len(items)
    if n == 0:
        raise ValueError(
            "proofs_aunts_device: empty tree has no proofs "
            "(use proofs_from_byte_slices_device for the empty-root case)"
        )
    levels = proof_levels_device(items)
    root = bytes(levels[-1][0])
    depth = len(levels) - 1
    aunts = np.zeros((n, depth, 32), np.uint8)
    counts = np.zeros(n, np.int32)
    idx = np.arange(n)
    for l in range(depth):
        level = levels[l]
        a = (idx >> l) ^ 1
        have = a < level.shape[0]
        rows = idx[have]
        aunts[rows, counts[rows]] = level[a[have]]
        counts[rows] += 1
    return root, levels[0], aunts, counts


class DeviceProofs:
    """Lazy sequence of crypto/merkle Proof objects over the vectorized
    device proof data — building 64k Python Proof objects eagerly costs more
    than the hashing; callers usually need a handful."""

    def __init__(self, root, leaf_hashes, aunts, counts):
        self.root = root
        self._leaf = leaf_hashes
        self._aunts = aunts
        self._counts = counts

    def __len__(self):
        return self._leaf.shape[0]

    def __getitem__(self, i):
        from cometbft_tpu.crypto.merkle.proof import Proof

        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return Proof(
            total=len(self),
            index=i,
            leaf_hash=bytes(self._leaf[i]),
            aunts=[bytes(a) for a in self._aunts[i, : self._counts[i]]],
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


def proofs_from_byte_slices_device(items: list[bytes]):
    """Device analog of crypto/merkle.proofs_from_byte_slices: returns
    (root bytes, DeviceProofs). Falls back to the host implementation for
    the empty tree."""
    if len(items) == 0:
        from cometbft_tpu.crypto.merkle import proofs_from_byte_slices

        return proofs_from_byte_slices(items)
    root, leaf_hashes, aunts, counts = proofs_aunts_device(items)
    return root, DeviceProofs(root, leaf_hashes, aunts, counts)

"""Batched SHA-512 on TPU (device tier of the ed25519 challenge hash).

The verify equation's k = SHA-512(R || A || M) mod L was the last host-side
crypto in the batch path (hashlib, ~12 ms per 10k batch). This kernel hashes
all lanes' messages in SPMD lockstep: 64-bit words are emulated as
(hi, lo) uint32 pairs — TPU has no int64 — with ~5 int32 ops per 64-bit add
(sum + carry-compare) and ~6 per rotation, so one 80-round compression is a
few thousand [N]-wide VPU ops, traced once inside a lax.fori_loop over the
message's 128-byte blocks with per-lane active masking (same pattern as
sha256_kernel._leaf_core).

Host side packs variable-length messages into padded blocks
(pack_messages512, the SHA-512 analog of sha256_kernel.pack_messages).
"""

from __future__ import annotations

import functools
import struct

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# -- constants (FIPS 180-4) --------------------------------------------------

_K = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]
_K_HI = jnp.asarray(np.array([k >> 32 for k in _K], np.uint32))
_K_LO = jnp.asarray(np.array([k & 0xFFFFFFFF for k in _K], np.uint32))


def _add2(a, b):
    """64-bit add of (hi, lo) uint32 pairs."""
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    return a[0] + b[0] + carry, lo


def _add_many(*vals):
    acc = vals[0]
    for v in vals[1:]:
        acc = _add2(acc, v)
    return acc


def _rotr(x, n: int):
    hi, lo = x
    if n == 0:
        return x
    if n < 32:
        return (
            (hi >> n) | (lo << (32 - n)),
            (lo >> n) | (hi << (32 - n)),
        )
    if n == 32:
        return lo, hi
    n -= 32
    return (
        (lo >> n) | (hi << (32 - n)),
        (hi >> n) | (lo << (32 - n)),
    )


def _shr(x, n: int):
    hi, lo = x
    if n < 32:
        return hi >> n, (lo >> n) | (hi << (32 - n))
    return jnp.zeros_like(hi), hi >> (n - 32)


def _xor(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _xor3(a, b, c):
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _big_sigma0(x):
    return _xor3(_rotr(x, 28), _rotr(x, 34), _rotr(x, 39))


def _big_sigma1(x):
    return _xor3(_rotr(x, 14), _rotr(x, 18), _rotr(x, 41))


def _small_sigma0(x):
    return _xor3(_rotr(x, 1), _rotr(x, 8), _shr(x, 7))


def _small_sigma1(x):
    return _xor3(_rotr(x, 19), _rotr(x, 61), _shr(x, 6))


def _ch(x, y, z):
    return (
        (x[0] & y[0]) ^ (~x[0] & z[0]),
        (x[1] & y[1]) ^ (~x[1] & z[1]),
    )


def _maj(x, y, z):
    return (
        (x[0] & y[0]) ^ (x[0] & z[0]) ^ (y[0] & z[0]),
        (x[1] & y[1]) ^ (x[1] & z[1]) ^ (y[1] & z[1]),
    )


def iv_state(n: int):
    """uint32[2, 8, N]: (hi/lo, word, lane)."""
    hi = np.array([v >> 32 for v in _IV], np.uint32)
    lo = np.array([v & 0xFFFFFFFF for v in _IV], np.uint32)
    st = np.stack([hi, lo])[:, :, None]  # [2, 8, 1]
    return jnp.broadcast_to(jnp.asarray(st), (2, 8, n))


def compress(state, block):
    """One SHA-512 compression: state uint32[2, 8, N], block uint32[2, 16, N]
    (big-endian 64-bit message words as hi/lo pairs). The 80 rounds run in a
    lax.fori_loop with the 16-word message schedule as a circular window —
    an unrolled form is ~8k ops per block and XLA:CPU's compile time is
    superlinear in fusion size (same lesson as field25519's lowerings)."""
    n = state.shape[2]

    def w_at(w_arr, j):
        sl = lax.dynamic_slice(w_arr, (0, j, 0), (2, 1, n))
        return sl[0, 0], sl[1, 0]

    def body(t, carry):
        a, b, c, d, e, f, g, h, w_arr = carry
        idx = t % 16
        scheduled = _add_many(
            _small_sigma1(w_at(w_arr, (t - 2) % 16)),
            w_at(w_arr, (t - 7) % 16),
            _small_sigma0(w_at(w_arr, (t - 15) % 16)),
            w_at(w_arr, idx),
        )
        cur = w_at(w_arr, idx)
        in_first16 = t < 16
        wt = (
            jnp.where(in_first16, cur[0], scheduled[0]),
            jnp.where(in_first16, cur[1], scheduled[1]),
        )
        w_arr = lax.dynamic_update_slice(
            w_arr, jnp.stack([wt[0], wt[1]])[:, None, :], (0, idx, 0)
        )
        k = (_K_HI[t], _K_LO[t])
        t1 = _add_many(h, _big_sigma1(e), _ch(e, f, g), k, wt)
        t2 = _add2(_big_sigma0(a), _maj(a, b, c))
        return (_add2(t1, t2), a, b, c, _add2(d, t1), e, f, g, w_arr)

    init = tuple((state[0, i], state[1, i]) for i in range(8))
    carry = (*init, block)
    a, b, c, d, e, f, g, h, _ = lax.fori_loop(0, 80, body, carry)
    out = [a, b, c, d, e, f, g, h]
    hi = jnp.stack([_add2(out[i], (state[0, i], state[1, i]))[0] for i in range(8)])
    lo = jnp.stack([_add2(out[i], (state[0, i], state[1, i]))[1] for i in range(8)])
    return jnp.stack([hi, lo])


def hash_blocks_core(blocks, nblocks):
    """Hash N variable-length pre-padded messages: blocks uint32[B, 2, 16, N]
    (B = max block count), nblocks int32[N]. Lanes stop updating once their
    block count is reached. Returns uint32[2, 8, N]."""
    n = blocks.shape[3]
    init = iv_state(n)

    def body(i, st):
        new = compress(st, blocks[i])
        active = (i < nblocks)[None, None, :]
        return jnp.where(active, new, st)

    return lax.fori_loop(0, blocks.shape[0], body, init)


@functools.lru_cache(maxsize=None)
def _hash_jit(bmax: int, n: int):
    return jax.jit(hash_blocks_core)


def blocks_for(lens: np.ndarray) -> np.ndarray:
    """Message byte lengths -> SHA-512 block counts (0x80 + 16-byte len)."""
    return ((lens + 17 + 127) // 128).astype(np.int32)


def write_padding(buf: np.ndarray, lens: np.ndarray, nblocks: np.ndarray) -> None:
    """Write the FIPS 180-4 pad into buf uint8[n, B*128] rows holding
    messages of the given byte lengths: the 0x80 terminator plus the
    128-bit big-endian bit length at each row's last-block end (messages
    here are < 2^53 bits so the low 64 bits suffice). Shared by the generic
    packer and the ed25519 challenge packer so the padding rules live once."""
    n = buf.shape[0]
    idx = np.arange(n)
    buf[idx, lens] = 0x80
    ends = nblocks.astype(np.int64) * 128
    bl_bytes = (lens * 8).astype(">u8").view(np.uint8).reshape(n, 8)
    for k in range(8):
        buf[idx, ends - 8 + k] = bl_bytes[:, k]


def pack_messages512(msgs: list[bytes]):
    """Pad + pack variable-length messages into SHA-512 blocks:
    (uint32[B, 2, 16, N], int32[N]). Vectorized where it counts: one
    big byte buffer, length-grouped padding writes."""
    n = len(msgs)
    lens = np.fromiter((len(m) for m in msgs), np.int64, n)
    nblocks = blocks_for(lens)
    bmax = int(nblocks.max()) if n else 1
    buf = np.zeros((n, bmax * 128), np.uint8)
    for i, m in enumerate(msgs):
        buf[i, : lens[i]] = np.frombuffer(m, np.uint8)
    write_padding(buf, lens, nblocks)
    words = buf.view(">u4").reshape(n, bmax, 32).astype(np.uint32)
    # -> [B, 2(hi/lo), 16, N]: 64-bit word t is words[.., 2t](hi), 2t+1(lo)
    hi = words[:, :, 0::2]
    lo = words[:, :, 1::2]
    out = np.stack([hi, lo], axis=1).transpose(2, 1, 3, 0)
    return np.ascontiguousarray(out), nblocks


def bswap32(x):
    """Device-side 32-bit byte swap (uint32 arrays)."""
    return (
        ((x & jnp.uint32(0xFF)) << 24)
        | ((x & jnp.uint32(0xFF00)) << 8)
        | ((x >> 8) & jnp.uint32(0xFF00))
        | (x >> 24)
    )


def digest_to_le_words(state):
    """Device-side uint32[2, 8, N] SHA-512 state -> int32[16, N] little-endian
    uint32 words of the 64-byte digest stream (the layout
    unpack.digest_words_to_digits consumes). Word 2t is the byte-swapped hi
    half of 64-bit word t, word 2t+1 the byte-swapped lo half."""
    hi = bswap32(state[0])  # [8, N]
    lo = bswap32(state[1])
    out = jnp.stack([hi, lo], axis=1).reshape(16, -1)  # interleave hi/lo
    return out.astype(jnp.int32)


def digest_words_to_arr(state: np.ndarray) -> np.ndarray:
    """uint32[2, 8, N] -> uint8[N, 64] big-endian digests."""
    st = np.asarray(state)
    inter = np.empty((st.shape[2], 16), np.uint32)
    inter[:, 0::2] = st[0].T
    inter[:, 1::2] = st[1].T
    return np.ascontiguousarray(inter.astype(">u4")).view(np.uint8).reshape(-1, 64)


def sha512_batch(msgs: list[bytes]) -> list[bytes]:
    """Hash a batch of messages on device; returns 64-byte digests."""
    if not msgs:
        return []
    blocks, nblocks = pack_messages512(msgs)
    st = _hash_jit(blocks.shape[0], blocks.shape[3])(blocks, nblocks)
    return [bytes(r) for r in digest_words_to_arr(np.asarray(st))]

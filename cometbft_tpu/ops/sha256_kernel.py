"""Vectorized SHA-256 for TPU (device tier of crypto/tmhash + crypto/merkle).

One compression call hashes N independent 64-byte blocks in SPMD lockstep:
state and message words are uint32[·, N] with the batch in the lane
dimension. uint32 adds wrap mod 2^32 natively, so the round function is
exactly FIPS 180-4 with no emulation.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from cometbft_tpu import native

_K = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
]

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def iv_state(n):
    """Initial state broadcast to batch n: uint32[8, N]."""
    return jnp.broadcast_to(
        jnp.asarray(IV, jnp.uint32)[:, None], (8, n)
    )


def compress(state, words):
    """One SHA-256 compression. state uint32[8, N], words uint32[16, N].

    Rolled into two fori_loops (message schedule, then rounds) so the graph
    stays ~100 ops regardless of the 64-round depth — unrolling produced a
    1k-op chain that XLA compiled orders of magnitude slower."""
    from jax import lax

    n = words.shape[1]
    # Tie the state carry to the (possibly device-varying) words so the loop
    # carries have uniform varying-axes under shard_map (no-op arithmetic).
    state = state + (words[:1] & jnp.uint32(0))
    w = jnp.concatenate([words, jnp.zeros((48, n), jnp.uint32)], axis=0)

    def sched(t, w):
        x15 = w[t - 15]
        x2 = w[t - 2]
        s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> 3)
        s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> 10)
        return w.at[t].set(w[t - 16] + s0 + w[t - 7] + s1)

    w = lax.fori_loop(16, 64, sched, w)
    k = jnp.asarray(_K, jnp.uint32)

    def rnd(t, carry):
        a, b, c, d, e, f, g, h = carry
        big_s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + big_s1 + ch + k[t] + w[t]
        big_s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = big_s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    out = lax.fori_loop(0, 64, rnd, tuple(state[i] for i in range(8)))
    return state + jnp.stack(out)


def pack_messages(msgs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Host: SHA-256 pad N byte strings -> (uint32[B, 16, N] big-endian word
    blocks, int32[N] block counts), B = max blocks over the batch.  The
    native tier fuses the pad and the [N,B,16]->[B,16,N] lane transpose in
    one tiled C pass (cmtpu_sha256_pack); the numpy fallback is fully
    vectorized but pays an 8 MB strided transpose at 64k messages (~40 ms
    measured against the device Merkle path's 215 ms total)."""
    n = len(msgs)
    if n == 0:
        return np.zeros((1, 16, 0), np.uint32), np.zeros(0, np.int32)
    lens = np.fromiter((len(m) for m in msgs), np.int64, n)
    lib = native.ready()
    if lib is None:
        native.ensure_built_async()
    else:
        bmax = int((int(lens.max()) + 8) // 64 + 1)
        offs = np.zeros(n + 1, np.uint64)
        np.cumsum(lens, out=offs[1:])
        out = np.empty((bmax, 16, n), np.uint32)
        nblocks = np.empty(n, np.int32)
        lib.cmtpu_sha256_pack(
            n,
            b"".join(msgs),
            offs.ctypes.data,
            bmax,
            out.ctypes.data,
            nblocks.ctypes.data,
        )
        if nblocks[0] != -1:  # -1 = allocation failure; fall through
            return out, nblocks
    return _pack_messages_np(msgs, lens)


def _pack_messages_np(
    msgs: list[bytes], lens: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Numpy fallback for pack_messages (also the test anchor for the C
    path): one join + fancy-index scatter + strided lane transpose."""
    n = len(msgs)
    nblocks = ((lens + 8) // 64 + 1).astype(np.int32)
    bmax = int(nblocks.max())
    buf = np.zeros((n, bmax * 64), np.uint8)
    flat = np.frombuffer(b"".join(msgs), np.uint8)
    rows = np.repeat(np.arange(n), lens)
    ends = np.cumsum(lens)
    cols = np.arange(ends[-1]) - np.repeat(ends - lens, lens)
    buf[rows, cols] = flat
    ridx = np.arange(n)
    buf[ridx, lens] = 0x80
    bl = nblocks.astype(np.int64) * 64
    bitlen = lens * 8
    for k in range(8):
        buf[ridx, bl - 8 + k] = (bitlen >> (8 * (7 - k))) & 0xFF
    words = buf.reshape(n, bmax, 16, 4)
    words = (
        words[..., 0].astype(np.uint32) << 24
        | words[..., 1].astype(np.uint32) << 16
        | words[..., 2].astype(np.uint32) << 8
        | words[..., 3].astype(np.uint32)
    )  # [N, B, 16]
    return np.ascontiguousarray(words.transpose(1, 2, 0)), nblocks


def digest_words_to_arr(words: np.ndarray) -> np.ndarray:
    """uint32[8, N] -> uint8[N, 32] big-endian digests (host, vectorized)."""
    w = np.asarray(words).T.astype(">u4")  # [N, 8]
    return np.ascontiguousarray(w).view(np.uint8).reshape(w.shape[0], 32)


def digest_words_to_bytes(words: np.ndarray) -> list[bytes]:
    """uint32[8, N] -> N 32-byte big-endian digests (host)."""
    return [bytes(row) for row in digest_words_to_arr(words)]

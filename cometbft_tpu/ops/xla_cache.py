"""One definition of the repo's persistent XLA compile-cache setup.

The 8-device virtual-mesh programs (sharded verify, the two-process
multihost commit step, the bn254 aggregate kernel) cost tens of seconds
to compile on XLA:CPU; pointing every jax-using entry point — conftest,
bench subprocess workers, the multihost/fanout shard workers — at the
same `.jax_cache` directory under the repo root means each program
compiles once per machine, not once per process. This used to be the
same five lines copy-pasted into each of those files; a helper keeps the
next worker script from drifting (e.g. forgetting the min-size knobs and
silently caching nothing).
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def cache_dir(repo_root: str | None = None) -> str:
    return os.path.join(repo_root or _REPO_ROOT, ".jax_cache")


def enable_persistent_cache(repo_root: str | None = None) -> bool:
    """Point this process's JAX at the shared on-disk compile cache, with
    the size/time floors zeroed so even small programs persist. Imports
    jax (and may initialize its config layer, NOT the backend); returns
    False instead of raising when the running jaxlib lacks the knobs, so
    callers can log-and-continue."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir(repo_root))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return True
    except Exception:
        return False

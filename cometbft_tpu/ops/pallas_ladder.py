"""Pallas lowering of the windowed double-scalar ladder (weak-#5 probe).

The XLA lowerings trade arithmetic shape against GRAPH SIZE: the planar
row form is the minimal-arithmetic program but its ~75k-op full-ladder
graph never finished compiling on the device, so the stacked Toeplitz
band (same products, ~45x smaller graph) became the accelerator default
(ops/DESIGN.md).  Pallas dissolves that trade: the whole ladder runs as
ONE kernel whose body Mosaic compiles once — accumulator, the per-lane
[1..8]A table, and every intermediate live in VMEM across all 252
doublings instead of streaming through HBM between XLA fusions — and the
body is the planar row arithmetic (reusing field25519's closure-free
_mul_rows/_sq_rows/_carry_rows), because inside a kernel the graph-size
concern is gone.

Pallas rejects kernels that close over ARRAY constants, so every field
constant here (4p, 2d, the [0..8]B table) is plain python ints that
broadcast into the lanes; the algorithms mirror ops/edwards.py exactly
(same precomp form, same signed-window schedule) and are held to it by
tests/test_pallas_ladder.py in interpret mode.

Routed by CMTPU_LADDER=pallas (ed25519_kernel); A/B'd on device by
tpu_ab.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from cometbft_tpu.ops import edwards as ed
from cometbft_tpu.ops import field25519 as fe

TILE = 128

# Constants as python ints (closure-safe in Pallas kernels).
_F4 = [int(v) for v in np.asarray(fe._FOUR_P).reshape(-1)]
_TWO_D = [int(v) for v in fe.int_to_limbs(fe.TWO_D_INT)]
# [0..8]B in precomp form (ymx, ypx, 2dT, Z), [9][4][17] ints.
_TB = [
    [[int(v) for v in np.asarray(ed.TABLE_B_PRE)[e, c, :, 0]] for c in range(4)]
    for e in range(9)
]

_mulr = fe._mul_rows
_sqr = fe._sq_rows
_carryr = fe._carry_rows


def _addr(a, b):
    return _carryr([x + y for x, y in zip(a, b)])


def _subr(a, b):
    return _carryr([x + p4 - y for x, y, p4 in zip(a, b, _F4)])


def _negr(a):
    return _carryr([p4 - x for x, p4 in zip(a, _F4)])


def _mul_intconst(a, climbs):
    return _mulr(a, climbs)


def _to_precomp(p):
    """(X:Y:Z:T) -> (Y-X, Y+X, 2d*T, Z), mirroring edwards.to_precomp."""
    x, y, z, t = p
    return (_subr(y, x), _addr(y, x), _mul_intconst(t, _TWO_D), z)


def _add_precomp(p, q_pre, z2_is_one):
    """edwards._add_precomp_core: complete hwcd addition against a
    precomputed point; z2_is_one skips the Z1*Z2 multiply."""
    x1, y1, z1, t1 = p
    ymx, ypx, td2, z2 = q_pre
    a = _mulr(_subr(y1, x1), ymx)
    b = _mulr(_addr(y1, x1), ypx)
    c = _mulr(t1, td2)
    zz = z1 if z2_is_one else _mulr(z1, z2)
    d = _carryr([2 * v for v in zz])
    e = _subr(b, a)
    f = _subr(d, c)
    g = _addr(d, c)
    h = _addr(b, a)
    return (_mulr(e, f), _mulr(g, h), _mulr(f, g), _mulr(e, h))


def _pdbl(p):
    """edwards.point_double (dbl-2008-hwcd for a = -1)."""
    x1, y1, z1, _ = p
    a = _sqr(x1)
    b = _sqr(y1)
    zz = _sqr(z1)
    c = _carryr([2 * v for v in zz])
    e = _subr(_subr(_sqr(_addr(x1, y1)), a), b)
    g = _subr(b, a)
    f = _subr(g, c)
    h = _negr(_addr(a, b))
    return (_mulr(e, f), _mulr(g, h), _mulr(f, g), _mulr(e, h))


def _select_a(table, digits):
    """Signed lookup from the per-lane A table (list of 8 precomp entries
    for [1..8]A): |d| selects, d<0 negates (swap ymx/ypx, negate 2dT),
    d==0 yields the precomp identity (1, 1, 0, 1)."""
    idx = jnp.abs(digits)
    neg = digits < 0
    one = jnp.ones_like(digits)
    zero = jnp.zeros_like(digits)
    out = []
    for coord in range(4):
        rows = []
        for limb in range(fe.LIMBS):
            # identity entry: ymx=ypx=z=1 (limb0), 2dT=0
            init = (
                one if (coord in (0, 1, 3) and limb == 0) else zero
            )
            acc = init
            for e in range(1, 9):
                acc = jnp.where(idx == e, table[e - 1][coord][limb], acc)
            rows.append(acc)
        out.append(rows)
    ymx, ypx, td2, z = out
    sel_ymx = [jnp.where(neg, b, a) for a, b in zip(ymx, ypx)]
    sel_ypx = [jnp.where(neg, a, b) for a, b in zip(ymx, ypx)]
    ntd2 = _negr(td2)
    sel_td2 = [jnp.where(neg, b, a) for a, b in zip(td2, ntd2)]
    return (sel_ymx, sel_ypx, sel_td2, z)


def _select_b(digits):
    """Signed lookup from the constant [0..8]B table (python ints)."""
    idx = jnp.abs(digits)
    neg = digits < 0
    out = []
    for coord in range(4):
        rows = []
        for limb in range(fe.LIMBS):
            acc = jnp.full_like(digits, _TB[0][coord][limb])
            for e in range(1, 9):
                acc = jnp.where(idx == e, _TB[e][coord][limb], acc)
            rows.append(acc)
        out.append(rows)
    ymx, ypx, td2, z = out
    sel_ymx = [jnp.where(neg, b, a) for a, b in zip(ymx, ypx)]
    sel_ypx = [jnp.where(neg, a, b) for a, b in zip(ymx, ypx)]
    ntd2 = _negr(td2)
    sel_td2 = [jnp.where(neg, b, a) for a, b in zip(td2, ntd2)]
    return (sel_ymx, sel_ypx, sel_td2, z)


def _ladder_math(s_dig, k_dig, ax, ay, az, at, n_windows=None):
    """The closure-free ladder over stacked [.., T] arrays — the kernel
    body's math, also directly jit-testable on CPU without Pallas emulation
    (tests/test_pallas_ladder.py).  n_windows < DIGITS truncates to the top
    windows (the cheap interpret-mode plumbing smoke)."""
    if n_windows is None:
        n_windows = ed.DIGITS
    a_point = tuple(
        [r[i] for i in range(fe.LIMBS)] for r in (ax, ay, az, at)
    )

    # per-lane [1..8]A table in precomp form. The chain is UNROLLED in
    # python: the rolled fori_loop form needed `tbl.at[i].set(...)` with a
    # traced index, which jnp lowers to `scatter` — a primitive Mosaic's TC
    # kernel lowering does not implement (measured on device, tpu_ab.log
    # round 5). Seven inlined point adds cost trace size, but inside ONE
    # Mosaic kernel the XLA whole-graph compile ceiling that forced the
    # rolled form on the stacked path does not apply.
    pp = _to_precomp(a_point)
    table = [pp]
    cur = a_point
    for _ in range(7):
        cur = _add_precomp(cur, pp, z2_is_one=False)
        table.append(_to_precomp(cur))

    t = s_dig.shape[1]
    zero = jnp.zeros((t,), jnp.int32)
    one = jnp.ones((t,), jnp.int32)
    ident = (
        [zero] * fe.LIMBS,
        [one] + [zero] * (fe.LIMBS - 1),
        [one] + [zero] * (fe.LIMBS - 1),
        [zero] * fe.LIMBS,
    )

    def body(w, acc):
        row = ed.DIGITS - 1 - w
        # rolled doublings (same compile-size control as the XLA ladder)
        acc = lax.fori_loop(
            0, ed.WINDOW_BITS,
            lambda _, a: tuple(tuple(c) for c in _pdbl(a)), acc,
        )
        # Digit-row fetch as a one-hot masked reduction: Mosaic's TC
        # lowering implements neither `scatter` nor `dynamic_slice`
        # (both measured on device, round-5 A/B), and a [DIGITS, T]
        # mask-multiply-sum per window is noise next to the point math.
        sel = (
            lax.broadcasted_iota(jnp.int32, (ed.DIGITS, 1), 0) == row
        ).astype(jnp.int32)
        kd = jnp.sum(k_dig * sel, axis=0)
        sd = jnp.sum(s_dig * sel, axis=0)
        acc = _add_precomp(acc, _select_a(table, kd), z2_is_one=False)
        acc = _add_precomp(acc, _select_b(sd), z2_is_one=True)
        # normalize to the carry treedef (tuples, not the lists the row
        # helpers produce)
        return tuple(tuple(c) for c in acc)

    acc = lax.fori_loop(0, n_windows, body, tuple(tuple(c) for c in ident))
    return tuple(jnp.stack(list(c)) for c in acc)


def _ladder_kernel(s_ref, k_ref, ax_ref, ay_ref, az_ref, at_ref,
                   ox_ref, oy_ref, oz_ref, ot_ref, *, n_windows):
    outs = _ladder_math(
        s_ref[...], k_ref[...], ax_ref[...], ay_ref[...], az_ref[...],
        at_ref[...], n_windows=n_windows,
    )
    ox_ref[...] = outs[0]
    oy_ref[...] = outs[1]
    oz_ref[...] = outs[2]
    ot_ref[...] = outs[3]


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile", "n_windows")
)
def _ladder_call(s_digits, k_digits, ax, ay, az, at, interpret=False,
                 tile=TILE, n_windows=None):
    n = s_digits.shape[1]
    assert n % tile == 0, n
    grid = (n // tile,)
    dig_spec = pl.BlockSpec((ed.DIGITS, tile), lambda i: (0, i))
    fe_spec = pl.BlockSpec((fe.LIMBS, tile), lambda i: (0, i))
    out_shape = [
        jax.ShapeDtypeStruct((fe.LIMBS, n), jnp.int32) for _ in range(4)
    ]
    return pl.pallas_call(
        functools.partial(_ladder_kernel, n_windows=n_windows),
        grid=grid,
        in_specs=[dig_spec, dig_spec, fe_spec, fe_spec, fe_spec, fe_spec],
        out_specs=[fe_spec, fe_spec, fe_spec, fe_spec],
        out_shape=out_shape,
        interpret=interpret,
    )(s_digits, k_digits, ax, ay, az, at)


def windowed_double_base_mult(
    s_digits: jnp.ndarray,
    k_digits: jnp.ndarray,
    a_point,
    interpret: bool = False,
    tile: int = TILE,
    n_windows: int | None = None,
):
    """Drop-in for edwards.windowed_double_base_mult via one Pallas kernel.

    Lanes are padded to a tile multiple (callers are shape-bucketed exactly
    like the XLA path, so padding cost is bounded).  `tile`/`n_windows` are
    overridable for interpret-mode tests, where small shapes keep the
    emulation cheap."""
    n = s_digits.shape[1]
    pad = (-n) % tile
    if pad:
        s_digits = jnp.pad(s_digits, ((0, 0), (0, pad)))
        k_digits = jnp.pad(k_digits, ((0, 0), (0, pad)))
        a_point = tuple(jnp.pad(c, ((0, 0), (0, pad))) for c in a_point)
    outs = _ladder_call(
        s_digits, k_digits, *a_point, interpret=interpret, tile=tile,
        n_windows=n_windows,
    )
    if pad:
        outs = [o[:, :n] for o in outs]
    return tuple(outs)

"""Batched edwards25519 point operations for TPU.

Points are extended homogeneous coordinates (X, Y, Z, T), each an
int32[16, N] field element (see field25519). On edwards25519, a = -1 is a
square mod p and d is not, so the hwcd-3 addition formula is COMPLETE: one
branch-free formula covers doubling, identity, and small-order inputs —
exactly what SPMD lockstep over a signature batch needs (the reference's
curve25519-voi backend branches per point class instead;
crypto/ed25519/ed25519.go:27-29).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from cometbft_tpu.ops import field25519 as fe

# -- constants ---------------------------------------------------------------

_P = fe.P_INT
_D = fe.D_INT
_BY = (4 * pow(5, _P - 2, _P)) % _P


def _recover_x_int(y: int, sign: int) -> int:
    y2 = y * y % _P
    u = (y2 - 1) % _P
    v = (_D * y2 + 1) % _P
    x = (u * pow(v, 3, _P)) % _P * pow((u * pow(v, 7, _P)) % _P, (_P - 5) // 8, _P) % _P
    if v * x % _P * x % _P != u:
        x = x * fe.SQRT_M1_INT % _P
    if x & 1 != sign:
        x = _P - x
    return x


_BX = _recover_x_int(_BY, 0)

D_FE = fe.const_fe(_D)
TWO_D_FE = fe.const_fe(fe.TWO_D_INT)
SQRT_M1_FE = fe.const_fe(fe.SQRT_M1_INT)
ONE_FE = fe.const_fe(1)
ZERO_FE = fe.const_fe(0)
BASE_X = fe.const_fe(_BX)
BASE_Y = fe.const_fe(_BY)
BASE_T = fe.const_fe(_BX * _BY % _P)


def identity(n: int):
    """(0 : 1 : 1 : 0) broadcast to batch n."""
    z = jnp.zeros((fe.LIMBS, n), jnp.int32)
    o = jnp.tile(ONE_FE, (1, n))
    return (z, o, o, jnp.zeros((fe.LIMBS, n), jnp.int32))


def base_point(n: int):
    """The ed25519 base point broadcast to batch n."""
    return (
        jnp.tile(BASE_X, (1, n)),
        jnp.tile(BASE_Y, (1, n)),
        jnp.tile(ONE_FE, (1, n)),
        jnp.tile(BASE_T, (1, n)),
    )


# -- group law ---------------------------------------------------------------


def point_add(p, q):
    """Unified complete addition (add-2008-hwcd-3, a=-1): 9 field muls."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe.fe_mul(fe.fe_sub(y1, x1), fe.fe_sub(y2, x2))
    b = fe.fe_mul(fe.fe_add(y1, x1), fe.fe_add(y2, x2))
    c = fe.fe_mul(fe.fe_mul(t1, TWO_D_FE), t2)
    zz = fe.fe_mul(z1, z2)
    d = fe.fe_add(zz, zz)
    e = fe.fe_sub(b, a)
    f = fe.fe_sub(d, c)
    g = fe.fe_add(d, c)
    h = fe.fe_add(b, a)
    return (fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g), fe.fe_mul(e, h))


def point_double(p):
    """dbl-2008-hwcd for a=-1: 4 squarings + 4 muls."""
    x1, y1, z1, _ = p
    a = fe.fe_sq(x1)
    b = fe.fe_sq(y1)
    zz = fe.fe_sq(z1)
    c = fe.fe_add(zz, zz)
    e = fe.fe_sub(fe.fe_sub(fe.fe_sq(fe.fe_add(x1, y1)), a), b)
    g = fe.fe_sub(b, a)           # a*A + B with a = -1
    f = fe.fe_sub(g, c)
    h = fe.fe_neg(fe.fe_add(a, b))  # a*A - B
    return (fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g), fe.fe_mul(e, h))


def point_neg(p):
    x, y, z, t = p
    return (fe.fe_neg(x), y, z, fe.fe_neg(t))


def point_select(mask, p, q):
    """Per-lane point select: mask bool[N]."""
    return tuple(fe.fe_select(mask, a, b) for a, b in zip(p, q))


def point_is_identity(p):
    """bool[N]: P == (0:1:1:0), i.e. X == 0 and Y == Z (projectively)."""
    x, y, z, _ = p
    return fe.fe_is_zero(x) & fe.fe_is_zero(fe.fe_sub(y, z))


def point_compress(p) -> jnp.ndarray:
    """Canonical 255-bit y with x-parity sign bit, as limbs [16, N] plus the
    sign bool[N] (serialization handled host-side)."""
    x, y, z, _ = p
    zinv = fe.fe_invert(z)
    xa = fe.fe_freeze(fe.fe_mul(x, zinv))
    ya = fe.fe_freeze(fe.fe_mul(y, zinv))
    return ya, (xa[0] & 1) == 1


# -- decompression (ZIP-215) -------------------------------------------------


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Batched ZIP-215 decoding (mirrors crypto/ed25519_pure.point_decompress_
    zip215): y may be non-canonical (>= p, reduced implicitly); x = 0 with
    sign 1 rejected; returns (point, ok[N])."""
    y = y_limbs
    y2 = fe.fe_sq(y)
    u = fe.fe_sub(y2, jnp.broadcast_to(ONE_FE, y.shape))
    v = fe.fe_add(fe.fe_mul(y2, D_FE), jnp.broadcast_to(ONE_FE, y.shape))
    v3 = fe.fe_mul(fe.fe_sq(v), v)
    v7 = fe.fe_mul(fe.fe_sq(v3), v)
    t = fe.fe_pow2523(fe.fe_mul(u, v7))
    x = fe.fe_mul(fe.fe_mul(u, v3), t)  # candidate root of u/v
    vxx = fe.fe_mul(v, fe.fe_sq(x))
    ok_direct = fe.fe_eq(vxx, u)
    ok_flip = fe.fe_is_zero(fe.fe_add(vxx, u))  # vxx == -u
    x = fe.fe_select(ok_flip & ~ok_direct, fe.fe_mul(x, SQRT_M1_FE), x)
    ok = ok_direct | ok_flip
    x_is_zero = fe.fe_is_zero(x)
    ok = ok & ~(x_is_zero & sign)
    x = fe.fe_select(fe.fe_parity(x) != sign, fe.fe_neg(x), x)
    return (x, y, jnp.broadcast_to(ONE_FE, y.shape), fe.fe_mul(x, y)), ok


# -- stacked (lane-concatenated) group ops -----------------------------------
#
# The MXU/VPU want FEW, WIDE ops: each hwcd stage's 4 independent field muls
# are concatenated along the batch axis into ONE [17, 4N] fe_mul, so a ladder
# step is 4 wide muls instead of 17 narrow ones — 4x fewer dispatches/HLO ops
# (faster XLA compile) and 4x wider matmul N for MXU tiling. The addend comes
# from a table kept in precomputed (y-x, y+x, 2d*t, z) form, the standard
# "cached point" trick, so its 2d scaling costs nothing inside the loop.


def _mul4(xs, ys):
    """Four independent fe_mul as one wide one. xs/ys: 4-tuples of [17, N]."""
    n = xs[0].shape[1]
    x = jnp.concatenate(xs, axis=1)
    y = jnp.concatenate(ys, axis=1)
    z = fe.fe_mul(x, y)
    return (z[:, :n], z[:, n : 2 * n], z[:, 2 * n : 3 * n], z[:, 3 * n :])


def to_precomp(p):
    """(X:Y:Z:T) -> (Y-X, Y+X, 2d*T, Z)."""
    x, y, z, t = p
    return (fe.fe_sub(y, x), fe.fe_add(y, x), fe.fe_mul(t, TWO_D_FE), z)


def precomp_identity(n: int):
    o = jnp.tile(ONE_FE, (1, n))
    return (o, o, jnp.zeros((fe.LIMBS, n), jnp.int32), o)


def precomp_select(mask, p, q):
    return tuple(fe.fe_select(mask, a, b) for a, b in zip(p, q))


def add_precomp(p, q_pre):
    """Complete addition against a precomputed point: 2 wide muls."""
    x1, y1, z1, t1 = p
    ymx, ypx, td2, z2 = q_pre
    a, b, c, zz = _mul4(
        (fe.fe_sub(y1, x1), fe.fe_add(y1, x1), t1, z1), (ymx, ypx, td2, z2)
    )
    d = fe.fe_add(zz, zz)
    e = fe.fe_sub(b, a)
    f = fe.fe_sub(d, c)
    g = fe.fe_add(d, c)
    h = fe.fe_add(b, a)
    return _mul4((e, g, f, e), (f, h, g, h))


def double_stacked(p):
    """dbl-2008-hwcd as 2 wide muls (one a wide square)."""
    x1, y1, z1, _ = p
    s = jnp.concatenate((x1, y1, z1, fe.fe_add(x1, y1)), axis=1)
    sq = fe.fe_sq(s)
    n = x1.shape[1]
    a, b, zz, s4 = (
        sq[:, :n],
        sq[:, n : 2 * n],
        sq[:, 2 * n : 3 * n],
        sq[:, 3 * n :],
    )
    c = fe.fe_add(zz, zz)
    e = fe.fe_sub(fe.fe_sub(s4, a), b)
    g = fe.fe_sub(b, a)
    f = fe.fe_sub(g, c)
    h = fe.fe_neg(fe.fe_add(a, b))
    return _mul4((e, g, f, e), (f, h, g, h))


# -- double-scalar multiplication -------------------------------------------

SCALAR_BITS = 253  # scalars are < L < 2^253


def shamir_double_base_mult(s_bits: jnp.ndarray, k_bits: jnp.ndarray, a_point):
    """[s]B + [k]A batched: interleaved (Shamir) MSB-first double-and-add over
    the precomputed table {identity, B, A, B+A}, one complete add per bit —
    the batched analog of the reference's double-scalar verification equation
    (crypto/ed25519/ed25519.go:168-175). 4 wide [17,4N] muls per bit.

    s_bits/k_bits: int32[253, N] (bit i = coefficient of 2^i).
    """
    n = s_bits.shape[1]
    ident = identity(n)
    b = base_point(n)
    id_pre = precomp_identity(n)
    b_pre = to_precomp(b)
    a_pre = to_precomp(a_point)
    ba_pre = to_precomp(point_add(b, a_point))

    def body(i, acc):
        idx = SCALAR_BITS - 1 - i
        bs = s_bits[idx] == 1
        bk = k_bits[idx] == 1
        acc = double_stacked(acc)
        addend = precomp_select(
            bs & bk,
            ba_pre,
            precomp_select(bk, a_pre, precomp_select(bs, b_pre, id_pre)),
        )
        return add_precomp(acc, addend)

    return lax.fori_loop(0, SCALAR_BITS, body, ident)


def scalars_to_bits(scalars: np.ndarray) -> np.ndarray:
    """uint8[N, 32] little-endian scalars -> int32[253, N] bit planes (host)."""
    bits = np.unpackbits(scalars, axis=1, bitorder="little")  # [N, 256]
    return np.ascontiguousarray(bits[:, :SCALAR_BITS].T).astype(np.int32)

"""Batched edwards25519 point operations for TPU.

Points are extended homogeneous coordinates (X, Y, Z, T), each an
int32[17, N] field element (see field25519). On edwards25519, a = -1 is a
square mod p and d is not, so the hwcd-3 addition formula is COMPLETE: one
branch-free formula covers doubling, identity, and small-order inputs —
exactly what SPMD lockstep over a signature batch needs (the reference's
curve25519-voi backend branches per point class instead;
crypto/ed25519/ed25519.go:27-29).

Double-scalar multiplication [s]B + [k]A uses SIGNED 4-bit fixed windows
(64 digits in [-8, 8]): 4 doublings + 2 precomputed-table additions per
window instead of the 1 doubling + 1 addition per BIT of a Shamir ladder —
252 doublings + 128 adds total vs 253 + 253. The per-lane table for A is
built once per batch (4 doublings + 3 additions); the table for the fixed
base B is a compile-time constant (the analog of curve25519-voi's fixed-base
precomputation that the reference's single-verify path leans on). Negated
digits cost one conditional precomp negation — on Edwards that is a
coordinate swap, which is why signed windows halve the table size for free.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from cometbft_tpu.ops import field25519 as fe

# -- constants ---------------------------------------------------------------

_P = fe.P_INT
_D = fe.D_INT
_BY = (4 * pow(5, _P - 2, _P)) % _P


def _recover_x_int(y: int, sign: int) -> int:
    y2 = y * y % _P
    u = (y2 - 1) % _P
    v = (_D * y2 + 1) % _P
    x = (u * pow(v, 3, _P)) % _P * pow((u * pow(v, 7, _P)) % _P, (_P - 5) // 8, _P) % _P
    if v * x % _P * x % _P != u:
        x = x * fe.SQRT_M1_INT % _P
    if x & 1 != sign:
        x = _P - x
    return x


_BX = _recover_x_int(_BY, 0)

D_FE = fe.const_fe(_D)
TWO_D_FE = fe.const_fe(fe.TWO_D_INT)
SQRT_M1_FE = fe.const_fe(fe.SQRT_M1_INT)
ONE_FE = fe.const_fe(1)
ZERO_FE = fe.const_fe(0)
BASE_X = fe.const_fe(_BX)
BASE_Y = fe.const_fe(_BY)
BASE_T = fe.const_fe(_BX * _BY % _P)


def identity(n: int):
    """(0 : 1 : 1 : 0) broadcast to batch n."""
    z = jnp.zeros((fe.LIMBS, n), jnp.int32)
    o = jnp.tile(ONE_FE, (1, n))
    return (z, o, o, jnp.zeros((fe.LIMBS, n), jnp.int32))


def base_point(n: int):
    """The ed25519 base point broadcast to batch n."""
    return (
        jnp.tile(BASE_X, (1, n)),
        jnp.tile(BASE_Y, (1, n)),
        jnp.tile(ONE_FE, (1, n)),
        jnp.tile(BASE_T, (1, n)),
    )


# -- group law ---------------------------------------------------------------


def point_add(p, q):
    """Unified complete addition (add-2008-hwcd-3, a=-1): 9 field muls."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe.fe_mul(fe.fe_sub(y1, x1), fe.fe_sub(y2, x2))
    b = fe.fe_mul(fe.fe_add(y1, x1), fe.fe_add(y2, x2))
    c = fe.fe_mul(fe.fe_mul(t1, TWO_D_FE), t2)
    zz = fe.fe_mul(z1, z2)
    d = fe.fe_add(zz, zz)
    e = fe.fe_sub(b, a)
    f = fe.fe_sub(d, c)
    g = fe.fe_add(d, c)
    h = fe.fe_add(b, a)
    return (fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g), fe.fe_mul(e, h))


def point_double(p):
    """dbl-2008-hwcd for a=-1: 4 squarings + 4 muls."""
    x1, y1, z1, _ = p
    a = fe.fe_sq(x1)
    b = fe.fe_sq(y1)
    zz = fe.fe_sq(z1)
    c = fe.fe_add(zz, zz)
    e = fe.fe_sub(fe.fe_sub(fe.fe_sq(fe.fe_add(x1, y1)), a), b)
    g = fe.fe_sub(b, a)           # a*A + B with a = -1
    f = fe.fe_sub(g, c)
    h = fe.fe_neg(fe.fe_add(a, b))  # a*A - B
    return (fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g), fe.fe_mul(e, h))


def point_neg(p):
    x, y, z, t = p
    return (fe.fe_neg(x), y, z, fe.fe_neg(t))


def point_select(mask, p, q):
    """Per-lane point select: mask bool[N]."""
    return tuple(fe.fe_select(mask, a, b) for a, b in zip(p, q))


def point_is_identity(p):
    """bool[N]: P == (0:1:1:0), i.e. X == 0 and Y == Z (projectively)."""
    x, y, z, _ = p
    return fe.fe_is_zero(x) & fe.fe_is_zero(fe.fe_sub(y, z))


def point_compress(p) -> jnp.ndarray:
    """Canonical 255-bit y with x-parity sign bit, as limbs [17, N] plus the
    sign bool[N] (serialization handled host-side)."""
    x, y, z, _ = p
    zinv = fe.fe_invert(z)
    xa = fe.fe_freeze(fe.fe_mul(x, zinv))
    ya = fe.fe_freeze(fe.fe_mul(y, zinv))
    return ya, (xa[0] & 1) == 1


# -- decompression (ZIP-215) -------------------------------------------------


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Batched ZIP-215 decoding (mirrors crypto/ed25519_pure.point_decompress_
    zip215): y may be non-canonical (>= p, reduced implicitly); x = 0 with
    sign 1 rejected; returns (point, ok[N])."""
    y = y_limbs
    y2 = fe.fe_sq(y)
    u = fe.fe_sub(y2, jnp.broadcast_to(ONE_FE, y.shape))
    v = fe.fe_add(fe.fe_mul(y2, D_FE), jnp.broadcast_to(ONE_FE, y.shape))
    v3 = fe.fe_mul(fe.fe_sq(v), v)
    v7 = fe.fe_mul(fe.fe_sq(v3), v)
    t = fe.fe_pow2523(fe.fe_mul(u, v7))
    x = fe.fe_mul(fe.fe_mul(u, v3), t)  # candidate root of u/v
    vxx = fe.fe_mul(v, fe.fe_sq(x))
    ok_direct = fe.fe_eq(vxx, u)
    ok_flip = fe.fe_is_zero(fe.fe_add(vxx, u))  # vxx == -u
    x = fe.fe_select(ok_flip & ~ok_direct, fe.fe_mul(x, SQRT_M1_FE), x)
    ok = ok_direct | ok_flip
    x_is_zero = fe.fe_is_zero(x)
    ok = ok & ~(x_is_zero & sign)
    x = fe.fe_select(fe.fe_parity(x) != sign, fe.fe_neg(x), x)
    return (x, y, jnp.broadcast_to(ONE_FE, y.shape), fe.fe_mul(x, y)), ok


# -- precomputed ("cached") point form ---------------------------------------
#
# Table entries live in (Y-X, Y+X, 2d*T, Z) form so the 2d scaling is paid
# once at table-build time; adding a cached point costs 8 field muls, or 7
# against a Z == 1 table (add_precomp_z1 — the constant B table qualifies).


def to_precomp(p):
    """(X:Y:Z:T) -> (Y-X, Y+X, 2d*T, Z)."""
    x, y, z, t = p
    return (fe.fe_sub(y, x), fe.fe_add(y, x), fe.fe_mul(t, TWO_D_FE), z)


def precomp_identity(n: int):
    o = jnp.tile(ONE_FE, (1, n))
    return (o, o, jnp.zeros((fe.LIMBS, n), jnp.int32), o)


def precomp_select(mask, p, q):
    return tuple(fe.fe_select(mask, a, b) for a, b in zip(p, q))


def precomp_neg(q_pre):
    """-(Y-X, Y+X, 2dT, Z) = (Y+X, Y-X, -2dT, Z): a swap plus one negation."""
    ymx, ypx, td2, z = q_pre
    return (ypx, ymx, fe.fe_neg(td2), z)


def _add_precomp_core(p, q_pre, zz):
    """Shared hwcd addition body; zz = Z1*Z2 already computed by the caller
    (so the Z2 == 1 path can skip that multiply)."""
    x1, y1, _, t1 = p
    ymx, ypx, td2, _ = q_pre
    a = fe.fe_mul(fe.fe_sub(y1, x1), ymx)
    b = fe.fe_mul(fe.fe_add(y1, x1), ypx)
    c = fe.fe_mul(t1, td2)
    d = fe.fe_add(zz, zz)
    e = fe.fe_sub(b, a)
    f = fe.fe_sub(d, c)
    g = fe.fe_add(d, c)
    h = fe.fe_add(b, a)
    return (fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g), fe.fe_mul(e, h))


def add_precomp(p, q_pre):
    """Complete addition against a precomputed point: 8 field muls."""
    return _add_precomp_core(p, q_pre, fe.fe_mul(p[2], q_pre[3]))


def add_precomp_z1(p, q_pre):
    """add_precomp for a precomputed point with Z == 1 (the constant
    [0..8]B table, identity and negated selections included): zz = Z1,
    saving one field multiply of the eight — a free ~2% on the ladder
    since half its additions hit the B table."""
    return _add_precomp_core(p, q_pre, p[2])


# -- signed-window double-scalar multiplication ------------------------------

WINDOW_BITS = 4
DIGITS = 64  # ceil(253 / 4) windows cover scalars < L < 2^253 (+ carry room)


def build_table_pre(p) -> jnp.ndarray:
    """Per-lane window table [0..8]P in precomp form as ONE int32[9, 4, 17, N]
    array (axis 1 = ymx/ypx/2dT/Z). Built by a rolled chain of additions so
    the table costs a single compiled add_precomp body, not 7 inlined point
    ops (compile-size control: every planar field mul is ~1.5k HLO ops)."""
    n = p[0].shape[1]
    pp = to_precomp(p)
    tbl = jnp.zeros((9, 4, fe.LIMBS, n), jnp.int32)
    tbl = tbl.at[0].set(jnp.stack(precomp_identity(n)))
    tbl = tbl.at[1].set(jnp.stack(pp))

    def body(i, carry):
        tbl, cur = carry
        nxt = add_precomp(cur, pp)
        tbl = tbl.at[i].set(jnp.stack(to_precomp(nxt)))
        return tbl, nxt

    tbl, _ = lax.fori_loop(2, 9, body, (tbl, p))
    return tbl


def _host_table_b() -> np.ndarray:
    """Constant table [0..8]B in precomp form: int32[9, 4, 17, 1], computed
    with host integer math at import (the fixed-base precomputation — B is a
    compile-time constant, so [s]B rides the same select/add path as [k]A
    with a broadcastable table)."""

    def add_int(P1, P2):
        x1, y1 = P1
        x2, y2 = P2
        num = _D * x1 * x2 % _P * y1 % _P * y2 % _P
        x3 = (x1 * y2 + x2 * y1) % _P * pow(1 + num, _P - 2, _P) % _P
        y3 = (y1 * y2 + x1 * x2) % _P * pow(1 - num + _P, _P - 2, _P) % _P
        return (x3, y3)

    rows = [
        np.stack(
            [
                fe.int_to_limbs(1),
                fe.int_to_limbs(1),
                fe.int_to_limbs(0),
                fe.int_to_limbs(1),
            ]
        )
    ]
    cur = (_BX, _BY)
    for _ in range(8):
        x, y = cur
        rows.append(
            np.stack(
                [
                    fe.int_to_limbs((y - x) % _P),
                    fe.int_to_limbs((y + x) % _P),
                    fe.int_to_limbs(x * y % _P * fe.TWO_D_INT % _P),
                    fe.int_to_limbs(1),
                ]
            )
        )
        cur = add_int(cur, (_BX, _BY))
    # numpy literal so the Pallas kernel can close over it (see const_fe)
    return np.stack(rows)[:, :, :, None]  # [9, 4, 17, 1]


TABLE_B_PRE = _host_table_b()


def select_precomp_signed(table: jnp.ndarray, digits: jnp.ndarray):
    """Per-lane signed table lookup: digits int32[N] in [-8, 8] -> precomp
    point table[|d|], negated when d < 0. Binary-cascade selects over the
    stacked table (no gather: TPU per-lane gathers lower to far slower code
    than a 4-level vector select tree). table: [9, 4, 17, N] or [9, 4, 17, 1]
    (constant B table, broadcast over lanes)."""
    idx = jnp.abs(digits)
    m = lambda bit: ((idx & bit) == bit)[None, None, None, :]
    u = table[:8]
    s = jnp.where(m(1), u[1::2], u[0::2])          # [4,4,17,N], groups by bits 3..2
    s = jnp.where(m(2)[0], s[1::2], s[0::2])       # [2,4,17,N], groups by bit 3
    s = jnp.where(m(4)[0, 0], s[1], s[0])          # [4, 17, N]
    s = jnp.where(m(8)[0, 0], table[8], s)         # |d| == 8
    pt = (s[0], s[1], s[2], s[3])
    return precomp_select(digits < 0, precomp_neg(pt), pt)


def windowed_double_base_mult(s_digits: jnp.ndarray, k_digits: jnp.ndarray, a_point):
    """[s]B + [k]A batched over lanes: signed 4-bit fixed windows, MSB-first.
    s_digits/k_digits: int32[64, N] signed digits (weight 16^w at row w, from
    scalars_to_digits). The batched analog of the reference's double-scalar
    verification equation (crypto/ed25519/ed25519.go:168-175), restructured
    for SPMD: per window, 4 accumulator doublings + one add from the
    per-lane [1..8]A table + one add from the constant [1..8]B table."""
    n = s_digits.shape[1]
    table_a = build_table_pre(a_point)

    def body(w, acc):
        row = DIGITS - 1 - w
        acc = lax.fori_loop(0, WINDOW_BITS, lambda _, a: point_double(a), acc)
        acc = add_precomp(acc, select_precomp_signed(table_a, k_digits[row]))
        # every entry of the constant B table (incl. identity, incl. the
        # negated selections) has Z == 1
        acc = add_precomp_z1(acc, select_precomp_signed(TABLE_B_PRE, s_digits[row]))
        return acc

    return lax.fori_loop(0, DIGITS, body, identity(n))


def scalars_to_digits(scalars: np.ndarray) -> np.ndarray:
    """uint8[N, 32] little-endian scalars (< 2^253) -> int32[64, N] signed
    radix-16 digits in [-8, 7] (host). Row w has weight 16^w.

    Vectorized via the add-8s identity: for t = s + 0x88...8 (64 eights),
    nibble_w(t) - 8 is a valid signed digit string for s — the +8 absorbs
    each nibble's worst-case borrow so no sequential carry loop is needed.
    The big-int add runs as four uint64 word adds with a 3-step carry chain.
    s < 2^253 keeps the top nibble <= 1+8, so t never overflows 256 bits."""
    n = scalars.shape[0]
    if n == 0:
        return np.zeros((DIGITS, 0), np.int32)
    words = (
        np.ascontiguousarray(scalars, np.uint8).view("<u8").reshape(n, 4)
    )
    eights = np.uint64(0x8888888888888888)
    t = np.zeros((n, 4), np.uint64)
    carry = np.zeros(n, np.uint64)
    with np.errstate(over="ignore"):
        for w in range(4):
            tw = words[:, w] + eights
            wrapped = tw < words[:, w]
            tw2 = tw + carry
            wrapped |= (carry == 1) & (tw2 == 0)
            t[:, w] = tw2
            carry = wrapped.astype(np.uint64)
    tb = t.view(np.uint8).reshape(n, 32)  # little-endian byte stream of t
    nib = np.empty((n, DIGITS), np.int32)
    nib[:, 0::2] = tb & 15
    nib[:, 1::2] = tb >> 4
    return np.ascontiguousarray((nib - 8).T)

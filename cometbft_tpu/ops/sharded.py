"""Multi-chip sharding of the verification data path (SURVEY.md §2.13, §5.7).

The "sequence parallelism" analog of this framework: a 10k+ signature commit
batch is sharded across chips on a 1-D `sig` mesh (pure data parallel — the
Shamir ladder is elementwise over lanes, zero communication), and Merkle
trees are sharded by subtree: each chip reduces its leaf shard level-by-level
locally, subtree roots ride one all_gather over ICI, and the (tiny) top of
the tree is finished replicated. The overall-valid bit is a psum reduction.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax.shard_map graduated from experimental in newer releases
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from cometbft_tpu.ops import ed25519_kernel as ek
from cometbft_tpu.ops import merkle_kernel as mk
from cometbft_tpu.ops import sha256_kernel as sha


def make_mesh(devices=None, axis: str = "sig") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def _verify_specs(axis: str):
    if ek.HOST_HASH:
        raise NotImplementedError(
            "CMTPU_HOST_HASH=1 is an A/B probe mode for the single-chip "
            "kernel; the sharded path always hashes on device"
        )
    return (
        P(None, axis),  # a_words [8, N]
        P(None, axis),  # r_words [8, N]
        P(None, axis),  # s_words [8, N]
        P(axis, None),  # msg_words [N, B*32]
        P(axis),  # msg_nblocks [N]
    )


def sharded_verify_fn(mesh: Mesh, axis: str = "sig"):
    """jit-compiled batch verify with operands sharded over the batch dim
    (raw words + padded challenge blocks: everything after message
    construction — SHA-512 included — runs shard-local on device). Returns
    ok bool[N] (sharded)."""
    return jax.jit(
        ek.verify_core,
        in_shardings=tuple(NamedSharding(mesh, s) for s in _verify_specs(axis)),
        out_shardings=NamedSharding(mesh, P(axis)),
    )


def sharded_verify_replicated_fn(mesh: Mesh, axis: str = "sig"):
    """Batch verify with the ok bitmap REPLICATED instead of batch-sharded:
    on a multi-HOST mesh, `sharded_verify_fn`'s sharded output leaves each
    host holding only its addressable slice — but the fanout-serving seam
    (ops/multihost.py) needs the LEADER process to read the whole bitmap
    locally to answer the sidecar client. The replication all-gather is
    inserted by GSPMD from the out_sharding, same as the commit step's
    all-valid bit."""
    return jax.jit(
        ek.verify_core,
        in_shardings=tuple(NamedSharding(mesh, s) for s in _verify_specs(axis)),
        out_shardings=NamedSharding(mesh, P()),
    )


def _local_tree_root(leaves):
    """Reduce uint32[8, m] leaf digests (m a power of two) to one root [8, 1]
    with level-synchronous pairing."""
    cur = leaves
    while cur.shape[1] > 1:
        cur = mk._inner_core(cur[:, 0::2], cur[:, 1::2])
    return cur


def sharded_merkle_fn(mesh: Mesh, axis: str = "sig"):
    """shard_map'd subtree-parallel Merkle root: leaf digests uint32[8, n]
    (n = pow2, divisible by mesh size) -> replicated root uint32[8, 1]."""

    def local(leaf_shard):
        root = _local_tree_root(leaf_shard)  # [8, 1] per device
        roots = jax.lax.all_gather(root[:, 0], axis, axis=1)  # [8, ndev]
        # Every device computes the identical top reduction; emit one column
        # per device (JAX's varying-axis checker can't see the replication).
        return _local_tree_root(roots)

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=P(None, axis),
            out_specs=P(None, axis),
        )
    )
    return lambda leaves: fn(leaves)[:, :1]


def sharded_leaves_to_root_fn(mesh: Mesh, axis: str = "sig"):
    """shard_map'd FUSED leaves->root: pre-padded leaf messages (blocks
    uint32[B, 16, n], nblocks int32[n]; n = pow2, n/mesh-size a pow2) are
    leaf-hashed shard-local, each chip reduces its subtree, subtree roots
    ride one all_gather, and every chip finishes the (tiny) replicated top.
    The multi-chip analog of merkle_kernel.leaves_to_root_core — one
    dispatch end to end, which is what matters on tunneled deployments.
    Returns uint32[8, 1]."""

    def local(block_shard, nblock_shard):
        root = _local_tree_root(mk._leaf_core(block_shard, nblock_shard))
        roots = jax.lax.all_gather(root[:, 0], axis, axis=1)  # [8, ndev]
        # Identical top reduction on every device; emit one column each
        # (JAX's varying-axis checker can't see the replication).
        return _local_tree_root(roots)

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(None, None, axis), P(axis)),
            out_specs=P(None, axis),
        )
    )
    return lambda blocks, nblocks: fn(blocks, nblocks)[:, :1]


def sharded_commit_step_fn(mesh: Mesh, axis: str = "sig"):
    """The full 'training step' analog: one jitted program that verifies a
    sharded signature batch AND reduces a sharded Merkle leaf forest, with a
    psum for the all-valid bit."""

    def step(a_words, r_words, s_words, msg_blocks, msg_nblocks, leaf_digests):
        ok = ek.verify_core(a_words, r_words, s_words, msg_blocks, msg_nblocks)

        def reduce_shard(ok_shard, leaf_shard):
            local_ok = jnp.all(ok_shard).astype(jnp.int32)
            total_ok = jax.lax.psum(local_ok, axis)  # ICI all-reduce
            root = _local_tree_root(leaf_shard)
            roots = jax.lax.all_gather(root[:, 0], axis, axis=1)
            top = _local_tree_root(roots)  # identical on every device
            return total_ok[None], top

        total_ok, root_cols = shard_map(
            reduce_shard,
            mesh=mesh,
            in_specs=(P(axis), P(None, axis)),
            out_specs=(P(axis), P(None, axis)),
        )(ok, leaf_digests)
        n_dev = mesh.devices.size
        all_valid = jnp.sum(total_ok) == n_dev * n_dev  # psum'd per shard
        return ok, all_valid, root_cols[:, :1]

    return jax.jit(
        step,
        in_shardings=tuple(
            NamedSharding(mesh, s)
            for s in (*_verify_specs(axis), P(None, axis))
        ),
        # Explicit out shardings so every HOST of a multi-process mesh can
        # read the verdict + root locally (ops/multihost.py): the bitmap
        # stays batch-sharded, the all-valid bit and root are replicated.
        out_shardings=(
            NamedSharding(mesh, P(axis)),
            NamedSharding(mesh, P()),
            NamedSharding(mesh, P(None, None)),
        ),
    )


def make_example_batch(n: int):
    """Deterministic signed batch packed for verify_core (host crypto is
    C-speed; used by bench + graft entry)."""
    from cometbft_tpu.crypto import ed25519 as host_ed

    pubs, msgs, sigs = [], [], []
    for i in range(n):
        priv = host_ed.gen_priv_key_from_secret(b"bench-%d" % i)
        pub = priv.pub_key().bytes()
        msg = b"commit-vote-%d" % i
        pubs.append(pub)
        msgs.append(msg)
        sigs.append(priv.sign(msg))
    operands, host_ok = ek.pack_batch(pubs, msgs, sigs)
    assert all(host_ok[: len(pubs)])
    return tuple(jnp.asarray(o) for o in operands)


def example_txs(n: int) -> list[bytes]:
    """The deterministic tx fixture shared by the multi-chip dryrun, the
    multi-host worker, and their root cross-checks — one definition so the
    copies cannot drift."""
    return [b"tx-%d" % i for i in range(n)]


def make_example_leaves(n: int):
    """Leaf digests uint32[8, n] for n power-of-two txs."""
    return jnp.asarray(mk.hash_leaves_device(example_txs(n)))

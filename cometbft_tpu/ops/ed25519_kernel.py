"""Batched Ed25519 ZIP-215 verification on TPU.

The device-tier implementation of the reference's `crypto/ed25519`
BatchVerifier (crypto/ed25519/ed25519.go:196-228). Instead of the reference's
random-linear-combination batch equation + bisection on failure, every lane
checks its own cofactored equation

    [8]([s]B + [k](-A) + (-R)) == identity

in SPMD lockstep, so one device call yields the exact per-signature validity
bitmap the callers need (types/validation.go:234-249) with no re-runs.

Host side: SHA-512 challenge hashing of the variable-length messages
(hashlib, C speed) and s-range checks — nothing else. The kernel takes the
RAW 32/64-byte encodings as little-endian uint32 words (128 bytes per
signature over the host->device link) and unpacks on device: point
y-limbs/sign, k = digest mod L, and the signed-window digit recode
(ops/unpack.py). Device side: decompression, the signed-4-bit-window
double-scalar ladder (edwards.windowed_double_base_mult), and the identity
test — one jit-compiled program per batch-size bucket.
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

import jax
import jax.numpy as jnp

from cometbft_tpu.ops import edwards as ed
from cometbft_tpu.ops import field25519 as fe
from cometbft_tpu.ops import unpack

L = 2**252 + 27742317777372353535851937790883648493

# Fixed batch buckets: one compiled program per size, reused forever
# (SURVEY.md §7 "pre-compiled fixed-shape programs + bucketed batch sizes").
BUCKETS = (8, 32, 128, 512, 1024, 4096, 10240, 16384, 32768)


def bucket_for(n: int) -> int:
    for b in BUCKETS:
        if n <= b:
            return b
    return int(2 ** np.ceil(np.log2(n)))


def verify_core(a_words, r_words, s_words, k_words):
    """Pure jittable core: raw little-endian words in (A, R as int32[8, N];
    S as int32[8, N]; the SHA-512 challenge as int32[16, N]), bool[N] out.
    Unpacking (limbs, mod L, digit recode) happens on device first; the A
    and R decompressions then ride ONE width-2N pass (lane-stacked) — same
    op count in half the program. Straight-line sections use compact_scope
    (meaningful only under the opt-in planar lowering; a no-op for the
    default stacked form)."""
    n = a_words.shape[1]
    y_a, sign_a = unpack.words_to_limbs255(a_words)
    y_r, sign_r = unpack.words_to_limbs255(r_words)
    s_digits = unpack.scalar_words_to_digits(s_words)
    k_digits = unpack.digest_words_to_digits(k_words)
    with fe.compact_scope():
        y2 = jnp.concatenate([y_a, y_r], axis=1)
        sg2 = jnp.concatenate([sign_a, sign_r])
        pt, ok = ed.decompress(y2, sg2)
        a = tuple(c[:, :n] for c in pt)
        r = tuple(c[:, n:] for c in pt)
        neg_a = ed.point_neg(a)
    acc = ed.windowed_double_base_mult(s_digits, k_digits, neg_a)
    with fe.compact_scope():
        acc = ed.point_add(acc, ed.point_neg(r))
        acc = ed.point_double(ed.point_double(ed.point_double(acc)))
        return ok[:n] & ok[n:] & ed.point_is_identity(acc)


@functools.lru_cache(maxsize=None)
def _compiled(n: int):
    return jax.jit(verify_core)


def warmup(buckets=(128, 1024, 10240), merkle_leaves=(1024, 65536)) -> None:
    """Precompile the verify program for the given batch buckets AND the
    fused Merkle leaves->root program ahead of first use (SURVEY §7 hard
    part 3: the <2 ms latency budget cannot absorb a per-call XLA compile).
    Shape-only: feeds all-zero operands of each bucket's shape through the
    jit so the compiled executable (and the persistent compile cache entry)
    exists before the first real commit."""
    for b in buckets:
        operands, _ = pack_batch([b""] * b, [b""] * b, [b""] * b)
        jax.block_until_ready(_compiled(operands[0].shape[1])(*operands))
    from cometbft_tpu.ops import merkle_kernel as mk

    for n in merkle_leaves:
        blocks = np.zeros((1, 16, n), np.uint32)
        nblocks = np.ones(n, np.int32)
        jax.block_until_ready(mk._leaves_to_root_jit(1, n)(blocks, nblocks))


def pack_batch(pubs, msgs, sigs):
    """Host-side packing of one verification batch: per-signature SHA-512
    challenges (hashlib, C speed), the vectorized s < L check, and raw-byte
    -> word views — all limb/digit work happens on device (ops/unpack.py).
    Returns device operands plus the host-decided validity mask (shape
    errors, s >= L). Invalid entries are packed as zeros — lanes the device
    evaluates but the mask vetoes."""
    n = len(pubs)
    nb = bucket_for(n)
    zero_pub, zero_sig = b"\x00" * 32, b"\x00" * 64
    shape_ok = [len(pubs[i]) == 32 and len(sigs[i]) == 64 for i in range(n)]
    pubs_c = [pubs[i] if shape_ok[i] else zero_pub for i in range(n)]
    sigs_c = [sigs[i] if shape_ok[i] else zero_sig for i in range(n)]

    a_enc = np.zeros((nb, 32), np.uint8)
    r_enc = np.zeros((nb, 32), np.uint8)
    s_le = np.zeros((nb, 32), np.uint8)
    k_le = np.zeros((nb, 64), np.uint8)
    if n:
        a_enc[:n] = np.frombuffer(b"".join(pubs_c), np.uint8).reshape(n, 32)
        sig_arr = np.frombuffer(b"".join(sigs_c), np.uint8).reshape(n, 64)
        r_enc[:n] = sig_arr[:, :32]
        s_le[:n] = sig_arr[:, 32:]

    host_ok = np.zeros(nb, bool)
    if n:
        # s < L, vectorized: compare the four little-endian uint64 words
        # most-significant first.
        s_words = s_le[:n].view("<u8")  # [n, 4]
        l_words = np.frombuffer(L.to_bytes(32, "little"), dtype="<u8")
        s_in_range = np.zeros(n, bool)
        decided = np.zeros(n, bool)
        for w in (3, 2, 1, 0):
            lt = ~decided & (s_words[:, w] < l_words[w])
            gt = ~decided & (s_words[:, w] > l_words[w])
            s_in_range |= lt
            decided |= lt | gt
        # s == L (all words equal) leaves decided False -> not in range.
        s_le[:n][~s_in_range] = 0
    digest_rows = bytearray(64 * n)
    sha512 = hashlib.sha512
    for i in range(n):
        if not shape_ok[i] or not s_in_range[i]:
            continue
        h = sha512(sigs_c[i][:32])
        h.update(pubs_c[i])
        h.update(msgs[i])
        digest_rows[64 * i : 64 * (i + 1)] = h.digest()
        host_ok[i] = True
    if n:
        k_le[:n] = np.frombuffer(bytes(digest_rows), np.uint8).reshape(n, 64)

    return (
        unpack.bytes_to_words(a_enc),
        unpack.bytes_to_words(r_enc),
        unpack.bytes_to_words(s_le),
        unpack.bytes_to_words(k_le),
    ), host_ok


def batch_verify(pubs, msgs, sigs) -> tuple[bool, list]:
    """The crypto.BatchVerifier device path: (overall ok, per-sig bitmap)."""
    n = len(pubs)
    if n == 0:
        return False, []
    operands, host_ok = pack_batch(pubs, msgs, sigs)
    dev_ok = np.asarray(_compiled(operands[0].shape[1])(*operands))
    results = [bool(host_ok[i] and dev_ok[i]) for i in range(n)]
    return all(results), results

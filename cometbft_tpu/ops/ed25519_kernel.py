"""Batched Ed25519 ZIP-215 verification on TPU.

The device-tier implementation of the reference's `crypto/ed25519`
BatchVerifier (crypto/ed25519/ed25519.go:196-228). Instead of the reference's
random-linear-combination batch equation + bisection on failure, every lane
checks its own cofactored equation

    [8]([s]B + [k](-A) + (-R)) == identity

in SPMD lockstep, so one device call yields the exact per-signature validity
bitmap the callers need (types/validation.go:234-249) with no re-runs.

Host side: shape checks, the vectorized s-range check, and packing the
challenge messages R || A || M into padded SHA-512 blocks — no crypto at
all. The kernel takes the RAW 32-byte encodings as little-endian uint32
words plus the padded challenge blocks, and runs the WHOLE verification on
device: SHA-512 (sha512_kernel), k = digest mod L + signed-window recode +
point decoding (ops/unpack.py), the signed-4-bit-window double-scalar
ladder (edwards.windowed_double_base_mult), and the identity test — one
jit-compiled program per (batch, block-count) bucket pair.

CMTPU_HOST_HASH=1 opts back into hashlib challenge hashing on the host
(the device then receives 64-byte digests instead of message blocks) for
A/B probes.
"""

from __future__ import annotations

import functools
import hashlib
import os
import queue
import threading
from concurrent.futures import Future

import numpy as np

import jax
import jax.numpy as jnp

from cometbft_tpu.ops import edwards as ed
from cometbft_tpu.ops import field25519 as fe
from cometbft_tpu.ops import sha512_kernel as s5
from cometbft_tpu.ops import unpack

L = 2**252 + 27742317777372353535851937790883648493

HOST_HASH = os.environ.get("CMTPU_HOST_HASH") == "1"

# Fixed batch buckets: one compiled program per size, reused forever
# (SURVEY.md §7 "pre-compiled fixed-shape programs + bucketed batch sizes").
# 2048/6144/8192 exist for the hybrid tier's device share: splitting a
# 10,240-signature commit needs a bucket near the throughput-balanced
# point (device ~100 sigs/ms vs host MSM ~70 sigs/ms -> ~6k device lanes),
# and padding to the next coarse bucket would burn the whole saving.
BUCKETS = (8, 32, 128, 512, 1024, 2048, 4096, 6144, 8192, 10240, 16384, 32768)
# Challenge-message block counts bucket the other program axis: a canonical
# vote challenge is 64 + ~120 bytes = 2 blocks; odd app messages fall into
# the larger buckets.
BLOCK_BUCKETS = (2, 4, 8, 32)


_probed_width = 0  # mesh_width()'s last answer; 0 = never probed


@functools.lru_cache(maxsize=1)
def mesh_width() -> int:
    """Process-local chips one verify dispatch can shard across (the 1-D
    `sig` mesh of ops/sharded). 1 under CMTPU_HOST_HASH — the hosthash
    program is never mesh-sharded — and 1 when the device probe fails.
    First call may initialize the JAX backend; callers that must never do
    that (node metric scrapes, the coalescer's default cap) read
    known_mesh_width() instead."""
    global _probed_width
    n = 1
    if not HOST_HASH:
        try:
            n = max(1, jax.local_device_count())
        except Exception:
            n = 1
    _probed_width = n
    return n


def known_mesh_width() -> int:
    """mesh_width() if some caller already probed it, else 0. Never
    initializes jax — safe from lazy metric closures and constructors that
    must not touch a possibly-wedged device tunnel."""
    return _probed_width


def mesh_floor() -> int:
    """Smallest batch bucket worth spreading across the mesh. Default:
    the mesh width itself (each chip gets at least one lane — the historic
    divisibility rule's implicit floor); CMTPU_MESH_FLOOR overrides for
    deployments where tiny sharded dispatches lose to collective setup."""
    env = os.environ.get("CMTPU_MESH_FLOOR", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return mesh_width()


def bucket_for(n: int) -> int:
    """Batch bucket for n signatures, rounded up to a multiple of the mesh
    width once at/above the sharding floor — every bucket the router would
    shard divides the device count evenly, so a 6-chip host pads 2048 to
    2052 instead of leaving 5 chips idle (the pre-mesh ladder silently fell
    back to one chip for any non-divisible bucket)."""
    for b in BUCKETS:
        if n <= b:
            break
    else:
        b = int(2 ** np.ceil(np.log2(n)))
    w = mesh_width()
    if w > 1 and b >= mesh_floor() and b % w:
        b += w - b % w
    return b


def preferred_stream_chunk() -> int:
    """Chunk size the sidecar advertises to streaming clients (Ping
    capability field 4): the smallest compiled batch bucket that is both
    commit-sized and a mesh-width multiple, so every streamed chunk lands
    on the bucket ladder with zero padding and — at/above mesh_floor() —
    routes through the sharded program like the in-process tier. Uses the
    passively-known width only: a sidecar that has never dispatched yet
    must not probe a possibly-wedged tunnel from a Ping."""
    w = known_mesh_width() or 1
    target = max(1024, 128 * w)
    for b in BUCKETS:
        if target <= b:
            break
    else:
        b = int(2 ** np.ceil(np.log2(target)))
    if w > 1 and b % w:
        b += w - b % w
    return b


_mesh_lock = threading.Lock()
_mesh_counters = {
    "sharded_dispatches": 0,  # verify dispatches routed to the mesh program
    "padded_lanes": 0,        # bucket-padding lanes shipped on those
    "merkle_sharded_dispatches": 0,  # fused roots via the subtree program
}


def mesh_counters() -> dict:
    """Snapshot of the mesh routing counters plus the (passively read)
    device count — the source for the node's lazy mesh_* gauges and the
    bench JSON's attribution fields."""
    with _mesh_lock:
        out = dict(_mesh_counters)
    out["devices"] = known_mesh_width()
    return out


def _mesh_count(key: str, delta: int = 1) -> None:
    with _mesh_lock:
        _mesh_counters[key] += delta


def block_bucket_for(b: int) -> int:
    for bb in BLOCK_BUCKETS:
        if b <= bb:
            return bb
    return int(2 ** np.ceil(np.log2(b)))


def verify_core(a_words, r_words, s_words, msg_words, msg_nblocks):
    """Pure jittable core: raw little-endian words in (A, R, S as
    int32[8, N]) plus the padded SHA-512 challenge byte stream as native
    uint32 words (uint32[N, B*32] — a FREE view of the host pack buffer —
    and per-lane block counts int32[N]), bool[N] out. The whole verification
    is on-device: block-layout transpose + byte swap, challenge hash,
    k = digest mod L, digit recodes, point decoding, window ladder, identity
    test. The A and R decompressions ride ONE width-2N pass (lane-stacked) —
    same op count in half the program. Straight-line sections use
    compact_scope (meaningful only under the opt-in planar lowering; a
    no-op for the default stacked form)."""
    n, bwords = msg_words.shape
    bmax = bwords // 32
    # [N, B*32] LE words -> [B, 2(hi/lo), 16, N] big-endian block words:
    # layout shuffle + byte swap are the program's first (cheap, fused)
    # ops instead of multi-MB host passes.
    x = msg_words.astype(jnp.uint32).reshape(n, bmax, 16, 2)
    blocks_be = s5.bswap32(jnp.transpose(x, (1, 3, 2, 0)))
    k_words = s5.digest_to_le_words(s5.hash_blocks_core(blocks_be, msg_nblocks))
    return _verify_from_words(a_words, r_words, s_words, k_words)


def verify_core_hosthash(a_words, r_words, s_words, k_words):
    """A/B variant (CMTPU_HOST_HASH=1): the 64-byte challenge digests come
    pre-hashed from the host as int32[16, N] little-endian words."""
    return _verify_from_words(a_words, r_words, s_words, k_words)


def _verify_from_words(a_words, r_words, s_words, k_words):
    n = a_words.shape[1]
    y_a, sign_a = unpack.words_to_limbs255(a_words)
    y_r, sign_r = unpack.words_to_limbs255(r_words)
    s_digits = unpack.scalar_words_to_digits(s_words)
    k_digits = unpack.digest_words_to_digits(k_words)
    with fe.compact_scope():
        y2 = jnp.concatenate([y_a, y_r], axis=1)
        sg2 = jnp.concatenate([sign_a, sign_r])
        pt, ok = ed.decompress(y2, sg2)
        a = tuple(c[:, :n] for c in pt)
        r = tuple(c[:, n:] for c in pt)
        neg_a = ed.point_neg(a)
    if os.environ.get("CMTPU_LADDER", "xla") == "pallas":
        # Opt-in A/B probe (ops/pallas_ladder.py): the whole ladder as one
        # Mosaic kernel — attacks the XLA graph-size ceiling directly.
        from cometbft_tpu.ops import pallas_ladder

        acc = pallas_ladder.windowed_double_base_mult(
            s_digits, k_digits, neg_a,
            interpret=jax.default_backend() == "cpu",
        )
    else:
        acc = ed.windowed_double_base_mult(s_digits, k_digits, neg_a)
    with fe.compact_scope():
        acc = ed.point_add(acc, ed.point_neg(r))
        acc = ed.point_double(ed.point_double(ed.point_double(acc)))
        return ok[:n] & ok[n:] & ed.point_is_identity(acc)


@functools.lru_cache(maxsize=None)
def _compiled(n: int, bmax: int = 0):
    """One jitted program per (batch, block-count) bucket pair; bmax 0 is
    the host-hash program (pre-hashed digests in). The lru wrapper (vs one
    global jax.jit) lets tests force a retrace after flipping the fe
    lowering mode via cache_clear()."""
    if bmax == 0:
        return jax.jit(verify_core_hosthash)
    return jax.jit(verify_core)


def warmup(buckets=(128, 1024, 6144, 10240), merkle_leaves=(1024, 65536)) -> None:
    """Precompile the verify program for the given batch buckets AND the
    fused Merkle leaves->root program ahead of first use (SURVEY §7 hard
    part 3: the <2 ms latency budget cannot absorb a per-call XLA compile).
    Feeds vote-shaped (2-block) challenge messages so the compiled
    executable (and the persistent compile cache entry) exists before the
    first real commit."""
    msg = b"\x00" * 120  # canonical-vote-sized: 64 + 120 -> 2 blocks
    for b in buckets:
        operands, _ = pack_batch([b"\x00" * 32] * b, [msg] * b, [b"\x00" * 64] * b)
        jax.block_until_ready(_verify_fn_for(operands)(*operands))
    from cometbft_tpu.ops import merkle_kernel as mk

    for n in merkle_leaves:
        blocks = np.zeros((1, 16, n), np.uint32)
        nblocks = np.ones(n, np.int32)
        jax.block_until_ready(mk._leaves_to_root_jit(1, n)(blocks, nblocks))


def _bucket_key(operands) -> tuple[int, int]:
    """(batch, block) bucket pair; bmax 0 selects the host-hash program
    (4 operands: either CMTPU_HOST_HASH=1, or the oversized-message
    fallback in pack_batch)."""
    n = operands[0].shape[1]
    bmax = operands[3].shape[1] // 32 if len(operands) == 5 else 0
    return n, bmax


def _host_checks(pubs, sigs):
    """Shared host-side packing: shape checks, byte matrices, vectorized
    s < L. Returns (a_enc, r_enc, s_le, pubs_c, sigs_c, shape_ok,
    s_in_range) with nb = bucket_for(n) rows."""
    n = len(pubs)
    nb = bucket_for(n)
    zero_pub, zero_sig = b"\x00" * 32, b"\x00" * 64
    shape_ok = [len(pubs[i]) == 32 and len(sigs[i]) == 64 for i in range(n)]
    pubs_c = [pubs[i] if shape_ok[i] else zero_pub for i in range(n)]
    sigs_c = [sigs[i] if shape_ok[i] else zero_sig for i in range(n)]

    a_enc = np.zeros((nb, 32), np.uint8)
    r_enc = np.zeros((nb, 32), np.uint8)
    s_le = np.zeros((nb, 32), np.uint8)
    s_in_range = np.zeros(n, bool)
    if n:
        a_enc[:n] = np.frombuffer(b"".join(pubs_c), np.uint8).reshape(n, 32)
        sig_arr = np.frombuffer(b"".join(sigs_c), np.uint8).reshape(n, 64)
        r_enc[:n] = sig_arr[:, :32]
        s_le[:n] = sig_arr[:, 32:]
        # s < L, vectorized: compare the four little-endian uint64 words
        # most-significant first.
        s_words = s_le[:n].view("<u8")  # [n, 4]
        l_words = np.frombuffer(L.to_bytes(32, "little"), dtype="<u8")
        decided = np.zeros(n, bool)
        for w in (3, 2, 1, 0):
            lt = ~decided & (s_words[:, w] < l_words[w])
            gt = ~decided & (s_words[:, w] > l_words[w])
            s_in_range |= lt
            decided |= lt | gt
        # s == L (all words equal) leaves decided False -> not in range.
        s_le[:n][~s_in_range] = 0
    return a_enc, r_enc, s_le, pubs_c, sigs_c, shape_ok, s_in_range


def pack_batch(pubs, msgs, sigs):
    """Host-side packing of one verification batch — no crypto: shape
    checks, the vectorized s < L check, raw-byte -> word views, and the
    challenge messages R || A || M padded into SHA-512 blocks (the hashing
    itself runs on device). Returns device operands plus the host-decided
    validity mask (shape errors, s >= L). Invalid entries are packed as
    zeros — lanes the device evaluates but the mask vetoes."""
    n = len(pubs)
    nb = bucket_for(n)
    a_enc, r_enc, s_le, pubs_c, sigs_c, shape_ok, s_in_range = _host_checks(
        pubs, sigs
    )
    host_ok = np.zeros(nb, bool)
    if n:
        mlens = np.fromiter(
            (len(msgs[i]) if shape_ok[i] else 0 for i in range(n)), np.int64, n
        )
    else:
        mlens = np.zeros(0, np.int64)
    # Oversized messages (past the largest block bucket) fall back to host
    # hashing: the hosthash program's shapes are independent of message
    # length, so an adversary feeding growing messages cannot force a fresh
    # XLA compile per size.
    oversized = n > 0 and int(mlens.max()) + 64 > BLOCK_BUCKETS[-1] * 128 - 17
    if HOST_HASH or oversized:
        k_le = np.zeros((nb, 64), np.uint8)
        digest_rows = bytearray(64 * n)
        sha512 = hashlib.sha512
        for i in range(n):
            if not shape_ok[i] or not s_in_range[i]:
                continue
            h = sha512(sigs_c[i][:32])
            h.update(pubs_c[i])
            h.update(msgs[i])
            digest_rows[64 * i : 64 * (i + 1)] = h.digest()
            host_ok[i] = True
        if n:
            k_le[:n] = np.frombuffer(bytes(digest_rows), np.uint8).reshape(n, 64)
        operands = (
            unpack.bytes_to_words(a_enc),
            unpack.bytes_to_words(r_enc),
            unpack.bytes_to_words(s_le),
            unpack.bytes_to_words(k_le),
        )
        return operands, host_ok

    host_ok[:n] = np.asarray(shape_ok) & s_in_range
    # Challenge blocks R || A || M, padded, built vectorized: R and A bulk-
    # copy from the already-built byte matrices; messages fill in one pass
    # per DISTINCT length (a commit's sign-bytes have 1-3 layouts, so this
    # is a couple of reshaped assignments, not an n-row python loop).
    tot = mlens + 64
    nblocks = s5.blocks_for(tot)
    bmax = block_bucket_for(int(nblocks.max()) if n else 1)
    buf = np.zeros((nb, bmax * 128), np.uint8)
    if n:
        buf[:n, 0:32] = r_enc[:n]
        buf[:n, 32:64] = a_enc[:n]
        for ln in np.unique(mlens):
            if ln == 0:
                continue  # shape-invalid rows were forced to length 0
            rows = np.nonzero(mlens == ln)[0]
            joined = b"".join(msgs[i] for i in rows)
            buf[rows, 64 : 64 + ln] = np.frombuffer(joined, np.uint8).reshape(
                len(rows), ln
            )
        s5.write_padding(buf[:n], tot, nblocks)
    # Native-LE word view (free — no copy, no transpose; the device does
    # the block-layout shuffle and byte swap itself).
    pb = buf.view("<u4")
    pnb = np.zeros(nb, np.int32)
    pnb[:n] = nblocks
    # padded lanes hash zero blocks (nblocks 0 -> IV digest): vetoed by mask
    operands = (
        unpack.bytes_to_words(a_enc),
        unpack.bytes_to_words(r_enc),
        unpack.bytes_to_words(s_le),
        pb,
        pnb,
    )
    return operands, host_ok


_device_pool = None
_device_pool_lock = threading.Lock()


class _DeviceOwner:
    """One DAEMON device-owner thread: serializes dispatches (the axon
    tunnel wedges under concurrent clients) and gives the hybrid tier a
    genuinely async seam even if the remote PJRT's execute blocks until
    completion. Deliberately not a ThreadPoolExecutor: its workers are
    joined at interpreter exit, so one dispatch wedged in the tunnel would
    hang process shutdown forever."""

    def __init__(self):
        self._q = queue.Queue()
        t = threading.Thread(target=self._run, name="cmtpu-dev", daemon=True)
        t.start()

    def _run(self):
        while True:
            fn, fut = self._q.get()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # surfaced at fut.result()
                fut.set_exception(e)

    def submit(self, fn):
        fut = Future()
        self._q.put((fn, fut))
        return fut


def _pool() -> _DeviceOwner:
    global _device_pool
    if _device_pool is None:
        with _device_pool_lock:
            if _device_pool is None:
                _device_pool = _DeviceOwner()
    return _device_pool


@functools.lru_cache(maxsize=1)
def _sharded_verify():
    """(local_device_count, sharded verify fn) when this PROCESS owns
    multiple chips, else None. Routes the shipped BatchVerifier seam
    across every process-local chip (ops/sharded's 1-D sig mesh —
    lane-sharded operands, zero collectives in the verify body) instead
    of leaving n-1 chips idle. Local, not global, devices: after
    jax.distributed joins a multi-host cluster, a mesh over the global
    device list would contain non-addressable devices and break every
    ordinary local verify."""
    n_dev = mesh_width()
    if n_dev <= 1:
        return None
    from cometbft_tpu.ops import sharded

    return n_dev, sharded.sharded_verify_fn(sharded.make_mesh(jax.local_devices()))


def _route_for(operands):
    """(program, mesh-sharded?) the routing layer would run for these packed
    operands: the lane-sharded multi-chip program when this process owns
    several chips and the bucket is at/above the sharding floor (the
    mesh-aware ladder guarantees such buckets divide the device count),
    else the single-device bucket program."""
    key = _bucket_key(operands)
    if key[1] != 0:  # hosthash program shapes aren't mesh-sharded
        sh = _sharded_verify()
        if (
            sh is not None
            and key[0] >= mesh_floor()
            and key[0] % sh[0] == 0
        ):
            return sh[1], True
    return _compiled(*key), False


def _verify_fn_for(operands):
    """Shared by batch_verify_submit and warmup so warmup precompiles what
    will actually run."""
    return _route_for(operands)[0]


def clear_compiled_caches() -> None:
    """Retrace seam for the fe-lowering tests: drops BOTH program caches
    (the per-bucket single-device jits and the sharded-mesh jit) plus the
    cached mesh width so a flipped CMTPU_FE_MODE actually re-lowers what
    batch_verify runs."""
    _compiled.cache_clear()
    _sharded_verify.cache_clear()
    mesh_width.cache_clear()


def batch_verify_submit(pubs, msgs, sigs):
    """Pack on the calling thread, dispatch on the device-owner thread,
    return a collect() -> (ok, bitmap) closure. The hybrid backend runs its
    host MSM share between submit and collect; callers that want the
    blocking behavior just collect immediately (batch_verify below)."""
    n = len(pubs)
    operands, host_ok = pack_batch(pubs, msgs, sigs)
    key = _bucket_key(operands)
    fn, sharded = _route_for(operands)
    if sharded:
        _mesh_count("sharded_dispatches")
        _mesh_count("padded_lanes", key[0] - n)
    fut = _pool().submit(lambda: np.asarray(fn(*operands)))

    def collect() -> tuple[bool, list]:
        dev_ok = fut.result()
        results = [bool(host_ok[i] and dev_ok[i]) for i in range(n)]
        return all(results), results

    # (batch bucket, block bucket) — the compiled-program identity, so
    # callers can tell a first dispatch (XLA compile) from a steady one.
    collect.program_key = key
    return collect


def batch_verify(pubs, msgs, sigs) -> tuple[bool, list]:
    """The crypto.BatchVerifier device path: (overall ok, per-sig bitmap)."""
    n = len(pubs)
    if n == 0:
        return False, []
    return batch_verify_submit(pubs, msgs, sigs)()

"""Device multi-pairing for BN254 BLS commits.

The Miller loop is the batchable part of a pairing: every (G1, G2) lane of a
commit walks the same 65-bit ate ladder, so one `lax.scan` body — traced once
— runs all lanes in lockstep, data-parallel over the lane axis and shardable
over the local mesh exactly like the ed25519 bucket programs. Per-lane Miller
values come back to the host, which multiplies the *real* lanes (padding is
simply skipped — no device masking), runs ONE shared fast final
exponentiation, and compares against F12_ONE.

Field representation: Fp elements are 13 limbs of 21 bits in float64
(13*21 = 273 bits > 254). All arithmetic is exact: products of |limb| < 2^26
inputs stay under 2^52; reduction is outer-product columns -> hi/lo split ->
one-hot einsum scatter to 26 columns -> sequential signed carry -> high-column
fold against precomputed 2^(21k) mod P rows -> three carry+fold rounds whose
top carries shrink 2^25 -> 2^6 -> <=1, leaving |limb| < 2^22. Every multi-term
sum is condensed back under the 2^26 mul bound before feeding another
multiply. Host reconstruction sum(l_i * 2^21i) mod P is exact for loose and
negative limbs alike.

G2 runs Jacobian (no inversions); line coefficients are the standard sparse
(c0, c1*w, c3*w^3) untwist form scaled by Z^6 (doubling) / Z^3 (addition) —
Fp2 scalar factors are killed by the final exponentiation, asserted
decision-identical to crypto.bn254.pairing_check by the agg tests.

float64 is exact on XLA:CPU (and the virtual-mesh tests pin CPU); real TPU
f64 is emulated and slow, which is why `device_available()` is opt-in via
CMTPU_BN254_DEVICE=1 and the bench labels the arm honestly when absent.
Keccak/SHA hash-to-field stays host-side (same convention as
CMTPU_HOST_HASH); CMTPU_FE_MODE does not apply — this kernel has a single
stacked-limb lowering (the fe modes are ed25519-field concerns).
"""

from __future__ import annotations

import contextlib
import os
import threading
from functools import lru_cache

from cometbft_tpu.crypto import bn254 as _b

BASE = 1 << 21
NLIMB = 13
NCOL = 2 * NLIMB
P = _b.P

# Ate-loop bits, MSB skipped — the same constant ladder the host loop walks.
_BITS = [1 if c == "1" else 0 for c in bin(_b._ATE_LOOP)[3:]]

_LADDER = [8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192]
MAX_LANES = _LADDER[-1]

_counters = {"dispatches": 0, "lanes": 0, "sharded_dispatches": 0}
_counters_lock = threading.Lock()


def to_limbs(x: int) -> list:
    """254-bit int -> 13 limbs of 21 bits (little-endian)."""
    out = []
    for _ in range(NLIMB):
        out.append(float(x & (BASE - 1)))
        x >>= 21
    return out


def from_limbs(limbs) -> int:
    """Loose (possibly negative) limbs -> exact int mod P."""
    acc = 0
    for i, v in enumerate(limbs):
        acc += int(round(float(v))) << (21 * i)
    return acc % P


# Fold tables (plain python — device copies built lazily in _tables()).
_M_ROWS = [to_limbs(pow(2, 21 * (NLIMB + k), P)) for k in range(NLIMB)]
_C26 = to_limbs(pow(2, 21 * NCOL, P))
_K13 = to_limbs(pow(2, 21 * NLIMB, P))


def device_available() -> bool:
    """Opt-in only: the Miller scan is a heavy compile and must never be
    probed at node start (CLAUDE.md: the axon relay wedges under concurrent
    clients). Bench/tests set CMTPU_BN254_DEVICE=1 for the device arm."""
    if os.environ.get("CMTPU_BN254_DEVICE", "") != "1":
        return False
    try:
        import jax  # noqa: F401

        return True
    except Exception:
        return False


def mesh_width() -> int:
    try:
        from cometbft_tpu.ops import ed25519_kernel as _ek

        return max(1, int(_ek.mesh_width()))
    except Exception:
        return 1


def _mesh_floor() -> int:
    try:
        from cometbft_tpu.ops import ed25519_kernel as _ek

        return max(1, int(_ek.mesh_floor()))
    except Exception:
        return 1


def bucket_for(n: int) -> int:
    """Pow2-ish ladder rounded up to mesh-width multiples at/above the mesh
    floor — the same shape as ed25519_kernel.bucket_for."""
    n = max(1, int(n))
    b = next((x for x in _LADDER if x >= n), MAX_LANES)
    w = mesh_width()
    if w > 1 and b >= _mesh_floor():
        b = ((b + w - 1) // w) * w
    return b


def counters() -> dict:
    with _counters_lock:
        return dict(_counters)


class _Tables:
    pass


@lru_cache(maxsize=1)
def _tables():
    import jax
    import jax.numpy as jnp
    import numpy as np

    t = _Tables()
    t.jax, t.jnp, t.np = jax, jnp, np
    with _x64(jax):
        f64 = np.float64
        e0 = np.zeros((NLIMB, NLIMB, NCOL), dtype=f64)
        e1 = np.zeros((NLIMB, NLIMB, NCOL), dtype=f64)
        for i in range(NLIMB):
            for j in range(NLIMB):
                e0[i, j, i + j] = 1.0
                e1[i, j, i + j + 1] = 1.0
        t.e0 = jnp.asarray(e0)
        t.e1 = jnp.asarray(e1)
        t.m = jnp.asarray(np.array(_M_ROWS, dtype=f64))
        t.c26 = jnp.asarray(np.array(_C26, dtype=f64))
        t.k13 = jnp.asarray(np.array(_K13, dtype=f64))
        t.bits = jnp.asarray(np.array(_BITS, dtype=f64))
        # f12 squaring: 21 symmetric (i, j) products, cross terms weight 2
        pairs21 = [(i, j) for i in range(6) for j in range(i, 6)]
        s21 = np.zeros((len(pairs21), 12), dtype=f64)
        for k, (i, j) in enumerate(pairs21):
            s21[k, i + j] = 2.0 if i != j else 1.0
        t.i21 = jnp.asarray(np.array([i for i, _ in pairs21]))
        t.j21 = jnp.asarray(np.array([j for _, j in pairs21]))
        t.s21 = jnp.asarray(s21)
        # sparse line mul: f[i] * c_j for the line's w^0, w^1, w^3 slots
        slots = (0, 1, 3)
        trip18 = [(i, jj) for i in range(6) for jj in range(3)]
        s18 = np.zeros((len(trip18), 12), dtype=f64)
        for k, (i, jj) in enumerate(trip18):
            s18[k, i + slots[jj]] = 1.0
        t.i18 = jnp.asarray(np.array([i for i, _ in trip18]))
        t.jsel18 = jnp.asarray(np.array([jj for _, jj in trip18]))
        t.s18 = jnp.asarray(s18)
    return t


def _x64(jax):
    """Confine float64 to this kernel's traces — the rest of the process
    keeps jax's default x32 promotion rules."""
    try:
        return jax.experimental.enable_x64()
    except Exception:
        jax.config.update("jax_enable_x64", True)
        return contextlib.nullcontext()


# ---------------------------------------------------------------------------
# Fp (13x21-bit f64 limbs)


def _carry_round(x, t, fold=None):
    """One parallel carry round: every limb drops its multiple of BASE into
    its neighbor simultaneously (floor carries handle negatives; exact for
    |value| < 2^52). With `fold`, the top limb's carry re-enters at 2^273
    mod P; without, it is returned for the caller to fold."""
    jnp = t.jnp
    c = jnp.floor(x * (1.0 / BASE))
    low = x - c * BASE
    y = low + jnp.concatenate(
        [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
    )
    if fold is not None:
        return y + c[..., -1:] * fold
    return y, c[..., -1]


def _fp_condense(x, t):
    """|limb| < 2^46 -> |limb| < 2^23 via four parallel carry+fold rounds.
    The top column's fold contribution is tiny (K13's top limb is < 4), so
    successive top carries shrink 2^25 -> 2^6 -> 2^4 -> <=1 and the lateral
    carries collapse with them."""
    for _ in range(4):
        x = _carry_round(x, t, fold=t.k13)
    return x


def _fp_mul(a, b, t):
    """Exact modular multiply, |input limb| < 2^26 -> |output limb| < 2^23."""
    jnp = t.jnp
    prod = a[..., :, None] * b[..., None, :]  # < 2^52, exact
    hi = jnp.floor(prod * (1.0 / BASE))
    lo = prod - hi * BASE
    cols = jnp.einsum("...ij,ijk->...k", lo, t.e0) + jnp.einsum(
        "...ij,ijk->...k", hi, t.e1
    )
    # One parallel round takes the 26 columns from < 2^38.5 to < 2^21.1 —
    # small enough that the high-half fold stays under 2^46.
    limbs, top = _carry_round(cols, t)
    low, high = limbs[..., :NLIMB], limbs[..., NLIMB:]
    red = high @ t.m + top[..., None] * t.c26
    return _fp_condense(low + red, t)


# ---------------------------------------------------------------------------
# Packed Fp2: arrays (..., 2, 13), u^2 = -1. Every multiply in a stage is
# stacked into ONE batched _fp_mul: a Miller bit is ~200 field muls, and
# issuing them as individual subgraphs made XLA chew minutes of compile —
# batched, the body is a handful of wide einsums.


def _f2_mul_many(xs, ys, t):
    """Karatsuba Fp2 multiply for k independent pairs in one _fp_mul call.
    An Fp operand rides as (re, 0) — one wasted lane beats a second path."""
    jnp = t.jnp
    k = len(xs)
    X = jnp.stack(xs, axis=1)  # (n, k, 2, 13)
    Y = jnp.stack(ys, axis=1)
    L = jnp.concatenate(
        [X[:, :, 0], X[:, :, 1], X[:, :, 0] + X[:, :, 1]], axis=1
    )
    R = jnp.concatenate(
        [Y[:, :, 0], Y[:, :, 1], Y[:, :, 0] + Y[:, :, 1]], axis=1
    )
    prod = _fp_mul(L, R, t)
    a, b, c = prod[:, :k], prod[:, k : 2 * k], prod[:, 2 * k :]
    out = jnp.stack([a - b, c - a - b], axis=2)
    return [out[:, i] for i in range(k)]


def _f2_cond_many(xs, t):
    jnp = t.jnp
    v = _fp_condense(jnp.stack(xs, axis=1), t)
    return [v[:, i] for i in range(len(xs))]


# ---------------------------------------------------------------------------
# Fp12 = Fp2[w]/(w^6 - xi): packed (n, 6, 2, 13), same basis as crypto.bn254


def _fold_cond(re, im, t):
    """Scatter residues 6..11 back through w^6 = xi = 9 + u, then condense.
    re/im: (n, 12, 13)."""
    jnp = t.jnp
    r6 = re[:, :6] + 9 * re[:, 6:] - im[:, 6:]
    i6 = im[:, :6] + re[:, 6:] + 9 * im[:, 6:]
    return _fp_condense(jnp.stack([r6, i6], axis=2), t)


def _f12_sqr(F, t):
    """Schoolbook squaring with symmetry: 21 Fp2 products (cross terms
    carry weight 2 in the scatter matrix), one batched mul."""
    jnp = t.jnp
    aL, aR = F[:, t.i21], F[:, t.j21]  # (n, 21, 2, 13)
    L = jnp.concatenate(
        [aL[:, :, 0], aL[:, :, 1], aL[:, :, 0] + aL[:, :, 1]], axis=1
    )
    R = jnp.concatenate(
        [aR[:, :, 0], aR[:, :, 1], aR[:, :, 0] + aR[:, :, 1]], axis=1
    )
    prod = _fp_mul(L, R, t)
    a, b, c = prod[:, :21], prod[:, 21:42], prod[:, 42:]
    re = jnp.einsum("nkl,km->nml", a - b, t.s21)
    im = jnp.einsum("nkl,km->nml", c - a - b, t.s21)
    return _fold_cond(re, im, t)


def _f12_sparse(F, line, t):
    """F * line for a line sparse at w^0, w^1, w^3: 18 Fp2 products, one
    batched mul."""
    jnp = t.jnp
    C = jnp.stack(line, axis=1)  # (n, 3, 2, 13)
    aL, aR = F[:, t.i18], C[:, t.jsel18]
    L = jnp.concatenate(
        [aL[:, :, 0], aL[:, :, 1], aL[:, :, 0] + aL[:, :, 1]], axis=1
    )
    R = jnp.concatenate(
        [aR[:, :, 0], aR[:, :, 1], aR[:, :, 0] + aR[:, :, 1]], axis=1
    )
    prod = _fp_mul(L, R, t)
    a, b, c = prod[:, :18], prod[:, 18:36], prod[:, 36:]
    re = jnp.einsum("nkl,km->nml", a - b, t.s18)
    im = jnp.einsum("nkl,km->nml", c - a - b, t.s18)
    return _fold_cond(re, im, t)


# ---------------------------------------------------------------------------
# G2 Jacobian steps with scaled sparse lines (Fp2 scalings die in the final
# exponentiation; asserted against the host affine loop by the agg tests).
# Stages batch every multiply whose operands are already available.


def _dbl_and_line(X, Y, Z, xp2, yp2, t):
    """Double T=(X,Y,Z) and evaluate the tangent at (xp, yp), scaled Z^6:
    c0 = 2*Y*Z^3*yp, c1 = -3*X^2*Z^2*xp, c3 = 3*X^3 - 2*Y^2."""
    A, Bv, Z2 = _f2_mul_many([X, Y, Z], [X, Y, Z], t)
    Cv, XB, Z3p, YZ = _f2_mul_many(
        [Bv, X + Bv, Z2, Y], [Bv, X + Bv, Z, Z], t
    )
    D, E = _f2_cond_many([2 * (XB - A - Cv), 3 * A], t)
    F2, EZ2, AX, YZ3 = _f2_mul_many([E, E, A, Y], [E, Z2, X, Z3p], t)
    X3, c3, Z3 = _f2_cond_many([F2 - 2 * D, 3 * AX - 2 * Bv, 2 * YZ], t)
    EDX, c0h, c1h = _f2_mul_many([E, YZ3, EZ2], [D - X3, yp2, xp2], t)
    Y3 = _f2_cond_many([EDX - 8 * Cv], t)[0]
    return X3, Y3, Z3, (2 * c0h, -c1h, c3)


def _add_and_line(X, Y, Z, xq, yq, xp2, yp2, t):
    """Mixed add T + Q (Q affine) and the chord line through Q, scaled Z^3:
    c0 = H*Z*yp, c1 = -r*xp, c3 = r*xq - yq*H*Z."""
    Z2 = _f2_mul_many([Z], [Z], t)[0]
    Z3p, U2 = _f2_mul_many([Z2, xq], [Z, Z2], t)
    S2 = _f2_mul_many([yq], [Z3p], t)[0]
    H, r = _f2_cond_many([U2 - X, S2 - Y], t)
    H2, rsq, ZH = _f2_mul_many([H, r, Z], [H, r, H], t)
    H3, V, rxq, yqZH, c0, c1h = _f2_mul_many(
        [H2, X, r, yq, ZH, r], [H, H2, xq, ZH, yp2, xp2], t
    )
    X3, Z3 = _f2_cond_many([rsq - H3 - 2 * V, ZH], t)
    rVX3, YH3 = _f2_mul_many([r, Y], [V - X3, H3], t)
    Y3 = _f2_cond_many([rVX3 - YH3], t)[0]
    return X3, Y3, Z3, (c0, -c1h, rxq - yqZH)


def _build_program(t):
    """One traced body for every bucket size: the scan is over the constant
    ate bits, the add branch always computed and where-selected."""
    jnp = t.jnp

    def run(p1, q, q1, q2):
        n = p1.shape[0]
        zero = jnp.zeros((n, NLIMB), dtype=p1.dtype)
        xp2 = jnp.stack([p1[:, 0], zero], axis=1)  # Fp as (re, 0)
        yp2 = jnp.stack([p1[:, 1], zero], axis=1)
        xq, yq = q[:, 0], q[:, 1]  # (n, 2, 13)
        F = jnp.zeros((n, 6, 2, NLIMB), dtype=p1.dtype).at[:, 0, 0, 0].set(1.0)
        Z1 = jnp.zeros((n, 2, NLIMB), dtype=p1.dtype).at[:, 0, 0].set(1.0)
        X, Y, Z = xq, yq, Z1

        def body(carry, bit):
            F, X, Y, Z = carry
            F = _f12_sqr(F, t)
            Xd, Yd, Zd, ldbl = _dbl_and_line(X, Y, Z, xp2, yp2, t)
            F = _f12_sparse(F, ldbl, t)
            Xa, Ya, Za, ladd = _add_and_line(Xd, Yd, Zd, xq, yq, xp2, yp2, t)
            Fa = _f12_sparse(F, ladd, t)
            take = bit > 0.5

            def sel(a, b):
                return jnp.where(take, a, b)

            return (sel(Fa, F), sel(Xa, Xd), sel(Ya, Yd), sel(Za, Zd)), None

        (F, X, Y, Z), _ = t.jax.lax.scan(body, (F, X, Y, Z), t.bits)
        # Frobenius adjustment: Q1 = pi(Q), Q2 = -pi^2(Q), host-precomputed.
        Xn, Yn, Zn, l1 = _add_and_line(X, Y, Z, q1[:, 0], q1[:, 1], xp2, yp2, t)
        F = _f12_sparse(F, l1, t)
        _, _, _, l2 = _add_and_line(Xn, Yn, Zn, q2[:, 0], q2[:, 1], xp2, yp2, t)
        F = _f12_sparse(F, l2, t)
        return F  # (n, 6, 2, 13)

    return run


@lru_cache(maxsize=8)
def _program(n):
    t = _tables()
    return t.jax.jit(_build_program(t))


# ---------------------------------------------------------------------------
# Host packing / dispatch


def _pack(pairs, bucket, np):
    p1 = np.zeros((bucket, 2, NLIMB), dtype=np.float64)
    qa = np.zeros((bucket, 2, 2, NLIMB), dtype=np.float64)
    q1a = np.zeros_like(qa)
    q2a = np.zeros_like(qa)
    padded = list(pairs) + [(_b.G1, _b.G2)] * (bucket - len(pairs))
    for lane, (p_pt, q) in enumerate(padded):
        p1[lane, 0] = to_limbs(p_pt[0] % P)
        p1[lane, 1] = to_limbs(p_pt[1] % P)
        q1 = _b._g2_frobenius(q)
        q2 = _b._g2_neg(_b._g2_frobenius(q1))
        for arr, pt in ((qa, q), (q1a, q1), (q2a, q2)):
            for ci, comp in enumerate(pt):  # x, y
                arr[lane, ci, 0] = to_limbs(comp[0] % P)
                arr[lane, ci, 1] = to_limbs(comp[1] % P)
    return p1, qa, q1a, q2a


def _unpack_lane(out, lane) -> tuple:
    return tuple(
        (from_limbs(out[lane, k, 0]), from_limbs(out[lane, k, 1]))
        for k in range(6)
    )


def _dispatch(pairs) -> list:
    """Run one chunk of (G1, G2-affine) lanes on device; exact per-lane f12
    Miller values back as host ints."""
    t = _tables()
    bucket = bucket_for(len(pairs))
    with _x64(t.jax):
        arrays = _pack(pairs, bucket, t.np)
        w = mesh_width()
        sharded = w > 1 and bucket % w == 0 and bucket >= _mesh_floor()
        if sharded:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            mesh = Mesh(t.np.array(t.jax.devices()[:w]), ("lane",))
            sh = NamedSharding(mesh, PartitionSpec("lane"))
            arrays = tuple(t.jax.device_put(a, sh) for a in arrays)
        out = t.np.asarray(_program(bucket)(*arrays))
    with _counters_lock:
        _counters["dispatches"] += 1
        _counters["lanes"] += len(pairs)
        if sharded:
            _counters["sharded_dispatches"] += 1
    return [_unpack_lane(out, lane) for lane in range(len(pairs))]


def multi_miller_values(pairs) -> list:
    """Per-lane f_{6t+2,Q}(P) (Jacobian-scaled; valid under final exp).
    None lanes (point at infinity) come back as F12_ONE, matching the host
    multi_miller_loop's filtering, so indices stay 1:1."""
    live = [
        (i, pr)
        for i, pr in enumerate(pairs)
        if pr[0] is not None and pr[1] is not None
    ]
    vals = [_b.F12_ONE] * len(pairs)
    for start in range(0, len(live), MAX_LANES):
        chunk = live[start : start + MAX_LANES]
        outs = _dispatch([pr for _, pr in chunk])
        for (i, _), v in zip(chunk, outs):
            vals[i] = v
    return vals


def multi_pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 with device Miller loops and one shared host
    final exponentiation."""
    if not pairs:
        return True
    f = _b.F12_ONE
    for v in multi_miller_values(pairs):
        f = _b.f12_mul(f, v)
    return _b.final_exponentiation_fast(f) == _b.F12_ONE


def warmup(n: int = 8) -> None:
    """Precompile the bucket for n lanes (the scan body is size-independent
    but each bucket is its own XLA executable)."""
    _dispatch([(_b.G1, _b.G2)] * min(n, MAX_LANES))


def clear_compiled_caches() -> None:
    _program.cache_clear()


# ---------------------------------------------------------------------------
# Chain tier


class Bn254DeviceBackend:
    """Device tier of the bn254 chain: same (pubs, msgs, sigs) byte-column
    protocol as Bn254HostBackend, Miller loops on device, parse + weights +
    final exponentiation on host."""

    name = "bn254-device"

    def aggregate_verify(self, pubs, msgs, agg_sig) -> bool:
        if len(pubs) != len(msgs) or not pubs:
            return False
        if len(agg_sig) not in (
            _b.SIGNATURE_SIZE,
            _b.SIGNATURE_SIZE_COMPRESSED,
        ):
            return False
        try:
            s = _b.g2_unmarshal(bytes(agg_sig))
            pairs = []
            for pk_b, m in zip(pubs, msgs):
                pk = _b.g1_decompress(bytes(pk_b))
                if pk is None:
                    return False
                hm = _b._hash_to_g2_cached(bytes(m))
                pairs.append(((pk[0], (P - pk[1]) % P), hm))
            pairs.append((_b.G1, s))
        except (ValueError, TypeError):
            return False
        return multi_pairing_check(pairs)

    def batch_verify(self, pubs, msgs, sigs):
        n = len(pubs)
        bits = [False] * n
        parsed: dict[int, tuple] = {}
        for i in range(n):
            try:
                pk = _b.g1_decompress(bytes(pubs[i]))
                s = _b.g2_unmarshal(bytes(sigs[i]))
                if pk is None or s is None:
                    continue
            except (ValueError, TypeError):
                continue
            parsed[i] = (
                (pk[0], (P - pk[1]) % P),
                _b._hash_to_g2_cached(bytes(msgs[i])),
                s,
            )
        if not parsed:
            return False, bits
        ws = _b._batch_weights(
            [bytes(p) for p in pubs],
            [bytes(m) for m in msgs],
            [bytes(s) for s in sigs],
        )
        # Two lanes per signature — e([w](-pk), H(m)) and e(G1, [w]s) — so a
        # failed product attributes per-sig with one extra final exp each,
        # no re-dispatch. Host scalar mults are ~ms-scale: fine at vote
        # batch sizes, and the 10k commit path uses the aggregate form.
        order = sorted(parsed)
        lanes = []
        for i in order:
            neg_pk, hm, s = parsed[i]
            lanes.append((_b._g1_mul(ws[i], neg_pk), hm))
            lanes.append((_b.G1, _b._g2_mul(ws[i], s)))
        vals = multi_miller_values(lanes)
        f = _b.F12_ONE
        for v in vals:
            f = _b.f12_mul(f, v)
        if _b.final_exponentiation_fast(f) == _b.F12_ONE:
            for i in order:
                bits[i] = True
        else:
            for k, i in enumerate(order):
                v = _b.f12_mul(vals[2 * k], vals[2 * k + 1])
                bits[i] = (
                    _b.final_exponentiation_fast(v) == _b.F12_ONE
                )
        return (n > 0 and all(bits)), bits

    def merkle_root(self, leaves):
        from cometbft_tpu.crypto import merkle

        return merkle.hash_from_byte_slices(list(leaves))

    def mesh_width(self) -> int:
        return mesh_width()

    def ping(self) -> bool:
        if not device_available():
            return False
        try:
            _tables()
            return True
        except Exception:
            return False

"""Device-side operand unpacking for the ed25519 verify kernel.

Round-3's kernel took host-packed limbs and signed digits: ~650 bytes per
signature over the host->device link and ~20 ms of numpy/bigint work per
10k batch. This module moves everything after SHA-512 onto the device —
the kernel now takes the RAW encodings (A, R, S as 8 little-endian uint32
words per 32-byte string; the 64-byte SHA-512 challenge as 16 words), i.e.
128 bytes per signature, and computes on-chip:

  - point y-limbs + sign bit        (words_to_limbs255)
  - s -> signed 4-bit window digits (scalar_words_to_digits)
  - k = digest mod L -> digits      (digest_words_to_digits)

The mod-L reduction uses 12-bit limbs so every schoolbook product fits
int32 (24-bit products, column sums < 2^28.3), folding with
2^252 = -c (mod L), c = L - 2^252 (125 bits). Negative intermediates are
avoided by adding a precomputed multiple of L before each subtraction
(R = lo + (M - hi*c)); three folds bring 512 bits to < lo_max + L < 2L,
then one conditional subtract of L finishes. The signed-window recode is
the same add-8s identity the host packer used (see edwards.scalars_to_
digits), done limb-wise with an unrolled carry chain.

All functions trace into the verify program: a few hundred [N]-wide int32
ops, negligible next to the window ladder, compiled once per bucket.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from cometbft_tpu.ops import field25519 as fe

L = 2**252 + 27742317777372353535851937790883648493
C = L - 2**252  # 125 bits

_LB = 12  # limb bits for the scalar arithmetic
_LMASK = (1 << _LB) - 1


def _int_to_limbs12(v: int, n: int) -> np.ndarray:
    return np.array([(v >> (_LB * i)) & _LMASK for i in range(n)], np.int32)


_C_LIMBS = _int_to_limbs12(C, 11)
_L_LIMBS = _int_to_limbs12(L, 22)
# Multiples of L with headroom for each fold's subtraction (see module doc).
_M1_LIMBS = _int_to_limbs12(L << 140, 33)  # >= max D1 = 2^264 * c < 2^389
_M2_LIMBS = _int_to_limbs12(L << 15, 23)  # >= max D2 = 2^141 * c < 2^266
_M3_LIMBS = _L_LIMBS  # >= max D3 = 2^16 * c < 2^141
_EIGHTS_LIMBS = _int_to_limbs12(int("8" * 64, 16), 22)


def bytes_to_words(b: np.ndarray) -> np.ndarray:
    """uint8[N, 4k] little-endian -> int32[k, N] holding the uint32 words
    (host-side zero-copy-ish view + transpose)."""
    w = np.ascontiguousarray(b, np.uint8).view("<u4")  # [N, k]
    return np.ascontiguousarray(w.T).astype(np.int32)  # int32 BIT pattern


def _u(w):
    return w.astype(jnp.uint32)


def words_to_limbs255(w: jnp.ndarray):
    """int32[8, N] words -> (int32[17, N] 15-bit limbs of bits 0..254,
    bool[N] sign = bit 255). Device analog of fe.fe_from_bytes_le."""
    wu = _u(w)
    limbs = []
    for i in range(fe.LIMBS):
        lo_bit = 15 * i
        j, off = divmod(lo_bit, 32)
        v = wu[j] >> np.uint32(off)
        if off > 32 - 15 and j + 1 < 8:
            v = v | (wu[j + 1] << np.uint32(32 - off))
        limbs.append((v & np.uint32(0x7FFF)).astype(jnp.int32))
    sign = (wu[7] >> np.uint32(31)) == 1
    return jnp.stack(limbs), sign


def _words_to_limbs12(w: jnp.ndarray, nbits: int) -> list:
    """int32[k, N] uint32 words -> list of int32[N] 12-bit limbs covering
    nbits bits."""
    wu = _u(w)
    nwords = w.shape[0]
    out = []
    for i in range((nbits + _LB - 1) // _LB):
        lo_bit = _LB * i
        j, off = divmod(lo_bit, 32)
        v = wu[j] >> np.uint32(off)
        if off > 32 - _LB and j + 1 < nwords:
            v = v | (wu[j + 1] << np.uint32(32 - off))
        out.append((v & np.uint32(_LMASK)).astype(jnp.int32))
    return out


def _carry_seq(limbs: list, nout: int) -> list:
    """Sequential signed carry chain: normalize to nout limbs in [0, 2^12).
    Arithmetic >> keeps negative intermediates correct (q = v >> 12 floors,
    r = v - (q << 12) is always in range). The overall value must be
    non-negative and fit nout limbs; the final carry folds into the top."""
    out = []
    carry = None
    for i in range(nout):
        v = limbs[i] if i < len(limbs) else None
        if v is None:
            v = carry
        elif carry is not None:
            v = v + carry
        if v is None:
            out.append(jnp.zeros_like(limbs[0]))
            continue
        q = v >> _LB
        out.append(v - (q << _LB))
        carry = q
    return out


def _mul_limbs(a: list, b_const: np.ndarray) -> list:
    """Schoolbook a * b_const over 12-bit limbs -> unnormalized columns
    (each < len(b) * 2^24 < 2^28.3, int32-safe)."""
    cols = [None] * (len(a) + len(b_const))
    for j, bj in enumerate(b_const):
        bj = int(bj)
        if bj == 0:
            continue
        for i, ai in enumerate(a):
            p = ai * bj
            cols[i + j] = p if cols[i + j] is None else cols[i + j] + p
    return cols


def _fold(limbs: list, m_limbs: np.ndarray, nout: int) -> list:
    """One reduction round: split at limb 21 (bit 252), return
    lo + (M - hi*c) carried to nout limbs."""
    lo, hi = limbs[:21], limbs[21:]
    d = _mul_limbs(hi, _C_LIMBS)
    acc = []
    for i in range(nout):
        v = None
        if i < len(lo):
            v = lo[i]
        if i < len(m_limbs) and m_limbs[i]:
            mv = jnp.int32(int(m_limbs[i]))
            v = mv if v is None else v + mv
        if i < len(d) and d[i] is not None:
            v = -d[i] if v is None else v - d[i]
        acc.append(v if v is not None else jnp.zeros_like(limbs[0]))
    return _carry_seq(acc, nout)


def _cond_sub_l(limbs: list) -> list:
    """limbs (22, value < 2L) -> value mod L via one conditional subtract."""
    diff = []
    borrow = None
    for i in range(22):
        v = limbs[i] - int(_L_LIMBS[i])
        if borrow is not None:
            v = v + borrow
        q = v >> _LB  # 0 or -1
        diff.append(v - (q << _LB))
        borrow = q
    ge = borrow == 0  # no final borrow -> value >= L
    return [jnp.where(ge, d, o) for d, o in zip(diff, limbs)]


def _limbs_to_digits(limbs: list) -> jnp.ndarray:
    """22 12-bit limbs (value < 2^253) -> int32[64, N] signed radix-16
    digits in [-8, 7] via the add-8s identity (t = v + 0x88..8; nibble - 8),
    matching edwards.scalars_to_digits bit for bit."""
    t = [limbs[i] + int(_EIGHTS_LIMBS[i]) for i in range(22)]
    t = _carry_seq(t, 22)
    digits = []
    for d in range(64):
        # 4 divides 12, so a nibble never straddles limbs.
        j, off = divmod(4 * d, _LB)
        digits.append(((t[j] >> off) & 15) - 8)
    return jnp.stack(digits)


def scalar_words_to_digits(w: jnp.ndarray) -> jnp.ndarray:
    """int32[8, N] words of s (< L, host-checked) -> signed digits [64, N]."""
    limbs = _words_to_limbs12(w, 256)  # 22 limbs
    return _limbs_to_digits(limbs)


def digest_words_to_digits(w: jnp.ndarray) -> jnp.ndarray:
    """int32[16, N] words of the 64-byte SHA-512 challenge -> signed digits
    of (digest mod L), entirely on device."""
    limbs = _words_to_limbs12(w, 512)  # 43 limbs
    r1 = _fold(limbs, _M1_LIMBS, 33)
    r2 = _fold(r1, _M2_LIMBS, 23)
    r3 = _fold(r2, _M3_LIMBS, 22)  # < lo_max + L < 2L
    return _limbs_to_digits(_cond_sub_l(r3))

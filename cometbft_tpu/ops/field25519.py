"""GF(2^255-19) arithmetic vectorized for TPU (device tier of crypto/ed25519).

Representation: 17 little-endian limbs of radix 2^15, stacked int32[17, N]
with the batch N in the TPU lane dimension. 17*15 = 255 exactly, so the
wrap-around factor is just 19 (2^255 = 19 mod p) — no oversized fold
constants. Limbs carry a LOOSE invariant: every public op returns limbs in
[0, 2^15 + 95], which keeps all intermediates exact:

  - products:         (2^15+95)^2          < 2^30.2  (int32, no overflow)
  - split halves:     lo < 2^15, hi < 2^15.2
  - column sums:      <= 17 * 2^15.2       < 2^19.3  (int32)
  - 19-fold:          < 2^23.7             (int32)

The multiply has THREE lowerings, chosen per backend at trace time:

  - STACKED (TPU default): the schoolbook convolution as ~35 chunky HLO ops
    — pad x to 33 limbs, stack 17 rolls into a Toeplitz band [17, 33, N],
    broadcast-multiply by y, 15-bit-split, reduce over the j axis, 19-fold,
    stacked carries. Same 289 limb products as the planar form but the
    graph is ~45x smaller: the planar program for the full verify ladder
    took XLA:TPU >8 MINUTES to compile (pass time superlinear in the
    ~75k-op loop body), which timed out the round-3 bench driver; the
    stacked program compiles in seconds and runs on the same VPU path.
  - PLANAR (opt-in via CMTPU_FE_MODE=planar): all 289 limb products and
    their column sums as individual [N]-wide VPU ops (one big XLA fusion).
    Minimal arithmetic (no padded zeros, squaring symmetry) but compile
    time makes it unshippable for the ladder; kept for A/B probes.
  - COMPACT (CPU): the [17,17,N] product tensor + one-hot f32 accumulation
    matmul (~15 HLO ops per multiply). XLA:CPU's compile time is quadratic
    in elementwise-fusion size — a straight-line chain of 8 planar muls
    takes minutes to compile on CPU — so the CPU backend (tests, the
    8-virtual-device dryrun, the host fallback) gets the small-graph form.

Carries are one shift-mask pass per call: ~4 array ops on the stacked form
(_carry_arr, used by the stacked and compact lowerings) or 17 planar
shift-mask chains under CMTPU_FE_MODE=planar (_carry_rows). This is the
TPU-native replacement for curve25519-voi's assembly field element
(reference backend of crypto/ed25519/ed25519.go:27-29).
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

LIMBS = 17
LIMB_BITS = 15
MASK = 0x7FFF

P_INT = 2**255 - 19
D_INT = (-121665 * pow(121666, P_INT - 2, P_INT)) % P_INT
TWO_D_INT = (2 * D_INT) % P_INT
SQRT_M1_INT = pow(2, (P_INT - 1) // 4, P_INT)


def int_to_limbs(v: int) -> np.ndarray:
    """Python int -> int32[17] little-endian 15-bit limbs (host)."""
    return np.array([(v >> (LIMB_BITS * i)) & MASK for i in range(LIMBS)], np.int32)


def limbs_to_int(a) -> int:
    """int32[17] (or [17,1]) -> Python int (host, for tests)."""
    a = np.asarray(a).reshape(LIMBS)
    return sum(int(a[i]) << (LIMB_BITS * i) for i in range(LIMBS))


_P_LIMBS = [int(x) for x in int_to_limbs(P_INT)]
# 4p per-limb: every limb >= 4*(2^15-19) > 2^15+95, so a - b + 4p stays
# non-negative limb-wise under the loose invariant.
_FOUR_P = np.array([4 * x for x in _P_LIMBS], np.int32).reshape(LIMBS, 1)


def const_fe(v: int) -> np.ndarray:
    """Field constant as int32[17, 1] (broadcasts over the batch).  Kept as
    a NUMPY literal: jnp consumers convert on use, and the Pallas ladder
    kernel (ops/pallas_ladder.py) can close over it — Pallas rejects
    captured traced arrays but inlines host constants."""
    return int_to_limbs(v).reshape(LIMBS, 1)


def fe_from_bytes_le(b: np.ndarray) -> np.ndarray:
    """uint8[N, 32] little-endian -> int32[17, N] limbs, using bits 0..254
    (bit 255 — the point-compression sign — is dropped; extract it first)."""
    b = np.ascontiguousarray(b, dtype=np.uint8)
    bits = np.unpackbits(b, axis=1, bitorder="little")[:, :255]  # [N, 255]
    pows = (1 << np.arange(LIMB_BITS, dtype=np.int32)).astype(np.int32)
    limbs = bits.reshape(-1, LIMBS, LIMB_BITS).astype(np.int32) @ pows  # [N, 17]
    return np.ascontiguousarray(limbs.T)


def fe_to_bytes_le(x) -> np.ndarray:
    """int32[17, N] canonical limbs -> uint8[N, 32] (host)."""
    a = np.asarray(x).T  # [N, 17]
    bits = np.zeros((a.shape[0], 256), np.uint8)
    for l in range(LIMBS):
        for i in range(LIMB_BITS):
            bits[:, l * LIMB_BITS + i] = (a[:, l] >> i) & 1
    return np.packbits(bits, axis=1, bitorder="little")


# -- planar internals --------------------------------------------------------
#
# Rows of a [17, N] field element are sliced into 17 independent [N] arrays,
# operated on as plain SSA values, and re-stacked only at op boundaries; XLA's
# slice-of-concat simplification makes chained ops planar end-to-end.


def _rows(x) -> list:
    return [x[i] for i in range(LIMBS)]


def _carry_rows(c: list) -> list:
    """One parallel carry pass over 17 planar columns: split each at 15 bits,
    carry up one limb, top carry wraps to limb 0 with factor 19."""
    hi = [v >> LIMB_BITS for v in c]
    lo = [v & MASK for v in c]
    out = [lo[0] + 19 * hi[LIMBS - 1]]
    for k in range(1, LIMBS):
        out.append(lo[k] + hi[k - 1])
    return out


def _carry(x: jnp.ndarray) -> jnp.ndarray:
    if _mode() == "planar":
        return jnp.stack(_carry_rows(_rows(x)))
    return _carry_arr(x)


def _mul_rows(xs: list, ys: list) -> list:
    """289 limb products, 15-bit split per product, planar column sums,
    19-fold, two carry passes. Returns 17 loose planar columns."""
    cols = [None] * (2 * LIMBS)

    def acc(k, v):
        cols[k] = v if cols[k] is None else cols[k] + v

    for i in range(LIMBS):
        for j in range(LIMBS):
            p = xs[i] * ys[j]
            acc(i + j, p & MASK)
            acc(i + j + 1, p >> LIMB_BITS)
    folded = [cols[k] + 19 * cols[k + LIMBS] for k in range(LIMBS)]
    return _carry_rows(_carry_rows(folded))


def _sq_rows(xs: list) -> list:
    """Squaring: 153 products (symmetry), cross terms doubled AFTER the
    15-bit split (2*p would overflow int32 at loose-limb maxima)."""
    cols = [None] * (2 * LIMBS)

    def acc(k, v):
        cols[k] = v if cols[k] is None else cols[k] + v

    for i in range(LIMBS):
        p = xs[i] * xs[i]
        acc(2 * i, p & MASK)
        acc(2 * i + 1, p >> LIMB_BITS)
        for j in range(i + 1, LIMBS):
            p = xs[i] * xs[j]
            acc(i + j, (p & MASK) * 2)
            acc(i + j + 1, (p >> LIMB_BITS) * 2)
    folded = [cols[k] + 19 * cols[k + LIMBS] for k in range(LIMBS)]
    return _carry_rows(_carry_rows(folded))


# -- stacked (Toeplitz-band) multiply: the TPU-default lowering --------------


def _carry_arr(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass as ~4 array ops on the stacked [17, N] form
    (same math as _carry_rows: split at 15 bits, carry up one limb, top
    carry wraps to limb 0 with factor 19)."""
    hi = x >> LIMB_BITS
    lo = x & MASK
    wrap = jnp.concatenate([19 * hi[LIMBS - 1 :], hi[: LIMBS - 1]], axis=0)
    return lo + wrap


def _mul_stacked(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook multiply as ~35 chunky HLO ops: z_col[c] = sum_j
    x[c-j] * y[j] via a rolled Toeplitz band. Products are split at 15 bits
    BEFORE the j-reduction (raw column sums of 2^30.2 products would
    overflow int32), the high halves land one column up, and columns 17..33
    fold back with factor 19 (2^255 = 19 mod p). All bounds as the planar
    form: split sums < 2^19.3, folded columns < 2^24.5, two carry passes
    restore the loose invariant."""
    n = x.shape[1]
    xp = jnp.concatenate([x, jnp.zeros((LIMBS - 1, n), jnp.int32)], axis=0)
    band = jnp.stack([jnp.roll(xp, j, axis=0) for j in range(LIMBS)])
    p = band * y[:, None, :]  # [17 (j), 33 (col), N], each < 2^30.2
    lo = (p & MASK).sum(axis=0)  # [33, N], < 17 * 2^15
    hi = (p >> LIMB_BITS).sum(axis=0)  # [33, N], < 17 * 2^15.2
    zrow = jnp.zeros((1, n), jnp.int32)
    cols = jnp.concatenate([lo, zrow], axis=0) + jnp.concatenate([zrow, hi], axis=0)
    folded = cols[:LIMBS] + 19 * cols[LIMBS:]
    return _carry_arr(_carry_arr(folded))


# -- compact (matmul-accumulation) multiply for the CPU backend --------------

# One-hot accumulation matrix: entry [k, j*17+i] = 1 where the low half of
# product x_i*y_j lands in column i+j, and [k, 289 + j*17+i] = 1 where the
# high half lands in column i+j+1. One f32 matmul replaces ~580 adds; exact
# because every UNWEIGHTED column sum stays under 2^21 (f32 integer-exact
# range) — the 19-fold happens afterwards in int32, where a folded column
# can exceed 2^24 and would NOT be f32-exact.
_ACC = np.zeros((2 * LIMBS, 2 * LIMBS * LIMBS), np.float32)
for _j in range(LIMBS):
    for _i in range(LIMBS):
        _ACC[_i + _j, _j * LIMBS + _i] = 1.0
        _ACC[_i + _j + 1, LIMBS * LIMBS + _j * LIMBS + _i] = 1.0


def _mul_compact(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    n = x.shape[1]
    p = x[None, :, :] * y[:, None, :]  # [j, i, N] int32, < 2^30.2
    lo = (p & MASK).astype(jnp.float32).reshape(LIMBS * LIMBS, n)
    hi = (p >> LIMB_BITS).astype(jnp.float32).reshape(LIMBS * LIMBS, n)
    flat = jnp.concatenate([lo, hi], axis=0)  # [578, N]
    cols = lax.dot_general(
        jnp.asarray(_ACC),
        flat,
        (((1,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
    ).astype(jnp.int32)  # [34, N]
    folded = cols[:LIMBS] + 19 * cols[LIMBS:]
    return _carry(_carry(folded))


_ACCEL: bool | None = None
_SCOPE = threading.local()
# CMTPU_FE_MODE: auto (default; stacked on accelerators, compact on CPU),
# or an explicit stacked / planar / compact override for A/B probes. A typo
# must fail loudly, not silently measure the default lowering.
_MODE_ENV = os.environ.get("CMTPU_FE_MODE", "auto")
if _MODE_ENV not in ("auto", "stacked", "planar", "compact"):
    raise ValueError(
        f"CMTPU_FE_MODE={_MODE_ENV!r}: expected auto|stacked|planar|compact"
    )


def _is_accel() -> bool:
    """True on non-CPU backends. Matched by exclusion: the TPU tunnel on
    this deployment registers its PJRT platform as "axon", not "tpu". The
    backend is sampled once per process — mixed-backend processes would need
    per-trace plumbing this framework doesn't require."""
    global _ACCEL
    if _ACCEL is None:
        _ACCEL = jax.default_backend() != "cpu"
    return _ACCEL


def _mode() -> str:
    """Lowering for the current trace (see module docstring)."""
    if _MODE_ENV in ("stacked", "compact"):
        return _MODE_ENV
    if _MODE_ENV == "planar":
        # Historical behavior for A/B probes: planar ladder, compact scopes.
        if getattr(_SCOPE, "compact", False) or not _is_accel():
            return "compact"
        return "planar"
    return "stacked" if _is_accel() else "compact"


@contextmanager
def compact_scope():
    """Mark a STRAIGHT-LINE trace region (decompression's inversion chain,
    final adds). Only meaningful under CMTPU_FE_MODE=planar, where such
    sections would dominate compile time for a marginal runtime share and
    are forced compact; the default stacked lowering is small-graph
    everywhere, so the scope is a no-op there."""
    prev = getattr(_SCOPE, "compact", False)
    _SCOPE.compact = True
    try:
        yield
    finally:
        _SCOPE.compact = prev


def fe_mul(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """z = x*y mod p under the loose invariant."""
    m = _mode()
    if m == "stacked":
        return _mul_stacked(x, y)
    if m == "planar":
        return jnp.stack(_mul_rows(_rows(x), _rows(y)))
    return _mul_compact(x, y)


def fe_sq(x: jnp.ndarray) -> jnp.ndarray:
    m = _mode()
    if m == "stacked":
        return _mul_stacked(x, x)
    if m == "planar":
        return jnp.stack(_sq_rows(_rows(x)))
    return _mul_compact(x, x)


def fe_add(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return _carry(x + y)


def fe_sub(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return _carry(x + jnp.asarray(_FOUR_P) - y)


def fe_neg(x: jnp.ndarray) -> jnp.ndarray:
    return _carry(jnp.asarray(_FOUR_P) - x)


def _seq_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Sequential carry chain with wrap — tightens limbs to < 2^15 except a
    tiny residue in limb 0; used only inside freeze."""
    cols = [x[k] for k in range(LIMBS)]
    out = []
    c = None
    for k in range(LIMBS):
        t = cols[k] if c is None else cols[k] + c
        out.append(t & MASK)
        c = t >> LIMB_BITS
    out[0] = out[0] + 19 * c
    return jnp.stack(out)


def fe_freeze(x: jnp.ndarray) -> jnp.ndarray:
    """Canonical residue in [0, p). Two sequential passes bring the value
    below 2^255 + 19; two conditional subtractions of p finish."""
    x = _seq_carry(_seq_carry(x))
    for _ in range(2):
        cols = [x[k] - _P_LIMBS[k] for k in range(LIMBS)]
        out = []
        b = None
        for k in range(LIMBS):
            t = cols[k] if b is None else cols[k] + b
            out.append(t & MASK)
            b = t >> LIMB_BITS  # arithmetic shift: 0 or -1 (borrow)
        ge = b == 0  # no final borrow -> x >= p -> keep subtracted form
        x = jnp.stack([jnp.where(ge, out[k], x[k]) for k in range(LIMBS)])
    return x


def fe_is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: x == 0 mod p (freezes internally)."""
    f = fe_freeze(x)
    acc = f[0]
    for k in range(1, LIMBS):
        acc = acc | f[k]
    return acc == 0


def fe_eq(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return fe_is_zero(fe_sub(x, y))


def fe_parity(x: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: least significant bit of the canonical residue."""
    return (fe_freeze(x)[0] & 1) == 1


def fe_select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """where(mask, a, b) with mask [N] broadcast over limbs."""
    return jnp.where(mask[None, :], a, b)


def _sq_n(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """n repeated squarings; rolled into fori_loop to bound program size."""
    if n <= 4:
        for _ in range(n):
            x = fe_sq(x)
        return x
    return lax.fori_loop(0, n, lambda _, t: fe_sq(t), x)


def fe_pow2523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3), the square-root exponent for point
    decompression (crypto/ed25519 decoding). Standard 2^k-1 ladder chain."""
    t0 = fe_sq(z)                      # z^2
    t1 = fe_mul(z, _sq_n(t0, 2))       # z^9
    t0 = fe_mul(t0, t1)                # z^11
    t0 = fe_mul(t1, fe_sq(t0))         # z^31   = z^(2^5 - 1)
    t0 = fe_mul(_sq_n(t0, 5), t0)      # 2^10 - 1
    t1 = fe_mul(_sq_n(t0, 10), t0)     # 2^20 - 1
    t2 = fe_mul(_sq_n(t1, 20), t1)     # 2^40 - 1
    t1 = fe_mul(_sq_n(t2, 10), t0)     # 2^50 - 1
    t2 = fe_mul(_sq_n(t1, 50), t1)     # 2^100 - 1
    t2 = fe_mul(_sq_n(t2, 100), t2)    # 2^200 - 1
    t1 = fe_mul(_sq_n(t2, 50), t1)     # 2^250 - 1
    return fe_mul(_sq_n(t1, 2), z)     # 2^252 - 3


def fe_invert(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21) via the same ladder (for point compression)."""
    t0 = fe_sq(z)                      # z^2
    t1 = fe_mul(z, _sq_n(t0, 2))       # z^9
    t1b = fe_mul(t0, t1)               # z^11
    t0 = fe_mul(t1, fe_sq(t1b))        # z^31
    t0 = fe_mul(_sq_n(t0, 5), t0)      # 2^10 - 1
    t1 = fe_mul(_sq_n(t0, 10), t0)     # 2^20 - 1
    t2 = fe_mul(_sq_n(t1, 20), t1)     # 2^40 - 1
    t1 = fe_mul(_sq_n(t2, 10), t0)     # 2^50 - 1
    t2 = fe_mul(_sq_n(t1, 50), t1)     # 2^100 - 1
    t2 = fe_mul(_sq_n(t2, 100), t2)    # 2^200 - 1
    t1 = fe_mul(_sq_n(t2, 50), t1)     # 2^250 - 1
    return fe_mul(_sq_n(t1, 5), t1b)   # 2^255 - 21

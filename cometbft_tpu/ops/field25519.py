"""GF(2^255-19) arithmetic vectorized for TPU (device tier of crypto/ed25519).

Representation: 17 little-endian limbs of radix 2^15, stacked int32[17, N]
with the batch N in the TPU lane dimension. 17*15 = 255 exactly, so the
wrap-around factor is just 19 (2^255 = 19 mod p) — no oversized fold
constants. Limbs carry a LOOSE invariant: every public op returns limbs in
[0, 2^15 + 95], which keeps all intermediates exact:

  - products:       (2^15+95)^2           < 2^30.1  (int32, no overflow)
  - split halves:   lo < 2^15, hi < 2^15.1 (exact in float32)
  - column sums:    <= 34 * 2^15.1 < 2^20.2 (exact in float32 accumulation)
  - 19-fold:        < 2^24.5              (int32)

Carries are PARALLEL (shift-mask-roll over the limb axis), not sequential
chains: two passes after a multiply, one after add/sub — the shape XLA fuses
into a handful of vector ops. This is the TPU-native replacement for
curve25519-voi's assembly field element (reference backend of
crypto/ed25519/ed25519.go:27-29).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

LIMBS = 17
LIMB_BITS = 15
MASK = 0x7FFF

P_INT = 2**255 - 19
D_INT = (-121665 * pow(121666, P_INT - 2, P_INT)) % P_INT
TWO_D_INT = (2 * D_INT) % P_INT
SQRT_M1_INT = pow(2, (P_INT - 1) // 4, P_INT)


def int_to_limbs(v: int) -> np.ndarray:
    """Python int -> int32[17] little-endian 15-bit limbs (host)."""
    return np.array([(v >> (LIMB_BITS * i)) & MASK for i in range(LIMBS)], np.int32)


def limbs_to_int(a) -> int:
    """int32[17] (or [17,1]) -> Python int (host, for tests)."""
    a = np.asarray(a).reshape(LIMBS)
    return sum(int(a[i]) << (LIMB_BITS * i) for i in range(LIMBS))


_P_LIMBS = [int(x) for x in int_to_limbs(P_INT)]
# 4p per-limb: every limb >= 4*(2^15-19) > 2^15+95, so a - b + 4p stays
# non-negative limb-wise under the loose invariant.
_FOUR_P = np.array([4 * x for x in _P_LIMBS], np.int32).reshape(LIMBS, 1)

# Wrap weights for the parallel carry: carry out of limb 16 re-enters limb 0
# multiplied by 19 (2^255 = 19 mod p); all other carries shift up one limb.
_WRAP = np.array([19] + [1] * (LIMBS - 1), np.int32).reshape(LIMBS, 1)


def const_fe(v: int) -> jnp.ndarray:
    """Field constant as int32[17, 1] (broadcasts over the batch)."""
    return jnp.asarray(int_to_limbs(v).reshape(LIMBS, 1))


def fe_from_bytes_le(b: np.ndarray) -> np.ndarray:
    """uint8[N, 32] little-endian -> int32[17, N] limbs, using bits 0..254
    (bit 255 — the point-compression sign — is dropped; extract it first)."""
    b = np.ascontiguousarray(b, dtype=np.uint8)
    bits = np.unpackbits(b, axis=1, bitorder="little")[:, :255]  # [N, 255]
    pows = (1 << np.arange(LIMB_BITS, dtype=np.int32)).astype(np.int32)
    limbs = bits.reshape(-1, LIMBS, LIMB_BITS).astype(np.int32) @ pows  # [N, 17]
    return np.ascontiguousarray(limbs.T)


def fe_to_bytes_le(x) -> np.ndarray:
    """int32[17, N] canonical limbs -> uint8[N, 32] (host)."""
    a = np.asarray(x).T  # [N, 17]
    bits = np.zeros((a.shape[0], 256), np.uint8)
    for l in range(LIMBS):
        for i in range(LIMB_BITS):
            bits[:, l * LIMB_BITS + i] = (a[:, l] >> i) & 1
    return np.packbits(bits, axis=1, bitorder="little")


def _carry(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass: split each limb at 15 bits, shift carries up
    one limb (top carry wraps to limb 0 with factor 19)."""
    c = x >> LIMB_BITS
    r = x & MASK
    return r + jnp.roll(c, 1, axis=0) * jnp.asarray(_WRAP)


# One-hot accumulation matrix: entry [k, j*17+i] = 1 where the low half of
# product x_i*y_j lands in column i+j, and [k, 289 + j*17+i] = 1 where the
# high half lands in column i+j+1. One f32 matmul replaces 34 pad+adds —
# a single MXU-friendly op with exact integer arithmetic (all values < 2^21
# are exactly representable in float32).
_ACC = np.zeros((2 * LIMBS, 2 * LIMBS * LIMBS), np.float32)
for _j in range(LIMBS):
    for _i in range(LIMBS):
        _ACC[_i + _j, _j * LIMBS + _i] = 1.0
        _ACC[_i + _j + 1, LIMBS * LIMBS + _j * LIMBS + _i] = 1.0


def fe_mul(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """z = x*y mod p under the loose invariant. Schoolbook [17,17,N] product,
    15-bit split, one-hot f32 matmul column accumulation (exact: columns
    < 2^21), 19-fold, two parallel carry passes."""
    n = x.shape[1]
    p = x[None, :, :] * y[:, None, :]  # [j, i, N] int32, < 2^30.1
    lo = (p & MASK).astype(jnp.float32).reshape(LIMBS * LIMBS, n)
    hi = (p >> LIMB_BITS).astype(jnp.float32).reshape(LIMBS * LIMBS, n)
    flat = jnp.concatenate([lo, hi], axis=0)  # [578, N]
    cols = lax.dot_general(
        jnp.asarray(_ACC),
        flat,
        (((1,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
    ).astype(jnp.int32)  # [34, N]
    folded = cols[:LIMBS] + 19 * cols[LIMBS:]
    return _carry(_carry(folded))


def fe_sq(x: jnp.ndarray) -> jnp.ndarray:
    return fe_mul(x, x)


def fe_add(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return _carry(x + y)


def fe_sub(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return _carry(x + jnp.asarray(_FOUR_P) - y)


def fe_neg(x: jnp.ndarray) -> jnp.ndarray:
    return _carry(jnp.asarray(_FOUR_P) - x)


def _seq_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Sequential carry chain with wrap — tightens limbs to < 2^15 except a
    tiny residue in limb 0; used only inside freeze."""
    cols = [x[k] for k in range(LIMBS)]
    out = []
    c = None
    for k in range(LIMBS):
        t = cols[k] if c is None else cols[k] + c
        out.append(t & MASK)
        c = t >> LIMB_BITS
    out[0] = out[0] + 19 * c
    return jnp.stack(out)


def fe_freeze(x: jnp.ndarray) -> jnp.ndarray:
    """Canonical residue in [0, p). Two sequential passes bring the value
    below 2^255 + 19; two conditional subtractions of p finish."""
    x = _seq_carry(_seq_carry(x))
    for _ in range(2):
        cols = [x[k] - _P_LIMBS[k] for k in range(LIMBS)]
        out = []
        b = None
        for k in range(LIMBS):
            t = cols[k] if b is None else cols[k] + b
            out.append(t & MASK)
            b = t >> LIMB_BITS  # arithmetic shift: 0 or -1 (borrow)
        ge = b == 0  # no final borrow -> x >= p -> keep subtracted form
        x = jnp.stack([jnp.where(ge, out[k], x[k]) for k in range(LIMBS)])
    return x


def fe_is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: x == 0 mod p (freezes internally)."""
    f = fe_freeze(x)
    acc = f[0]
    for k in range(1, LIMBS):
        acc = acc | f[k]
    return acc == 0


def fe_eq(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return fe_is_zero(fe_sub(x, y))


def fe_parity(x: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: least significant bit of the canonical residue."""
    return (fe_freeze(x)[0] & 1) == 1


def fe_select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """where(mask, a, b) with mask [N] broadcast over limbs."""
    return jnp.where(mask[None, :], a, b)


def _sq_n(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """n repeated squarings; rolled into fori_loop to bound program size."""
    if n <= 4:
        for _ in range(n):
            x = fe_sq(x)
        return x
    return lax.fori_loop(0, n, lambda _, t: fe_sq(t), x)


def fe_pow2523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3), the square-root exponent for point
    decompression (crypto/ed25519 decoding). Standard 2^k-1 ladder chain."""
    t0 = fe_sq(z)                      # z^2
    t1 = fe_mul(z, _sq_n(t0, 2))       # z^9
    t0 = fe_mul(t0, t1)                # z^11
    t0 = fe_mul(t1, fe_sq(t0))         # z^31   = z^(2^5 - 1)
    t0 = fe_mul(_sq_n(t0, 5), t0)      # 2^10 - 1
    t1 = fe_mul(_sq_n(t0, 10), t0)     # 2^20 - 1
    t2 = fe_mul(_sq_n(t1, 20), t1)     # 2^40 - 1
    t1 = fe_mul(_sq_n(t2, 10), t0)     # 2^50 - 1
    t2 = fe_mul(_sq_n(t1, 50), t1)     # 2^100 - 1
    t2 = fe_mul(_sq_n(t2, 100), t2)    # 2^200 - 1
    t1 = fe_mul(_sq_n(t2, 50), t1)     # 2^250 - 1
    return fe_mul(_sq_n(t1, 2), z)     # 2^252 - 3


def fe_invert(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21) via the same ladder (for point compression)."""
    t0 = fe_sq(z)                      # z^2
    t1 = fe_mul(z, _sq_n(t0, 2))       # z^9
    t1b = fe_mul(t0, t1)               # z^11
    t0 = fe_mul(t1, fe_sq(t1b))        # z^31
    t0 = fe_mul(_sq_n(t0, 5), t0)      # 2^10 - 1
    t1 = fe_mul(_sq_n(t0, 10), t0)     # 2^20 - 1
    t2 = fe_mul(_sq_n(t1, 20), t1)     # 2^40 - 1
    t1 = fe_mul(_sq_n(t2, 10), t0)     # 2^50 - 1
    t2 = fe_mul(_sq_n(t1, 50), t1)     # 2^100 - 1
    t2 = fe_mul(_sq_n(t2, 100), t2)    # 2^200 - 1
    t1 = fe_mul(_sq_n(t2, 50), t1)     # 2^250 - 1
    return fe_mul(_sq_n(t1, 5), t1b)   # 2^255 - 21

"""Device-tier kernels (JAX/XLA, TPU-first).

Everything the reference dispatches through `crypto.BatchVerifier`
(crypto/ed25519/ed25519.go:196-228) and `crypto/merkle`
(crypto/merkle/tree.go:11) runs here as vectorized, jit-compiled programs:

  - field25519:    GF(2^255-19) limb arithmetic, batch-last layout
  - edwards:       complete twisted-Edwards point ops + Shamir ladder
  - sha256_kernel: vectorized SHA-256 compression
  - ed25519_kernel: batched ZIP-215 signature verification
  - merkle_kernel: level-synchronous RFC-6962 tree hashing

Layouts put the batch dimension LAST ([limbs, N] / [words, N]) so the batch
fills TPU vector lanes while limb/word indices stay static Python ints.
"""

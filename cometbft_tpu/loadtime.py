"""Load generation + block-interval/latency report (reference:
test/loadtime/{cmd,payload,report} and test/e2e/runner/benchmark.go:14-56).

The reference's loadtime tool pumps transactions whose payload embeds the
creation time, then a report tool reads the committed chain back and derives
tx latency (block time - creation time); the e2e runner's Benchmark reports
mean/σ/min/max block interval over a window of consecutive blocks.  This
module is both halves against an in-process devnet: `run_load` drives a
4-validator TCP devnet at a target tx rate until the window has passed,
`build_report` recovers latencies from the committed payloads.

Exercised by the gated bench stage (bench.py) and `python -m
cometbft_tpu.cmd loadtime`.
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import dataclass


@dataclass
class Report:
    """mean/σ/min/max block interval + tx latency (benchmark.go:14-21,
    loadtime/report/report.go)."""

    blocks: int = 0
    start_height: int = 0
    end_height: int = 0
    txs_committed: int = 0
    duration_s: float = 0.0
    block_interval_mean_s: float = 0.0
    block_interval_stddev_s: float = 0.0
    block_interval_min_s: float = 0.0
    block_interval_max_s: float = 0.0
    tx_latency_mean_s: float = 0.0
    tx_latency_p50_s: float = 0.0
    tx_latency_p95_s: float = 0.0
    tx_latency_max_s: float = 0.0
    tx_per_s: float = 0.0
    blocks_per_s: float = 0.0
    rate_requested: int = 0
    connections: int = 0

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


def make_payload(seq: int, now_ns: int, size: int = 64) -> bytes:
    """loadtime/payload: id + creation time in the tx, padded to size."""
    base = b"load/%d/%d/" % (seq, now_ns)
    return base + b"x" * max(0, size - len(base))


def make_signed_payload(
    priv, seq: int, now_ns: int, size: int = 64, priority: int = 0
) -> bytes:
    """A loadtime payload wrapped in a SignedTxEnvelope, so load generation
    exercises the QoS ingress preverify path (mempool/ingress.py)."""
    from cometbft_tpu.mempool.ingress import encode_envelope

    return encode_envelope(
        priv, make_payload(seq, now_ns, size), priority=priority, nonce=seq
    )


def parse_payload(tx: bytes) -> int | None:
    """Creation time (ns) if this is a loadtime tx (enveloped or bare)."""
    if tx and tx[0] == 0xCE:  # SignedTxEnvelope: latency lives in the payload
        try:
            from cometbft_tpu.mempool.ingress import decode_envelope

            env = decode_envelope(tx)
        except Exception:
            return None
        if env is None:
            return None
        tx = env.payload
    if not tx.startswith(b"load/"):
        return None
    try:
        return int(tx.split(b"/", 3)[2])
    except (IndexError, ValueError):
        return None


def build_report(block_store, start_height: int, end_height: int) -> Report:
    """Walk committed blocks: intervals from consecutive header times
    (benchmark.go splitIntoBlockIntervals), latencies from payloads."""
    rep = Report(start_height=start_height, end_height=end_height)
    times: list[float] = []
    latencies: list[float] = []
    for h in range(start_height, end_height + 1):
        blk = block_store.load_block(h)
        if blk is None:
            continue
        t = blk.header.time.seconds + blk.header.time.nanos / 1e9
        times.append(t)
        for tx in blk.data.txs:
            created_ns = parse_payload(bytes(tx))
            if created_ns is not None:
                rep.txs_committed += 1
                latencies.append(max(0.0, t - created_ns / 1e9))
    rep.blocks = len(times)
    if len(times) >= 2:
        intervals = [b - a for a, b in zip(times, times[1:])]
        rep.duration_s = times[-1] - times[0]
        rep.block_interval_mean_s = sum(intervals) / len(intervals)
        rep.block_interval_stddev_s = math.sqrt(
            sum((x - rep.block_interval_mean_s) ** 2 for x in intervals)
            / len(intervals)
        )
        rep.block_interval_min_s = min(intervals)
        rep.block_interval_max_s = max(intervals)
        if rep.duration_s > 0:
            rep.blocks_per_s = (rep.blocks - 1) / rep.duration_s
            rep.tx_per_s = rep.txs_committed / rep.duration_s
    if latencies:
        latencies.sort()
        rep.tx_latency_mean_s = sum(latencies) / len(latencies)
        rep.tx_latency_p50_s = latencies[len(latencies) // 2]
        rep.tx_latency_p95_s = latencies[int(len(latencies) * 0.95)]
        rep.tx_latency_max_s = latencies[-1]
    return rep


def run_load(
    n_vals: int = 4,
    rate: int = 200,
    min_blocks: int = 100,
    connections: int = 1,
    timeout_s: float = 120.0,
    signed: bool = False,
    log=lambda s: None,
) -> Report:
    """Drive an in-process TCP devnet at `rate` tx/s (split over
    `connections` submitter threads, loadtime's `-c`) until `min_blocks`
    consecutive blocks have been produced under load; report over exactly
    that window.  With ``signed=True`` each connection signs its txs into
    SignedTxEnvelopes and submits through the node's ingress pipeline, so
    the run measures admission through batched signature pre-verification
    rather than bare FIFO insertion."""
    if rate <= 0 or connections <= 0 or min_blocks <= 0:
        raise ValueError("rate, connections, and min_blocks must be positive")
    from cometbft_tpu.abci.client import LocalClientCreator
    from cometbft_tpu.abci.example.kvstore import KVStoreApplication
    from cometbft_tpu.config import test_config
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types import cmttime
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    pvs = [
        FilePV(ed25519.gen_priv_key_from_secret(b"load-val-%d" % i))
        for i in range(n_vals)
    ]
    gen = GenesisDoc(
        chain_id="loadtime-devnet",
        genesis_time=cmttime.now(),
        validators=[
            GenesisValidator(pv.get_pub_key().address(), pv.get_pub_key(), 10, f"v{i}")
            for i, pv in enumerate(pvs)
        ],
    )
    gen.validate_and_complete()
    nodes = []
    for pv in pvs:
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        nodes.append(Node(cfg, gen, pv, LocalClientCreator(KVStoreApplication())))
    try:
        for nd in nodes:
            nd.start()
        addrs = [nd.switch.node_info.listen_addr for nd in nodes]
        for i, nd in enumerate(nodes):
            for j, a in enumerate(addrs):
                if i != j:
                    nd.switch.dial_peer(a)
        stop = threading.Event()
        seq_lock = threading.Lock()
        seq = [0]

        def submitter(conn_idx: int):
            # Each connection paces itself to rate/connections tx/s
            per = rate / connections
            next_t = time.monotonic()
            sender_priv = (
                ed25519.gen_priv_key_from_secret(b"load-sender-%d" % conn_idx)
                if signed
                else None
            )
            while not stop.is_set():
                with seq_lock:
                    k = seq[0]
                    seq[0] += 1
                nd = nodes[conn_idx % n_vals]
                if signed:
                    tx = make_signed_payload(sender_priv, k, time.time_ns())
                    target = nd.ingress or nd.mempool
                else:
                    tx = make_payload(k, time.time_ns())
                    target = nd.mempool
                try:
                    target.check_tx(tx)
                except Exception:
                    pass
                next_t += 1.0 / per
                delay = next_t - time.monotonic()
                if delay > 0:
                    time.sleep(delay)

        threads = [
            threading.Thread(target=submitter, args=(c,), daemon=True)
            for c in range(connections)
        ]
        for t in threads:
            t.start()
        # let load reach steady state before opening the window
        time.sleep(1.0)
        start_h = nodes[0].block_store.height() + 1
        target_h = start_h + min_blocks - 1
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            h = nodes[0].block_store.height()
            if h >= target_h:
                break
            log(f"loadtime: height {h}/{target_h}")
            time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=2)
        end_h = min(nodes[0].block_store.height(), target_h)
        rep = build_report(nodes[0].block_store, start_h, end_h)
        rep.rate_requested = rate
        rep.connections = connections
        return rep
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:
                pass

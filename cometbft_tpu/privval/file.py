"""File-based private validator with double-sign protection
(reference: privval/file.go).

Two files: a plaintext key file and a last-sign-state file persisted BEFORE
every signature, so a restarted validator can never sign conflicting
votes/proposals for a height/round/step it already signed
(privval/file.go:76-94 CheckHRS, :151 FilePV). Re-signing the same HRS is
allowed only when the message differs solely in timestamp (file.go:280-320).
"""

from __future__ import annotations

import base64
import json
import os
import tempfile
from dataclasses import dataclass, field as dfield, replace

from cometbft_tpu.crypto import bn254, ed25519, secp256k1, sr25519
from cometbft_tpu.types.block import PRECOMMIT_TYPE, PREVOTE_TYPE, PROPOSAL_TYPE
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.priv_validator import PrivValidator
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.wire import proto as wire

STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_TYPE_TO_STEP = {
    PROPOSAL_TYPE: STEP_PROPOSE,
    PREVOTE_TYPE: STEP_PREVOTE,
    PRECOMMIT_TYPE: STEP_PRECOMMIT,
}


class DoubleSignError(Exception):
    pass


# Key-type registry (reference: privval/file.go GenFilePV takes a keyType
# string routed through privval.GenFilePV -> crypto keygen; the JSON names
# are the amino-era type tags each crypto package registers).
_KEY_MODULES = (ed25519, secp256k1, sr25519, bn254)
KEY_TYPES = tuple(m.KEY_TYPE for m in _KEY_MODULES)
_BY_KEY_TYPE = {m.KEY_TYPE: m for m in _KEY_MODULES}
_BY_PRIV_NAME = {m.PRIV_KEY_NAME: m for m in _KEY_MODULES}


@dataclass
class LastSignState:
    """privval/file.go:40-140 FilePVLastSignState."""

    height: int = 0
    round: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """file.go:76-94: False-with-error on regression; True when same HRS
        with an existing signature (caller may re-sign timestamp changes)."""
        if self.height > height:
            raise DoubleSignError(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}. Got {round_}, last round {self.round}"
                )
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at height {height} round {round_}. "
                        f"Got {step}, last step {self.step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no SignBytes found")
                    if not self.signature:
                        raise RuntimeError("pv: Signature is nil but SignBytes is not!")
                    return True
        return False

    def save(self) -> None:
        if not self.file_path:
            return
        data = json.dumps(
            {
                "height": str(self.height),
                "round": self.round,
                "step": self.step,
                "signature": base64.b64encode(self.signature).decode() if self.signature else None,
                "signbytes": self.sign_bytes.hex().upper() if self.sign_bytes else None,
            },
            indent=2,
        )
        _atomic_write(self.file_path, data)

    @classmethod
    def load(cls, path: str) -> "LastSignState":
        with open(path) as f:
            d = json.load(f)
        if not isinstance(d, dict):
            # Loud, typed failure: this file IS the double-sign guard —
            # callers must never be tempted to catch-and-regenerate.
            raise ValueError(f"corrupt last-sign state {path}: not an object")
        return cls(
            height=int(d.get("height", "0")),
            round=d.get("round", 0),
            step=d.get("step", 0),
            signature=base64.b64decode(d["signature"]) if d.get("signature") else b"",
            sign_bytes=bytes.fromhex(d["signbytes"]) if d.get("signbytes") else b"",
            file_path=path,
        )


class FilePV(PrivValidator):
    """privval/file.go:151-400."""

    def __init__(self, priv_key, key_file_path: str = "", state_file_path: str = ""):
        self.priv_key = priv_key
        self.key_file_path = key_file_path
        self.last_sign_state = LastSignState(file_path=state_file_path)

    # -- construction / persistence ------------------------------------------

    @classmethod
    def generate(
        cls,
        key_file_path: str = "",
        state_file_path: str = "",
        key_type: str = ed25519.KEY_TYPE,
    ) -> "FilePV":
        """privval/file.go GenFilePV: fresh key of the requested type."""
        mod = _BY_KEY_TYPE.get(key_type)
        if mod is None:
            raise ValueError(
                f"unsupported privval key type {key_type!r} (want one of {KEY_TYPES})"
            )
        return cls(mod.gen_priv_key(), key_file_path, state_file_path)

    @classmethod
    def load(cls, key_file_path: str, state_file_path: str) -> "FilePV":
        with open(key_file_path) as f:
            d = json.load(f)
        name = d["priv_key"].get("type", ed25519.PRIV_KEY_NAME)
        mod = _BY_PRIV_NAME.get(name)
        if mod is None:
            raise ValueError(f"unknown priv_key type {name!r} in {key_file_path}")
        priv_raw = base64.b64decode(d["priv_key"]["value"])
        pv = cls(mod.PrivKey(priv_raw), key_file_path, state_file_path)
        if os.path.exists(state_file_path):
            pv.last_sign_state = LastSignState.load(state_file_path)
            pv.last_sign_state.file_path = state_file_path
        return pv

    @classmethod
    def load_or_generate(
        cls,
        key_file_path: str,
        state_file_path: str,
        key_type: str = ed25519.KEY_TYPE,
    ) -> "FilePV":
        if os.path.exists(key_file_path):
            return cls.load(key_file_path, state_file_path)
        pv = cls.generate(key_file_path, state_file_path, key_type=key_type)
        pv.save()
        return pv

    def save(self) -> None:
        pub = self.priv_key.pub_key()
        mod = _BY_KEY_TYPE[self.priv_key.type()]
        data = json.dumps(
            {
                "address": pub.address().hex().upper(),
                "pub_key": {
                    "type": mod.PUB_KEY_NAME,
                    "value": base64.b64encode(pub.bytes()).decode(),
                },
                "priv_key": {
                    "type": mod.PRIV_KEY_NAME,
                    "value": base64.b64encode(self.priv_key.bytes()).decode(),
                },
            },
            indent=2,
        )
        if self.key_file_path:
            _atomic_write(self.key_file_path, data)
        self.last_sign_state.save()

    # -- PrivValidator interface ----------------------------------------------

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        """file.go:230-290 signVote: HRS check, same-HRS timestamp re-sign."""
        height, round_, step = vote.height, vote.round, _TYPE_TO_STEP[vote.type]
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                return replace(vote, signature=lss.signature)
            ts = _checked_vote_timestamp(lss.sign_bytes, sign_bytes)
            if ts is not None:
                # Only the timestamp differs: re-use the previous timestamp+sig.
                return replace(vote, timestamp=ts, signature=lss.signature)
            raise DoubleSignError("conflicting data")
        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        return replace(vote, signature=sig)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        """file.go:300-350 signProposal."""
        height, round_, step = proposal.height, proposal.round, STEP_PROPOSE
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                return replace(proposal, signature=lss.signature)
            ts = _checked_proposal_timestamp(lss.sign_bytes, sign_bytes)
            if ts is not None:
                return replace(proposal, timestamp=ts, signature=lss.signature)
            raise DoubleSignError("conflicting data")
        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        return replace(proposal, signature=sig)

    def _save_signed(self, height, round_, step, sign_bytes, sig) -> None:
        self.last_sign_state.height = height
        self.last_sign_state.round = round_
        self.last_sign_state.step = step
        self.last_sign_state.signature = sig
        self.last_sign_state.sign_bytes = sign_bytes
        self.last_sign_state.save()

    def address(self) -> bytes:
        return self.get_pub_key().address()


def _atomic_write(path: str, data: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        os.unlink(tmp)
        raise


def _strip_timestamp_field(sign_bytes: bytes, field_num: int):
    """Drop the canonical timestamp field from length-delimited sign bytes;
    returns (stripped, timestamp) — the equality basis for same-HRS re-signs
    (privval/file.go checkVotesOnlyDifferByTimestamp)."""
    body_len, pos = wire.decode_uvarint(sign_bytes, 0)
    body = sign_bytes[pos : pos + body_len]
    fields_out = b""
    ts = None
    p = 0
    while p < len(body):
        key, p2 = wire.decode_uvarint(body, p)
        fnum, wt = key >> 3, key & 7
        if wt == wire.WT_VARINT:
            _, p3 = wire.decode_uvarint(body, p2)
        elif wt == wire.WT_FIXED64:
            p3 = p2 + 8
        elif wt == wire.WT_LEN:
            ln, p2b = wire.decode_uvarint(body, p2)
            p3 = p2b + ln
        else:
            return None, None
        if fnum == field_num and wt == wire.WT_LEN:
            ln, p2b = wire.decode_uvarint(body, p2)
            ts = Time.decode(body[p2b : p2b + ln])
        else:
            fields_out += body[p:p3]
        p = p3
    return fields_out, ts


def _checked_vote_timestamp(last_sign_bytes: bytes, new_sign_bytes: bytes):
    """If the two canonical votes differ only in timestamp (field 5), return
    the LAST timestamp (to be reused); else None."""
    last_stripped, last_ts = _strip_timestamp_field(last_sign_bytes, 5)
    new_stripped, _ = _strip_timestamp_field(new_sign_bytes, 5)
    if last_stripped is None or new_stripped is None:
        return None
    return last_ts if last_stripped == new_stripped else None


def _checked_proposal_timestamp(last_sign_bytes: bytes, new_sign_bytes: bytes):
    """Same for canonical proposals (timestamp is field 6)."""
    last_stripped, last_ts = _strip_timestamp_field(last_sign_bytes, 6)
    new_stripped, _ = _strip_timestamp_field(new_sign_bytes, 6)
    if last_stripped is None or new_stripped is None:
        return None
    return last_ts if last_stripped == new_stripped else None

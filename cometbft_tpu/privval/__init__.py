"""Validator signing (reference: privval/, 1,770 LoC)."""

from cometbft_tpu.privval.file import FilePV, LastSignState
from cometbft_tpu.privval.signer import (
    RemoteSignerError,
    RetrySignerClient,
    SignerClient,
    SignerListenerEndpoint,
    SignerServer,
)

__all__ = [
    "FilePV",
    "LastSignState",
    "RemoteSignerError",
    "RetrySignerClient",
    "SignerClient",
    "SignerListenerEndpoint",
    "SignerServer",
]

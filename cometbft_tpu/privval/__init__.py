"""Validator signing (reference: privval/, 1,770 LoC)."""

from cometbft_tpu.privval.file import FilePV, LastSignState

__all__ = ["FilePV", "LastSignState"]

"""Remote signer: validator key isolation in a separate process
(reference: privval/signer_client.go:133, signer_listener_endpoint.go:223,
signer_dialer_endpoint.go, signer_server.go, retry_signer_client.go:96).

Topology matches the reference: the NODE listens on
config.base.priv_validator_laddr (SignerListenerEndpoint); the SIGNER
process dials in (SignerDialerEndpoint) and then serves PubKey/SignVote/
SignProposal requests over that single long-lived connection. The signer
owns the key AND the last-sign-state, so the double-sign guard survives
node crashes and signer restarts alike.

Wire: varint-length-delimited privval Message oneof
(proto/tendermint/privval/types.proto:65) over unix/TCP.
"""

from __future__ import annotations

import socket
import threading
import time

from cometbft_tpu.types.priv_validator import PrivValidator
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.wire import proto as wire

# privval Message oneof field numbers (types.proto:65-76).
PUB_KEY_REQUEST = 1
PUB_KEY_RESPONSE = 2
SIGN_VOTE_REQUEST = 3
SIGNED_VOTE_RESPONSE = 4
SIGN_PROPOSAL_REQUEST = 5
SIGNED_PROPOSAL_RESPONSE = 6
PING_REQUEST = 7
PING_RESPONSE = 8


class RemoteSignerError(Exception):
    def __init__(self, code: int, description: str):
        super().__init__(description)
        self.code = code
        self.description = description


def _enc_signer_error(e: RemoteSignerError | None) -> bytes | None:
    if e is None:
        return None
    return wire.field_varint(1, e.code) + wire.field_string(2, e.description)


def _dec_signer_error(data: bytes) -> RemoteSignerError | None:
    if not data:
        return None
    f = wire.decode_fields(data)
    return RemoteSignerError(wire.get_varint(f, 1), wire.get_string(f, 2))


def _frame(num: int, body: bytes) -> bytes:
    msg = wire.field_message(num, body, emit_empty=True)
    return wire.encode_uvarint(len(msg)) + msg


def _read_frame(rf) -> tuple[int, bytes] | None:
    shift = 0
    length = 0
    while True:
        b = rf.read(1)
        if not b:
            return None
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 35:
            raise ValueError("privval frame length overflow")
    if length > 1 << 20:
        raise ValueError("privval message too large")
    data = b""
    while len(data) < length:
        chunk = rf.read(length - len(data))
        if not chunk:
            raise EOFError("short privval frame")
        data += chunk
    f = wire.decode_fields(data)
    for num in range(1, 9):
        if num in f:
            return num, wire.get_bytes(f, num)
    raise ValueError("empty privval message")


def _enc_pub_key(pub) -> bytes:
    from cometbft_tpu.abci.wire import _enc_pub_key as enc

    return enc(pub)


def _dec_pub_key(data: bytes):
    from cometbft_tpu.abci.wire import _dec_pub_key as dec

    return dec(data)


def _shutdown_close(conn: socket.socket) -> None:
    try:
        conn.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        conn.close()
    except OSError:
        pass


# -- node side ----------------------------------------------------------------


class SignerListenerEndpoint:
    """privval/signer_listener_endpoint.go: the node's accept side. Holds at
    most one live signer connection; requests block until one is present (or
    the accept deadline passes)."""

    def __init__(self, laddr: str, accept_timeout: float = 30.0):
        from cometbft_tpu.abci.server import parse_addr

        self.laddr = laddr
        self.accept_timeout = accept_timeout
        scheme, target = parse_addr(laddr)
        if scheme == "unix":
            import os

            if os.path.exists(target):
                os.unlink(target)
            ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            ls.bind(target)
            self.bound = laddr
        else:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind(target)
            self.bound = f"tcp://{target[0]}:{ls.getsockname()[1]}"
        ls.listen(1)
        self._listener = ls
        self._conn: socket.socket | None = None
        self._rf = None
        self._wf = None
        self._mtx = threading.Lock()
        self._have_conn = threading.Condition(self._mtx)
        self._running = True
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                if not self._running:
                    return
                # Transient accept failure (ECONNABORTED from a signer that
                # hung up while queued, EMFILE pressure): the endpoint must
                # keep accepting or the validator misses votes until a
                # process restart.
                time.sleep(0.05)
                continue
            # Bound reads on the signer connection: request() holds the
            # endpoint mutex across write+read, and an untimed read on a
            # half-open connection (peer power loss, partition without RST)
            # would hold it forever — blocking this accept loop from ever
            # installing a reconnecting signer.
            conn.settimeout(10.0)
            with self._mtx:
                if not self._running:
                    # A thread parked in accept() keeps the kernel listener
                    # alive past listener.close(), so a redialing signer can
                    # still connect and land HERE after close() — installing
                    # it would strand the signer on a dead endpoint.
                    _shutdown_close(conn)
                    return
                self._drop_conn_locked()
                self._conn = conn
                self._rf = conn.makefile("rb")
                self._wf = conn.makefile("wb")
                self._have_conn.notify_all()

    def close(self) -> None:
        self._running = False
        # shutdown() (inside the helper) wakes a thread parked in accept()
        # (close() alone does not on Linux), so the accept loop exits and
        # the kernel listener actually dies — otherwise a tcp:// endpoint
        # would keep its port bound forever and a same-port re-create would
        # fail with EADDRINUSE.
        _shutdown_close(self._listener)
        with self._mtx:
            self._drop_conn_locked()
            self._have_conn.notify_all()  # wake request() waiters to fail fast

    def _drop_conn_locked(self) -> None:
        if self._conn is not None:
            # shutdown() before close(): the makefile() reader/writer keep
            # the fd alive past close(), so no FIN would reach the signer
            # and it would never notice the endpoint is gone.
            _shutdown_close(self._conn)
        self._conn = None
        self._rf = self._wf = None

    def request(self, num: int, body: bytes) -> tuple[int, bytes]:
        """One request/response exchange; waits for a signer connection."""
        with self._mtx:
            deadline = time.monotonic() + self.accept_timeout
            while self._conn is None:
                if not self._running:
                    raise ConnectionError("signer endpoint closed")
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("no signer connected")
                self._have_conn.wait(left)
            try:
                self._wf.write(_frame(num, body))
                self._wf.flush()
                out = _read_frame(self._rf)
            except (OSError, EOFError, ValueError) as e:
                self._drop_conn_locked()
                raise ConnectionError(f"signer connection failed: {e}") from e
            if out is None:
                self._drop_conn_locked()
                raise ConnectionError("signer closed the connection")
            return out


class SignerClient(PrivValidator):
    """privval/signer_client.go: PrivValidator over a listener endpoint."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str = ""):
        self.endpoint = endpoint
        self.chain_id = chain_id

    def ping(self) -> bool:
        num, _ = self.endpoint.request(PING_REQUEST, b"")
        return num == PING_RESPONSE

    def get_pub_key(self):
        num, body = self.endpoint.request(
            PUB_KEY_REQUEST, wire.field_string(1, self.chain_id)
        )
        if num != PUB_KEY_RESPONSE:
            raise RemoteSignerError(0, f"unexpected response {num}")
        f = wire.decode_fields(body)
        err = _dec_signer_error(wire.get_bytes(f, 2))
        if err is not None:
            raise err
        return _dec_pub_key(wire.get_bytes(f, 1))

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        body = wire.field_message(1, vote.encode(), emit_empty=True)
        body += wire.field_string(2, chain_id)
        num, out = self.endpoint.request(SIGN_VOTE_REQUEST, body)
        if num != SIGNED_VOTE_RESPONSE:
            raise RemoteSignerError(0, f"unexpected response {num}")
        f = wire.decode_fields(out)
        err = _dec_signer_error(wire.get_bytes(f, 2))
        if err is not None:
            raise err
        return Vote.decode(wire.get_bytes(f, 1))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        body = wire.field_message(1, proposal.encode(), emit_empty=True)
        body += wire.field_string(2, chain_id)
        num, out = self.endpoint.request(SIGN_PROPOSAL_REQUEST, body)
        if num != SIGNED_PROPOSAL_RESPONSE:
            raise RemoteSignerError(0, f"unexpected response {num}")
        f = wire.decode_fields(out)
        err = _dec_signer_error(wire.get_bytes(f, 2))
        if err is not None:
            raise err
        return Proposal.decode(wire.get_bytes(f, 1))

    def address(self) -> bytes:
        return self.get_pub_key().address()


class RetrySignerClient(PrivValidator):
    """privval/retry_signer_client.go: bounded retries over transient
    endpoint failures (signer restarting, connection mid-flap). Signing
    errors from the signer itself (double-sign guard!) are NOT retried."""

    def __init__(self, client: SignerClient, retries: int = 5, timeout: float = 1.0):
        self.client = client
        self.retries = retries
        self.timeout = timeout

    def _retry(self, fn):
        last = None
        for _ in range(self.retries):
            try:
                return fn()
            except RemoteSignerError:
                raise  # the signer answered: a real refusal, not a flake
            except Exception as e:
                last = e
                time.sleep(self.timeout)
        raise last

    def get_pub_key(self):
        return self._retry(self.client.get_pub_key)

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        return self._retry(lambda: self.client.sign_vote(chain_id, vote))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        return self._retry(lambda: self.client.sign_proposal(chain_id, proposal))

    def address(self) -> bytes:
        return self._retry(self.client.address)


# -- signer side ---------------------------------------------------------------


class SignerServer:
    """privval/signer_server.go + signer_dialer_endpoint.go: dial the node,
    serve signing requests with the wrapped FilePV. Reconnects with backoff
    until stopped."""

    def __init__(self, node_addr: str, chain_id: str, privval):
        self.node_addr = node_addr
        self.chain_id = chain_id
        self.privval = privval
        self._running = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False

    def _run(self) -> None:
        from cometbft_tpu.abci.server import parse_addr

        scheme, target = parse_addr(self.node_addr)
        backoff = 0.1
        while self._running:
            try:
                if scheme == "unix":
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                else:
                    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.connect(target)
                backoff = 0.1
                self._serve(s)
            except OSError:
                time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    def _serve(self, s: socket.socket) -> None:
        rf = s.makefile("rb")
        wf = s.makefile("wb")
        try:
            while self._running:
                out = _read_frame(rf)
                if out is None:
                    return
                num, body = out
                wf.write(self._handle(num, body))
                wf.flush()
        except (OSError, EOFError, ValueError):
            pass
        finally:
            try:
                s.close()
            except OSError:
                pass

    def _handle(self, num: int, body: bytes) -> bytes:
        if num == PING_REQUEST:
            return _frame(PING_RESPONSE, b"")
        if num == PUB_KEY_REQUEST:
            resp = wire.field_message(
                1, _enc_pub_key(self.privval.get_pub_key()), emit_empty=True
            )
            return _frame(PUB_KEY_RESPONSE, resp)
        if num == SIGN_VOTE_REQUEST:
            f = wire.decode_fields(body)
            chain_id = wire.get_string(f, 2)
            try:
                vote = Vote.decode(wire.get_bytes(f, 1))
                signed = self.privval.sign_vote(chain_id, vote)
                resp = wire.field_message(1, signed.encode(), emit_empty=True)
            except Exception as e:
                resp = wire.field_message(
                    2, _enc_signer_error(RemoteSignerError(2, str(e))), emit_empty=True
                )
            return _frame(SIGNED_VOTE_RESPONSE, resp)
        if num == SIGN_PROPOSAL_REQUEST:
            f = wire.decode_fields(body)
            chain_id = wire.get_string(f, 2)
            try:
                proposal = Proposal.decode(wire.get_bytes(f, 1))
                signed = self.privval.sign_proposal(chain_id, proposal)
                resp = wire.field_message(1, signed.encode(), emit_empty=True)
            except Exception as e:
                resp = wire.field_message(
                    2, _enc_signer_error(RemoteSignerError(2, str(e))), emit_empty=True
                )
            return _frame(SIGNED_PROPOSAL_RESPONSE, resp)
        return _frame(
            PUB_KEY_RESPONSE,
            wire.field_message(
                2,
                _enc_signer_error(RemoteSignerError(1, f"unexpected request {num}")),
                emit_empty=True,
            ),
        )


def main(argv=None) -> int:
    """`python -m cometbft_tpu.privval.signer`: the external signer daemon."""
    import argparse

    from cometbft_tpu.privval.file import FilePV

    p = argparse.ArgumentParser(prog="cometbft_tpu.privval.signer")
    p.add_argument("--addr", required=True, help="node's priv_validator_laddr to dial")
    p.add_argument("--chain-id", required=True)
    p.add_argument("--key-file", required=True)
    p.add_argument("--state-file", required=True)
    args = p.parse_args(argv)
    pv = FilePV.load_or_generate(args.key_file, args.state_file)
    srv = SignerServer(args.addr, args.chain_id, pv)
    srv.start()
    print(f"remote signer serving {args.addr}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""`python -m cometbft_tpu.sidecar` — run the verification sidecar server."""

from cometbft_tpu.sidecar.service import main

main()

"""Deterministic fault injection for verification backends.

`ChaosBackend` wraps any `VerifyBackend` tier and injects failures drawn
from a seeded RNG, so tests and the e2e harness can *prove* the
supervisor's behavior (deadlines fire, breakers trip, the degradation
chain serves a correct result) instead of hoping a real relay wedges on
cue.  The fault classes mirror what the axon tunnel actually does to this
host (CLAUDE.md: wedges under concurrent clients, slow compiles that are
really a dead relay) plus the one failure a resilience layer must never
pass through silently: a device computing garbage *accepts*.

Env spec (`CMTPU_FAULTS`), comma-separated, each `kind:probability[:ms]`:

    latency:p:ms   with probability p, sleep ms before the call
    error:p        with probability p, raise ConnectionError
    wedge:p[:ms]   with probability p, hang for ms (default 300000 —
                   "forever" at deadline scale) before answering
    flip:p         with probability p, corrupt batch_verify's result into
                   a false-accept (ok=True, all-True bitmap) — the
                   bit-flip a cpu cross-check must catch

Determinism contract: the same (spec, seed) wrapping the same call
sequence injects the same faults — `random.Random(seed)` drives every
draw, no clocks involved — so a failing chaos run reproduces from its
seed exactly like a generator manifest does.
"""

from __future__ import annotations

import os
import random
import threading
import time

from cometbft_tpu.sidecar.backend import VerifyBackend

# "Forever" at per-call-deadline scale, but bounded so a wedged test
# process still unwinds.
_DEFAULT_WEDGE_MS = 300_000.0

_KINDS = ("latency", "error", "wedge", "flip")


class FaultSpecError(ValueError):
    pass


def parse_faults(spec: str) -> dict[str, tuple[float, float]]:
    """`latency:p:ms,error:p,...` -> {kind: (probability, ms)}.

    ms is meaningful for latency/wedge only; error/flip reject a third
    field loudly (a silently ignored knob reads as coverage that isn't).
    """
    faults: dict[str, tuple[float, float]] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        fields = part.split(":")
        kind = fields[0]
        if kind not in _KINDS:
            raise FaultSpecError(f"unknown fault kind {kind!r} (want {_KINDS})")
        try:
            prob = float(fields[1])
        except (IndexError, ValueError):
            raise FaultSpecError(f"fault {part!r}: want {kind}:probability") from None
        if not 0.0 <= prob <= 1.0:
            raise FaultSpecError(f"fault {part!r}: probability outside [0, 1]")
        ms = None
        if len(fields) >= 3:
            if kind not in ("latency", "wedge"):
                raise FaultSpecError(f"fault {part!r}: {kind} takes no duration")
            ms = float(fields[2])
        if len(fields) > 3:
            raise FaultSpecError(f"fault {part!r}: too many fields")
        if kind == "latency" and ms is None:
            raise FaultSpecError(f"fault {part!r}: latency needs latency:p:ms")
        if kind == "wedge" and ms is None:
            ms = _DEFAULT_WEDGE_MS
        faults[kind] = (prob, ms if ms is not None else 0.0)
    return faults


def faults_from_env() -> dict[str, tuple[float, float]] | None:
    spec = os.environ.get("CMTPU_FAULTS", "").strip()
    return parse_faults(spec) if spec else None


class ChaosBackend(VerifyBackend):
    """A `VerifyBackend` (or sidecar client) with seeded fault injection.

    Transparent when healthy: delegates `batch_verify`/`merkle_root` (and
    `ping`, when the inner tier has one — so half-open probes see the same
    weather as real calls).  The draw order is fixed per call —
    latency, error, wedge, then flip on the result — so a spec's faults
    compose deterministically under one seed.
    """

    def __init__(self, inner: VerifyBackend, spec: str | dict, seed: int = 0):
        self.inner = inner
        self.name = f"chaos({inner.name})"
        self.faults = parse_faults(spec) if isinstance(spec, str) else dict(spec)
        self.seed = seed
        self._rng = random.Random(seed)
        # One draw stream shared by every calling thread: the lock keeps
        # the stream itself deterministic; cross-thread interleaving is
        # the caller's to pin (single-threaded tests, or per-tier workers).
        self._rng_lock = threading.Lock()
        self.injected: dict[str, int] = {k: 0 for k in _KINDS}

    def _draw(self, kind: str) -> tuple[bool, float]:
        prob, ms = self.faults.get(kind, (0.0, 0.0))
        if prob <= 0.0:
            return False, ms
        with self._rng_lock:
            hit = self._rng.random() < prob
        if hit:
            self.injected[kind] += 1
        return hit, ms

    def _pre_call(self) -> None:
        hit, ms = self._draw("latency")
        if hit:
            time.sleep(ms / 1000.0)
        hit, _ = self._draw("error")
        if hit:
            raise ConnectionError(f"chaos: injected error ({self.name})")
        hit, ms = self._draw("wedge")
        if hit:
            time.sleep(ms / 1000.0)

    def batch_verify(self, pubs, msgs, sigs):
        self._pre_call()
        ok, bits = self.inner.batch_verify(pubs, msgs, sigs)
        hit, _ = self._draw("flip")
        if hit:
            # The dangerous corruption: a FALSE-ACCEPT. A degraded device
            # reporting all-valid for a batch that isn't must be caught by
            # the supervisor's cpu cross-check, never served.
            return True, [True] * len(pubs)
        return ok, bits

    def aggregate_verify(self, pubs, msgs, agg_sig):
        self._pre_call()
        ok = self.inner.aggregate_verify(pubs, msgs, agg_sig)
        hit, _ = self._draw("flip")
        if hit:
            # An aggregate verdict is ONE boolean, so the false-accept
            # corruption is a plain inversion-to-True; the supervisor's
            # anchor recompute must catch it (there is no per-lane sample
            # granularity to catch it cheaper).
            return True
        return ok

    def merkle_root(self, leaves):
        self._pre_call()
        return self.inner.merkle_root(leaves)

    def ping(self):
        self._pre_call()
        inner_ping = getattr(self.inner, "ping", None)
        return inner_ping() if inner_ping is not None else True

    def mesh_width(self) -> int:
        # Shape, not weather: the supervisor's cap sizing must see the
        # wrapped tier's real width (a chaos-wrapped fanout fleet still
        # has the fleet's chips), so no fault draw here.
        mw = getattr(self.inner, "mesh_width", None)
        return int(mw()) if mw is not None else 1

    def counters(self) -> dict:
        inner_counters = getattr(self.inner, "counters", None)
        out = dict(inner_counters()) if inner_counters is not None else {}
        out["chaos_injected"] = dict(self.injected)
        return out

    def close(self):
        inner_close = getattr(self.inner, "close", None)
        if inner_close is not None:
            inner_close()

"""Verification sidecar: one long-lived process owns the TPU and serves
batch verification + Merkle hashing to any number of node processes.

This is the §7 design stance ("JAX/Pallas behind a gRPC verification
sidecar", SURVEY.md). The transport is deliberately NOT grpcio (although
grpcio is importable in this image and abci/grpc.py uses it for ABCI
parity): the sidecar sits on the consensus hot path, and the hand-framed
protocol keeps per-call overhead to one length-prefixed write + read with
zero HTTP/2 machinery. It is the same shape as the reference's ABCI socket
protocol
(abci/client/socket_client.go:529 — length-prefixed protobuf over TCP/unix,
pipelined requests) carrying gRPC-style unary methods:

    BatchVerify(pubs, msgs, sigs) -> (ok, bitmap)   crypto.BatchVerifier
    MerkleRoot(leaves)            -> root           crypto/merkle/tree.go:11
    Ping()                        -> pong           health check
    Warmup(buckets)               -> ok             precompile batch buckets

Wire format: every frame is a 4-byte big-endian length + protobuf body.
  Request  { 1: id (uvarint), 2: method (string), 3: payload (bytes) }
  Response { 1: id (uvarint), 2: ok (bool), 3: error (string), 4: payload }
  BatchVerifyReq  { 1..3: repeated pubs/msgs/sigs (bytes) }
  BatchVerifyResp { 1: all_ok (bool), 2: bitmap (bytes, 1 byte per sig) }
  MerkleReq       { 1: repeated leaves (bytes) }
  MerkleResp      { 1: root (bytes) }
  WarmupReq       { 1: repeated buckets (uvarint) }

Running the device behind one process also serializes TPU access — exactly
the property this host needs (the axon tunnel wedges under concurrent
clients; see tpu_watch.sh / memory notes).
"""

from __future__ import annotations

import os
import random
import socket
import socketserver
import struct
import threading
import time

from cometbft_tpu.sidecar.backend import TpuBackend, VerifyBackend, device_backend
from cometbft_tpu.wire import proto

DEFAULT_ADDR = "127.0.0.1:26670"
DEFAULT_BUCKETS = (128, 1024, 10240)
_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30


# -- framing ------------------------------------------------------------------


def write_frame(sock: socket.socket, body: bytes) -> None:
    sock.sendall(_LEN.pack(len(body)) + body)


def read_frame(sock: socket.socket) -> bytes | None:
    hdr = _read_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return _read_exact(sock, n)


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _encode_request(req_id: int, method: str, payload: bytes) -> bytes:
    return (
        proto.field_varint(1, req_id, emit_default=True)
        + proto.field_string(2, method)
        + proto.field_bytes(3, payload)
    )


def _encode_response(req_id: int, ok: bool, error: str, payload: bytes) -> bytes:
    return (
        proto.field_varint(1, req_id, emit_default=True)
        + proto.field_bool(2, ok)
        + proto.field_string(3, error)
        + proto.field_bytes(4, payload)
    )


# -- server -------------------------------------------------------------------


class SidecarServer:
    """The long-lived device owner. Device calls are serialized with a lock
    (one TPU, one XLA stream); socket handling is one thread per connection,
    so hosts can pipeline requests like the reference's socket ABCI client."""

    def __init__(self, addr: str = DEFAULT_ADDR, backend: VerifyBackend | None = None):
        self.addr = addr
        self.backend = backend if backend is not None else device_backend(
            os.environ.get("CMTPU_SIDECAR_DEVICE", "auto").lower()
        )
        self._device_lock = threading.Lock()
        host, port = addr.rsplit(":", 1)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                while True:
                    try:
                        body = read_frame(sock)
                    except (OSError, ValueError):
                        return
                    if body is None:
                        return
                    req_id = 0
                    try:  # fault isolation per request, incl. malformed bodies
                        fields = proto.decode_fields(body)
                        req_id = proto.get_uvarint(fields, 1)
                        method = proto.get_string(fields, 2)
                        payload = proto.get_bytes(fields, 3)
                        out = outer._dispatch(method, payload)
                        resp = _encode_response(req_id, True, "", out)
                    except Exception as e:
                        resp = _encode_response(req_id, False, f"{type(e).__name__}: {e}", b"")
                    try:
                        write_frame(sock, resp)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, int(port)), Handler)

    def _dispatch(self, method: str, payload: bytes) -> bytes:
        if method == "Ping":
            # Capability reply: PingResp { 1: "pong", 2: mesh_width }.
            # The width is the REMOTE pod's chip count, so client-side
            # sizing (the coalescer's default merge cap, chain pricing)
            # sees the serving mesh, not the local host's. Legacy clients
            # that compared the raw body to b"pong" must upgrade with the
            # server; new clients still accept a bare b"pong" from an old
            # server (width defaults to 1).
            width = 1
            mw = getattr(self.backend, "mesh_width", None)
            if mw is not None:
                try:
                    width = max(1, int(mw()))
                except Exception:
                    width = 1
            return proto.field_bytes(1, b"pong") + proto.field_varint(2, width)
        if method == "BatchVerify":
            fields = proto.decode_fields(payload)
            pubs = proto.get_repeated_bytes(fields, 1)
            msgs = proto.get_repeated_bytes(fields, 2)
            sigs = proto.get_repeated_bytes(fields, 3)
            if not (len(pubs) == len(msgs) == len(sigs)):
                raise ValueError("pubs/msgs/sigs length mismatch")
            with self._device_lock:
                ok, bitmap = self.backend.batch_verify(pubs, msgs, sigs)
            return proto.field_bool(1, ok) + proto.field_bytes(
                2, bytes(1 if b else 0 for b in bitmap)
            )
        if method == "MerkleRoot":
            fields = proto.decode_fields(payload)
            leaves = proto.get_repeated_bytes(fields, 1)
            with self._device_lock:
                root = self.backend.merkle_root(leaves)
            return proto.field_bytes(1, root)
        if method == "Warmup":
            fields = proto.decode_fields(payload)
            buckets = tuple(proto.get_repeated_uvarint(fields, 1)) or DEFAULT_BUCKETS
            self.warmup(buckets)
            return b""
        raise ValueError(f"unknown method {method!r}")

    def warmup(self, buckets=DEFAULT_BUCKETS) -> None:
        """Precompile the batch-verify buckets so the first real commit does
        not pay an XLA compile (SURVEY §7 hard part 3, <2 ms budget)."""
        if isinstance(self.backend, TpuBackend):
            from cometbft_tpu.ops import ed25519_kernel

            with self._device_lock:
                ed25519_kernel.warmup(buckets)

    def serve_forever(self):
        self._server.serve_forever()

    def start(self) -> "SidecarServer":
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return self

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


# -- client -------------------------------------------------------------------


class GrpcBackend(VerifyBackend):
    """The `CMTPU_BACKEND=grpc` client: speaks the framed protocol above.
    Thread-safe (one in-flight request per connection, guarded by a lock);
    reconnects once on a broken connection. Fails loudly when the sidecar is
    unreachable — an explicitly configured remote verifier must not silently
    fall back to a different trust path."""

    name = "grpc"

    # Redial backoff bounds: first failure waits _REDIAL_BASE_S, doubling
    # (with jitter inside the doubling) to the _REDIAL_MAX_S cap.
    _REDIAL_BASE_S = 0.05
    _REDIAL_MAX_S = 5.0

    def __init__(
        self,
        addr: str = DEFAULT_ADDR,
        timeout_s: float = 300.0,
        connect_timeout_s: float = 5.0,
    ):
        # timeout_s is the per-REQUEST deadline (slot wait below);
        # connect_timeout_s bounds dial time only. One 300 s knob doing
        # both meant a dead relay cost five minutes per connect attempt.
        self.addr = addr
        self.timeout_s = timeout_s
        self.connect_timeout_s = connect_timeout_s
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()  # serializes frame WRITES only
        self._plock = threading.Lock()  # connection + pending table
        # id -> [Event, body | None, owning socket]: the socket tag lets a
        # dead connection's reader sweep fail ONLY its own waiters.
        self._pending: dict[int, list] = {}
        self._next_id = 0
        # Capped redial-with-backoff (under _plock): a client object used
        # to die for good once the sidecar went away; now each failed dial
        # opens a backoff window in which calls fail FAST, and the next
        # call after the window redials.
        self._redial_failures = 0
        self._redial_not_before = 0.0
        # Remote pod width from the Ping capability reply (1 until probed).
        self._remote_mesh_width = 1

    def _connect_locked(self) -> None:
        now = time.monotonic()
        if self._redial_failures and now < self._redial_not_before:
            raise ConnectionError(
                f"sidecar {self.addr} in redial backoff "
                f"({self._redial_failures} consecutive dial failures)"
            )
        host, port = self.addr.rsplit(":", 1)
        try:
            s = socket.create_connection(
                (host, int(port)), timeout=self.connect_timeout_s
            )
        except OSError as e:
            self._redial_failures += 1
            base = min(
                self._REDIAL_BASE_S * 2 ** (self._redial_failures - 1),
                self._REDIAL_MAX_S,
            )
            self._redial_not_before = now + base * random.uniform(0.5, 1.0)
            raise ConnectionError(f"sidecar dial {self.addr}: {e}") from e
        self._redial_failures = 0
        # Blocking mode from here: request deadlines are enforced by the
        # waiter's Event (timeout_s), and a lingering socket timeout would
        # make the reader thread kill an idle-but-healthy connection.
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        threading.Thread(
            target=self._reader_loop, args=(s,), daemon=True, name="sidecar-reader"
        ).start()

    def _reader_loop(self, sock: socket.socket) -> None:
        """Demultiplexes responses by request id so callers can PIPELINE:
        many requests may be in flight on the one connection (the server's
        handler advertises pipelining; the old client serialized write+read
        under a single lock — VERDICT r3 weak #8)."""
        while True:
            try:
                body = read_frame(sock)
            except OSError:
                body = None
            if body is None:
                break
            fields = proto.decode_fields(body)
            req_id = proto.get_uvarint(fields, 1)
            with self._plock:
                slot = self._pending.pop(req_id, None)
            if slot is not None:
                slot[1] = body
                slot[0].set()
        # Connection died: fail the waiters that belong to THIS socket so
        # they can retry. A delayed cleanup must not sweep requests already
        # registered on a replacement connection (that race turned one
        # reconnect into a spurious second failure).
        with self._plock:
            if self._sock is sock:
                self._sock = None
            dead = {k: v for k, v in self._pending.items() if v[2] is sock}
            for k in dead:
                del self._pending[k]
        for slot in dead.values():
            slot[0].set()

    def _call_once(self, method: str, payload: bytes) -> bytes:
        slot = [threading.Event(), None, None]
        with self._plock:
            if self._sock is None:
                self._connect_locked()
            self._next_id += 1
            req_id = self._next_id
            sock = self._sock
            slot[2] = sock
            self._pending[req_id] = slot
        req = _encode_request(req_id, method, payload)
        try:
            with self._wlock:
                write_frame(sock, req)
        except OSError as e:
            with self._plock:
                self._pending.pop(req_id, None)
            err = ConnectionError(str(e))
            err.sock = sock  # which connection failed (see _call)
            raise err from e
        if not slot[0].wait(self.timeout_s):
            with self._plock:
                self._pending.pop(req_id, None)
            raise TimeoutError(f"sidecar {method} timed out")
        if slot[1] is None:
            err = ConnectionError("sidecar connection lost mid-request")
            err.sock = sock
            raise err
        return slot[1]

    def _call(self, method: str, payload: bytes) -> bytes:
        for attempt in (0, 1):
            try:
                body = self._call_once(method, payload)
                break
            except ConnectionError as e:
                # Tear down only the connection that actually failed: a
                # thread handling a stale failure must not close the
                # replacement another thread just established.
                failed = getattr(e, "sock", None)
                with self._plock:
                    if self._sock is not None and (
                        failed is None or self._sock is failed
                    ):
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                if attempt:
                    raise
        fields = proto.decode_fields(body)
        if not proto.get_bool(fields, 2):
            raise RuntimeError(f"sidecar error: {proto.get_string(fields, 3)}")
        return proto.get_bytes(fields, 4)

    def ping(self) -> bool:
        body = self._call("Ping", b"")
        if body == b"pong":  # pre-capability server
            return True
        try:
            fields = proto.decode_fields(body)
            if proto.get_bytes(fields, 1) != b"pong":
                return False
            width = proto.get_uvarint(fields, 2)
            if width:
                self._remote_mesh_width = int(width)
            return True
        except Exception:
            return False

    def mesh_width(self) -> int:
        """The serving pod's chip count, learned from the Ping capability
        reply. Never dials: an unpinged client reports 1 and the caller's
        periodic refresh picks the real width up after the first probe."""
        return self._remote_mesh_width

    def batch_verify(self, pubs, msgs, sigs):
        payload = b"".join(
            proto.field_bytes(1, p, emit_default=True) for p in pubs
        ) + b"".join(
            proto.field_bytes(2, m, emit_default=True) for m in msgs
        ) + b"".join(
            proto.field_bytes(3, s, emit_default=True) for s in sigs
        )
        out = self._call("BatchVerify", payload)
        fields = proto.decode_fields(out)
        bitmap = proto.get_bytes(fields, 2)
        return proto.get_bool(fields, 1), [bool(b) for b in bitmap[: len(pubs)]]

    def merkle_root(self, leaves):
        payload = b"".join(
            proto.field_bytes(1, leaf, emit_default=True) for leaf in leaves
        )
        out = self._call("MerkleRoot", payload)
        return proto.get_bytes(proto.decode_fields(out), 1)

    def warmup(self, buckets=DEFAULT_BUCKETS) -> None:
        self._call(
            "Warmup",
            b"".join(proto.field_varint(1, b, emit_default=True) for b in buckets),
        )

    def close(self) -> None:
        with self._plock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


def main() -> None:
    """`python -m cometbft_tpu.sidecar`: serve until killed."""
    addr = os.environ.get("CMTPU_SIDECAR_ADDR", DEFAULT_ADDR)
    server = SidecarServer(addr)
    print(f"sidecar: serving on {addr} (backend={server.backend.name})", flush=True)
    if os.environ.get("CMTPU_SIDECAR_WARM", "1") == "1":
        server.warmup()
        print("sidecar: warmup complete", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()

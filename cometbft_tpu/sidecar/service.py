"""Verification sidecar: one long-lived process owns the TPU and serves
batch verification + Merkle hashing to any number of node processes.

This is the §7 design stance ("JAX/Pallas behind a gRPC verification
sidecar", SURVEY.md). The transport is deliberately NOT grpcio (although
grpcio is importable in this image and abci/grpc.py uses it for ABCI
parity): the sidecar sits on the consensus hot path, and the hand-framed
protocol keeps per-call overhead to one length-prefixed write + read with
zero HTTP/2 machinery. It is the same shape as the reference's ABCI socket
protocol
(abci/client/socket_client.go:529 — length-prefixed protobuf over TCP/unix,
pipelined requests) carrying gRPC-style unary methods:

    BatchVerify(pubs, msgs, sigs) -> (ok, bitmap)   crypto.BatchVerifier
    MerkleRoot(leaves)            -> root           crypto/merkle/tree.go:11
    Ping()                        -> pong           health + capability probe
    Warmup(buckets)               -> ok             precompile batch buckets
    BatchVerifyChunk(...)         -> ack | bitmap   streamed BatchVerify

Wire format: every frame is a 4-byte big-endian length + protobuf body.
  Request  { 1: id (uvarint), 2: method (string), 3: payload (bytes) }
  Response { 1: id (uvarint), 2: ok (bool), 3: error (string), 4: payload }
  BatchVerifyReq  { 1..3: repeated pubs/msgs/sigs (bytes) }
  BatchVerifyResp { 1: all_ok (bool), 2: bitmap (bytes, 1 byte per sig) }
  MerkleReq       { 1: repeated leaves (bytes) }
  MerkleResp      { 1: root (bytes) }
  WarmupReq       { 1: repeated buckets (uvarint) }
  PingResp        { 1: "pong", 2: mesh_width, 3: streaming, 4: chunk }
  ChunkReq        { 1: stream_id, 2: seq, 3: final (bool),
                    4..6: repeated pubs/msgs/sigs (bytes) }

Streaming (round 10): a large BatchVerify splits into mesh-width-aligned
chunks, each sent as an ordinary framed request (its own id, so the
pipelined reader/pending-table/deadline machinery is unchanged). The
server submits every chunk to its scheduler as it arrives and acks chunk
k only after chunk k-1's dispatch resolved — a double buffer that
overlaps wire receive + host pack of chunk k+1 with device dispatch of
chunk k, one in-flight dispatch per connection. The FINAL chunk's
response carries the whole stream's BatchVerifyResp; any chunk error
fails the stream with an error response (never a partial bitmap).
Capability-gated: servers advertise streaming in the Ping reply (field
3) and clients fall back to unary against old servers; old unary clients
see a protocol identical to round 9's.

Running the device behind one process also serializes TPU access — exactly
the property this host needs (the axon tunnel wedges under concurrent
clients; see tpu_watch.sh / memory notes). Concurrent CONNECTIONS now
coalesce: the server routes verifications through a CoalescingScheduler
over the device lock, so many node processes sharing one tunnel merge
into single columnar dispatches with per-request bitmap slicing.
"""

from __future__ import annotations

import os
import random
import socket
import socketserver
import struct
import threading
import time

from cometbft_tpu.sidecar.backend import (
    LockedBackend,
    TpuBackend,
    VerifyBackend,
    device_backend,
)
from cometbft_tpu.sidecar.scheduler import CoalescingScheduler, VerifyFuture
from cometbft_tpu.wire import proto

DEFAULT_ADDR = "127.0.0.1:26670"
DEFAULT_BUCKETS = (128, 1024, 10240)
_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30
# Chunk size a server with no device tier loaded advertises (field 4 of the
# Ping reply); a device-backed server asks the kernel for a bucket-aligned
# size instead (ed25519_kernel.preferred_stream_chunk).
DEFAULT_STREAM_CHUNK = 1024


class FrameTooLarge(ValueError):
    """A frame exceeded CMTPU_SIDECAR_MAX_FRAME. Recoverable on the server
    (error response, connection survives); a client-side raise means the
    caller must chunk (the streaming path) — never silently truncate."""


def _max_frame() -> int:
    env = os.environ.get("CMTPU_SIDECAR_MAX_FRAME", "")
    if env:
        try:
            return max(1024, int(env))
        except ValueError:
            pass
    return MAX_FRAME


# -- framing ------------------------------------------------------------------


def write_frame(sock: socket.socket, body: bytes) -> None:
    cap = _max_frame()
    if len(body) > cap:
        raise FrameTooLarge(
            f"refusing to send {len(body)}-byte frame "
            f"(CMTPU_SIDECAR_MAX_FRAME={cap}); chunk the request instead"
        )
    sock.sendall(_LEN.pack(len(body)) + body)


def read_frame(sock: socket.socket) -> bytes | None:
    hdr = _read_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    cap = _max_frame()
    if n > cap:
        # Drain the oversized body in bounded chunks (never one n-byte
        # allocation) so the stream stays framed and the connection can
        # carry an error response + further requests.
        remaining = n
        while remaining:
            chunk = sock.recv(min(65536, remaining))
            if not chunk:
                return None
            remaining -= len(chunk)
        raise FrameTooLarge(
            f"peer sent {n}-byte frame (CMTPU_SIDECAR_MAX_FRAME={cap})"
        )
    return _read_exact(sock, n)


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _encode_request(req_id: int, method: str, payload: bytes) -> bytes:
    return (
        proto.field_varint(1, req_id, emit_default=True)
        + proto.field_string(2, method)
        + proto.field_bytes(3, payload)
    )


def _encode_response(req_id: int, ok: bool, error: str, payload: bytes) -> bytes:
    return (
        proto.field_varint(1, req_id, emit_default=True)
        + proto.field_bool(2, ok)
        + proto.field_string(3, error)
        + proto.field_bytes(4, payload)
    )


# -- server -------------------------------------------------------------------


class _ServerStream:
    """Per-connection state of one in-progress BatchVerifyChunk stream:
    the futures of every submitted chunk (resolved in submission order by
    the scheduler's single dispatcher) and the expected next sequence."""

    __slots__ = ("futures", "next_seq")

    def __init__(self):
        self.futures: list[tuple] = []  # (VerifyFuture, n_sigs)
        self.next_seq = 0


class SidecarServer:
    """The long-lived device owner. Device calls are serialized with a lock
    (one TPU, one XLA stream); socket handling is one thread per connection,
    so hosts can pipeline requests like the reference's socket ABCI client.
    Verifications route through a CoalescingScheduler over the device lock
    (CMTPU_COALESCE=0 strips it): concurrent connections — many node
    processes sharing one tunnel — merge into single columnar dispatches
    with per-request bitmap slicing, the round-8 in-process move applied
    across the wire."""

    def __init__(self, addr: str = DEFAULT_ADDR, backend: VerifyBackend | None = None):
        self.addr = addr
        self.backend = backend if backend is not None else device_backend(
            os.environ.get("CMTPU_SIDECAR_DEVICE", "auto").lower()
        )
        self._device_lock = threading.Lock()
        self._sched: CoalescingScheduler | None = None
        if os.environ.get("CMTPU_COALESCE", "1") != "0":
            self._sched = CoalescingScheduler(
                LockedBackend(self.backend, self._device_lock)
            )
        host, port = addr.rsplit(":", 1)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                conn = {"streams": {}}  # per-connection stream table
                while True:
                    try:
                        body = read_frame(sock)
                    except FrameTooLarge as e:
                        # Loud but survivable: the offending request is
                        # unidentifiable (its body was drained, not parsed),
                        # so the error response carries id 0 and the
                        # connection keeps serving.
                        try:
                            write_frame(
                                sock,
                                _encode_response(0, False, f"FrameTooLarge: {e}", b""),
                            )
                            continue
                        except OSError:
                            return
                    except (OSError, ValueError):
                        return
                    if body is None:
                        return
                    req_id = 0
                    try:  # fault isolation per request, incl. malformed bodies
                        fields = proto.decode_fields(body)
                        req_id = proto.get_uvarint(fields, 1)
                        method = proto.get_string(fields, 2)
                        payload = proto.get_bytes(fields, 3)
                        out = outer._dispatch(method, payload, conn)
                        resp = _encode_response(req_id, True, "", out)
                    except Exception as e:
                        resp = _encode_response(req_id, False, f"{type(e).__name__}: {e}", b"")
                    try:
                        write_frame(sock, resp)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, int(port)), Handler)

    def _submit(self, pubs, msgs, sigs) -> VerifyFuture:
        """One chunk/request into the verification path: async through the
        scheduler (cross-connection coalescing + the device lock inside its
        dispatcher) when wired, an immediately-resolved future otherwise —
        the streaming handler's double buffer works against either."""
        if self._sched is not None:
            return self._sched.submit(pubs, msgs, sigs)
        fut = VerifyFuture(len(pubs))
        try:
            with self._device_lock:
                fut._set_result(self.backend.batch_verify(pubs, msgs, sigs))
        except BaseException as e:
            fut._set_error(e)
        return fut

    def _preferred_chunk(self) -> int:
        """Streamed-chunk size advertised in the Ping reply: the kernel's
        bucket-aligned choice when the device tier is loaded (zero padding,
        mesh-width multiple), a flat default otherwise. Never imports jax —
        a host-only server must not pull the device stack for a Ping."""
        import sys

        ek = sys.modules.get("cometbft_tpu.ops.ed25519_kernel")
        if ek is not None:
            try:
                return int(ek.preferred_stream_chunk())
            except Exception:
                pass
        return DEFAULT_STREAM_CHUNK

    def scheduler_counters(self) -> dict:
        """The server-side coalescer's counters (empty when stripped) —
        the bench `sidecar` stage reads the cross-connection merge ratio
        from here."""
        return self._sched.counters() if self._sched is not None else {}

    def _dispatch(self, method: str, payload: bytes, conn: dict | None = None) -> bytes:
        if method == "Ping":
            # Capability reply: PingResp { 1: "pong", 2: mesh_width,
            # 3: streaming, 4: chunk }. The width is the REMOTE pod's chip
            # count, so client-side sizing (the coalescer's default merge
            # cap, chain pricing) sees the serving mesh, not the local
            # host's; field 3 advertises the chunked-streaming method and
            # field 4 the server's preferred chunk size. Legacy clients
            # that compared the raw body to b"pong" must upgrade with the
            # server; new clients still accept a bare b"pong" from an old
            # server (width defaults to 1, streaming to off).
            width = 1
            mw = getattr(self.backend, "mesh_width", None)
            if mw is not None:
                try:
                    width = max(1, int(mw()))
                except Exception:
                    width = 1
            return (
                proto.field_bytes(1, b"pong")
                + proto.field_varint(2, width)
                + proto.field_varint(3, 1)
                + proto.field_varint(4, self._preferred_chunk())
            )
        if method == "BatchVerify":
            fields = proto.decode_fields(payload)
            pubs = proto.get_repeated_bytes(fields, 1)
            msgs = proto.get_repeated_bytes(fields, 2)
            sigs = proto.get_repeated_bytes(fields, 3)
            if not (len(pubs) == len(msgs) == len(sigs)):
                raise ValueError("pubs/msgs/sigs length mismatch")
            if not pubs:
                # The scheduler short-circuits empty submissions with its
                # own sentinel; keep the backend's empty-batch answer.
                with self._device_lock:
                    ok, bitmap = self.backend.batch_verify(pubs, msgs, sigs)
            else:
                ok, bitmap = self._submit(pubs, msgs, sigs).result()
            return proto.field_bool(1, ok) + proto.field_bytes(
                2, bytes(1 if b else 0 for b in bitmap)
            )
        if method == "BatchVerifyChunk":
            if conn is None:
                raise ValueError("BatchVerifyChunk requires a connection")
            return self._dispatch_chunk(payload, conn["streams"])
        if method == "MerkleRoot":
            fields = proto.decode_fields(payload)
            leaves = proto.get_repeated_bytes(fields, 1)
            with self._device_lock:
                root = self.backend.merkle_root(leaves)
            return proto.field_bytes(1, root)
        if method == "Warmup":
            fields = proto.decode_fields(payload)
            buckets = tuple(proto.get_repeated_uvarint(fields, 1)) or DEFAULT_BUCKETS
            self.warmup(buckets)
            return b""
        raise ValueError(f"unknown method {method!r}")

    def _dispatch_chunk(self, payload: bytes, streams: dict) -> bytes:
        """One chunk of a streamed BatchVerify (module docstring: ChunkReq).
        Non-final chunks are submitted to the scheduler and acked — after
        the PREVIOUS chunk's dispatch resolved, the double buffer that
        paces the client to one in-flight dispatch while it packs/sends
        the next chunk. The final chunk's response is the whole stream's
        BatchVerifyResp. Any failure tears the stream down and surfaces as
        this chunk's error response — never a partial bitmap."""
        fields = proto.decode_fields(payload)
        sid = proto.get_uvarint(fields, 1)
        seq = proto.get_uvarint(fields, 2)
        final = proto.get_bool(fields, 3)
        pubs = proto.get_repeated_bytes(fields, 4)
        msgs = proto.get_repeated_bytes(fields, 5)
        sigs = proto.get_repeated_bytes(fields, 6)
        if seq == 0:
            if sid in streams:
                raise ValueError(f"stream {sid} already open")
            if len(streams) >= 64:  # a leaking client must not hoard futures
                raise ValueError("too many open streams on this connection")
            streams[sid] = _ServerStream()
        st = streams.get(sid)
        if st is None:
            raise ValueError(f"unknown stream {sid} (chunk seq {seq})")
        try:
            if seq != st.next_seq:
                raise ValueError(
                    f"stream {sid}: chunk seq {seq}, expected {st.next_seq}"
                )
            st.next_seq += 1
            if not (len(pubs) == len(msgs) == len(sigs)):
                raise ValueError("pubs/msgs/sigs length mismatch")
            if pubs:
                st.futures.append((self._submit(pubs, msgs, sigs), len(pubs)))
            if not final:
                if len(st.futures) >= 2:
                    st.futures[-2][0].result()
                return b""
            all_ok = True
            bits_out = bytearray()
            for fut, n in st.futures:
                ok, bits = fut.result()
                if len(bits) != n:
                    raise ValueError(
                        f"stream {sid}: chunk answered {len(bits)} of {n} lanes"
                    )
                all_ok = all_ok and ok
                bits_out.extend(1 if b else 0 for b in bits)
            del streams[sid]
            return proto.field_bool(1, all_ok) + proto.field_bytes(
                2, bytes(bits_out)
            )
        except Exception:
            streams.pop(sid, None)
            raise

    def warmup(self, buckets=DEFAULT_BUCKETS) -> None:
        """Precompile the batch-verify buckets so the first real commit does
        not pay an XLA compile (SURVEY §7 hard part 3, <2 ms budget)."""
        if isinstance(self.backend, TpuBackend):
            from cometbft_tpu.ops import ed25519_kernel

            with self._device_lock:
                ed25519_kernel.warmup(buckets)

    @property
    def bound_addr(self) -> str:
        """host:port actually bound — differs from `addr` when the caller
        asked for port 0 (the fanout shard workers and tests do, to dodge
        port races; they print this so the parent learns the real port)."""
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def serve_forever(self):
        self._server.serve_forever()

    def start(self) -> "SidecarServer":
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return self

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        if self._sched is not None:
            self._sched.close()


# -- client -------------------------------------------------------------------


class GrpcBackend(VerifyBackend):
    """The `CMTPU_BACKEND=grpc` client: speaks the framed protocol above.
    Thread-safe (one in-flight request per connection, guarded by a lock);
    reconnects once on a broken connection. Fails loudly when the sidecar is
    unreachable — an explicitly configured remote verifier must not silently
    fall back to a different trust path."""

    name = "grpc"

    # Redial backoff bounds: first failure waits _REDIAL_BASE_S, doubling
    # (with jitter inside the doubling) to the _REDIAL_MAX_S cap.
    _REDIAL_BASE_S = 0.05
    _REDIAL_MAX_S = 5.0

    def __init__(
        self,
        addr: str = DEFAULT_ADDR,
        timeout_s: float = 300.0,
        connect_timeout_s: float = 5.0,
    ):
        # timeout_s is the per-REQUEST deadline (slot wait below);
        # connect_timeout_s bounds dial time only. One 300 s knob doing
        # both meant a dead relay cost five minutes per connect attempt.
        self.addr = addr
        self.timeout_s = timeout_s
        self.connect_timeout_s = connect_timeout_s
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()  # serializes frame WRITES only
        self._plock = threading.Lock()  # connection + pending table
        # id -> [Event, body | None, owning socket]: the socket tag lets a
        # dead connection's reader sweep fail ONLY its own waiters.
        self._pending: dict[int, list] = {}
        self._next_id = 0
        # Capped redial-with-backoff (under _plock): a client object used
        # to die for good once the sidecar went away; now each failed dial
        # opens a backoff window in which calls fail FAST, and the next
        # call after the window redials.
        self._redial_failures = 0
        self._redial_not_before = 0.0
        # Remote pod width from the Ping capability reply (1 until probed).
        self._remote_mesh_width = 1
        # Streaming capability: None = never probed, False = legacy server,
        # True = server speaks BatchVerifyChunk. The first large
        # batch_verify self-probes (one Ping on the same connection).
        self._remote_streams: bool | None = None
        # Server-preferred chunk size from the Ping reply (field 4).
        self._remote_chunk = DEFAULT_STREAM_CHUNK
        self._next_stream = 0
        self.counters_ = {
            "unary_calls": 0,
            "streamed_calls": 0,
            "streamed_chunks": 0,
            "stream_retries": 0,
        }

    def _connect_locked(self) -> None:
        now = time.monotonic()
        if self._redial_failures and now < self._redial_not_before:
            raise ConnectionError(
                f"sidecar {self.addr} in redial backoff "
                f"({self._redial_failures} consecutive dial failures)"
            )
        host, port = self.addr.rsplit(":", 1)
        try:
            s = socket.create_connection(
                (host, int(port)), timeout=self.connect_timeout_s
            )
        except OSError as e:
            self._redial_failures += 1
            base = min(
                self._REDIAL_BASE_S * 2 ** (self._redial_failures - 1),
                self._REDIAL_MAX_S,
            )
            self._redial_not_before = now + base * random.uniform(0.5, 1.0)
            raise ConnectionError(f"sidecar dial {self.addr}: {e}") from e
        self._redial_failures = 0
        # Blocking mode from here: request deadlines are enforced by the
        # waiter's Event (timeout_s), and a lingering socket timeout would
        # make the reader thread kill an idle-but-healthy connection.
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        threading.Thread(
            target=self._reader_loop, args=(s,), daemon=True, name="sidecar-reader"
        ).start()

    def _reader_loop(self, sock: socket.socket) -> None:
        """Demultiplexes responses by request id so callers can PIPELINE:
        many requests may be in flight on the one connection (the server's
        handler advertises pipelining; the old client serialized write+read
        under a single lock — VERDICT r3 weak #8)."""
        while True:
            try:
                body = read_frame(sock)
            except (OSError, FrameTooLarge):
                # An over-cap RESPONSE means client and server disagree on
                # the frame cap; treat the connection as unusable rather
                # than strand its waiters.
                body = None
            if body is None:
                break
            fields = proto.decode_fields(body)
            req_id = proto.get_uvarint(fields, 1)
            with self._plock:
                slot = self._pending.pop(req_id, None)
            if slot is not None:
                slot[1] = body
                slot[0].set()
        # Connection died: fail the waiters that belong to THIS socket so
        # they can retry. A delayed cleanup must not sweep requests already
        # registered on a replacement connection (that race turned one
        # reconnect into a spurious second failure).
        with self._plock:
            if self._sock is sock:
                self._sock = None
            dead = {k: v for k, v in self._pending.items() if v[2] is sock}
            for k in dead:
                del self._pending[k]
        for slot in dead.values():
            slot[0].set()

    def _begin_call(self, method: str, payload: bytes, pin_sock=None):
        """Register a pending slot and write the request frame; returns
        (slot, req_id) for _await_slot. `pin_sock` (streaming) demands the
        frame ride a specific connection: a mid-stream reconnect would
        scatter one stream's chunks across sockets, and the server would
        rightly reject the orphaned tail."""
        slot = [threading.Event(), None, None]
        with self._plock:
            if pin_sock is not None and self._sock is not pin_sock:
                err = ConnectionError("sidecar connection lost mid-stream")
                err.sock = pin_sock
                raise err
            if self._sock is None:
                self._connect_locked()
            self._next_id += 1
            req_id = self._next_id
            sock = self._sock
            slot[2] = sock
            self._pending[req_id] = slot
        req = _encode_request(req_id, method, payload)
        try:
            with self._wlock:
                write_frame(sock, req)
        except FrameTooLarge:
            # Not a connection fault: fail fast, no retry, no teardown.
            with self._plock:
                self._pending.pop(req_id, None)
            raise
        except OSError as e:
            with self._plock:
                self._pending.pop(req_id, None)
            err = ConnectionError(str(e))
            err.sock = sock  # which connection failed (see _call)
            raise err from e
        return slot, req_id

    def _await_slot(self, slot, req_id: int, method: str) -> bytes:
        if not slot[0].wait(self.timeout_s):
            with self._plock:
                self._pending.pop(req_id, None)
            raise TimeoutError(f"sidecar {method} timed out")
        if slot[1] is None:
            err = ConnectionError("sidecar connection lost mid-request")
            err.sock = slot[2]
            raise err
        return slot[1]

    def _call_once(self, method: str, payload: bytes) -> bytes:
        slot, req_id = self._begin_call(method, payload)
        return self._await_slot(slot, req_id, method)

    def _call(self, method: str, payload: bytes) -> bytes:
        for attempt in (0, 1):
            try:
                body = self._call_once(method, payload)
                break
            except ConnectionError as e:
                # Tear down only the connection that actually failed: a
                # thread handling a stale failure must not close the
                # replacement another thread just established.
                failed = getattr(e, "sock", None)
                with self._plock:
                    if self._sock is not None and (
                        failed is None or self._sock is failed
                    ):
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                if attempt:
                    raise
        fields = proto.decode_fields(body)
        if not proto.get_bool(fields, 2):
            raise RuntimeError(f"sidecar error: {proto.get_string(fields, 3)}")
        return proto.get_bytes(fields, 4)

    def ping(self) -> bool:
        body = self._call("Ping", b"")
        if body == b"pong":  # pre-capability server
            self._remote_streams = False
            return True
        try:
            fields = proto.decode_fields(body)
            if proto.get_bytes(fields, 1) != b"pong":
                return False
            width = proto.get_uvarint(fields, 2)
            if width:
                self._remote_mesh_width = int(width)
            self._remote_streams = bool(proto.get_uvarint(fields, 3))
            chunk = proto.get_uvarint(fields, 4)
            if chunk:
                self._remote_chunk = int(chunk)
            return True
        except Exception:
            return False

    def mesh_width(self) -> int:
        """The serving pod's chip count, learned from the Ping capability
        reply. Never dials: an unpinged client reports 1 and the caller's
        periodic refresh picks the real width up after the first probe."""
        return self._remote_mesh_width

    def chunk_size(self) -> int:
        """Streamed-chunk size: CMTPU_SIDECAR_CHUNK when set, else the
        server's Ping-advertised preference, rounded UP to a multiple of
        the remote pod's width so every chunk fills the serving mesh."""
        env = os.environ.get("CMTPU_SIDECAR_CHUNK", "")
        size = 0
        if env:
            try:
                size = int(env)
            except ValueError:
                size = 0
        if size <= 0:
            size = self._remote_chunk
        w = max(1, self._remote_mesh_width)
        if size % w:
            size += w - size % w
        return max(size, w)

    def batch_verify(self, pubs, msgs, sigs):
        n = len(pubs)
        chunk = self.chunk_size()
        if n > chunk:
            if self._remote_streams is None:
                # Lazy capability probe on the first oversized batch: one
                # Ping on the same connection (errors propagate exactly as
                # the unary call's would).
                self.ping()
            if self._remote_streams:
                return self._batch_verify_streamed(pubs, msgs, sigs, chunk)
        with self._plock:
            self.counters_["unary_calls"] += 1
        payload = b"".join(
            proto.field_bytes(1, p, emit_default=True) for p in pubs
        ) + b"".join(
            proto.field_bytes(2, m, emit_default=True) for m in msgs
        ) + b"".join(
            proto.field_bytes(3, s, emit_default=True) for s in sigs
        )
        out = self._call("BatchVerify", payload)
        fields = proto.decode_fields(out)
        bitmap = proto.get_bytes(fields, 2)
        return proto.get_bool(fields, 1), [bool(b) for b in bitmap[: len(pubs)]]

    def _batch_verify_streamed(self, pubs, msgs, sigs, chunk: int):
        """Chunked-streaming BatchVerify with the same two-attempt redial
        discipline as _call: a ConnectionError tears down the failed
        socket and the SECOND attempt re-streams from chunk 0 on a fresh
        connection (streams never resume mid-way — the server holds no
        cross-connection state, so a partial bitmap is impossible)."""
        for attempt in (0, 1):
            try:
                return self._stream_once(pubs, msgs, sigs, chunk)
            except ConnectionError as e:
                failed = getattr(e, "sock", None)
                with self._plock:
                    if self._sock is not None and (
                        failed is None or self._sock is failed
                    ):
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    self.counters_["stream_retries"] += 1
                if attempt:
                    raise

    def _check_ack(self, body: bytes) -> None:
        fields = proto.decode_fields(body)
        if not proto.get_bool(fields, 2):
            raise RuntimeError(f"sidecar error: {proto.get_string(fields, 3)}")

    @staticmethod
    def _stream_window() -> int:
        """Unacked-chunk pipeline depth. The server still only ever has one
        dispatch in flight per connection (its ack of chunk k gates on
        chunk k-1's dispatch) — a deeper client window just keeps frames in
        the socket on their way there, which is what hides a long wire RTT
        behind device dispatch. Floor 2: below that the pipeline degenerates
        into send/ack lockstep and the overlap disappears."""
        try:
            return max(2, int(os.environ.get("CMTPU_SIDECAR_WINDOW", "6")))
        except ValueError:
            return 6

    def _stream_once(self, pubs, msgs, sigs, chunk: int):
        n = len(pubs)
        with self._plock:
            self._next_stream += 1
            sid = self._next_stream
        n_chunks = (n + chunk - 1) // chunk
        window = self._stream_window()
        slots: list[tuple] = []
        pinned = None
        for seq in range(n_chunks):
            lo, hi = seq * chunk, min((seq + 1) * chunk, n)
            payload = (
                proto.field_varint(1, sid, emit_default=True)
                + proto.field_varint(2, seq, emit_default=True)
                + proto.field_bool(3, seq == n_chunks - 1)
                + b"".join(
                    proto.field_bytes(4, p, emit_default=True) for p in pubs[lo:hi]
                )
                + b"".join(
                    proto.field_bytes(5, m, emit_default=True) for m in msgs[lo:hi]
                )
                + b"".join(
                    proto.field_bytes(6, s, emit_default=True) for s in sigs[lo:hi]
                )
            )
            # Windowed pipelining: at most `window` unacked chunks in
            # flight — the server is packing/dispatching chunk k while this
            # thread packs and sends later chunks, and the k-th ack gates
            # chunk k+window so a slow server applies backpressure instead
            # of buffering the whole batch in socket memory.
            if seq >= window:
                self._check_ack(
                    self._await_slot(*slots[seq - window], "BatchVerifyChunk")
                )
            slots.append(self._begin_call("BatchVerifyChunk", payload, pin_sock=pinned))
            if pinned is None:
                pinned = slots[0][0][2]
        with self._plock:
            self.counters_["streamed_chunks"] += n_chunks
        for i in range(max(0, n_chunks - window), n_chunks - 1):
            self._check_ack(self._await_slot(*slots[i], "BatchVerifyChunk"))
        final = self._await_slot(*slots[-1], "BatchVerifyChunk")
        fields = proto.decode_fields(final)
        if not proto.get_bool(fields, 2):
            raise RuntimeError(f"sidecar error: {proto.get_string(fields, 3)}")
        out = proto.decode_fields(proto.get_bytes(fields, 4))
        bitmap = proto.get_bytes(out, 2)
        if len(bitmap) != n:
            raise RuntimeError(
                f"sidecar stream answered {len(bitmap)} of {n} lanes"
            )
        with self._plock:
            self.counters_["streamed_calls"] += 1
        return proto.get_bool(out, 1), [bool(b) for b in bitmap]

    def counters(self) -> dict:
        with self._plock:
            out = dict(self.counters_)
        out["remote_mesh_width"] = self._remote_mesh_width
        out["remote_chunk"] = self._remote_chunk
        out["streaming"] = bool(self._remote_streams)
        return out

    def merkle_root(self, leaves):
        payload = b"".join(
            proto.field_bytes(1, leaf, emit_default=True) for leaf in leaves
        )
        out = self._call("MerkleRoot", payload)
        return proto.get_bytes(proto.decode_fields(out), 1)

    def warmup(self, buckets=DEFAULT_BUCKETS) -> None:
        self._call(
            "Warmup",
            b"".join(proto.field_varint(1, b, emit_default=True) for b in buckets),
        )

    def close(self) -> None:
        with self._plock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


def main() -> None:
    """`python -m cometbft_tpu.sidecar`: serve until killed."""
    addr = os.environ.get("CMTPU_SIDECAR_ADDR", DEFAULT_ADDR)
    server = SidecarServer(addr)
    print(
        f"sidecar: serving on {server.bound_addr} (backend={server.backend.name})",
        flush=True,
    )
    if os.environ.get("CMTPU_SIDECAR_WARM", "1") == "1":
        server.warmup()
        print("sidecar: warmup complete", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()

"""Coalescing scheduler — compat shim over the continuous-batching engine.

Round 6 built `CoalescingScheduler` as the micro-batching front of the
`CMTPU_BACKEND=auto` chain: concurrent callers' requests merge into one
columnar dispatch with within-batch triple dedup, per-request bitmap
slicing, and per-request fallback retries when a merged dispatch fails.
Round 14 generalized that machinery into the continuous-batching
verification engine (`sidecar/engine.py`) — priority classes, starvation
escape, deadline-aware dispatch sizing — and this class became a thin
shim that embeds one.

The public surface is unchanged: `submit()` returns a `VerifyFuture`,
`batch_verify` is submit + wait, the knobs keep their names
(`CMTPU_COALESCE_WINDOW_MS` maps onto the engine's compat hold,
`CMTPU_COALESCE_MAX` pins the merge cap, `CMTPU_COALESCE=0` still strips
the layer in backend.py), `counters()` keeps its legacy keys, and
`refresh_cap()` delegates to the engine so a Ping-advertised wider remote
mesh still grows the auto merge cap (grow-only; pinned caps never move).
Everything a caller observed of the round-6 scheduler — dispatch shapes,
slicing, error isolation — is the engine behaving identically for
untagged (blocksync-class) traffic under a compat hold.

The sidecar SERVER embeds the same shim over its device lock
(sidecar/service.py, round 10): there the concurrent submitters are
CONNECTIONS — many node processes sharing one tunnel — and streamed
chunks, so cross-process requests merge into one columnar dispatch with
the identical slicing/fallback discipline.
"""

from __future__ import annotations

import os

from cometbft_tpu.sidecar.backend import VerifyBackend
from cometbft_tpu.sidecar.engine import (  # noqa: F401  (re-exports)
    VerificationEngine,
    VerifyFuture,
    _env_float,
    _mesh_width_for_cap,
)


class CoalescingScheduler(VerifyBackend):
    """Micro-batching front of the verification chain (module docstring)."""

    name = "coalesce"

    def __init__(
        self,
        inner: VerifyBackend,
        window_ms: float | None = None,
        max_sigs: int | None = None,
    ):
        if window_ms is None:
            window_ms = _env_float("CMTPU_COALESCE_WINDOW_MS", 2.0)
        if max_sigs is None and os.environ.get("CMTPU_COALESCE_MAX", ""):
            max_sigs = int(_env_float("CMTPU_COALESCE_MAX", 16384))
        # max_sigs None -> the engine derives its pod-width auto cap
        # (16384 x mesh width, grow-only via refresh_cap).
        self.engine = VerificationEngine(
            inner, hold_ms=window_ms, max_sigs=max_sigs
        )

    # -- engine views (no local copies: refresh_cap must never leave a
    # stale cap behind on the shim) ---------------------------------------

    @property
    def inner(self) -> VerifyBackend:
        return self.engine.inner

    @property
    def window_ms(self) -> float:
        return self.engine.hold_ms

    @window_ms.setter
    def window_ms(self, v: float) -> None:
        self.engine.hold_ms = v

    @property
    def max_sigs(self) -> int:
        return self.engine.max_sigs

    @max_sigs.setter
    def max_sigs(self, v: int) -> None:
        self.engine.max_sigs = v

    @property
    def counters_(self) -> dict:
        return self.engine.counters_

    # -- delegated surface -------------------------------------------------

    def submit(self, pubs, msgs, sigs) -> VerifyFuture:
        return self.engine.submit(pubs, msgs, sigs)

    def batch_verify(self, pubs, msgs, sigs):
        return self.engine.batch_verify(pubs, msgs, sigs)

    def aggregate_verify(self, pubs, msgs, agg_sig):
        return self.engine.aggregate_verify(pubs, msgs, agg_sig)

    def merkle_root(self, leaves):
        return self.engine.merkle_root(leaves)

    def mesh_width(self) -> int:
        return self.engine.mesh_width()

    def refresh_cap(self) -> int:
        return self.engine.refresh_cap()

    def ping(self):
        return self.engine.ping()

    def counters(self) -> dict:
        return self.engine.counters()

    def register_metrics(self, registry) -> None:
        self.engine.register_metrics(registry)

    def close(self) -> None:
        self.engine.close()

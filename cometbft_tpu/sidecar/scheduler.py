"""Dynamic micro-batching verification scheduler.

The supervised chain bounds one call and the hybrid planner splits one
call, but until this layer every CALLER still dispatched alone: concurrent
verifications from consensus, blocksync, the light client and RPC each paid
the full device dispatch latency and serialized on the device-owner thread.
The lane-parallel kernel is indifferent to which commit a signature belongs
to, so signatures from many in-flight requests can share one dispatch —
the same request-coalescing move inference servers make (Orca-style
continuous batching, Triton-style dynamic batchers).

`CoalescingScheduler` is the outermost tier of the `CMTPU_BACKEND=auto`
chain (backend.py wires it above `build_resilient()`'s supervisor):

  callers --submit--> scheduler --ONE batch_verify--> supervisor -> hybrid -> cpu

* Callers block on a future (`batch_verify` is submit + wait, so the
  `VerifyBackend` surface is unchanged and every existing dispatch site —
  types/validation commit verification, the blocksync window pre-verify,
  the light client — coalesces without modification).
* A single dispatcher thread accumulates requests for a short window
  (`CMTPU_COALESCE_WINDOW_MS`, default 2 ms) or until the batch reaches
  `CMTPU_COALESCE_MAX` signatures, packs them into one columnar batch with
  within-batch triple dedup (N light clients bisecting the same chain
  submit identical triples — they share lanes), issues ONE `batch_verify`
  through the chain, and slices the returned bitmap back per request.
* Requests queued while a dispatch is in flight coalesce into the next
  dispatch (continuous batching): a burst's first request pays at most the
  window, the rest pay nothing.
* A failed coalesced dispatch falls back to per-request retries, so one
  poisoned request (oversized sig that makes a tier raise, a wedge that
  outlives the chain) cannot fail its batchmates; only the guilty
  request's caller sees the error.

Single requests larger than `CMTPU_COALESCE_MAX` are never split — the
hybrid planner owns WITHIN-call splitting; this layer only merges ACROSS
callers, and the supervisor between them bounds whatever is dispatched.

The sidecar SERVER embeds the same scheduler over its device lock
(sidecar/service.py, round 10): there the concurrent submitters are
CONNECTIONS — many node processes sharing one tunnel — and streamed
chunks, so cross-process requests merge into one columnar dispatch with
the identical slicing/fallback discipline.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from cometbft_tpu.sidecar.backend import VerifyBackend

_WAIT_SAMPLES = 512  # queue-wait ring buffer (p50/p95 source)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _mesh_width_for_cap() -> int:
    """Device count behind the default dispatch cap (16384 x width), read
    WITHOUT risking a device-tunnel probe from this constructor: use the
    kernel's already-probed width when available (the auto chain constructs
    its device tier — which probes — before this layer), and only probe
    ourselves when JAX is pinned to the local CPU backend with a forced
    virtual device count (the test/dryrun mesh). Everywhere else the probe
    could hang a node start behind a wedged axon tunnel, and a cpu-only
    deployment shouldn't pay a jax import for a cap it can't use."""
    ek = sys.modules.get("cometbft_tpu.ops.ed25519_kernel")
    if ek is not None and ek.known_mesh_width():
        return ek.known_mesh_width()
    if (
        os.environ.get("JAX_PLATFORMS", "") == "cpu"
        and "xla_force_host_platform_device_count"
        in os.environ.get("XLA_FLAGS", "")
    ):
        try:
            from cometbft_tpu.ops import ed25519_kernel as ek2

            return ek2.mesh_width()
        except Exception:
            return 1
    return 1


class VerifyFuture:
    """Result slot a submitter blocks on; filled by the dispatcher."""

    __slots__ = ("_event", "_result", "_error", "t_submit", "n_sigs")

    def __init__(self, n_sigs: int):
        self._event = threading.Event()
        self._result: tuple[bool, list[bool]] | None = None
        self._error: BaseException | None = None
        self.t_submit = time.perf_counter()
        self.n_sigs = n_sigs

    def _set_result(self, result: tuple[bool, list[bool]]) -> None:
        self._result = result
        self._event.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> tuple[bool, list[bool]]:
        if not self._event.wait(timeout):
            raise TimeoutError("verification future not resolved in time")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("pubs", "msgs", "sigs", "future", "t_start")

    def __init__(self, pubs, msgs, sigs, future):
        self.pubs = pubs
        self.msgs = msgs
        self.sigs = sigs
        self.future = future
        self.t_start = 0.0  # set when the dispatcher picks it up


class CoalescingScheduler(VerifyBackend):
    """Micro-batching front of the verification chain (module docstring)."""

    name = "coalesce"

    def __init__(
        self,
        inner: VerifyBackend,
        window_ms: float | None = None,
        max_sigs: int | None = None,
    ):
        self.inner = inner
        self.window_ms = (
            _env_float("CMTPU_COALESCE_WINDOW_MS", 2.0)
            if window_ms is None
            else window_ms
        )
        self._cap_auto = False
        if max_sigs is not None:
            self.max_sigs = max_sigs
        elif os.environ.get("CMTPU_COALESCE_MAX", ""):
            self.max_sigs = int(_env_float("CMTPU_COALESCE_MAX", 16384))
        else:
            # Pod-width default: one merged dispatch can fill every chip
            # (16384 lanes each — the single-chip cap this generalizes).
            # An explicit env or arg always wins. The auto cap re-reads the
            # chain's width periodically (refresh_cap) because the width a
            # grpc tier serves is only learned from the sidecar's Ping
            # capability reply AFTER the first connect.
            self._cap_auto = True
            self.max_sigs = 16384 * max(1, _mesh_width_for_cap())
        self._queue: list[_Request] = []
        self._cond = threading.Condition()
        self._closed = False
        self._thread: threading.Thread | None = None
        self._wait_ms: list[float] = []  # ring buffer of queue waits
        self._wait_i = 0
        self.counters_ = {
            "requests": 0,
            "dispatches": 0,
            "coalesced_dispatches": 0,  # dispatches carrying >1 request
            "batched_requests": 0,      # requests that shared a dispatch
            "coalesced_sigs": 0,        # sigs that rode a shared dispatch
            "dedup_sigs": 0,            # lanes saved by within-batch dedup
            "fallback_splits": 0,       # coalesced dispatches split on error
        }

    # -- submission surface ------------------------------------------------

    def submit(self, pubs, msgs, sigs) -> VerifyFuture:
        """Enqueue one verification request; returns the future its caller
        blocks on.  Raises after close() — a scheduler with no dispatcher
        must fail loudly, not hang the submitter forever."""
        fut = VerifyFuture(len(pubs))
        if not pubs:
            fut._set_result((False, []))
            return fut
        req = _Request(list(pubs), list(msgs), list(sigs), fut)
        with self._cond:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            self.counters_["requests"] += 1
            self._queue.append(req)
            self._ensure_thread()
            self._cond.notify_all()
        return fut

    def batch_verify(self, pubs, msgs, sigs):
        return self.submit(pubs, msgs, sigs).result()

    def aggregate_verify(self, pubs, msgs, agg_sig):
        # One boolean per whole commit: nothing to slice across callers;
        # pass straight through to the supervised chain.
        return self.inner.aggregate_verify(pubs, msgs, agg_sig)

    def merkle_root(self, leaves):
        # Roots carry no cross-caller coalescing opportunity (one tree per
        # call); pass straight through to the chain.
        return self.inner.merkle_root(leaves)

    def mesh_width(self) -> int:
        mw = getattr(self.inner, "mesh_width", None)
        return int(mw()) if mw is not None else 1

    def refresh_cap(self) -> int:
        """Re-derive the auto merge cap from the chain's CURRENT width
        (local chips, or a remote pod's once the sidecar Ping capability
        reply has been seen). Pinned caps (arg/env) never move."""
        if self._cap_auto:
            try:
                width = max(1, self.mesh_width())
            except Exception:
                return self.max_sigs
            new_cap = 16384 * width
            if new_cap > self.max_sigs:
                self.max_sigs = new_cap
        return self.max_sigs

    def ping(self):
        inner_ping = getattr(self.inner, "ping", None)
        return inner_ping() if inner_ping is not None else True

    # -- dispatcher --------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="verify-coalescer"
            )
            self._thread.start()

    def _collect(self) -> list[_Request]:
        """Block until work exists, hold the window open for batchmates,
        then drain whole requests up to max_sigs (never splitting one)."""
        with self._cond:
            while not self._queue and not self._closed:
                self._cond.wait()
            if not self._queue:
                return []
            window_s = self.window_ms / 1000.0
            first_t = self._queue[0].future.t_submit
            while window_s > 0 and not self._closed:
                if sum(len(r.pubs) for r in self._queue) >= self.max_sigs:
                    break
                remaining = first_t + window_s - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch = []
            total = 0
            while self._queue:
                n = len(self._queue[0].pubs)
                if batch and total + n > self.max_sigs:
                    break
                req = self._queue.pop(0)
                total += n
                batch.append(req)
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                return  # closed and drained
            now = time.perf_counter()
            for req in batch:
                req.t_start = now
                self._record_wait((now - req.future.t_submit) * 1000.0)
            try:
                self._dispatch(batch)
            except BaseException as e:  # never kill the dispatcher
                for req in batch:
                    if not req.future.done():
                        req.future._set_error(e)

    def _dispatch(self, batch: list[_Request]) -> None:
        with self._cond:
            self.counters_["dispatches"] += 1
            refresh = self._cap_auto and self.counters_["dispatches"] % 64 == 1
        if refresh:
            # Cheap cached-width read (no dial): pick up a remote pod's
            # width once the grpc tier has seen a Ping capability reply.
            try:
                self.refresh_cap()
            except Exception:
                pass
        with self._cond:
            if len(batch) > 1:
                self.counters_["coalesced_dispatches"] += 1
                self.counters_["batched_requests"] += len(batch)
                self.counters_["coalesced_sigs"] += sum(
                    len(r.pubs) for r in batch
                )
        if len(batch) == 1:
            # Nothing to slice or protect: serve the lone request directly
            # (errors propagate to its caller alone).
            req = batch[0]
            try:
                req.future._set_result(
                    self.inner.batch_verify(req.pubs, req.msgs, req.sigs)
                )
            except BaseException as e:
                req.future._set_error(e)
            return
        # Columnar pack with within-batch dedup: identical triples from
        # concurrent requests (N light clients walking the same descent)
        # share one lane.
        lane_of: dict[tuple, int] = {}
        pubs: list[bytes] = []
        msgs: list[bytes] = []
        sigs: list[bytes] = []
        lanes: list[list[int]] = []
        for req in batch:
            req_lanes = []
            for p, m, s in zip(req.pubs, req.msgs, req.sigs):
                key = (p, s, m)
                lane = lane_of.get(key)
                if lane is None:
                    lane = len(pubs)
                    lane_of[key] = lane
                    pubs.append(p)
                    msgs.append(m)
                    sigs.append(s)
                req_lanes.append(lane)
            lanes.append(req_lanes)
        dedup = sum(len(r.pubs) for r in batch) - len(pubs)
        if dedup:
            with self._cond:
                self.counters_["dedup_sigs"] += dedup
        try:
            _, bits = self.inner.batch_verify(pubs, msgs, sigs)
        except BaseException:
            self._fallback(batch)
            return
        if len(bits) != len(pubs):
            # A sick tier answering with the wrong shape is a failed
            # dispatch, not something to mis-slice.
            self._fallback(batch)
            return
        for req, req_lanes in zip(batch, lanes):
            req_bits = [bits[lane] for lane in req_lanes]
            req.future._set_result((all(req_bits), req_bits))

    def _fallback(self, batch: list[_Request]) -> None:
        """The coalesced dispatch failed: retry each request alone so one
        poisoned request cannot fail its batchmates.  Per-request errors go
        to that request's caller only."""
        with self._cond:
            self.counters_["fallback_splits"] += 1
        for req in batch:
            try:
                req.future._set_result(
                    self.inner.batch_verify(req.pubs, req.msgs, req.sigs)
                )
            except BaseException as e:
                req.future._set_error(e)

    # -- observability -----------------------------------------------------

    def _record_wait(self, ms: float) -> None:
        with self._cond:
            if len(self._wait_ms) < _WAIT_SAMPLES:
                self._wait_ms.append(ms)
            else:
                self._wait_ms[self._wait_i % _WAIT_SAMPLES] = ms
            self._wait_i += 1

    def _wait_percentile(self, q: float) -> float:
        with self._cond:
            if not self._wait_ms:
                return 0.0
            data = sorted(self._wait_ms)
        idx = min(len(data) - 1, int(q * (len(data) - 1) + 0.5))
        return data[idx]

    def counters(self) -> dict:
        with self._cond:
            out = dict(self.counters_)
            out["queue_depth"] = len(self._queue)
        out["max_sigs"] = self.max_sigs
        d = max(1, out["dispatches"])
        out["coalesce_ratio"] = round(out["requests"] / d, 3)
        out["queue_wait_p50_ms"] = round(self._wait_percentile(0.50), 3)
        out["queue_wait_p95_ms"] = round(self._wait_percentile(0.95), 3)
        inner_counters = getattr(self.inner, "counters", None)
        if inner_counters is not None:
            out["inner"] = inner_counters()
        return out

    def register_metrics(self, registry) -> None:
        """scheduler_* gauges on a libs.metrics Registry; the inner chain
        registers its own backend_* gauges (node/node.py wires both)."""
        registry.gauge_func(
            "scheduler", "requests", "Verification requests submitted.",
            lambda: self.counters_["requests"],
        )
        registry.gauge_func(
            "scheduler", "dispatches", "Backend dispatches issued.",
            lambda: self.counters_["dispatches"],
        )
        registry.gauge_func(
            "scheduler", "batched_requests",
            "Requests that shared a coalesced dispatch.",
            lambda: self.counters_["batched_requests"],
        )
        registry.gauge_func(
            "scheduler", "fallback_splits",
            "Coalesced dispatches split into per-request retries.",
            lambda: self.counters_["fallback_splits"],
        )
        registry.gauge_func(
            "scheduler", "coalesce_ratio_milli",
            "Requests per dispatch x1000.",
            lambda: int(
                1000 * self.counters_["requests"]
                / max(1, self.counters_["dispatches"])
            ),
        )
        registry.gauge_func(
            "scheduler", "queue_wait_p95_us",
            "95th-percentile queue wait, microseconds.",
            lambda: int(self._wait_percentile(0.95) * 1000),
        )

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        inner_close = getattr(self.inner, "close", None)
        if inner_close is not None:
            inner_close()

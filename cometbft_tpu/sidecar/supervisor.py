"""Resilient verification-backend supervisor.

`device_backend()` picks one tier at process start and every failure after
that is fatal or a 300 s hang — exactly the axon-relay failure mode
CLAUDE.md documents, sitting on the consensus hot path.  `ResilientBackend`
wraps the existing tiers with the four mechanisms a committee-consensus
verification path needs to degrade instead of stall:

* **Per-call deadlines** (`CMTPU_DEADLINE_MS`): every non-anchor tier's
  call is dispatched on that tier's watchdogged worker thread, so even the
  in-process tpu/hybrid tiers — whose device dispatch can block inside the
  tunnel where no socket timeout reaches — are bounded.  A wedged call
  leaves its worker busy; subsequent calls fail fast instead of queueing
  behind the wedge, so a dead relay costs ONE deadline, not liveness.
* **Bounded retry** with jittered exponential backoff for transient errors
  (`CMTPU_RETRIES`, `CMTPU_BACKOFF_MS`) — connection drops retry, deadline
  exhaustion does not (the time is already spent).
* **A per-tier circuit breaker**: `CMTPU_BREAKER_THRESHOLD` consecutive
  failures open the tier; after `CMTPU_BREAKER_COOLDOWN_MS` it goes
  half-open and one probe — the sidecar `Ping` RPC when the tier has one,
  the real call otherwise — re-promotes a healed tier to its chain slot.
* **An ordered degradation chain** `grpc|tpu -> hybrid -> cpu`: the last
  tier is the liveness anchor, called inline with no deadline — it must
  answer, and its answer is trusted.

Degraded results are additionally **cross-checked against the cpu tier**
(`CMTPU_CROSSCHECK` = off | sample | full, default sample): a deterministic
sample of the served bitmap re-verifies on the host path, so an injected
bit-flip false-accept from a sick tier is caught, counted, trips the tier,
and the anchor's answer is served instead.  This is the same ground-truth
seam ops/multihost.py uses for device merkle roots, applied to signatures.

`build_resilient()` assembles the chain `get_backend()` serves under
`CMTPU_BACKEND=auto`; `CMTPU_FAULTS` (sidecar/chaos.py) wraps the
non-anchor tiers for fault-injection runs.
"""

from __future__ import annotations

import hashlib
import os
import queue
import random
import threading
import time

from cometbft_tpu.sidecar.backend import (
    CpuBackend,
    HybridBackend,
    VerifyBackend,
    device_backend,
)

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"

# Transient faults worth a bounded retry on the SAME tier. TimeoutError is
# the sidecar client's own request deadline; DeadlineExceeded (ours) is
# deliberately absent — its time budget is already spent.
_TRANSIENT = (ConnectionError, TimeoutError, OSError)


class DeadlineExceeded(Exception):
    """A tier call outlived CMTPU_DEADLINE_MS on its worker."""


class TierWedged(Exception):
    """A tier's worker is still stuck inside an earlier wedged call."""


class ChainExhausted(Exception):
    """Every tier in the degradation chain failed the call."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _TierWorker:
    """One daemon worker per tier: the watchdogged execution lane that
    makes deadlines enforceable on in-process tiers (a jax dispatch stuck
    in the tunnel cannot be cancelled, only abandoned).  `busy` stays set
    while a wedged call is still running, so the supervisor fails fast
    instead of stacking new work behind the wedge."""

    def __init__(self, name: str):
        self.name = name
        self._q: queue.Queue = queue.Queue()
        self._busy = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name=f"tier-{self.name}"
                )
                self._thread.start()

    def _loop(self) -> None:
        while True:
            fn, slot, done = self._q.get()
            self._busy.set()
            try:
                slot[0] = ("ok", fn())
            except BaseException as e:  # delivered, not swallowed
                slot[0] = ("err", e)
            finally:
                self._busy.clear()
                done.set()

    @property
    def busy(self) -> bool:
        return self._busy.is_set() or not self._q.empty()

    def run(self, fn, timeout_s: float):
        if self.busy:
            raise TierWedged(f"tier {self.name}: worker still wedged")
        self._ensure_thread()
        slot: list = [None]
        done = threading.Event()
        self._q.put((fn, slot, done))
        if not done.wait(timeout_s):
            # Abandon, don't join: the worker stays busy until the wedged
            # call unwinds on its own, and `busy` fast-fails callers until
            # then. The stale result, when it lands, is discarded.
            raise DeadlineExceeded(
                f"tier {self.name}: no result within {timeout_s * 1000:.0f} ms"
            )
        status, value = slot[0]
        if status == "err":
            raise value
        return value


class _Tier:
    def __init__(self, name: str, backend: VerifyBackend):
        self.name = name
        self.backend = backend
        self.worker = _TierWorker(name)
        self.state = _CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.trips = 0
        self.calls = 0
        self.failures = 0
        self.width = 0  # last known mesh width (0 = never read)


class ResilientBackend(VerifyBackend):
    """The supervised degradation chain (see module docstring)."""

    name = "resilient"

    def __init__(
        self,
        tiers: list[tuple[str, VerifyBackend]],
        deadline_ms: float | None = None,
        retries: int | None = None,
        backoff_ms: float | None = None,
        breaker_threshold: int | None = None,
        breaker_cooldown_ms: float | None = None,
        crosscheck: str | None = None,
        clock=None,
    ):
        if not tiers:
            raise ValueError("ResilientBackend needs at least one tier")
        # Injected Clock surface (simnet/clock.py): breaker timestamps and
        # retry backoff run on it, so tests can pin breaker/backoff timing
        # to virtual time on a loaded host. Call DEADLINES stay on the
        # worker's real `Event.wait` — a wedged jax dispatch wedges in wall
        # time no matter what the test clock says.
        if clock is None:
            from cometbft_tpu.simnet.clock import MonotonicClock

            clock = MonotonicClock()
        self._clock = clock
        self.tiers = [_Tier(n, b) for n, b in tiers]
        self.deadline_ms = (
            _env_float("CMTPU_DEADLINE_MS", 0.0) if deadline_ms is None else deadline_ms
        )
        self.retries = (
            int(_env_float("CMTPU_RETRIES", 2)) if retries is None else retries
        )
        self.backoff_ms = (
            _env_float("CMTPU_BACKOFF_MS", 50.0) if backoff_ms is None else backoff_ms
        )
        self.breaker_threshold = (
            int(_env_float("CMTPU_BREAKER_THRESHOLD", 3))
            if breaker_threshold is None
            else breaker_threshold
        )
        self.breaker_cooldown_ms = (
            _env_float("CMTPU_BREAKER_COOLDOWN_MS", 5000.0)
            if breaker_cooldown_ms is None
            else breaker_cooldown_ms
        )
        self.crosscheck = (
            os.environ.get("CMTPU_CROSSCHECK", "sample")
            if crosscheck is None
            else crosscheck
        )
        if self.crosscheck not in ("off", "sample", "full"):
            raise ValueError(f"unknown CMTPU_CROSSCHECK {self.crosscheck!r}")
        self._lock = threading.Lock()  # breaker state + counters
        self._jitter = random.Random()  # retry jitter; no determinism contract
        self.counters_ = {
            "calls": 0,
            "degraded_calls": 0,
            "retries": 0,
            "deadline_exceeded": 0,
            "trips": 0,
            "crosscheck_catches": 0,
        }
        # The anchor's host tier doubles as the cross-check ground truth.
        self._cpu = self.tiers[-1].backend

    # -- breaker ----------------------------------------------------------

    def _admit(self, tier: _Tier) -> bool:
        """closed -> yes; open -> only once the cooldown elapsed (tier goes
        half-open and this call is the probe)."""
        with self._lock:
            if tier.state == _CLOSED:
                return True
            if (self._clock.now() - tier.opened_at) * 1000 < self.breaker_cooldown_ms:
                return False
            tier.state = _HALF_OPEN
            return True

    def _record_success(self, tier: _Tier) -> None:
        with self._lock:
            tier.consecutive_failures = 0
            tier.state = _CLOSED

    def _record_failure(self, tier: _Tier) -> None:
        with self._lock:
            tier.failures += 1
            tier.consecutive_failures += 1
            reopen = tier.state == _HALF_OPEN
            if reopen or tier.consecutive_failures >= self.breaker_threshold:
                if tier.state != _OPEN:
                    tier.trips += 1
                    self.counters_["trips"] += 1
                tier.state = _OPEN
                tier.opened_at = self._clock.now()
                tier.consecutive_failures = 0

    def _probe(self, tier: _Tier) -> bool:
        """Half-open recovery probe: the sidecar `Ping` RPC when the tier
        speaks it, else admit the real call as the probe."""
        ping = getattr(tier.backend, "ping", None)
        if ping is None:
            return True
        try:
            if self.deadline_ms > 0:
                return bool(tier.worker.run(ping, self.deadline_ms / 1000.0))
            return bool(ping())
        except Exception:
            return False

    # -- call protocol ----------------------------------------------------

    def _run_on(self, tier: _Tier, fn, *, anchored: bool):
        """One tier attempt with deadline + bounded jittered-backoff retry.
        The anchor runs inline and un-deadlined: it is the liveness floor,
        and with nowhere left to degrade a timeout would only convert a
        slow correct answer into no answer."""
        attempt = 0
        while True:
            try:
                if anchored or self.deadline_ms <= 0:
                    return fn()
                return tier.worker.run(fn, self.deadline_ms / 1000.0)
            except DeadlineExceeded:
                with self._lock:
                    self.counters_["deadline_exceeded"] += 1
                raise
            except _TRANSIENT:
                if attempt >= self.retries:
                    raise
                attempt += 1
                with self._lock:
                    self.counters_["retries"] += 1
                base = self.backoff_ms * (2 ** (attempt - 1))
                self._clock.sleep((base + self._jitter.uniform(0, base)) / 1000.0)

    def _call(self, op_name: str, fn_for, crosscheckable: bool = False):
        """Walk the chain: first admitted tier that answers wins.  `fn_for`
        maps a tier backend to the zero-arg call."""
        with self._lock:
            self.counters_["calls"] += 1
        last_err: Exception | None = None
        for i, tier in enumerate(self.tiers):
            anchored = i == len(self.tiers) - 1
            if not self._admit(tier):
                continue
            if tier.state == _HALF_OPEN and not self._probe(tier):
                self._record_failure(tier)  # reopens, restarts cooldown
                continue
            tier.calls += 1
            try:
                result = self._run_on(
                    tier, fn_for(tier.backend), anchored=anchored
                )
            except Exception as e:
                last_err = e
                self._record_failure(tier)
                continue
            if crosscheckable and not anchored and self.crosscheck != "off":
                caught, result = self._crosscheck(tier, result)
                if caught:
                    continue  # tier failed the ground truth; keep walking
            self._record_success(tier)
            if i > 0:
                with self._lock:
                    self.counters_["degraded_calls"] += 1
            return result
        raise ChainExhausted(
            f"{op_name}: every tier failed "
            f"({', '.join(t.name for t in self.tiers)})"
        ) from last_err

    # -- cross-check ------------------------------------------------------

    def _crosscheck(self, tier: _Tier, served):
        """Re-verify a deterministic sample (or all) of a non-anchor tier's
        batch_verify result on the host path.  Any disagreement counts as a
        tier failure — a false-accept must trip the breaker, not ship."""
        ok, bits, pubs, msgs, sigs = served
        n = len(pubs)
        if n == 0:
            return False, (ok, bits)
        if self.crosscheck == "full":
            idx = range(n)
        else:
            # Sample indices from the batch content, not a clock or RNG:
            # the same batch cross-checks the same lanes on every host.
            h = hashlib.sha256(b"".join(sigs[:64]) + n.to_bytes(4, "big"))
            rng = random.Random(h.digest())
            idx = sorted(rng.sample(range(n), min(32, n)))
        s_pubs = [pubs[i] for i in idx]
        s_msgs = [msgs[i] for i in idx]
        s_sigs = [sigs[i] for i in idx]
        _, truth_bits = self._cpu.batch_verify(s_pubs, s_msgs, s_sigs)
        if all(bits[i] == t for i, t in zip(idx, truth_bits)):
            return False, (ok, bits)
        with self._lock:
            self.counters_["crosscheck_catches"] += 1
        self._record_failure(tier)
        return True, None

    # -- VerifyBackend surface --------------------------------------------

    def batch_verify(self, pubs, msgs, sigs):
        def fn_for(backend):
            def call():
                ok, bits = backend.batch_verify(pubs, msgs, sigs)
                return ok, bits, pubs, msgs, sigs

            return call

        ok, bits, *_ = self._call("batch_verify", fn_for, crosscheckable=True)
        return ok, bits

    def aggregate_verify(self, pubs, msgs, agg_sig) -> bool:
        """One boolean over a whole aggregate-BLS commit (bn254 chain).

        Same walk as batch_verify — deadline, retry, breaker — but tiers
        that don't speak the verb are SKIPPED, not failed (the verb must
        not trip breakers on a chain that never advertised it). The
        crosscheck differs by necessity: an aggregate verdict has no
        per-lane sample granularity, so any non-off CMTPU_CROSSCHECK
        recomputes the WHOLE check on the anchor when a non-anchor tier
        served it — a flipped accept from a sick tier is caught, counted,
        and trips the tier, exactly like a bitmap flip would be."""
        with self._lock:
            self.counters_["calls"] += 1
        last_err: Exception | None = None
        speakers = [
            (i, t)
            for i, t in enumerate(self.tiers)
            if getattr(t.backend, "aggregate_verify", None) is not None
        ]
        if not speakers:
            raise ChainExhausted("aggregate_verify: no tier speaks the verb")
        for j, (i, tier) in enumerate(speakers):
            anchored = j == len(speakers) - 1
            if not self._admit(tier):
                continue
            if tier.state == _HALF_OPEN and not self._probe(tier):
                self._record_failure(tier)
                continue
            tier.calls += 1
            try:
                result = self._run_on(
                    tier,
                    lambda b=tier.backend: b.aggregate_verify(pubs, msgs, agg_sig),
                    anchored=anchored,
                )
            except Exception as e:
                last_err = e
                self._record_failure(tier)
                continue
            if not anchored and self.crosscheck != "off":
                anchor = speakers[-1][1].backend
                if bool(result) != bool(
                    anchor.aggregate_verify(pubs, msgs, agg_sig)
                ):
                    with self._lock:
                        self.counters_["crosscheck_catches"] += 1
                    self._record_failure(tier)
                    continue
            self._record_success(tier)
            if i > 0:
                with self._lock:
                    self.counters_["degraded_calls"] += 1
            return bool(result)
        raise ChainExhausted(
            "aggregate_verify: every tier failed "
            f"({', '.join(t.name for _, t in speakers)})"
        ) from last_err

    def merkle_root(self, leaves):
        return self._call(
            "merkle_root", lambda backend: lambda: backend.merkle_root(leaves)
        )

    def mesh_width(self) -> int:
        """Widest mesh any tier currently willing to serve can reach —
        local chips (hybrid/tpu tiers), a remote pod's (the grpc tier's
        Ping capability reply), or a whole fleet's (the fanout tier reports
        the SUM of its shards' widths, because shards verify concurrently
        while chain tiers are alternatives). The coalescer and engine size
        their merge caps from this.

        A tier whose breaker is open inside its cooldown is SKIPPED without
        touching its backend — a tripped grpc tier must not be dialed just
        to read its width — and every successful read is cached on the tier
        (`tier.width`), so a tier that errors on the read keeps reporting
        its last known width instead of vanishing from the estimate."""
        width = 1
        now = self._clock.now()
        for tier in self.tiers:
            with self._lock:
                tripped = tier.state == _OPEN and (
                    (now - tier.opened_at) * 1000 < self.breaker_cooldown_ms
                )
            if not tripped:
                mw = getattr(tier.backend, "mesh_width", None)
                if mw is not None:
                    try:
                        tier.width = max(1, int(mw()))
                    except Exception:
                        pass  # keep the cached width
                if tier.width:
                    width = max(width, tier.width)
        return width

    def ping(self) -> bool:
        return bool(
            self._call(
                "ping",
                lambda backend: (
                    getattr(backend, "ping", None) or (lambda: True)
                ),
            )
        )

    # -- observability ----------------------------------------------------

    @property
    def active_tier(self) -> str:
        """First tier currently willing to take a call."""
        now = self._clock.now()
        with self._lock:
            for tier in self.tiers:
                if tier.state != _OPEN or (
                    (now - tier.opened_at) * 1000 >= self.breaker_cooldown_ms
                ):
                    return tier.name
            return self.tiers[-1].name

    @property
    def active_tier_index(self) -> int:
        name = self.active_tier
        return next(i for i, t in enumerate(self.tiers) if t.name == name)

    def counters(self) -> dict:
        with self._lock:
            out = dict(self.counters_)
        out["active_tier"] = self.active_tier
        out["chain"] = [t.name for t in self.tiers]
        out["tiers"] = {}
        for t in self.tiers:
            entry = {
                "state": t.state,
                "calls": t.calls,
                "failures": t.failures,
                "trips": t.trips,
                "width": t.width,
            }
            # Tier backends with their own counters (the grpc client's
            # streamed/unary split, a chaos wrapper's injections) surface
            # them here so one snapshot explains the whole chain.
            tc = getattr(t.backend, "counters", None)
            if tc is not None:
                try:
                    entry["backend"] = tc()
                except Exception:
                    pass
            out["tiers"][t.name] = entry
        return out

    def register_metrics(self, registry) -> None:
        """backend_* gauges on a libs.metrics Registry (node/node.py wires
        this into the /metrics endpoint). active_tier is the chain index:
        0 = primary, rising as the chain degrades."""
        registry.gauge_func(
            "backend", "trips", "Circuit-breaker trips.",
            lambda: self.counters_["trips"],
        )
        registry.gauge_func(
            "backend", "retries", "Transient-error retries.",
            lambda: self.counters_["retries"],
        )
        registry.gauge_func(
            "backend", "deadline_exceeded", "Tier calls past CMTPU_DEADLINE_MS.",
            lambda: self.counters_["deadline_exceeded"],
        )
        registry.gauge_func(
            "backend", "active_tier",
            "Chain index of the serving tier (0 = primary).",
            lambda: self.active_tier_index,
        )

    def close(self) -> None:
        for tier in self.tiers:
            close = getattr(tier.backend, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:
                    pass


# -- chain assembly -----------------------------------------------------------


def build_chain() -> list[tuple[str, VerifyBackend]]:
    """The `fanout|grpc|tpu -> hybrid -> cpu` degradation order, from what
    this process can actually reach:

    * the fleet tier first, when `CMTPU_FANOUT_PEERS` names sidecar peers
      (sidecar/fanout.py — the widest tier; the local device tier rides it
      as the `local` shard so its chips count toward the fleet width);
    * a single-sidecar tier, when `CMTPU_SIDECAR_ADDR` names one;
    * the device tier `device_backend("auto")` selected (hybrid with an
      accelerator visible, nothing extra otherwise);
    * hybrid's own host tier as an intermediate when the device tier is
      hybrid (a tripped device still leaves the MSM path);
    * `CpuBackend` as the anchor — always present, always last.

    `CMTPU_FAULTS` wraps every non-anchor tier in ChaosBackend; on a chain
    with no non-anchor tier (cpu-only host) a chaos-wrapped cpu tier is
    *inserted* as the primary, so fault-injection runs still exercise
    degradation with the anchor kept clean.
    """
    from cometbft_tpu.sidecar.chaos import ChaosBackend, faults_from_env

    tiers: list[tuple[str, VerifyBackend]] = []
    primary = device_backend("auto")
    from cometbft_tpu.sidecar.fanout import build_fanout

    fan = build_fanout(primary if isinstance(primary, HybridBackend) else None)
    if fan is not None:
        tiers.append(("fanout", fan))
    addr = os.environ.get("CMTPU_SIDECAR_ADDR", "").strip()
    if addr:
        from cometbft_tpu.sidecar.service import GrpcBackend

        deadline_ms = _env_float("CMTPU_DEADLINE_MS", 0.0)
        timeout_s = deadline_ms / 1000.0 if deadline_ms > 0 else 300.0
        tiers.append(("grpc", GrpcBackend(addr, timeout_s=timeout_s)))
    if isinstance(primary, HybridBackend):
        tiers.append(("hybrid", primary))
    anchor = primary if isinstance(primary, CpuBackend) else CpuBackend()
    faults = faults_from_env()
    if faults:
        seed = int(_env_float("CMTPU_FAULTS_SEED", 0))
        tiers = [
            (name, ChaosBackend(b, faults, seed=seed + i))
            for i, (name, b) in enumerate(tiers)
        ]
        if not tiers:
            tiers.append(("chaos", ChaosBackend(CpuBackend(), faults, seed=seed)))
    tiers.append(("cpu", anchor))
    return tiers


def build_resilient() -> ResilientBackend:
    """The supervised chain `get_backend()` serves under CMTPU_BACKEND=auto."""
    return ResilientBackend(build_chain())

"""The verification sidecar: pluggable crypto backends (CPU, in-process TPU,
remote gRPC sidecar). This is the device-tier entry point selected through the
`crypto.BatchVerifier` seam (reference: crypto/crypto.go:46-54)."""

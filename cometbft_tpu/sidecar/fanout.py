"""Multi-host verification fan-out: one logical verifier across N sidecars.

Every sharded path below this layer keys on `jax.local_devices()` — one
host.  `FanoutBackend` is the fleet seam: it makes N streaming sidecars
(plus, optionally, this host's own device tier) look like ONE wide
`VerifyBackend`.  A merged columnar batch is split into contiguous
per-shard slices weighted by each shard's Ping-advertised mesh width, the
slices are dispatched concurrently over the existing v2 chunk-stream
protocol (`sidecar/service.py`), and the bitmap is reassembled exactly —
lane i of the answer is lane i of the request, whichever host verified it.

Failure is handled per shard, not per fleet: a dead or wedged shard's
slice is redistributed across the surviving shards (ONE retry round)
before the error escapes to the supervisor, so one sick host costs a
re-dispatch, not the whole dispatch.  Only when the retry round also
fails — or no shard is healthy at all — does the call raise and the
supervised chain degrade to the local tiers.

Width is a SUM here, not a max: the fleet's capacity is the total number
of chips behind all shards, and `mesh_width()` reports exactly that so the
engine's merge cap (16384 x width) and deadline sizing grow through the
combined fleet.  The supervisor's chain-level `mesh_width()` takes the max
ACROSS tiers because its tiers are alternatives (grpc OR hybrid OR cpu
serves a call); the fanout's shards verify CONCURRENTLY, so within this
tier the widths add.

Knobs (all read at construction):

* `CMTPU_FANOUT_PEERS`   — comma-separated `host:port` sidecars; setting
  it under `CMTPU_BACKEND=auto` puts the fanout tier at the head of the
  supervised chain (supervisor.build_chain).
* `CMTPU_FANOUT_DEADLINE_MS` — per-round slice deadline before a shard is
  declared wedged and its slice redistributed (default: `CMTPU_DEADLINE_MS`
  when set, else 30000).  Each of the two rounds gets a fresh window.
* `CMTPU_FANOUT_COOLDOWN_MS` — how long a failed shard sits out before
  the next dispatch tries it again (default 5000; the dispatch itself is
  the probe, mirroring the supervisor's half-open protocol).
"""

from __future__ import annotations

import os
import threading
import time

from cometbft_tpu.sidecar.backend import VerifyBackend


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ShardFailed(Exception):
    """A shard slice got no (usable) answer this round."""


class _Shard:
    """One member of the fleet: a remote GrpcBackend or the local tier."""

    def __init__(self, name: str, backend: VerifyBackend):
        self.name = name
        self.backend = backend
        self.width = 1  # last known; refreshed from ping/mesh_width
        self.down_until = 0.0
        self.calls = 0
        self.failures = 0
        self.last_error = ""

    def healthy(self, now: float) -> bool:
        return now >= self.down_until

    def read_width(self) -> int:
        """Cached-width read — never dials (GrpcBackend.mesh_width returns
        the width the last Ping capability reply advertised)."""
        mw = getattr(self.backend, "mesh_width", None)
        if mw is not None:
            try:
                self.width = max(1, int(mw()))
            except Exception:
                pass
        return self.width


class FanoutBackend(VerifyBackend):
    """N sidecar shards (plus the local tier) as one wide VerifyBackend."""

    name = "fanout"

    def __init__(
        self,
        shards: list[tuple[str, VerifyBackend]],
        deadline_ms: float | None = None,
        cooldown_ms: float | None = None,
    ):
        if not shards:
            raise ValueError("FanoutBackend needs at least one shard")
        self.shards = [_Shard(n, b) for n, b in shards]
        if deadline_ms is None:
            deadline_ms = _env_float(
                "CMTPU_FANOUT_DEADLINE_MS",
                _env_float("CMTPU_DEADLINE_MS", 0.0) or 30000.0,
            )
        self.deadline_ms = max(1.0, deadline_ms)
        self.cooldown_ms = (
            _env_float("CMTPU_FANOUT_COOLDOWN_MS", 5000.0)
            if cooldown_ms is None
            else cooldown_ms
        )
        self._lock = threading.Lock()
        self._probed = False
        self.counters_ = {
            "dispatches": 0,
            "shard_calls": 0,
            "shard_failures": 0,
            "redistributions": 0,
            "redistributed_sigs": 0,
        }
        # Engine rate-model seam (duck-typed like HybridBackend's): the
        # fleet dispatches slices concurrently, so its throughput is the
        # per-chip rate x the TOTAL chip count behind all shards.
        self._dev_rate = _env_float("CMTPU_DEV_RATE", 100.0)
        self._dev_overhead = _env_float("CMTPU_DEV_OVERHEAD_MS", 8.0)

    @property
    def _n_dev(self) -> int:
        return self.mesh_width()

    # -- fleet shape -------------------------------------------------------

    def mesh_width(self) -> int:
        """SUM of shard widths — the fleet verifies slices concurrently, so
        capacity adds across shards (see module docstring).  Cached widths
        only; nothing is dialed from here."""
        return sum(max(1, s.width) for s in self.shards)

    def shard_widths(self) -> dict[str, int]:
        return {s.name: max(1, s.width) for s in self.shards}

    def refresh_widths(self, dial: bool = True) -> None:
        """Learn each shard's width.  With `dial`, shards that speak `ping`
        are pinged concurrently (the Ping capability reply is where a
        sidecar advertises its mesh width); failures put the shard on
        cooldown instead of raising.  Without, only cached widths move."""
        if not dial:
            for s in self.shards:
                s.read_width()
            return

        def probe(s: _Shard) -> None:
            ping = getattr(s.backend, "ping", None)
            try:
                if ping is not None and not ping():
                    raise ConnectionError("ping returned false")
            except Exception as e:
                self._mark_failure(s, e)
            else:
                s.down_until = 0.0
            s.read_width()

        threads = [
            threading.Thread(target=probe, args=(s,), daemon=True)
            for s in self.shards
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self.deadline_ms / 1000.0
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        self._probed = True

    def _mark_failure(self, shard: _Shard, err: BaseException) -> None:
        with self._lock:
            shard.failures += 1
            shard.last_error = f"{type(err).__name__}: {err}"
            shard.down_until = time.monotonic() + self.cooldown_ms / 1000.0
            self.counters_["shard_failures"] += 1

    # -- slicing -----------------------------------------------------------

    def _split(self, lo: int, hi: int, shards: list[_Shard]):
        """Contiguous sub-slices of [lo, hi) weighted by shard width.  The
        widest shard absorbs rounding; empty slices are dropped (a fleet
        wider than the batch leaves the narrow tail shards idle)."""
        n = hi - lo
        total = sum(max(1, s.width) for s in shards)
        out, start, acc = [], lo, 0
        for i, s in enumerate(shards):
            acc += max(1, s.width)
            end = hi if i == len(shards) - 1 else lo + (n * acc) // total
            if end > start:
                out.append((s, start, end))
            start = end
        return out

    def _run_round(self, tasks, pubs, msgs, sigs, bits):
        """Dispatch every (shard, lo, hi) slice concurrently; fill `bits`
        in place; return the slices that got no usable answer within this
        round's deadline.  A thread past the deadline is abandoned, not
        joined — its shard sits out the cooldown and any late answer is
        discarded with the thread."""
        results: list = [None] * len(tasks)

        def call(i: int, shard: _Shard, lo: int, hi: int) -> None:
            try:
                ok, slice_bits = shard.backend.batch_verify(
                    pubs[lo:hi], msgs[lo:hi], sigs[lo:hi]
                )
                if len(slice_bits) != hi - lo:
                    raise ShardFailed(
                        f"shard {shard.name}: {len(slice_bits)} bits "
                        f"for a {hi - lo}-lane slice"
                    )
                results[i] = list(slice_bits)
            except BaseException as e:
                results[i] = e

        threads = []
        for i, (shard, lo, hi) in enumerate(tasks):
            with self._lock:
                shard.calls += 1
                self.counters_["shard_calls"] += 1
            t = threading.Thread(
                target=call, args=(i, shard, lo, hi), daemon=True,
                name=f"fanout-{shard.name}",
            )
            t.start()
            threads.append(t)
        deadline = time.monotonic() + self.deadline_ms / 1000.0
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        failed = []
        for (shard, lo, hi), res in zip(tasks, results):
            if isinstance(res, list):
                bits[lo:hi] = res
                shard.down_until = 0.0
            else:
                err = res if isinstance(res, BaseException) else (
                    TimeoutError(
                        f"no answer within {self.deadline_ms:.0f} ms"
                    )
                )
                self._mark_failure(shard, err)
                failed.append((shard, lo, hi, err))
        return failed

    # -- VerifyBackend surface ---------------------------------------------

    def batch_verify(self, pubs, msgs, sigs):
        n = len(pubs)
        if n == 0:
            return False, []
        if not self._probed:
            self.refresh_widths()
        with self._lock:
            self.counters_["dispatches"] += 1
        now = time.monotonic()
        live = [s for s in self.shards if s.healthy(now)]
        if not live:
            raise ConnectionError(
                "fanout: no healthy shard "
                f"({', '.join(s.name for s in self.shards)} all cooling down)"
            )
        bits: list = [False] * n
        tasks = self._split(0, n, live)
        failed = self._run_round(tasks, pubs, msgs, sigs, bits)
        if failed:
            # Redistribute the dead shards' slices across the survivors —
            # one retry round, then the supervisor takes over.
            bad = {id(s) for s, *_ in failed}
            survivors = [s for s in live if id(s) not in bad]
            if survivors:
                with self._lock:
                    self.counters_["redistributions"] += 1
                    self.counters_["redistributed_sigs"] += sum(
                        hi - lo for _, lo, hi, _ in failed
                    )
                retry_tasks = []
                for _, lo, hi, _ in failed:
                    retry_tasks.extend(self._split(lo, hi, survivors))
                failed = self._run_round(retry_tasks, pubs, msgs, sigs, bits)
        if failed:
            shard, lo, hi, err = failed[0]
            raise ConnectionError(
                f"fanout: {len(failed)} slice(s) unserved after "
                f"redistribution (shard {shard.name}, lanes "
                f"[{lo}:{hi}]): {err}"
            )
        return all(bits), bits

    def merkle_root(self, leaves):
        """One tree per call — no slicing opportunity; serve from the first
        healthy shard, walking on failure."""
        now = time.monotonic()
        last: BaseException | None = None
        ordered = [s for s in self.shards if s.healthy(now)] or self.shards
        for shard in ordered:
            with self._lock:
                shard.calls += 1
                self.counters_["shard_calls"] += 1
            try:
                root = shard.backend.merkle_root(leaves)
            except Exception as e:
                last = e
                self._mark_failure(shard, e)
                continue
            shard.down_until = 0.0
            return root
        raise ConnectionError("fanout: merkle_root failed on every shard") from last

    def ping(self) -> bool:
        """Fleet probe: refresh widths (dialing), true when ANY shard is
        up — the fanout can serve with survivors, so one live shard keeps
        the tier in the chain."""
        self.refresh_widths(dial=True)
        now = time.monotonic()
        return any(s.healthy(now) for s in self.shards)

    def counters(self) -> dict:
        with self._lock:
            out = dict(self.counters_)
        out["mesh_width"] = self.mesh_width()
        out["shards"] = {
            s.name: {
                "width": max(1, s.width),
                "calls": s.calls,
                "failures": s.failures,
                "down": not s.healthy(time.monotonic()),
                "last_error": s.last_error,
            }
            for s in self.shards
        }
        return out

    def close(self) -> None:
        for s in self.shards:
            close = getattr(s.backend, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:
                    pass


def fanout_peers() -> list[str]:
    """The `CMTPU_FANOUT_PEERS` fleet, parsed."""
    raw = os.environ.get("CMTPU_FANOUT_PEERS", "").strip()
    return [p.strip() for p in raw.split(",") if p.strip()]


def build_fanout(local: VerifyBackend | None = None) -> FanoutBackend | None:
    """The fleet tier `supervisor.build_chain` puts at the head of the
    chain when `CMTPU_FANOUT_PEERS` names peers: one GrpcBackend shard per
    peer, plus this host's own device tier as the `local` shard when the
    chain has one (its chips count toward the fleet width and its slice
    rides the same concurrent dispatch)."""
    peers = fanout_peers()
    if not peers:
        return None
    from cometbft_tpu.sidecar.service import GrpcBackend

    deadline_ms = _env_float(
        "CMTPU_FANOUT_DEADLINE_MS",
        _env_float("CMTPU_DEADLINE_MS", 0.0) or 30000.0,
    )
    shards: list[tuple[str, VerifyBackend]] = [
        (f"peer{i}", GrpcBackend(addr, timeout_s=deadline_ms / 1000.0))
        for i, addr in enumerate(peers)
    ]
    if local is not None:
        shards.append(("local", local))
    return FanoutBackend(shards)

"""Continuous-batching verification engine (round 14).

Until this round FOUR independent micro-batch windows fed the device tier —
the coalescer (sidecar/scheduler.py), vote admission (crypto/sigbatch.py),
ingress preverify (mempool/ingress.py) and the gateway prewarm
(light/gateway.py) — each with its own window knob, queue, dispatcher
thread and fallback path, each holding work the others could ride with.
This module is the one engine they all feed, run the way inference servers
run their device (vLLM/Orca continuous batching):

* There is no window-then-dispatch. The dispatcher sizes the next dispatch
  from whatever is queued THE MOMENT the device frees up — a burst's first
  request pays only the in-flight dispatch, never a fixed window. (A
  compat hold, `hold_ms`, reproduces the old window-from-first-waiter
  behavior for the CoalescingScheduler shim and its tests; the engine
  default is 0.)
* Requests carry a PRIORITY CLASS — consensus votes > blocksync > ingress
  preverify > light clients — drained strict-priority with a starvation
  escape hatch: any request older than `CMTPU_ENGINE_STARVATION_MS` is
  promoted ahead of fresher higher-class work, so a consensus flood can
  delay a light client but never park it forever.
* Dispatch sizing is DEADLINE-AWARE: a queued consensus request caps how
  large the merged dispatch may grow, using the hybrid planner's rate
  model (sigs/ms x chips + fixed overhead) to predict the dispatch wall —
  bulk work never drags a vote past its admission deadline.
* Fallback/crosscheck/degradation remain ONE story: the engine dispatches
  through whatever chain it wraps (normally `build_resilient()`'s
  supervisor), keeps the columnar pack + within-batch dedup + per-request
  bitmap slicing of the round-6 coalescer verbatim, and splits a failed
  merged dispatch into per-request retries so a poisoned request errors
  alone.

Callers tag their class either explicitly (`engine.submit(..., klass=...)`)
or ambiently via `submission_class(...)` — a threadlocal the engine reads
for traffic that reaches it through `ed25519.BatchVerifier` and the
backend chain without any API change (ingress preverify, gateway prewarm,
blocksync windows). Untagged traffic is blocksync-class: the middle of the
ladder, below votes, above opportunistic prewarm.

Knobs: `CMTPU_ENGINE_HOLD_MS` (compat hold, default 0 = continuous),
`CMTPU_ENGINE_MAX` (merge cap, default 16384 x mesh width, auto caps
grow-only via refresh_cap), `CMTPU_ENGINE_STARVATION_MS` (promotion age,
default 100), `CMTPU_ENGINE_DEADLINE_MS` (consensus admission deadline,
default `CMTPU_DEADLINE_MS` else 50), `CMTPU_ENGINE_RATE` /
`CMTPU_ENGINE_OVERHEAD_MS` (fallback dispatch-wall model when no hybrid
tier is present to read rates from).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time

from cometbft_tpu.sidecar.backend import VerifyBackend

# Priority classes, drained strict-priority (lower value wins).
CLASS_CONSENSUS = 0  # vote admission / commit verification on the hot path
CLASS_BLOCKSYNC = 1  # block-window pre-verify, untagged legacy callers
CLASS_INGRESS = 2    # mempool envelope preverify
CLASS_LIGHT = 3      # light-client speculative prewarm

CLASS_NAMES = ("consensus", "blocksync", "ingress", "light")
_N_CLASSES = len(CLASS_NAMES)

_WAIT_SAMPLES = 512  # admission-wait ring buffer (p50/p95 source)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _mesh_width_for_cap() -> int:
    """Device count behind the default dispatch cap (16384 x width), read
    WITHOUT risking a device-tunnel probe from this constructor: use the
    kernel's already-probed width when available (the auto chain constructs
    its device tier — which probes — before this layer), and only probe
    ourselves when JAX is pinned to the local CPU backend with a forced
    virtual device count (the test/dryrun mesh). Everywhere else the probe
    could hang a node start behind a wedged axon tunnel, and a cpu-only
    deployment shouldn't pay a jax import for a cap it can't use."""
    ek = sys.modules.get("cometbft_tpu.ops.ed25519_kernel")
    if ek is not None and ek.known_mesh_width():
        return ek.known_mesh_width()
    if (
        os.environ.get("JAX_PLATFORMS", "") == "cpu"
        and "xla_force_host_platform_device_count"
        in os.environ.get("XLA_FLAGS", "")
    ):
        try:
            from cometbft_tpu.ops import ed25519_kernel as ek2

            return ek2.mesh_width()
        except Exception:
            return 1
    return 1


# -- ambient class tagging ----------------------------------------------------

_tls = threading.local()


@contextlib.contextmanager
def submission_class(klass: int):
    """Tag every engine submission made on this thread inside the block.

    This is how surfaces that reach the engine through BatchVerifier and
    the backend chain (ingress, gateway, blocksync) declare their class
    without threading a parameter through crypto-layer APIs."""
    prev = getattr(_tls, "klass", None)
    _tls.klass = klass
    try:
        yield
    finally:
        _tls.klass = prev


def current_class() -> int:
    k = getattr(_tls, "klass", None)
    return CLASS_BLOCKSYNC if k is None else k


def engine_of(backend) -> "VerificationEngine | None":
    """The engine behind a backend, if one is active: the backend itself,
    or the one the CoalescingScheduler shim embeds. None for a bare chain
    (`CMTPU_COALESCE=0`) or a test-installed backend — callers keep their
    legacy private-dispatcher paths in that case."""
    if isinstance(backend, VerificationEngine):
        return backend
    eng = getattr(backend, "engine", None)
    return eng if isinstance(eng, VerificationEngine) else None


class VerifyFuture:
    """Result slot a submitter blocks on; filled by the dispatcher.

    `shared` reports (after resolution) whether the request rode a merged
    dispatch — surfaces use it for their legacy "batched" counters."""

    __slots__ = ("_event", "_result", "_error", "t_submit", "n_sigs", "shared")

    def __init__(self, n_sigs: int):
        self._event = threading.Event()
        self._result: tuple[bool, list[bool]] | None = None
        self._error: BaseException | None = None
        self.t_submit = time.perf_counter()
        self.n_sigs = n_sigs
        self.shared = False

    def _set_result(self, result: tuple[bool, list[bool]]) -> None:
        self._result = result
        self._event.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> tuple[bool, list[bool]]:
        if not self._event.wait(timeout):
            raise TimeoutError("verification future not resolved in time")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("pubs", "msgs", "sigs", "future", "klass", "deadline", "t_start")

    def __init__(self, pubs, msgs, sigs, future, klass, deadline):
        self.pubs = pubs
        self.msgs = msgs
        self.sigs = sigs
        self.future = future
        self.klass = klass
        self.deadline = deadline  # absolute perf_counter deadline or None
        self.t_start = 0.0  # set when the dispatcher picks it up


class VerificationEngine(VerifyBackend):
    """Continuous-batching front of the verification chain (module docstring)."""

    name = "engine"

    def __init__(
        self,
        inner: VerifyBackend,
        hold_ms: float | None = None,
        max_sigs: int | None = None,
        starvation_ms: float | None = None,
        deadline_ms: float | None = None,
    ):
        self.inner = inner
        # Compat hold: the round-6 window-from-first-waiter, kept for the
        # CoalescingScheduler shim. 0 = true continuous batching.
        self.hold_ms = (
            _env_float("CMTPU_ENGINE_HOLD_MS", 0.0)
            if hold_ms is None
            else hold_ms
        )
        self.starvation_ms = (
            _env_float("CMTPU_ENGINE_STARVATION_MS", 100.0)
            if starvation_ms is None
            else starvation_ms
        )
        # Consensus admission deadline: a queued vote must be RESOLVED
        # within this budget, so it caps merged-dispatch growth. Derived
        # from the supervisor's per-call deadline when one is configured.
        if deadline_ms is None:
            deadline_ms = _env_float(
                "CMTPU_ENGINE_DEADLINE_MS",
                _env_float("CMTPU_DEADLINE_MS", 0.0) or 50.0,
            )
        self.consensus_deadline_ms = deadline_ms
        self._cap_auto = False
        if max_sigs is not None:
            self.max_sigs = max_sigs
        elif os.environ.get("CMTPU_ENGINE_MAX", ""):
            self.max_sigs = int(_env_float("CMTPU_ENGINE_MAX", 16384))
        else:
            # Pod-width default: one merged dispatch can fill every chip
            # (16384 lanes each). An explicit env or arg always wins. The
            # auto cap re-reads the chain's width periodically
            # (refresh_cap) because the width a grpc tier serves is only
            # learned from the sidecar's Ping capability reply AFTER the
            # first connect.
            self._cap_auto = True
            self.max_sigs = 16384 * max(1, _mesh_width_for_cap())
        self._queues: list[list[_Request]] = [[] for _ in range(_N_CLASSES)]
        self._cond = threading.Condition()
        self._closed = False
        self._thread: threading.Thread | None = None
        self._wait_ms: list[float] = []  # aggregate admission-wait ring
        self._wait_i = 0
        self._class_wait: list[list[float]] = [[] for _ in range(_N_CLASSES)]
        self._class_wait_i = [0] * _N_CLASSES
        self._rate_cache: tuple[float, float] | None = None
        self.counters_ = {
            "requests": 0,
            "dispatches": 0,
            "coalesced_dispatches": 0,  # dispatches carrying >1 request
            "batched_requests": 0,      # requests that shared a dispatch
            "coalesced_sigs": 0,        # sigs that rode a shared dispatch
            "dedup_sigs": 0,            # lanes saved by within-batch dedup
            "fallback_splits": 0,       # coalesced dispatches split on error
        }
        self.class_counters_ = [
            {"admitted": 0, "dispatched_sigs": 0, "starvation_promotions": 0}
            for _ in range(_N_CLASSES)
        ]

    # -- submission surface ------------------------------------------------

    def submit(
        self,
        pubs,
        msgs,
        sigs,
        klass: int | None = None,
        deadline_ms: float | None = None,
    ) -> VerifyFuture:
        """Enqueue one verification request; returns the future its caller
        blocks on.  Raises after close() — an engine with no dispatcher
        must fail loudly, not hang the submitter forever."""
        if klass is None:
            klass = current_class()
        klass = min(max(int(klass), 0), _N_CLASSES - 1)
        fut = VerifyFuture(len(pubs))
        if not pubs:
            fut._set_result((False, []))
            return fut
        if deadline_ms is None and klass == CLASS_CONSENSUS:
            deadline_ms = self.consensus_deadline_ms
        deadline = (
            fut.t_submit + deadline_ms / 1000.0
            if deadline_ms and deadline_ms > 0
            else None
        )
        req = _Request(list(pubs), list(msgs), list(sigs), fut, klass, deadline)
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is closed")
            self.counters_["requests"] += 1
            self.class_counters_[klass]["admitted"] += 1
            self._queues[klass].append(req)
            self._ensure_thread()
            self._cond.notify_all()
        return fut

    def batch_verify(self, pubs, msgs, sigs):
        return self.submit(pubs, msgs, sigs).result()

    def aggregate_verify(self, pubs, msgs, agg_sig):
        # One boolean per whole commit: nothing to slice across callers;
        # pass straight through to the supervised chain.
        return self.inner.aggregate_verify(pubs, msgs, agg_sig)

    def merkle_root(self, leaves):
        # Roots carry no cross-caller coalescing opportunity (one tree per
        # call); pass straight through to the chain.
        return self.inner.merkle_root(leaves)

    def mesh_width(self) -> int:
        mw = getattr(self.inner, "mesh_width", None)
        return int(mw()) if mw is not None else 1

    def refresh_cap(self) -> int:
        """Re-derive the auto merge cap from the chain's CURRENT width
        (local chips, or a remote pod's once the sidecar Ping capability
        reply has been seen). Grow-only; pinned caps (arg/env) never move."""
        if self._cap_auto:
            try:
                width = max(1, self.mesh_width())
            except Exception:
                return self.max_sigs
            new_cap = 16384 * width
            if new_cap > self.max_sigs:
                self.max_sigs = new_cap
                # The width grew (a sidecar Ping reply arrived, a fanout
                # fleet came up): the dispatch-wall model must re-read
                # rates at the new device count or deadline sizing keeps
                # pricing the old, narrower chain.
                self._rate_cache = None
        return self.max_sigs

    def ping(self):
        inner_ping = getattr(self.inner, "ping", None)
        return inner_ping() if inner_ping is not None else True

    # -- dispatch-wall model -----------------------------------------------

    def _rate_model(self) -> tuple[float, float]:
        """(sigs/ms, fixed overhead ms) for one dispatch through the chain,
        read from the hybrid planner's EMA-calibrated rates when a hybrid
        tier is present (duck-typed walk — chain shapes vary by backend
        knob), else the env/default model."""
        cached = self._rate_cache
        if cached is not None:
            return cached
        rate = _env_float("CMTPU_ENGINE_RATE", 100.0)
        overhead = _env_float("CMTPU_ENGINE_OVERHEAD_MS", 8.0)
        stack = [self.inner]
        seen: set[int] = set()
        while stack:
            b = stack.pop()
            if b is None or id(b) in seen:
                continue
            seen.add(id(b))
            if hasattr(b, "_dev_rate") and hasattr(b, "_n_dev"):
                rate = float(b._dev_rate) * max(1, int(b._n_dev))
                overhead = float(getattr(b, "_dev_overhead", overhead))
                break
            # LIFO stack: push tiers reversed so the CHAIN-ORDER head pops
            # first — a fanout fleet tier must price the dispatch, not the
            # narrower hybrid tier sitting below it in the chain.
            for t in reversed(getattr(b, "tiers", ()) or ()):
                stack.append(getattr(t, "backend", None))
            stack.append(getattr(b, "inner", None))
        model = (max(rate, 1e-6), max(overhead, 0.0))
        self._rate_cache = model
        return model

    def _deadline_cap(self, now: float) -> int:
        """How many signatures the NEXT dispatch may carry without driving
        a queued consensus request past its admission deadline: predicted
        wall(overhead + n/rate) must fit the tightest remaining budget.
        Queued consensus work itself always fits (it IS the deadline's
        beneficiary; shrinking below it would only delay it further)."""
        cons = self._queues[CLASS_CONSENSUS]
        deadlines = [r.deadline for r in cons if r.deadline is not None]
        if not deadlines:
            return self.max_sigs
        budget_ms = (min(deadlines) - now) * 1000.0
        rate, overhead = self._rate_model()
        fit = int(rate * max(0.0, budget_ms - overhead))
        cons_sigs = sum(len(r.pubs) for r in cons)
        return min(self.max_sigs, max(fit, cons_sigs, 1))

    # -- dispatcher --------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="verify-engine"
            )
            self._thread.start()

    def _queued_sigs(self) -> int:
        return sum(len(r.pubs) for q in self._queues for r in q)

    def _have_work(self) -> bool:
        return any(self._queues)

    def _collect(self) -> list[_Request]:
        """Block until work exists; in compat-hold mode keep the window
        open for batchmates; then assemble the next dispatch: starvation
        promotions first (oldest first), then strict class priority, whole
        requests only up to the deadline-aware cap (first always taken)."""
        with self._cond:
            while not self._have_work() and not self._closed:
                self._cond.wait()
            if not self._have_work():
                return []
            hold_s = self.hold_ms / 1000.0
            first_t = min(q[0].future.t_submit for q in self._queues if q)
            while hold_s > 0 and not self._closed:
                if self._queued_sigs() >= self.max_sigs:
                    break
                remaining = first_t + hold_s - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            now = time.perf_counter()
            cap = self._deadline_cap(now)
            # Starvation escape hatch: requests older than starvation_ms
            # jump the class ladder (oldest first). Ages are monotone
            # within a FIFO queue, so only each queue's stale prefix needs
            # checking.
            starv_s = self.starvation_ms / 1000.0
            promoted: list[_Request] = []
            if self.starvation_ms > 0:
                for q in self._queues:
                    for r in q:
                        if now - r.future.t_submit >= starv_s:
                            promoted.append(r)
                        else:
                            break
                promoted.sort(key=lambda r: r.future.t_submit)
            promoted_ids = {id(r) for r in promoted}
            order = promoted + [
                r
                for klass in range(_N_CLASSES)
                for r in self._queues[klass]
                if id(r) not in promoted_ids
            ]
            batch: list[_Request] = []
            total = 0
            for req in order:
                n = len(req.pubs)
                if batch and total + n > cap:
                    break
                if id(req) in promoted_ids and any(
                    self._queues[k] for k in range(req.klass)
                ):
                    # Promotion only counts when the escape hatch actually
                    # bypassed fresher higher-class work.
                    self.class_counters_[req.klass]["starvation_promotions"] += 1
                self._queues[req.klass].remove(req)
                total += n
                batch.append(req)
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                return  # closed and drained
            now = time.perf_counter()
            for req in batch:
                req.t_start = now
                self._record_wait(req.klass, (now - req.future.t_submit) * 1000.0)
            try:
                self._dispatch(batch)
            except BaseException as e:  # never kill the dispatcher
                for req in batch:
                    if not req.future.done():
                        req.future._set_error(e)

    def _dispatch(self, batch: list[_Request]) -> None:
        shared = len(batch) > 1
        with self._cond:
            self.counters_["dispatches"] += 1
            for req in batch:
                req.future.shared = shared
                self.class_counters_[req.klass]["dispatched_sigs"] += len(
                    req.pubs
                )
            refresh = self._cap_auto and self.counters_["dispatches"] % 64 == 1
        if refresh:
            # Cheap cached-width read (no dial): pick up a remote pod's
            # width once the grpc tier has seen a Ping capability reply.
            try:
                self.refresh_cap()
            except Exception:
                pass
        with self._cond:
            if shared:
                self.counters_["coalesced_dispatches"] += 1
                self.counters_["batched_requests"] += len(batch)
                self.counters_["coalesced_sigs"] += sum(
                    len(r.pubs) for r in batch
                )
        if not shared:
            # Nothing to slice or protect: serve the lone request directly
            # (errors propagate to its caller alone).
            req = batch[0]
            try:
                req.future._set_result(
                    self.inner.batch_verify(req.pubs, req.msgs, req.sigs)
                )
            except BaseException as e:
                req.future._set_error(e)
            return
        # Columnar pack with within-batch dedup: identical triples from
        # concurrent requests (N light clients walking the same descent)
        # share one lane.
        lane_of: dict[tuple, int] = {}
        pubs: list[bytes] = []
        msgs: list[bytes] = []
        sigs: list[bytes] = []
        lanes: list[list[int]] = []
        for req in batch:
            req_lanes = []
            for p, m, s in zip(req.pubs, req.msgs, req.sigs):
                key = (p, s, m)
                lane = lane_of.get(key)
                if lane is None:
                    lane = len(pubs)
                    lane_of[key] = lane
                    pubs.append(p)
                    msgs.append(m)
                    sigs.append(s)
                req_lanes.append(lane)
            lanes.append(req_lanes)
        dedup = sum(len(r.pubs) for r in batch) - len(pubs)
        if dedup:
            with self._cond:
                self.counters_["dedup_sigs"] += dedup
        try:
            _, bits = self.inner.batch_verify(pubs, msgs, sigs)
        except BaseException:
            self._fallback(batch)
            return
        if len(bits) != len(pubs):
            # A sick tier answering with the wrong shape is a failed
            # dispatch, not something to mis-slice.
            self._fallback(batch)
            return
        for req, req_lanes in zip(batch, lanes):
            req_bits = [bits[lane] for lane in req_lanes]
            req.future._set_result((all(req_bits), req_bits))

    def _fallback(self, batch: list[_Request]) -> None:
        """The merged dispatch failed: retry each request alone so one
        poisoned request cannot fail its batchmates.  Per-request errors go
        to that request's caller only."""
        with self._cond:
            self.counters_["fallback_splits"] += 1
        for req in batch:
            try:
                req.future._set_result(
                    self.inner.batch_verify(req.pubs, req.msgs, req.sigs)
                )
            except BaseException as e:
                req.future._set_error(e)

    # -- observability -----------------------------------------------------

    def _record_wait(self, klass: int, ms: float) -> None:
        with self._cond:
            if len(self._wait_ms) < _WAIT_SAMPLES:
                self._wait_ms.append(ms)
            else:
                self._wait_ms[self._wait_i % _WAIT_SAMPLES] = ms
            self._wait_i += 1
            ring = self._class_wait[klass]
            if len(ring) < _WAIT_SAMPLES:
                ring.append(ms)
            else:
                ring[self._class_wait_i[klass] % _WAIT_SAMPLES] = ms
            self._class_wait_i[klass] += 1

    @staticmethod
    def _percentile(data: list[float], q: float) -> float:
        if not data:
            return 0.0
        data = sorted(data)
        idx = min(len(data) - 1, int(q * (len(data) - 1) + 0.5))
        return data[idx]

    def _wait_percentile(self, q: float) -> float:
        with self._cond:
            data = list(self._wait_ms)
        return self._percentile(data, q)

    def class_wait_p95_ms(self, klass: int) -> float:
        with self._cond:
            data = list(self._class_wait[klass])
        return self._percentile(data, 0.95)

    def counters(self) -> dict:
        with self._cond:
            out = dict(self.counters_)
            out["queue_depth"] = sum(len(q) for q in self._queues)
            classes = {
                CLASS_NAMES[k]: dict(self.class_counters_[k])
                for k in range(_N_CLASSES)
            }
        out["max_sigs"] = self.max_sigs
        d = max(1, out["dispatches"])
        out["coalesce_ratio"] = round(out["requests"] / d, 3)
        out["queue_wait_p50_ms"] = round(self._wait_percentile(0.50), 3)
        out["queue_wait_p95_ms"] = round(self._wait_percentile(0.95), 3)
        for k in range(_N_CLASSES):
            classes[CLASS_NAMES[k]]["p95_us"] = int(
                self.class_wait_p95_ms(k) * 1000
            )
        out["classes"] = classes
        inner_counters = getattr(self.inner, "counters", None)
        if inner_counters is not None:
            out["inner"] = inner_counters()
        return out

    def register_metrics(self, registry) -> None:
        """scheduler_* gauges (legacy names, dashboards keep reading) on a
        libs.metrics Registry; the per-class engine_* gauges are registered
        lazily by node/node.py so a scrape never constructs the backend."""
        registry.gauge_func(
            "scheduler", "requests", "Verification requests submitted.",
            lambda: self.counters_["requests"],
        )
        registry.gauge_func(
            "scheduler", "dispatches", "Backend dispatches issued.",
            lambda: self.counters_["dispatches"],
        )
        registry.gauge_func(
            "scheduler", "batched_requests",
            "Requests that shared a coalesced dispatch.",
            lambda: self.counters_["batched_requests"],
        )
        registry.gauge_func(
            "scheduler", "fallback_splits",
            "Coalesced dispatches split into per-request retries.",
            lambda: self.counters_["fallback_splits"],
        )
        registry.gauge_func(
            "scheduler", "coalesce_ratio_milli",
            "Requests per dispatch x1000.",
            lambda: int(
                1000 * self.counters_["requests"]
                / max(1, self.counters_["dispatches"])
            ),
        )
        registry.gauge_func(
            "scheduler", "queue_wait_p95_us",
            "95th-percentile queue wait, microseconds.",
            lambda: int(self._wait_percentile(0.95) * 1000),
        )

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        inner_close = getattr(self.inner, "close", None)
        if inner_close is not None:
            inner_close()

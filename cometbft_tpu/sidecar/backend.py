"""Crypto verification backends.

Selection (env `CMTPU_BACKEND`, default `auto`):
  - `cpu`:  host-only verification (C-speed single verifies + ZIP-215 fallback)
  - `tpu`:  in-process JAX batch kernels (TPU when available, else XLA:CPU)
  - `grpc`: remote verification sidecar over gRPC (cometbft_tpu/sidecar/service.py)
  - `auto`: `tpu` when a JAX accelerator is visible, else `cpu`

This mirrors where the reference chooses batch vs single verification
(types/validation.go:14-16, 43-50): the caller keeps its fallback path, the
backend only changes who executes the batch.
"""

from __future__ import annotations

import os
import threading


class VerifyBackend:
    """Interface for the device tier."""

    name = "abstract"

    def batch_verify(
        self, pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]
    ) -> tuple[bool, list[bool]]:
        raise NotImplementedError

    def merkle_root(self, leaves: list[bytes]) -> bytes:
        raise NotImplementedError


# Below this, per-signature OpenSSL verification beats the MSM's fixed
# costs (two decompressions per signature, window bookkeeping).
_NATIVE_BATCH_MIN = 16


class CpuBackend(VerifyBackend):
    """Host tier: the native C batch verifier (random-linear-combination
    equation over one Pippenger MSM — the same construction as the
    reference's curve25519-voi batch path, crypto/ed25519/ed25519.go:196)
    when the extension is built, per-signature OpenSSL otherwise.  Both
    preserve the (ok, per-sig bitmap) contract with ZIP-215 semantics."""

    name = "cpu"

    def __init__(self):
        from cometbft_tpu import native

        # Start the (possibly multi-second) gcc build off-thread now so the
        # first commit verification never stalls behind it; until it lands,
        # batch_verify falls through to per-signature OpenSSL.
        native.ensure_built_async()

    def batch_verify(self, pubs, msgs, sigs):
        if len(pubs) >= _NATIVE_BATCH_MIN:
            from cometbft_tpu import native

            if native.ready() is not None:
                return native.batch_verify(pubs, msgs, sigs)
        from cometbft_tpu.crypto import ed25519

        results = [
            ed25519.PubKey(p).verify_signature(m, s)
            for p, m, s in zip(pubs, msgs, sigs)
        ]
        return all(results), results

    def merkle_root(self, leaves):
        from cometbft_tpu.crypto.merkle import hash_from_byte_slices

        return hash_from_byte_slices(leaves)


class TpuBackend(VerifyBackend):
    """In-process JAX batch kernels (cometbft_tpu/ops/*)."""

    name = "tpu"

    def __init__(self):
        # Import lazily so host-only deployments never pay for JAX.
        from cometbft_tpu.ops import ed25519_kernel, merkle_kernel

        self._ed = ed25519_kernel
        self._merkle = merkle_kernel

    def batch_verify(self, pubs, msgs, sigs):
        return self._ed.batch_verify(pubs, msgs, sigs)

    def merkle_root(self, leaves):
        # Power-of-two forests take the fused single-dispatch program (one
        # host round-trip instead of 2 + log-levels); merkle_root_fused
        # falls back to the level loop for ragged counts.
        return self._merkle.merkle_root_fused(leaves)


_backend: VerifyBackend | None = None
_lock = threading.Lock()


def device_backend(choice: str = "auto") -> VerifyBackend:
    """cpu/tpu/auto selection shared by the in-process path and the sidecar
    server. auto: prefer an accelerator if one is visible; fall back to CPU
    if the device tier can't initialize rather than failing the first call."""
    if choice == "cpu":
        return CpuBackend()
    if choice == "tpu":
        return TpuBackend()
    # auto: a JAX_PLATFORMS=cpu environment means "no accelerator" without
    # importing jax at all — the axon PJRT plugin ignores the env var alone
    # and its init HANGS when the device tunnel is wedged, which would stall
    # the first commit verification of every CLI node in a CPU deployment.
    want = os.environ.get("JAX_PLATFORMS", "")
    if want == "cpu":
        return CpuBackend()
    try:
        import jax

        if want:
            jax.config.update("jax_platforms", want)
        if any(d.platform != "cpu" for d in jax.devices()):
            return TpuBackend()
    except Exception:
        pass
    return CpuBackend()


def _make_backend() -> VerifyBackend:
    choice = os.environ.get("CMTPU_BACKEND", "auto").lower()
    if choice == "grpc":
        from cometbft_tpu.sidecar.service import GrpcBackend

        return GrpcBackend(os.environ.get("CMTPU_SIDECAR_ADDR", "127.0.0.1:26670"))
    if choice not in ("auto", "cpu", "tpu"):
        raise ValueError(f"unknown CMTPU_BACKEND {choice!r}")
    return device_backend(choice)


def get_backend() -> VerifyBackend:
    global _backend
    if _backend is None:
        with _lock:
            if _backend is None:
                _backend = _make_backend()
    return _backend


def set_backend(backend: VerifyBackend | None) -> None:
    """Override the process-wide backend (tests, node bootstrap)."""
    global _backend
    with _lock:
        _backend = backend

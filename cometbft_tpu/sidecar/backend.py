"""Crypto verification backends.

Selection (env `CMTPU_BACKEND`, default `auto`):
  - `cpu`:    host-only verification (native C MSM batch + OpenSSL fallback)
  - `tpu`:    in-process JAX batch kernels (TPU when available, else XLA:CPU)
  - `hybrid`: device + host tiers concurrently — a throughput-balanced,
              bucket-aligned split of each large batch, small batches routed
              to whichever tier's cost model wins
  - `grpc`:   remote verification sidecar over gRPC (sidecar/service.py)
  - `auto`:   the SUPERVISED degradation chain (sidecar/supervisor.py):
              `grpc|tpu -> hybrid -> cpu` with per-call deadlines, bounded
              retry and per-tier circuit breakers. The device tier is
              `hybrid` whenever a JAX accelerator is visible (it degrades
              per-call to device-only until/unless the native library
              builds, so selection never blocks on gcc), else the chain is
              cpu-only.

This mirrors where the reference chooses batch vs single verification
(types/validation.go:14-16, 43-50): the caller keeps its fallback path, the
backend only changes who executes the batch.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_fallback_logged = False


def _log_fallback(reason: str) -> None:
    """One stderr line at selection time, first fallback only: the old bare
    `except Exception: pass` swallowed WHY a host silently ran cpu-only."""
    global _fallback_logged
    if not _fallback_logged:
        _fallback_logged = True
        print(f"backend: auto -> cpu ({reason})", file=sys.stderr, flush=True)


class VerifyBackend:
    """Interface for the device tier."""

    name = "abstract"

    def batch_verify(
        self, pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]
    ) -> tuple[bool, list[bool]]:
        raise NotImplementedError

    def merkle_root(self, leaves: list[bytes]) -> bytes:
        raise NotImplementedError


# Below this, per-signature OpenSSL verification beats the MSM's fixed
# costs (two decompressions per signature, window bookkeeping).
_NATIVE_BATCH_MIN = 16


class CpuBackend(VerifyBackend):
    """Host tier: the native C batch verifier (random-linear-combination
    equation over one Pippenger MSM — the same construction as the
    reference's curve25519-voi batch path, crypto/ed25519/ed25519.go:196)
    when the extension is built, per-signature OpenSSL otherwise.  Both
    preserve the (ok, per-sig bitmap) contract with ZIP-215 semantics."""

    name = "cpu"

    def __init__(self):
        from cometbft_tpu import native

        # Start the (possibly multi-second) gcc build off-thread now so the
        # first commit verification never stalls behind it; until it lands,
        # batch_verify falls through to per-signature OpenSSL.
        native.ensure_built_async()

    def batch_verify(self, pubs, msgs, sigs):
        if len(pubs) >= _NATIVE_BATCH_MIN:
            from cometbft_tpu import native

            if native.ready() is not None:
                return native.batch_verify(pubs, msgs, sigs)
        from cometbft_tpu.crypto import ed25519

        results = [
            ed25519.PubKey(p).verify_signature(m, s)
            for p, m, s in zip(pubs, msgs, sigs)
        ]
        return all(results), results

    def merkle_root(self, leaves):
        from cometbft_tpu.crypto.merkle import hash_from_byte_slices

        return hash_from_byte_slices(leaves)


class TpuBackend(VerifyBackend):
    """In-process JAX batch kernels (cometbft_tpu/ops/*)."""

    name = "tpu"

    def __init__(self):
        # Import lazily so host-only deployments never pay for JAX.
        from cometbft_tpu.ops import ed25519_kernel, merkle_kernel

        self._ed = ed25519_kernel
        self._merkle = merkle_kernel

    def batch_verify(self, pubs, msgs, sigs):
        return self._ed.batch_verify(pubs, msgs, sigs)

    def merkle_root(self, leaves):
        # Power-of-two forests take the fused single-dispatch program (one
        # host round-trip instead of 2 + log-levels); merkle_root_fused
        # falls back to the level loop for ragged counts.
        return self._merkle.merkle_root_fused(leaves)

    def mesh_width(self) -> int:
        # Safe to probe: constructing this tier already ran jax.devices().
        return self._ed.mesh_width()


class HybridBackend(VerifyBackend):
    """Device + host tiers working the same batch concurrently.

    The TPU kernel verifies ~100 sigs/ms at the 10k-commit scale but pays a
    fixed dispatch latency through the tunnel; the native host MSM
    (cometbft_tpu/native) runs ~70 sigs/ms with none. Neither dominates:
    the device wins big batches, the host wins small ones, and for the
    headline commit shape the OPTIMUM is both at once. batch_verify splits
    each large batch at a bucket-aligned point chosen by a rate model
    (EMA-updated from every measured call), dispatches the device share
    asynchronously (ed25519_kernel.batch_verify_submit), runs the host MSM
    share in the calling thread, and merges the bitmaps. Merkle roots go to
    the host SHA-NI tree (measured 10 ms vs 34 ms on device at 64k leaves,
    with no device round-trip).

    The reference has no analog — its batch verifier is single-tier
    (crypto/ed25519/ed25519.go:196-228); this is the TPU-first redesign's
    answer to owning both an accelerator and host SIMD.
    """

    name = "hybrid"

    def __init__(self):
        from cometbft_tpu import native

        self._native = native
        native.ensure_built_async()
        self._tpu = TpuBackend()
        self._cpu = CpuBackend()
        from cometbft_tpu.ops import ed25519_kernel as _ek

        # Chips one dispatch shards across: the planner prices the mesh as
        # ONE large device (per-chip rate x width, shared dispatch
        # overhead). Probing here is safe — device_backend("auto") already
        # ran jax.devices() before constructing this tier, and an explicit
        # CMTPU_BACKEND=hybrid means the operator asked for the device.
        self._n_dev = _ek.mesh_width()
        # sigs/ms PER CHIP; seeded from the first real TPU v5e stage splits
        # (tpu_bench_latest.json: verify 102 ms / 10,240 sigs device-side,
        # 147 ms native) and corrected by an EMA after every split call.
        self._dev_rate = float(os.environ.get("CMTPU_DEV_RATE", "100"))
        self._host_rate = float(os.environ.get("CMTPU_HOST_RATE", "70"))
        # Fixed per-dispatch device cost (pack + tunnel round trip), ms.
        self._dev_overhead = float(os.environ.get("CMTPU_DEV_OVERHEAD_MS", "8"))
        self._min_split = int(os.environ.get("CMTPU_HYBRID_MIN", "2048"))
        self._rate_lock = threading.Lock()
        # Compiled-program keys (batch bucket, block bucket, mesh width)
        # that have already run once in this process: the first dispatch of
        # a program can pay a multi-second XLA compile, which must not be
        # charged to the steady-state rate model.
        self._warmed: set[tuple] = set()
        # Measured device wall per (batch bucket, mesh width) — EMA,
        # straggler-observed only. The device cost is AFFINE — tens of ms
        # of fixed tunnel + dispatch latency plus a per-lane slope — so a
        # single sigs/ms rate learned at one bucket misprices every other;
        # real walls win. Width in the key so a mesh-size change (or a test
        # flipping the virtual mesh) can't reuse stale single-chip walls.
        self._dev_wall: dict[tuple[int, int], float] = {}
        # Hill-climb bias on the bucket ladder: when the device finishes
        # early its true wall is unobservable (collect() never blocks), so
        # the rate model alone can NEVER learn to grow the device share —
        # the controller shifts the split one bucket toward whichever tier
        # sat idle, bounded so a broken model can't run away.
        self._bias = 0
        # Share + stage walls of the most recent split call (observability;
        # bench reports these so device runs explain themselves).
        self.last_share = 0
        self.last_timing: dict = {}

    def _plan(self, n: int) -> int:
        """Device share (a bucket size, possibly 0=all-host or >=n=all-device)
        minimizing predicted max(device time, host time)."""
        from cometbft_tpu.ops import ed25519_kernel as ek

        # Snapshot under the lock: _update_rates inserts first-observation
        # bucket keys from straggler-collect threads, and iterating the live
        # dict here would race that insert (RuntimeError: dictionary changed
        # size during iteration) escaping into consensus/blocksync callers.
        # Only walls observed at the CURRENT mesh width apply.
        n_dev = self._n_dev
        with self._rate_lock:
            walls = {
                b: w for (b, nd), w in self._dev_wall.items() if nd == n_dev
            }
        # Mesh pricing: lanes run data-parallel across the chips, so the
        # modeled throughput is per-chip rate x width over ONE shared
        # dispatch overhead — without this an 8-chip mesh gets starved
        # with single-chip-sized shares.
        mesh_rate = self._dev_rate * n_dev

        def dev_ms(b):  # padded lanes compute like real ones
            bucket = ek.bucket_for(b)
            wall = walls.get(bucket)
            if wall is not None:
                return wall
            obs = sorted(walls.items())
            if len(obs) >= 2:
                # affine fit over the widest observed span
                (b1, w1), (b2, w2) = obs[0], obs[-1]
                slope = max((w2 - w1) / (b2 - b1), 0.0)
                return max(w1 + slope * (bucket - b1), 1.0)
            if len(obs) == 1:
                b1, w1 = obs[0]
                if bucket > b1:
                    return w1 + (bucket - b1) / mesh_rate
                # smaller buckets still pay the fixed dispatch floor
                return max(
                    w1 - (b1 - bucket) / mesh_rate, self._dev_overhead
                )
            return bucket / mesh_rate + self._dev_overhead

        def host_ms(k):
            return k / self._host_rate

        ladder = [*[b for b in ek.BUCKETS if b < n], n]
        best_b, best_cost = 0, host_ms(n)
        for b in ladder:
            cost = max(dev_ms(b), host_ms(n - b))
            if cost < best_cost:
                best_b, best_cost = b, cost
        if best_b > 0 and self._bias:
            i = ladder.index(best_b) + self._bias
            best_b = ladder[max(0, min(i, len(ladder) - 1))]
        return best_b

    def batch_verify(self, pubs, msgs, sigs):
        n = len(pubs)
        if n == 0:
            return False, []
        if n < self._min_split:
            # Small batches route host-side REGARDLESS of the native
            # build's state: below the split threshold even per-signature
            # OpenSSL (CpuBackend's own fallback) beats the tunnel's fixed
            # dispatch cost, and tiny batches carry no useful rate signal
            # and must not decay the bias learned on commit-sized ones.
            return self._cpu.batch_verify(pubs, msgs, sigs)
        if self._native.ready() is None:
            # Native tier still building (first seconds of a fresh host):
            # for commit-sized batches the device beats sequential OpenSSL.
            return self._tpu.batch_verify(pubs, msgs, sigs)
        share = self._plan(n)
        res, _ = self._routed_call(pubs, msgs, sigs, share)
        return res

    def _routed_call(self, pubs, msgs, sigs, share, between=None):
        """Execute one planned verification: all-host (share<=0), all-device
        (share>=n), or the concurrent split — the ONE copy of the
        plan->submit->host MSM->overlap->collect->rate-update protocol.
        `between` (optional) runs under the device wait (verify_and_root's
        merkle); returns ((ok, bitmap), between_result)."""
        from cometbft_tpu.ops import ed25519_kernel as ek

        n = len(pubs)
        extra = None
        if share <= 0:
            self.last_share = 0
            t0 = time.perf_counter()
            res = self._cpu.batch_verify(pubs, msgs, sigs)
            host_ms = (time.perf_counter() - t0) * 1000
            with self._rate_lock:
                if host_ms > 1:
                    r = min(max(n / host_ms, 5.0), 5000.0)
                    self._host_rate += 0.3 * (r - self._host_rate)
                self._decay_bias()
            if between is not None:
                extra = between()
            return res, extra
        share = min(share, n)
        self.last_share = share
        t0 = time.perf_counter()
        collect = ek.batch_verify_submit(pubs[:share], msgs[:share], sigs[:share])
        t_disp = time.perf_counter()
        if share < n:
            ok_h, bits_h = self._native.batch_verify(
                pubs[share:], msgs[share:], sigs[share:]
            )
        else:
            ok_h, bits_h = True, []
        t_host = time.perf_counter()
        if between is not None:
            extra = between()
        t_wait = time.perf_counter()
        ok_d, bits_d = collect()
        t_dev = time.perf_counter()
        self._update_rates(
            collect.program_key, share, n - share, t0, t_disp, t_host, t_wait, t_dev
        )
        if share < n:
            return (ok_d and ok_h, bits_d + bits_h), extra
        return (ok_d, bits_d), extra

    def _update_rates(self, key, n_dev, n_host, t0, t_disp, t_host, t_wait, t_dev):
        """EMA the rate model from what this call actually measured. The
        host share ran exclusively in [t_disp, t_host]. The device wall is
        only observable when the device was the straggler (collect(),
        entered at t_wait, actually blocked); when the device finished
        first, its wall time is unknowable from here — update NOTHING
        rather than mis-learn a rate dominated by host work. A bucket's
        first dispatch is also excluded: it can carry a multi-second XLA
        compile that would poison the steady-state model in one step."""
        alpha = 0.3
        host_ms = (t_host - t_disp) * 1000
        dev_ms = (t_dev - t0) * 1000
        warm_key = (*key, self._n_dev)
        first_use = warm_key not in self._warmed
        self._warmed.add(warm_key)
        self.last_timing = {
            "n_dev": n_dev,
            "mesh_devices": self._n_dev,
            "n_host": n_host,
            "pack_dispatch_ms": round((t_disp - t0) * 1000, 2),
            "host_msm_ms": round(host_ms, 2),
            "overlap_extra_ms": round((t_wait - t_host) * 1000, 2),
            "dev_wait_ms": round((t_dev - t_wait) * 1000, 2),
            "dev_wall_ms": round(dev_ms, 2),
            "total_ms": round((t_dev - t0) * 1000, 2),
            "first_use": first_use,
            "bias": self._bias,
        }
        with self._rate_lock:
            if host_ms > 1:
                r = min(max(n_host / host_ms, 5.0), 5000.0)
                self._host_rate += alpha * (r - self._host_rate)
            straggler = t_dev - t_wait > 0.001
            if straggler and not first_use and dev_ms > self._dev_overhead:
                # Learned rate stays PER CHIP (observed mesh throughput /
                # width) so it transfers if the mesh width changes.
                r = n_dev / (dev_ms - self._dev_overhead) / self._n_dev
                r = min(max(r, 5.0), 5000.0)
                self._dev_rate += alpha * (r - self._dev_rate)
                wall_key = (key[0], self._n_dev)
                prev = self._dev_wall.get(wall_key, dev_ms)
                self._dev_wall[wall_key] = prev + alpha * (dev_ms - prev)
            wait_ms = (t_dev - t_wait) * 1000
            if n_host == 0:
                # All-device/all-host calls carry no idle-tier signal;
                # decay toward the model's choice so neither extreme is
                # an absorbing state (the split paths stop updating the
                # moment the backend stops splitting). Decay is not a
                # timing measurement, so first-dispatch compiles don't
                # gate it.
                self._decay_bias()
            elif not first_use:
                if not straggler:
                    # device idle at collect: give it one bucket more
                    self._bias = min(self._bias + 1, 3)
                elif wait_ms > 0.2 * max(dev_ms, 1.0):
                    # device clearly the straggler: pull one bucket back
                    self._bias = max(self._bias - 1, -3)

    def _decay_bias(self):
        if self._bias > 0:
            self._bias -= 1
        elif self._bias < 0:
            self._bias += 1

    def merkle_root(self, leaves):
        if self._native.ready() is not None:
            return self._native.merkle_root(leaves)
        return self._tpu.merkle_root(leaves)

    def mesh_width(self) -> int:
        return self._n_dev

    def verify_and_root(self, pubs, msgs, sigs, leaves):
        """The commit-verification + block-tree fusion: device share in
        flight while the host runs its MSM share AND the SHA-NI merkle tree
        (_routed_call's `between` hook). Returns ((ok, bitmap), root)."""
        n = len(pubs)
        if n == 0:
            return (False, []), self.merkle_root(leaves)
        if n < self._min_split or self._native.ready() is None:
            ok, bits = self.batch_verify(pubs, msgs, sigs)
            return (ok, bits), self.merkle_root(leaves)
        share = self._plan(n)
        return self._routed_call(
            pubs, msgs, sigs, share, between=lambda: self.merkle_root(leaves)
        )


class LockedBackend(VerifyBackend):
    """Serializes every device call of a wrapped backend behind one lock.

    The sidecar server wires this under its CoalescingScheduler: the
    scheduler's single dispatcher merges requests from many CONNECTIONS
    into one columnar dispatch, and this wrapper keeps the historic
    one-TPU/one-XLA-stream discipline (the axon tunnel wedges under
    concurrent clients) for the calls that bypass the scheduler — merkle
    roots, warmup — without the handler threads holding the lock across a
    whole verification."""

    def __init__(self, inner: VerifyBackend, lock: threading.Lock):
        self.inner = inner
        self.name = getattr(inner, "name", "device")
        self._device_lock = lock

    def batch_verify(self, pubs, msgs, sigs):
        with self._device_lock:
            return self.inner.batch_verify(pubs, msgs, sigs)

    def merkle_root(self, leaves):
        with self._device_lock:
            return self.inner.merkle_root(leaves)

    def mesh_width(self) -> int:
        mw = getattr(self.inner, "mesh_width", None)
        return int(mw()) if mw is not None else 1


_backend: VerifyBackend | None = None
_lock = threading.Lock()


def device_backend(choice: str = "auto") -> VerifyBackend:
    """cpu/tpu/hybrid/auto selection shared by the in-process path and the
    sidecar server. auto: prefer hybrid (device + host MSM) when an
    accelerator is visible and a native toolchain exists, device-only
    otherwise; fall back to CPU if the device tier can't initialize rather
    than failing the first call."""
    if choice == "cpu":
        return CpuBackend()
    if choice == "tpu":
        return TpuBackend()
    if choice == "hybrid":
        return HybridBackend()
    # auto: a JAX_PLATFORMS=cpu environment means "no accelerator" without
    # importing jax at all — the axon PJRT plugin ignores the env var alone
    # and its init HANGS when the device tunnel is wedged, which would stall
    # the first commit verification of every CLI node in a CPU deployment.
    want = os.environ.get("JAX_PLATFORMS", "")
    if want == "cpu":
        return CpuBackend()
    try:
        import jax
    except ImportError as e:
        _log_fallback(f"jax not importable: {e}")
        return CpuBackend()
    try:
        if want:
            jax.config.update("jax_platforms", want)
        if any(d.platform != "cpu" for d in jax.devices()):
            # Hybrid degrades gracefully to pure-device while (or if) the
            # native build is unavailable, so select it without blocking on
            # native.available()'s gcc run (first-call-stall discipline).
            return HybridBackend()
    except (RuntimeError, OSError, ValueError) as e:
        # Device-probe failures only (no PJRT backend, plugin init error,
        # bad platform name). Anything else — a real bug in a tier's
        # constructor — propagates instead of silently degrading.
        _log_fallback(f"device probe failed: {type(e).__name__}: {e}")
        return CpuBackend()
    return CpuBackend()


def _make_backend() -> VerifyBackend:
    choice = os.environ.get("CMTPU_BACKEND", "auto").lower()
    if choice == "grpc":
        from cometbft_tpu.sidecar.service import GrpcBackend

        return GrpcBackend(os.environ.get("CMTPU_SIDECAR_ADDR", "127.0.0.1:26670"))
    if choice not in ("auto", "cpu", "tpu", "hybrid"):
        raise ValueError(f"unknown CMTPU_BACKEND {choice!r}")
    if choice == "auto":
        # auto ships the supervised degradation chain (grpc|tpu -> hybrid
        # -> cpu with deadlines + circuit breakers, sidecar/supervisor.py):
        # a wedged tier costs one CMTPU_DEADLINE_MS, never liveness.
        # Explicit single-tier choices stay bare — forcing `tpu` or `grpc`
        # means "fail loudly", not "silently verify somewhere else".
        from cometbft_tpu.sidecar.supervisor import build_resilient

        chain = build_resilient()
        if os.environ.get("CMTPU_COALESCE", "1") != "0":
            # Outermost tier: coalesce concurrent callers' requests into
            # single dispatches (sidecar/scheduler.py). CMTPU_COALESCE=0
            # strips the layer for A/B and for callers that need the bare
            # supervised chain.
            from cometbft_tpu.sidecar.scheduler import CoalescingScheduler

            return CoalescingScheduler(chain)
        return chain
    return device_backend(choice)


def get_backend() -> VerifyBackend:
    global _backend
    if _backend is None:
        with _lock:
            if _backend is None:
                _backend = _make_backend()
    return _backend


def set_backend(backend: VerifyBackend | None) -> None:
    """Override the process-wide backend (tests, node bootstrap)."""
    global _backend
    with _lock:
        _backend = backend

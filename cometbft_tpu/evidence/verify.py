"""Evidence verification (reference: evidence/verify.go).

Distinguishes duplicate votes (evidence/verify.go:116 VerifyDuplicateVote)
from light-client attacks (:128 VerifyLightClientAttack, which leans on
VerifyCommitLightTrusting/VerifyCommitLight — TPU-batched paths).
"""

from __future__ import annotations

from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
)
from cometbft_tpu.types.validation import Fraction
from cometbft_tpu.types.cmttime import Time


def verify_evidence(ev, state, state_store, block_store) -> None:
    """evidence/verify.go:20-100 verify(): age checks then type dispatch."""
    height = state.last_block_height
    ev_params = state.consensus_params.evidence
    age_num_blocks = height - ev.height()

    block_meta = block_store.load_block_meta(ev.height())
    if block_meta is None:
        raise ValueError(f"failed to verify evidence: missing block for height {ev.height()}")
    ev_time = block_meta.header.time
    age_duration_ns = state.last_block_time.unix_nanos() - ev_time.unix_nanos()
    if (
        age_duration_ns > ev_params.max_age_duration_ns
        and age_num_blocks > ev_params.max_age_num_blocks
    ):
        raise ValueError(
            f"evidence from height {ev.height()} is too old; evidence can not be older than "
            f"{ev_params.max_age_num_blocks} blocks"
        )

    if isinstance(ev, DuplicateVoteEvidence):
        val_set = state_store.load_validators(ev.height())
        verify_duplicate_vote(ev, state.chain_id, val_set)
        if ev.timestamp != ev_time:
            raise ValueError(
                f"evidence has a different time to the block it is associated with "
                f"({ev.timestamp} != {ev_time})"
            )
    elif isinstance(ev, LightClientAttackEvidence):
        common_vals = state_store.load_validators(ev.common_height)
        trusted_header = block_store.load_block_meta(ev.height())
        if trusted_header is None:
            raise ValueError(f"no header at height {ev.height()}")
        verify_light_client_attack(
            ev,
            common_vals,
            trusted_header.header,
            state.chain_id,
        )
        if ev.timestamp != ev_time:
            raise ValueError("evidence has a different time to the block it is associated with")
    else:
        raise ValueError(f"unrecognized evidence type: {type(ev)}")


def verify_duplicate_vote(ev: DuplicateVoteEvidence, chain_id: str, val_set) -> None:
    """evidence/verify.go:116-190."""
    _, val = val_set.get_by_address(ev.vote_a.validator_address)
    if val is None:
        raise ValueError(
            f"address {ev.vote_a.validator_address.hex().upper()} was not a validator at height {ev.height()}"
        )
    pub_key = val.pub_key
    # H/R/S must match; votes must differ by block ID; addresses equal.
    va, vb = ev.vote_a, ev.vote_b
    if va.height != vb.height or va.round != vb.round or va.type != vb.type:
        raise ValueError("duplicate votes must be for the same height/round/step")
    if va.validator_address != vb.validator_address:
        raise ValueError("duplicate votes must be from the same validator")
    if va.block_id == vb.block_id:
        raise ValueError("duplicate votes must be for different blocks")
    # Correct total power / validator power recorded.
    if ev.validator_power != val.voting_power:
        raise ValueError(
            f"validator power from evidence and our validator set does not match "
            f"({ev.validator_power} != {val.voting_power})"
        )
    if ev.total_voting_power != val_set.total_voting_power():
        raise ValueError(
            f"total voting power from the evidence and our validator set does not match "
            f"({ev.total_voting_power} != {val_set.total_voting_power()})"
        )
    # vote.verify semantics, but the two signatures ride ONE batched
    # dispatch through the micro-batch window — duplicate-vote evidence
    # always carries exactly two sigs from the same key.
    from cometbft_tpu.crypto import sigbatch
    from cometbft_tpu.types.vote import VoteError

    addr = pub_key.address()
    if addr != va.validator_address or addr != vb.validator_address:
        raise VoteError("invalid validator address")
    ok_a, ok_b = sigbatch.verify_triples(
        [pub_key, pub_key],
        [va.sign_bytes(chain_id), vb.sign_bytes(chain_id)],
        [va.signature, vb.signature],
    )
    if not ok_a or not ok_b:
        raise VoteError("invalid signature")


def verify_light_client_attack(
    ev: LightClientAttackEvidence, common_vals, trusted_header, chain_id: str
) -> None:
    """evidence/verify.go:128-230 (condensed): the conflicting header must
    carry a commit that a light client would have accepted from the common
    validators (1/3 trust) or the conflicting validator set itself (2/3)."""
    sh = ev.conflicting_block.signed_header
    if ev.common_height != sh.header.height:
        # Forward-lunatic or non-adjacent: common validators with 1/3 trust.
        common_vals.verify_commit_light_trusting(chain_id, sh.commit, Fraction(1, 3))
    else:
        if ev.conflicting_block.validator_set is None:
            raise ValueError("missing conflicting validator set")
        ev.conflicting_block.validator_set.verify_commit_light(
            chain_id,
            sh.commit.block_id,
            sh.header.height,
            sh.commit,
        )
    # The conflicting header must actually conflict with what we committed.
    if trusted_header.hash() == sh.header.hash():
        raise ValueError("trusted header matches conflicting header; no attack")

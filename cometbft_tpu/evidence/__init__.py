"""Evidence pool (reference: evidence/, 1,261 LoC)."""

from cometbft_tpu.evidence.pool import EvidencePool
from cometbft_tpu.evidence.verify import verify_evidence

__all__ = ["EvidencePool", "verify_evidence"]

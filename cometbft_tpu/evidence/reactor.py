"""Evidence gossip reactor (reference: evidence/reactor.go, channel 0x38).

Pending evidence is broadcast to every peer (reactor.go:107 broadcast
routine); received evidence is verified + pooled.
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.reactor import EVIDENCE_CHANNEL, Reactor
from cometbft_tpu.types.evidence import decode_evidence, encode_evidence
from cometbft_tpu.wire import proto as wire


def encode_evidence_list_msg(evidence: list) -> bytes:
    inner = b""
    for ev in evidence:
        inner += wire.field_message(1, encode_evidence(ev), emit_empty=True)
    return inner


def decode_evidence_list_msg(data: bytes) -> list:
    f = wire.decode_fields(data)
    return [decode_evidence(b) for b in wire.get_repeated_bytes(f, 1)]


class EvidenceReactor(Reactor):
    def __init__(self, evpool):
        super().__init__("EVIDENCE")
        self.evpool = evpool
        self._running = False
        self._peer_sent: dict[str, set] = {}

    def get_channels(self):
        return [ChannelDescriptor(EVIDENCE_CHANNEL, priority=6, send_queue_capacity=100)]

    def start(self) -> None:
        self._running = True

    def stop(self) -> None:
        self._running = False

    def add_peer(self, peer) -> None:
        self._peer_sent[peer.id] = set()
        threading.Thread(target=self._broadcast_routine, args=(peer,), daemon=True).start()

    def remove_peer(self, peer, reason) -> None:
        self._peer_sent.pop(peer.id, None)

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        for ev in decode_evidence_list_msg(msg_bytes):
            try:
                self.evpool.add_evidence(ev)
            except Exception:
                pass  # invalid/expired evidence from peers is dropped

    def _broadcast_routine(self, peer) -> None:
        while self._running and peer.id in self._peer_sent:
            sent = self._peer_sent.get(peer.id)
            if sent is None:
                return
            pending, _ = self.evpool.pending_evidence(-1)
            fresh = [ev for ev in pending if ev.hash() not in sent]
            if fresh:
                for ev in fresh:
                    sent.add(ev.hash())
                peer.try_send(EVIDENCE_CHANNEL, encode_evidence_list_msg(fresh))
            time.sleep(0.2)

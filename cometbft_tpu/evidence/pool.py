"""Evidence pool (reference: evidence/pool.go).

Pending evidence lives in the DB until committed or expired
(pool.go:105 Update, :134 AddEvidence, :192 CheckEvidence); consensus
reports double-signs directly via report_conflicting_votes
(consensus/state.go:69-72 evidencePool interface).
"""

from __future__ import annotations

import threading

from cometbft_tpu.evidence.verify import verify_evidence
from cometbft_tpu.libs.db import DB
from cometbft_tpu.types.cmttime import Time
from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    decode_evidence,
    encode_evidence,
)

_PENDING_PREFIX = b"evP:"
_COMMITTED_PREFIX = b"evC:"


def _key(prefix: bytes, ev) -> bytes:
    return prefix + b"%016x" % ev.height() + ev.hash()


class EvidencePool:
    """evidence/pool.go Pool."""

    def __init__(self, db: DB, state_store, block_store, logger=None):
        self._db = db
        self.state_store = state_store
        self.block_store = block_store
        self.logger = logger
        self._mtx = threading.Lock()
        self.state = state_store.load()
        self._pruning_height = 0
        self._pruning_time = Time()
        # Conflicting votes reported by consensus, turned into evidence on the
        # next Update (pool.go processConsensusBuffer analog).
        self._consensus_buffer: list = []
        # Lifetime counters surfaced by evidence_* gauges / evidence_stats
        # (simnet soak assertions and live nodes read the same numbers).
        self.stats = {
            "reported_total": 0,   # conflicting-vote reports from consensus
            "added_total": 0,      # evidence accepted into pending
            "committed_total": 0,  # evidence marked committed via Update
            "expired_total": 0,    # pending pruned past max-age
        }

    # -- ingest ---------------------------------------------------------------

    def add_evidence(self, ev) -> None:
        """pool.go:134-190 AddEvidence: dedup, verify, persist, gossip-ready."""
        with self._mtx:
            if self._is_pending(ev) or self._is_committed(ev):
                return
            verify_evidence(ev, self.state, self.state_store, self.block_store)
            self._db.set(_key(_PENDING_PREFIX, ev), encode_evidence(ev))
            self.stats["added_total"] += 1

    def report_conflicting_votes(self, vote_a, vote_b) -> None:
        """consensus hook (pool.go ReportConflictingVotes): buffered, turned
        into DuplicateVoteEvidence against the right validator set at Update."""
        with self._mtx:
            self._consensus_buffer.append((vote_a, vote_b))
            self.stats["reported_total"] += 1

    def _process_consensus_buffer(self, state) -> None:
        with self._mtx:
            buffered, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buffered:
            try:
                val_set = self.state_store.load_validators(vote_a.height)
                block_meta = self.block_store.load_block_meta(vote_a.height)
                ev_time = (
                    block_meta.header.time if block_meta else state.last_block_time
                )
                ev = DuplicateVoteEvidence.new(vote_a, vote_b, ev_time, val_set)
                with self._mtx:
                    if not self._is_pending(ev) and not self._is_committed(ev):
                        self._db.set(_key(_PENDING_PREFIX, ev), encode_evidence(ev))
                        self.stats["added_total"] += 1
            except Exception as e:
                if self.logger:
                    self.logger.error(f"failed to generate evidence from conflicting votes: {e}")

    # -- consumption ----------------------------------------------------------

    def pending_evidence(self, max_bytes: int) -> tuple[list, int]:
        """pool.go PendingEvidence: list for inclusion in a proposal."""
        out, size = [], 0
        for _, raw in self._db.iterator(_PENDING_PREFIX, _PENDING_PREFIX + b"\xff"):
            ev = decode_evidence(raw)
            ev_size = len(raw)
            if max_bytes >= 0 and size + ev_size > max_bytes:
                break
            out.append(ev)
            size += ev_size
        return out, size

    def check_evidence(self, evidence: list) -> None:
        """pool.go:192-240 CheckEvidence: every piece must be (or become)
        verified; duplicates within the list rejected."""
        hashes = set()
        for ev in evidence:
            key = ev.hash()
            if key in hashes:
                raise ValueError("duplicate evidence")
            hashes.add(key)
            if self._is_committed(ev):
                raise ValueError("evidence was already committed")
            if not self._is_pending(ev):
                verify_evidence(ev, self.state, self.state_store, self.block_store)
                self._db.set(_key(_PENDING_PREFIX, ev), encode_evidence(ev))
                self.stats["added_total"] += 1

    def update(self, state, evidence: list) -> None:
        """pool.go:105-130 Update: mark committed, prune expired."""
        if state.last_block_height <= self.state.last_block_height:
            raise ValueError("failed EvidencePool.Update: new state has lower height")
        self.state = state
        for ev in evidence:
            self._db.set(_key(_COMMITTED_PREFIX, ev), b"\x01")
            self._db.delete(_key(_PENDING_PREFIX, ev))
            self.stats["committed_total"] += 1
        self._process_consensus_buffer(state)
        self._prune_expired()

    def _prune_expired(self) -> None:
        params = self.state.consensus_params.evidence
        for k, raw in list(
            self._db.iterator(_PENDING_PREFIX, _PENDING_PREFIX + b"\xff")
        ):
            ev = decode_evidence(raw)
            age_blocks = self.state.last_block_height - ev.height()
            age_ns = (
                self.state.last_block_time.unix_nanos() - ev.time().unix_nanos()
            )
            if age_blocks > params.max_age_num_blocks and age_ns > params.max_age_duration_ns:
                self._db.delete(k)
                self.stats["expired_total"] += 1

    # -- queries --------------------------------------------------------------

    def pending_count(self) -> int:
        """Number of evidence pieces currently pending inclusion."""
        return sum(
            1 for _ in self._db.iterator(_PENDING_PREFIX, _PENDING_PREFIX + b"\xff")
        )

    def stats_snapshot(self) -> dict:
        """One coherent read for gauges / RPC / simnet soak assertions."""
        with self._mtx:
            out = dict(self.stats)
        out["pending"] = self.pending_count()
        return out

    def _is_pending(self, ev) -> bool:
        return self._db.has(_key(_PENDING_PREFIX, ev))

    def _is_committed(self, ev) -> bool:
        return self._db.has(_key(_COMMITTED_PREFIX, ev))

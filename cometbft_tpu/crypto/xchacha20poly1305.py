"""XChaCha20-Poly1305 AEAD (reference: crypto/xchacha20poly1305/).

HChaCha20 subkey derivation + standard ChaCha20-Poly1305 (via
crypto/compat — the `cryptography` wheel when present, pure RFC 8439
otherwise), 24-byte nonces. Used for key armoring and symmetric encryption.
"""

from __future__ import annotations

import struct

from cometbft_tpu.crypto.compat import ChaCha20Poly1305

KEY_SIZE = 32
NONCE_SIZE = 24


def _rotl32(v: int, c: int) -> int:
    return ((v << c) | (v >> (32 - c))) & 0xFFFFFFFF


def _quarter(state, a, b, c, d):
    state[a] = (state[a] + state[b]) & 0xFFFFFFFF
    state[d] = _rotl32(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & 0xFFFFFFFF
    state[b] = _rotl32(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b]) & 0xFFFFFFFF
    state[d] = _rotl32(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & 0xFFFFFFFF
    state[b] = _rotl32(state[b] ^ state[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20: derive a subkey from key + first 16 nonce bytes."""
    constants = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
    state = list(constants)
    state += list(struct.unpack("<8I", key))
    state += list(struct.unpack("<4I", nonce16))
    for _ in range(10):
        _quarter(state, 0, 4, 8, 12)
        _quarter(state, 1, 5, 9, 13)
        _quarter(state, 2, 6, 10, 14)
        _quarter(state, 3, 7, 11, 15)
        _quarter(state, 0, 5, 10, 15)
        _quarter(state, 1, 6, 11, 12)
        _quarter(state, 2, 7, 8, 13)
        _quarter(state, 3, 4, 9, 14)
    out = state[0:4] + state[12:16]
    return struct.pack("<8I", *out)


def seal(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    if len(key) != KEY_SIZE:
        raise ValueError("xchacha20poly1305: bad key length")
    if len(nonce) != NONCE_SIZE:
        raise ValueError("xchacha20poly1305: bad nonce length")
    subkey = hchacha20(key, nonce[:16])
    iv = b"\x00" * 4 + nonce[16:]
    return ChaCha20Poly1305(subkey).encrypt(iv, plaintext, aad)


def open_(key: bytes, nonce: bytes, ciphertext: bytes, aad: bytes = b"") -> bytes:
    if len(key) != KEY_SIZE:
        raise ValueError("xchacha20poly1305: bad key length")
    if len(nonce) != NONCE_SIZE:
        raise ValueError("xchacha20poly1305: bad nonce length")
    subkey = hchacha20(key, nonce[:16])
    iv = b"\x00" * 4 + nonce[16:]
    return ChaCha20Poly1305(subkey).decrypt(iv, ciphertext, aad)

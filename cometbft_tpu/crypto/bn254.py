"""BLS signatures on BN254 (reference fork addition: crypto/bn254/bn254.go).

The fork adds a zk-friendly BLS key type: pubkey = compressed G1 point
(32 bytes), signature = uncompressed G2 point (128 bytes), hash-to-field via
Keccak-256 (bn254.go:120-151), sign = [sk]·H(m) on G2 (bn254.go:46-53), verify
= pairing check e(pk, H(m)) == e(G1, sig). No batch verification — bn254 is
deliberately absent from crypto/batch (crypto/batch/batch.go:12-17).

Pure-Python BN254: Fp/Fp2/Fp6/Fp12 towers, optimal ate pairing. Verification
is not in the consensus hot path (bn254 validators verify per-vote, like
secp256k1 would), so Python-int speed is acceptable on the host tier.
"""

from __future__ import annotations

import hashlib
import os

from cometbft_tpu import crypto
from cometbft_tpu.crypto import tmhash

KEY_TYPE = "bn254"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 64  # fr scalar (32) || compressed pubkey (32), mirrors sizePrivateKey
SIGNATURE_SIZE = 128

PRIV_KEY_NAME = "tendermint/PrivKeyBn254"
PUB_KEY_NAME = "tendermint/PubKeyBn254"

# BN254 (alt_bn128) parameters
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

# G1 generator
G1 = (1, 2)

# G2 generator (from EIP-197 / gnark-crypto); Fp2 elements as (a0, a1) = a0 + a1*u
G2 = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

# ---------------------------------------------------------------------------
# Fp2 arithmetic: elements (a, b) = a + b*u with u^2 = -1


def f2_add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def f2_sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def f2_neg(x):
    return ((-x[0]) % P, (-x[1]) % P)


def f2_mul(x, y):
    a = x[0] * y[0] % P
    b = x[1] * y[1] % P
    c = (x[0] + x[1]) * (y[0] + y[1]) % P
    return ((a - b) % P, (c - a - b) % P)


def f2_sqr(x):
    return f2_mul(x, x)


def f2_inv(x):
    t = pow((x[0] * x[0] + x[1] * x[1]) % P, P - 2, P)
    return (x[0] * t % P, (-x[1] * t) % P)


def f2_scalar(x, k):
    return (x[0] * k % P, x[1] * k % P)


F2_ONE = (1, 0)
F2_ZERO = (0, 0)

# twist curve G2: y^2 = x^3 + b', b' = b / xi where xi = 9 + u
B = 3
XI = (9, 1)
B2 = f2_mul((B, 0), f2_inv(XI))

# ---------------------------------------------------------------------------
# Curve arithmetic (affine, generic over the field ops)


def _g1_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def _g1_mul(k, p):
    r = None
    while k > 0:
        if k & 1:
            r = _g1_add(r, p)
        p = _g1_add(p, p)
        k >>= 1
    return r


def _g2_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if f2_add(y1, y2) == F2_ZERO:
            return None
        lam = f2_mul(f2_scalar(f2_sqr(x1), 3), f2_inv(f2_scalar(y1, 2)))
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sub(f2_sqr(lam), x1), x2)
    return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))


def _g2_mul(k, p):
    r = None
    while k > 0:
        if k & 1:
            r = _g2_add(r, p)
        p = _g2_add(p, p)
        k >>= 1
    return r


def _g2_neg(p):
    if p is None:
        return None
    return (p[0], f2_neg(p[1]))


# ---------------------------------------------------------------------------
# Fp12 tower for pairing: Fp12 = Fp2[w] / (w^6 - xi), elements as 6-tuples of
# Fp2 coefficients (c0..c5) for c0 + c1 w + ... + c5 w^5.


def f12_mul(a, b):
    res = [F2_ZERO] * 12
    for i in range(6):
        if a[i] == F2_ZERO:
            continue
        for j in range(6):
            if b[j] == F2_ZERO:
                continue
            t = f2_mul(a[i], b[j])
            res[i + j] = f2_add(res[i + j], t)
    out = list(res[:6])
    for k in range(6, 12):
        if res[k] != F2_ZERO:
            out[k - 6] = f2_add(out[k - 6], f2_mul(res[k], XI))
    return tuple(out)


F12_ONE = (F2_ONE,) + (F2_ZERO,) * 5


def f12_conj_like_inv(a):
    """Generic Fp12 inversion via linear algebra is costly; use
    exponentiation: a^(p^12 - 2) is overkill. Instead solve with the tower:
    treat Fp12 as Fp6[w]/(w^2 - v) — here we just use Gaussian elimination on
    the 12x12 multiplication matrix over Fp (simple, runs rarely)."""
    # Build matrix M where M @ x = e1 represents a * x = 1.
    # Basis: (1, w, ..., w^5) over Fp2 → 12 Fp coordinates (re, im per coeff).
    import itertools

    def to_vec(el12):
        v = []
        for c in el12:
            v.extend([c[0], c[1]])
        return v

    # column j of M = a * basis_j
    cols = []
    for j in range(6):
        for im in range(2):
            basis = [F2_ZERO] * 6
            basis[j] = (0, 1) if im else (1, 0)
            cols.append(to_vec(f12_mul(a, tuple(basis))))
    n = 12
    M = [[cols[j][i] % P for j in range(n)] for i in range(n)]
    rhs = [1] + [0] * (n - 1)
    # Gaussian elimination mod P
    for col in range(n):
        piv = next(r for r in range(col, n) if M[r][col] != 0)
        M[col], M[piv] = M[piv], M[col]
        rhs[col], rhs[piv] = rhs[piv], rhs[col]
        inv = pow(M[col][col], P - 2, P)
        M[col] = [x * inv % P for x in M[col]]
        rhs[col] = rhs[col] * inv % P
        for r in range(n):
            if r != col and M[r][col]:
                f = M[r][col]
                M[r] = [(M[r][c] - f * M[col][c]) % P for c in range(n)]
                rhs[r] = (rhs[r] - f * rhs[col]) % P
    out = tuple((rhs[2 * j], rhs[2 * j + 1]) for j in range(6))
    return out


def f12_pow(a, e):
    r = F12_ONE
    while e > 0:
        if e & 1:
            r = f12_mul(r, a)
        a = f12_mul(a, a)
        e >>= 1
    return r


# Line evaluations for the Miller loop. G2 points are on the twist; we map the
# G1 point into the Fp12 embedding: for the D-twist with w^6 = xi,
# x' = x_t / w^2, y' = y_t / w^3 — equivalently multiply line coefficients by
# powers of w. We use the standard "untwist" evaluation:
#   line(P=(xp, yp)) for tangent/chord at Q=(xq, yq) in Fp2:
#   l = yp * 1 - lam * xp * w - (yq - lam*xq) * w^3  ... using the mapping
# below (coefficients placed so that all arithmetic stays in the tower).


def _line(q1, q2, p_pt):
    """Evaluate the line through q1,q2 (or tangent if equal) at G1 point p.
    Returns an Fp12 element. Embedding: G2 (x,y) ↦ (x/w^2, y/w^3)."""
    xp, yp = p_pt
    x1, y1 = q1
    x2, y2 = q2
    if x1 == x2 and y1 == y2:
        lam_num = f2_scalar(f2_sqr(x1), 3)
        lam_den = f2_scalar(y1, 2)
    elif x1 == x2:
        # Vertical line x = x1; under the untwist (x_t ↦ x_t·w^2) evaluated at
        # P: l = xp - x1·w^2. The lost constant factors are killed by the
        # final exponentiation.
        coeffs = [F2_ZERO] * 6
        coeffs[0] = (xp % P, 0)
        coeffs[2] = f2_neg(x1)
        return tuple(coeffs)
    else:
        lam_num = f2_sub(y2, y1)
        lam_den = f2_sub(x2, x1)
    # Untwist Q ↦ (x·w^2, y·w^3) so the slope is λ'·w with λ' = lam_num/lam_den
    # in Fp2. Line at P, scaled by lam_den (removed by final exp):
    #   l = yp·lam_den − lam_num·xp·w + (lam_num·x1 − y1·lam_den)·w^3
    coeffs = [F2_ZERO] * 6
    coeffs[0] = f2_scalar(lam_den, yp)
    coeffs[1] = f2_neg(f2_scalar(lam_num, xp))
    coeffs[3] = f2_sub(f2_mul(lam_num, x1), f2_mul(y1, lam_den))
    return tuple(coeffs)


# BN parameter for BN254
_T = 4965661367192848881
_ATE_LOOP = 6 * _T + 2


def miller_loop(q, p_pt):
    """Miller loop f_{6t+2,Q}(P) with the final Frobenius adjustment lines."""
    if q is None or p_pt is None:
        return F12_ONE
    f = F12_ONE
    t_pt = q
    bits = bin(_ATE_LOOP)[3:]  # skip MSB
    for bit in bits:
        f = f12_mul(f12_mul(f, f), _line(t_pt, t_pt, p_pt))
        t_pt = _g2_add(t_pt, t_pt)
        if bit == "1":
            f = f12_mul(f, _line(t_pt, q, p_pt))
            t_pt = _g2_add(t_pt, q)
    # Frobenius adjustment: Q1 = pi_p(Q), Q2 = -pi_p^2(Q)
    q1 = _g2_frobenius(q)
    q2 = _g2_neg(_g2_frobenius(q1))
    f = f12_mul(f, _line(t_pt, q1, p_pt))
    t_pt = _g2_add(t_pt, q1)
    f = f12_mul(f, _line(t_pt, q2, p_pt))
    return f


# Frobenius on the twist: (x, y) → (x^p * gamma12, y^p * gamma13)
_GAMMA12 = None
_GAMMA13 = None


def _f2_conj(x):
    return (x[0], (-x[1]) % P)


def _f2_pow(x, e):
    r = F2_ONE
    while e > 0:
        if e & 1:
            r = f2_mul(r, x)
        x = f2_sqr(x)
        e >>= 1
    return r


def _init_frobenius():
    global _GAMMA12, _GAMMA13
    _GAMMA12 = _f2_pow(XI, (P - 1) // 3)
    _GAMMA13 = _f2_pow(XI, (P - 1) // 2)


_init_frobenius()


def _g2_frobenius(q):
    if q is None:
        return None
    x, y = q
    return (f2_mul(_f2_conj(x), _GAMMA12), f2_mul(_f2_conj(y), _GAMMA13))


def final_exponentiation(f):
    """f^((p^12-1)/r) — plain big-exponent form (slow but simple & correct)."""
    e = (P**12 - 1) // R
    return f12_pow(f, e)


def pairing(p_pt, q) -> tuple:
    """e(P, Q) for P in G1, Q in G2 (on the twist)."""
    return final_exponentiation(miller_loop(q, p_pt))


def pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1."""
    f = F12_ONE
    for p_pt, q in pairs:
        f = f12_mul(f, miller_loop(q, p_pt))
    return final_exponentiation(f) == F12_ONE


# ---------------------------------------------------------------------------
# Hash-to-curve. The reference hashes to the curve via gnark's MapToG2
# (bn254.go:120-151 hashedMessage); scalar·generator constructions are
# forgeable (the dlog of H(m) would be public), so we hash to an x-coordinate
# by try-and-increment, then clear the twist cofactor c2 = 2p − r to land in
# the r-torsion. Unknown-dlog and deterministic.

_G2_COFACTOR = 2 * P - R


def _hash_to_g2(msg: bytes):
    base = hashlib.sha3_256(msg).digest()
    ctr = 0
    while True:
        h0 = hashlib.sha3_256(base + b"\x00" + ctr.to_bytes(4, "big")).digest()
        h1 = hashlib.sha3_256(base + b"\x01" + ctr.to_bytes(4, "big")).digest()
        x = (int.from_bytes(h0, "big") % P, int.from_bytes(h1, "big") % P)
        y2 = f2_add(f2_mul(f2_sqr(x), x), B2)
        y = _f2_sqrt(y2)
        if y is not None:
            # choose the lexicographically smaller root for determinism
            if (y[1], y[0]) > ((P - y[1]) % P, (P - y[0]) % P):
                y = f2_neg(y)
            q = _g2_mul(_G2_COFACTOR, (x, y))
            if q is not None:
                return q
        ctr += 1


def _f2_sqrt(a):
    """Square root in Fp2 (p ≡ 3 mod 4): complex method; None if non-residue."""
    if a == F2_ZERO:
        return F2_ZERO
    a0, a1 = a
    if a1 == 0:
        r = pow(a0, (P + 1) // 4, P)
        if r * r % P == a0:
            return (r, 0)
        # sqrt(a0) = sqrt(-a0) * sqrt(-1); -1 is a non-residue so a0 non-residue
        # means -a0 is a residue: root is purely imaginary.
        r = pow((-a0) % P, (P + 1) // 4, P)
        if r * r % P == (-a0) % P:
            return (0, r)
        return None
    # norm = a0^2 + a1^2 must be a residue
    norm = (a0 * a0 + a1 * a1) % P
    n = pow(norm, (P + 1) // 4, P)
    if n * n % P != norm:
        return None
    for sign in (1, -1):
        alpha = (a0 + sign * n) % P * pow(2, P - 2, P) % P
        x0 = pow(alpha, (P + 1) // 4, P)
        if x0 * x0 % P != alpha:
            continue
        x1 = a1 * pow(2 * x0 % P, P - 2, P) % P
        cand = (x0, x1)
        if f2_sqr(cand) == a:
            return cand
    return None


# ---------------------------------------------------------------------------
# Point serialization: gnark-style compressed G1 (32 bytes, big-endian x with
# 2-bit flag in the top bits) and uncompressed G2 (128 bytes).

_MASK = 0b11 << 6
_COMPRESSED_SMALLEST = 0b10 << 6
_COMPRESSED_LARGEST = 0b11 << 6
_COMPRESSED_INFINITY = 0b01 << 6


def g1_compress(p) -> bytes:
    if p is None:
        out = bytearray(32)
        out[0] = _COMPRESSED_INFINITY
        return bytes(out)
    x, y = p
    out = bytearray(x.to_bytes(32, "big"))
    neg_y = (P - y) % P
    flag = _COMPRESSED_LARGEST if y > neg_y else _COMPRESSED_SMALLEST
    out[0] |= flag
    return bytes(out)


def g1_decompress(b: bytes):
    if len(b) != 32:
        raise ValueError("bad G1 compressed length")
    flag = b[0] & _MASK
    if flag == _COMPRESSED_INFINITY:
        return None
    x_bytes = bytes([b[0] & ~_MASK]) + b[1:]
    x = int.from_bytes(x_bytes, "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y2 = (pow(x, 3, P) + B) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("not on curve")
    if flag == _COMPRESSED_LARGEST:
        if y < (P - y) % P:
            y = (P - y) % P
    else:
        if y > (P - y) % P:
            y = (P - y) % P
    return (x, y)


def g2_marshal(q) -> bytes:
    """Uncompressed G2: x.a1 || x.a0 || y.a1 || y.a0 big-endian (gnark order)."""
    if q is None:
        return b"\x00" * 128
    (x0, x1), (y0, y1) = q[0], q[1]
    return (
        x1.to_bytes(32, "big")
        + x0.to_bytes(32, "big")
        + y1.to_bytes(32, "big")
        + y0.to_bytes(32, "big")
    )


def g2_unmarshal(b: bytes):
    if len(b) != 128:
        raise ValueError("bad G2 length")
    if b == b"\x00" * 128:
        return None
    x1 = int.from_bytes(b[0:32], "big")
    x0 = int.from_bytes(b[32:64], "big")
    y1 = int.from_bytes(b[64:96], "big")
    y0 = int.from_bytes(b[96:128], "big")
    if any(v >= P for v in (x0, x1, y0, y1)):
        raise ValueError("G2 coordinate out of range")
    q = ((x0, x1), (y0, y1))
    # on-curve check
    lhs = f2_sqr(q[1])
    rhs = f2_add(f2_mul(f2_sqr(q[0]), q[0]), B2)
    if lhs != rhs:
        raise ValueError("G2 point not on curve")
    # subgroup check: the twist has cofactor 2p − r, so on-curve points outside
    # the r-torsion exist; reject them (gnark's SetBytes does the same).
    if _g2_mul(R, q) is not None:
        raise ValueError("G2 point not in r-torsion subgroup")
    return q


# ---------------------------------------------------------------------------


class PubKey(crypto.PubKey):
    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"bn254 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """Pairing check e(pk, H(m)) == e(G1, sig) ⇔
        e(-pk, H(m)) · e(G1, sig) == 1."""
        if len(sig) != SIGNATURE_SIZE:
            return False
        try:
            pk = g1_decompress(self._bytes)
            s = g2_unmarshal(sig)
            if pk is None or s is None:
                return False
            hm = _hash_to_g2(msg)
            neg_pk = (pk[0], (P - pk[1]) % P)
            return pairing_check([(neg_pk, hm), (G1, s)])
        except (ValueError, TypeError):
            return False

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(crypto.PrivKey):
    def __init__(self, data: bytes):
        if len(data) not in (32, PRIV_KEY_SIZE):
            raise ValueError("bn254 privkey must be 32 or 64 bytes")
        self._scalar_bytes = bytes(data[:32])
        self._scalar = int.from_bytes(self._scalar_bytes, "big") % R
        if self._scalar == 0:
            raise ValueError("invalid bn254 scalar")
        self._pub = PubKey(g1_compress(_g1_mul(self._scalar, G1)))

    def bytes(self) -> bytes:
        return self._scalar_bytes + self._pub.bytes()

    def sign(self, msg: bytes) -> bytes:
        """[sk]·H(m) on G2, uncompressed (bn254.go:46-53)."""
        hm = _hash_to_g2(msg)
        return g2_marshal(_g2_mul(self._scalar, hm))

    def pub_key(self) -> PubKey:
        return self._pub

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    while True:
        raw = os.urandom(32)
        if int.from_bytes(raw, "big") % R != 0:
            return PrivKey(raw)

"""BLS signatures on BN254 (reference fork addition: crypto/bn254/bn254.go).

The fork adds a zk-friendly BLS key type: pubkey = compressed G1 point
(32 bytes), signature = uncompressed G2 point (128 bytes), hash-to-field via
Keccak-256 (bn254.go:120-151), sign = [sk]·H(m) on G2 (bn254.go:46-53), verify
= pairing check e(pk, H(m)) == e(G1, sig). No batch verification — bn254 is
deliberately absent from crypto/batch (crypto/batch/batch.go:12-17).

Pure-Python BN254: Fp/Fp2/Fp6/Fp12 towers, optimal ate pairing. Verification
is not in the consensus hot path (bn254 validators verify per-vote, like
secp256k1 would), so Python-int speed is acceptable on the host tier.
"""

from __future__ import annotations

import hashlib
import os

from cometbft_tpu import crypto
from cometbft_tpu.crypto import tmhash

KEY_TYPE = "bn254"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 64  # fr scalar (32) || compressed pubkey (32), mirrors sizePrivateKey
SIGNATURE_SIZE = 128
# Compressed G2 (gnark-style: x only, 2-bit flag selecting the y root).
# Per-vote signatures stay uncompressed on the hot path; the 64-byte form is
# the wire encoding of the per-block aggregate under CMTPU_AGG_COMMITS.
SIGNATURE_SIZE_COMPRESSED = 64

PRIV_KEY_NAME = "tendermint/PrivKeyBn254"
PUB_KEY_NAME = "tendermint/PubKeyBn254"

# BN254 (alt_bn128) parameters
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

# G1 generator
G1 = (1, 2)

# G2 generator (from EIP-197 / gnark-crypto); Fp2 elements as (a0, a1) = a0 + a1*u
G2 = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

# ---------------------------------------------------------------------------
# Fp2 arithmetic: elements (a, b) = a + b*u with u^2 = -1


def f2_add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def f2_sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def f2_neg(x):
    return ((-x[0]) % P, (-x[1]) % P)


def f2_mul(x, y):
    a = x[0] * y[0] % P
    b = x[1] * y[1] % P
    c = (x[0] + x[1]) * (y[0] + y[1]) % P
    return ((a - b) % P, (c - a - b) % P)


def f2_sqr(x):
    return f2_mul(x, x)


def f2_inv(x):
    t = pow((x[0] * x[0] + x[1] * x[1]) % P, P - 2, P)
    return (x[0] * t % P, (-x[1] * t) % P)


def f2_scalar(x, k):
    return (x[0] * k % P, x[1] * k % P)


F2_ONE = (1, 0)
F2_ZERO = (0, 0)

# twist curve G2: y^2 = x^3 + b', b' = b / xi where xi = 9 + u
B = 3
XI = (9, 1)
B2 = f2_mul((B, 0), f2_inv(XI))

# ---------------------------------------------------------------------------
# Curve arithmetic (affine, generic over the field ops)


def _g1_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def _g1_mul(k, p):
    r = None
    while k > 0:
        if k & 1:
            r = _g1_add(r, p)
        p = _g1_add(p, p)
        k >>= 1
    return r


def _g2_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if f2_add(y1, y2) == F2_ZERO:
            return None
        lam = f2_mul(f2_scalar(f2_sqr(x1), 3), f2_inv(f2_scalar(y1, 2)))
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sub(f2_sqr(lam), x1), x2)
    return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))


def _g2_mul(k, p):
    r = None
    while k > 0:
        if k & 1:
            r = _g2_add(r, p)
        p = _g2_add(p, p)
        k >>= 1
    return r


def _g2_neg(p):
    if p is None:
        return None
    return (p[0], f2_neg(p[1]))


# ---------------------------------------------------------------------------
# Fp12 tower for pairing: Fp12 = Fp2[w] / (w^6 - xi), elements as 6-tuples of
# Fp2 coefficients (c0..c5) for c0 + c1 w + ... + c5 w^5.


def f12_mul(a, b):
    res = [F2_ZERO] * 12
    for i in range(6):
        if a[i] == F2_ZERO:
            continue
        for j in range(6):
            if b[j] == F2_ZERO:
                continue
            t = f2_mul(a[i], b[j])
            res[i + j] = f2_add(res[i + j], t)
    out = list(res[:6])
    for k in range(6, 12):
        if res[k] != F2_ZERO:
            out[k - 6] = f2_add(out[k - 6], f2_mul(res[k], XI))
    return tuple(out)


F12_ONE = (F2_ONE,) + (F2_ZERO,) * 5


def f12_conj_like_inv(a):
    """Generic Fp12 inversion via linear algebra is costly; use
    exponentiation: a^(p^12 - 2) is overkill. Instead solve with the tower:
    treat Fp12 as Fp6[w]/(w^2 - v) — here we just use Gaussian elimination on
    the 12x12 multiplication matrix over Fp (simple, runs rarely)."""
    # Build matrix M where M @ x = e1 represents a * x = 1.
    # Basis: (1, w, ..., w^5) over Fp2 → 12 Fp coordinates (re, im per coeff).
    import itertools

    def to_vec(el12):
        v = []
        for c in el12:
            v.extend([c[0], c[1]])
        return v

    # column j of M = a * basis_j
    cols = []
    for j in range(6):
        for im in range(2):
            basis = [F2_ZERO] * 6
            basis[j] = (0, 1) if im else (1, 0)
            cols.append(to_vec(f12_mul(a, tuple(basis))))
    n = 12
    M = [[cols[j][i] % P for j in range(n)] for i in range(n)]
    rhs = [1] + [0] * (n - 1)
    # Gaussian elimination mod P
    for col in range(n):
        piv = next(r for r in range(col, n) if M[r][col] != 0)
        M[col], M[piv] = M[piv], M[col]
        rhs[col], rhs[piv] = rhs[piv], rhs[col]
        inv = pow(M[col][col], P - 2, P)
        M[col] = [x * inv % P for x in M[col]]
        rhs[col] = rhs[col] * inv % P
        for r in range(n):
            if r != col and M[r][col]:
                f = M[r][col]
                M[r] = [(M[r][c] - f * M[col][c]) % P for c in range(n)]
                rhs[r] = (rhs[r] - f * rhs[col]) % P
    out = tuple((rhs[2 * j], rhs[2 * j + 1]) for j in range(6))
    return out


def f12_pow(a, e):
    r = F12_ONE
    while e > 0:
        if e & 1:
            r = f12_mul(r, a)
        a = f12_mul(a, a)
        e >>= 1
    return r


# Line evaluations for the Miller loop. G2 points are on the twist; we map the
# G1 point into the Fp12 embedding: for the D-twist with w^6 = xi,
# x' = x_t / w^2, y' = y_t / w^3 — equivalently multiply line coefficients by
# powers of w. We use the standard "untwist" evaluation:
#   line(P=(xp, yp)) for tangent/chord at Q=(xq, yq) in Fp2:
#   l = yp * 1 - lam * xp * w - (yq - lam*xq) * w^3  ... using the mapping
# below (coefficients placed so that all arithmetic stays in the tower).


def _line(q1, q2, p_pt):
    """Evaluate the line through q1,q2 (or tangent if equal) at G1 point p.
    Returns an Fp12 element. Embedding: G2 (x,y) ↦ (x/w^2, y/w^3)."""
    xp, yp = p_pt
    x1, y1 = q1
    x2, y2 = q2
    if x1 == x2 and y1 == y2:
        lam_num = f2_scalar(f2_sqr(x1), 3)
        lam_den = f2_scalar(y1, 2)
    elif x1 == x2:
        # Vertical line x = x1; under the untwist (x_t ↦ x_t·w^2) evaluated at
        # P: l = xp - x1·w^2. The lost constant factors are killed by the
        # final exponentiation.
        coeffs = [F2_ZERO] * 6
        coeffs[0] = (xp % P, 0)
        coeffs[2] = f2_neg(x1)
        return tuple(coeffs)
    else:
        lam_num = f2_sub(y2, y1)
        lam_den = f2_sub(x2, x1)
    # Untwist Q ↦ (x·w^2, y·w^3) so the slope is λ'·w with λ' = lam_num/lam_den
    # in Fp2. Line at P, scaled by lam_den (removed by final exp):
    #   l = yp·lam_den − lam_num·xp·w + (lam_num·x1 − y1·lam_den)·w^3
    coeffs = [F2_ZERO] * 6
    coeffs[0] = f2_scalar(lam_den, yp)
    coeffs[1] = f2_neg(f2_scalar(lam_num, xp))
    coeffs[3] = f2_sub(f2_mul(lam_num, x1), f2_mul(y1, lam_den))
    return tuple(coeffs)


# BN parameter for BN254
_T = 4965661367192848881
_ATE_LOOP = 6 * _T + 2


def miller_loop(q, p_pt):
    """Miller loop f_{6t+2,Q}(P) with the final Frobenius adjustment lines."""
    if q is None or p_pt is None:
        return F12_ONE
    f = F12_ONE
    t_pt = q
    bits = bin(_ATE_LOOP)[3:]  # skip MSB
    for bit in bits:
        f = f12_mul(f12_mul(f, f), _line(t_pt, t_pt, p_pt))
        t_pt = _g2_add(t_pt, t_pt)
        if bit == "1":
            f = f12_mul(f, _line(t_pt, q, p_pt))
            t_pt = _g2_add(t_pt, q)
    # Frobenius adjustment: Q1 = pi_p(Q), Q2 = -pi_p^2(Q)
    q1 = _g2_frobenius(q)
    q2 = _g2_neg(_g2_frobenius(q1))
    f = f12_mul(f, _line(t_pt, q1, p_pt))
    t_pt = _g2_add(t_pt, q1)
    f = f12_mul(f, _line(t_pt, q2, p_pt))
    return f


# Frobenius on the twist: (x, y) → (x^p * gamma12, y^p * gamma13)
_GAMMA12 = None
_GAMMA13 = None


def _f2_conj(x):
    return (x[0], (-x[1]) % P)


def _f2_pow(x, e):
    r = F2_ONE
    while e > 0:
        if e & 1:
            r = f2_mul(r, x)
        x = f2_sqr(x)
        e >>= 1
    return r


def _init_frobenius():
    global _GAMMA12, _GAMMA13
    _GAMMA12 = _f2_pow(XI, (P - 1) // 3)
    _GAMMA13 = _f2_pow(XI, (P - 1) // 2)


_init_frobenius()


def _g2_frobenius(q):
    if q is None:
        return None
    x, y = q
    return (f2_mul(_f2_conj(x), _GAMMA12), f2_mul(_f2_conj(y), _GAMMA13))


def final_exponentiation(f):
    """f^((p^12-1)/r) — plain big-exponent form (slow but simple & correct)."""
    e = (P**12 - 1) // R
    return f12_pow(f, e)


def pairing(p_pt, q) -> tuple:
    """e(P, Q) for P in G1, Q in G2 (on the twist)."""
    return final_exponentiation(miller_loop(q, p_pt))


def pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1."""
    f = F12_ONE
    for p_pt, q in pairs:
        f = f12_mul(f, miller_loop(q, p_pt))
    return final_exponentiation(f) == F12_ONE


# ---------------------------------------------------------------------------
# Hash-to-curve. The reference hashes to the curve via gnark's MapToG2
# (bn254.go:120-151 hashedMessage); scalar·generator constructions are
# forgeable (the dlog of H(m) would be public), so we hash to an x-coordinate
# by try-and-increment, then clear the twist cofactor c2 = 2p − r to land in
# the r-torsion. Unknown-dlog and deterministic.

_G2_COFACTOR = 2 * P - R


def _hash_to_g2(msg: bytes):
    base = hashlib.sha3_256(msg).digest()
    ctr = 0
    while True:
        h0 = hashlib.sha3_256(base + b"\x00" + ctr.to_bytes(4, "big")).digest()
        h1 = hashlib.sha3_256(base + b"\x01" + ctr.to_bytes(4, "big")).digest()
        x = (int.from_bytes(h0, "big") % P, int.from_bytes(h1, "big") % P)
        y2 = f2_add(f2_mul(f2_sqr(x), x), B2)
        y = _f2_sqrt(y2)
        if y is not None:
            # choose the lexicographically smaller root for determinism
            if (y[1], y[0]) > ((P - y[1]) % P, (P - y[0]) % P):
                y = f2_neg(y)
            q = _g2_mul(_G2_COFACTOR, (x, y))
            if q is not None:
                return q
        ctr += 1


def _f2_sqrt(a):
    """Square root in Fp2 (p ≡ 3 mod 4): complex method; None if non-residue."""
    if a == F2_ZERO:
        return F2_ZERO
    a0, a1 = a
    if a1 == 0:
        r = pow(a0, (P + 1) // 4, P)
        if r * r % P == a0:
            return (r, 0)
        # sqrt(a0) = sqrt(-a0) * sqrt(-1); -1 is a non-residue so a0 non-residue
        # means -a0 is a residue: root is purely imaginary.
        r = pow((-a0) % P, (P + 1) // 4, P)
        if r * r % P == (-a0) % P:
            return (0, r)
        return None
    # norm = a0^2 + a1^2 must be a residue
    norm = (a0 * a0 + a1 * a1) % P
    n = pow(norm, (P + 1) // 4, P)
    if n * n % P != norm:
        return None
    for sign in (1, -1):
        alpha = (a0 + sign * n) % P * pow(2, P - 2, P) % P
        x0 = pow(alpha, (P + 1) // 4, P)
        if x0 * x0 % P != alpha:
            continue
        x1 = a1 * pow(2 * x0 % P, P - 2, P) % P
        cand = (x0, x1)
        if f2_sqr(cand) == a:
            return cand
    return None


# ---------------------------------------------------------------------------
# Point serialization: gnark-style compressed G1 (32 bytes, big-endian x with
# 2-bit flag in the top bits) and uncompressed G2 (128 bytes).

_MASK = 0b11 << 6
_COMPRESSED_SMALLEST = 0b10 << 6
_COMPRESSED_LARGEST = 0b11 << 6
_COMPRESSED_INFINITY = 0b01 << 6


def g1_compress(p) -> bytes:
    if p is None:
        out = bytearray(32)
        out[0] = _COMPRESSED_INFINITY
        return bytes(out)
    x, y = p
    out = bytearray(x.to_bytes(32, "big"))
    neg_y = (P - y) % P
    flag = _COMPRESSED_LARGEST if y > neg_y else _COMPRESSED_SMALLEST
    out[0] |= flag
    return bytes(out)


def g1_decompress(b: bytes):
    if len(b) != 32:
        raise ValueError("bad G1 compressed length")
    flag = b[0] & _MASK
    if flag == _COMPRESSED_INFINITY:
        return None
    x_bytes = bytes([b[0] & ~_MASK]) + b[1:]
    x = int.from_bytes(x_bytes, "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y2 = (pow(x, 3, P) + B) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("not on curve")
    if flag == _COMPRESSED_LARGEST:
        if y < (P - y) % P:
            y = (P - y) % P
    else:
        if y > (P - y) % P:
            y = (P - y) % P
    return (x, y)


def g2_marshal(q) -> bytes:
    """Uncompressed G2: x.a1 || x.a0 || y.a1 || y.a0 big-endian (gnark order)."""
    if q is None:
        return b"\x00" * 128
    (x0, x1), (y0, y1) = q[0], q[1]
    return (
        x1.to_bytes(32, "big")
        + x0.to_bytes(32, "big")
        + y1.to_bytes(32, "big")
        + y0.to_bytes(32, "big")
    )


def g2_compress(q) -> bytes:
    """Compressed G2: x.a1 || x.a0 big-endian (64 bytes) with the gnark
    2-bit flag in the top bits of the first byte selecting which square
    root of y² the point carries (lexicographically larger = (y1, y0) >
    (-y1, -y0), matching gnark's Fp2 ordering)."""
    if q is None:
        out = bytearray(64)
        out[0] = _COMPRESSED_INFINITY
        return bytes(out)
    (x0, x1), (y0, y1) = q[0], q[1]
    out = bytearray(x1.to_bytes(32, "big") + x0.to_bytes(32, "big"))
    neg = ((P - y1) % P, (P - y0) % P)
    flag = _COMPRESSED_LARGEST if (y1, y0) > neg else _COMPRESSED_SMALLEST
    out[0] |= flag
    return bytes(out)


def g2_decompress(b: bytes):
    if len(b) != 64:
        raise ValueError("bad G2 compressed length")
    flag = b[0] & _MASK
    if flag == _COMPRESSED_INFINITY:
        if (b[0] & ~_MASK) or any(b[1:]):
            raise ValueError("bad G2 infinity encoding")
        return None
    if flag not in (_COMPRESSED_SMALLEST, _COMPRESSED_LARGEST):
        raise ValueError("bad G2 compression flag")
    x1 = int.from_bytes(bytes([b[0] & ~_MASK]) + b[1:32], "big")
    x0 = int.from_bytes(b[32:64], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 coordinate out of range")
    x = (x0, x1)
    y2 = f2_add(f2_mul(f2_sqr(x), x), B2)
    y = _f2_sqrt(y2)
    if y is None:
        raise ValueError("G2 x not on curve")
    larger = (y[1], y[0]) > ((P - y[1]) % P, (P - y[0]) % P)
    if (flag == _COMPRESSED_LARGEST) != larger:
        y = f2_neg(y)
    q = (x, y)
    if _g2_mul(R, q) is not None:
        raise ValueError("G2 point not in r-torsion subgroup")
    return q


def g2_unmarshal(b: bytes):
    if len(b) == SIGNATURE_SIZE_COMPRESSED:
        return g2_decompress(b)
    if len(b) != 128:
        raise ValueError("bad G2 length")
    if b == b"\x00" * 128:
        return None
    x1 = int.from_bytes(b[0:32], "big")
    x0 = int.from_bytes(b[32:64], "big")
    y1 = int.from_bytes(b[64:96], "big")
    y0 = int.from_bytes(b[96:128], "big")
    if any(v >= P for v in (x0, x1, y0, y1)):
        raise ValueError("G2 coordinate out of range")
    q = ((x0, x1), (y0, y1))
    # on-curve check
    lhs = f2_sqr(q[1])
    rhs = f2_add(f2_mul(f2_sqr(q[0]), q[0]), B2)
    if lhs != rhs:
        raise ValueError("G2 point not on curve")
    # subgroup check: the twist has cofactor 2p − r, so on-curve points outside
    # the r-torsion exist; reject them (gnark's SetBytes does the same).
    if _g2_mul(R, q) is not None:
        raise ValueError("G2 point not in r-torsion subgroup")
    return q


# ---------------------------------------------------------------------------


class PubKey(crypto.PubKey):
    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"bn254 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """Pairing check e(pk, H(m)) == e(G1, sig) ⇔
        e(-pk, H(m)) · e(G1, sig) == 1."""
        if len(sig) != SIGNATURE_SIZE:
            return False
        try:
            pk = g1_decompress(self._bytes)
            s = g2_unmarshal(sig)
            if pk is None or s is None:
                return False
            hm = _hash_to_g2(msg)
            neg_pk = (pk[0], (P - pk[1]) % P)
            return pairing_check([(neg_pk, hm), (G1, s)])
        except (ValueError, TypeError):
            return False

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(crypto.PrivKey):
    def __init__(self, data: bytes):
        if len(data) not in (32, PRIV_KEY_SIZE):
            raise ValueError("bn254 privkey must be 32 or 64 bytes")
        self._scalar_bytes = bytes(data[:32])
        self._scalar = int.from_bytes(self._scalar_bytes, "big") % R
        if self._scalar == 0:
            raise ValueError("invalid bn254 scalar")
        self._pub = PubKey(g1_compress(_g1_mul(self._scalar, G1)))

    def bytes(self) -> bytes:
        return self._scalar_bytes + self._pub.bytes()

    def sign(self, msg: bytes) -> bytes:
        """[sk]·H(m) on G2, uncompressed (bn254.go:46-53)."""
        hm = _hash_to_g2(msg)
        return g2_marshal(_g2_mul(self._scalar, hm))

    def pub_key(self) -> PubKey:
        return self._pub

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    while True:
        raw = os.urandom(32)
        if int.from_bytes(raw, "big") % R != 0:
            return PrivKey(raw)


# ===========================================================================
# Fast host-tier pairing path (ISSUE 9).
#
# Everything above is the reference-faithful slow form and stays untouched —
# `verify_signature_slow` below preserves it verbatim as the bench scalar
# arm and the ground-truth the fast path is tested against. The fast path
# changes the arithmetic, never the decision:
#
#  * `final_exponentiation_fast` — easy part by conjugation/Frobenius + one
#    Fp12 inversion, hard part by the Scott et al. addition chain in the BN
#    parameter t (3 exponentiations by t instead of one 2790-bit ladder).
#    Computes exactly f^((p^12-1)/r), asserted value-identical in tests.
#  * `multi_miller_loop` — one shared Fp12 squaring per iteration across
#    every pair of a whole commit (the squaring of a product is the product
#    of squarings, so n Miller loops share their doubling schedule).
#  * One shared final exponentiation per CHECK, not per signature — the
#    aggregate-BLS shape from arXiv:2302.00418.
#
# Line-function scalings live in Fp2, a proper subfield, so they are killed
# by the final exponentiation: check results are bit-identical to the slow
# engine (tested over valid, corrupted, and wrong-key signatures).


def f12_sqr(a):
    return f12_mul(a, a)


def f12_inv(a):
    return f12_conj_like_inv(a)


def _f12_conj6(a):
    """a^(p^6): the nontrivial automorphism fixing Fp6 = Fp2[w^2] — negates
    the odd-power-of-w coefficients. Equals a^-1 inside the cyclotomic
    subgroup (post-easy-part), which is what the hard part exploits."""
    return tuple(c if i % 2 == 0 else f2_neg(c) for i, c in enumerate(a))


# gamma[k][i] = xi^(i * (p^k - 1) / 6): the twist constants of the Fp12
# Frobenius x -> x^(p^k) on the w^i basis.
_F12_GAMMA = {
    k: tuple(_f2_pow(XI, i * (P**k - 1) // 6) for i in range(6)) for k in (1, 2, 3)
}


def _f12_frobenius(a, k):
    """a^(p^k) for k in {1,2,3}: coefficient-wise Fp2 Frobenius (conjugation
    when k is odd) times the basis twist gamma[k][i]."""
    g = _F12_GAMMA[k]
    if k % 2:
        return tuple(f2_mul(_f2_conj(c), g[i]) for i, c in enumerate(a))
    return tuple(f2_mul(c, g[i]) for i, c in enumerate(a))


def final_exponentiation_fast(f):
    """f^((p^12-1)/r), value-identical to `final_exponentiation`.

    Easy part (p^6-1)(p^2+1) via conjugation + one Fp12 inversion; hard
    part (p^4-p^2+1)/r via the Scott-Benger-Charlemagne-Perez-Kachisa
    addition chain in t (exact exponent, not a multiple)."""
    # easy part: m = f^((p^6-1)(p^2+1))
    t = f12_mul(_f12_conj6(f), f12_inv(f))  # f^(p^6-1)
    m = f12_mul(_f12_frobenius(t, 2), t)  # ^(p^2+1)
    # hard part: m^((p^4-p^2+1)/r); conj6 = inverse in the cyclotomic group
    fu = f12_pow(m, _T)
    fu2 = f12_pow(fu, _T)
    fu3 = f12_pow(fu2, _T)
    y0 = f12_mul(
        f12_mul(_f12_frobenius(m, 1), _f12_frobenius(m, 2)), _f12_frobenius(m, 3)
    )
    y1 = _f12_conj6(m)
    y2 = _f12_frobenius(fu2, 2)
    y3 = _f12_conj6(_f12_frobenius(fu, 1))
    y4 = _f12_conj6(f12_mul(fu, _f12_frobenius(fu2, 1)))
    y5 = _f12_conj6(fu2)
    y6 = _f12_conj6(f12_mul(fu3, _f12_frobenius(fu3, 1)))
    t0 = f12_mul(f12_mul(f12_sqr(y6), y4), y5)
    t1 = f12_mul(f12_mul(y3, y5), t0)
    t0 = f12_mul(t0, y2)
    t1 = f12_mul(f12_sqr(t1), t0)
    t1 = f12_sqr(t1)
    t0 = f12_mul(t1, y1)
    t1 = f12_mul(t1, y0)
    t0 = f12_mul(f12_sqr(t0), t1)
    return t0


def multi_miller_loop(pairs):
    """prod_i f_{6t+2,Q_i}(P_i) with ONE shared Fp12 squaring per iteration.

    Bit-for-bit the same doubling/addition schedule as `miller_loop` run per
    pair, but the accumulator is the product, so the per-iteration squaring
    (the only O(n)-independent cost) is paid once for the whole batch."""
    live = [(p_pt, q) for p_pt, q in pairs if p_pt is not None and q is not None]
    if not live:
        return F12_ONE
    f = F12_ONE
    ts = [q for _, q in live]
    bits = bin(_ATE_LOOP)[3:]
    for bit in bits:
        f = f12_sqr(f)
        for i, (p_pt, q) in enumerate(live):
            f = f12_mul(f, _line(ts[i], ts[i], p_pt))
            ts[i] = _g2_add(ts[i], ts[i])
            if bit == "1":
                f = f12_mul(f, _line(ts[i], q, p_pt))
                ts[i] = _g2_add(ts[i], q)
    for i, (p_pt, q) in enumerate(live):
        q1 = _g2_frobenius(q)
        q2 = _g2_neg(_g2_frobenius(q1))
        f = f12_mul(f, _line(ts[i], q1, p_pt))
        ts[i] = _g2_add(ts[i], q1)
        f = f12_mul(f, _line(ts[i], q2, p_pt))
    return f


def pairing_check_fast(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 via the shared-squaring Miller loop and fast
    final exponentiation. Decision-identical to `pairing_check`."""
    return final_exponentiation_fast(multi_miller_loop(pairs)) == F12_ONE


# -- hash-to-G2 cache --------------------------------------------------------
# Vote sign bytes recur across engines (vote admission, commit verify, light
# client, crosscheck); try-and-increment + cofactor clearing is ~5 ms, so a
# small LRU removes the dominant per-message cost of re-verification.

_HM_CACHE: dict[bytes, tuple] = {}
_HM_CACHE_MAX = 8192


def _hash_to_g2_cached(msg: bytes):
    key = bytes(msg)
    hit = _HM_CACHE.get(key)
    if hit is not None:
        return hit
    q = _hash_to_g2(key)
    if len(_HM_CACHE) >= _HM_CACHE_MAX:
        for k in list(_HM_CACHE)[: _HM_CACHE_MAX // 4]:
            _HM_CACHE.pop(k, None)
    _HM_CACHE[key] = q
    return q


def verify_signature_slow(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Today's scalar pairing, verbatim (pre-ISSUE-9 PubKey.verify_signature
    body): plain Miller loops, 2790-bit final-exponentiation ladder, uncached
    hash-to-G2. The bench `agg` scalar arm and the fast-path equivalence
    tests measure/check against THIS."""
    if len(sig) != SIGNATURE_SIZE:
        return False
    try:
        pk = g1_decompress(pub)
        s = g2_unmarshal(sig)
        if pk is None or s is None:
            return False
        hm = _hash_to_g2(msg)
        neg_pk = (pk[0], (P - pk[1]) % P)
        return pairing_check([(neg_pk, hm), (G1, s)])
    except (ValueError, TypeError):
        return False


# ---------------------------------------------------------------------------
# Aggregate BLS (ISSUE 9 tentpole): one G2 point for a whole commit.
#
# Trust model: sound for DISTINCT per-signer messages under proof-of-
# possession of the registered validator keys (the standard BLS deployment
# assumption; a rogue pubkey registered as pk' = [x]G1 - pk_victim could
# otherwise claim the victim co-signed an identical message). Documented in
# ops/DESIGN.md; CMTPU_AGG_COMMITS stays default-off.


def aggregate_signatures(sigs) -> bytes:
    """G2 sum of BLS signatures -> one 128-byte uncompressed point.

    Every input is fully validated (on-curve + r-torsion) by g2_unmarshal;
    a malformed signature raises ValueError rather than silently poisoning
    the aggregate."""
    total = None
    for s in sigs:
        total = _g2_add(total, g2_unmarshal(bytes(s)))
    return g2_marshal(total)


def aggregate_signatures_compressed(sigs) -> bytes:
    """Same G2 sum, emitted in the 64-byte compressed wire form the block
    commit carries under CMTPU_AGG_COMMITS."""
    total = None
    for s in sigs:
        total = _g2_add(total, g2_unmarshal(bytes(s)))
    return g2_compress(total)


def verify_aggregate(pub_keys, msgs, agg_sig: bytes) -> bool:
    """e(G1, agg) == prod_i e(pk_i, H(m_i)) as n+1 Miller loops sharing one
    final exponentiation. pub_keys are compressed G1 bytes, msgs the
    per-signer (distinct) messages."""
    if len(pub_keys) != len(msgs) or not pub_keys:
        return False
    try:
        s = g2_unmarshal(bytes(agg_sig))
    except (ValueError, TypeError):
        return False
    pairs = []
    for pb, m in zip(pub_keys, msgs):
        try:
            pk = g1_decompress(bytes(pb))
        except (ValueError, TypeError):
            return False
        if pk is None:
            return False
        pairs.append(((pk[0], (P - pk[1]) % P), _hash_to_g2_cached(m)))
    pairs.append((G1, s))
    return pairing_check_fast(pairs)


def verify_aggregate_slow(pub_keys, msgs, agg_sig: bytes) -> bool:
    """Decision-identical slow-arithmetic form of verify_aggregate (plain
    per-pair Miller loops + the 2790-bit final-exp ladder) — the anchor the
    equivalence tests and the bench scalar arm compare against."""
    if len(pub_keys) != len(msgs) or not pub_keys:
        return False
    try:
        s = g2_unmarshal(bytes(agg_sig))
        pairs = []
        for pb, m in zip(pub_keys, msgs):
            pk = g1_decompress(bytes(pb))
            if pk is None:
                return False
            pairs.append(((pk[0], (P - pk[1]) % P), _hash_to_g2(m)))
        pairs.append((G1, s))
        return pairing_check(pairs)
    except (ValueError, TypeError):
        return False


# ---------------------------------------------------------------------------
# Proof of possession (round 10). Plain BLS aggregation is vulnerable to the
# rogue-key attack: a registrant who publishes pk' = pk_rogue − Σ pk_honest
# can forge an aggregate "signed" by the whole set. The standard defence
# (Ristenpart–Yilek; draft-irtf-cfrg-bls-signature §3.3) is to demand, at
# KEY REGISTRATION time, a signature over the key's own serialization under
# a domain-separation tag no consensus message can collide with — consensus
# sign-bytes are length-prefixed protobuf of SignedMsgType ≥ 1, so this
# ASCII prefix is unreachable from any vote or proposal.

POP_DST = b"CMTPU-BN254-POP-V1|"


def pop_sign_bytes(pub_key_bytes: bytes) -> bytes:
    return POP_DST + bytes(pub_key_bytes)


def prove_possession(priv: "PrivKey") -> bytes:
    """64-byte compressed G2 proof that the holder knows the secret scalar
    behind their published pubkey — required in genesis for bn254 keys."""
    sig = priv.sign(pop_sign_bytes(priv.pub_key().bytes()))
    return g2_compress(g2_unmarshal(sig))


def verify_possession(pub_key_bytes: bytes, pop: bytes) -> bool:
    """One fast pairing check; accepts either G2 wire form. Never raises —
    malformed input is simply an invalid proof."""
    if len(pop) not in (SIGNATURE_SIZE, SIGNATURE_SIZE_COMPRESSED):
        return False
    try:
        pk = g1_decompress(bytes(pub_key_bytes))
        s = g2_unmarshal(bytes(pop))
        if pk is None or s is None:
            return False
        hm = _hash_to_g2_cached(pop_sign_bytes(pub_key_bytes))
        neg_pk = (pk[0], (P - pk[1]) % P)
        return pairing_check_fast([(neg_pk, hm), (G1, s)])
    except (ValueError, TypeError):
        return False


# ---------------------------------------------------------------------------
# Batched per-signature verification with a bitmap (the BatchVerifier
# protocol). A naive product check is UNSOUND for bitmap semantics — two bad
# signatures can cancel (e(G1, s+d) * e(G1, s'-d) preserves the product) —
# so each signature is weighted by an unpredictable 64-bit scalar derived
# Fiat-Shamir-style from the whole batch:
#     prod_i e([w_i](-pk_i), H(m_i)) * e(G1, sum_i [w_i] s_i) == 1
# A cancellation would need the adversary to predict w_i before fixing the
# signatures that determine them. On failure the check bisects to the exact
# bad lanes (the per-sig bitmap the verify_commit error path needs).


def _batch_weights(pubs, msgs, sigs):
    h = hashlib.sha256()
    for col in (pubs, msgs, sigs):
        for x in col:
            h.update(len(x).to_bytes(4, "big"))
            h.update(x)
    seed = h.digest()
    return [
        int.from_bytes(
            hashlib.sha256(seed + i.to_bytes(4, "big")).digest()[:8], "big"
        )
        | 1
        for i in range(len(pubs))
    ]


def batch_verify_signatures(pubs, msgs, sigs) -> tuple[bool, list]:
    """(all_ok, per-sig bitmap) over raw byte columns — the host multi-
    pairing engine behind Bn254HostBackend. Structurally invalid entries are
    False lanes and never poison the rest."""
    n = len(pubs)
    bits = [False] * n
    parsed: dict[int, tuple] = {}
    for i in range(n):
        try:
            pk = g1_decompress(bytes(pubs[i]))
            s = g2_unmarshal(bytes(sigs[i]))
            if pk is None or s is None:
                continue
        except (ValueError, TypeError):
            continue
        parsed[i] = (
            (pk[0], (P - pk[1]) % P),
            _hash_to_g2_cached(bytes(msgs[i])),
            s,
        )
    ws = _batch_weights(
        [bytes(p) for p in pubs], [bytes(m) for m in msgs], [bytes(s) for s in sigs]
    )

    def check(idxs) -> bool:
        pairs = []
        agg = None
        for i in idxs:
            neg_pk, hm, s = parsed[i]
            pairs.append((_g1_mul(ws[i], neg_pk), hm))
            agg = _g2_add(agg, _g2_mul(ws[i], s))
        pairs.append((G1, agg))
        return pairing_check_fast(pairs)

    stack = [sorted(parsed)] if parsed else []
    while stack:
        idxs = stack.pop()
        if not idxs:
            continue
        if check(idxs):
            for i in idxs:
                bits[i] = True
        elif len(idxs) == 1:
            bits[idxs[0]] = False
        else:
            mid = len(idxs) // 2
            stack.append(idxs[:mid])
            stack.append(idxs[mid:])
    return (n > 0 and all(bits)), bits


# ---------------------------------------------------------------------------
# Verification backends: the same VerifyBackend shape the ed25519 chain
# speaks ((pubs, msgs, sigs) byte columns -> (ok, bitmap)), so the generic
# CoalescingScheduler / ResilientBackend / ChaosBackend stack applies
# unchanged. The bn254 chain is its OWN instance — the ed25519 singleton
# cannot verify bn254 triples — with the same env knobs.


class Bn254HostBackend:
    """Randomized-weight multi-pairing with shared final exponentiation."""

    name = "bn254-host"

    def batch_verify(self, pubs, msgs, sigs):
        return batch_verify_signatures(pubs, msgs, sigs)

    def aggregate_verify(self, pubs, msgs, agg_sig) -> bool:
        return verify_aggregate(pubs, msgs, agg_sig)

    def merkle_root(self, leaves):
        from cometbft_tpu.crypto import merkle

        return merkle.hash_from_byte_slices(list(leaves))

    def ping(self) -> bool:
        return True


class Bn254ScalarBackend:
    """The chain anchor: independent scalar pairing checks, one per
    signature — no shared state with the batched engines, so it is valid
    crosscheck ground truth for them."""

    name = "bn254-cpu"

    def batch_verify(self, pubs, msgs, sigs):
        bits = []
        for p, m, s in zip(pubs, msgs, sigs):
            bits.append(_scalar_verify(bytes(p), bytes(m), bytes(s)))
        return (len(bits) > 0 and all(bits)), bits

    def aggregate_verify(self, pubs, msgs, agg_sig) -> bool:
        # The aggregate has no per-sig form; the anchor's check is the
        # exact-integer host multi-pairing (same decision as the slow
        # reference ladder, asserted by the equivalence tests).
        return verify_aggregate(pubs, msgs, agg_sig)

    def merkle_root(self, leaves):
        from cometbft_tpu.crypto import merkle

        return merkle.hash_from_byte_slices(list(leaves))

    def ping(self) -> bool:
        return True


def _scalar_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """One pairing check (fast arithmetic, scalar semantics): the per-sig
    anchor. Decision-identical to verify_signature_slow."""
    if len(sig) != SIGNATURE_SIZE:
        return False
    try:
        pk = g1_decompress(pub)
        s = g2_unmarshal(sig)
        if pk is None or s is None:
            return False
        hm = _hash_to_g2_cached(msg)
        neg_pk = (pk[0], (P - pk[1]) % P)
        return pairing_check_fast([(neg_pk, hm), (G1, s)])
    except (ValueError, TypeError):
        return False


def build_bn254_chain():
    """bn254-device -> bn254-host -> scalar-cpu anchor, with the same
    CMTPU_FAULTS chaos wrapping rules as supervisor.build_chain (non-anchor
    tiers only; cpu-only + faults inserts a chaos-wrapped host primary)."""
    from cometbft_tpu.sidecar.chaos import ChaosBackend, faults_from_env

    tiers = []
    try:
        from cometbft_tpu.ops import bn254_kernel as _bk

        if _bk.device_available():
            tiers.append(("bn254-device", _bk.Bn254DeviceBackend()))
    except Exception:
        pass  # no jax / kernel import failure: host tiers still serve
    tiers.append(("bn254-host", Bn254HostBackend()))
    faults = faults_from_env()
    if faults:
        seed = int(os.environ.get("CMTPU_FAULTS_SEED", "0") or 0)
        tiers = [
            (name, ChaosBackend(b, faults, seed=seed + i))
            for i, (name, b) in enumerate(tiers)
        ]
    tiers.append(("cpu", Bn254ScalarBackend()))
    return tiers


_backend = None
_backend_lock = None


def get_bn254_backend():
    """Process singleton mirroring sidecar.backend.get_backend(): under
    CMTPU_BACKEND=auto the supervised chain behind the coalescer; any other
    choice serves the bare host multi-pairing engine (always CPU-capable,
    fails loudly — never a silent downgrade to per-sig verification)."""
    global _backend, _backend_lock
    if _backend is not None:
        return _backend
    import threading

    if _backend_lock is None:
        _backend_lock = threading.Lock()
    with _backend_lock:
        if _backend is not None:
            return _backend
        choice = os.environ.get("CMTPU_BACKEND", "auto").strip() or "auto"
        if choice == "auto":
            from cometbft_tpu.sidecar.scheduler import CoalescingScheduler
            from cometbft_tpu.sidecar.supervisor import ResilientBackend

            chain = ResilientBackend(build_bn254_chain())
            if os.environ.get("CMTPU_COALESCE", "1") != "0":
                _backend = CoalescingScheduler(chain)
            else:
                _backend = chain
        else:
            _backend = Bn254HostBackend()
    return _backend


def set_bn254_backend(b) -> None:
    """Test/bench hook (None re-resolves lazily on next use)."""
    global _backend
    old = _backend
    _backend = b
    if old is not None and hasattr(old, "close") and old is not b:
        try:
            old.close()
        except Exception:
            pass


# -- verified-triple cache (same contract as ed25519._verified) --------------

_VERIFIED_MAX = int(os.environ.get("CMTPU_VERIFY_CACHE_MAX", "") or 131072)
_verified: dict[tuple, None] = {}


def _verified_put(key: tuple) -> None:
    if key in _verified:
        del _verified[key]
    elif len(_verified) >= _VERIFIED_MAX:
        for k in list(_verified)[: max(1, _VERIFIED_MAX // 4)]:
            _verified.pop(k, None)
    _verified[key] = None


class BatchVerifier(crypto.BatchVerifier):
    """crypto.BatchVerifier over bn254 triples: verified-triple LRU filter,
    within-batch dedup, the supervised bn254 chain, per-sig scalar fallback
    on ChainExhausted — the same lifecycle ed25519.BatchVerifier has."""

    def __init__(self):
        self._pubs: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, key, msg: bytes, sig: bytes) -> None:
        if not isinstance(key, PubKey):
            raise TypeError("bn254.BatchVerifier requires bn254 public keys")
        if len(sig) != SIGNATURE_SIZE:
            raise ValueError(f"bn254 signature must be {SIGNATURE_SIZE} bytes")
        self._pubs.append(key.bytes())
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(sig))

    def count(self) -> int:
        return len(self._pubs)

    def verify(self) -> tuple[bool, list]:
        n = len(self._pubs)
        if n == 0:
            return False, []
        bits: list = [None] * n
        first_at: dict[tuple, int] = {}
        sub_idx: list[int] = []
        for i in range(n):
            key = (self._pubs[i], self._sigs[i], self._msgs[i])
            if key in _verified:
                bits[i] = True
            elif key in first_at:
                bits[i] = first_at[key]  # lane alias, resolved below
            else:
                first_at[key] = i
                sub_idx.append(i)
        if sub_idx:
            sub_pubs = [self._pubs[i] for i in sub_idx]
            sub_msgs = [self._msgs[i] for i in sub_idx]
            sub_sigs = [self._sigs[i] for i in sub_idx]
            from cometbft_tpu.sidecar.supervisor import ChainExhausted

            try:
                _, sub_bits = get_bn254_backend().batch_verify(
                    sub_pubs, sub_msgs, sub_sigs
                )
                if len(sub_bits) != len(sub_idx):
                    raise ValueError("backend returned wrong-shaped bitmap")
            except ChainExhausted:
                sub_bits = [
                    _scalar_verify(p, m, s)
                    for p, m, s in zip(sub_pubs, sub_msgs, sub_sigs)
                ]
            for j, i in enumerate(sub_idx):
                bits[i] = bool(sub_bits[j])
                if bits[i]:
                    _verified_put((self._pubs[i], self._sigs[i], self._msgs[i]))
        out = []
        for b in bits:
            if isinstance(b, bool):
                out.append(b)
            else:  # alias lane: int index of the first occurrence
                out.append(bool(bits[b]))
        return all(out), out

# The name commit verification uses via crypto.batch's registry.
Bn254BatchVerifier = BatchVerifier

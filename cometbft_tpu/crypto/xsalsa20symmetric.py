"""Symmetric secretbox-style encryption (reference: crypto/xsalsa20symmetric/).

The reference uses NaCl secretbox (XSalsa20-Poly1305) with a random 24-byte
nonce prepended to the ciphertext. We keep the same envelope shape
(nonce || sealed) but seal with XChaCha20-Poly1305 — an equally-strong AEAD
from the same family — since the host crypto library does not expose XSalsa20.
Decryption of reference-produced ciphertexts is a non-goal (these never cross
the wire between implementations; they protect local key files).
"""

from __future__ import annotations

import os

from cometbft_tpu.crypto import xchacha20poly1305

NONCE_LEN = 24
SECRET_LEN = 32


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """EncryptSymmetric (xsalsa20symmetric/symmetric.go:23-38)."""
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be of length: {SECRET_LEN}")
    nonce = os.urandom(NONCE_LEN)
    sealed = xchacha20poly1305.seal(secret, nonce, plaintext)
    return nonce + sealed


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    """DecryptSymmetric (xsalsa20symmetric/symmetric.go:42-63)."""
    if len(secret) != SECRET_LEN:
        raise ValueError(f"secret must be of length: {SECRET_LEN}")
    if len(ciphertext) <= NONCE_LEN + 16:
        raise ValueError("ciphertext is too short")
    nonce, sealed = ciphertext[:NONCE_LEN], ciphertext[NONCE_LEN:]
    try:
        return xchacha20poly1305.open_(secret, nonce, sealed)
    except Exception as e:
        raise ValueError("ciphertext decryption failed") from e

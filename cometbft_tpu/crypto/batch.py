"""Key-type dispatch for batch verification (reference: crypto/batch/batch.go).

Only ed25519 and sr25519 support batching (batch.go:11-32); bn254 does not —
matching the fork's behavior.
"""

from __future__ import annotations

from cometbft_tpu import crypto
from cometbft_tpu.crypto import ed25519, sr25519


def create_batch_verifier(pk: crypto.PubKey) -> crypto.BatchVerifier:
    """batch.CreateBatchVerifier (batch.go:11-21)."""
    if isinstance(pk, ed25519.PubKey):
        return ed25519.BatchVerifier()
    if isinstance(pk, sr25519.PubKey):
        return sr25519.BatchVerifier()
    raise ValueError("only ed25519 and sr25519 are supported")


def supports_batch_verifier(pk: crypto.PubKey | None) -> bool:
    """batch.SupportsBatchVerifier (batch.go:25-32)."""
    return isinstance(pk, (ed25519.PubKey, sr25519.PubKey))

"""Key-type dispatch for batch verification (reference: crypto/batch/batch.go).

The reference registry covers ed25519 and sr25519 (batch.go:11-32); this
rebuild adds bn254, whose BatchVerifier rides the supervised multi-pairing
chain. The registry is keyed by the key-type STRING so commit verification
can pick an engine for whatever key type a homogeneous signer run actually
uses — dispatching on the proposer's key alone mis-batches mixed validator
sets (see types/validation._batch_key_type).
"""

from __future__ import annotations

from cometbft_tpu import crypto
from cometbft_tpu.crypto import bn254, ed25519, sr25519

# key type -> (PubKey class, BatchVerifier factory)
_REGISTRY: dict[str, tuple] = {
    ed25519.KEY_TYPE: (ed25519.PubKey, ed25519.BatchVerifier),
    sr25519.KEY_TYPE: (sr25519.PubKey, sr25519.BatchVerifier),
    bn254.KEY_TYPE: (bn254.PubKey, bn254.BatchVerifier),
}


def _key_type_of(key) -> str | None:
    if isinstance(key, str):
        return key if key in _REGISTRY else None
    for kt, (cls, _) in _REGISTRY.items():
        if isinstance(key, cls):
            return kt
    return None


def create_batch_verifier(key) -> crypto.BatchVerifier:
    """batch.CreateBatchVerifier (batch.go:11-21), extended to accept either
    a PubKey instance or a key-type string."""
    kt = _key_type_of(key)
    if kt is None:
        raise ValueError(
            f"only {', '.join(sorted(_REGISTRY))} support batch verification"
        )
    return _REGISTRY[kt][1]()


def supports_batch_verifier(key) -> bool:
    """batch.SupportsBatchVerifier (batch.go:25-32); PubKey or key-type
    string."""
    return _key_type_of(key) is not None

"""schnorrkel-compatible sr25519 over Ristretto255 (reference: crypto/sr25519/).

The reference backs this with curve25519-voi's schnorrkel implementation
(sr25519/pubkey.go, sr25519/batch.go:18, privkey.go:16).  This module follows
the same construction end to end:

  - group: ristretto255 (RFC 9496) over the edwards25519 backend;
  - signing context: merlin transcript ``Transcript("SigningContext")`` with
    the empty context label, message appended under ``sign-bytes``
    (privkey.go:16 NewSigningContext([]byte{}).NewTranscriptBytes);
  - Schnorr challenge: ``proto-name``="Schnorr-sig", points committed under
    ``sign:pk`` / ``sign:R``, 64-byte challenge under ``sign:c`` reduced
    mod L (schnorrkel sign.rs);
  - signature wire form: R || s with schnorrkel's high-bit marker on s
    (byte 63 bit 7 set on encode, required + cleared on decode);
  - key expansion: 32-byte MiniSecretKey -> SHA-512 -> ed25519-clamped
    scalar divided by the cofactor + 32-byte transcript-witness nonce
    (schnorrkel ExpandEd25519 — the substrate default), so a mini secret
    from a real chain derives the identical public key;
  - batch verification: random-linear-combination of the per-signature
    Schnorr equations with per-signature transcript challenges
    (sr25519/batch.go), per-signature fallback for the validity bitmap.

Address is SHA256-20 of the raw pubkey bytes (sr25519/pubkey.go:26-31).
The merlin/STROBE layer underneath is test-vector-validated
(tests/test_merlin.py).
"""

from __future__ import annotations

import hashlib
import os

from cometbft_tpu import crypto
from cometbft_tpu.crypto import tmhash
from cometbft_tpu.crypto.ed25519_pure import (
    D,
    IDENTITY,
    L,
    P,
    SQRT_M1,
    point_add,
    point_neg,
    scalar_mult,
)
from cometbft_tpu.crypto.merlin import Transcript

KEY_TYPE = "sr25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 32
SIGNATURE_SIZE = 64

PRIV_KEY_NAME = "tendermint/PrivKeySr25519"
PUB_KEY_NAME = "tendermint/PubKeySr25519"

# ---------------------------------------------------------------------------
# ristretto255 (RFC 9496) over the edwards25519 backend


def _is_neg(x: int) -> bool:
    return x & 1 == 1


def _abs(x: int) -> int:
    return P - x if _is_neg(x) else x


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    u_neg = (P - u) % P
    correct_sign = check == u % P
    flipped_sign = check == u_neg
    flipped_sign_i = check == u_neg * SQRT_M1 % P
    if flipped_sign or flipped_sign_i:
        r = r * SQRT_M1 % P
    return (correct_sign or flipped_sign), _abs(r)


_INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, (-1 - D) % P)[1]
_SQRT_AD_MINUS_ONE = _sqrt_ratio_m1((-1 * D - 1) % P, 1)[1]


def ristretto_decode(s_bytes: bytes):
    """RFC 9496 §4.3.1; None on failure."""
    if len(s_bytes) != 32:
        return None
    s = int.from_bytes(s_bytes, "little")
    if s >= P or _is_neg(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = ((P - D) * u1 % P * u1 - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = _abs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P
    if not was_square or _is_neg(t) or y == 0:
        return None
    return (x, y, 1, t)


def ristretto_encode(p) -> bytes:
    """RFC 9496 §4.3.2."""
    X, Y, Z, T = p
    u1 = (Z + Y) * (Z - Y) % P
    u2 = X * Y % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * T % P
    if _is_neg(T * z_inv % P):
        ix = X * SQRT_M1 % P
        iy = Y * SQRT_M1 % P
        x, y = iy, ix
        den_inv = den1 * _INVSQRT_A_MINUS_D % P
    else:
        x, y = X, Y
        den_inv = den2
    if _is_neg(x * z_inv % P):
        y = (P - y) % P
    s = _abs(den_inv * ((Z - y) % P) % P)
    return int.to_bytes(s, 32, "little")


# Ristretto basepoint = edwards25519 basepoint.
from cometbft_tpu.crypto.ed25519_pure import BASE as _BASE  # noqa: E402

# The reference constructs ONE signing context with the empty label
# (privkey.go:16) and clones it per message.
_SIGNING_CTX = Transcript(b"SigningContext")
_SIGNING_CTX.append_message(b"", b"")


def signing_transcript(msg: bytes) -> Transcript:
    """NewSigningContext([]byte{}).NewTranscriptBytes(msg)."""
    t = _SIGNING_CTX.clone()
    t.append_message(b"sign-bytes", msg)
    return t


def _challenge(t: Transcript, pub: bytes, r_bytes: bytes) -> int:
    """schnorrkel's challenge derivation on a signing transcript."""
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    t.append_message(b"sign:R", r_bytes)
    return int.from_bytes(t.challenge_bytes(b"sign:c", 64), "little") % L


def _decode_sig(sig: bytes) -> tuple[bytes, int] | None:
    """(R_bytes, s) after checking/clearing the schnorrkel marker bit."""
    if len(sig) != SIGNATURE_SIZE or not sig[63] & 0x80:
        return None
    s = int.from_bytes(sig[32:62] + bytes([sig[62], sig[63] & 0x7F]), "little")
    if s >= L:
        return None
    return sig[:32], s


class PubKey(crypto.PubKey):
    def __init__(self, data: bytes):
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(self._bytes) != PUB_KEY_SIZE:
            return False
        dec = _decode_sig(sig)
        if dec is None:
            return False
        r_bytes, s = dec
        A = ristretto_decode(self._bytes)
        R = ristretto_decode(r_bytes)
        if A is None or R is None:
            return False
        k = _challenge(signing_transcript(msg), self._bytes, r_bytes)
        # s·B - k·A == R  (compared in the canonical encoding)
        rhs = point_add(scalar_mult(s, _BASE), point_neg(scalar_mult(k, A)))
        return ristretto_encode(rhs) == r_bytes

    def type(self) -> str:
        return KEY_TYPE


def _expand_ed25519(mini: bytes) -> tuple[int, bytes]:
    """MiniSecretKey.ExpandEd25519 (schnorrkel keys.rs; substrate default):
    SHA-512, ed25519 clamping, scalar divided by the cofactor; the second
    half is the signing nonce."""
    h = hashlib.sha512(mini).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    scalar = int.from_bytes(bytes(key), "little") >> 3
    return scalar, h[32:64]


class PrivKey(crypto.PrivKey):
    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"sr25519 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)  # MiniSecretKey, like the reference's msk
        # ExpandEd25519 clamping guarantees scalar in [2^251, 2^252) — always
        # nonzero mod L, so no validity check is needed here.
        self._scalar, self._nonce = _expand_ed25519(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        pub = self.pub_key().bytes()
        t = signing_transcript(msg)
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", pub)
        # witness nonce: transcript RNG rekeyed with the expanded key's
        # nonce half + system entropy (schnorrkel witness_scalar)
        rng = t.build_rng().rekey_with_witness_bytes(b"signing", self._nonce)
        rng.finalize(os.urandom(32))
        r = int.from_bytes(rng.fill_bytes(64), "little") % L
        r_bytes = ristretto_encode(scalar_mult(r, _BASE))
        t.append_message(b"sign:R", r_bytes)
        k = int.from_bytes(t.challenge_bytes(b"sign:c", 64), "little") % L
        s = (r + k * self._scalar) % L
        s_bytes = bytearray(int.to_bytes(s, 32, "little"))
        s_bytes[31] |= 0x80  # schnorrkel signature marker
        return r_bytes + bytes(s_bytes)

    def pub_key(self) -> PubKey:
        return PubKey(ristretto_encode(scalar_mult(self._scalar, _BASE)))

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    # ExpandEd25519 clamping sets bit 254, so the expanded scalar always
    # lies in [2^251, 2^252) — nonzero mod L for every seed.
    return PrivKey(os.urandom(PRIV_KEY_SIZE))


class BatchVerifier(crypto.BatchVerifier):
    """sr25519 batch verification (reference: sr25519/batch.go).

    Random linear combination of the per-signature Schnorr equations
    (transcript challenges included); on failure, per-signature fallback
    produces the validity vector."""

    def __init__(self):
        self._entries: list[tuple[bytes, bytes, bytes]] = []

    def add(self, key: crypto.PubKey, message: bytes, signature: bytes) -> None:
        if not isinstance(key, PubKey):
            raise TypeError("pubkey is not sr25519")
        if len(signature) != SIGNATURE_SIZE:
            raise ValueError("invalid signature")
        self._entries.append((key.bytes(), bytes(message), bytes(signature)))

    def verify(self) -> tuple[bool, list[bool]]:
        n = len(self._entries)
        if n == 0:
            return False, []
        decoded = []
        ok = [True] * n
        for i, (pub, msg, sig) in enumerate(self._entries):
            dec = _decode_sig(sig)
            A = ristretto_decode(pub)
            R = ristretto_decode(sig[:32]) if dec else None
            if dec is None or A is None or R is None:
                ok[i] = False
                continue
            k = _challenge(signing_transcript(msg), pub, sig[:32])
            decoded.append((A, R, dec[1], k))
        if all(ok):
            s_acc = 0
            acc = IDENTITY
            for (A, R, s, k) in decoded:
                z = int.from_bytes(os.urandom(16), "little") | 1
                s_acc = (s_acc + z * s) % L
                acc = point_add(acc, scalar_mult(z, point_add(R, scalar_mult(k, A))))
            diff = point_add(scalar_mult(s_acc, _BASE), point_neg(acc))
            if ristretto_encode(diff) == ristretto_encode(IDENTITY):
                return True, [True] * n
        results = [
            ok[i] and PubKey(pub).verify_signature(msg, sig)
            for i, (pub, msg, sig) in enumerate(self._entries)
        ]
        return all(results), results

"""Schnorr signatures over Ristretto255 (reference: crypto/sr25519/).

The reference backs this with curve25519-voi's schnorrkel implementation
(sr25519/pubkey.go, sr25519/batch.go:18). This implementation uses a
ristretto255 group (RFC 9496 encode/decode over the edwards25519 backend in
ed25519_pure) with a domain-separated SHA-512 challenge in place of
schnorrkel's merlin transcript — self-consistent sign/verify/batch inside this
framework; wire compatibility with schnorrkel signatures is a non-goal for
now and is documented as such.

Address is SHA256-20 of the raw pubkey bytes (sr25519/pubkey.go:26-31).
"""

from __future__ import annotations

import hashlib
import os

from cometbft_tpu import crypto
from cometbft_tpu.crypto import tmhash
from cometbft_tpu.crypto.ed25519_pure import (
    D,
    IDENTITY,
    L,
    P,
    SQRT_M1,
    point_add,
    point_double,
    point_neg,
    scalar_mult,
)

KEY_TYPE = "sr25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 32
SIGNATURE_SIZE = 64

PRIV_KEY_NAME = "tendermint/PrivKeySr25519"
PUB_KEY_NAME = "tendermint/PubKeySr25519"

_SIG_DOMAIN = b"cometbft-tpu/sr25519-schnorr-v1"

# ---------------------------------------------------------------------------
# ristretto255 (RFC 9496) over the edwards25519 backend


def _is_neg(x: int) -> bool:
    return x & 1 == 1


def _abs(x: int) -> int:
    return P - x if _is_neg(x) else x


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    u_neg = (P - u) % P
    correct_sign = check == u % P
    flipped_sign = check == u_neg
    flipped_sign_i = check == u_neg * SQRT_M1 % P
    if flipped_sign or flipped_sign_i:
        r = r * SQRT_M1 % P
    return (correct_sign or flipped_sign), _abs(r)


_INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, (-1 - D) % P)[1]
_SQRT_AD_MINUS_ONE = _sqrt_ratio_m1((-1 * D - 1) % P, 1)[1]


def ristretto_decode(s_bytes: bytes):
    """RFC 9496 §4.3.1; None on failure."""
    if len(s_bytes) != 32:
        return None
    s = int.from_bytes(s_bytes, "little")
    if s >= P or _is_neg(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = ((P - D) * u1 % P * u1 - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = _abs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P
    if not was_square or _is_neg(t) or y == 0:
        return None
    return (x, y, 1, t)


def ristretto_encode(p) -> bytes:
    """RFC 9496 §4.3.2."""
    X, Y, Z, T = p
    u1 = (Z + Y) * (Z - Y) % P
    u2 = X * Y % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * T % P
    if _is_neg(T * z_inv % P):
        ix = X * SQRT_M1 % P
        iy = Y * SQRT_M1 % P
        x, y = iy, ix
        den_inv = den1 * _INVSQRT_A_MINUS_D % P
    else:
        x, y = X, Y
        den_inv = den2
    if _is_neg(x * z_inv % P):
        y = (P - y) % P
    s = _abs(den_inv * ((Z - y) % P) % P)
    return int.to_bytes(s, 32, "little")


# Ristretto basepoint = edwards25519 basepoint.
from cometbft_tpu.crypto.ed25519_pure import BASE as _BASE  # noqa: E402


def _challenge(r_bytes: bytes, pub: bytes, msg: bytes) -> int:
    h = hashlib.sha512(_SIG_DOMAIN + r_bytes + pub + msg).digest()
    return int.from_bytes(h, "little") % L


class PubKey(crypto.PubKey):
    def __init__(self, data: bytes):
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE or len(self._bytes) != PUB_KEY_SIZE:
            return False
        A = ristretto_decode(self._bytes)
        R = ristretto_decode(sig[:32])
        if A is None or R is None:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            return False
        k = _challenge(sig[:32], self._bytes, msg)
        # s·B == R + k·A
        lhs = scalar_mult(s, _BASE)
        rhs = point_add(R, scalar_mult(k, A))
        diff = point_add(lhs, point_neg(rhs))
        return ristretto_encode(diff) == ristretto_encode(IDENTITY)

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(crypto.PrivKey):
    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"sr25519 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._scalar = int.from_bytes(self._bytes, "little") % L
        if self._scalar == 0:
            raise ValueError("invalid sr25519 scalar")

    def bytes(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        pub = self.pub_key().bytes()
        # deterministic nonce (domain-separated), then Schnorr
        r = (
            int.from_bytes(
                hashlib.sha512(b"nonce" + self._bytes + pub + msg).digest(), "little"
            )
            % L
        )
        R = scalar_mult(r, _BASE)
        r_bytes = ristretto_encode(R)
        k = _challenge(r_bytes, pub, msg)
        s = (r + k * self._scalar) % L
        return r_bytes + int.to_bytes(s, 32, "little")

    def pub_key(self) -> PubKey:
        return PubKey(ristretto_encode(scalar_mult(self._scalar, _BASE)))

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    while True:
        raw = os.urandom(PRIV_KEY_SIZE)
        if int.from_bytes(raw, "little") % L != 0:
            return PrivKey(raw)


class BatchVerifier(crypto.BatchVerifier):
    """sr25519 batch verification (reference: sr25519/batch.go).

    Random linear combination of Schnorr equations; on failure, per-signature
    fallback produces the validity vector.
    """

    def __init__(self):
        self._entries: list[tuple[bytes, bytes, bytes]] = []

    def add(self, key: crypto.PubKey, message: bytes, signature: bytes) -> None:
        if not isinstance(key, PubKey):
            raise TypeError("pubkey is not sr25519")
        if len(signature) != SIGNATURE_SIZE:
            raise ValueError("invalid signature")
        self._entries.append((key.bytes(), bytes(message), bytes(signature)))

    def verify(self) -> tuple[bool, list[bool]]:
        n = len(self._entries)
        if n == 0:
            return False, []
        decoded = []
        ok = [True] * n
        for i, (pub, msg, sig) in enumerate(self._entries):
            A = ristretto_decode(pub)
            R = ristretto_decode(sig[:32])
            s = int.from_bytes(sig[32:], "little")
            if A is None or R is None or s >= L:
                ok[i] = False
                continue
            decoded.append((A, R, s, _challenge(sig[:32], pub, msg)))
        if all(ok):
            s_acc = 0
            acc = IDENTITY
            for (A, R, s, k) in decoded:
                z = int.from_bytes(os.urandom(16), "little") | 1
                s_acc = (s_acc + z * s) % L
                acc = point_add(acc, scalar_mult(z, point_add(R, scalar_mult(k, A))))
            diff = point_add(scalar_mult(s_acc, _BASE), point_neg(acc))
            if ristretto_encode(diff) == ristretto_encode(IDENTITY):
                return True, [True] * n
        results = [
            ok[i] and PubKey(pub).verify_signature(msg, sig)
            for i, (pub, msg, sig) in enumerate(self._entries)
        ]
        return all(results), results

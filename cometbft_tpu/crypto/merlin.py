"""Merlin transcripts (the construction under curve25519-voi's
primitives/merlin, used by the reference for the SecretConnection handshake
transcript — p2p/conn/secret_connection.go:111-135 — and by schnorrkel for
sr25519 signing contexts).

A Transcript is STROBE-128 under protocol label "Merlin v1.0" with:
  append_message(label, msg):   meta-AD(label || LE32(len)) ; AD(msg)
  challenge_bytes(label, n):    meta-AD(label || LE32(n))   ; PRF(n)
Transcript construction appends the application label as
append_message(b"dom-sep", label).
"""

from __future__ import annotations

import struct

from cometbft_tpu.crypto.strobe import Strobe128

_MERLIN_PROTOCOL_LABEL = b"Merlin v1.0"


class Transcript:
    __slots__ = ("_strobe",)

    def __init__(self, label: bytes, _strobe: Strobe128 | None = None):
        if _strobe is not None:
            self._strobe = _strobe
            return
        self._strobe = Strobe128(_MERLIN_PROTOCOL_LABEL)
        self.append_message(b"dom-sep", label)

    def clone(self) -> "Transcript":
        return Transcript(b"", _strobe=self._strobe.clone())

    def append_message(self, label: bytes, message: bytes) -> None:
        self._strobe.meta_ad(label, False)
        self._strobe.meta_ad(struct.pack("<I", len(message)), True)
        self._strobe.ad(bytes(message), False)

    def append_u64(self, label: bytes, value: int) -> None:
        self.append_message(label, struct.pack("<Q", value))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self._strobe.meta_ad(label, False)
        self._strobe.meta_ad(struct.pack("<I", n), True)
        return self._strobe.prf(n)

    # curve25519-voi naming (used by the secret-connection port)
    def extract_bytes(self, label: bytes, n: int) -> bytes:
        return self.challenge_bytes(label, n)

    # -- witness generation (schnorrkel signing nonces) ---------------------

    def build_rng(self) -> "TranscriptRng":
        return TranscriptRng(self._strobe.clone())


class TranscriptRng:
    """merlin's TranscriptRngBuilder finalized with system randomness:
    rekey(witness...) then KEY(64 bytes of entropy), challenges via PRF.
    Deterministic iff the caller passes fixed entropy (tests)."""

    __slots__ = ("_strobe",)

    def __init__(self, strobe: Strobe128):
        self._strobe = strobe

    def rekey_with_witness_bytes(self, label: bytes, witness: bytes) -> "TranscriptRng":
        self._strobe.meta_ad(label, False)
        self._strobe.meta_ad(struct.pack("<I", len(witness)), True)
        self._strobe.key(witness, False)
        return self

    def finalize(self, entropy32: bytes) -> "TranscriptRng":
        if len(entropy32) != 32:
            raise ValueError("need exactly 32 bytes of entropy")
        self._strobe.meta_ad(b"rng", False)
        self._strobe.key(entropy32, False)
        return self

    def fill_bytes(self, n: int) -> bytes:
        self._strobe.meta_ad(struct.pack("<I", n), False)
        return self._strobe.prf(n)

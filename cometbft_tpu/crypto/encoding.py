"""proto ↔ key-object conversion (reference: crypto/encoding/codec.go).

Wire schema: tendermint.crypto.PublicKey oneof {ed25519=1, secp256k1=2,
bn254=3} (proto/tendermint/crypto/keys.proto; bn254 is the fork's addition).
"""

from __future__ import annotations

from cometbft_tpu import crypto
from cometbft_tpu.crypto import bn254, ed25519, secp256k1, sr25519
from cometbft_tpu.wire import proto as wire


def pub_key_to_proto(k: crypto.PubKey) -> bytes:
    """PubKeyToProto (codec.go:22-48) → serialized tendermint.crypto.PublicKey."""
    if isinstance(k, ed25519.PubKey):
        return wire.field_bytes(1, k.bytes(), emit_default=True)
    if isinstance(k, secp256k1.PubKey):
        return wire.field_bytes(2, k.bytes(), emit_default=True)
    if isinstance(k, bn254.PubKey):
        return wire.field_bytes(3, k.bytes(), emit_default=True)
    if isinstance(k, sr25519.PubKey):
        # EXTENSION beyond the reference: its keys.proto stops at bn254=3,
        # so a Go node panics in Validator.Bytes() for sr25519 validators
        # (types/validator.go:117-121) — sr25519 validator SETS are
        # impossible there.  Field 4 makes them first-class here without
        # disturbing any encoding the reference can produce.
        return wire.field_bytes(4, k.bytes(), emit_default=True)
    raise ValueError(f"toproto: key type {k} is not supported")


def pub_key_from_proto(data: bytes) -> crypto.PubKey:
    """PubKeyFromProto (codec.go:51-93)."""
    fields = wire.decode_fields(data)
    if 1 in fields:
        raw = fields[1][-1]
        if len(raw) != ed25519.PUB_KEY_SIZE:
            raise ValueError(
                f"invalid size for PubKeyEd25519. Got {len(raw)}, "
                f"expected {ed25519.PUB_KEY_SIZE}"
            )
        return ed25519.PubKey(raw)
    if 2 in fields:
        raw = fields[2][-1]
        if len(raw) != secp256k1.PUB_KEY_SIZE:
            raise ValueError(
                f"invalid size for PubKeySecp256k1. Got {len(raw)}, "
                f"expected {secp256k1.PUB_KEY_SIZE}"
            )
        return secp256k1.PubKey(raw)
    if 3 in fields:
        raw = fields[3][-1]
        if len(raw) != bn254.PUB_KEY_SIZE:
            raise ValueError(
                f"invalid size for PubKeyBN254. Got {len(raw)}, "
                f"expected {bn254.PUB_KEY_SIZE}"
            )
        return bn254.PubKey(raw)
    if 4 in fields:  # sr25519 extension (see pub_key_to_proto)
        raw = fields[4][-1]
        if len(raw) != sr25519.PUB_KEY_SIZE:
            raise ValueError(
                f"invalid size for PubKeySr25519. Got {len(raw)}, "
                f"expected {sr25519.PUB_KEY_SIZE}"
            )
        return sr25519.PubKey(raw)
    raise ValueError("fromproto: key type is not supported")


_KEY_TYPE_TO_CLASS = {
    ed25519.KEY_TYPE: (ed25519.PubKey, ed25519.PUB_KEY_SIZE),
    secp256k1.KEY_TYPE: (secp256k1.PubKey, secp256k1.PUB_KEY_SIZE),
    bn254.KEY_TYPE: (bn254.PubKey, bn254.PUB_KEY_SIZE),
    sr25519.KEY_TYPE: (sr25519.PubKey, sr25519.PUB_KEY_SIZE),
    # Amino-style names as they appear on the JSON wire (genesis files, RPC
    # /validators responses — types/genesis.go + rpc serialization).
    ed25519.PUB_KEY_NAME: (ed25519.PubKey, ed25519.PUB_KEY_SIZE),
    secp256k1.PUB_KEY_NAME: (secp256k1.PubKey, secp256k1.PUB_KEY_SIZE),
    bn254.PUB_KEY_NAME: (bn254.PubKey, bn254.PUB_KEY_SIZE),
    sr25519.PUB_KEY_NAME: (sr25519.PubKey, sr25519.PUB_KEY_SIZE),
}


def pub_key_from_type_and_bytes(key_type: str, raw: bytes) -> crypto.PubKey:
    """Genesis/JSON path: construct a pubkey from its registered type name."""
    if key_type not in _KEY_TYPE_TO_CLASS:
        raise ValueError(f"unsupported key type {key_type}")
    cls, size = _KEY_TYPE_TO_CLASS[key_type]
    if len(raw) != size:
        raise ValueError(f"invalid {key_type} pubkey size {len(raw)}, want {size}")
    return cls(raw)
